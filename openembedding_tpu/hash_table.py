"""Hash-table embedding variant for unbounded int64 key spaces.

TPU-native redesign of the reference's hash-table embedding
(/root/reference/openembedding/variable/EmbeddingTable.h:55-118 —
``EasyHashMap<key, T*>`` + block pool, selected when
``vocabulary_size >= 2^63``, Meta.h:44-46): a **static-capacity
open-addressing table in HBM** so every lookup/insert is a fixed-shape XLA
program (no host round trips, no dynamic allocation):

* ``keys``: ``[capacity]`` array, ``EMPTY`` sentinel for free slots; weights
  and optimizer slots are parallel ``[capacity, ...]`` arrays as in the array
  table.
* The slot space is organized in **buckets of 128 slots** (one int32 lane
  row, so a bucket is a single aligned DMA for the Pallas probe kernel and a
  single contiguous row gather for XLA). A key hashes to a start bucket and
  may overflow into the next bucket(s) of its chain — ``max_probes`` is the
  total probed slots (chain length = ``max_probes / 128`` buckets; tables
  smaller than a bucket degenerate to one whole-table bucket).
* **Lookup** gathers the chain's ``[n, W]`` candidate keys in one pass, then
  a masked argmax. A key is only ever placed in bucket ``b+j`` if buckets
  ``b..b+j-1`` were full at insert time, and slots are never freed — so the
  chain scan is exact up to chain overflow.
* **Insert** is the reference's deferred materialization
  (EmbeddingOptimizerVariable.h:242-266: pull lazily creates rows in
  ``_new_weights``, merged on the next update) made functional: a *pull* of a
  missing key returns its **deterministic per-key initializer row** (PRNG
  folded with the key) without mutating anything; the *update* inserts the
  row (claim-based parallel probing, ``lax.fori_loop`` over probe rounds) and
  applies the gradient on top of that same deterministic init. Pull-then-push
  therefore behaves exactly as if the row had materialized on pull.
* Window overflow (table nearly full / pathological clustering) drops the
  update and bumps ``insert_failures`` — observable, like the reference's
  table growth being observable via item pool stats. Size the capacity for a
  load factor <= ~0.7 and the default 32-probe window is effectively exact.

Key dtype follows the incoming indices (int32 by default). The reference's
full 2^62 hashed key space is available two ways: ``key_width=64`` stores
keys as [capacity, 2] int32 (lo, hi) pairs and takes [n, 2] pair queries —
NO global flag needed (cf. ``split64``/``join64``); or
``key_dtype=jnp.int64`` under ``jax_enable_x64``. The ``EMPTY`` sentinel is
``iinfo(dtype).min`` — the same value dedup uses as its padding fill, so
padding slots are naturally invalid keys here (wide slots are free iff the
HI word is EMPTY).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from flax import struct

from .meta import EmbeddingVariableMeta
from .ops import dedup
from .optim.initializers import Initializer, make_initializer
from .optim.optimizers import SparseOptimizer, make_optimizer
from . import table as table_lib

BUCKET = 128            # slots per bucket = one int32 lane row
DEFAULT_MAX_PROBES = 256  # probed slots per lookup (2-bucket chain)


def empty_key(dtype) -> int:
    return int(jnp.iinfo(dtype).min)


# --- wide (64-bit) keys without jax_enable_x64 -------------------------------
#
# A process without the global x64 flag cannot hold jnp int64 arrays, but the
# reference's key space is 2^62 (hashed ids, criteo_deepctr.py
# to_hash_bucket_fast(2**62)). Wide keys are therefore carried as [n, 2]
# int32 (lo, hi) pairs end-to-end on device; a slot is free iff its hi word
# equals the EMPTY sentinel (keys with hi == INT32_MIN are excluded — the
# top 2^32 of a 2^64 space, matching the reference's own 2^62 bound).

def is_wide(keys: jnp.ndarray) -> bool:
    """[n, 2] (lo, hi) pair keys vs plain [n] keys."""
    return keys.ndim == 2


def split64(keys64: np.ndarray) -> np.ndarray:
    """Host helper: int64 numpy keys -> [n, 2] int32 (lo, hi) pairs."""
    k = np.asarray(keys64, np.int64)
    return np.stack([(k & 0xFFFFFFFF).astype(np.uint32).view(np.int32),
                     (k >> 32).astype(np.int32)], axis=-1)


def join64(pairs: np.ndarray) -> np.ndarray:
    """Host helper: [n, 2] int32 pairs -> int64 numpy keys."""
    p = np.asarray(pairs)
    lo = p[..., 0].view(np.uint32).astype(np.uint64)
    hi = p[..., 1].astype(np.int64)
    return (hi << np.int64(32)) | lo.astype(np.int64)


def widen_ids(ids: jnp.ndarray) -> jnp.ndarray:
    """Narrow integer ids (any shape) -> ``[..., 2]`` int32 (lo, hi) pairs.

    The device-side bridge that lets WIDE tables (the default hash key
    space) accept plain int32/int64 id columns: each id becomes the pair
    encoding of its sign-extended 64-bit value, so a pipeline feeding
    int32 ids and one feeding ``split64`` pairs address the same rows.
    The narrow dtype's own invalid sentinel (its minimum value — the
    framework-wide EMPTY/padding id) maps to the EMPTY pair, preserving
    the invalid-id contract across the widening.
    """
    ids = jnp.asarray(ids)
    empty = jnp.int32(empty_key(jnp.int32))
    if ids.dtype.itemsize == 8:
        lo = (ids & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32).astype(
            jnp.int32)
        hi = (ids >> jnp.int64(32)).astype(jnp.int32)
        invalid = ids == jnp.iinfo(jnp.int64).min
    else:
        ids = ids.astype(jnp.int32)
        lo = ids
        hi = ids >> jnp.int32(31)      # arithmetic: 0 or -1 (sign extend)
        invalid = ids == empty
    pair = jnp.stack([lo, hi], axis=-1)
    return jnp.where(invalid[..., None], empty, pair)


def pair_mod(pairs: jnp.ndarray, g: int) -> jnp.ndarray:
    """``join64(pairs) mod g`` computed in 32-bit words (x64-off safe).

    The serving shard-group owner rule for wide keys — identical to the
    narrow rule ``id % g`` on the joined 64-bit value, so a model keeps
    its placement across key-width migrations (int32 dump -> wide table,
    wide dump -> int64 table). Python-modulo semantics (result in
    [0, g)): ``(hi*2^32 + lo_unsigned) mod g`` decomposes as
    ``((hi mod g) * (2^32 mod g) + lo mod g) mod g``; every intermediate
    fits int32 for any realistic shard count (g < 2^15).
    """
    if not 0 < g < (1 << 15):
        raise ValueError(f"shard count {g} out of range [1, 2^15)")
    hi_m = jnp.mod(pairs[..., 1], jnp.int32(g))           # in [0, g)
    lo_m = (pairs[..., 0].astype(jnp.uint32)
            % jnp.uint32(g)).astype(jnp.int32)
    return jnp.mod(hi_m * jnp.int32((1 << 32) % g) + lo_m, jnp.int32(g))


def _mix_pair(lo: jnp.ndarray, hi: jnp.ndarray) -> jnp.ndarray:
    """32-bit-only avalanche over a key pair (x64-off safe)."""
    a = lo.astype(jnp.uint32)
    b = hi.astype(jnp.uint32)
    h = a ^ (b * jnp.uint32(0x9E3779B9))
    h = (h ^ (h >> 16)) * jnp.uint32(0x85EBCA6B)
    h = h ^ b
    h = (h ^ (h >> 13)) * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


def table_layout(capacity: int, max_probes: int) -> Tuple[int, int, int]:
    """(bucket_size, num_buckets, chain_buckets) for a table's slot space.

    ``capacity`` must be a multiple of the bucket size (``round_capacity``
    does the rounding at creation). Tables smaller than ``BUCKET`` collapse
    to a single whole-table bucket.
    """
    b = min(BUCKET, capacity)
    if capacity % b:
        raise ValueError(
            f"hash-table capacity {capacity} is not a multiple of the "
            f"bucket size {b}; use round_capacity() when allocating")
    nb = capacity // b
    chain = max(1, min(max_probes // b, nb))
    return b, nb, chain


def round_capacity(capacity: int) -> int:
    """Round a requested capacity up to the bucket granularity."""
    if capacity >= BUCKET:
        return -(-capacity // BUCKET) * BUCKET
    return capacity


def probe_window(capacity: int, max_probes: int) -> int:
    """Total probed slots per lookup (chain_buckets * bucket_size)."""
    b, _nb, chain = table_layout(capacity, max_probes)
    return b * chain


def probe_starts(keys: jnp.ndarray, capacity: int,
                 max_probes: int) -> jnp.ndarray:
    """First probe SLOT per key — always bucket-aligned.

    ``mix(key) % (num_buckets - chain + 1) * bucket_size``: the whole chain
    fits without wrapping, so a lookup's candidate slots are one CONTIGUOUS
    aligned run — a single ``[chain, 128]`` DMA for the Pallas probe kernel,
    plain ``start + i`` adds everywhere else. The last ``chain - 1`` buckets
    are only reachable as chain tails; the occupancy skew is
    O(chain/num_buckets), negligible at real sizes.
    """
    b, nb, chain = table_layout(capacity, max_probes)
    if is_wide(keys):
        mixed = _mix_pair(keys[:, 0], keys[:, 1])
    else:
        mixed = _mix(keys)
    span = jnp.asarray(nb - chain + 1, mixed.dtype)
    return ((mixed % span).astype(jnp.int32)) * b


def _mix(keys: jnp.ndarray) -> jnp.ndarray:
    """Avalanche-mix keys to probe start positions (unsigned arithmetic).

    murmur3/splitmix-style finalizer so sequential or strided ids spread
    uniformly — the reference gets this from EasyHashMap's hash policy.
    """
    if keys.dtype.itemsize == 8:
        u = keys.astype(jnp.uint64)
        u = (u ^ (u >> 33)) * jnp.uint64(0xFF51AFD7ED558CCD)
        u = (u ^ (u >> 33)) * jnp.uint64(0xC4CEB9FE1A85EC53)
        u = u ^ (u >> 33)
    else:
        u = keys.astype(jnp.uint32)
        u = (u ^ (u >> 16)) * jnp.uint32(0x85EBCA6B)
        u = (u ^ (u >> 13)) * jnp.uint32(0xC2B2AE35)
        u = u ^ (u >> 16)
    return u


@struct.dataclass
class HashTableState:
    """Pytree for one hash-table shard."""

    keys: jnp.ndarray                    # [capacity], EMPTY = free
    weights: jnp.ndarray                 # [capacity, dim]
    slots: Dict[str, jnp.ndarray]        # each [capacity, ...]
    init_rng: jax.Array                  # base PRNG for per-key row init
    insert_failures: jnp.ndarray         # int32 scalar, probe-window overflows

    @property
    def capacity(self) -> int:
        return self.keys.shape[0]

    @property
    def dim(self) -> int:
        return self.weights.shape[1]

    @property
    def wide(self) -> bool:
        return self.keys.ndim == 2

    def num_used(self) -> jnp.ndarray:
        empty = empty_key(self.keys.dtype)
        live = (self.keys[:, 1] != empty) if self.wide \
            else (self.keys != empty)
        return jnp.sum(live).astype(jnp.int32)


def create_hash_table(meta: EmbeddingVariableMeta,
                      optimizer: Any,
                      *,
                      capacity: int,
                      rng: Optional[jax.Array] = None,
                      key_dtype=jnp.int32,
                      key_width: int = 32) -> HashTableState:
    """Allocate an empty hash table shard.

    ``capacity`` plays the reference's ``reserve_items`` role
    (EmbeddingInitOperator.cpp:138-168) — hash vocabularies are unbounded so
    the caller must budget rows. Rounded up to the bucket granularity.
    ``key_width=64`` stores keys as [capacity, 2] int32 (lo, hi) pairs —
    the reference's 2^62 key space WITHOUT the global jax_enable_x64 flag
    (queries then come as [n, 2] pairs, cf. :func:`split64`).
    """
    optimizer = make_optimizer(optimizer)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    capacity = round_capacity(capacity)
    dtype = table_lib.resolve_dtype(meta)
    dim = meta.embedding_dim
    if key_width == 64:
        keys = jnp.full((capacity, 2), empty_key(jnp.int32),
                        dtype=jnp.int32)
    else:
        keys = jnp.full((capacity,), empty_key(key_dtype), dtype=key_dtype)
    # weights hold placeholder zeros; live rows are written on insert with the
    # deterministic per-key init, so this buffer's initial content never leaks.
    weights = jnp.zeros((capacity, dim), dtype=dtype)
    slots = optimizer.init_slots(capacity, dim, dtype)
    return HashTableState(keys=keys, weights=weights, slots=slots,
                          init_rng=rng,
                          insert_failures=jnp.zeros((), jnp.int32))


def _wide_query(keys: jnp.ndarray, indices: jnp.ndarray) -> jnp.ndarray:
    """Validate + flatten a wide-table query to [n, 2] pairs."""
    if indices.ndim < 2 or indices.shape[-1] != 2:
        raise ValueError(
            f"key-shape mismatch: wide (64-bit pair) tables take [..., 2] "
            f"int32 queries (hash_table.split64), got {indices.shape}")
    return check_key_dtype(keys, indices.reshape(-1, 2))


def init_rows(initializer: Initializer, base_rng: jax.Array,
              keys: jnp.ndarray, dim: int, dtype) -> jnp.ndarray:
    """Deterministic initializer row per key: fold key into the base PRNG.
    Wide keys fold both words, so rows depend on the full 64-bit key."""
    if is_wide(keys):
        def one(k):
            r = jax.random.fold_in(base_rng, k[0])
            return initializer.init(jax.random.fold_in(r, k[1]),
                                    (dim,), dtype)
    else:
        def one(k):
            return initializer.init(jax.random.fold_in(base_rng, k),
                                    (dim,), dtype)
    return jax.vmap(one)(keys)


def check_key_dtype(table_keys: jnp.ndarray, query: jnp.ndarray) -> jnp.ndarray:
    """Cast query keys to the table's key dtype, refusing silent truncation.

    A table created with int32 keys cannot address an int64 id space — that
    would alias ids modulo 2^32. Use ``key_width=64`` (pair keys, works
    with x64 off) or ``key_dtype=jnp.int64`` (requires jax_enable_x64) for
    the reference's full 2^62 hashed key space.
    """
    if is_wide(table_keys) != is_wide(query):
        raise ValueError(
            f"key-shape mismatch: table keys {table_keys.shape} vs query "
            f"{query.shape} — wide (64-bit pair) tables take [n, 2] int32 "
            "queries (hash_table.split64)")
    if query.dtype.itemsize > table_keys.dtype.itemsize:
        raise ValueError(
            f"query keys are {query.dtype} but the table stores "
            f"{table_keys.dtype} keys; create the table with "
            f"key_dtype={query.dtype} (int64 needs jax_enable_x64) or "
            "key_width=64 (pair keys, x64-off)")
    return query.astype(table_keys.dtype)


def find_rows(table_keys: jnp.ndarray, query: jnp.ndarray,
              max_probes: int = DEFAULT_MAX_PROBES) -> jnp.ndarray:
    """Slot index for each query key, or -1 when absent / invalid.

    Probes by gathering whole bucket ROWS (``[n, chain, 128]`` via a row
    gather of the ``[num_buckets, 128]`` key view), then a masked
    first-match. Row gathers are the operation XLA's TPU gather is built
    for; the element-wise ``[n, W]`` scalar gather an earlier layout needed
    measured ~30x slower on v5e (2.1 ms vs 61 ms for 32k lookups in a
    2^22-slot table) — the bucket-aligned layout is what makes the probe a
    row gather.
    """
    query = check_key_dtype(table_keys, query)
    capacity = table_keys.shape[0]
    n = query.shape[0]
    bsz, nb, chain = table_layout(capacity, max_probes)
    h = probe_starts(query, capacity, max_probes)
    b0 = h // bsz
    bkts = b0[:, None] + jnp.arange(chain, dtype=jnp.int32)[None, :]
    empty = empty_key(table_keys.dtype)
    if is_wide(table_keys):
        probed = jnp.take(table_keys.reshape(nb, bsz, 2), bkts, axis=0)
        probed = probed.reshape(n, chain * bsz, 2)
        match = ((probed[..., 0] == query[:, None, 0])
                 & (probed[..., 1] == query[:, None, 1]))
        valid = query[:, 1] != empty
    else:
        probed = jnp.take(table_keys.reshape(nb, bsz), bkts, axis=0)
        match = probed.reshape(n, chain * bsz) == query[:, None]
        valid = query != empty
    hit = jnp.any(match, axis=1)
    first = jnp.argmax(match, axis=1).astype(jnp.int32)
    slot = h + first
    return jnp.where(hit & valid, slot, -1)


def find_or_insert(table_keys: jnp.ndarray, new_keys: jnp.ndarray,
                   valid: jnp.ndarray,
                   max_probes: int = DEFAULT_MAX_PROBES
                   ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Find each (unique) key's slot, inserting missing keys.

    One pass per chain level: every unplaced key probes its level-j bucket —
    a contiguous 128-slot row — matches existing entries, then unmatched
    keys are assigned free slots by RANK: contenders for the same bucket are
    grouped (stable sort by bucket id), ranked within the group, and rank r
    takes the bucket's (r+1)-th free slot. Keys are unique, ranks within a
    bucket are unique, so assignments never collide; keys ranked past the
    free count overflow to the next chain level — which is exactly the
    "only overflow when the bucket filled up" invariant lookup relies on.

    Every level costs O(batch * 128) gathers + O(batch log batch) sort work
    — *independent of table capacity* (an earlier design materialized a
    [capacity] claim buffer per probe round: O(max_probes * capacity) HBM
    traffic per insert call, benign at 2^23 rows, fatal at the reference's
    10^9-row scale, documents/en/pmem.md north star).

    Returns ``(table_keys, slot [n] (-1 = failed), inserted [n],
    failed [n])``.
    """
    capacity = table_keys.shape[0]
    n = new_keys.shape[0]
    empty = empty_key(table_keys.dtype)
    wide = is_wide(table_keys)
    bsz, nb, chain = table_layout(capacity, max_probes)
    h = probe_starts(new_keys, capacity, max_probes)
    b0 = h // bsz
    oob = jnp.asarray(capacity, jnp.int32)
    ids = jnp.arange(n, dtype=jnp.int32)

    def level(j, carry):
        keys_arr, slot, done, inserted = carry
        bj = b0 + j
        start = bj * bsz
        if wide:
            rows = jnp.take(keys_arr.reshape(nb, bsz, 2), bj, axis=0)
            match = ((rows[..., 0] == new_keys[:, None, 0])
                     & (rows[..., 1] == new_keys[:, None, 1]))
            emptym = rows[..., 1] == empty
        else:
            rows = jnp.take(keys_arr.reshape(nb, bsz), bj, axis=0)
            match = rows == new_keys[:, None]
            emptym = rows == empty
        active = valid & ~done
        # already present (keys are unique; at most one slot matches)
        hitm = active & jnp.any(match, axis=1)
        moff = jnp.argmax(match, axis=1).astype(jnp.int32)
        slot = jnp.where(hitm, start + moff, slot)
        done = done | hitm
        active = active & ~hitm
        # rank contenders within each bucket: stable sort by bucket id,
        # rank = distance from the group's first sorted position
        bid = jnp.where(active, bj, nb)
        order = jnp.argsort(bid, stable=True)
        sorted_bid = bid[order]
        seg = jnp.concatenate([
            jnp.ones((1,), bool), sorted_bid[1:] != sorted_bid[:-1]])
        group_start = lax.cummax(jnp.where(seg, ids, 0))
        rank = jnp.zeros((n,), jnp.int32).at[order].set(ids - group_start)
        # rank r takes the (r+1)-th free slot of the bucket
        cum = jnp.cumsum(emptym, axis=1).astype(jnp.int32)
        nfree = cum[:, -1]
        place = active & (rank < nfree)
        tgt = jnp.argmax((cum == rank[:, None] + 1) & emptym,
                         axis=1).astype(jnp.int32)
        pslot = start + tgt
        keys_arr = keys_arr.at[jnp.where(place, pslot, oob)].set(
            new_keys, mode="drop")
        slot = jnp.where(place, pslot, slot)
        done = done | place
        inserted = inserted | place
        return keys_arr, slot, done, inserted

    slot0 = jnp.full((n,), -1, jnp.int32)
    done0 = ~valid
    ins0 = jnp.zeros((n,), bool)
    table_keys, slot, done, inserted = lax.fori_loop(
        0, chain, level, (table_keys, slot0, done0, ins0))
    failed = valid & ~done
    return table_keys, slot, inserted, failed


def insert_rows(state: HashTableState,
                keys: jnp.ndarray,
                weights: jnp.ndarray,
                slot_rows: Optional[Dict[str, jnp.ndarray]] = None,
                max_probes: int = DEFAULT_MAX_PROBES) -> HashTableState:
    """Directly set rows (and optionally optimizer-state rows) for keys.

    The load-path primitive (reference EmbeddingInitItems delivery,
    EmbeddingLoadOperator.cpp:58-111): inserts missing keys and overwrites
    weights/states verbatim — no optimizer math. ``keys`` must be unique;
    EMPTY-sentinel keys are skipped.
    """
    empty = empty_key(state.keys.dtype)
    if state.wide:
        keys = _wide_query(state.keys, keys)
        valid = keys[:, 1] != empty
    else:
        keys = check_key_dtype(state.keys, keys.ravel())
        valid = keys != empty
    keys_arr, slot, _inserted, failed = find_or_insert(
        state.keys, keys, valid, max_probes)
    ok = valid & (slot >= 0)
    oob = jnp.asarray(state.capacity, jnp.int32)
    scatter_idx = jnp.where(ok, slot, oob)
    new_weights = state.weights.at[scatter_idx].set(
        weights.astype(state.weights.dtype), mode="drop")
    slots = dict(state.slots)
    if slot_rows:
        for name, rows in slot_rows.items():
            slots[name] = state.slots[name].at[scatter_idx].set(
                rows.astype(state.slots[name].dtype), mode="drop")
    return HashTableState(
        keys=keys_arr, weights=new_weights, slots=slots,
        init_rng=state.init_rng,
        insert_failures=state.insert_failures + jnp.sum(failed).astype(jnp.int32))


def pull(state: HashTableState, indices: jnp.ndarray,
         initializer: Any,
         max_probes: int = DEFAULT_MAX_PROBES) -> jnp.ndarray:
    """Lookup rows; missing keys return their deterministic init row.

    Mirrors the reference's pull contract (present -> stored row, absent ->
    freshly initialized row, EmbeddingOptimizerVariable.h:242-266) without
    mutation: the same init row materializes again at insert time. Keys equal
    to the EMPTY sentinel return zeros.

    ``initializer=None`` selects the **read-only** (serving) contract:
    missing keys return zero rows with no init math — the reference's
    read_only get_weights path (EmbeddingPullOperator.cpp:179-181).
    """
    if state.wide:
        flat = _wide_query(state.keys, indices)
        invalid = flat[:, 1] == empty_key(state.keys.dtype)
        out_shape = indices.shape[:-1] + (state.dim,)
    else:
        flat = check_key_dtype(state.keys, indices.ravel())
        invalid = flat == empty_key(state.keys.dtype)
        out_shape = indices.shape + (state.dim,)
    slot = find_rows(state.keys, flat, max_probes)
    hit = slot >= 0
    rows = jnp.take(state.weights, jnp.where(hit, slot, 0), axis=0, mode="clip")
    if initializer is None:
        fresh = jnp.zeros_like(rows)
    else:
        initializer = make_initializer(initializer)
        fresh = init_rows(initializer, state.init_rng, flat, state.dim,
                          state.weights.dtype)
    rows = jnp.where(hit[:, None], rows, fresh)
    rows = jnp.where(invalid[:, None], jnp.zeros_like(rows), rows)
    return rows.reshape(out_shape)


def apply_gradients(state: HashTableState,
                    optimizer: SparseOptimizer,
                    initializer: Any,
                    indices: jnp.ndarray,
                    grads: jnp.ndarray,
                    *,
                    dedup_capacity: Optional[int] = None,
                    max_probes: int = DEFAULT_MAX_PROBES,
                    in_counts: Optional[jnp.ndarray] = None) -> HashTableState:
    """Combine duplicate grads, insert missing keys, update touched rows.

    The hash-table analogue of ``table.apply_gradients``: dedup -> claim/probe
    insert -> gather (with deterministic init for fresh rows) -> vectorized
    optimizer -> scatter. Window-overflow keys are dropped and counted.
    ``in_counts`` ([n]) marks grads that are already pre-reduced sums of that
    many originals (owner side of the all-to-all exchange).
    """
    optimizer = make_optimizer(optimizer)
    initializer = make_initializer(initializer)
    dim = state.dim
    empty = empty_key(state.keys.dtype)
    if state.wide:
        flat_idx = _wide_query(state.keys, indices)
        n = flat_idx.shape[0]
        capacity = dedup_capacity or n
        uniq, inverse, valid = dedup.unique_pairs(
            flat_idx, capacity, fill_value=empty)
        valid = valid & (uniq[:, 1] != empty)
    else:
        flat_idx = check_key_dtype(state.keys, indices.ravel())
        n = flat_idx.shape[0]
        capacity = dedup_capacity or n
        uniq, inverse, valid = dedup.unique_indices(
            flat_idx, capacity, fill_value=empty)
        valid = valid & (uniq != empty)
    flat_grads = grads.reshape(-1, dim)
    summed, counts = dedup.combine_gradients(flat_grads, inverse, capacity,
                                             in_counts)

    keys_arr, slot, inserted, failed = find_or_insert(
        state.keys, uniq, valid, max_probes)
    ok = valid & (slot >= 0)
    safe_slot = jnp.where(ok, slot, 0)

    w = jnp.take(state.weights, safe_slot, axis=0)
    fresh = init_rows(initializer, state.init_rng, uniq, dim,
                      state.weights.dtype)
    w = jnp.where(inserted[:, None], fresh, w)
    s = {k: jnp.take(v, safe_slot, axis=0) for k, v in state.slots.items()}

    new_w, new_s = table_lib.optimizer_block_update(optimizer, w, s,
                                                    summed, counts)

    oob = jnp.asarray(state.capacity, jnp.int32)
    scatter_idx = jnp.where(ok, safe_slot, oob)
    weights = state.weights.at[scatter_idx].set(new_w, mode="drop")
    slots = {k: state.slots[k].at[scatter_idx].set(new_s[k], mode="drop")
             for k in state.slots}
    return HashTableState(
        keys=keys_arr, weights=weights, slots=slots,
        init_rng=state.init_rng,
        insert_failures=state.insert_failures + jnp.sum(failed).astype(jnp.int32))
