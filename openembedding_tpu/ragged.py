"""Ragged / variable-length sequence features: padding + pooling.

Capability parity with the reference's RaggedTensor lookups
(/root/reference/openembedding/tensorflow/exb.py:315-321 — ``sparse_read``
maps flat values of a RaggedTensor through the pull op) and TF's sparse
combiners (sum / mean / sqrtn). Dynamic row lengths are hostile to XLA, so
the TPU-native shape is **padded [B, L] id matrices**:

* padding slots hold an *invalid* id — ``-1`` for bounded vocabs, the hash
  EMPTY sentinel for hash variables (``pad_id_for``). The framework-wide
  invalid-index contract (zero pull rows, dropped gradients) then makes the
  padding mathematically inert with no extra masks.
* pooling is declared on the spec (``EmbeddingSpec(pooling="mean")``):
  ``EmbeddingCollection.pull`` reduces ``[B, L, dim] -> [B, dim]`` and
  ``apply_gradients`` expands the pooled row-gradient with the matching
  VJP — the same custom-gradient structure the reference builds around its
  pull op (exb.py:89-104).

``sum``: plain sum (padding rows are zero). ``mean``: sum / count of valid
ids (clamped at 1). ``sqrtn``: sum / sqrt(count) — TF's third combiner.
"""

from __future__ import annotations

from typing import Iterable, Optional, Sequence

import jax.numpy as jnp
import numpy as np

POOLINGS = ("sum", "mean", "sqrtn")


def pad_id_for(spec) -> int:
    """Canonical padding id for one EmbeddingSpec's key space."""
    if spec.use_hash:
        from . import hash_table as hash_lib
        return hash_lib.empty_key(jnp.dtype(spec.key_dtype))
    return -1


def pad_ragged(sequences: Iterable[Sequence[int]],
               max_len: Optional[int] = None,
               pad_id: int = -1,
               dtype=np.int32) -> np.ndarray:
    """Host-side: list of variable-length id lists -> [B, L] padded matrix.

    Sequences longer than ``max_len`` keep their most recent ``max_len`` ids
    (recommendation behavior histories truncate from the front).
    """
    info = np.iinfo(np.dtype(dtype))
    if not (info.min <= pad_id <= info.max):
        raise ValueError(
            f"pad_id {pad_id} does not fit dtype {np.dtype(dtype)} — for "
            "int64-keyed hash features pass dtype=np.int64 (numpy would "
            "silently wrap the sentinel onto a valid key)")
    seqs = [np.asarray(s, dtype=dtype).ravel() for s in sequences]
    if max_len is None:
        max_len = max((s.size for s in seqs), default=1) or 1
    out = np.full((len(seqs), max_len), pad_id, dtype=dtype)
    for i, s in enumerate(seqs):
        if s.size > max_len:
            s = s[-max_len:]
        out[i, :s.size] = s
    return out


def valid_mask(ids: jnp.ndarray, pad_id: int,
               vocab: Optional[int] = None) -> jnp.ndarray:
    """[B, L] bool: slots holding a real id (pull's validity contract)."""
    if vocab is not None and pad_id == -1:
        return (ids >= 0) & (ids < vocab)
    return ids != jnp.asarray(pad_id, ids.dtype)


def seq_lengths(ids: jnp.ndarray, pad_id: int,
                vocab: Optional[int] = None) -> jnp.ndarray:
    """[B] count of valid ids per row (clamped below at 1 for division)."""
    n = jnp.sum(valid_mask(ids, pad_id, vocab), axis=-1)
    return jnp.maximum(n, 1)


def _scale(pooling: str, ids: jnp.ndarray, pad_id: int,
           vocab: Optional[int], dtype) -> jnp.ndarray:
    """[B, 1] divisor applied to the pooled sum (and to expanded grads)."""
    if pooling == "sum":
        return jnp.ones((ids.shape[0], 1), dtype)
    n = seq_lengths(ids, pad_id, vocab).astype(dtype)[:, None]
    return n if pooling == "mean" else jnp.sqrt(n)


def pool_rows(rows: jnp.ndarray, ids: jnp.ndarray, pooling: str,
              pad_id: int, vocab: Optional[int] = None) -> jnp.ndarray:
    """[B, L, dim] -> [B, dim] combiner. Padding rows are zero by contract,
    so the sum needs no mask; mean/sqrtn divide by the true lengths."""
    if pooling not in POOLINGS:
        raise ValueError(f"unknown pooling {pooling!r}; known: {POOLINGS}")
    if rows.ndim != 3:
        raise ValueError(
            f"pooling needs [B, L, dim] rows, got shape {rows.shape} — "
            "sequence features take [B, L] padded id matrices")
    s = jnp.sum(rows, axis=1)
    return s / _scale(pooling, ids, pad_id, vocab, s.dtype)


def expand_pooled_grads(g: jnp.ndarray, ids: jnp.ndarray, pooling: str,
                        pad_id: int,
                        vocab: Optional[int] = None) -> jnp.ndarray:
    """VJP of :func:`pool_rows` wrt the rows: [B, dim] -> [B, L, dim].

    Every valid slot receives the pooled grad (scaled for mean/sqrtn);
    padding slots receive it too but their invalid ids make the update a
    no-op downstream, keeping the expansion mask-free.
    """
    if pooling not in POOLINGS:
        raise ValueError(f"unknown pooling {pooling!r}; known: {POOLINGS}")
    scaled = g / _scale(pooling, ids, pad_id, vocab, g.dtype)
    return jnp.broadcast_to(scaled[:, None, :],
                            (ids.shape[0], ids.shape[1], g.shape[-1]))
