"""Ragged / variable-length sequence features: padding + pooling.

Capability parity with the reference's RaggedTensor lookups
(/root/reference/openembedding/tensorflow/exb.py:315-321 — ``sparse_read``
maps flat values of a RaggedTensor through the pull op) and TF's sparse
combiners (sum / mean / sqrtn). Dynamic row lengths are hostile to XLA, so
the TPU-native shape is **padded [B, L] id matrices**:

* padding slots hold an *invalid* id — ``-1`` for bounded vocabs, the hash
  EMPTY sentinel for hash variables (``pad_id_for``). The framework-wide
  invalid-index contract (zero pull rows, dropped gradients) then makes the
  padding mathematically inert with no extra masks.
* pooling is declared on the spec (``EmbeddingSpec(pooling="mean")``):
  ``EmbeddingCollection.pull`` reduces ``[B, L, dim] -> [B, dim]`` and
  ``apply_gradients`` expands the pooled row-gradient with the matching
  VJP — the same custom-gradient structure the reference builds around its
  pull op (exb.py:89-104).

``sum``: plain sum (padding rows are zero). ``mean``: sum / count of valid
ids (clamped at 1). ``sqrtn``: sum / sqrt(count) — TF's third combiner.
"""

from __future__ import annotations

from typing import Iterable, Optional, Sequence

import jax.numpy as jnp
import numpy as np

POOLINGS = ("sum", "mean", "sqrtn")


def pad_id_for(spec) -> int:
    """Canonical padding id for one EmbeddingSpec's key space.

    Wide (64-bit pair) hash features pad with the EMPTY *hi word*
    (INT32_MIN): a ``[B, L, 2]`` id matrix's padding slots carry
    ``(EMPTY, EMPTY)`` pairs, and a pair is invalid iff its hi word is
    EMPTY — the framework-wide wide-key invalidity rule."""
    if spec.use_hash:
        from . import hash_table as hash_lib
        if spec.key_dtype == "wide":
            return hash_lib.empty_key(jnp.int32)
        return hash_lib.empty_key(jnp.dtype(spec.key_dtype))
    return -1


def pad_ragged(sequences: Iterable[Sequence[int]],
               max_len: Optional[int] = None,
               pad_id: int = -1,
               dtype=np.int32) -> np.ndarray:
    """Host-side: list of variable-length id lists -> [B, L] padded matrix.

    Sequences longer than ``max_len`` keep their most recent ``max_len`` ids
    (recommendation behavior histories truncate from the front).
    """
    info = np.iinfo(np.dtype(dtype))
    if not (info.min <= pad_id <= info.max):
        raise ValueError(
            f"pad_id {pad_id} does not fit dtype {np.dtype(dtype)} — for "
            "int64-keyed hash features pass dtype=np.int64 (numpy would "
            "silently wrap the sentinel onto a valid key)")
    seqs = [np.asarray(s, dtype=dtype).ravel() for s in sequences]
    if max_len is None:
        max_len = max((s.size for s in seqs), default=1) or 1
    out = np.full((len(seqs), max_len), pad_id, dtype=dtype)
    for i, s in enumerate(seqs):
        if s.size > max_len:
            s = s[-max_len:]
        out[i, :s.size] = s
    return out


def pad_ragged_wide(sequences: Iterable[Sequence[int]],
                    max_len: Optional[int] = None) -> np.ndarray:
    """Host-side: variable-length INT64 id lists -> [B, L, 2] padded pair
    matrix (``hash_table.split64`` per id; padding slots are (EMPTY, EMPTY)
    pairs, invalid by the hi-word rule). The wide twin of
    :func:`pad_ragged` for x64-off processes addressing the 2^62 space."""
    from . import hash_table as hash_lib
    empty = hash_lib.empty_key(jnp.int32)
    seqs = [np.asarray(s, dtype=np.int64).ravel() for s in sequences]
    if max_len is None:
        max_len = max((s.size for s in seqs), default=1) or 1
    out = np.full((len(seqs), max_len, 2), empty, dtype=np.int32)
    for i, s in enumerate(seqs):
        if s.size > max_len:
            s = s[-max_len:]
        if s.size:
            pairs = hash_lib.split64(s)
            # ids in [-2^63, -2^63+2^32) split to hi == EMPTY — they would
            # read as padding and be silently dropped; the wide encoding
            # excludes that band (same guard as the checkpoint loader)
            banded = pairs[:, 1] == empty
            if banded.any():
                raise ValueError(
                    f"sequence {i}: {int(banded.sum())} id(s) fall in the "
                    "wide-key EMPTY band (ids in [-2^63, -2^63+2^32)); "
                    "the pair encoding excludes that range")
            out[i, :s.size] = pairs
    return out


def valid_mask(ids: jnp.ndarray, pad_id: int,
               vocab: Optional[int] = None,
               wide: bool = False) -> jnp.ndarray:
    """[B, L] bool: slots holding a real id (pull's validity contract).
    ``wide``: ids are [B, L, 2] pairs, invalid iff the hi word is EMPTY."""
    if wide:
        return ids[..., 1] != jnp.asarray(pad_id, ids.dtype)
    if vocab is not None and pad_id == -1:
        return (ids >= 0) & (ids < vocab)
    return ids != jnp.asarray(pad_id, ids.dtype)


def seq_lengths(ids: jnp.ndarray, pad_id: int,
                vocab: Optional[int] = None,
                wide: bool = False) -> jnp.ndarray:
    """[B] count of valid ids per row (clamped below at 1 for division)."""
    n = jnp.sum(valid_mask(ids, pad_id, vocab, wide), axis=-1)
    return jnp.maximum(n, 1)


def _scale(pooling: str, ids: jnp.ndarray, pad_id: int,
           vocab: Optional[int], dtype, wide: bool) -> jnp.ndarray:
    """[B, 1] divisor applied to the pooled sum (and to expanded grads)."""
    if pooling == "sum":
        return jnp.ones((ids.shape[0], 1), dtype)
    n = seq_lengths(ids, pad_id, vocab, wide).astype(dtype)[:, None]
    return n if pooling == "mean" else jnp.sqrt(n)


def pool_rows(rows: jnp.ndarray, ids: jnp.ndarray, pooling: str,
              pad_id: int, vocab: Optional[int] = None,
              wide: bool = False) -> jnp.ndarray:
    """[B, L, dim] -> [B, dim] combiner. Padding rows are zero by contract,
    so the sum needs no mask; mean/sqrtn divide by the true lengths.
    ``wide``: ids are [B, L, 2] (lo, hi) pairs (full 64-bit key space,
    reference RaggedTensor-over-hash lookups, exb.py:315-321)."""
    if pooling not in POOLINGS:
        raise ValueError(f"unknown pooling {pooling!r}; known: {POOLINGS}")
    if rows.ndim != 3:
        raise ValueError(
            f"pooling needs [B, L, dim] rows, got shape {rows.shape} — "
            "sequence features take [B, L] padded id matrices "
            "([B, L, 2] pair matrices for wide keys)")
    s = jnp.sum(rows, axis=1)
    return s / _scale(pooling, ids, pad_id, vocab, s.dtype, wide)


def expand_pooled_grads(g: jnp.ndarray, ids: jnp.ndarray, pooling: str,
                        pad_id: int,
                        vocab: Optional[int] = None,
                        wide: bool = False) -> jnp.ndarray:
    """VJP of :func:`pool_rows` wrt the rows: [B, dim] -> [B, L, dim].

    Every valid slot receives the pooled grad (scaled for mean/sqrtn);
    padding slots receive it too but their invalid ids make the update a
    no-op downstream, keeping the expansion mask-free.
    """
    if pooling not in POOLINGS:
        raise ValueError(f"unknown pooling {pooling!r}; known: {POOLINGS}")
    scaled = g / _scale(pooling, ids, pad_id, vocab, g.dtype, wide)
    return jnp.broadcast_to(scaled[:, None, :],
                            (ids.shape[0], ids.shape[1], g.shape[-1]))
