"""``sparse_as_dense`` hybrid: small-vocab embeddings as dense params.

Capability parity with the reference's "Cache" mode: embeddings whose vocab
is small (``input_dim <= sparse_as_dense_size``, default 64, or smaller than
the batch) are kept as *worker-side dense variables* updated by the plain
dense optimizer and allreduced with the rest of the model, while big tables
stay on the sharded PS path — a documented ~+10% benchmark configuration
(/root/reference/openembedding/tensorflow/exb.py:100-104,241-248 gather +
unsorted_segment_sum variables; exb.py:617-632 automatic threshold at model
conversion; documents/en/benchmark.md:24-37).

TPU-native shape: a dense-kept feature is an ordinary flax param (replicated
over the mesh, optax-updated, grads all-reduced by XLA over the data axis).
JAX differentiates the gather into exactly the scatter-add the reference
hand-writes as its custom gradient. Like the reference, dense-kept features
follow *dense* optimizer semantics (momentum/decay applied every step, not
only on touched rows — README.md:240 documents the same divergence).

Usage::

    specs = make_feature_specs(names, vocabs, dim)
    sparse_specs, dense_specs = split_sparse_dense(specs, 64)
    coll = EmbeddingCollection(sparse_specs, mesh)
    trainer = Trainer(model, coll, optax.adagrad(...),
                      sparse_as_dense=dense_specs)

The Trainer wraps the model so dense-kept rows are computed inside the flax
apply; batches keep one ``sparse`` dict — the Trainer routes each column to
the right path by name.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

import jax.numpy as jnp
from flax import linen as nn

from .embedding import EmbeddingSpec
from .optim.initializers import make_initializer
from . import table as table_lib


@dataclasses.dataclass(frozen=True)
class DenseFeatureSpec:
    """Static description of one dense-kept (sparse_as_dense) feature."""

    name: str
    input_dim: int
    output_dim: int
    dtype: str = "float32"
    initializer: Optional[tuple] = None  # frozen config items or None
    pooling: Optional[str] = None        # sequence combiner, as EmbeddingSpec


def _freeze_config(cfg) -> Optional[tuple]:
    if cfg is None:
        return None
    if isinstance(cfg, dict):
        return tuple(sorted(cfg.items()))
    return cfg


def _thaw_config(cfg):
    return dict(cfg) if isinstance(cfg, tuple) else cfg


def to_dense_spec(spec: EmbeddingSpec) -> DenseFeatureSpec:
    if spec.use_hash:
        raise ValueError(
            f"hash variable {spec.name!r} cannot be kept dense "
            "(unbounded key space; the reference's threshold only ever "
            "converts bounded vocabs, exb.py:617-632)")
    return DenseFeatureSpec(
        name=spec.name, input_dim=spec.input_dim, output_dim=spec.output_dim,
        dtype=spec.dtype, initializer=_freeze_config(spec.initializer),
        pooling=spec.pooling)


def split_sparse_dense(specs: Sequence[EmbeddingSpec],
                       sparse_as_dense_size: int = 64,
                       batch_size: Optional[int] = None
                       ) -> Tuple[Tuple[EmbeddingSpec, ...],
                                  Tuple[DenseFeatureSpec, ...]]:
    """Partition specs into (sharded sparse, dense-kept) by vocab size.

    The reference's conversion rule (exb.py:602,617-632): bounded vocab
    ``<= sparse_as_dense_size`` — or smaller than the global batch, when
    given — is cheaper as a dense variable than as PS traffic.
    """
    sparse, dense = [], []
    for spec in specs:
        small = (not spec.use_hash) and (
            spec.input_dim <= sparse_as_dense_size
            or (batch_size is not None and spec.input_dim < batch_size))
        (dense if small else sparse).append(spec)
    return tuple(sparse), tuple(to_dense_spec(s) for s in dense)


class DenseEmbeddings(nn.Module):
    """Flax module owning the dense-kept embedding tables.

    Lookup keeps the framework's invalid-index contract (negative or
    out-of-range ids -> zero rows, gradients dropped), so a feature behaves
    identically on either path.
    """

    specs: Tuple[DenseFeatureSpec, ...]

    @nn.compact
    def __call__(self, ids: Dict[str, jnp.ndarray]
                 ) -> Dict[str, jnp.ndarray]:
        rows = {}
        for s in self.specs:
            if s.name not in ids:
                continue
            init = make_initializer(
                _thaw_config(s.initializer) or table_lib.DEFAULT_INITIALIZER)
            table = self.param(
                s.name,
                lambda key, shape, dtype, _i=init: _i.init(key, shape, dtype),
                (s.input_dim, s.output_dim), jnp.dtype(s.dtype))
            idx = ids[s.name]
            flat = idx.ravel()
            valid = (flat >= 0) & (flat < s.input_dim)
            r = jnp.take(table, jnp.where(valid, flat, 0), axis=0,
                         mode="clip")
            r = jnp.where(valid[:, None], r, jnp.zeros_like(r))
            r = r.reshape(idx.shape + (s.output_dim,))
            if s.pooling:
                # pooled sequence features combine here; autodiff provides
                # the VJP the sharded path writes by hand
                from . import ragged
                r = ragged.pool_rows(r, idx, s.pooling, -1, s.input_dim)
            rows[s.name] = r
        return rows


class HybridModel(nn.Module):
    """Inner CTR model + dense-kept embeddings in one flax apply.

    ``__call__(dense, rows, dense_ids)``: looks up ``dense_ids`` in the
    module-owned tables, merges with the sharded-path ``rows`` and runs the
    inner model — the reference's converted model where some Embedding
    layers became plain tf.Variables and the rest PS variables.
    """

    inner: nn.Module
    dense_specs: Tuple[DenseFeatureSpec, ...]

    @nn.compact
    def __call__(self, dense, rows: Dict[str, jnp.ndarray],
                 dense_ids: Dict[str, jnp.ndarray]):
        drows = DenseEmbeddings(self.dense_specs, name="sparse_as_dense")(
            dense_ids)
        return self.inner(dense, {**rows, **drows})
