"""Sharded checkpoint save/load + dense export.

Capability parity with the reference's dump/load pipeline (SURVEY §3.4;
/root/reference/openembedding/server/EmbeddingDumpOperator.cpp,
EmbeddingLoadOperator.cpp, client/Model.cpp:89-134):

* ``<path>/model_meta`` — the same self-describing JSON head (model_sign,
  ordered variable metas, format version; reference Meta.h "0.2", ours
  ``META_FORMAT_VERSION``). Load validates variable metas match before
  touching any table (Model.cpp:110-121).
* per-variable ``var_<id>_<name>.d/{weights,slot_*,keys}.npy`` —
  logical-row-order arrays (+ named optimizer-state files when
  ``include_optimizer``, the reference's state_line_size != 0 flag,
  EmbeddingDumpOperator.cpp:36-76); hash variables store (keys, weights,
  states) triples of live rows only — the reference's streamed (indices,
  weights, states) blocks with re-globalized keys (EmbeddingShardFile.h:
  21-23). **Dump and load stream per-shard ~4MB blocks** (device slices on
  save, memmapped strided reads + direct per-device placement on load), so
  host memory stays bounded no matter the table size — the reference's
  server-side block streaming, not a whole-table host copy. Legacy
  single-file ``var_*.npz`` checkpoints still load.
* **Shard-topology independence**: arrays are written in *logical id order*
  (the physical mod-layout permutation is undone on save and re-applied on
  load), and hash rows are keyed — so a checkpoint taken on an 8-way mesh
  loads onto a 2-way mesh, like the reference re-shards by
  ``key % shard_num`` at load.
* ``export_dense`` — the ``save_as_original_model`` equivalent
  (exb.py:506-547): materializes every bounded variable as a dense array for
  serving without this framework; hash variables are rejected exactly like
  the reference (exb.py:536).

Dense flax params ride flax.serialization msgpack next to the sparse dump.
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization

from .analysis import scope
from .analysis.concurrency import sync_point
from .embedding import EmbeddingCollection
from .meta import ModelMeta
from . import hash_table as hash_lib
from . import table as table_lib
from .parallel import hot_cache
from .parallel import sharded_hash as sh
from .parallel import sharded_table as st
from .utils import fs

MODEL_META_FILE = "model_meta"
DENSE_FILE = "dense_state.msgpack"
_LOAD_CHUNK = 1 << 16
# streamed block granularity — the reference dumps ~1MB lines per shard
# (EmbeddingDumpOperator.cpp:84-87 server_block_num_items)
_BLOCK_BYTES = 4 << 20


def _var_file(variable_id: int, name: str) -> str:
    safe = name.replace("/", "_").replace(":", "__")
    return f"var_{variable_id}_{safe}.npz"


def _var_dir(variable_id: int, name: str) -> str:
    safe = name.replace("/", "_").replace(":", "__")
    return f"var_{variable_id}_{safe}.d"


def _logical_perm(spec: st.ShardingSpec) -> np.ndarray:
    """physical position of logical row r under the sharded layout."""
    r = np.arange(spec.padded_vocab, dtype=np.int64)
    shard = r % spec.num_shards if spec.layout == "mod" else r // spec.rows_per_shard
    local = r // spec.num_shards if spec.layout == "mod" else r % spec.rows_per_shard
    return shard * spec.rows_per_shard + local


def _logical_slice(spec: st.ShardingSpec, vocab: int, phys_start: int,
                   n: int):
    """(file_slice, n_valid) for physical rows [phys_start, phys_start+n).

    A physical block lies inside one shard, and a shard's logical rows form
    a *basic* numpy slice of the logical-order file — strided (every Nth
    row) under "mod", contiguous under "div" — so both dump and load move
    data with plain slice assignments, never fancy indexing.
    """
    rps = spec.rows_per_shard
    s = phys_start // rps
    l0 = phys_start % rps
    assert (phys_start + n - 1) // rps == s, "block crosses a shard boundary"
    if spec.layout == "mod":
        # shard s owns logical rows l*N + s; valid while < vocab
        nv_shard = max(0, -(-(vocab - s) // spec.num_shards)) \
            if s < vocab else 0
        nv = max(0, min(n, nv_shard - l0))
        N = spec.num_shards
        return slice(s + l0 * N, s + (l0 + nv) * N, N), nv
    nv = max(0, min(n, vocab - phys_start))
    return slice(phys_start, phys_start + nv), nv


def _iter_shard_blocks(arr):
    """Yield (physical_row_start, host_block) in bounded blocks per shard.

    Streams each addressable shard device->host in ~_BLOCK_BYTES slices —
    the dump never materializes the whole table on the host, matching the
    reference's per-shard block streaming (EmbeddingDumpOperator.cpp:50-96).
    Replicated shards (psum plane: data-axis copies) are emitted once.
    """
    shards = sorted((s for s in arr.addressable_shards if s.replica_id == 0),
                    key=lambda s: s.index[0].start or 0)
    for shard in shards:
        data = shard.data
        rows = data.shape[0]
        if not rows:
            continue
        start = shard.index[0].start or 0
        row_bytes = max(1, data.nbytes // rows)
        per = max(1, _BLOCK_BYTES // row_bytes)
        for lo in range(0, rows, per):
            hi = min(rows, lo + per)
            yield start + lo, np.asarray(jax.device_get(data[lo:hi]))


def _sync(name: str) -> None:
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(name)


# --- parallel shard writers --------------------------------------------------

# window granularity of the PARALLEL full-save path: small enough that a
# single-table dump still fans out across writers, large enough that each
# task's file region writes sequentially at disk bandwidth
_PAR_WINDOW_BYTES = 32 << 20


def _default_writers() -> int:
    """Writer-thread pool width (``OE_CKPT_WRITERS`` overrides; 1 =
    serialized, the pre-parallel behavior bit-for-bit)."""
    env = os.environ.get("OE_CKPT_WRITERS", "")
    if env:
        return max(1, int(env))
    return max(1, min(8, os.cpu_count() or 1))


def _run_writers(tasks, *, max_workers: Optional[int] = None) -> None:
    """Run writer callables on a bounded pool of named, joined threads.

    The parallelism unit of both the full-save and delta-save paths:
    every task owns a DISJOINT file region (its own file, or its own
    window/shard slice of a pre-sized memmap), so tasks never contend on
    bytes — only on the device-get and disk queues, which is the point
    (device->host streams for shard A overlap disk writes for shard B).
    Threads are non-daemon and always joined here (graftrace JG104);
    the first task error is re-raised after the join, remaining queued
    tasks are abandoned (their files are tmp/partial debris the next
    save's GC or overwrite cleans up).
    """
    tasks = deque(tasks)
    if not tasks:
        return
    n = min(len(tasks), max_workers or _default_writers())
    if n <= 1:
        while tasks:
            sync_point("ckpt.writer.run")
            tasks.popleft()()
        return
    errs: list = []

    def _drain():
        while not errs:
            try:
                task = tasks.popleft()   # deque.popleft is atomic
            except IndexError:
                return
            try:
                sync_point("ckpt.writer.run")
                task()
            except BaseException as e:  # noqa: BLE001 — re-raised at join
                errs.append(e)
                return

    threads = [threading.Thread(target=_drain, daemon=False,
                                name=f"oe-ckpt-writer-{i}")
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errs:
        raise errs[0]


def _sorted_shards(arr):
    return sorted((s for s in arr.addressable_shards if s.replica_id == 0),
                  key=lambda s: s.index[0].start or 0)


def gather_logical_window(shards, sspec: st.ShardingSpec, l0: int, l1: int,
                          row_shape: tuple, dtype) -> np.ndarray:
    """Assemble logical rows ``[l0, l1)`` of a sharded array into one host
    buffer. Each shard's contribution is a CONTIGUOUS device slice (bulk
    transfer); the mod-layout interleave happens in the staging buffer.
    Shared by the full-save window writers and the delta-chunk writers.
    """
    S, rps = sspec.num_shards, sspec.rows_per_shard
    buf = np.empty((l1 - l0,) + row_shape, dtype)
    for sh_ in shards:
        p0 = sh_.index[0].start or 0
        s = p0 // rps
        if sspec.layout == "mod":
            # shard s owns logical ids l = local * S + s
            lo_s = max(0, -(-(l0 - s) // S))
            hi_s = max(0, -(-(l1 - s) // S))
            hi_s = min(hi_s, sh_.data.shape[0])
            if hi_s <= lo_s:
                continue
            block = np.asarray(jax.device_get(sh_.data[lo_s:hi_s]))
            a = s + lo_s * S - l0
            buf[a:a + (hi_s - lo_s - 1) * S + 1:S] = block
        else:
            # div layout: logical == physical position
            a = max(l0, p0)
            b = min(l1, p0 + sh_.data.shape[0])
            if b <= a:
                continue
            block = np.asarray(jax.device_get(sh_.data[a - p0:b - p0]))
            buf[a - l0:b - l0] = block
    return buf


def save_checkpoint(path: str,
                    collection: EmbeddingCollection,
                    states: Dict[str, Any],
                    *,
                    dense_state: Any = None,
                    include_optimizer: bool = True,
                    model_sign: str = "",
                    compress: str = "",
                    mode: str = "full",
                    step: int = 0,
                    max_workers: Optional[int] = None,
                    extra: Optional[Dict[str, Any]] = None
                    ) -> Dict[str, Any]:
    """Dump all embedding variables (+ optional dense pytree) under ``path``.

    Works single- or multi-host: with N > 1 processes each host streams its
    own shards into per-host part files (the reference's per-node
    ``model_<node>_<fileid>`` dump layout, EmbeddingDumpOperator.cpp:28) —
    ``path`` must be a shared filesystem. Rank 0 writes the meta; barriers
    bracket the writes.

    ``path`` may be an fsspec URI (``gs://``, ``s3://``, ``hdfs://``,
    ``memory://``): remote dumps always use the keyed part format, whose
    writes are purely SEQUENTIAL streams — the reference's piped
    hadoop shard files (EmbeddingShardFile.h:57-63). Local paths keep the
    memmapped logical-order format.

    ``compress``: codec for the block streams (``""``/``"zlib"``/
    ``"zstd"`` — the reference's ``server.message_compress`` knob applied
    to its shard-file streams, client/EnvConfig.cpp:27-34). Compressed
    dumps use the keyed part format with ``.npyz`` framed streams; every
    Python load path reads them transparently, but the native mmap
    serving library (``native/oe_serving.cc``) needs raw ``.npy`` — keep
    serving dumps uncompressed.

    ``mode="delta"``: write only the chunks dirtied since the last save
    (``collection.enable_dirty_tracking()`` must be armed) as one delta
    appended to the checkpoint's chain — the reference's ICDE'23
    incremental checkpoints (``checkpoint_delta.py``). Falls back to a
    FULL save (recorded as ``forced_full``) when no base exists yet or
    the chain was just compacted away. ``step`` stamps the save for the
    serving hot-swap version protocol. Local full saves fan out over
    parallel per-shard writer threads (``OE_CKPT_WRITERS`` /
    ``max_workers``; 1 serializes). Returns an info dict
    (mode/bytes/seconds, plus seq/chain length for delta saves).

    ``extra``: JSON-serializable bookkeeping committed with the save —
    delta saves stamp their chain entry, full saves the re-armed
    manifest base. ``load_checkpoint(info=...)`` returns it as
    ``info["resume_extra"]`` resolved against what the load actually
    applied (the ``Trainer.fit`` autosave/resume channel).
    """
    if mode not in ("full", "delta"):
        raise ValueError(f"unknown checkpoint mode {mode!r}; "
                         "use 'full' or 'delta'")
    import time as _time
    from .utils import observability
    with scope.span("checkpoint.save", detail={"mode": mode}):
        if mode == "delta":
            from . import checkpoint_delta as cd
            return cd.save_delta(
                path, collection, states, step=step,
                dense_state=dense_state,
                include_optimizer=include_optimizer, compress=compress,
                model_sign=model_sign, max_workers=max_workers,
                extra=extra)
        t0 = _time.perf_counter()
        nbytes = _save_checkpoint_impl(
            path, collection, states, dense_state=dense_state,
            include_optimizer=include_optimizer, model_sign=model_sign,
            compress=compress, step=step, max_workers=max_workers,
            extra=extra)
        dt = _time.perf_counter() - t0
        observability.record_ckpt_save("full", nbytes, dt, chain_len=0)
        return {"mode": "full", "bytes": int(nbytes),
                "seconds": dt, "seq": 0}


def _save_checkpoint_impl(path: str,
                          collection: EmbeddingCollection,
                          states: Dict[str, Any],
                          *,
                          dense_state: Any,
                          include_optimizer: bool,
                          model_sign: str,
                          compress: str,
                          step: int = 0,
                          max_workers: Optional[int] = None,
                          extra: Optional[Dict[str, Any]] = None) -> int:
    """Full dump; returns the logical bytes written (table rows + slots,
    pre-compression — the rate the ``ckpt_write_gbps`` gauge reports)."""
    from . import checkpoint_delta as cd
    from .utils import compress as compress_lib
    compress = compress_lib.check(compress)
    nproc = jax.process_count()
    rank = jax.process_index()
    remote = fs.is_remote(path)
    fs.makedirs(path)
    # a running background compactor owns this directory's base files —
    # join it (and surface its error) BEFORE touching anything, or its
    # folded-file renames would land over the fresh base mid-save
    if not remote:
        cd.join_compactor(path)
    # a full save RESETS any existing delta chain FIRST (manifest removed
    # before base files change): a crash mid-save must leave either the
    # old chain intact-and-referenced or no chain at all — never a stale
    # chain replayed over a half-new base (checkpoint_delta.reset_chain).
    # The old chain's last_seq is captured BEFORE the reset and carried
    # into the re-arm below: seqs are burned, never reused — re-arming
    # at 0 would hand the next delta a seq every serving replica has
    # already applied, so replicas would ack it as stale and silently
    # stop updating (graftproto delta_chain `full_save_resets_seq`)
    carried_seq = 0
    if rank == 0:
        if not remote:
            try:
                prev_manifest = cd.read_manifest(path)
            except ValueError:
                prev_manifest = None  # unknown-format manifest: reset anyway
            if prev_manifest is not None:
                carried_seq = int(prev_manifest.get("last_seq", 0))
            else:
                # no manifest, but the dir may still carry a burn
                # counter in its meta: a NON-arming full save (part/
                # compressed/remote layouts, or tracker-less) records
                # it there below, so the seq line survives a format
                # roundtrip instead of silently restarting at 0
                carried_seq = _prev_meta_last_seq(path)
        sync_point("ckpt.full.reset")
        cd.reset_chain(path)
    # trackers snapshot at the START: marks landing during the save refer
    # to pushes on NEWER state objects than the pytree being dumped, and
    # must survive for the next delta
    for tracker in collection.dirty_trackers.values():
        tracker.snapshot_clear()
    meta = collection.model_meta(model_sign=model_sign, model_uri=path)
    meta.extra["include_optimizer"] = bool(include_optimizer)
    if nproc > 1:
        meta.extra["num_parts"] = nproc
    # persist hash-table geometry so a loader (e.g. the serving registry,
    # which rebuilds specs from this meta alone) allocates tables that can
    # hold every stored row — the reference's load path delivers every row
    # or fails (EmbeddingLoadOperator.cpp:58-111)
    hash_info = {
        name: {"hash_capacity": spec.hash_capacity,
               "key_dtype": spec.key_dtype}
        for name, spec in collection.specs.items() if spec.use_hash
    }
    if hash_info:
        meta.extra["hash_variables"] = hash_info
    # per-field storage dtypes ("tpu-2"): numpy serializes non-native
    # dtypes (ml_dtypes bfloat16 — the at-rest precision-ladder rung) as
    # opaque '<V2' descrs; loaders view such chunks back under the TRUE
    # dtype recorded here, then cast to the target (upcast on load)
    # hot-swap burn counter, persisted OUTSIDE the manifest too: layouts
    # that cannot arm a chain (part/compressed/remote, or no trackers)
    # would otherwise drop it at reset_chain, and the next arming save
    # would restart seqs at 0 — replicas then ack real deltas as stale
    meta.extra["delta_last_seq"] = int(carried_seq)
    meta.extra["storage_dtypes"] = {
        name: _field_dtypes(hot_cache.unwrap(states[name]),
                            include_optimizer)
        for name in collection.specs
    }
    if rank == 0:
        with fs.open_file(fs.join(path, MODEL_META_FILE), "wb") as f:
            f.write(meta.dumps().encode("utf-8"))
        for name in collection.specs:
            vdir = fs.join(
                path, _var_dir(collection.variable_id(name), name))
            if fs.isdir(vdir):
                # a previous save under a different optimizer could leave
                # stale slot files a later load would mistake for state
                fs.rmtree(vdir)
            fs.makedirs(vdir)
    _sync("ckpt_dirs_ready")

    tasks: list = []
    finals: list = []
    nbytes = 0
    for name, spec in collection.specs.items():
        # a hot-row replica (a2a+cache plane) is derived state: only the
        # authoritative table is dumped
        state = hot_cache.unwrap(states[name])
        vid = collection.variable_id(name)
        vdir = fs.join(path, _var_dir(vid, name))
        part = f"part{rank}_" if (nproc > 1 or remote or compress) else ""
        if spec.use_hash:
            if part:
                _save_hash_var(vdir, state, include_optimizer, part=part,
                               compress=compress)
                nbytes += _hash_state_bytes(state, include_optimizer)
            else:
                t, f, b = _hash_save_tasks(vdir, state, include_optimizer)
                tasks += t
                finals += f
                nbytes += b
        elif nproc > 1 or remote or compress:
            # compressed dumps ride the sequential part format — framed
            # streams have no memmap representation
            _save_array_var_part(vdir, rank, state,
                                 collection.sharding_spec(name),
                                 spec.input_dim, include_optimizer,
                                 compress=compress)
            nbytes += _array_state_bytes(state, spec.input_dim,
                                         collection.sharding_spec(name),
                                         include_optimizer)
        else:
            t, f, b = _array_save_tasks(vdir, state,
                                        collection.sharding_spec(name),
                                        spec.input_dim, include_optimizer)
            tasks += t
            finals += f
            nbytes += b
    # the parallel shard writers: every task owns a disjoint file region
    # (a logical window of one field's memmap, or one shard's contiguous
    # slice of a hash dump), so device->host streams and disk writes for
    # different shards overlap instead of serializing through one stream
    _run_writers(tasks, max_workers=max_workers)
    for fin in finals:
        fin()

    if dense_state is not None and rank == 0:
        with fs.open_file(fs.join(path, DENSE_FILE), "wb") as f:
            f.write(serialization.to_bytes(jax.device_get(dense_state)))
    if rank == 0 and collection.dirty_trackers \
            and not (nproc > 1 or remote or compress):
        # arm the delta chain: later mode="delta" saves append to this
        # base (the manifest is the single commit point for the chain).
        # ONLY the local uncompressed single-process layout arms —
        # part/compressed/remote bases have no raw .npy files for the
        # compactor to fold, so a chain over them could never rebase;
        # a delta save into such a dir stays forced-full (and rewrites
        # the base raw)
        sync_point("ckpt.full.arm")
        cd.init_manifest(path, step=step,
                         include_optimizer=include_optimizer,
                         last_seq=carried_seq, extra=extra)
    _sync("ckpt_done")
    return nbytes


def _prev_meta_last_seq(path: str) -> int:
    """Burn counter recorded by a previous save's meta (0 when the dir
    is fresh, pre-counter, or unreadable — matching the chain-less
    default)."""
    mpath = fs.join(path, MODEL_META_FILE)
    try:
        if not fs.exists(mpath):
            return 0
        with fs.open_file(mpath, "rb") as f:
            meta = ModelMeta.loads(f.read().decode("utf-8"))
        return int(meta.extra.get("delta_last_seq", 0))
    except Exception:  # noqa: BLE001 — a corrupt old meta never blocks
        return 0       # a full save; the save rewrites it wholesale


def _field_dtypes(state, include_optimizer: bool) -> Dict[str, str]:
    """name -> numpy dtype string of every dumped field of one state."""
    out = {"weights": np.dtype(state.weights.dtype).name}
    if hasattr(state, "keys"):
        out["keys"] = np.dtype(state.keys.dtype).name
    if include_optimizer:
        for sname, sval in state.slots.items():
            out[f"slot_{sname}"] = np.dtype(sval.dtype).name
    return out


def _decode_rows(arr, true_dtype: Optional[str], target_dtype,
                 legacy_dtype: Optional[str] = None):
    """One stored chunk -> rows castable to ``target_dtype``.

    Opaque void descrs (numpy's serialization of ml_dtypes bfloat16)
    are viewed back under their TRUE dtype — the "tpu-2" meta records
    it per field. Absent (a "tpu-1" dump), the target dtype stands in
    when the itemsize matches (the pre-existing remote-path contract),
    then ``legacy_dtype`` — the dump's TABLE datatype, because tpu-1
    slots were stored at the table dtype, so a pre-ladder bf16 dump's
    slot chunks are bf16 even though today's slot target is f32. The
    final cast is the transparent up/down-conversion of a dtype
    migration (f32 dump -> bf16 table and vice versa).
    """
    arr = np.asarray(arr)
    target = np.dtype(target_dtype)
    if arr.dtype.kind == "V":
        for cand in (true_dtype, target, legacy_dtype):
            if cand is not None \
                    and np.dtype(cand).itemsize == arr.dtype.itemsize:
                arr = arr.view(np.dtype(cand))
                break
        else:
            raise ValueError(
                f"stored void chunk of itemsize {arr.dtype.itemsize} "
                f"matches none of (recorded={true_dtype!r}, "
                f"target={target}, dump table dtype={legacy_dtype!r}) "
                "— checkpoint storage_dtypes out of sync with the data "
                "files")
    return arr if arr.dtype == target else arr.astype(target)


def _array_state_bytes(state, vocab: int, sspec: st.ShardingSpec,
                       include_optimizer: bool) -> int:
    per_row = state.weights.nbytes // max(1, state.weights.shape[0])
    if include_optimizer:
        per_row += sum(v.nbytes // max(1, v.shape[0])
                       for v in state.slots.values())
    return int(vocab) * int(per_row)


def _hash_state_bytes(state, include_optimizer: bool,
                      live_rows: Optional[int] = None) -> int:
    cap = max(1, state.keys.shape[0])
    if live_rows is None:
        live_rows = int(jax.device_get(state.num_used()))
    per_row = state.keys.nbytes // cap + state.weights.nbytes // cap
    if include_optimizer:
        per_row += sum(v.nbytes // cap for v in state.slots.values())
    return int(live_rows) * int(per_row)


def _array_save_tasks(vdir: str, state, sspec: st.ShardingSpec, vocab: int,
                      include_optimizer: bool):
    """Writer tasks dumping one bounded variable to
    ``<vdir>/{weights,slot_*}.npy``; returns ``(tasks, finals, bytes)``.

    Arrays are written in *logical id order* (only the real vocab rows —
    padding rows differ across mesh shapes and are unreachable), so the
    checkpoint is shard-topology independent. Each TASK owns one logical
    WINDOW of one field's pre-sized memmap: each shard's contribution to
    a window is a CONTIGUOUS slice of its device buffer (device reads
    stay bulk transfers), the mod-layout interleave happens in a RAM
    staging buffer, and the window is written as one sequential region —
    strided memmap writes measured 0.015 GB/s on local disk
    (page-granularity random IO); window regions run at disk bandwidth.
    Windows are disjoint file regions, so ``_run_writers`` streams them
    concurrently; host memory stays bounded by window size x writers.
    """
    targets = {"weights": state.weights}
    if include_optimizer:
        for sname, sval in state.slots.items():
            targets[f"slot_{sname}"] = sval
    tasks, finals = [], []
    nbytes = 0
    for fname, arr in targets.items():
        dtype = np.dtype(arr.dtype)
        row_shape = arr.shape[1:]
        row_bytes = max(1, int(np.prod(row_shape, dtype=np.int64))
                        * dtype.itemsize)
        win = max(1, _PAR_WINDOW_BYTES // row_bytes)
        shards = _sorted_shards(arr)
        mm = np.lib.format.open_memmap(
            os.path.join(vdir, fname + ".npy"), mode="w+",
            dtype=dtype, shape=(vocab,) + row_shape)
        nbytes += vocab * row_bytes

        def _write(l0, l1, mm=mm, shards=shards, row_shape=row_shape,
                   dtype=dtype):
            mm[l0:l1] = gather_logical_window(shards, sspec, l0, l1,
                                              row_shape, dtype)

        for l0 in range(0, vocab, win):
            tasks.append(partial(_write, l0, min(vocab, l0 + win)))

        def _finish(mm=mm):
            mm.flush()

        finals.append(_finish)
    return tasks, finals, nbytes


def _hash_save_tasks(vdir: str, state, include_optimizer: bool):
    """Writer tasks dumping one hash variable's live rows to
    ``<vdir>/{keys,weights,slot_*}.npy``; returns ``(tasks, finals,
    bytes)``.

    Pass 1 counts live rows per addressable shard on-device (cheap
    reductions), which fixes each shard's CONTIGUOUS destination range
    ``[offset_s, offset_s + count_s)`` in the pre-sized memmaps; one
    writer task per shard then streams that shard's blocks and writes
    the live subset — disjoint contiguous file regions, parallel across
    shards (``_run_writers``), same on-disk format as before.
    """
    empty = hash_lib.empty_key(np.dtype(state.keys.dtype))
    wide = hash_lib.is_wide(state.keys)
    key_dtype = np.dtype(state.keys.dtype)
    key_shards = _sorted_shards(state.keys)
    counts = []
    for s in key_shards:
        col = s.data[:, 1] if wide else s.data
        counts.append(int(jax.device_get(
            jnp.sum(col != np.asarray(empty, dtype=key_dtype)))))
    offsets = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
    total = int(offsets[-1])
    targets = {"keys": state.keys, "weights": state.weights}
    if include_optimizer:
        for sname, sval in state.slots.items():
            targets[f"slot_{sname}"] = sval
    shard_lists = {f: _sorted_shards(a) for f, a in targets.items()}
    mms = {}
    nbytes = 0
    for fname, arr in targets.items():
        mms[fname] = np.lib.format.open_memmap(
            os.path.join(vdir, fname + ".npy"), mode="w+",
            dtype=np.dtype(arr.dtype), shape=(total,) + arr.shape[1:])
        nbytes += total * max(1, int(np.prod(arr.shape[1:],
                                             dtype=np.int64))
                              * np.dtype(arr.dtype).itemsize)

    def _write_shard(i: int, off: int) -> None:
        datas = {f: sl[i].data for f, sl in shard_lists.items()}
        rows = datas["keys"].shape[0]
        if not rows:
            assert counts[i] == 0
            return
        row_bytes = sum(max(1, d.nbytes // rows) for d in datas.values())
        per = max(1, _BLOCK_BYTES // row_bytes)
        o = off
        for lo in range(0, rows, per):
            hi = min(rows, lo + per)
            blocks = {f: np.asarray(jax.device_get(d[lo:hi]))
                      for f, d in datas.items()}
            bk = blocks["keys"]
            # wide ([cap, 2]) keys: a slot is free iff its HI word is EMPTY
            live = (bk[:, 1] != empty) if wide else (bk != empty)
            n = int(live.sum())
            if n:
                for f, b in blocks.items():
                    mms[f][o:o + n] = b[live]
                o += n
        assert o - off == counts[i], (i, o - off, counts[i])

    tasks = [partial(_write_shard, i, int(offsets[i]))
             for i in range(len(key_shards))]

    def _finish():
        for mm in mms.values():
            mm.flush()

    return tasks, [_finish], nbytes


def _seq_writer(path_npy: str, dtype, shape, compress: str = ""):
    """Sequential block writer: raw ``.npy`` or, with a codec, the framed
    compressed ``.npyz`` container (``fs.NpyzWriter``)."""
    if compress:
        return fs.NpyzWriter(path_npy + "z", dtype, shape, compress)
    return fs.NpyWriter(path_npy, dtype, shape)


def _save_array_var_part(vdir: str, rank: int, state,
                         sspec: st.ShardingSpec, vocab: int,
                         include_optimizer: bool,
                         compress: str = "") -> None:
    """Multi-host / remote dump of one bounded variable: this process
    streams ITS addressable shards into keyed part files
    ``part<rank>_{ids,weights,slot_*}.npy`` (logical ids + rows) — the
    per-node dump files of the reference, re-shardable onto any mesh at
    load. Writes are purely sequential (``fs.NpyWriter``), so the same
    code path serves shared local filesystems and object stores."""
    targets = {"weights": state.weights}
    if include_optimizer:
        for sname, sval in state.slots.items():
            targets[f"slot_{sname}"] = sval
    # count this process's valid rows (shared across all targets)
    nv_total = 0
    shards = sorted(
        (s for s in state.weights.addressable_shards if s.replica_id == 0),
        key=lambda s: s.index[0].start or 0)
    for s in shards:
        _, nv = _logical_slice(sspec, vocab, s.index[0].start or 0,
                               s.data.shape[0])
        nv_total += nv
    with _seq_writer(fs.join(vdir, f"part{rank}_ids.npy"),
                     np.int64, (nv_total,), compress) as ids_w:
        for i, (fname, arr) in enumerate(targets.items()):
            with _seq_writer(
                    fs.join(vdir, f"part{rank}_{fname}.npy"),
                    np.dtype(arr.dtype),
                    (nv_total,) + arr.shape[1:], compress) as w:
                off = 0
                for phys_start, block in _iter_shard_blocks(arr):
                    sl, nv = _logical_slice(sspec, vocab, phys_start,
                                            block.shape[0])
                    if not nv:
                        continue
                    w.write(block[:nv])
                    if i == 0:
                        ids_w.write(np.arange(sl.start, sl.stop,
                                              sl.step or 1, dtype=np.int64))
                    off += nv
                assert off == nv_total, (fname, off, nv_total)


def _save_hash_var(vdir: str, state, include_optimizer: bool,
                   part: str = "", compress: str = "") -> None:
    """Stream one hash variable's live rows to ``<vdir>/<part>*.npy``.

    Pass 1 counts live rows per addressable shard on-device; pass 2 streams
    (keys, weights, states) blocks and writes the live subset — the
    reference's streamed (indices, weights, states) block dump with
    re-globalized keys (EmbeddingShardFile.h:21-23). ``part`` prefixes the
    files for multi-host dumps (each host writes only its shards).
    """
    empty = hash_lib.empty_key(np.dtype(state.keys.dtype))
    wide = hash_lib.is_wide(state.keys)
    total = sum(
        int(jax.device_get(jnp.sum(
            (s.data[:, 1] if wide else s.data) != np.asarray(
                empty, dtype=np.dtype(state.keys.dtype)))))
        for s in state.keys.addressable_shards if s.replica_id == 0)
    targets = {"keys": state.keys, "weights": state.weights}
    if include_optimizer:
        for sname, sval in state.slots.items():
            targets[f"slot_{sname}"] = sval
    from contextlib import ExitStack
    with ExitStack() as stack:
        writers = {
            fname: stack.enter_context(
                _seq_writer(fs.join(vdir, part + fname + ".npy"),
                            np.dtype(arr.dtype), (total,) + arr.shape[1:],
                            compress))
            for fname, arr in targets.items()
        }
        offset = 0
        for blocks in _aligned_shard_blocks(targets):
            bk = blocks["keys"]
            # wide ([cap, 2]) keys: a slot is free iff its HI word is EMPTY
            live = (bk[:, 1] != empty) if hash_lib.is_wide(bk) \
                else (bk != empty)
            n = int(live.sum())
            if n:
                for fname, block in blocks.items():
                    writers[fname].write(block[live])
            offset += n
        assert offset == total, (offset, total)


def _aligned_shard_blocks(arrays: Dict[str, Any]):
    """Yield row-aligned host blocks across several identically-sharded
    arrays (keys + weights + slots share the table's sharding, but their
    row widths differ, so the block row count must be chosen jointly)."""
    shard_lists = {
        f: sorted((s for s in a.addressable_shards if s.replica_id == 0),
                  key=lambda s: s.index[0].start or 0)
        for f, a in arrays.items()
    }
    for i in range(len(shard_lists["keys"])):
        datas = {f: sl[i].data for f, sl in shard_lists.items()}
        rows = datas["keys"].shape[0]
        if not rows:
            continue
        row_bytes = sum(max(1, d.nbytes // rows) for d in datas.values())
        per = max(1, _BLOCK_BYTES // row_bytes)
        for lo in range(0, rows, per):
            hi = min(rows, lo + per)
            yield {f: np.asarray(jax.device_get(d[lo:hi]))
                   for f, d in datas.items()}


class _NpyDirReader:
    """dict-like lazy reader over a ``var_*.d`` directory of .npy files.

    Local directories open files memmapped (``__getitem__`` random access —
    the fast strided-slice load path); remote URIs expose only sequential
    ``rows``/``chunks`` streaming — the access pattern object stores (and
    the reference's piped hadoop reads, EmbeddingShardFile.h:57-63) are
    built for. One class, fs-dispatched, so the part-file format can never
    drift between local and remote loads.
    """

    def __init__(self, vdir: str, prefix: str = ""):
        self._vdir = vdir
        self._prefix = prefix
        self._remote = fs.is_remote(vdir)
        # name -> file suffix: raw ".npy" (memmap-able locally) or the
        # compressed framed ".npyz" container (stream-only everywhere)
        self._suffix: Dict[str, str] = {}
        for f in fs.listdir(vdir):
            sfx = ".npy" if f.endswith(".npy") else \
                ".npyz" if f.endswith(".npyz") else None
            if sfx and f.startswith(prefix) \
                    and (prefix or not f.startswith("part")):
                self._suffix[f[len(prefix):-len(sfx)]] = sfx
        self._names = set(self._suffix)

    def __contains__(self, name: str) -> bool:
        return name in self._names

    @property
    def streaming(self) -> bool:
        """True when this part has no random-access representation
        (remote URI or compressed frames) — loaders must take the
        sequential ``rows``/``chunks`` path."""
        return self._remote or ".npyz" in self._suffix.values()

    def _path(self, name: str) -> str:
        if name not in self._names:
            raise KeyError(name)
        return fs.join(self._vdir, self._prefix + name + self._suffix[name])

    def __getitem__(self, name: str):
        if self._remote or self._suffix.get(name) == ".npyz":
            raise TypeError(
                "remote/compressed readers stream; use rows()/chunks()")
        return np.load(self._path(name), mmap_mode="r")

    def rows(self, name: str) -> int:
        if self._suffix.get(name) == ".npyz":
            return fs.npyz_shape(self._path(name))[1][0]
        if self._remote:
            return fs.npy_shape(self._path(name))[1][0]
        return self[name].shape[0]

    def chunks(self, name: str, size: int):
        if self._suffix.get(name) == ".npyz":
            return fs.iter_npyz_chunks(self._path(name), size)
        if self._remote:
            return fs.iter_npy_chunks(self._path(name), size)
        arr = self[name]
        return (np.asarray(arr[lo:lo + size])
                for lo in range(0, arr.shape[0], size))


def _aligned_reader_chunks(reader, names, size: int):
    """Yield dicts of row-aligned chunks for several fields of one reader.

    Readers with ``.chunks`` stream (memmap or remote); legacy npz handles
    are sliced in place.
    """
    if hasattr(reader, "chunks"):
        iters = {n: iter(reader.chunks(n, size)) for n in names}
        while True:
            out = {}
            for n in names:
                try:
                    out[n] = next(iters[n])
                except StopIteration:
                    assert not out, f"field {n} shorter than {names[0]}"
                    return
            yield out
    else:
        # legacy npz: materialize each member ONCE (NpzFile.__getitem__
        # decompresses the whole member on every access)
        arrs = {m: reader[m] for m in names}
        n_rows = arrs[names[0]].shape[0]
        for lo in range(0, n_rows, size):
            yield {m: np.asarray(a[lo:lo + size]) for m, a in arrs.items()}


def _open_var(path: str, vid: int, name: str):
    """Readers for one variable: a list with one dict-like entry per dump
    part (multi-host dumps have one per writing process; single-host and
    legacy npz dumps have exactly one)."""
    vdir = fs.join(path, _var_dir(vid, name))
    if fs.isdir(vdir):
        prefixes = sorted({f.split("_", 1)[0] + "_"
                           for f in fs.listdir(vdir)
                           if f.startswith("part")})
        if prefixes:
            return [_NpyDirReader(vdir, p) for p in prefixes]
        return [_NpyDirReader(vdir)]
    return [np.load(os.path.join(path, _var_file(vid, name)))]  # legacy npz


def _load_array_var(readers, spec, sspec: st.ShardingSpec, optimizer,
                    shardings, with_opt: bool,
                    stored_dtypes: Optional[Dict[str, str]] = None,
                    legacy_dtype: Optional[str] = None):
    """Assemble one bounded variable shard-by-shard from its dump.

    ``readers`` is the part list from ``_open_var``. A single-part dump is
    read in logical order (each device's rows are a basic strided slice of
    the file); keyed multi-host parts carry (ids, rows) and are scattered
    into the owning device buffers part-at-a-time. Either way host memory
    peaks at one shard and no full-table host array ever exists.
    """
    vocab = spec.input_dim
    dtype = np.dtype(table_lib.resolve_dtype(spec.meta()))
    pv = sspec.padded_vocab
    keyed = len(readers) > 1 or "ids" in readers[0]
    # one ids read + physical-position computation per part, shared across
    # every (field, device) pair below
    parts_phys = []
    if keyed:
        for r in readers:
            ids = np.asarray(r["ids"])
            shard, local_idx = sspec.shard_and_local(ids)
            parts_phys.append(
                (ids, shard * sspec.rows_per_shard + local_idx))

    stored_dtypes = stored_dtypes or {}

    def build(fname, fill, store_dtype, row_shape, sharding):
        global_shape = (pv,) + row_shape
        locals_ = []
        devs = sorted(
            sharding.addressable_devices_indices_map(global_shape).items(),
            key=lambda kv: kv[1][0].start or 0)
        sources = [r[fname] if fname in r else None for r in readers]
        true = stored_dtypes.get(fname)
        for dev, idx in devs:
            start = idx[0].start or 0
            stop = idx[0].stop if idx[0].stop is not None else pv
            local = np.full((stop - start,) + row_shape, fill,
                            dtype=store_dtype)
            if keyed:
                for (ids, phys), source in zip(parts_phys, sources):
                    if source is None:
                        continue
                    sel = (phys >= start) & (phys < stop) & (ids < vocab)
                    if sel.any():
                        local[phys[sel] - start] = _decode_rows(
                            source[sel], true, store_dtype,
                            legacy_dtype)
            elif sources[0] is not None:
                stored = min(vocab, sources[0].shape[0])
                sl, nv = _logical_slice(sspec, stored, start, stop - start)
                if nv:
                    # basic (strided/contiguous) memmap slice: streams this
                    # shard's rows without touching the rest of the file
                    local[:nv] = _decode_rows(sources[0][sl], true,
                                              store_dtype, legacy_dtype)
            locals_.append(jax.device_put(local, dev))
        return jax.make_array_from_single_device_arrays(
            global_shape, sharding, locals_)

    dim0 = readers[0]["weights"].shape[1:]
    weights = build("weights", 0.0, dtype, dim0, shardings.weights)
    new_slots = {}
    dim = spec.output_dim
    for sname, sshape in optimizer.slot_shapes(dim).items():
        sdtype = np.dtype(optimizer.slot_dtype(sname, dtype))
        fill = optimizer.slot_init(sname)
        fname = f"slot_{sname}" if with_opt else "__absent__"
        # absent from the dump (saved without optimizer state, or under a
        # different optimizer category): fresh slot init, weights kept —
        # copy_from hot-swap semantics (EmbeddingVariable.cpp:29-60)
        new_slots[sname] = build(fname, fill, sdtype, tuple(sshape),
                                 shardings.slots[sname])
    return table_lib.TableState(weights=weights, slots=new_slots)


def _load_array_var_stream(readers, spec, sspec: st.ShardingSpec, optimizer,
                           mesh, with_opt: bool, from_hash: bool = False,
                           shard_slice: Optional[tuple] = None,
                           stored_dtypes: Optional[Dict[str, str]] = None,
                           legacy_dtype: Optional[str] = None):
    """Streamed twin of ``_load_array_var``: blank sharded arrays +
    sequential keyed chunk delivery (``deliver_rows_sharded``), so a
    gs://-scale table loads with bounded host memory and purely sequential
    reads — the reference's piped hadoop load
    (EmbeddingLoadOperator.cpp:58-111).

    ``from_hash`` converts a HASH dump into this bounded variable (the
    reference's copy_from hot-swap, EmbeddingVariable.cpp:29-60): stored
    keys become logical row ids, and any key outside the bounded vocab
    fails the load — a conversion must deliver every row or fail.
    """
    if from_hash and shard_slice is not None:
        raise ValueError("hash->array conversion cannot be combined with a "
                         "serving shard slice (serve hash dumps as hash)")
    vocab = spec.input_dim
    stored_dtypes = stored_dtypes or {}
    dtype = np.dtype(table_lib.resolve_dtype(spec.meta()))
    dim = spec.output_dim
    weights = st.filled_sharded(mesh, sspec, (dim,), 0.0, dtype)
    slots = {}
    slot_dtypes = {}
    for sname, sshape in optimizer.slot_shapes(dim).items():
        sdtype = np.dtype(optimizer.slot_dtype(sname, dtype))
        slot_dtypes[sname] = sdtype
        slots[sname] = st.filled_sharded(mesh, sspec, tuple(sshape),
                                         optimizer.slot_init(sname), sdtype)
    for r in readers:
        id_field = "keys" if from_hash else ("ids" if "ids" in r else None)
        names = ([id_field] if id_field else []) + ["weights"] + [
            f"slot_{s}" for s in slots
            if with_opt and f"slot_{s}" in r]
        # legacy npz handles have no .rows (they are plain NpzFile mappings)
        n_rows = r.rows(id_field or "weights") if hasattr(r, "rows") \
            else r[id_field or "weights"].shape[0]
        size = min(_LOAD_CHUNK, max(n_rows, 1))
        offset = 0
        for chunk in _aligned_reader_chunks(r, names, size):
            if id_field:
                ids = chunk[id_field]
                if ids.ndim == 2:
                    # wide (pair) hash dump: join to 64-bit logical ids
                    ids = hash_lib.join64(ids)
                ids = ids.astype(np.int64)
                if from_hash and ids.size and (
                        ids.min() < 0 or ids.max() >= vocab):
                    bad = ids[(ids < 0) | (ids >= vocab)][0]
                    raise ValueError(
                        f"hash->array conversion: stored key {bad} is "
                        f"outside the bounded vocab {vocab}; a load must "
                        "deliver every row or fail")
            else:
                # logical-order dump (no ids file): row i IS logical id i,
                # so a local-format dump copied to object storage streams
                # back with synthesized ids
                got = chunk["weights"].shape[0]
                ids = np.arange(offset, offset + got, dtype=np.int64)
                offset += got
            if shard_slice is not None:
                # serving shard group: keep only owned global ids and map
                # them to the local row space (local l holds id l*G + k)
                k, G = shard_slice
                sel = (ids % G) == k
                ids = ids[sel] // G
            else:
                sel = None
            shard, local = sspec.shard_and_local(ids)
            phys = np.where(ids < vocab,
                            shard * sspec.rows_per_shard + local, -1)
            n = phys.shape[0]
            phys_p = np.full((size,), -1, np.int64)
            phys_p[:n] = phys
            jphys = jnp.asarray(phys_p)

            def pad_rows(rows):
                if sel is not None:
                    rows = rows[sel]
                out = np.zeros((size,) + rows.shape[1:], rows.dtype)
                out[:n] = rows
                return jnp.asarray(out)

            weights = st.deliver_rows_sharded(
                weights, jphys,
                pad_rows(_decode_rows(chunk["weights"],
                                      stored_dtypes.get("weights"),
                                      dtype, legacy_dtype)),
                mesh=mesh, spec=sspec)
            for sname in slots:
                f = f"slot_{sname}"
                if f in chunk:
                    slots[sname] = st.deliver_rows_sharded(
                        slots[sname], jphys,
                        pad_rows(_decode_rows(chunk[f],
                                              stored_dtypes.get(f),
                                              slot_dtypes[sname],
                                              legacy_dtype)),
                        mesh=mesh, spec=sspec)
    return table_lib.TableState(weights=weights, slots=slots)


def _is_hash_meta(m) -> bool:
    from .meta import UNBOUNDED_VOCAB
    return m.vocabulary_size >= UNBOUNDED_VOCAB


def _check_meta(path: str, collection: EmbeddingCollection,
                shard_slice: Optional[tuple] = None) -> ModelMeta:
    """Validate the dump's variable metas against the model's.

    dim must match exactly; the datatype may differ within the
    {float32, bfloat16} precision family (the at-rest rung of the
    compressed-exchange ladder, ``parallel/precision.py``) — the
    loaders cast row-by-row, so an f32 dump loads into a bf16 table
    (downcast) and a bf16 dump upcasts into f32 transparently. The
    vocabulary may differ when the TABLE CATEGORY differs (array dump
    -> hash variable, or hash dump -> array variable): the loader
    converts by streaming rows through the target's delivery path —
    the reference's ``copy_from`` hot-swap
    (/root/reference/openembedding/variable/EmbeddingVariable.cpp:29-60),
    which loads any dump into any table/optimizer implementation. A
    bounded->bounded vocabulary mismatch still fails (resizing a bounded
    table is a model change, not a storage conversion; grow via hash).
    """
    with fs.open_file(fs.join(path, MODEL_META_FILE), "rb") as f:
        meta = ModelMeta.loads(f.read().decode("utf-8"))
    want = collection.model_meta()
    got_vars = {v.name: v for v in meta.variables}
    for v in want.variables:
        if v.name not in got_vars:
            raise ValueError(f"checkpoint at {path!r} has no variable "
                             f"{v.name!r}")
        g = got_vars[v.name]
        if g.meta != v.meta:
            dtype_ok = (
                g.meta.datatype == v.meta.datatype
                or {g.meta.datatype, v.meta.datatype}
                <= {"float32", "bfloat16"})   # precision migration
            same_shape = (g.meta.embedding_dim == v.meta.embedding_dim
                          and dtype_ok)
            same_vocab = (g.meta.vocabulary_size == v.meta.vocabulary_size)
            category_swap = _is_hash_meta(g.meta) != _is_hash_meta(v.meta)
            slice_ok = (
                shard_slice is not None and same_shape
                and not _is_hash_meta(g.meta) and not _is_hash_meta(v.meta)
                and v.meta.vocabulary_size == shard_slice_vocab(
                    g.meta.vocabulary_size, *shard_slice))
            if not ((same_shape and (category_swap or same_vocab))
                    or slice_ok):
                raise ValueError(
                    f"variable {v.name!r} meta mismatch: checkpoint "
                    f"{g.meta} vs model {v.meta}")
    return meta


def shard_slice_vocab(full_vocab: int, shard_index: int,
                      shard_count: int) -> int:
    """Rows owned by serving-process shard k of G: ids ≡ k (mod G)."""
    return max(0, -(-(full_vocab - shard_index) // shard_count))


def load_checkpoint(path: str,
                    collection: EmbeddingCollection,
                    *,
                    dense_state_template: Any = None,
                    rng: Optional[jax.Array] = None,
                    shard_slice: Optional[tuple] = None,
                    info: Optional[Dict[str, Any]] = None):
    """Rebuild all embedding states from ``path`` (any source mesh shape).

    Returns ``states`` or ``(states, dense_state)`` when a template pytree is
    given. Equivalent of Model::load_model: meta check -> clear weights ->
    re-deliver rows to owning shards (Model.cpp:110-134).

    ``shard_slice=(k, G)`` loads only the rows this SERVING PROCESS owns —
    bounded ids / hash keys with ``id % G == k`` — so a model larger than
    one process serves from a G-process shard group (the reference places
    shard x replica over PS nodes the same way, client/Model.cpp:153-186).
    Bounded variables' local vocab must be ``shard_slice_vocab(V, k, G)``
    (local row ``l`` holds global id ``l * G + k``); hash variables keep
    their keys verbatim and simply skip non-owned ones.

    ``load_checkpoint`` transparently REPLAYS a delta chain on top of the
    base (``checkpoint_delta.py``): the manifest's committed entries are
    checksum-verified and applied in order; a torn FINAL delta (a killed
    writer) is discarded whole — the load recovers to the last complete
    delta, never a half-applied one.

    ``info`` (a caller-supplied dict) receives ``applied_seq``: the
    chain version THIS load's states actually reflect, from the same
    verify pass the replay used — plus ``resume_extra``, the caller
    bookkeeping committed with that exact version (the
    ``Trainer.fit(resume_from=)`` channel; ``{}`` when the save carried
    none). Version-sensitive callers (the serving
    registry's hot-swap gate) must use it instead of a separate
    ``checkpoint_delta.applied_seq`` read — against a directory a
    trainer is actively saving into, a second read can see a newer
    chain than the load replayed, and a model versioned ahead of its
    rows acks the next delta as stale and silently loses it
    (graftproto-found divergence, pinned by
    tests/test_graftproto_replay.py).
    """
    with scope.span("checkpoint.load"):
        from . import checkpoint_delta as cd
        # a loader racing the writer's BACKGROUND COMPACTOR can read base
        # files from one generation and the manifest from another; the
        # manifest's base_id pins the generation — retry once when it
        # moved under the load (folding is idempotent, so one settled
        # re-read is always consistent)
        last_err = None
        for _attempt in range(2):
            m0 = cd.read_manifest(path)
            id0 = m0["base_id"] if m0 else None
            try:
                out = _load_checkpoint_impl(
                    path, collection,
                    dense_state_template=dense_state_template,
                    rng=rng, shard_slice=shard_slice, info=info)
            except RuntimeError as e:
                m1 = cd.read_manifest(path)
                if (m1["base_id"] if m1 else None) != id0:
                    last_err = e
                    continue
                raise
            m1 = cd.read_manifest(path)
            if (m1["base_id"] if m1 else None) == id0:
                return out
            last_err = RuntimeError("chain compacted under the load")
        raise RuntimeError(
            f"checkpoint at {path!r} kept changing under the load "
            "(background compaction); quiesce the writer or retry"
        ) from last_err


def _load_checkpoint_impl(path: str,
                          collection: EmbeddingCollection,
                          *,
                          dense_state_template: Any,
                          rng: Optional[jax.Array],
                          shard_slice: Optional[tuple],
                          info: Optional[Dict[str, Any]] = None):
    meta = _check_meta(path, collection, shard_slice=shard_slice)
    with_opt = bool(meta.extra.get("include_optimizer", True))
    stored_all = meta.extra.get("storage_dtypes", {})
    dump_meta = {v.name: v.meta for v in meta.variables}
    hash_names = [n for n, s in collection.specs.items() if s.use_hash]
    # only hash variables need fresh (empty) device tables; bounded tables are
    # assembled host-side below and never pay the random-init program
    states = collection.init(rng, only=hash_names)
    out = {}
    for name, spec in collection.specs.items():
        vid = collection.variable_id(name)
        data = _open_var(path, vid, name)
        sspec = collection.sharding_spec(name)
        optimizer = collection.optimizer(name)
        dump_hash = _is_hash_meta(dump_meta[name])
        if spec.use_hash:
            state = hot_cache.unwrap(states[name])
            total_rows = 0
            for data_part in data:
                state, n_part = _insert_hash_rows(
                    state, data_part, collection, sspec, with_opt,
                    from_array=not dump_hash, shard_slice=shard_slice,
                    stored_dtypes=stored_all.get(name),
                    legacy_dtype=dump_meta[name].datatype)
                total_rows += n_part
            failed = int(jax.device_get(state.insert_failures))
            if failed > 0:
                raise RuntimeError(
                    f"hash variable {name!r}: {failed} of {total_rows} "
                    f"checkpoint rows did not fit (hash_capacity="
                    f"{spec.hash_capacity}); increase hash_capacity — a "
                    "load must deliver every row or fail")
            out[name] = state
        elif dump_hash:
            # hash dump -> bounded variable: copy_from conversion
            out[name] = _load_array_var_stream(
                data, spec, sspec, optimizer, collection.mesh, with_opt,
                from_hash=True, shard_slice=shard_slice,
                stored_dtypes=stored_all.get(name),
                legacy_dtype=dump_meta[name].datatype)
        elif fs.is_remote(path) or shard_slice is not None \
                or any(getattr(r, "streaming", False) for r in data):
            out[name] = _load_array_var_stream(
                data, spec, sspec, optimizer, collection.mesh, with_opt,
                shard_slice=shard_slice,
                stored_dtypes=stored_all.get(name),
                legacy_dtype=dump_meta[name].datatype)
        else:
            shardings = collection.state_shardings()[name]
            if isinstance(shardings, hot_cache.CachedState):
                shardings = shardings.table
            out[name] = _load_array_var(
                data, spec, sspec, optimizer, shardings, with_opt,
                stored_dtypes=stored_all.get(name),
                legacy_dtype=dump_meta[name].datatype)
    # delta chain replay: committed deltas patched over the base, newest
    # wins; torn final delta discarded whole (checkpoint_delta.py)
    from . import checkpoint_delta as cd
    manifest = cd.read_manifest(path)
    if manifest and manifest.get("chain"):
        out = cd.replay_chain(path, collection, out, manifest=manifest,
                              with_opt=with_opt, shard_slice=shard_slice,
                              dump_meta=dump_meta, info=info)
    elif info is not None:
        # chainless: the base bytes reflect content_seq (0 for plain
        # full dumps and pre-content_seq manifests) and the manifest
        # base's extra (what the full save was stamped with)
        info["applied_seq"] = cd.verified_seq(manifest, [])
        info["resume_extra"] = cd.resume_extra(manifest, [])
    for name in out:
        # cached-plane variables come back with a fresh all-pad replica;
        # the first HotCacheManager refresh re-admits the hot set
        out[name] = collection.wrap_hot_cache(name, out[name])
    if dense_state_template is not None:
        with fs.open_file(fs.join(path, DENSE_FILE), "rb") as f:
            dense = serialization.from_bytes(dense_state_template, f.read())
        return out, dense
    return out


def _insert_hash_rows(state, data, collection, sspec, with_opt,
                      from_array: bool = False,
                      shard_slice: Optional[tuple] = None,
                      stored_dtypes: Optional[Dict[str, str]] = None,
                      legacy_dtype: Optional[str] = None):
    """Stream one reader's (keys, weights, states) rows into the table.

    Consumes row-aligned chunks so the same code path serves memmapped
    local dumps, legacy npz handles, and remote sequential streams.
    ``from_array`` converts a BOUNDED dump into this hash variable —
    logical row ids become keys (the reference's copy_from hot-swap for
    bounded-vocab growth, EmbeddingVariable.cpp:29-60).
    """
    # slots present in both the checkpoint and the current optimizer are
    # restored; others keep their fresh init — loading into a different
    # optimizer category keeps weights and re-initializes slots, the
    # reference's copy_from hot-swap semantics (EmbeddingVariable.cpp:29-60)
    if from_array:
        id_field = "ids" if "ids" in data else None
    else:
        id_field = "keys"
    names = ([id_field] if id_field else []) + ["weights"] + (
        [f"slot_{s}" for s in state.slots if f"slot_{s}" in data]
        if with_opt else [])
    # stream fixed-size chunks (padded with EMPTY) to keep shapes static
    key_dtype = np.dtype(state.keys.dtype)
    empty = hash_lib.empty_key(key_dtype)
    n = data.rows(id_field or "weights") if hasattr(data, "rows") \
        else data[id_field or "weights"].shape[0]
    size = min(_LOAD_CHUNK, max(n, 1))
    offset = 0
    for chunk in _aligned_reader_chunks(data, names, size):
        got = chunk["weights"].shape[0]
        if id_field:
            raw_keys = chunk[id_field]
        else:
            raw_keys = np.arange(offset, offset + got, dtype=np.int64)
            offset += got
        if not from_array and hash_lib.is_wide(state.keys) \
                and raw_keys.ndim == 1:
            # int32-key dump loading into a wide table (the natural key
            # migration): narrow keys become (lo, hi=sign-extension) pairs
            # == the same 64-bit values. Keys landing in the wide EMPTY
            # band (hi == INT32_MIN, only reachable from int64 dumps) must
            # fail the load, not silently read as free slots
            pairs = hash_lib.split64(raw_keys.astype(np.int64))
            banded = pairs[:, 1] == empty
            if banded.any():
                raise ValueError(
                    f"{int(banded.sum())} dump keys fall in the wide-key "
                    "EMPTY band (hi word == INT32_MIN, keys in [-2^63, "
                    "-2^63+2^32)); the wide pair encoding excludes that "
                    "range — keep such dumps on int64 tables")
            raw_keys = pairs
        elif not from_array and not hash_lib.is_wide(state.keys) \
                and raw_keys.ndim == 2:
            # wide dump into a narrow table: join and refuse truncation
            joined = hash_lib.join64(raw_keys)
            kmax = np.iinfo(np.dtype(state.keys.dtype)).max
            kmin = np.iinfo(np.dtype(state.keys.dtype)).min
            if joined.size and (joined.max() > kmax or
                                joined.min() < kmin):
                raise ValueError(
                    "wide-key dump holds keys outside the table's "
                    f"{np.dtype(state.keys.dtype)} range; load into a "
                    "key_dtype='wide' variable instead")
            raw_keys = joined.astype(np.dtype(state.keys.dtype))
        if from_array:
            if hash_lib.is_wide(state.keys):
                # wide target: logical id i becomes the pair (lo=i, hi=0)
                # == the 64-bit key i (split64 of the int64 id)
                raw_keys = hash_lib.split64(raw_keys.astype(np.int64))
            else:
                # logical ids are bounded by the dump vocab; refuse ids the
                # table's key dtype cannot hold rather than alias mod 2^32
                if raw_keys.size and int(raw_keys.max()) > np.iinfo(
                        key_dtype).max:
                    raise ValueError(
                        f"array->hash conversion: logical id "
                        f"{raw_keys.max()} does not fit key dtype "
                        f"{key_dtype}")
                raw_keys = raw_keys.astype(key_dtype)
        # wide pair keys pad with all-EMPTY rows (hi EMPTY marks padding)
        ck = np.full((size,) + raw_keys.shape[1:], empty,
                     dtype=raw_keys.dtype)
        ck[:got] = raw_keys
        if shard_slice is not None:
            # serving shard group: non-owned keys become EMPTY (skipped by
            # the insert path). The owner rule is ``id % G`` on the JOINED
            # 64-bit value — identical for every key width, so placement
            # survives key migrations and matches the router's partition
            # (ha.py ShardedRoutingClient) and the in-process filter
            # (registry.py ServingModel.lookup / hash_table.pair_mod)
            k, G = shard_slice
            ids64 = hash_lib.join64(raw_keys) if raw_keys.ndim == 2 \
                else raw_keys.astype(np.int64)
            ck[:got][(ids64 % G) != k] = empty
        wdtype = np.dtype(state.weights.dtype)
        stored = stored_dtypes or {}
        cw = np.zeros((size,) + chunk["weights"].shape[1:], wdtype)
        cw[:got] = _decode_rows(chunk["weights"], stored.get("weights"),
                                wdtype, legacy_dtype)
        srows = {}
        for fname in (m for m in names if m.startswith("slot_")):
            sname = fname[len("slot_"):]
            sdtype = np.dtype(state.slots[sname].dtype)
            cs = np.zeros((size,) + chunk[fname].shape[1:], sdtype)
            cs[:got] = _decode_rows(chunk[fname], stored.get(fname),
                                    sdtype, legacy_dtype)
            srows[sname] = jnp.asarray(cs)
        state = sh.insert_rows_sharded(
            state, jnp.asarray(ck), jnp.asarray(cw), srows,
            mesh=collection.mesh, spec=sspec)
    return state, n


def export_dense(collection: EmbeddingCollection,
                 states: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """Materialize bounded variables as dense [vocab, dim] arrays.

    ``save_as_original_model`` equivalent (exb.py:506-547): the result plugs
    into any plain embedding lookup. Hash variables cannot be densified and
    raise, matching exb.py:536.
    """
    out = {}
    for name, spec in collection.specs.items():
        if spec.use_hash:
            raise ValueError(
                f"variable {name!r} uses an unbounded hash key space and "
                "cannot be exported densely (reference rejects this too)")
        sspec = collection.sharding_spec(name)
        perm = _logical_perm(sspec)
        state = hot_cache.unwrap(states[name])
        weights = np.asarray(jax.device_get(state.weights))[perm]
        out[name] = weights[:spec.input_dim]  # drop padding rows
    return out
