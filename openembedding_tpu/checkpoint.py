"""Sharded checkpoint save/load + dense export.

Capability parity with the reference's dump/load pipeline (SURVEY §3.4;
/root/reference/openembedding/server/EmbeddingDumpOperator.cpp,
EmbeddingLoadOperator.cpp, client/Model.cpp:89-134):

* ``<path>/model_meta`` — the same self-describing JSON head (model_sign,
  ordered variable metas, format version; reference Meta.h "0.2", ours
  ``META_FORMAT_VERSION``). Load validates variable metas match before
  touching any table (Model.cpp:110-121).
* per-variable ``var_<id>_<name>.d/{weights,slot_*,keys}.npy`` —
  logical-row-order arrays (+ named optimizer-state files when
  ``include_optimizer``, the reference's state_line_size != 0 flag,
  EmbeddingDumpOperator.cpp:36-76); hash variables store (keys, weights,
  states) triples of live rows only — the reference's streamed (indices,
  weights, states) blocks with re-globalized keys (EmbeddingShardFile.h:
  21-23). **Dump and load stream per-shard ~4MB blocks** (device slices on
  save, memmapped strided reads + direct per-device placement on load), so
  host memory stays bounded no matter the table size — the reference's
  server-side block streaming, not a whole-table host copy. Legacy
  single-file ``var_*.npz`` checkpoints still load.
* **Shard-topology independence**: arrays are written in *logical id order*
  (the physical mod-layout permutation is undone on save and re-applied on
  load), and hash rows are keyed — so a checkpoint taken on an 8-way mesh
  loads onto a 2-way mesh, like the reference re-shards by
  ``key % shard_num`` at load.
* ``export_dense`` — the ``save_as_original_model`` equivalent
  (exb.py:506-547): materializes every bounded variable as a dense array for
  serving without this framework; hash variables are rejected exactly like
  the reference (exb.py:536).

Dense flax params ride flax.serialization msgpack next to the sparse dump.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization

from .embedding import EmbeddingCollection
from .meta import ModelMeta
from . import hash_table as hash_lib
from . import table as table_lib
from .parallel import sharded_hash as sh
from .parallel import sharded_table as st

MODEL_META_FILE = "model_meta"
DENSE_FILE = "dense_state.msgpack"
_LOAD_CHUNK = 1 << 16
# streamed block granularity — the reference dumps ~1MB lines per shard
# (EmbeddingDumpOperator.cpp:84-87 server_block_num_items)
_BLOCK_BYTES = 4 << 20


def _var_file(variable_id: int, name: str) -> str:
    safe = name.replace("/", "_").replace(":", "__")
    return f"var_{variable_id}_{safe}.npz"


def _var_dir(variable_id: int, name: str) -> str:
    safe = name.replace("/", "_").replace(":", "__")
    return f"var_{variable_id}_{safe}.d"


def _logical_perm(spec: st.ShardingSpec) -> np.ndarray:
    """physical position of logical row r under the sharded layout."""
    r = np.arange(spec.padded_vocab, dtype=np.int64)
    shard = r % spec.num_shards if spec.layout == "mod" else r // spec.rows_per_shard
    local = r // spec.num_shards if spec.layout == "mod" else r % spec.rows_per_shard
    return shard * spec.rows_per_shard + local


def _logical_slice(spec: st.ShardingSpec, vocab: int, phys_start: int,
                   n: int):
    """(file_slice, n_valid) for physical rows [phys_start, phys_start+n).

    A physical block lies inside one shard, and a shard's logical rows form
    a *basic* numpy slice of the logical-order file — strided (every Nth
    row) under "mod", contiguous under "div" — so both dump and load move
    data with plain slice assignments, never fancy indexing.
    """
    rps = spec.rows_per_shard
    s = phys_start // rps
    l0 = phys_start % rps
    assert (phys_start + n - 1) // rps == s, "block crosses a shard boundary"
    if spec.layout == "mod":
        # shard s owns logical rows l*N + s; valid while < vocab
        nv_shard = max(0, -(-(vocab - s) // spec.num_shards)) \
            if s < vocab else 0
        nv = max(0, min(n, nv_shard - l0))
        N = spec.num_shards
        return slice(s + l0 * N, s + (l0 + nv) * N, N), nv
    nv = max(0, min(n, vocab - phys_start))
    return slice(phys_start, phys_start + nv), nv


def _iter_shard_blocks(arr):
    """Yield (physical_row_start, host_block) in bounded blocks per shard.

    Streams each addressable shard device->host in ~_BLOCK_BYTES slices —
    the dump never materializes the whole table on the host, matching the
    reference's per-shard block streaming (EmbeddingDumpOperator.cpp:50-96).
    Replicated shards (psum plane: data-axis copies) are emitted once.
    """
    for shard in arr.addressable_shards:
        if shard.replica_id != 0:
            continue  # psum-plane data-axis replica: identical copy
        data = shard.data
        rows = data.shape[0]
        if not rows:
            continue
        start = shard.index[0].start or 0
        row_bytes = max(1, data.nbytes // rows)
        per = max(1, _BLOCK_BYTES // row_bytes)
        for lo in range(0, rows, per):
            hi = min(rows, lo + per)
            yield start + lo, np.asarray(jax.device_get(data[lo:hi]))


def _require_single_process(what: str) -> None:
    if jax.process_count() > 1:
        raise NotImplementedError(
            f"{what} currently runs on a single-controller process; on a "
            "multi-host cluster write per-host part files (the reference's "
            "model_<node>_<fileid> layout) — not implemented yet")


def save_checkpoint(path: str,
                    collection: EmbeddingCollection,
                    states: Dict[str, Any],
                    *,
                    dense_state: Any = None,
                    include_optimizer: bool = True,
                    model_sign: str = "") -> None:
    """Dump all embedding variables (+ optional dense pytree) under ``path``."""
    _require_single_process("save_checkpoint")  # before any writes
    os.makedirs(path, exist_ok=True)
    meta = collection.model_meta(model_sign=model_sign, model_uri=path)
    meta.extra["include_optimizer"] = bool(include_optimizer)
    # persist hash-table geometry so a loader (e.g. the serving registry,
    # which rebuilds specs from this meta alone) allocates tables that can
    # hold every stored row — the reference's load path delivers every row
    # or fails (EmbeddingLoadOperator.cpp:58-111)
    hash_info = {
        name: {"hash_capacity": spec.hash_capacity,
               "key_dtype": spec.key_dtype}
        for name, spec in collection.specs.items() if spec.use_hash
    }
    if hash_info:
        meta.extra["hash_variables"] = hash_info
    with open(os.path.join(path, MODEL_META_FILE), "w",
              encoding="utf-8") as f:
        f.write(meta.dumps())

    for name, spec in collection.specs.items():
        state = states[name]
        vid = collection.variable_id(name)
        vdir = os.path.join(path, _var_dir(vid, name))
        if os.path.isdir(vdir):
            # a previous save under a different optimizer could leave stale
            # slot files behind, which a later load would mistake for state
            import shutil
            shutil.rmtree(vdir)
        os.makedirs(vdir)
        if spec.use_hash:
            _save_hash_var(vdir, state, include_optimizer)
        else:
            _save_array_var(vdir, state, collection.sharding_spec(name),
                            spec.input_dim, include_optimizer)

    if dense_state is not None:
        with open(os.path.join(path, DENSE_FILE), "wb") as f:
            f.write(serialization.to_bytes(jax.device_get(dense_state)))


def _save_array_var(vdir: str, state, sspec: st.ShardingSpec, vocab: int,
                    include_optimizer: bool) -> None:
    """Stream one bounded variable to ``<vdir>/{weights,slot_*}.npy``.

    Arrays are written in *logical id order* (only the real vocab rows —
    padding rows differ across mesh shapes and are unreachable), so the
    checkpoint is shard-topology independent. Each shard's physical block
    maps to logical positions with vectorized index math; host memory stays
    bounded by the block size.
    """
    targets = {"weights": state.weights}
    if include_optimizer:
        for sname, sval in state.slots.items():
            targets[f"slot_{sname}"] = sval
    for fname, arr in targets.items():
        mm = np.lib.format.open_memmap(
            os.path.join(vdir, fname + ".npy"), mode="w+",
            dtype=np.dtype(arr.dtype), shape=(vocab,) + arr.shape[1:])
        for phys_start, block in _iter_shard_blocks(arr):
            sl, nv = _logical_slice(sspec, vocab, phys_start, block.shape[0])
            if nv:
                mm[sl] = block[:nv]
        mm.flush()
        del mm


def _save_hash_var(vdir: str, state, include_optimizer: bool) -> None:
    """Stream one hash variable's live rows to ``<vdir>/*.npy``.

    Pass 1 counts live rows per shard on-device (a scalar per shard); pass 2
    streams (keys, weights, states) blocks and writes the live subset — the
    reference's streamed (indices, weights, states) block dump with
    re-globalized keys (EmbeddingShardFile.h:21-23).
    """
    empty = hash_lib.empty_key(np.dtype(state.keys.dtype))
    total = int(jax.device_get(state.num_used()))
    targets = {"keys": state.keys, "weights": state.weights}
    if include_optimizer:
        for sname, sval in state.slots.items():
            targets[f"slot_{sname}"] = sval
    mms = {
        fname: np.lib.format.open_memmap(
            os.path.join(vdir, fname + ".npy"), mode="w+",
            dtype=np.dtype(arr.dtype), shape=(total,) + arr.shape[1:])
        for fname, arr in targets.items()
    }
    offset = 0
    for blocks in _aligned_shard_blocks(targets):
        live = blocks["keys"] != empty
        n = int(live.sum())
        if n:
            for fname, block in blocks.items():
                mms[fname][offset:offset + n] = block[live]
        offset += n
    assert offset == total, (offset, total)
    for mm in mms.values():
        mm.flush()


def _aligned_shard_blocks(arrays: Dict[str, Any]):
    """Yield row-aligned host blocks across several identically-sharded
    arrays (keys + weights + slots share the table's sharding, but their
    row widths differ, so the block row count must be chosen jointly)."""
    shard_lists = {
        f: sorted((s for s in a.addressable_shards if s.replica_id == 0),
                  key=lambda s: s.index[0].start or 0)
        for f, a in arrays.items()
    }
    for i in range(len(shard_lists["keys"])):
        datas = {f: sl[i].data for f, sl in shard_lists.items()}
        rows = datas["keys"].shape[0]
        if not rows:
            continue
        row_bytes = sum(max(1, d.nbytes // rows) for d in datas.values())
        per = max(1, _BLOCK_BYTES // row_bytes)
        for lo in range(0, rows, per):
            hi = min(rows, lo + per)
            yield {f: np.asarray(jax.device_get(d[lo:hi]))
                   for f, d in datas.items()}


class _NpyDirReader:
    """dict-like lazy reader over a ``var_*.d`` directory of .npy files.

    Files are opened memmapped so the loader streams from disk instead of
    materializing whole tables host-side; the same mapping interface as a
    legacy ``np.load`` npz handle, so one loader serves both formats.
    """

    def __init__(self, vdir: str):
        self._vdir = vdir
        self._names = {f[:-4] for f in os.listdir(vdir) if f.endswith(".npy")}

    def __contains__(self, name: str) -> bool:
        return name in self._names

    def __getitem__(self, name: str):
        if name not in self._names:
            raise KeyError(name)
        return np.load(os.path.join(self._vdir, name + ".npy"),
                       mmap_mode="r")


def _open_var(path: str, vid: int, name: str):
    vdir = os.path.join(path, _var_dir(vid, name))
    if os.path.isdir(vdir):
        return _NpyDirReader(vdir)
    return np.load(os.path.join(path, _var_file(vid, name)))  # legacy npz


def _load_array_var(data, spec, sspec: st.ShardingSpec, optimizer,
                    shardings, with_opt: bool):
    """Assemble one bounded variable shard-by-shard from logical-order data.

    For every addressable device, reads exactly its rows (a strided slice of
    the logical file under the "mod" layout), pads rows beyond the stored
    vocab with the fill value, and places them directly — host memory peaks
    at one shard, and no full-table host array ever exists (the streaming
    inverse of _save_array_var).
    """
    vocab = spec.input_dim
    dtype = np.dtype(table_lib.resolve_dtype(spec.meta()))
    pv = sspec.padded_vocab

    def build(source, fill, store_dtype, row_shape, sharding):
        global_shape = (pv,) + row_shape
        locals_ = []
        devs = sorted(
            sharding.addressable_devices_indices_map(global_shape).items(),
            key=lambda kv: kv[1][0].start or 0)
        stored = 0 if source is None else min(vocab, source.shape[0])
        for dev, idx in devs:
            start = idx[0].start or 0
            stop = idx[0].stop if idx[0].stop is not None else pv
            local = np.full((stop - start,) + row_shape, fill,
                            dtype=store_dtype)
            sl, nv = _logical_slice(sspec, stored, start, stop - start)
            if nv:
                # basic (strided/contiguous) memmap slice: streams this
                # shard's rows without touching the rest of the file
                local[:nv] = source[sl]
            locals_.append(jax.device_put(local, dev))
        return jax.make_array_from_single_device_arrays(
            global_shape, sharding, locals_)

    w = data["weights"]  # bind once: npz access decompresses per access
    weights = build(w, 0.0, dtype, w.shape[1:], shardings.weights)
    new_slots = {}
    dim = spec.output_dim
    for sname, sshape in optimizer.slot_shapes(dim).items():
        sdtype = np.dtype(optimizer.slot_dtype(sname, dtype))
        fill = optimizer.slot_init(sname)
        fname = f"slot_{sname}"
        source = data[fname] if (with_opt and fname in data) else None
        # absent from the dump (saved without optimizer state, or under a
        # different optimizer category): fresh slot init, weights kept —
        # copy_from hot-swap semantics (EmbeddingVariable.cpp:29-60)
        new_slots[sname] = build(source, fill, sdtype, tuple(sshape),
                                 shardings.slots[sname])
    return table_lib.TableState(weights=weights, slots=new_slots)


def _check_meta(path: str, collection: EmbeddingCollection) -> ModelMeta:
    with open(os.path.join(path, MODEL_META_FILE),
              encoding="utf-8") as f:
        meta = ModelMeta.loads(f.read())
    want = collection.model_meta()
    got_vars = {v.name: v for v in meta.variables}
    for v in want.variables:
        if v.name not in got_vars:
            raise ValueError(f"checkpoint at {path!r} has no variable "
                             f"{v.name!r}")
        g = got_vars[v.name]
        if g.meta != v.meta:
            raise ValueError(
                f"variable {v.name!r} meta mismatch: checkpoint "
                f"{g.meta} vs model {v.meta}")
    return meta


def load_checkpoint(path: str,
                    collection: EmbeddingCollection,
                    *,
                    dense_state_template: Any = None,
                    rng: Optional[jax.Array] = None):
    """Rebuild all embedding states from ``path`` (any source mesh shape).

    Returns ``states`` or ``(states, dense_state)`` when a template pytree is
    given. Equivalent of Model::load_model: meta check -> clear weights ->
    re-deliver rows to owning shards (Model.cpp:110-134).
    """
    meta = _check_meta(path, collection)
    with_opt = bool(meta.extra.get("include_optimizer", True))
    hash_names = [n for n, s in collection.specs.items() if s.use_hash]
    # only hash variables need fresh (empty) device tables; bounded tables are
    # assembled host-side below and never pay the random-init program
    states = collection.init(rng, only=hash_names)
    out = {}
    for name, spec in collection.specs.items():
        vid = collection.variable_id(name)
        data = _open_var(path, vid, name)
        sspec = collection.sharding_spec(name)
        optimizer = collection.optimizer(name)
        if spec.use_hash:
            state = states[name]
            keys = data["keys"]
            weights = data["weights"]
            # slots present in both the checkpoint and the current optimizer
            # are restored; others keep their fresh init — loading into a
            # different optimizer category keeps weights and re-initializes
            # slots, the reference's copy_from hot-swap semantics
            # (EmbeddingVariable.cpp:29-60)
            slot_data = ({s: data[f"slot_{s}"] for s in state.slots
                          if f"slot_{s}" in data}
                         if with_opt else {})
            # stream fixed-size chunks (padded with EMPTY) to keep shapes static
            empty = hash_lib.empty_key(np.dtype(state.keys.dtype))
            n = keys.shape[0]
            for lo in range(0, max(n, 1), _LOAD_CHUNK):
                hi = min(lo + _LOAD_CHUNK, n)
                size = min(_LOAD_CHUNK, max(n, 1))
                ck = np.full((size,), empty, dtype=keys.dtype)
                cw = np.zeros((size,) + weights.shape[1:], weights.dtype)
                ck[:hi - lo] = keys[lo:hi]
                cw[:hi - lo] = weights[lo:hi]
                srows = {}
                for sname, full in slot_data.items():
                    cs = np.zeros((size,) + full.shape[1:], full.dtype)
                    cs[:hi - lo] = full[lo:hi]
                    srows[sname] = jnp.asarray(cs)
                state = sh.insert_rows_sharded(
                    state, jnp.asarray(ck), jnp.asarray(cw), srows,
                    mesh=collection.mesh, spec=sspec)
            failed = int(jax.device_get(state.insert_failures))
            if failed > 0:
                raise RuntimeError(
                    f"hash variable {name!r}: {failed} of {n} checkpoint "
                    f"rows did not fit (hash_capacity="
                    f"{spec.hash_capacity}); increase hash_capacity — a "
                    "load must deliver every row or fail")
            out[name] = state
        else:
            out[name] = _load_array_var(
                data, spec, sspec, optimizer,
                collection.state_shardings()[name], with_opt)
    if dense_state_template is not None:
        with open(os.path.join(path, DENSE_FILE), "rb") as f:
            dense = serialization.from_bytes(dense_state_template, f.read())
        return out, dense
    return out


def export_dense(collection: EmbeddingCollection,
                 states: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """Materialize bounded variables as dense [vocab, dim] arrays.

    ``save_as_original_model`` equivalent (exb.py:506-547): the result plugs
    into any plain embedding lookup. Hash variables cannot be densified and
    raise, matching exb.py:536.
    """
    out = {}
    for name, spec in collection.specs.items():
        if spec.use_hash:
            raise ValueError(
                f"variable {name!r} uses an unbounded hash key space and "
                "cannot be exported densely (reference rejects this too)")
        sspec = collection.sharding_spec(name)
        perm = _logical_perm(sspec)
        weights = np.asarray(jax.device_get(states[name].weights))[perm]
        out[name] = weights[:spec.input_dim]  # drop padding rows
    return out
