"""Sharded checkpoint save/load + dense export.

Capability parity with the reference's dump/load pipeline (SURVEY §3.4;
/root/reference/openembedding/server/EmbeddingDumpOperator.cpp,
EmbeddingLoadOperator.cpp, client/Model.cpp:89-134):

* ``<path>/model_meta`` — the same self-describing JSON head (model_sign,
  ordered variable metas, format version; reference Meta.h "0.2", ours
  ``META_FORMAT_VERSION``). Load validates variable metas match before
  touching any table (Model.cpp:110-121).
* per-variable ``var_<id>_<name>.npz`` — logical-row-order weights (+ named
  optimizer-state arrays when ``include_optimizer``, the reference's
  state_line_size != 0 flag, EmbeddingDumpOperator.cpp:36-76); hash variables
  store (keys, weights, states) triples of live rows only — the reference's
  streamed (indices, weights, states) blocks with re-globalized keys
  (EmbeddingShardFile.h:21-23).
* **Shard-topology independence**: arrays are written in *logical id order*
  (the physical mod-layout permutation is undone on save and re-applied on
  load), and hash rows are keyed — so a checkpoint taken on an 8-way mesh
  loads onto a 2-way mesh, like the reference re-shards by
  ``key % shard_num`` at load.
* ``export_dense`` — the ``save_as_original_model`` equivalent
  (exb.py:506-547): materializes every bounded variable as a dense array for
  serving without this framework; hash variables are rejected exactly like
  the reference (exb.py:536).

Dense flax params ride flax.serialization msgpack next to the sparse dump.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization

from .embedding import EmbeddingCollection
from .meta import ModelMeta
from . import hash_table as hash_lib
from . import table as table_lib
from .parallel import sharded_hash as sh
from .parallel import sharded_table as st

MODEL_META_FILE = "model_meta"
DENSE_FILE = "dense_state.msgpack"
_LOAD_CHUNK = 1 << 16


def _var_file(variable_id: int, name: str) -> str:
    safe = name.replace("/", "_").replace(":", "__")
    return f"var_{variable_id}_{safe}.npz"


def _logical_perm(spec: st.ShardingSpec) -> np.ndarray:
    """physical position of logical row r under the sharded layout."""
    r = np.arange(spec.padded_vocab, dtype=np.int64)
    shard = r % spec.num_shards if spec.layout == "mod" else r // spec.rows_per_shard
    local = r // spec.num_shards if spec.layout == "mod" else r % spec.rows_per_shard
    return shard * spec.rows_per_shard + local


def save_checkpoint(path: str,
                    collection: EmbeddingCollection,
                    states: Dict[str, Any],
                    *,
                    dense_state: Any = None,
                    include_optimizer: bool = True,
                    model_sign: str = "") -> None:
    """Dump all embedding variables (+ optional dense pytree) under ``path``."""
    os.makedirs(path, exist_ok=True)
    meta = collection.model_meta(model_sign=model_sign, model_uri=path)
    meta.extra["include_optimizer"] = bool(include_optimizer)
    # persist hash-table geometry so a loader (e.g. the serving registry,
    # which rebuilds specs from this meta alone) allocates tables that can
    # hold every stored row — the reference's load path delivers every row
    # or fails (EmbeddingLoadOperator.cpp:58-111)
    hash_info = {
        name: {"hash_capacity": spec.hash_capacity,
               "key_dtype": spec.key_dtype}
        for name, spec in collection.specs.items() if spec.use_hash
    }
    if hash_info:
        meta.extra["hash_variables"] = hash_info
    with open(os.path.join(path, MODEL_META_FILE), "w") as f:
        f.write(meta.dumps())

    for name, spec in collection.specs.items():
        state = states[name]
        vid = collection.variable_id(name)
        arrays = {}
        if spec.use_hash:
            keys = np.asarray(jax.device_get(state.keys))
            weights = np.asarray(jax.device_get(state.weights))
            live = keys != hash_lib.empty_key(keys.dtype)
            arrays["keys"] = keys[live]
            arrays["weights"] = weights[live]
            if include_optimizer:
                for sname, sval in state.slots.items():
                    arrays[f"slot_{sname}"] = np.asarray(
                        jax.device_get(sval))[live]
        else:
            # store only the real vocab rows in logical id order — padding
            # rows (vocab..padded_vocab) are unreachable by contract and
            # differ across mesh shapes, so dropping them is what makes the
            # checkpoint shard-topology independent
            sspec = collection.sharding_spec(name)
            perm = _logical_perm(sspec)[:spec.input_dim]
            arrays["weights"] = np.asarray(
                jax.device_get(state.weights))[perm]
            if include_optimizer:
                for sname, sval in state.slots.items():
                    arrays[f"slot_{sname}"] = np.asarray(
                        jax.device_get(sval))[perm]
        np.savez(os.path.join(path, _var_file(vid, name)), **arrays)

    if dense_state is not None:
        with open(os.path.join(path, DENSE_FILE), "wb") as f:
            f.write(serialization.to_bytes(jax.device_get(dense_state)))


def _check_meta(path: str, collection: EmbeddingCollection) -> ModelMeta:
    with open(os.path.join(path, MODEL_META_FILE)) as f:
        meta = ModelMeta.loads(f.read())
    want = collection.model_meta()
    got_vars = {v.name: v for v in meta.variables}
    for v in want.variables:
        if v.name not in got_vars:
            raise ValueError(f"checkpoint at {path!r} has no variable "
                             f"{v.name!r}")
        g = got_vars[v.name]
        if g.meta != v.meta:
            raise ValueError(
                f"variable {v.name!r} meta mismatch: checkpoint "
                f"{g.meta} vs model {v.meta}")
    return meta


def load_checkpoint(path: str,
                    collection: EmbeddingCollection,
                    *,
                    dense_state_template: Any = None,
                    rng: Optional[jax.Array] = None):
    """Rebuild all embedding states from ``path`` (any source mesh shape).

    Returns ``states`` or ``(states, dense_state)`` when a template pytree is
    given. Equivalent of Model::load_model: meta check -> clear weights ->
    re-deliver rows to owning shards (Model.cpp:110-134).
    """
    meta = _check_meta(path, collection)
    with_opt = bool(meta.extra.get("include_optimizer", True))
    hash_names = [n for n, s in collection.specs.items() if s.use_hash]
    # only hash variables need fresh (empty) device tables; bounded tables are
    # assembled host-side below and never pay the random-init program
    states = collection.init(rng, only=hash_names)
    out = {}
    for name, spec in collection.specs.items():
        vid = collection.variable_id(name)
        data = np.load(os.path.join(path, _var_file(vid, name)))
        sspec = collection.sharding_spec(name)
        optimizer = collection.optimizer(name)
        if spec.use_hash:
            state = states[name]
            keys = data["keys"]
            weights = data["weights"]
            # slots present in both the checkpoint and the current optimizer
            # are restored; others keep their fresh init — loading into a
            # different optimizer category keeps weights and re-initializes
            # slots, the reference's copy_from hot-swap semantics
            # (EmbeddingVariable.cpp:29-60)
            slot_data = ({s: data[f"slot_{s}"] for s in state.slots
                          if f"slot_{s}" in data}
                         if with_opt else {})
            # stream fixed-size chunks (padded with EMPTY) to keep shapes static
            empty = hash_lib.empty_key(np.dtype(state.keys.dtype))
            n = keys.shape[0]
            for lo in range(0, max(n, 1), _LOAD_CHUNK):
                hi = min(lo + _LOAD_CHUNK, n)
                size = min(_LOAD_CHUNK, max(n, 1))
                ck = np.full((size,), empty, dtype=keys.dtype)
                cw = np.zeros((size,) + weights.shape[1:], weights.dtype)
                ck[:hi - lo] = keys[lo:hi]
                cw[:hi - lo] = weights[lo:hi]
                srows = {}
                for sname, full in slot_data.items():
                    cs = np.zeros((size,) + full.shape[1:], full.dtype)
                    cs[:hi - lo] = full[lo:hi]
                    srows[sname] = jnp.asarray(cs)
                state = sh.insert_rows_sharded(
                    state, jnp.asarray(ck), jnp.asarray(cw), srows,
                    mesh=collection.mesh, spec=sspec)
            failed = int(jax.device_get(state.insert_failures))
            if failed > 0:
                raise RuntimeError(
                    f"hash variable {name!r}: {failed} of {n} checkpoint "
                    f"rows did not fit (hash_capacity="
                    f"{spec.hash_capacity}); increase hash_capacity — a "
                    "load must deliver every row or fail")
            out[name] = state
        else:
            # assemble the physical (mod-layout) arrays host-side, padding
            # rows beyond the stored vocab with zeros / slot-init values (they
            # are unreachable), then place them sharded
            perm = _logical_perm(sspec)
            shardings = collection.state_shardings()[name]
            dtype = np.dtype(table_lib.resolve_dtype(spec.meta()))
            dim = spec.output_dim
            pv = sspec.padded_vocab

            def _to_physical(logical_rows, fill, store_dtype):
                full = np.full((pv,) + logical_rows.shape[1:], fill,
                               dtype=store_dtype)
                full[:logical_rows.shape[0]] = logical_rows
                phys = np.empty_like(full)
                phys[perm] = full
                return phys

            weights = _to_physical(data["weights"], 0.0, dtype)
            new_slots = {}
            for sname, sshape in optimizer.slot_shapes(dim).items():
                sdtype = np.dtype(optimizer.slot_dtype(sname, dtype))
                fill = optimizer.slot_init(sname)
                if with_opt and f"slot_{sname}" in data:
                    rows = data[f"slot_{sname}"]
                else:
                    # absent from the dump (saved without optimizer state, or
                    # under a different optimizer category): fresh slot init,
                    # weights kept — copy_from hot-swap semantics
                    rows = np.empty((0, *sshape), dtype=sdtype)
                new_slots[sname] = jax.device_put(
                    _to_physical(rows, fill, sdtype), shardings.slots[sname])
            out[name] = table_lib.TableState(
                weights=jax.device_put(weights, shardings.weights),
                slots=new_slots)
    if dense_state_template is not None:
        with open(os.path.join(path, DENSE_FILE), "rb") as f:
            dense = serialization.from_bytes(dense_state_template, f.read())
        return out, dense
    return out


def export_dense(collection: EmbeddingCollection,
                 states: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """Materialize bounded variables as dense [vocab, dim] arrays.

    ``save_as_original_model`` equivalent (exb.py:506-547): the result plugs
    into any plain embedding lookup. Hash variables cannot be densified and
    raise, matching exb.py:536.
    """
    out = {}
    for name, spec in collection.specs.items():
        if spec.use_hash:
            raise ValueError(
                f"variable {name!r} uses an unbounded hash key space and "
                "cannot be exported densely (reference rejects this too)")
        sspec = collection.sharding_spec(name)
        perm = _logical_perm(sspec)
        weights = np.asarray(jax.device_get(states[name].weights))[perm]
        out[name] = weights[:spec.input_dim]  # drop padding rows
    return out
