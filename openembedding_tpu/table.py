"""Single-shard embedding table: functional pull / push+update.

TPU-native redesign of the reference's variable layer
(/root/reference/openembedding/variable/EmbeddingTable.h:121-197 array table,
EmbeddingOptimizerVariable.h:242-297 pull/push/update composition):

* The table is a dense ``[capacity, dim]`` array in HBM plus named optimizer
  slot arrays co-indexed with it — the reference's "weights and optimizer
  state contiguous per row" layout, split into parallel arrays so XLA keeps
  each slot contiguous and fuses the update elementwise.
* ``pull``: one gather. The reference's deferred materialization (_new_weights
  side table for unseen keys) is unnecessary because rows are initialized
  eagerly at creation with a PRNG (statistically identical, compiler-friendly).
* ``apply_gradients`` replaces the reference's push + store pipeline
  (MpscGradientReducer reduce → per-row optimizer update under shard lock):
  capacity-padded dedup, scatter-add combine, gather touched rows, vectorized
  optimizer ``update_rows``, scatter back. Exactly the touched-rows-only
  sparse semantics, in one fused XLA program instead of two RPC round trips.

The hash-table variant for unbounded (2^63) key spaces lives in
``hash_table.py``; both present the same pull/apply surface.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from flax import struct

from .meta import EmbeddingVariableMeta
from .ops import dedup
from .optim.initializers import Initializer, make_initializer
from .optim.optimizers import SparseOptimizer, make_optimizer


# Shared default: small-uniform like the reference's default variable config.
DEFAULT_INITIALIZER = {"category": "uniform", "minval": -1e-3, "maxval": 1e-3}


def resolve_dtype(meta: EmbeddingVariableMeta):
    """Table dtype with the x64 guard (float64 needs jax_enable_x64)."""
    dtype = jnp.dtype(meta.datatype)
    if dtype == jnp.float64 and not jax.config.jax_enable_x64:
        raise ValueError(
            "datatype='float64' requires jax_enable_x64; enable it with "
            "jax.config.update('jax_enable_x64', True) or use float32/bfloat16")
    return dtype


@struct.dataclass
class TableState:
    """Pytree holding one shard's weights + optimizer slots."""

    weights: jnp.ndarray                 # [capacity, dim]
    slots: Dict[str, jnp.ndarray]        # each [capacity, ...]

    @property
    def capacity(self) -> int:
        return self.weights.shape[0]

    @property
    def dim(self) -> int:
        return self.weights.shape[1]


def create_table(meta: EmbeddingVariableMeta,
                 optimizer: Any,
                 initializer: Any = None,
                 *,
                 rng: Optional[jax.Array] = None,
                 capacity: Optional[int] = None) -> TableState:
    """Materialize a table shard (weights initialized, slots at their init value).

    ``capacity`` defaults to ``meta.vocabulary_size`` (the whole table — use
    the sharded wrappers in ``parallel/`` to build per-shard slices).
    """
    optimizer = make_optimizer(optimizer)
    initializer = make_initializer(initializer or DEFAULT_INITIALIZER)
    if capacity is None:
        capacity = meta.vocabulary_size
    if rng is None:
        rng = jax.random.PRNGKey(0)
    dtype = resolve_dtype(meta)
    weights = initializer.init(rng, (capacity, meta.embedding_dim), dtype)
    slots = optimizer.init_slots(capacity, meta.embedding_dim, dtype)
    return TableState(weights=weights, slots=slots)


def pull(state: TableState, indices: jnp.ndarray) -> jnp.ndarray:
    """Embedding lookup: rows for (possibly duplicated) indices.

    Invalid indices (negative or >= capacity) return zero rows — the same
    contract as the sharded path and as apply_gradients, which drops them.
    Output shape = indices.shape + [dim].
    """
    flat = indices.ravel()
    valid = (flat >= 0) & (flat < state.capacity)
    rows = jnp.take(state.weights, jnp.where(valid, flat, 0), axis=0, mode="clip")
    rows = jnp.where(valid[:, None], rows, jnp.zeros_like(rows))
    return rows.reshape(indices.shape + (state.dim,))


def optimizer_block_update(optimizer: SparseOptimizer,
                           weights: jnp.ndarray,
                           slots: Dict[str, jnp.ndarray],
                           summed: jnp.ndarray,
                           counts: jnp.ndarray):
    """One vectorized optimizer step over a gathered [U, D] row block,
    with the framework-wide storage-dtype contract: math runs at >=
    float32 even for bfloat16 tables, results are cast back to each
    array's storage dtype. Shared by the array/hash apply paths and the
    hot-row replica update (``parallel/hot_cache.py``)."""
    compute = jnp.promote_types(weights.dtype, jnp.float32)
    new_w, new_s = optimizer.update_rows(
        weights.astype(compute),
        {k: v.astype(jnp.promote_types(v.dtype, jnp.float32))
         for k, v in slots.items()},
        summed.astype(compute), counts)
    new_w = new_w.astype(weights.dtype)
    new_s = {k: new_s[k].astype(slots[k].dtype) for k in new_s}
    return new_w, new_s


def apply_gradients(state: TableState,
                    optimizer: SparseOptimizer,
                    indices: jnp.ndarray,
                    grads: jnp.ndarray,
                    *,
                    dedup_capacity: Optional[int] = None,
                    in_counts: Optional[jnp.ndarray] = None) -> TableState:
    """Push + update in one step: combine duplicate grads, update touched rows.

    ``indices`` is [n] (or any shape), ``grads`` matches with a trailing
    [dim]. Rows not referenced are untouched (no state decay), duplicates are
    summed with counts — the reference's documented sparse-update contract.
    ``in_counts`` ([n]) marks grads that are already pre-reduced sums of that
    many originals (owner side of the all-to-all exchange).
    """
    dim = state.dim
    flat_idx = indices.ravel()
    flat_grads = grads.reshape(-1, dim)
    n = flat_idx.shape[0]
    capacity = dedup_capacity or n

    uniq, inverse, valid = dedup.unique_indices(flat_idx, capacity)
    # negative indices are invalid keys: pull clamps them to row 0, the
    # update must NOT let them wrap around onto a real row.
    valid = valid & (uniq >= 0)
    summed, counts = dedup.combine_gradients(flat_grads, inverse, capacity,
                                             in_counts)

    # Gather touched rows + slots; padding slots gather row 0 then are dropped
    # on the scatter, so their (garbage) update never lands.
    safe_uniq = jnp.where(valid, uniq, 0)
    w = jnp.take(state.weights, safe_uniq, axis=0)
    s = {k: jnp.take(v, safe_uniq, axis=0) for k, v in state.slots.items()}

    new_w, new_s = optimizer_block_update(optimizer, w, s, summed, counts)

    oob = jnp.asarray(state.capacity, dtype=safe_uniq.dtype)
    scatter_idx = jnp.where(valid, safe_uniq, oob)  # padding -> dropped
    weights = state.weights.at[scatter_idx].set(new_w, mode="drop")
    slots = {k: state.slots[k].at[scatter_idx].set(new_s[k], mode="drop")
             for k in state.slots}
    return TableState(weights=weights, slots=slots)
