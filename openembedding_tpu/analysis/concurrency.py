"""graftrace: lock-discipline analysis for the threaded host planes.

The graftcheck gate (:mod:`.contracts`) and graftlint (:mod:`.lint`)
prove invariants of the *jitted* device program; the host side that
keeps a production PS alive — offload's daemon writer/persister threads
(``offload.py``), the HA failover/registry/REST serving plane
(``serving/``), and the shared observability counters — is real
multithreaded code. This module is the third leg of the static-analysis
gate: it finds lock-discipline bugs the way Eraser (lockset analysis,
Savage et al. 1997) and ThreadSanitizer (happens-before detection,
Serebryany & Iskhodzhanov 2009) showed is mechanical, in three planes:

**1. Static lock-discipline linter** (AST, same shape as :mod:`.lint`,
stdlib-only so ``tools/graftrace.py`` loads it standalone)::

    JG100  file fails to parse (linted zero lines)
    JG101  unguarded shared-field access in a thread-spawning class
    JG102  inconsistent lock-acquisition order (cycle in the static
           lock-order graph)
    JG103  blocking call while holding a lock
    JG104  daemon thread with no join/shutdown path

Scope and honesty: JG101 is per-class lockset analysis. A class is
analyzed only when it BOTH owns a lock field and spawns a thread — a
class with locks but no threads protects against *callers'* threads the
analyzer cannot see (cross-module spawns like the Trainer's lookahead
driving ``offload.host_prepare`` are invisible; the runtime plane below
covers those). A field is *shared* when it is written outside
``__init__`` and accessed both from a thread-entry-reachable unit and
from elsewhere; it has a *discipline* when at least one access holds a
lock. Violations are accesses of disciplined shared fields holding no
guard lock — plus a field-level report when the accesses' locksets have
an empty intersection (no common lock). Held-lock context propagates
interprocedurally by call-site intersection: a method invoked *only*
from inside ``with self._lock:`` blocks is analyzed with that lock held
(the ``offload._evict`` pattern). Fields guarded purely by a
join/happens-before protocol (never locked anywhere — the offload host
store) are deliberately out of JG101's reach; they are what the
deterministic interleaving harness pins instead.

Suppression syntax — on the offending line or its enclosing ``def``
line::

    self.count += 1          # graftrace: disable=JG101
    def worker(self):        # graftrace: disable=JG101,JG103

CLI: ``python -m tools.graftrace openembedding_tpu/`` (nonzero exit on
violations) — wired into CI next to graftlint/graftcheck.

**2. Runtime detection** — :class:`TracedLock` / :class:`TracedRLock`
wrappers feeding a process-global lock-order graph with cycle detection
(*potential* deadlocks are reported even when never realized: an A→B
edge recorded anywhere plus a later B→A acquisition is a report, no
matter how the schedule happened to land) and per-lock contention /
wait / hold counters (:func:`lock_stats`, surfaced through
``utils/observability.py``). Opt-in: :func:`make_lock` /
:func:`make_rlock` return plain ``threading`` locks unless
``OE_REPORT_TRACE_LOCKS=1`` (the EnvConfig ``report.trace_locks``
field) or :func:`set_trace_locks` — production paths pay nothing.

**3. Deterministic interleaving harness** — :func:`sync_point` markers
(no-op global ``None`` check when no schedule is installed) at the
instrumented lock/thread points of offload, serving, and the Trainer
lookahead; :class:`SerialSchedule` replays a prescribed cross-thread
order and :class:`PointGate` holds named points closed until the test
releases them, turning the raciest interleavings into reproducible
regression tests (``tests/test_interleaving.py``).
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import threading
import time
import tokenize
from collections import deque
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

RULES: Dict[str, str] = {
    "JG100": "file fails to parse (linted zero lines)",
    "JG101": "unguarded shared-field access in a thread-spawning class",
    "JG102": "inconsistent lock-acquisition order (cycle in the static "
             "lock-order graph)",
    "JG103": "blocking call while holding a lock",
    "JG104": "daemon thread with no join/shutdown path",
}

# constructors whose result is a lock for guard/order purposes (Condition
# wraps a lock; Event/Semaphore are NOT guards)
_LOCK_CTORS = {"Lock", "RLock", "Condition", "TracedLock", "TracedRLock",
               "make_lock", "make_rlock"}

# callee names that block the calling thread (JG103). Deliberately
# narrow — `.wait()` is excluded (Condition.wait RELEASES its lock and
# is the sanctioned pattern), `.join` is special-cased to thread-bound
# receivers below (str.join would drown the rule in false positives).
_BLOCKING = {"sleep", "urlopen", "urlretrieve", "block_until_ready",
             "device_get", "getaddrinfo", "create_connection",
             "check_output", "check_call"}

# receiver-method names that mutate their receiver (shared with the
# graftlint JG001 notion; an access via these counts as a WRITE)
_MUTATORS = {"append", "extend", "update", "insert", "setdefault", "pop",
             "popleft", "remove", "discard", "clear", "add", "write",
             "put", "increment"}

_SUPPRESS_RE = re.compile(
    r"#\s*graftrace:\s*disable(?P<eq>=)?(?P<rules>[A-Za-z0-9, ]*)")
_RULE_TOKEN_RE = re.compile(r"JG\d+")


@dataclasses.dataclass(frozen=True)
class TraceViolation:
    path: str
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message} " \
               f"[{RULES[self.rule]}]"


def _suppressions(source: str) -> Dict[int, Optional[Set[str]]]:
    """line -> suppressed rule set (None = all rules) from comments."""
    out: Dict[int, Optional[Set[str]]] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            if m.group("eq"):
                # explicit rule list: parse FAIL-CLOSED — only tokens
                # shaped JGxxx count (case-normalized), and a list that
                # parses to nothing suppresses nothing. The alternative
                # (treating `disable=jg1o3` as bare `disable`) would
                # silently widen a typo into a blanket suppression.
                out[tok.start[0]] = {
                    t for t in (s.strip().upper()
                                for s in m.group("rules").split(","))
                    if _RULE_TOKEN_RE.fullmatch(t)}
            else:
                out[tok.start[0]] = None    # bare disable = all rules
    except (tokenize.TokenError, SyntaxError):
        # IndentationError (a SyntaxError) escapes tokenize on malformed
        # source — swallow it here so ast.parse gets to report JG100
        pass
    return out


def _reaches_in(succ, src, dst) -> bool:
    """dst reachable from src in the successor mapping ``succ`` — shared
    by the static JG102 pass (LockId keys) and the runtime lock-order
    graph (name keys)."""
    seen, work = set(), [src]
    while work:
        n = work.pop()
        if n == dst:
            return True
        if n in seen:
            continue
        seen.add(n)
        work.extend(succ.get(n, ()))
    return False


def _call_name(func: ast.expr) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _self_attr(node: ast.expr) -> Optional[str]:
    """'x' for ``self.x``, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _receiver_base(expr: ast.expr) -> ast.expr:
    """Innermost base of a dotted/subscripted chain."""
    while isinstance(expr, (ast.Attribute, ast.Subscript)):
        expr = expr.value
    return expr


def _self_field(expr: ast.expr) -> Optional[str]:
    """'x' for ``self.x``, ``self.x[...]``, ``self.x.y[...]`` — the field
    hanging directly off ``self`` in a dotted/subscripted chain."""
    while isinstance(expr, (ast.Attribute, ast.Subscript)):
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self":
            return expr.attr
        expr = expr.value
    return None


# lock identity: ("<ClassName>", attr) for self attrs, ("", name) for
# module-level locks
LockId = Tuple[str, str]


def _lock_id_of(expr: ast.expr, cls: Optional["_ClassInfo"],
                module_locks: Set[str]) -> Optional[LockId]:
    attr = _self_attr(expr)
    if attr is not None and cls is not None and attr in cls.lock_fields:
        return (cls.name, attr)
    if isinstance(expr, ast.Name) and expr.id in module_locks:
        return ("", expr.id)
    return None


def _fmt_lock(lock: LockId) -> str:
    return f"{lock[0]}.{lock[1]}" if lock[0] else lock[1]


@dataclasses.dataclass
class _Unit:
    """One analyzable code body: a method, or a function nested inside
    one (thread targets are usually nested ``_run`` defs)."""

    name: str
    node: ast.AST
    cls: Optional["_ClassInfo"]
    entry_held: Set[LockId] = dataclasses.field(default_factory=set)
    # (frozenset(entry_held), held_at) memo for _lexical_held — JG101's
    # fixed point, the order-graph warm-up, and JG103 all walk the same
    # units; the held map only changes when entry_held does
    held_cache: Optional[Tuple[frozenset, Dict[int, Set[LockId]]]] = None


@dataclasses.dataclass
class _ClassInfo:
    name: str
    node: ast.ClassDef
    lock_fields: Set[str] = dataclasses.field(default_factory=set)
    method_names: Set[str] = dataclasses.field(default_factory=set)
    thread_attrs: Set[str] = dataclasses.field(default_factory=set)
    spawns_thread: bool = False
    units: List[_Unit] = dataclasses.field(default_factory=list)
    # thread target names: self-attr method names and nested-def names
    thread_targets: Set[str] = dataclasses.field(default_factory=set)


@dataclasses.dataclass
class _Access:
    field: str
    line: int
    held: Set[LockId]
    unit: _Unit
    write: bool


class _ThreadBinding:
    """One ``threading.Thread(...)`` creation site (JG104 bookkeeping)."""

    def __init__(self, node: ast.Call, daemon: bool,
                 bound_name: Optional[str], bound_attr: Optional[str],
                 cls: Optional[str]):
        self.node = node
        self.daemon = daemon
        self.bound_name = bound_name   # local/module variable name
        self.bound_attr = bound_attr   # self.<attr> name
        self.cls = cls                 # owning class, for attr scoping


def _is_thread_ctor(call: ast.Call) -> bool:
    return _call_name(call.func) == "Thread"


def _thread_daemon(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
            return bool(kw.value.value)
    return False


def _thread_target(call: ast.Call) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == "target":
            return kw.value
    return None


class Analyzer:
    """Single-file analyzer; :func:`trace_source` is the functional
    entry point."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.violations: List[TraceViolation] = []
        self.suppress = _suppressions(source)
        self.module_locks: Set[str] = set()
        self.classes: List[_ClassInfo] = []
        self.module_units: List[_Unit] = []
        self.thread_bindings: List[_ThreadBinding] = []
        # name -> bound-from-Thread (for `.join` receiver resolution)
        self.thread_names: Set[str] = set()
        self.thread_attr_by_class: Dict[str, Set[str]] = {}
        self.joined_names: Set[str] = set()
        self.joined_attrs_by_class: Dict[str, Set[str]] = {}
        # static lock-order graph: edge -> first line it was observed on
        self.order_edges: Dict[Tuple[LockId, LockId], int] = {}

    # -- suppression ---------------------------------------------------------
    def _suppressed(self, rule: str, line: int,
                    def_line: Optional[int]) -> bool:
        for ln in (line, def_line):
            if ln is None or ln not in self.suppress:
                continue
            rules = self.suppress[ln]
            if rules is None or rule in rules:
                return True
        return False

    def _emit(self, rule: str, line: int, msg: str,
              def_line: Optional[int] = None) -> None:
        if not self._suppressed(rule, line, def_line):
            self.violations.append(
                TraceViolation(self.path, line, rule, msg))

    # -- indexing ------------------------------------------------------------
    def _index(self, tree: ast.Module) -> None:
        for node in tree.body:
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    _call_name(node.value.func) in _LOCK_CTORS:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.module_locks.add(t.id)
            if isinstance(node, ast.ClassDef):
                self.classes.append(self._index_class(node))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.module_units.append(_Unit(node.name, node, None))
        # thread bindings + joins, module-wide
        self._index_threads(tree)

    def _index_class(self, node: ast.ClassDef) -> _ClassInfo:
        info = _ClassInfo(name=node.name, node=node)
        for item in node.body:
            if not isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            info.method_names.add(item.name)
            info.units.append(_Unit(f"{node.name}.{item.name}", item, info))
            # nested defs are separate units (thread-target bodies)
            for sub in ast.walk(item):
                if sub is not item and isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    info.units.append(_Unit(
                        f"{node.name}.{item.name}.{sub.name}", sub, info))
        for unit in info.units:
            for sub in self._own_nodes(unit.node):
                # lock fields: self.x = threading.Lock()/make_lock(...)
                if isinstance(sub, ast.Assign) and \
                        isinstance(sub.value, ast.Call) and \
                        _call_name(sub.value.func) in _LOCK_CTORS:
                    for t in sub.targets:
                        attr = _self_attr(t)
                        if attr:
                            info.lock_fields.add(attr)
                if isinstance(sub, ast.Call) and _is_thread_ctor(sub):
                    info.spawns_thread = True
                    target = _thread_target(sub)
                    if target is not None:
                        attr = _self_attr(target)
                        if attr:
                            info.thread_targets.add(attr)
                        elif isinstance(target, ast.Name):
                            info.thread_targets.add(target.id)
        return info

    @staticmethod
    def _own_nodes(fn: ast.AST) -> Iterable[ast.AST]:
        """All nodes of a unit excluding nested function bodies."""
        def walk(node: ast.AST):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                yield child
                yield from walk(child)
        yield from walk(fn)

    def _index_threads(self, tree: ast.Module) -> None:
        """Thread creations, their bindings, and every ``.join`` receiver
        (JG104's join-path evidence). Attr bindings are scoped per class;
        bare-name bindings are module-wide (a name joined anywhere in the
        module counts — the Trainer's chained-prep idiom joins under a
        different binding of the same loop variable)."""
        cls_of: Dict[int, str] = {}
        for cls in self.classes:
            for sub in ast.walk(cls.node):
                cls_of[id(sub)] = cls.name

        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    _is_thread_ctor(node.value):
                cls = cls_of.get(id(node))
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr:
                        self.thread_bindings.append(_ThreadBinding(
                            node.value, _thread_daemon(node.value),
                            None, attr, cls))
                        if cls:
                            ci = next(c for c in self.classes
                                      if c.name == cls)
                            ci.thread_attrs.add(attr)
                    elif isinstance(t, ast.Name):
                        self.thread_bindings.append(_ThreadBinding(
                            node.value, _thread_daemon(node.value),
                            t.id, None, cls))
                        self.thread_names.add(t.id)
            # Thread() creations NOT bound by an Assign are caught
            # directly in _check_jg104 via the bound_calls set
            if isinstance(node, ast.Attribute) and node.attr == "join":
                base = node.value
                attr = _self_attr(base)
                if attr:
                    cls = cls_of.get(id(node), "")
                    self.joined_attrs_by_class.setdefault(
                        cls, set()).add(attr)
                elif isinstance(base, ast.Name):
                    self.joined_names.add(base.id)

    # -- held-lock computation ----------------------------------------------
    def _lexical_held(self, unit: _Unit) -> Dict[int, Set[LockId]]:
        """node-id -> lock set held lexically at that node (with-blocks),
        plus the unit's entry-held context."""
        key = frozenset(unit.entry_held)
        if unit.held_cache is not None and unit.held_cache[0] == key:
            return unit.held_cache[1]
        held_at: Dict[int, Set[LockId]] = {}
        cls = unit.cls
        mlocks = self.module_locks

        def walk(node: ast.AST, held: Set[LockId]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                if isinstance(child, ast.With):
                    acquired: Set[LockId] = set()
                    for item in child.items:
                        lock = _lock_id_of(item.context_expr, cls, mlocks)
                        if lock is not None:
                            acquired.add(lock)
                            # record static lock-order edges
                            for h in held | acquired - {lock}:
                                if h != lock:
                                    self.order_edges.setdefault(
                                        (h, lock), child.lineno)
                    held_at[id(child)] = set(held)
                    walk(child, held | acquired)
                    continue
                held_at[id(child)] = set(held)
                walk(child, held)

        walk(unit.node, set(unit.entry_held))
        unit.held_cache = (key, held_at)
        return held_at

    def _propagate_entry_held(self, cls: _ClassInfo) -> None:
        """Fixed point: a method called ONLY under lock L inherits L."""
        method_units = {u.name.split(".", 1)[1]: u for u in cls.units
                        if u.name.count(".") == 1}
        for _ in range(len(method_units) + 1):
            changed = False
            # gather call sites per method with current contexts
            sites: Dict[str, List[Set[LockId]]] = {m: []
                                                   for m in method_units}
            for unit in cls.units:
                held_at = self._lexical_held(unit)
                for node in self._own_nodes(unit.node):
                    if isinstance(node, ast.Call):
                        attr = _self_attr(node.func)
                        if attr in method_units:
                            sites[attr].append(held_at.get(id(node),
                                                           set()))
            for m, contexts in sites.items():
                new = (set.intersection(*contexts) if contexts else set())
                if new != method_units[m].entry_held:
                    method_units[m].entry_held = new
                    changed = True
            if not changed:
                break

    # -- thread reachability -------------------------------------------------
    def _thread_reachable(self, cls: _ClassInfo) -> Set[int]:
        """ids of units reachable from this class's thread entries."""
        by_method: Dict[str, _Unit] = {}
        by_nested: Dict[str, List[_Unit]] = {}
        for u in cls.units:
            parts = u.name.split(".")
            if len(parts) == 2:
                by_method[parts[1]] = u
            else:
                by_nested.setdefault(parts[-1], []).append(u)

        entries: List[_Unit] = []
        for t in cls.thread_targets:
            if t in by_method:
                entries.append(by_method[t])
            entries.extend(by_nested.get(t, ()))
        reach: Set[int] = set()
        work = list(entries)
        while work:
            u = work.pop()
            if id(u) in reach:
                continue
            reach.add(id(u))
            for node in self._own_nodes(u.node):
                if isinstance(node, ast.Call):
                    attr = _self_attr(node.func)
                    if attr in by_method and id(by_method[attr]) not in reach:
                        work.append(by_method[attr])
        return reach

    # -- accesses ------------------------------------------------------------
    def _collect_accesses(self, cls: _ClassInfo) -> List[_Access]:
        out: List[_Access] = []
        skip = cls.lock_fields | cls.method_names | cls.thread_attrs
        for unit in cls.units:
            if unit.node.name in ("__init__", "__post_init__"):
                continue
            held_at = self._lexical_held(unit)
            write_ids: Set[int] = set()
            for node in self._own_nodes(unit.node):
                targets: List[ast.expr] = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    targets = [node.target]
                for t in targets:
                    attr = _self_field(t)
                    if attr and attr not in skip:
                        out.append(_Access(attr, node.lineno,
                                           held_at.get(id(node), set()),
                                           unit, write=True))
                        for sub in ast.walk(t):
                            write_ids.add(id(sub))
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr in _MUTATORS:
                    attr = _self_field(node.func.value)
                    if attr and attr not in skip:
                        out.append(_Access(attr, node.lineno,
                                           held_at.get(id(node), set()),
                                           unit, write=True))
                        for sub in ast.walk(node.func):
                            write_ids.add(id(sub))
            # reads: remaining self.F loads not already counted as writes
            for node in self._own_nodes(unit.node):
                if isinstance(node, ast.Attribute) and \
                        isinstance(node.ctx, ast.Load) and \
                        id(node) not in write_ids:
                    attr = _self_attr(node)
                    if attr and attr not in skip:
                        out.append(_Access(attr, node.lineno,
                                           held_at.get(id(node), set()),
                                           unit, write=False))
        return out

    # -- rules ---------------------------------------------------------------
    def _check_jg101(self, cls: _ClassInfo) -> None:
        if not cls.lock_fields or not cls.spawns_thread:
            return
        self._propagate_entry_held(cls)
        reach = self._thread_reachable(cls)
        accesses = self._collect_accesses(cls)
        by_field: Dict[str, List[_Access]] = {}
        for a in accesses:
            by_field.setdefault(a.field, []).append(a)
        for field, accs in sorted(by_field.items()):
            written = any(a.write for a in accs)
            in_thread = any(id(a.unit) in reach for a in accs)
            outside = any(id(a.unit) not in reach for a in accs)
            if not (written and in_thread and outside):
                continue            # not shared, or read-only config
            guards = set().union(*(a.held for a in accs))
            if not guards:
                continue            # join/happens-before protocol field
            bare = [a for a in accs if not a.held]
            for a in bare:
                where = ("thread-reachable " if id(a.unit) in reach
                         else "")
                self._emit(
                    "JG101", a.line,
                    f"field `self.{field}` is guarded by "
                    f"{sorted(_fmt_lock(g) for g in guards)} elsewhere "
                    f"but accessed lock-free in {where}"
                    f"`{a.unit.name}`", a.unit.node.lineno)
            if not bare:
                common = set.intersection(*(a.held for a in accs))
                if not common:
                    first = min(accs, key=lambda a: a.line)
                    locksets = sorted(
                        {tuple(sorted(_fmt_lock(g) for g in a.held))
                         for a in accs})
                    self._emit(
                        "JG101", first.line,
                        f"accesses of `self.{field}` hold no COMMON "
                        f"lock (locksets seen: {locksets})",
                        first.unit.node.lineno)

    def _check_jg102(self) -> None:
        """Cycle in the static lock-order graph: report every edge that
        participates in a cycle (each is a fix site)."""
        succ: Dict[LockId, Set[LockId]] = {}
        for (a, b) in self.order_edges:
            succ.setdefault(a, set()).add(b)

        for (a, b), line in sorted(self.order_edges.items(),
                                   key=lambda kv: kv[1]):
            if _reaches_in(succ, b, a):
                self._emit(
                    "JG102", line,
                    f"acquiring `{_fmt_lock(b)}` while holding "
                    f"`{_fmt_lock(a)}` closes a lock-order cycle "
                    f"(`{_fmt_lock(b)}` is also acquired before "
                    f"`{_fmt_lock(a)}` elsewhere)")

    def _check_jg103(self) -> None:
        all_units = list(self.module_units)
        for cls in self.classes:
            all_units.extend(cls.units)
        for unit in all_units:
            held_at = self._lexical_held(unit)
            for node in self._own_nodes(unit.node):
                if not isinstance(node, ast.Call):
                    continue
                held = held_at.get(id(node), set())
                if not held:
                    continue
                name = _call_name(node.func)
                blocking = name in _BLOCKING
                if not blocking and name == "join" and \
                        isinstance(node.func, ast.Attribute):
                    base = node.func.value
                    attr = _self_attr(base)
                    if attr and unit.cls and attr in unit.cls.thread_attrs:
                        blocking = True
                    elif isinstance(base, ast.Name) and \
                            base.id in self.thread_names:
                        blocking = True
                if blocking:
                    self._emit(
                        "JG103", node.lineno,
                        f"`{ast.unparse(node.func)}(...)` blocks while "
                        f"holding {sorted(_fmt_lock(h) for h in held)} — "
                        "every other thread needing the lock stalls "
                        "behind the wait", unit.node.lineno)

    def _check_jg104(self, tree: ast.Module) -> None:
        bound_calls = {id(b.node) for b in self.thread_bindings}
        # unbound daemon creations: Thread(...).start() / bare Thread(...)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and _is_thread_ctor(node) \
                    and id(node) not in bound_calls \
                    and _thread_daemon(node):
                self._emit(
                    "JG104", node.lineno,
                    "fire-and-forget daemon thread: nothing can join it, "
                    "observe its exception, or shut it down — it dies "
                    "with the interpreter mid-work")
        for b in self.thread_bindings:
            if not b.daemon:
                continue
            if b.bound_name is not None:
                if b.bound_name not in self.joined_names:
                    self._emit(
                        "JG104", b.node.lineno,
                        f"daemon thread bound to `{b.bound_name}` is "
                        "never joined anywhere in this module — errors "
                        "and shutdown are silent")
            elif b.bound_attr is not None:
                joined = self.joined_attrs_by_class.get(b.cls or "", set())
                if b.bound_attr not in joined:
                    self._emit(
                        "JG104", b.node.lineno,
                        f"daemon thread stored in `self.{b.bound_attr}` "
                        f"is never joined by {b.cls or 'this module'} — "
                        "errors and shutdown are silent")

    # -- main ----------------------------------------------------------------
    def run(self) -> List[TraceViolation]:
        try:
            tree = ast.parse(self.source)
        except SyntaxError as e:
            self.violations.append(TraceViolation(
                self.path, e.lineno or 0, "JG100",
                f"file does not parse: {e.msg}"))
            return self.violations
        self._index(tree)
        for cls in self.classes:
            self._check_jg101(cls)
        # populate the static lock-order graph over EVERY unit before the
        # cycle check — module-level functions matter too (module locks
        # order against class locks); entry-held propagation first where
        # a class owns locks, so interprocedurally-held edges appear
        # (_check_jg101 already propagated the thread-spawning classes)
        for cls in self.classes:
            if cls.lock_fields and not cls.spawns_thread:
                self._propagate_entry_held(cls)
        for unit in self.module_units:
            self._lexical_held(unit)
        for cls in self.classes:
            for u in cls.units:
                self._lexical_held(u)
        self._check_jg102()
        self._check_jg103()
        self._check_jg104(tree)
        self.violations.sort(key=lambda v: (v.line, v.rule))
        return self.violations


def trace_source(source: str, path: str = "<string>"
                 ) -> List[TraceViolation]:
    """Analyze one module's source text."""
    return Analyzer(path, source).run()


def trace_paths(paths: Sequence[str]) -> List[TraceViolation]:
    """Analyze files and/or directory trees (``.py``, recursively)."""
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, names in os.walk(p):
                if "__pycache__" in root:
                    continue
                files.extend(os.path.join(root, n) for n in sorted(names)
                             if n.endswith(".py"))
        else:
            files.append(p)
    out: List[TraceViolation] = []
    for f in files:
        with open(f, "r", encoding="utf-8") as fh:
            out.extend(trace_source(fh.read(), f))
    return out


# ---------------------------------------------------------------------------
# Runtime plane: traced locks, lock-order graph, contention counters
# ---------------------------------------------------------------------------

_TRACE_ENV = "OE_REPORT_TRACE_LOCKS"
_trace_forced: Optional[bool] = None

_RT = threading.Lock()                   # guards the registries below
_ORDER: Dict[str, Set[str]] = {}         # lock name -> successors
_CYCLES: List[str] = []                  # recorded potential deadlocks
_CYCLE_PAIRS: Set[Tuple[str, str]] = set()
_STATS: Dict[str, Dict[str, float]] = {}
_HELD = threading.local()                # .stack: [(name, t_acquired)]
_STACKS: List[list] = []                 # every thread's held stack, for
                                         # cross-thread releases


def set_trace_locks(on: Optional[bool]) -> None:
    """Force runtime lock tracing on/off; ``None`` restores the
    environment-variable default (``OE_REPORT_TRACE_LOCKS``)."""
    global _trace_forced
    _trace_forced = on


def trace_locks_enabled() -> bool:
    if _trace_forced is not None:
        return _trace_forced
    v = os.environ.get(_TRACE_ENV, "")
    return v.lower() in ("1", "true", "yes", "on")


def make_lock(name: str):
    """A named lock: :class:`TracedLock` when tracing is enabled, a plain
    ``threading.Lock`` otherwise (the enablement check runs ONCE, at
    construction — production paths pay nothing per acquire)."""
    return TracedLock(name) if trace_locks_enabled() else threading.Lock()


def make_rlock(name: str):
    """Reentrant variant of :func:`make_lock`."""
    return TracedRLock(name) if trace_locks_enabled() \
        else threading.RLock()


def reset_runtime() -> None:
    """Clear the lock-order graph, recorded cycles, and counters
    (test isolation)."""
    with _RT:
        _ORDER.clear()
        _CYCLES.clear()
        _CYCLE_PAIRS.clear()
        _STATS.clear()
        # _STACKS is NOT pruned: each live thread's thread-local still
        # references its (usually empty) list, and dropping it here
        # would orphan the thread from cross-thread release lookups.
        # A dead thread leaks one empty list — negligible.


def potential_deadlocks() -> List[str]:
    """Every lock-order cycle the traced locks have observed so far —
    *potential* deadlocks: an A→B ordering recorded anywhere plus a
    B→A acquisition is reported even if the schedule never realized the
    deadlock (the lock-order-graph method, same as the static JG102 but
    over the orders that actually executed)."""
    with _RT:
        return list(_CYCLES)


def lock_stats() -> Dict[str, Dict[str, float]]:
    """Per-lock runtime counters: ``acquires``, ``contended`` (acquire
    found the lock held), ``wait_s`` (time blocked acquiring), ``hold_s``
    (time held). Surfaced through ``observability.lock_stats()``."""
    with _RT:
        return {k: dict(v) for k, v in _STATS.items()}


def _stat(name: str) -> Dict[str, float]:
    return _STATS.setdefault(name, {"acquires": 0, "contended": 0,
                                    "wait_s": 0.0, "hold_s": 0.0})


def _held_stack() -> list:
    stack = getattr(_HELD, "stack", None)
    if stack is None:
        stack = _HELD.stack = []
        with _RT:
            _STACKS.append(stack)
    return stack


def _note_acquired(name: str, contended: bool, wait: float) -> None:
    stack = _held_stack()
    with _RT:
        st = _stat(name)
        st["acquires"] += 1
        st["contended"] += 1 if contended else 0
        st["wait_s"] += wait
        for held, _t0 in stack:
            if held == name:
                continue
            _ORDER.setdefault(held, set()).add(name)
            # closing edge? then name ->* held already existed
            if (held, name) not in _CYCLE_PAIRS and \
                    _reaches_in(_ORDER, name, held):
                _CYCLE_PAIRS.add((held, name))
                _CYCLE_PAIRS.add((name, held))
                _CYCLES.append(
                    f"potential deadlock: `{held}` -> `{name}` acquired "
                    f"while the reverse order `{name}` -> `{held}` was "
                    "recorded earlier")
        # under _RT: the cross-thread-release branch below scans and
        # pops OTHER threads' stacks, so even own-stack mutation races
        # against it lock-free
        stack.append((name, time.perf_counter()))


def _note_released(name: str) -> None:
    stack = _held_stack()
    with _RT:
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] == name:
                _n, t0 = stack.pop(i)
                _stat(name)["hold_s"] += time.perf_counter() - t0
                return
        # released by a thread other than the acquirer — legal for
        # threading.Lock (handoff/signaling patterns). Close the
        # acquirer's entry: left stale, it would fabricate an order edge
        # for every lock that thread acquires next, poisoning
        # potential_deadlocks()
        for other in _STACKS:
            if other is stack:
                continue
            for i in range(len(other) - 1, -1, -1):
                if other[i][0] == name:
                    _n, t0 = other.pop(i)
                    _stat(name)["hold_s"] += time.perf_counter() - t0
                    return


class TracedLock:
    """``threading.Lock`` wrapper feeding the lock-order graph and the
    contention/hold counters; every acquire/release is also a
    :func:`sync_point` (``lock:<name>:acquire`` / ``:release``) so the
    interleaving harness can schedule around it."""

    _reentrant = False

    def __init__(self, name: str):
        self.name = name
        self._inner = self._make_inner()
        self._depth = threading.local()
        self._owner: Optional[int] = None   # holder ident (reentrant only)

    @staticmethod
    def _make_inner():
        return threading.Lock()

    def _depth_get(self) -> int:
        return getattr(self._depth, "n", 0)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        sync_point(f"lock:{self.name}:acquire")
        if self._reentrant and self._depth_get() > 0:
            got = self._inner.acquire(blocking, timeout)
            if got:
                self._depth.n = self._depth_get() + 1
            return got
        t0 = time.perf_counter()
        got = self._inner.acquire(False)
        contended = not got
        if not got and blocking:
            got = self._inner.acquire(True, timeout)
        if got:
            _note_acquired(self.name, contended,
                           time.perf_counter() - t0)
            if self._reentrant:
                self._depth.n = self._depth_get() + 1
                self._owner = threading.get_ident()
        return got

    def release(self) -> None:
        if self._reentrant:
            self._depth.n = self._depth_get() - 1
            if self._depth.n > 0:
                self._inner.release()
                return
            self._owner = None
        _note_released(self.name)
        self._inner.release()
        sync_point(f"lock:{self.name}:release")

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class TracedRLock(TracedLock):
    """Reentrant :class:`TracedLock`: only the outermost acquire/release
    updates the order graph and the hold timer."""

    _reentrant = True

    @staticmethod
    def _make_inner():
        return threading.RLock()

    def locked(self) -> bool:
        # RLock grows .locked() only in Python 3.14; the owner field
        # kept by the outermost acquire/release answers without touching
        # the lock itself (an acquire-probe would steal the lock for a
        # moment and spuriously fail concurrent non-blocking acquires)
        return self._owner is not None


# ---------------------------------------------------------------------------
# Deterministic interleaving harness
# ---------------------------------------------------------------------------

_SCHEDULE = None


def install_schedule(schedule) -> None:
    """Install a schedule (``SerialSchedule``/``PointGate``/anything with
    ``sync(key, point)``); :func:`clear_schedule` removes it. ONE global
    slot: schedules are a test-harness facility, not production state."""
    global _SCHEDULE
    _SCHEDULE = schedule


def clear_schedule() -> None:
    global _SCHEDULE
    _SCHEDULE = None


def sync_point(point: str) -> None:
    """Named interleaving marker. A no-op (one global ``None`` check)
    unless a schedule is installed; then the schedule decides when the
    calling thread may proceed. Keys are matched as the bare ``point``
    or ``"<thread name>/<point>"`` (name the test's threads to address
    them individually)."""
    sched = _SCHEDULE
    if sched is None:
        return
    sched.sync(f"{threading.current_thread().name}/{point}", point)


class SerialSchedule:
    """Replay a prescribed total order of sync points across threads.

    ``order`` is a list of keys — ``"<thread>/<point>"`` to address one
    thread's arrival, or a bare ``"<point>"`` to match whichever thread
    arrives. A thread reaching a point that appears in the remaining
    order blocks until its key is at the head; points not in the
    remaining order pass through untouched. A ``timeout`` expiry raises
    (a wedged schedule must fail the test, not hang the suite).
    """

    def __init__(self, order: Sequence[str], timeout: float = 20.0):
        self._order = deque(order)
        self._cv = threading.Condition()
        self._timeout = timeout

    def sync(self, key: str, point: str) -> None:
        deadline = time.monotonic() + self._timeout
        with self._cv:
            while True:
                if not self._order or (key not in self._order
                                       and point not in self._order):
                    return
                head = self._order[0]
                if head in (key, point):
                    self._order.popleft()
                    self._cv.notify_all()
                    return
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"schedule wedged: {key!r} waited for head "
                        f"{head!r} (remaining order "
                        f"{list(self._order)!r})")
                self._cv.wait(remaining)

    def done(self) -> bool:
        with self._cv:
            return not self._order


class PointGate:
    """Hold named sync points CLOSED until the test opens them.

    ``gate = PointGate(["offload.writeback.scatter"])`` blocks any
    thread reaching that point; ``gate.wait_arrival(point)`` lets the
    test confirm a thread is parked there (the deterministic observation
    window), and ``gate.open(point)`` releases it — and every later
    arrival. Entries may be bare points (gate every thread) or
    ``"<thread name>/<point>"`` keys (gate one thread — two named
    threads parked at the same point is the canonical race-observation
    window). Points not listed pass through untouched.
    """

    def __init__(self, points: Sequence[str], timeout: float = 20.0):
        self._open = {p: threading.Event() for p in points}
        self._arrived = {p: threading.Event() for p in points}
        self._timeout = timeout

    def sync(self, key: str, point: str) -> None:
        # the thread-specific key wins over the bare point, so a test can
        # gate "racer-0/p" while other threads pass "p" untouched
        k = key if key in self._open else point
        ev = self._open.get(k)
        if ev is None:
            return
        self._arrived[k].set()
        if not ev.wait(self._timeout):
            raise TimeoutError(f"gate {k!r} never opened")

    def wait_arrival(self, point: str, timeout: Optional[float] = None
                     ) -> bool:
        return self._arrived[point].wait(timeout or self._timeout)

    def open(self, point: str) -> None:
        self._open[point].set()

    def open_all(self) -> None:
        for ev in self._open.values():
            ev.set()
