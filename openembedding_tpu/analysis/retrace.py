"""Retrace guard: a compile-count budget around training loops.

A steady-state training loop should compile NOTHING: every step reuses
the jitted step program, every pull/push program is cached by its static
config. A recompile per step — a shape wobble from an unpadded last
batch, a Python value smuggled into a traced signature, an lru_cache key
that includes a per-step object — silently turns a ~ms step into a
~second step. The reference's answer is operational (jax_log_compiles
eyeballing); this guard makes it mechanical: count XLA backend compiles
over a scope and fail when they exceed the declared budget.

Counting uses :mod:`jax.monitoring`'s duration events (the
``/jax/core/compile/backend_compile_duration`` key fires once per real
XLA compilation, cache hits fire nothing), so the guard is exact and
costs nothing per step. Wired into :meth:`Trainer.fit`
(``retrace_budget=``) and the deepctr example (``--retrace_budget``).

Usage::

    with RetraceGuard(budget=0, name="steady-state loop"):
        for batch in batches:
            state, metrics = trainer.train_step(state, batch)

Nesting is supported; each guard counts every compile that happens while
it is open (an inner guard's compiles are also the outer one's).
"""

from __future__ import annotations

import threading
from typing import List, Optional

import jax

_COMPILE_EVENT = "backend_compile"


class RetraceBudgetExceeded(RuntimeError):
    """More XLA compilations happened inside the guard than budgeted."""


_lock = threading.Lock()
_active: List["RetraceGuard"] = []
_listener_registered = False


def _on_event(event: str, duration_secs: float, **_kw) -> None:
    if _COMPILE_EVENT not in event:
        return
    with _lock:
        for guard in _active:
            guard._compiles += 1


def _ensure_listener() -> None:
    """Register the module's single monitoring listener (idempotent).

    jax.monitoring has no public unregister, so one listener stays
    installed once any guard has been used; it is a no-op dict walk when
    no guard is active.
    """
    global _listener_registered
    with _lock:
        if _listener_registered:
            return
        jax.monitoring.register_event_duration_secs_listener(_on_event)
        _listener_registered = True


class RetraceGuard:
    """Context manager failing when XLA compiles exceed ``budget``.

    ``budget`` is the number of compilations ALLOWED inside the scope
    (0 = a steady-state loop that must be compile-free). ``on_exceed``:
    ``"raise"`` (default) raises :class:`RetraceBudgetExceeded` on exit;
    ``"warn"`` prints one warning and continues — the mode the example
    wires in so a budget trip shows up in CI logs without killing a run
    mid-epoch.
    """

    def __init__(self, budget: int = 0, *, name: str = "",
                 on_exceed: str = "raise"):
        if on_exceed not in ("raise", "warn"):
            raise ValueError(f"on_exceed must be 'raise' or 'warn', "
                             f"got {on_exceed!r}")
        self.budget = int(budget)
        self.name = name
        self.on_exceed = on_exceed
        self._compiles = 0
        self._entered = False

    @property
    def compiles(self) -> int:
        """XLA compilations observed so far inside this guard."""
        return self._compiles

    @property
    def exceeded(self) -> bool:
        return self._compiles > self.budget

    def __enter__(self) -> "RetraceGuard":
        if self._entered:
            raise RuntimeError("RetraceGuard is not reentrant; create a "
                               "new guard per scope")
        _ensure_listener()
        self._compiles = 0
        self._entered = True
        with _lock:
            _active.append(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        with _lock:
            if self in _active:
                _active.remove(self)
        self._entered = False
        if exc_type is not None:
            return False            # the original error is the story
        if self.exceeded:
            label = f" [{self.name}]" if self.name else ""
            msg = (f"retrace budget exceeded{label}: {self._compiles} "
                   f"XLA compilation(s) > budget {self.budget} — "
                   "something in the loop retraces per step (shape "
                   "wobble, Python value in a traced signature, or a "
                   "program-cache key churning)")
            if self.on_exceed == "raise":
                raise RetraceBudgetExceeded(msg)
            import warnings
            warnings.warn(msg, RuntimeWarning, stacklevel=2)
        return False


def compile_count(fn, *args, **kwargs) -> int:
    """Run ``fn(*args, **kwargs)`` and return how many XLA compilations
    it triggered (a measurement helper for tests and diagnostics)."""
    with RetraceGuard(budget=1 << 30) as g:
        fn(*args, **kwargs)
        n = g.compiles
    return n


def assert_no_recompiles(fn, *args, warmup: int = 1, **kwargs) -> None:
    """Call ``fn`` ``warmup`` times, then once more under a zero-budget
    guard: the steady-state invocation must be compile-free."""
    for _ in range(max(0, warmup)):
        fn(*args, **kwargs)
    with RetraceGuard(budget=0, name=getattr(fn, "__name__", "fn")):
        fn(*args, **kwargs)
