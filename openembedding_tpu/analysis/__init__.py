"""graftcheck: static analysis of the compiled programs and the source.

Three enforcement layers, all mechanical (ISSUE 3):

* :mod:`.contracts` — declarative per-plane contracts over compiled HLO
  text: which collectives each data plane's pull/push/step program may
  contain and how big their buffers may be, plus cross-cutting audits
  (no f64 leaks, donation honored, no host transfers inside the step).
* :mod:`.lint` — a jit-purity AST linter over the package's own source
  (host-state mutation under trace, tracer materialization, retrace-risk
  branches, undonated step functions). CLI: ``python -m tools.graftlint``.
* :mod:`.concurrency` — graftrace (ISSUE 4): a lock-discipline linter
  over the threaded host planes (rules JG101-JG104, CLI
  ``python -m tools.graftrace``), runtime TracedLock/TracedRLock
  wrappers with lock-order-cycle (potential-deadlock) detection and
  contention counters, and the deterministic interleaving harness
  (``sync_point``/``SerialSchedule``/``PointGate``).
* :mod:`.retrace` — a runtime guard that counts XLA compilations around
  a training loop and fails past a declared budget.
* :mod:`.scope` — graftscope (ISSUE 6): span tracing into per-thread
  ring buffers (Chrome-trace/Perfetto export), the log-bucket histogram
  registry behind the ``/metrics`` ``_bucket``/``_sum``/``_count``
  series, and the expected-vs-measured collective-byte ledger (CLI
  ``python -m tools.graftscope``).
* :mod:`.memwatch` — graftwatch (ISSUE 7): the per-plane compiled-
  program MEMORY ledger (``memory_analysis`` argument/output/temp/alias
  bytes via the jaxcompat shim) with the peak-temp-bytes contract, and
  the substrate under the ``tools/graftwatch.py`` bench-trajectory
  regression gate.
* :mod:`.protomodel` — graftproto (ISSUE 13): explicit-state BFS model
  checker + faithful models of the four shipped host protocols (delta
  chain, serving hot-swap, DirtyTracker claims, HA registry), each
  action bridged to real ``sync_point`` names so counterexample
  schedules replay against the implementation. CLI:
  ``python -m tools.graftproto``.

Import discipline: ``contracts``, ``lint``, ``concurrency``, and
``scope`` are stdlib-only at import time and imported eagerly, so every
subsystem module (and the graftlint/graftrace CLIs) can use
``@host_fn`` / ``make_lock`` / ``sync_point`` / ``span`` without paying
for jax (``scope`` looks jax up lazily, and only when something else
already imported it). ``retrace`` (imports jax) and ``programs``
(lowers real programs) load lazily via module ``__getattr__`` — the
public surface is unchanged.
"""

from . import concurrency, contracts, lint, protomodel, scope
from .concurrency import (TraceViolation, TracedLock, TracedRLock,
                          make_lock, make_rlock, sync_point,
                          trace_paths, trace_source)
from .contracts import (ContractViolation, ProgramContract, OpBudget,
                        REGISTRY, check_program, collect_collectives,
                        summarize, check_a2a_pull_hlo)
from .lint import LintViolation, host_fn, lint_paths, lint_source
from .scope import (HISTOGRAMS, HistogramRegistry, Span,
                    export_chrome_trace, span, step_span)

_LAZY = {
    "retrace": ".retrace", "programs": ".programs",
    "memwatch": ".memwatch",
    "RetraceBudgetExceeded": ".retrace", "RetraceGuard": ".retrace",
}


def __getattr__(name):  # PEP 562: defer the jax-importing submodules
    if name in _LAZY:
        import importlib
        mod = importlib.import_module(_LAZY[name], __name__)
        if name in ("retrace", "programs", "memwatch"):
            return mod
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "concurrency", "contracts", "lint", "retrace", "programs", "scope",
    "memwatch", "protomodel",
    "HISTOGRAMS", "HistogramRegistry", "Span", "export_chrome_trace",
    "span", "step_span",
    "ContractViolation", "ProgramContract", "OpBudget", "REGISTRY",
    "check_program", "collect_collectives", "summarize",
    "check_a2a_pull_hlo",
    "LintViolation", "host_fn", "lint_paths", "lint_source",
    "TraceViolation", "TracedLock", "TracedRLock", "make_lock",
    "make_rlock", "sync_point", "trace_paths", "trace_source",
    "RetraceBudgetExceeded", "RetraceGuard",
]
