"""Lower the framework's data-plane programs to compiled HLO text.

Shared by ``tests/test_analysis_contracts.py`` and the
``tools/graftcheck.py`` CI gate: build a collection on a mesh, lower the
pull / push / train-step programs exactly as the training path runs them
(batch-sharded inputs, batch-sharded outputs — a replicated output would
force an artifact gather and fail the pull bound for the wrong reason),
and return ``(hlo_text, params)`` ready for
:func:`..analysis.contracts.check_program`.

Imports of the wider package happen inside the functions (this module
is part of ``analysis``, which the rest of the package may import at
module level — see the package docstring).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

CACHE_K = 128


def _collection(mesh, plane: str, *, vocab: int, dim: int,
                use_hash: bool):
    from ..embedding import EmbeddingCollection, EmbeddingSpec
    if use_hash:
        spec = EmbeddingSpec(name="t", input_dim=-1, output_dim=dim,
                             hash_capacity=vocab, plane=plane,
                             cache_k=CACHE_K)
    else:
        spec = EmbeddingSpec(name="t", input_dim=vocab, output_dim=dim,
                             plane=plane, cache_k=CACHE_K)
    return EmbeddingCollection((spec,), mesh)


def contract_params(mesh, *, batch: int, dim: int, itemsize: int = 4,
                    vocab: Optional[int] = None,
                    state_nbytes: Optional[int] = None) -> Dict[str, int]:
    from ..parallel.mesh import DATA_AXIS
    data = mesh.shape[DATA_AXIS]
    params = {"batch_slice": batch // data, "global_batch": batch,
              "dim": dim, "itemsize": itemsize, "cache_k": CACHE_K,
              "num_shards": mesh.size}
    if vocab is not None:
        # one table shard's WEIGHT bytes — the unit the memory-ledger
        # peak-temp audit detects accidental materializations in
        params["table_shard_bytes"] = vocab * dim * itemsize // mesh.size
    if state_nbytes is not None:
        # the whole state pytree's per-device share (weights + optimizer
        # slots + hash keys); replicated leaves (cache replicas) make
        # this a slight underestimate, absorbed by the audit's slack
        params["state_shard_bytes"] = int(state_nbytes) // mesh.size
    return params


def _state_nbytes(states) -> int:
    import jax
    return int(sum(x.nbytes for x in jax.tree.leaves(states)))


def _wire_params(plane: str, program: str) -> Dict[str, int]:
    """Precision-aware contract params for a (possibly compressed)
    plane token: the wire itemsize of the program's row/grad payload
    (``parallel/precision.py``). Empty for uncompressed planes, so the
    f32 bounds stay byte-identical to before."""
    from ..parallel import precision
    _base, ep, pp = precision.parse_plane(plane)
    rung = ep if program == "pull" else pp
    if rung == "f32":
        return {}
    return {"wire_itemsize": precision.wire_itemsize(rung)}


def compile_pull(mesh, plane: str, *, vocab: int = 1 << 16, dim: int = 16,
                 batch: int = 1024, use_hash: bool = False,
                 out_replicated: bool = False):
    """Compiled pull program + contract params — the object form, for
    callers that also need ``memory_analysis()`` (graftwatch's memory
    ledger); :func:`lower_pull` is the HLO-text view of the same build.

    ``out_replicated=True`` deliberately breaks the output sharding
    annotation (rows replicated instead of batch-sharded): XLA must then
    gather the global batch onto every device — the regression shape the
    a2a pull contract exists to catch. Test-only.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..parallel.mesh import DATA_AXIS
    coll = _collection(mesh, plane, vocab=vocab, dim=dim,
                       use_hash=use_hash)
    states = coll.init(jax.random.PRNGKey(0))

    def pull_fn(states, idx):
        return coll.pull(states, {"t": idx})["t"]

    idx = jax.device_put(jnp.zeros((batch,), jnp.int32),
                         NamedSharding(mesh, P(DATA_AXIS)))
    out_spec = P() if out_replicated else P(DATA_AXIS)
    compiled = jax.jit(
        pull_fn, out_shardings=NamedSharding(mesh, out_spec)
    ).lower(states, idx).compile()
    params = contract_params(mesh, batch=batch, dim=dim, vocab=vocab,
                             state_nbytes=_state_nbytes(states))
    params.update(_wire_params(plane, "pull"))
    return compiled, params


def lower_pull(mesh, plane: str, *, vocab: int = 1 << 16, dim: int = 16,
               batch: int = 1024, use_hash: bool = False,
               out_replicated: bool = False) -> Tuple[str, Dict[str, int]]:
    """Compiled HLO text of one plane's pull program on ``mesh``."""
    compiled, params = compile_pull(mesh, plane, vocab=vocab, dim=dim,
                                    batch=batch, use_hash=use_hash,
                                    out_replicated=out_replicated)
    return compiled.as_text(), params


def compile_push(mesh, plane: str, *, vocab: int = 1 << 16, dim: int = 16,
                 batch: int = 1024, use_hash: bool = False):
    """Compiled push (apply_gradients) program + contract params."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..parallel.mesh import DATA_AXIS
    coll = _collection(mesh, plane, vocab=vocab, dim=dim,
                       use_hash=use_hash)
    states = coll.init(jax.random.PRNGKey(0))

    def push_fn(states, idx, grads):
        return coll.apply_gradients(states, {"t": idx}, {"t": grads})

    sh = NamedSharding(mesh, P(DATA_AXIS))
    idx = jax.device_put(jnp.zeros((batch,), jnp.int32), sh)
    grads = jax.device_put(jnp.zeros((batch, dim), jnp.float32), sh)
    compiled = jax.jit(push_fn).lower(states, idx, grads).compile()
    params = contract_params(mesh, batch=batch, dim=dim, vocab=vocab,
                             state_nbytes=_state_nbytes(states))
    params.update(_wire_params(plane, "push"))
    return compiled, params


def lower_push(mesh, plane: str, *, vocab: int = 1 << 16, dim: int = 16,
               batch: int = 1024,
               use_hash: bool = False) -> Tuple[str, Dict[str, int]]:
    """Compiled HLO text of one plane's push program."""
    compiled, params = compile_push(mesh, plane, vocab=vocab, dim=dim,
                                    batch=batch, use_hash=use_hash)
    return compiled.as_text(), params


def _grouped_collection(mesh, *, tables: int, vocab: int, dim: int,
                        use_hash: bool):
    from ..embedding import EmbeddingCollection, EmbeddingSpec
    if use_hash:
        specs = tuple(
            EmbeddingSpec(name=f"t{i}", input_dim=-1, output_dim=dim,
                          hash_capacity=vocab, plane="a2a+grouped")
            for i in range(tables))
    else:
        # distinct vocabs: heterogeneous tables that the per-table loop
        # could never fuse, but the planner batches (same dim bucket)
        specs = tuple(
            EmbeddingSpec(name=f"t{i}", input_dim=vocab + 64 * i,
                          output_dim=dim, plane="a2a+grouped")
            for i in range(tables))
    return EmbeddingCollection(specs, mesh)


def count_exchange_a2a(mesh, program: str, *, vocab: int = 1 << 16,
                       dim: int = 16, batch: int = 1024,
                       use_hash: bool = False) -> int:
    """All-to-all ops ONE single-table a2a exchange compiles to on this
    mesh — the empirical per-exchange unit the grouped plane's launch-count
    contract multiplies by ``num_groups``."""
    from . import contracts
    lower = lower_pull if program == "pull" else lower_push
    txt, _ = lower(mesh, "a2a", vocab=vocab, dim=dim, batch=batch,
                   use_hash=use_hash)
    return contracts.summarize(txt).get("all-to-all", (0, 0))[0]


def grouped_params(mesh, coll, names, *, batch: int, dim: int,
                   program: str, a2a_ops: Optional[int] = None,
                   itemsize: int = 4,
                   state_nbytes: Optional[int] = None,
                   vocab: Optional[int] = None) -> Dict[str, int]:
    """Contract params for a grouped-plane program: the base params plus
    num_tables / num_groups (from the planner itself) / the padded bucket
    dim / the per-exchange all-to-all count.

    The per-exchange unit is counted from a SINGLE-TABLE a2a program at
    the LARGEST group's concatenated stream size (``max group members *
    batch`` — XLA's all-to-all decomposition depends on the exchanged
    buffer size, so a unit counted at the per-table batch undercounts
    once the concat stream crosses a split threshold: at batch 256 the
    grouped pull compiles 8 all-to-alls where the 256-entry
    single-table unit is 4). The widest group, not ``num_tables``: on a
    multi-group plan the whole-collection stream size would inflate the
    unit past what any one group exchanges, slackening the
    ``num_groups * unit`` cap. Counting at the widest group's stream
    calibrates the cap for ANY audited batch; a per-table-loop
    regression still fails it (num_tables x per-table units always
    exceeds one stream-sized unit set per group).
    """
    from ..parallel import grouped
    plans = grouped.plan_groups(coll, tuple(names), read_only=True)
    if a2a_ops is None:
        widest = max(len(p.members) for p in plans)
        a2a_ops = count_exchange_a2a(mesh, program,
                                     batch=batch * widest, dim=dim)
    params = contract_params(mesh, batch=batch, dim=dim, itemsize=itemsize,
                             vocab=vocab, state_nbytes=state_nbytes)
    params.update({
        "num_tables": len(names), "num_groups": len(plans),
        "dim_bucket": max(p.bucket_dim for p in plans),
        "a2a_ops_per_exchange": a2a_ops})
    return params


def compile_grouped_pull(mesh, *, tables: int = 3, vocab: int = 1 << 14,
                         dim: int = 16, batch: int = 1024,
                         use_hash: bool = False,
                         a2a_ops: Optional[int] = None,
                         out_replicated: bool = False):
    """Compiled COLLECTION-level grouped pull over ``tables`` same-dim
    tables (one exchange group) + params. ``out_replicated=True`` breaks
    the output annotation like :func:`compile_pull` — the negative test."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..parallel.mesh import DATA_AXIS
    coll = _grouped_collection(mesh, tables=tables, vocab=vocab, dim=dim,
                               use_hash=use_hash)
    states = coll.init(jax.random.PRNGKey(0))
    names = tuple(coll.specs)

    def pull_fn(states, idxs):
        return coll.pull(states, idxs)

    sh = NamedSharding(mesh, P(DATA_AXIS))
    idxs = {n: jax.device_put(jnp.zeros((batch,), jnp.int32), sh)
            for n in names}
    out_spec = P() if out_replicated else P(DATA_AXIS)
    compiled = jax.jit(
        pull_fn, out_shardings=NamedSharding(mesh, out_spec)
    ).lower(states, idxs).compile()
    return compiled, grouped_params(
        mesh, coll, names, batch=batch, dim=dim, program="pull",
        a2a_ops=a2a_ops, vocab=vocab,
        state_nbytes=_state_nbytes(states))


def lower_grouped_pull(mesh, *, tables: int = 3, vocab: int = 1 << 14,
                       dim: int = 16, batch: int = 1024,
                       use_hash: bool = False,
                       a2a_ops: Optional[int] = None,
                       out_replicated: bool = False
                       ) -> Tuple[str, Dict[str, int]]:
    """Compiled HLO text of the collection-level grouped pull."""
    compiled, params = compile_grouped_pull(
        mesh, tables=tables, vocab=vocab, dim=dim, batch=batch,
        use_hash=use_hash, a2a_ops=a2a_ops, out_replicated=out_replicated)
    return compiled.as_text(), params


def compile_grouped_push(mesh, *, tables: int = 3, vocab: int = 1 << 14,
                         dim: int = 16, batch: int = 1024,
                         use_hash: bool = False,
                         a2a_ops: Optional[int] = None):
    """Compiled collection-level grouped push + params."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..parallel.mesh import DATA_AXIS
    coll = _grouped_collection(mesh, tables=tables, vocab=vocab, dim=dim,
                               use_hash=use_hash)
    states = coll.init(jax.random.PRNGKey(0))
    names = tuple(coll.specs)

    def push_fn(states, idxs, grads):
        return coll.apply_gradients(states, idxs, grads)

    sh = NamedSharding(mesh, P(DATA_AXIS))
    idxs = {n: jax.device_put(jnp.zeros((batch,), jnp.int32), sh)
            for n in names}
    grads = {n: jax.device_put(jnp.zeros((batch, dim), jnp.float32), sh)
             for n in names}
    compiled = jax.jit(push_fn).lower(states, idxs, grads).compile()
    return compiled, grouped_params(
        mesh, coll, names, batch=batch, dim=dim, program="push",
        a2a_ops=a2a_ops, vocab=vocab,
        state_nbytes=_state_nbytes(states))


def lower_grouped_push(mesh, *, tables: int = 3, vocab: int = 1 << 14,
                       dim: int = 16, batch: int = 1024,
                       use_hash: bool = False,
                       a2a_ops: Optional[int] = None
                       ) -> Tuple[str, Dict[str, int]]:
    """Compiled HLO text of the collection-level grouped push."""
    compiled, params = compile_grouped_push(
        mesh, tables=tables, vocab=vocab, dim=dim, batch=batch,
        use_hash=use_hash, a2a_ops=a2a_ops)
    return compiled.as_text(), params


def compile_train_step(mesh, plane: str = "a2a", *, vocab: int = 4096,
                       dim: int = 8, batch: int = 256,
                       model: str = "deepfm"):
    """Compiled Trainer train-step program + contract params.

    The step contract audits cross-cutting properties: donation of the
    state pytree honored (tables updated in place), no f64, no host
    transfers smuggled into the step.
    """
    import numpy as np
    import jax
    import optax
    from ..embedding import EmbeddingCollection
    from ..models import deepctr
    from ..training import Trainer
    features = ("c0", "c1")
    specs = deepctr.make_feature_specs(features, vocab, dim, plane=plane)
    coll = EmbeddingCollection(
        specs, mesh,
        default_optimizer={"category": "adagrad", "learning_rate": 0.1})
    trainer = Trainer(deepctr.build_model(model, features), coll,
                     optax.adam(1e-2))
    rng = np.random.RandomState(0)
    batch_data = {
        "label": rng.randint(0, 2, size=batch).astype(np.float32),
        "dense": rng.randn(batch, 4).astype(np.float32),
        "sparse": {f: rng.randint(0, vocab, size=batch).astype(np.int32)
                   for f in features}
    }
    for f in features:
        batch_data["sparse"][f + deepctr.LINEAR_SUFFIX] = \
            batch_data["sparse"][f]
    state = trainer.init(jax.random.PRNGKey(0),
                         trainer.shard_batch(batch_data))
    step = trainer._build_train_step()
    compiled = step.lower(state,
                          trainer.shard_batch(batch_data)).compile()
    return compiled, contract_params(mesh, batch=batch, dim=dim,
                                     vocab=vocab,
                                     state_nbytes=_state_nbytes(state))


def lower_train_step(mesh, plane: str = "a2a", *, vocab: int = 4096,
                     dim: int = 8, batch: int = 256,
                     model: str = "deepfm"
                     ) -> Tuple[str, Dict[str, int]]:
    """Compiled HLO text of the Trainer's whole jitted train step."""
    compiled, params = compile_train_step(mesh, plane, vocab=vocab,
                                          dim=dim, batch=batch,
                                          model=model)
    return compiled.as_text(), params


def compile_pipelined_step(mesh, *, vocab: int = 4096, dim: int = 8,
                           batch: int = 256, model: str = "deepfm",
                           force_serialize: bool = False):
    """Compiled PIPELINED Trainer step + contract params.

    Builds the same deepfm harness as :func:`compile_train_step` with
    every variable on ``plane="a2a+pipelined"``, primes the pipeline
    (the warmup prologue), and lowers the steady-state step program —
    dense(N) on the prefetched buffer, push(N), prefetch pull(N+1) —
    exactly as ``Trainer.train_step`` dispatches it. The params carry
    ``pipeline_rows_bytes`` (the primed row buffer's size) so the
    peak-temp bound earns exactly one extra pulled-row buffer.

    ``force_serialize=True`` compiles the deliberately-serialized
    variant (the loss routed into the prefetch indices): the overlap
    contract's negative shape. Test-only.
    """
    import numpy as np
    import jax
    import optax
    from ..embedding import EmbeddingCollection
    from ..models import deepctr
    from ..training import Trainer
    features = ("c0", "c1")
    specs = deepctr.make_feature_specs(features, vocab, dim,
                                       plane="a2a+pipelined")
    coll = EmbeddingCollection(
        specs, mesh,
        default_optimizer={"category": "adagrad", "learning_rate": 0.1})
    trainer = Trainer(deepctr.build_model(model, features), coll,
                      optax.adam(1e-2))
    rng = np.random.RandomState(0)
    batch_data = {
        "label": rng.randint(0, 2, size=batch).astype(np.float32),
        "dense": rng.randn(batch, 4).astype(np.float32),
        "sparse": {f: rng.randint(0, vocab, size=batch).astype(np.int32)
                   for f in features}
    }
    for f in features:
        batch_data["sparse"][f + deepctr.LINEAR_SUFFIX] = \
            batch_data["sparse"][f]
    state = trainer.init(jax.random.PRNGKey(0),
                         trainer.shard_batch(batch_data))
    state = trainer._prime_pipeline(state, batch_data)
    pull_inputs, _ = trainer._split_sparse(batch_data["sparse"])
    next_pull = trainer.shard_batch(pull_inputs)
    step = trainer._build_pipelined_train_step(
        force_serialize=force_serialize)
    compiled = step.lower(state, trainer.shard_batch(batch_data),
                          next_pull).compile()
    # the pipe buffer is accounted ONCE, via pipeline_rows_bytes — the
    # state term must exclude it or the bound earns the buffer twice
    params = contract_params(
        mesh, batch=batch, dim=dim, vocab=vocab,
        state_nbytes=_state_nbytes(state.replace(pipe=None)))
    params["pipeline_rows_bytes"] = _state_nbytes(state.pipe)
    # one pull + one push exchange pipeline per sparse variable live in
    # the step — the peak-temp bound's step-scratch multiplier — and
    # one sanctioned post-push weights-shard materialization per
    # dim-carrying table (the linears ride the 1.1 slack)
    params["num_exchange_pipelines"] = 2 * len(coll.specs)
    params["step_weight_shards"] = len(features)
    return compiled, params


def lower_pipelined_step(mesh, *, vocab: int = 4096, dim: int = 8,
                         batch: int = 256, model: str = "deepfm",
                         force_serialize: bool = False
                         ) -> Tuple[str, Dict[str, int]]:
    """Compiled HLO text of the pipelined Trainer step program."""
    compiled, params = compile_pipelined_step(
        mesh, vocab=vocab, dim=dim, batch=batch, model=model,
        force_serialize=force_serialize)
    return compiled.as_text(), params
