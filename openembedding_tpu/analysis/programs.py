"""Lower the framework's data-plane programs to compiled HLO text.

Shared by ``tests/test_analysis_contracts.py`` and the
``tools/graftcheck.py`` CI gate: build a collection on a mesh, lower the
pull / push / train-step programs exactly as the training path runs them
(batch-sharded inputs, batch-sharded outputs — a replicated output would
force an artifact gather and fail the pull bound for the wrong reason),
and return ``(hlo_text, params)`` ready for
:func:`..analysis.contracts.check_program`.

Imports of the wider package happen inside the functions (this module
is part of ``analysis``, which the rest of the package may import at
module level — see the package docstring).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

CACHE_K = 128


def _collection(mesh, plane: str, *, vocab: int, dim: int,
                use_hash: bool):
    from ..embedding import EmbeddingCollection, EmbeddingSpec
    if use_hash:
        spec = EmbeddingSpec(name="t", input_dim=-1, output_dim=dim,
                             hash_capacity=vocab, plane=plane,
                             cache_k=CACHE_K)
    else:
        spec = EmbeddingSpec(name="t", input_dim=vocab, output_dim=dim,
                             plane=plane, cache_k=CACHE_K)
    return EmbeddingCollection((spec,), mesh)


def contract_params(mesh, *, batch: int, dim: int,
                    itemsize: int = 4) -> Dict[str, int]:
    from ..parallel.mesh import DATA_AXIS
    data = mesh.shape[DATA_AXIS]
    return {"batch_slice": batch // data, "global_batch": batch,
            "dim": dim, "itemsize": itemsize, "cache_k": CACHE_K,
            "num_shards": mesh.size}


def lower_pull(mesh, plane: str, *, vocab: int = 1 << 16, dim: int = 16,
               batch: int = 1024, use_hash: bool = False,
               out_replicated: bool = False) -> Tuple[str, Dict[str, int]]:
    """Compiled HLO of one plane's pull program on ``mesh``.

    ``out_replicated=True`` deliberately breaks the output sharding
    annotation (rows replicated instead of batch-sharded): XLA must then
    gather the global batch onto every device — the regression shape the
    a2a pull contract exists to catch. Test-only.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..parallel.mesh import DATA_AXIS
    coll = _collection(mesh, plane, vocab=vocab, dim=dim,
                       use_hash=use_hash)
    states = coll.init(jax.random.PRNGKey(0))

    def pull_fn(states, idx):
        return coll.pull(states, {"t": idx})["t"]

    idx = jax.device_put(jnp.zeros((batch,), jnp.int32),
                         NamedSharding(mesh, P(DATA_AXIS)))
    out_spec = P() if out_replicated else P(DATA_AXIS)
    compiled = jax.jit(
        pull_fn, out_shardings=NamedSharding(mesh, out_spec)
    ).lower(states, idx).compile()
    return compiled.as_text(), contract_params(mesh, batch=batch, dim=dim)


def lower_push(mesh, plane: str, *, vocab: int = 1 << 16, dim: int = 16,
               batch: int = 1024,
               use_hash: bool = False) -> Tuple[str, Dict[str, int]]:
    """Compiled HLO of one plane's push (apply_gradients) program."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..parallel.mesh import DATA_AXIS
    coll = _collection(mesh, plane, vocab=vocab, dim=dim,
                       use_hash=use_hash)
    states = coll.init(jax.random.PRNGKey(0))

    def push_fn(states, idx, grads):
        return coll.apply_gradients(states, {"t": idx}, {"t": grads})

    sh = NamedSharding(mesh, P(DATA_AXIS))
    idx = jax.device_put(jnp.zeros((batch,), jnp.int32), sh)
    grads = jax.device_put(jnp.zeros((batch, dim), jnp.float32), sh)
    compiled = jax.jit(push_fn).lower(states, idx, grads).compile()
    return compiled.as_text(), contract_params(mesh, batch=batch, dim=dim)


def _grouped_collection(mesh, *, tables: int, vocab: int, dim: int,
                        use_hash: bool):
    from ..embedding import EmbeddingCollection, EmbeddingSpec
    if use_hash:
        specs = tuple(
            EmbeddingSpec(name=f"t{i}", input_dim=-1, output_dim=dim,
                          hash_capacity=vocab, plane="a2a+grouped")
            for i in range(tables))
    else:
        # distinct vocabs: heterogeneous tables that the per-table loop
        # could never fuse, but the planner batches (same dim bucket)
        specs = tuple(
            EmbeddingSpec(name=f"t{i}", input_dim=vocab + 64 * i,
                          output_dim=dim, plane="a2a+grouped")
            for i in range(tables))
    return EmbeddingCollection(specs, mesh)


def count_exchange_a2a(mesh, program: str, *, vocab: int = 1 << 16,
                       dim: int = 16, batch: int = 1024,
                       use_hash: bool = False) -> int:
    """All-to-all ops ONE single-table a2a exchange compiles to on this
    mesh — the empirical per-exchange unit the grouped plane's launch-count
    contract multiplies by ``num_groups``."""
    from . import contracts
    lower = lower_pull if program == "pull" else lower_push
    txt, _ = lower(mesh, "a2a", vocab=vocab, dim=dim, batch=batch,
                   use_hash=use_hash)
    return contracts.summarize(txt).get("all-to-all", (0, 0))[0]


def grouped_params(mesh, coll, names, *, batch: int, dim: int,
                   program: str, a2a_ops: Optional[int] = None,
                   itemsize: int = 4) -> Dict[str, int]:
    """Contract params for a grouped-plane program: the base params plus
    num_tables / num_groups (from the planner itself) / the padded bucket
    dim / the per-exchange all-to-all count."""
    from ..parallel import grouped
    plans = grouped.plan_groups(coll, tuple(names), read_only=True)
    if a2a_ops is None:
        a2a_ops = count_exchange_a2a(mesh, program, batch=batch, dim=dim)
    params = contract_params(mesh, batch=batch, dim=dim, itemsize=itemsize)
    params.update({
        "num_tables": len(names), "num_groups": len(plans),
        "dim_bucket": max(p.bucket_dim for p in plans),
        "a2a_ops_per_exchange": a2a_ops})
    return params


def lower_grouped_pull(mesh, *, tables: int = 3, vocab: int = 1 << 14,
                       dim: int = 16, batch: int = 1024,
                       use_hash: bool = False,
                       a2a_ops: Optional[int] = None,
                       out_replicated: bool = False
                       ) -> Tuple[str, Dict[str, int]]:
    """Compiled HLO of the COLLECTION-level grouped pull over ``tables``
    same-dim tables (one exchange group). ``out_replicated=True`` breaks
    the output annotation like :func:`lower_pull` — the negative test."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..parallel.mesh import DATA_AXIS
    coll = _grouped_collection(mesh, tables=tables, vocab=vocab, dim=dim,
                               use_hash=use_hash)
    states = coll.init(jax.random.PRNGKey(0))
    names = tuple(coll.specs)

    def pull_fn(states, idxs):
        return coll.pull(states, idxs)

    sh = NamedSharding(mesh, P(DATA_AXIS))
    idxs = {n: jax.device_put(jnp.zeros((batch,), jnp.int32), sh)
            for n in names}
    out_spec = P() if out_replicated else P(DATA_AXIS)
    compiled = jax.jit(
        pull_fn, out_shardings=NamedSharding(mesh, out_spec)
    ).lower(states, idxs).compile()
    return compiled.as_text(), grouped_params(
        mesh, coll, names, batch=batch, dim=dim, program="pull",
        a2a_ops=a2a_ops)


def lower_grouped_push(mesh, *, tables: int = 3, vocab: int = 1 << 14,
                       dim: int = 16, batch: int = 1024,
                       use_hash: bool = False,
                       a2a_ops: Optional[int] = None
                       ) -> Tuple[str, Dict[str, int]]:
    """Compiled HLO of the collection-level grouped push."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..parallel.mesh import DATA_AXIS
    coll = _grouped_collection(mesh, tables=tables, vocab=vocab, dim=dim,
                               use_hash=use_hash)
    states = coll.init(jax.random.PRNGKey(0))
    names = tuple(coll.specs)

    def push_fn(states, idxs, grads):
        return coll.apply_gradients(states, idxs, grads)

    sh = NamedSharding(mesh, P(DATA_AXIS))
    idxs = {n: jax.device_put(jnp.zeros((batch,), jnp.int32), sh)
            for n in names}
    grads = {n: jax.device_put(jnp.zeros((batch, dim), jnp.float32), sh)
             for n in names}
    compiled = jax.jit(push_fn).lower(states, idxs, grads).compile()
    return compiled.as_text(), grouped_params(
        mesh, coll, names, batch=batch, dim=dim, program="push",
        a2a_ops=a2a_ops)


def lower_train_step(mesh, plane: str = "a2a", *, vocab: int = 4096,
                     dim: int = 8, batch: int = 256,
                     model: str = "deepfm"
                     ) -> Tuple[str, Dict[str, int]]:
    """Compiled HLO of the Trainer's whole jitted train step.

    The step contract audits cross-cutting properties: donation of the
    state pytree honored (tables updated in place), no f64, no host
    transfers smuggled into the step.
    """
    import numpy as np
    import jax
    import optax
    from ..embedding import EmbeddingCollection
    from ..models import deepctr
    from ..training import Trainer
    features = ("c0", "c1")
    specs = deepctr.make_feature_specs(features, vocab, dim, plane=plane)
    coll = EmbeddingCollection(
        specs, mesh,
        default_optimizer={"category": "adagrad", "learning_rate": 0.1})
    trainer = Trainer(deepctr.build_model(model, features), coll,
                     optax.adam(1e-2))
    rng = np.random.RandomState(0)
    batch_data = {
        "label": rng.randint(0, 2, size=batch).astype(np.float32),
        "dense": rng.randn(batch, 4).astype(np.float32),
        "sparse": {f: rng.randint(0, vocab, size=batch).astype(np.int32)
                   for f in features}
    }
    for f in features:
        batch_data["sparse"][f + deepctr.LINEAR_SUFFIX] = \
            batch_data["sparse"][f]
    state = trainer.init(jax.random.PRNGKey(0),
                         trainer.shard_batch(batch_data))
    step = trainer._build_train_step()
    compiled = step.lower(state,
                          trainer.shard_batch(batch_data)).compile()
    return compiled.as_text(), contract_params(mesh, batch=batch, dim=dim)
