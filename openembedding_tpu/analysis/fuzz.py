"""graftfuzz — differential fuzzing over the untrusted-bytes surface.

Fifth static-gate leg (after graftlint/graftrace/graftcheck/graftproto):
the four existing legs reason about the package's OWN code, none of them
sees the parsers that consume bytes the package did not write — the
native checkpoint reader (``native/oe_serving.cc``: npz central
directory, delta-chain replay, crc32, zip64/deflate refusal), the Python
delta readers (``checkpoint_delta.py`` ``load_checkpoint`` replay /
``read_deltas_since`` / ``decode_delta``) and the ingest framers
(TFRecord length+crc framing, Criteo TSV rows). PR 12 found real
memory-safety bugs here by hand (crafted ``name_len`` SIGSEGV, uint32
local-header-offset overflow); this module makes that search mechanical,
deterministic and gated.

Three lanes, one seeded PRNG (every run replayable from ``--seed``):

* **ckpt** — structure-aware mutations of a real delta-chain checkpoint
  directory: bit flips (crc-caught and crc-PRESERVING — the latter
  proves the checksum is actually checked, not just present), tail and
  mid-chain truncations, npz central-directory/local-header field
  mutations (name_len, offset overflow, zip64 markers, stored->deflate
  method swaps, EOCD damage, .npy descr swaps), manifest field
  mutations (crc swap, seq gap/dupe/overflow, base_id swap, chunk-crc
  corruption, payload swaps with and without matching crcs, structural
  JSON garbage, 2000-deep nesting), and model_meta field fuzz.
* **wire** — ``encode_delta`` frames (the REST ``POST /models/<sign>/
  delta`` body): truncation, bit flips, header-JSON structure fuzz
  (huge/negative shapes, bad descrs, bogus codecs), magic garbage.
* **ingest** — synthetic Criteo shards (``write_synthetic_shards``)
  with TFRecord length/crc32c corruption, mid-record truncation and
  raw-bytes TSV splices, consumed through :class:`ShardStream`.

**Oracle — differential trichotomy.** For every mutated checkpoint
directory each reader (Python full loader, Python delta reader, native
reader under BOTH ASan and UBSan builds, each native probe in its own
subprocess so a sanitizer abort kills the probe, never the harness)
must either (a) load and bit-agree with every other loaded reader on
``(version, row-digest)``, (b) refuse with a clean TYPED error
(``DeltaDecodeError``/``ValueError``/``KeyError``/``RuntimeError``/
``OSError`` for Python; ``oe_model_load -> NULL`` + ``oe_last_error``
for native), or (c) recover to the same documented version (the
torn-final contract — recovery IS a load, at a lower version, so (c)
reduces to (a)). Never a SIGSEGV, never UB, never a hang past the
deadline, never an untyped Python exception escaping a byte parser,
never a silent Python-vs-native divergence. The wire lane additionally
decodes every frame twice and demands bit-identical results; the ingest
lane demands skip-and-count (``ingest_bad_rows``) or a loud typed
failure within the deadline — a dead reader must never hang the ring.

Coverage is accounted per mutation class and the CLI
(``python -m tools.graftfuzz``) exits nonzero on any violation OR any
declared class that never fired — the same no-hollow-exploration
discipline graftproto v2 pins with state-count floors. Reports carry no
wall-clock: two runs with the same seed are byte-identical.

This file doubles as the native-probe SUBPROCESS (``python fuzz.py
--native-probe`` with a JSON spec on stdin): module-level imports stay
stdlib-only so the probe starts in milliseconds without jax/numpy.
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import shutil
import struct
import subprocess
import sys
import threading
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

DEADLINE_S = 30.0
MANIFEST = "delta_manifest"

# Typed-refusal set for Python probes: DeltaDecodeError subclasses
# ValueError; RecursionError subclasses RuntimeError; FileNotFoundError
# subclasses OSError. struct.error / zlib.error / BadZipFile /
# AttributeError / TypeError escaping a parser are scored as crashes.
PY_REFUSALS = (ValueError, KeyError, RuntimeError, OSError)


# --- report hygiene ----------------------------------------------------------

def _scrub(text: str, roots: List[str]) -> str:
    """Strip run-local tmp paths so reports are byte-stable across runs."""
    for r in roots:
        if r:
            text = text.replace(r, "<tmp>")
    return text


# --- zip byte surgery (stdlib struct; mirrors what oe_serving parses) --------

def _u16(buf: bytes, off: int) -> int:
    return struct.unpack_from("<H", buf, off)[0]


def _u32(buf: bytes, off: int) -> int:
    return struct.unpack_from("<I", buf, off)[0]


def _p16(buf: bytearray, off: int, v: int) -> None:
    struct.pack_into("<H", buf, off, v & 0xFFFF)


def _p32(buf: bytearray, off: int, v: int) -> None:
    struct.pack_into("<I", buf, off, v & 0xFFFFFFFF)


def _eocd_offset(buf: bytes) -> int:
    lo = max(0, len(buf) - 65557)
    off = bytes(buf).rfind(b"PK\x05\x06", lo)
    if off < 0:
        raise ValueError("no EOCD in npz")
    return off


def _central_entries(buf: bytes) -> Tuple[List[Dict[str, int]], int]:
    """Central-directory entries of an npz (field OFFSETS for patching)."""
    eocd = _eocd_offset(buf)
    n = _u16(buf, eocd + 10)
    off = _u32(buf, eocd + 16)
    out: List[Dict[str, int]] = []
    for _ in range(n):
        if buf[off:off + 4] != b"PK\x01\x02":
            break
        nlen = _u16(buf, off + 28)
        xlen = _u16(buf, off + 30)
        clen = _u16(buf, off + 32)
        out.append({
            "off": off,
            "method_off": off + 10,
            "crc_off": off + 16,
            "csize_off": off + 20,
            "usize_off": off + 24,
            "nlen_off": off + 28,
            "lho_off": off + 42,
            "name": bytes(buf[off + 46:off + 46 + nlen]).decode(
                "latin-1"),
            "lho": _u32(buf, off + 42),
        })
        off += 46 + nlen + xlen + clen
    if not out:
        raise ValueError("no central entries in npz")
    return out, eocd


# --- manifest surgery --------------------------------------------------------

def _load_m(d: str) -> Dict[str, Any]:
    with open(os.path.join(d, MANIFEST)) as f:
        return json.load(f)


def _store_m(d: str, m: Any) -> None:
    with open(os.path.join(d, MANIFEST), "w") as f:
        json.dump(m, f)


def _chain_recs(m: Dict[str, Any]) -> List[Tuple[int, str, Dict[str, Any]]]:
    out = []
    for ei, entry in enumerate(m.get("chain", [])):
        for name in sorted(entry["vars"]):
            out.append((ei, name, entry["vars"][name]))
    return out


def _refresh_crc(d: str, m: Dict[str, Any], fname: str) -> None:
    """Recompute a chain file's whole-file crc32 in the manifest — used
    by STRUCTURAL mutators so their damage reaches the parser instead of
    being masked by the (already-tested) file checksum."""
    with open(os.path.join(d, fname), "rb") as f:
        crc = zlib.crc32(f.read())
    for _, _, rec in _chain_recs(m):
        if rec.get("file") == fname:
            rec["crc32"] = int(crc)


def _pick_rec(rng: random.Random, d: str, m: Dict[str, Any],
              entry: Optional[int] = None,
              kind: Optional[str] = None) -> Tuple[int, str, Dict[str, Any]]:
    recs = [(ei, name, rec) for ei, name, rec in _chain_recs(m)
            if (entry is None or ei == entry)
            and (kind is None or rec.get("kind") == kind)]
    if not recs:
        raise ValueError(f"no chain records (entry={entry}, kind={kind})")
    return recs[rng.randrange(len(recs))]


def _mutate_file_bytes(d: str, fname: str,
                       fn: Callable[[bytearray], str]) -> str:
    p = os.path.join(d, fname)
    with open(p, "rb") as f:
        buf = bytearray(f.read())
    note = fn(buf)
    with open(p, "wb") as f:
        f.write(buf)
    return note


# --- ckpt-lane mutation classes ----------------------------------------------
# Every mutator: fn(rng, dirpath) -> note string (no absolute paths).

def _m_npz_bitflip(rng: random.Random, d: str) -> str:
    """Random bit flips in a chain file; the manifest crc is NOT fixed,
    so the whole-file checksum must catch it (tear semantics)."""
    m = _load_m(d)
    _, _, rec = _pick_rec(rng, d, m)

    def flip(buf: bytearray) -> str:
        n = rng.randint(1, 8)
        for _ in range(n):
            i = rng.randrange(len(buf))
            buf[i] ^= 1 << rng.randrange(8)
        return f"{rec['file']}: {n} bit flips, crc stale"
    return _mutate_file_bytes(d, rec["file"], flip)


def _m_npz_bitflip_crc_fixed(rng: random.Random, d: str) -> str:
    """Bit flips WITH the manifest whole-file crc re-stamped: reaches
    the npz parser / chunk-crc layer — proves the inner defenses hold
    when the outer checksum has been laundered."""
    m = _load_m(d)
    _, _, rec = _pick_rec(rng, d, m)

    def flip(buf: bytearray) -> str:
        n = rng.randint(1, 8)
        for _ in range(n):
            i = rng.randrange(len(buf))
            buf[i] ^= 1 << rng.randrange(8)
        return f"{rec['file']}: {n} bit flips, crc re-stamped"
    note = _mutate_file_bytes(d, rec["file"], flip)
    _refresh_crc(d, m, rec["file"])
    _store_m(d, m)
    return note


def _m_trunc_torn_final(rng: random.Random, d: str) -> str:
    """Truncate a FINAL-entry file (a killed writer): recover to the
    previous complete delta — the documented torn-final contract."""
    m = _load_m(d)
    last = len(m["chain"]) - 1
    _, _, rec = _pick_rec(rng, d, m, entry=last)
    p = os.path.join(d, rec["file"])
    size = os.path.getsize(p)
    keep = rng.randrange(size)
    with open(p, "r+b") as f:
        f.truncate(keep)
    return f"{rec['file']}: truncated {size} -> {keep} bytes (final entry)"


def _m_trunc_midchain(rng: random.Random, d: str) -> str:
    """Truncate a NON-final entry's file: later deltas build on it, so
    every loader must fail loudly (never silently skip a middle link)."""
    m = _load_m(d)
    if len(m["chain"]) < 2:
        raise ValueError("mid-chain truncation needs a chain of >= 2")
    ei = rng.randrange(len(m["chain"]) - 1)
    _, _, rec = _pick_rec(rng, d, m, entry=ei)
    p = os.path.join(d, rec["file"])
    size = os.path.getsize(p)
    keep = rng.randrange(size)
    with open(p, "r+b") as f:
        f.truncate(keep)
    return f"{rec['file']}: truncated {size} -> {keep} bytes (entry {ei})"


def _zip_class(rng: random.Random, d: str,
               patch: Callable[[random.Random, bytearray], str]) -> str:
    """Shared shape of the npz structural classes: damage the zip
    structure of one chain file, then RE-STAMP its manifest crc so the
    mutation reaches the central-directory parser."""
    m = _load_m(d)
    _, _, rec = _pick_rec(rng, d, m)
    note = _mutate_file_bytes(d, rec["file"],
                              lambda buf: patch(rng, buf))
    _refresh_crc(d, m, rec["file"])
    _store_m(d, m)
    return f"{rec['file']}: {note}"


def _m_zip_name_len(rng: random.Random, d: str) -> str:
    """Oversized central-directory name_len (the PR-12 SIGSEGV shape)."""
    def patch(rng: random.Random, buf: bytearray) -> str:
        ents, _ = _central_entries(buf)
        e = ents[rng.randrange(len(ents))]
        v = rng.choice([0xEEEE, 0xFFFF, len(buf) & 0xFFFF | 0x8000])
        _p16(buf, e["nlen_off"], v)
        return f"name_len {v:#x} on member {e['name']!r}"
    return _zip_class(rng, d, patch)


def _m_zip_offset_overflow(rng: random.Random, d: str) -> str:
    """Local-header offset pointing far past the file (PR-12's uint32
    overflow shape)."""
    def patch(rng: random.Random, buf: bytearray) -> str:
        ents, _ = _central_entries(buf)
        e = ents[rng.randrange(len(ents))]
        v = rng.choice([0xFFFFFF00, 0x7FFFFFFF, len(buf) + 1])
        _p32(buf, e["lho_off"], v)
        return f"local-header offset {v:#x} on member {e['name']!r}"
    return _zip_class(rng, d, patch)


def _m_zip_zip64_marker(rng: random.Random, d: str) -> str:
    """0xFFFFFFFF zip64 markers in csize/usize/offset — the native
    reader documents zip64 as refused, not misread."""
    def patch(rng: random.Random, buf: bytearray) -> str:
        ents, _ = _central_entries(buf)
        e = ents[rng.randrange(len(ents))]
        field = rng.choice(["csize_off", "usize_off", "lho_off"])
        _p32(buf, e[field], 0xFFFFFFFF)
        return f"zip64 marker in {field[:-4]} of member {e['name']!r}"
    return _zip_class(rng, d, patch)


def _m_zip_method_deflate(rng: random.Random, d: str) -> str:
    """Stored->deflate method swap (central + local header): the
    dependency-free native reader must refuse, and the Python side must
    surface zipfile's confusion typed."""
    def patch(rng: random.Random, buf: bytearray) -> str:
        ents, _ = _central_entries(buf)
        e = ents[rng.randrange(len(ents))]
        _p16(buf, e["method_off"], 8)
        lho = e["lho"]
        if buf[lho:lho + 4] == b"PK\x03\x04":
            _p16(buf, lho + 8, 8)
        return f"method=deflate on member {e['name']!r}"
    return _zip_class(rng, d, patch)


def _m_zip_eocd_fuzz(rng: random.Random, d: str) -> str:
    """EOCD entry-count / central-directory-offset damage."""
    def patch(rng: random.Random, buf: bytearray) -> str:
        eocd = _eocd_offset(buf)
        which = rng.choice(["count", "cd_off", "cd_size"])
        if which == "count":
            _p16(buf, eocd + 10, rng.choice([0xFFFF, 0,
                                             _u16(buf, eocd + 10) + 7]))
        elif which == "cd_off":
            _p32(buf, eocd + 16, rng.choice([0xFFFFFF00, len(buf) + 9,
                                             rng.randrange(len(buf))]))
        else:
            _p32(buf, eocd + 12, rng.randrange(1 << 32))
        return f"EOCD {which} fuzzed"
    return _zip_class(rng, d, patch)


def _m_npy_descr_fuzz(rng: random.Random, d: str) -> str:
    """Same-length .npy header descr swaps inside npz members (key
    dtype narrowing, float widening): the readers must either refuse
    the dtype or both decode the same bytes the same way."""
    swaps = [(b"'<i8'", b"'<i2'"), (b"'<i8'", b"'<u8'"),
             (b"'<f4'", b"'<f8'"), (b"'<f4'", b"'<i4'"),
             (b"'<i4'", b"'<i2'")]
    m = _load_m(d)
    recs = list(_chain_recs(m))
    rng.shuffle(recs)
    for _, _, rec in recs:
        p = os.path.join(d, rec["file"])
        with open(p, "rb") as f:
            buf = bytearray(f.read())
        hits = [(old, new) for old, new in swaps if bytes(buf).find(old) >= 0]
        if not hits:
            continue
        old, new = hits[rng.randrange(len(hits))]
        i = bytes(buf).find(old)
        buf[i:i + len(old)] = new
        with open(p, "wb") as f:
            f.write(buf)
        _refresh_crc(d, m, rec["file"])
        _store_m(d, m)
        return (f"{rec['file']}: descr {old.decode()} -> {new.decode()}"
                f" at {i}")
    raise ValueError("no descr swap target found")


def _m_manifest_crc_swap(rng: random.Random, d: str) -> str:
    """Swap the crc32 fields of two manifest records: both files now
    fail their checksum (tear semantics, position-dependent)."""
    m = _load_m(d)
    recs = _chain_recs(m)
    if len(recs) < 2:
        raise ValueError("crc swap needs >= 2 records")
    (ai, an, a), (bi, bn, b) = rng.sample(recs, 2)
    a["crc32"], b["crc32"] = b["crc32"], a["crc32"]
    _store_m(d, m)
    return f"crc32 swap: entry{ai}/{an} <-> entry{bi}/{bn}"


def _m_manifest_seq_fuzz(rng: random.Random, d: str) -> str:
    """seq renumbering: gaps, dupes, and int64-overflow values. Gaps
    and dupes replay (entry ORDER is the contract); overflow seqs must
    be refused identically by Python bignums and native int64."""
    m = _load_m(d)
    chain = m["chain"]
    which = rng.choice(["gap", "dupe", "overflow", "negative"])
    if which == "gap":
        chain[-1]["seq"] += rng.randint(3, 9)
        m["last_seq"] = chain[-1]["seq"]
    elif which == "dupe" and len(chain) >= 2:
        chain[-1]["seq"] = chain[0]["seq"]
        m["last_seq"] = chain[-1]["seq"]
    elif which == "negative":
        chain[rng.randrange(len(chain))]["seq"] = -rng.randint(1, 99)
    else:
        which = "overflow"
        chain[rng.randrange(len(chain))]["seq"] = rng.choice(
            [10 ** 300, 2 ** 63, 1e300])
        m["last_seq"] = 10 ** 9
    _store_m(d, m)
    return f"seq {which}"


def _m_manifest_base_id_swap(rng: random.Random, d: str) -> str:
    """base_id / content_seq identity fuzz: loads must stay consistent
    (the id is lineage metadata, not row data)."""
    m = _load_m(d)
    if rng.random() < 0.5:
        m["base_id"] = "%032x" % rng.getrandbits(128)
        note = "base_id swapped"
    else:
        m["content_seq"] = int(m.get("content_seq", 0)) + rng.randint(0, 3)
        note = f"content_seq -> {m['content_seq']}"
    _store_m(d, m)
    return note


def _m_manifest_chunk_crc_corrupt(rng: random.Random, d: str) -> str:
    """Perturb one per-chunk checksum: whole-file crc still passes, the
    chunk layer must catch it in BOTH readers (tear semantics)."""
    m = _load_m(d)
    recs = [(ei, n, r) for ei, n, r in _chain_recs(m)
            if isinstance(r.get("chunk_crc"), list) and r["chunk_crc"]]
    if not recs:
        raise ValueError("no chunk_crc records")
    ei, name, rec = recs[rng.randrange(len(recs))]
    k = rng.randrange(len(rec["chunk_crc"]))
    rec["chunk_crc"][k] = int(rec["chunk_crc"][k]) ^ (1 + rng.randrange(255))
    _store_m(d, m)
    return f"entry{ei}/{name}: chunk_crc[{k}] perturbed"


def _m_payload_swap(rng: random.Random, d: str) -> str:
    """Swap the BYTES of two chain files, manifest untouched: both
    whole-file crcs must mis-match (tear semantics)."""
    m = _load_m(d)
    ei = rng.randrange(len(m["chain"]))
    names = sorted(m["chain"][ei]["vars"])
    if len(names) < 2:
        raise ValueError("payload swap needs >= 2 vars in an entry")
    fa = m["chain"][ei]["vars"][names[0]]["file"]
    fb = m["chain"][ei]["vars"][names[1]]["file"]
    pa, pb = os.path.join(d, fa), os.path.join(d, fb)
    with open(pa, "rb") as f:
        ba = f.read()
    with open(pb, "rb") as f:
        bb = f.read()
    with open(pa, "wb") as f:
        f.write(bb)
    with open(pb, "wb") as f:
        f.write(ba)
    return f"entry{ei}: swapped bytes of {fa} <-> {fb}"


def _m_payload_swap_crc_preserved(rng: random.Random, d: str) -> str:
    """Swap two chain files' bytes AND re-stamp both whole-file crcs:
    the outer checksum now PASSES on wrong payloads — only the chunk
    crcs / payload-kind checks stand between this and silently serving
    another variable's rows."""
    note = _m_payload_swap(rng, d)
    m = _load_m(d)
    for _, _, rec in _chain_recs(m):
        _refresh_crc(d, m, rec["file"])
    _store_m(d, m)
    return note + ", crcs re-stamped"


def _m_manifest_json_garbage(rng: random.Random, d: str) -> str:
    """Structural manifest damage: truncation, deep nesting, wrong
    types in load-bearing fields — every reader must refuse typed
    (structural corruption is never tear-recovered)."""
    p = os.path.join(d, MANIFEST)
    with open(p, "rb") as f:
        raw = f.read()
    variant = rng.choice(["truncate", "deep", "format", "chain_scalar",
                          "entry_scalar", "vars_scalar", "crc_str",
                          "file_nonstr", "not_json", "rec_scalar"])
    if variant == "truncate":
        with open(p, "wb") as f:
            f.write(raw[:rng.randrange(max(1, len(raw) - 1))])
    elif variant == "deep":
        n = 2000
        with open(p, "w") as f:
            f.write('{"format": 1, "chain": ' + "[" * n + "]" * n + "}")
    elif variant == "not_json":
        with open(p, "wb") as f:
            f.write(b"\x00\xffgarbage" * rng.randint(1, 99))
    else:
        m = json.loads(raw)
        if variant == "format":
            m["format"] = rng.choice([2, "one", None])
        elif variant == "chain_scalar":
            m["chain"] = rng.choice([7, "x", {"a": 1}])
        elif variant == "entry_scalar":
            m["chain"][rng.randrange(len(m["chain"]))] = rng.choice(
                [5, "entry", None, []])
        elif variant == "vars_scalar":
            m["chain"][rng.randrange(len(m["chain"]))]["vars"] = \
                rng.choice([3, "vars", [1, 2]])
        elif variant == "crc_str":
            _, _, rec = _pick_rec(rng, d, m)
            rec["crc32"] = rng.choice(["abc", None, [1]])
        elif variant == "rec_scalar":
            ei = rng.randrange(len(m["chain"]))
            vars_ = m["chain"][ei]["vars"]
            name = sorted(vars_)[rng.randrange(len(vars_))]
            vars_[name] = rng.choice([9, "rec", [1, 2, 3]])
        else:                                   # file_nonstr
            _, _, rec = _pick_rec(rng, d, m)
            rec["file"] = rng.choice([7, None, ["delta.npz"]])
        _store_m(d, m)
    return f"manifest {variant}"


def _m_meta_field_fuzz(rng: random.Random, d: str) -> str:
    """model_meta field fuzz (native-only probe: the Python loaders
    read variable geometry from their own specs, the native reader is
    the meta consumer): huge/NaN numbers must never hit float->int UB."""
    p = os.path.join(d, "model_meta")
    with open(p) as f:
        meta = json.load(f)
    variant = rng.choice(["vid_huge", "dim_bad", "vocab_bad",
                          "vars_scalar", "deep", "truncate"])
    if variant == "deep":
        n = 2000
        with open(p, "w") as f:
            f.write("[" * n + "]" * n)
        return "model_meta deep nesting"
    if variant == "truncate":
        raw = json.dumps(meta)
        with open(p, "w") as f:
            f.write(raw[:rng.randrange(max(1, len(raw) - 1))])
        return "model_meta truncated"
    if variant == "vars_scalar":
        meta["variables"] = rng.choice([5, "vars", None])
    else:
        variables = meta.get("variables") or []
        if not variables:
            raise ValueError("model_meta has no variables")
        v = variables[rng.randrange(len(variables))]
        if variant == "vid_huge":
            v["variable_id"] = rng.choice([1e300, -1e300, 2 ** 40])
        elif variant == "dim_bad":
            v["embedding_dim"] = rng.choice([-5, 1e300, 0])
        else:
            v["vocabulary_size"] = rng.choice([-1e300, 1e300, -7])
    with open(p, "w") as f:
        json.dump(meta, f)
    return f"model_meta {variant}"


CKPT_CLASSES: Dict[str, Callable[[random.Random, str], str]] = {
    "npz_bitflip": _m_npz_bitflip,
    "npz_bitflip_crc_fixed": _m_npz_bitflip_crc_fixed,
    "trunc_torn_final": _m_trunc_torn_final,
    "trunc_midchain": _m_trunc_midchain,
    "zip_name_len": _m_zip_name_len,
    "zip_offset_overflow": _m_zip_offset_overflow,
    "zip_zip64_marker": _m_zip_zip64_marker,
    "zip_method_deflate": _m_zip_method_deflate,
    "zip_eocd_fuzz": _m_zip_eocd_fuzz,
    "npy_descr_fuzz": _m_npy_descr_fuzz,
    "manifest_crc_swap": _m_manifest_crc_swap,
    "manifest_seq_fuzz": _m_manifest_seq_fuzz,
    "manifest_base_id_swap": _m_manifest_base_id_swap,
    "manifest_chunk_crc_corrupt": _m_manifest_chunk_crc_corrupt,
    "manifest_json_garbage": _m_manifest_json_garbage,
    "payload_swap": _m_payload_swap,
    "payload_swap_crc_preserved": _m_payload_swap_crc_preserved,
    "meta_field_fuzz": _m_meta_field_fuzz,
}

# model_meta is read by the NATIVE reader only (the Python loaders get
# variable geometry from the collection's own specs) — probing the
# Python side there would score its absent meta parser, not a parser.
NATIVE_ONLY_CLASSES = frozenset({"meta_field_fuzz"})


# --- wire-lane mutation classes ----------------------------------------------
# fn(rng, frame) -> (mutated_frame, note)

def _w_truncate(rng: random.Random, frame: bytes) -> Tuple[bytes, str]:
    keep = rng.randrange(len(frame))
    return frame[:keep], f"truncated {len(frame)} -> {keep} bytes"


def _w_bitflip(rng: random.Random, frame: bytes) -> Tuple[bytes, str]:
    buf = bytearray(frame)
    n = rng.randint(1, 16)
    for _ in range(n):
        i = rng.randrange(len(buf))
        buf[i] ^= 1 << rng.randrange(8)
    return bytes(buf), f"{n} bit flips"


def _w_bad_magic(rng: random.Random, frame: bytes) -> Tuple[bytes, str]:
    variant = rng.choice(["png", "no_newline", "empty", "binary_head"])
    if variant == "png":
        return b"\x89PNG\r\n" + frame, "PNG magic prepended"
    if variant == "no_newline":
        return frame.split(b"\n", 1)[0], "header line only, no newline"
    if variant == "empty":
        return b"", "empty frame"
    return bytes(rng.randrange(256) for _ in range(64)) + frame, \
        "64 random bytes prepended"


def _w_header_fuzz(rng: random.Random, frame: bytes) -> Tuple[bytes, str]:
    nl = frame.index(b"\n")
    head = json.loads(frame[:nl])
    body = frame[nl + 1:]
    variant = rng.choice(["vars_list", "shape_huge", "shape_negative",
                          "descr_garbage", "codec_bogus", "seq_str",
                          "spec_arity", "vars_missing", "shape_str"])
    if variant == "vars_list":
        head["vars"] = [1, 2, 3]
    elif variant == "vars_missing":
        del head["vars"]
    elif variant == "seq_str":
        head["seq"] = rng.choice(["x", None, [1]])
    elif variant == "codec_bogus":
        head["compress"] = rng.choice(["zstd", "nope", "zlib"])
    else:
        name = sorted(head["vars"])[rng.randrange(len(head["vars"]))]
        specs = head["vars"][name]
        spec = specs[rng.randrange(len(specs))]
        if variant == "shape_huge":
            spec[2] = [2 ** 40, 2 ** 40]
        elif variant == "shape_negative":
            spec[2] = [-8, 4]
        elif variant == "shape_str":
            spec[2] = "abc"
        elif variant == "descr_garbage":
            spec[1] = rng.choice(["not-a-dtype", 7, "<f99"])
        else:                                  # spec_arity
            del spec[rng.randrange(len(spec))]
    return json.dumps(head).encode() + b"\n" + body, f"header {variant}"


WIRE_CLASSES: Dict[str, Callable[[random.Random, bytes],
                                 Tuple[bytes, str]]] = {
    "wire_truncate": _w_truncate,
    "wire_bitflip": _w_bitflip,
    "wire_bad_magic": _w_bad_magic,
    "wire_header_fuzz": _w_header_fuzz,
}


# --- ingest-lane mutation classes --------------------------------------------
# fn(rng, src_shard, dst_shard) -> (fmt, note)

def _tfrecord_frames(raw: bytes) -> List[Tuple[int, int]]:
    """(offset, data_len) of each record frame; stops at damage."""
    out = []
    off = 0
    while off + 12 <= len(raw):
        n = struct.unpack_from("<Q", raw, off)[0]
        if off + 12 + n + 4 > len(raw):
            break
        out.append((off, n))
        off += 12 + n + 4
    return out


def _i_tfrecord_len(rng: random.Random, src: str,
                    dst: str) -> Tuple[str, str]:
    """Corrupt a record's length field; half the time re-stamp its
    masked crc32c so the framing READS but the record boundary lies."""
    from ..data import tfrecord
    with open(src, "rb") as f:
        raw = bytearray(f.read())
    frames = _tfrecord_frames(raw)
    off, n = frames[rng.randrange(len(frames))]
    newlen = rng.choice([n + 1, n * 7 + 13, (1 << 60) | n, 0])
    struct.pack_into("<Q", raw, off, newlen)
    fix = rng.random() < 0.5
    if fix:
        struct.pack_into("<I", raw, off + 8,
                         tfrecord.masked_crc(bytes(raw[off:off + 8])))
    with open(dst, "wb") as f:
        f.write(raw)
    return "tfrecord", (f"record@{off}: len {n} -> {newlen}"
                        f" ({'crc re-stamped' if fix else 'crc stale'})")


def _i_tfrecord_data(rng: random.Random, src: str,
                     dst: str) -> Tuple[str, str]:
    """Flip bits inside record DATA without touching its crc32c."""
    with open(src, "rb") as f:
        raw = bytearray(f.read())
    frames = _tfrecord_frames(raw)
    off, n = frames[rng.randrange(len(frames))]
    k = rng.randint(1, 8)
    for _ in range(k):
        i = off + 12 + rng.randrange(max(1, n))
        raw[i] ^= 1 << rng.randrange(8)
    with open(dst, "wb") as f:
        f.write(raw)
    return "tfrecord", f"record@{off}: {k} data bit flips"


def _i_tfrecord_trunc(rng: random.Random, src: str,
                      dst: str) -> Tuple[str, str]:
    """Cut the shard mid-record (a dying disk / partial copy)."""
    with open(src, "rb") as f:
        raw = f.read()
    keep = rng.randrange(1, len(raw))
    with open(dst, "wb") as f:
        f.write(raw[:keep])
    return "tfrecord", f"truncated {len(raw)} -> {keep} bytes"


def _i_tsv_garbage(rng: random.Random, src: str,
                   dst: str) -> Tuple[str, str]:
    """Raw-bytes TSV damage: binary splices, non-utf8 lines, an
    unterminated megarow — skip-and-count or die loudly, never hang."""
    with open(src, "rb") as f:
        raw = bytearray(f.read())
    variant = rng.choice(["splice", "non_utf8", "megarow", "nulls"])
    if variant == "splice":
        i = rng.randrange(len(raw))
        raw[i:i] = bytes(rng.randrange(256) for _ in range(256))
    elif variant == "non_utf8":
        raw += b"1\t" + bytes([0xC3, 0x28]) * 20 + b"\n"
    elif variant == "megarow":
        raw += b"2\t" + b"9" * 100_000        # no trailing newline
    else:
        for _ in range(32):
            raw[rng.randrange(len(raw))] = 0
    with open(dst, "wb") as f:
        f.write(raw)
    return "tsv", f"tsv {variant}"


INGEST_CLASSES: Dict[str, Callable[[random.Random, str, str],
                                   Tuple[str, str]]] = {
    "tfrecord_len_field": _i_tfrecord_len,
    "tfrecord_data_corrupt": _i_tfrecord_data,
    "tfrecord_truncate": _i_tfrecord_trunc,
    "tsv_garbage": _i_tsv_garbage,
}

LANE_OF = {}
for _n in CKPT_CLASSES:
    LANE_OF[_n] = "ckpt"
for _n in WIRE_CLASSES:
    LANE_OF[_n] = "wire"
for _n in INGEST_CLASSES:
    LANE_OF[_n] = "ingest"


# --- deadline execution ------------------------------------------------------

def _deadline_call(fn: Callable[[], Any], deadline: float
                   ) -> Tuple[str, Any]:
    """Run ``fn`` on a watchdog thread: ('ok', result) | ('raise', exc)
    | ('hang', None). A hung probe's thread is abandoned (daemon) — the
    violation is recorded and the harness moves on."""
    box: Dict[str, Any] = {}

    def run() -> None:
        try:
            box["r"] = fn()
        except BaseException as e:  # noqa: BLE001 — probe boundary
            box["e"] = e

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(deadline)
    if t.is_alive():
        return "hang", None
    if "e" in box:
        return "raise", box["e"]
    return "ok", box.get("r")


# --- native probe (subprocess) -----------------------------------------------

def _native_probe_main() -> int:
    """Subprocess entry (``python fuzz.py --native-probe`` + JSON spec
    on stdin): ctypes-load the sanitizer .so, open the dir, pull the
    probe rows, print one JSON line. stdlib-only: starts in ~50 ms, and
    a sanitizer abort/SIGSEGV kills THIS process, never the harness."""
    import ctypes
    spec = json.load(sys.stdin)
    lib = ctypes.CDLL(spec["lib"])
    lib.oe_last_error.restype = ctypes.c_char_p
    lib.oe_model_load.restype = ctypes.c_void_p
    lib.oe_model_load.argtypes = [ctypes.c_char_p]
    lib.oe_model_free.argtypes = [ctypes.c_void_p]
    lib.oe_model_version.restype = ctypes.c_int64
    lib.oe_model_version.argtypes = [ctypes.c_void_p]
    lib.oe_model_variable.restype = ctypes.c_void_p
    lib.oe_model_variable.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.oe_variable_dim.restype = ctypes.c_int
    lib.oe_variable_dim.argtypes = [ctypes.c_void_p]
    lib.oe_pull_weights.restype = ctypes.c_int
    lib.oe_pull_weights.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_float)]
    m = lib.oe_model_load(spec["dir"].encode())
    if not m:
        err = (lib.oe_last_error() or b"").decode("utf-8", "replace")
        print(json.dumps({"outcome": "refuse", "error": err},
                         sort_keys=True))
        return 0
    h = hashlib.sha256()
    for v in spec["vars"]:
        var = lib.oe_model_variable(m, v["name"].encode())
        if not var:
            h.update(b"missing:" + v["name"].encode())
            continue
        dim = lib.oe_variable_dim(var)
        ids = v["ids"]
        keys = (ctypes.c_int64 * len(ids))(*ids)
        out = (ctypes.c_float * (len(ids) * dim))()
        rc = lib.oe_pull_weights(var, keys, len(ids), out)
        if rc != 0:
            err = (lib.oe_last_error() or b"").decode("utf-8", "replace")
            lib.oe_model_free(m)
            print(json.dumps({"outcome": "refuse",
                              "error": f"pull failed: {err}"},
                             sort_keys=True))
            return 0
        h.update(bytes(out))
    version = int(lib.oe_model_version(m))
    lib.oe_model_free(m)
    print(json.dumps({"outcome": "load", "version": version,
                      "digest": h.hexdigest()}, sort_keys=True))
    return 0


def _asan_preload() -> str:
    """gcc does not link the ASan runtime into shared objects — the
    probe interpreter must LD_PRELOAD it for the .so to resolve."""
    out = subprocess.run(["gcc", "-print-file-name=libasan.so"],
                         capture_output=True, text=True, check=True)
    p = out.stdout.strip()
    if not os.path.isabs(p):
        raise RuntimeError(f"libasan.so not found (gcc said {p!r})")
    return p


def probe_native(d: str, lib: str, probe_vars: List[Dict[str, Any]],
                 *, deadline: float = DEADLINE_S,
                 sanitizer: str = "") -> Dict[str, Any]:
    """Run the native reader over ``d`` in a contained subprocess.

    Returns {"outcome": "load"|"refuse"|"crash"|"hang", ...}. ``crash``
    carries the exit code and the stderr tail (the sanitizer report)."""
    env = dict(os.environ)
    env["ASAN_OPTIONS"] = "detect_leaks=0:abort_on_error=1"
    env["UBSAN_OPTIONS"] = "halt_on_error=1:print_stacktrace=1"
    if sanitizer == "asan":
        env["LD_PRELOAD"] = _asan_preload()
    spec = json.dumps({"dir": d, "lib": lib, "vars": probe_vars})
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--native-probe"],
            input=spec, capture_output=True, text=True, env=env,
            timeout=deadline)
    except subprocess.TimeoutExpired:
        return {"outcome": "hang"}
    for line in reversed(out.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except ValueError:
                break
    return {"outcome": "crash", "exit": out.returncode,
            "stderr_tail": out.stderr[-800:]}


# --- python probes -----------------------------------------------------------

class SeedContext:
    """One trained seed checkpoint + everything the probes need: the
    collection pair (tracked writer / untracked loader), the probe id
    sets, and the native probe spec. Built once per run."""

    def __init__(self, tmp_root: str, *, vocab: int = 64, dim: int = 4,
                 steps: int = 2):
        import numpy as np
        import jax
        import jax.numpy as jnp
        from .. import EmbeddingCollection, EmbeddingSpec
        from .. import checkpoint as ckpt
        from .. import checkpoint_delta as cd
        from ..parallel.mesh import create_mesh
        self.tmp_root = tmp_root
        self.vocab, self.dim, self.steps = vocab, dim, steps
        self.seed_dir = os.path.join(tmp_root, "seed")
        mesh = create_mesh(1, 1, jax.devices()[:1])

        def make(track: bool) -> Any:
            specs = (EmbeddingSpec(name="arr", input_dim=vocab,
                                   output_dim=dim),
                     EmbeddingSpec(name="hsh", input_dim=-1,
                                   output_dim=dim, hash_capacity=256))
            coll = EmbeddingCollection(
                specs, mesh, default_optimizer={"category": "adagrad",
                                                "learning_rate": 0.1})
            if track:
                coll.enable_dirty_tracking(target_chunks=8)
            return coll

        coll = make(track=True)
        states = coll.init(jax.random.PRNGKey(0))
        ckpt.save_checkpoint(self.seed_dir, coll, states,
                             model_sign="graftfuzz-seed")
        hkeys: List[int] = []
        for i in range(steps):
            rs = np.random.RandomState(100 + i)
            idx = {"arr": jnp.asarray(
                       rs.randint(0, vocab, 16).astype(np.int32)),
                   "hsh": jnp.asarray(
                       rs.randint(0, 2 ** 20, 16).astype(np.int32))}
            rows = coll.pull(states, idx, batch_sharded=False)
            grads = {k: jnp.ones_like(v) * 0.25 for k, v in rows.items()}
            states = coll.apply_gradients(states, idx, grads,
                                          batch_sharded=False)
            info = cd.save_delta(self.seed_dir, coll, states, step=i + 1,
                                 compact_chain_len=1000,
                                 compact_bytes_ratio=1000.0)
            assert info["seq"] == i + 1, info
            hkeys.extend(int(k) for k in np.asarray(idx["hsh"]))
        self.load_coll = make(track=False)
        self.arr_ids = list(range(vocab)) + [-1, vocab, 10 ** 7]
        self.hsh_keys = sorted(set(hkeys)) + [123456789]
        self.wire_frames = self._build_frames(cd)

    def _build_frames(self, cd: Any) -> List[bytes]:
        delta = cd.read_delta(self.seed_dir)
        return [cd.encode_delta(delta),
                cd.encode_delta(delta, compress="zlib")]

    @property
    def native_vars(self) -> List[Dict[str, Any]]:
        return [{"name": "arr", "ids": self.arr_ids},
                {"name": "hsh", "ids": self.hsh_keys}]

    def digest_states(self, states: Any) -> str:
        """sha256 over the probe rows as f32 — byte-comparable with the
        native probe's pulls (the existing native tests assert exact
        equality on this same path)."""
        import numpy as np
        import jax.numpy as jnp
        h = hashlib.sha256()
        ids = np.asarray(self.arr_ids, np.int64)
        gt = np.where((ids < 0) | (ids >= self.vocab), -1, ids)
        rows = np.asarray(self.load_coll.pull(
            states, {"arr": jnp.asarray(gt.astype(np.int32))},
            batch_sharded=False, read_only=True)["arr"], np.float32)
        h.update(rows.tobytes())
        keys = np.asarray(self.hsh_keys, np.int64)
        rows = np.asarray(self.load_coll.pull(
            states, {"hsh": jnp.asarray(keys.astype(np.int32))},
            batch_sharded=False, read_only=True)["hsh"], np.float32)
        h.update(rows.tobytes())
        return h.hexdigest()


def probe_python_full(ctx: SeedContext, d: str, *,
                      deadline: float = DEADLINE_S) -> Dict[str, Any]:
    """``load_checkpoint`` + probe-row digest, deadline-bounded."""
    from .. import checkpoint as ckpt

    def go() -> Dict[str, Any]:
        info: Dict[str, Any] = {}
        states = ckpt.load_checkpoint(d, ctx.load_coll, info=info)
        return {"outcome": "load",
                "version": int(info.get("applied_seq", 0)),
                "digest": ctx.digest_states(states)}

    status, r = _deadline_call(go, deadline)
    if status == "hang":
        return {"outcome": "hang"}
    if status == "raise":
        if isinstance(r, PY_REFUSALS):
            return {"outcome": "refuse",
                    "error": f"{type(r).__name__}: {r}"}
        return {"outcome": "crash",
                "error": f"untyped {type(r).__name__}: {r}"}
    return r


def probe_python_delta(ctx: SeedContext, d: str, *,
                       deadline: float = DEADLINE_S) -> Dict[str, Any]:
    """``read_deltas_since(d, 0)`` — the catch-up stream a lagging
    replica replays. Participates in the crash/hang/typed-refusal
    oracle; its payloads are delta-domain (not whole-model rows), so
    they are digested for determinism but not cross-compared."""
    import numpy as np
    from .. import checkpoint_delta as cd

    def go() -> Dict[str, Any]:
        deltas = cd.read_deltas_since(d, 0)
        h = hashlib.sha256()
        for dl in deltas:
            h.update(str(int(dl.seq)).encode())
            for name in sorted(dl.vars):
                for field in sorted(dl.vars[name]):
                    h.update(field.encode())
                    h.update(np.asarray(dl.vars[name][field]).tobytes())
        return {"outcome": "load", "deltas": len(deltas),
                "seqs": [int(dl.seq) for dl in deltas],
                "digest": h.hexdigest()}

    status, r = _deadline_call(go, deadline)
    if status == "hang":
        return {"outcome": "hang"}
    if status == "raise":
        if isinstance(r, PY_REFUSALS):
            return {"outcome": "refuse",
                    "error": f"{type(r).__name__}: {r}"}
        return {"outcome": "crash",
                "error": f"untyped {type(r).__name__}: {r}"}
    return r


# --- oracle ------------------------------------------------------------------

def judge(outcomes: Dict[str, Dict[str, Any]]) -> List[str]:
    """The trichotomy, scored: crashes/hangs always lose; every probe
    that LOADED whole-model rows must agree with every other on
    (version, digest). Refusals are always acceptable — which reader
    refuses WHAT is pinned by the regression corpus, not here."""
    bad: List[str] = []
    for name, oc in sorted(outcomes.items()):
        if oc["outcome"] == "hang":
            bad.append(f"{name}: hang past deadline")
        elif oc["outcome"] == "crash":
            detail = oc.get("error") or (
                f"exit {oc.get('exit')}: {oc.get('stderr_tail', '')}")
            bad.append(f"{name}: crash ({detail.strip()})")
    loaders = [(n, oc) for n, oc in sorted(outcomes.items())
               if oc["outcome"] == "load" and "version" in oc
               and n != "python_delta"]
    for i in range(1, len(loaders)):
        (an, a), (bn, b) = loaders[0], loaders[i]
        if a["version"] != b["version"]:
            bad.append(f"divergence: {an} version {a['version']} != "
                       f"{bn} version {b['version']}")
        elif a["digest"] != b["digest"]:
            bad.append(f"divergence: {an} and {bn} loaded version "
                       f"{a['version']} with different row bytes")
    return bad


# --- lane drivers ------------------------------------------------------------

def fuzz_ckpt_dir(ctx: SeedContext, cls: str, rng: random.Random,
                  work_dir: str, libs: Dict[str, str], *,
                  deadline: float = DEADLINE_S
                  ) -> Tuple[str, Dict[str, Dict[str, Any]], List[str]]:
    """One ckpt-lane iteration: copy seed -> mutate -> all probes ->
    judge. Returns (note, outcomes, violations)."""
    d = os.path.join(work_dir, "mut")
    if os.path.exists(d):
        shutil.rmtree(d)
    shutil.copytree(ctx.seed_dir, d)
    note = CKPT_CLASSES[cls](rng, d)
    outcomes: Dict[str, Dict[str, Any]] = {}
    for variant, lib in sorted(libs.items()):
        outcomes[f"native_{variant}"] = probe_native(
            d, lib, ctx.native_vars, deadline=deadline,
            sanitizer=variant)
    if cls not in NATIVE_ONLY_CLASSES:
        outcomes["python_full"] = probe_python_full(ctx, d,
                                                    deadline=deadline)
        outcomes["python_delta"] = probe_python_delta(ctx, d,
                                                      deadline=deadline)
    return note, outcomes, judge(outcomes)


def fuzz_wire(ctx: SeedContext, cls: str, rng: random.Random, *,
              deadline: float = DEADLINE_S
              ) -> Tuple[str, Dict[str, Dict[str, Any]], List[str]]:
    """One wire-lane iteration: mutate a frame, decode it TWICE — each
    decode must be a Delta or a DeltaDecodeError, and the two must
    agree bit-for-bit (a nondeterministic decoder would let two
    replicas apply different rows from the same published frame)."""
    import numpy as np
    from .. import checkpoint_delta as cd

    frame = ctx.wire_frames[rng.randrange(len(ctx.wire_frames))]
    mut, note = WIRE_CLASSES[cls](rng, frame)

    def digest(delta: Any) -> str:
        h = hashlib.sha256()
        h.update(str((int(delta.seq), int(delta.step))).encode())
        for name in sorted(delta.vars):
            for field in sorted(delta.vars[name]):
                a = np.asarray(delta.vars[name][field])
                h.update(f"{name}/{field}/{a.dtype.str}/"
                         f"{a.shape}".encode())
                h.update(a.tobytes())
        return h.hexdigest()

    def decode_once() -> Dict[str, Any]:
        try:
            return {"outcome": "load",
                    "digest": digest(cd.decode_delta(mut))}
        except cd.DeltaDecodeError as e:
            return {"outcome": "refuse",
                    "error": f"DeltaDecodeError: {e}"}

    outcomes: Dict[str, Dict[str, Any]] = {}
    for k in ("decode_a", "decode_b"):
        status, r = _deadline_call(decode_once, deadline)
        if status == "hang":
            outcomes[k] = {"outcome": "hang"}
        elif status == "raise":
            outcomes[k] = {"outcome": "crash",
                           "error": f"untyped {type(r).__name__}: {r}"}
        else:
            outcomes[k] = r
    bad = [f"{k}: {oc['outcome']} ({oc.get('error', '')})"
           for k, oc in sorted(outcomes.items())
           if oc["outcome"] in ("hang", "crash")]
    a, b = outcomes["decode_a"], outcomes["decode_b"]
    if not bad and a != b:
        bad.append("wire decode is nondeterministic: two decodes of the "
                   "same frame disagree")
    return note, outcomes, bad


def fuzz_ingest(ctx: SeedContext, cls: str, rng: random.Random,
                work_dir: str, shard_src: Dict[str, str], *,
                deadline: float = DEADLINE_S
                ) -> Tuple[str, Dict[str, Dict[str, Any]], List[str]]:
    """One ingest-lane iteration: mutate a shard, stream it through
    :class:`ShardStream`. Acceptable: complete (skip-and-count) or a
    typed loud failure. Never a hang, never an untyped escape."""
    from ..data.stream import ShardStream
    from ..utils import observability

    fmt_hint = "tfrecord" if cls.startswith("tfrecord") else "tsv"
    src = shard_src[fmt_hint]
    dst = os.path.join(work_dir, os.path.basename(src))
    fmt, note = INGEST_CLASSES[cls](rng, src, dst)

    def consume() -> Dict[str, Any]:
        before = observability.GLOBAL.snapshot().get(
            "ingest_bad_rows", {}).get("count", 0)
        s = ShardStream([dst], batch_size=32, fmt=fmt, readers=1,
                        epochs=1, drop_remainder=False, name="graftfuzz")
        try:
            nrows = 0
            for batch in s:
                nrows += int(batch["label"].shape[0])
        finally:
            s.close()
        after = observability.GLOBAL.snapshot().get(
            "ingest_bad_rows", {}).get("count", 0)
        return {"outcome": "load", "rows": nrows,
                "bad_rows": int(after - before)}

    status, r = _deadline_call(consume, deadline)
    if status == "hang":
        oc: Dict[str, Any] = {"outcome": "hang"}
    elif status == "raise":
        if isinstance(r, PY_REFUSALS):
            oc = {"outcome": "refuse", "error": f"{type(r).__name__}: {r}"}
        else:
            oc = {"outcome": "crash",
                  "error": f"untyped {type(r).__name__}: {r}"}
    else:
        oc = r
    outcomes = {"stream": oc}
    bad = []
    if oc["outcome"] == "hang":
        bad.append("stream: reader hang past deadline")
    elif oc["outcome"] == "crash":
        bad.append(f"stream: crash ({oc['error']})")
    return note, outcomes, bad


# --- sanitizer builds --------------------------------------------------------

def sanitizer_libs(*, build: bool = True,
                   variants: Tuple[str, ...] = ("asan", "ubsan")
                   ) -> Dict[str, str]:
    """{'asan': .so path, 'ubsan': .so path} — built via the Makefile's
    sanitizer targets (``make -C native asan ubsan``)."""
    from ..serving import native as native_mod
    return {v: native_mod.build_library(force=build, variant=v)
            for v in variants}


# --- the run -----------------------------------------------------------------

def all_classes(lanes: Tuple[str, ...] = ("ckpt", "wire", "ingest")
                ) -> List[str]:
    return [n for n in list(CKPT_CLASSES) + list(WIRE_CLASSES)
            + list(INGEST_CLASSES) if LANE_OF[n] in lanes]


def run_fuzz(*, seed: int = 0, iters: Optional[int] = None,
             lanes: Tuple[str, ...] = ("ckpt", "wire", "ingest"),
             deadline: float = DEADLINE_S, tmp_root: Optional[str] = None,
             build: bool = True, ctx: Optional[SeedContext] = None,
             libs: Optional[Dict[str, str]] = None,
             log: Optional[Callable[[str], None]] = None
             ) -> Dict[str, Any]:
    """The full deterministic run. Classes fire round-robin so
    ``iters >= len(classes)`` guarantees full coverage; fewer iters
    leaves silent classes, which the report marks and the CLI fails —
    a run that LOOKS green must have actually explored every declared
    mutation class. The report carries no wall-clock or absolute paths:
    same seed, same bytes."""
    import tempfile
    classes = all_classes(lanes)
    if iters is None:
        iters = len(classes)
    own_tmp = tmp_root is None
    if own_tmp:
        tmp_root = tempfile.mkdtemp(prefix="graftfuzz-")
    scrub_roots = [tmp_root]
    try:
        if ctx is None:
            ctx = SeedContext(os.path.join(tmp_root, "ctx"))
        scrub_roots.append(ctx.tmp_root)
        if libs is None:
            libs = sanitizer_libs(build=build) if "ckpt" in lanes else {}
        shard_src: Dict[str, str] = {}
        if "ingest" in lanes:
            from ..data.stream import write_synthetic_shards
            for fmt in ("tsv", "tfrecord"):
                sd = os.path.join(tmp_root, f"shards-{fmt}")
                paths = write_synthetic_shards(
                    sd, num_shards=1, rows_per_shard=96, fmt=fmt,
                    seed=7)
                shard_src[fmt] = paths[0]
        per_class: Dict[str, Dict[str, Any]] = {
            n: {"fired": 0, "violations": 0, "outcomes": {}}
            for n in classes}
        violations: List[Dict[str, Any]] = []
        iterations: List[Dict[str, Any]] = []
        work_dir = os.path.join(tmp_root, "work")
        os.makedirs(work_dir, exist_ok=True)
        for i in range(iters):
            cls = classes[i % len(classes)]
            rng = random.Random(f"{seed}:{i}:{cls}")
            try:
                if LANE_OF[cls] == "ckpt":
                    note, outcomes, bad = fuzz_ckpt_dir(
                        ctx, cls, rng, work_dir, libs, deadline=deadline)
                elif LANE_OF[cls] == "wire":
                    note, outcomes, bad = fuzz_wire(ctx, cls, rng,
                                                    deadline=deadline)
                else:
                    note, outcomes, bad = fuzz_ingest(
                        ctx, cls, rng, work_dir, shard_src,
                        deadline=deadline)
            except Exception as e:  # noqa: BLE001 — mutator failed
                note = f"mutator error: {type(e).__name__}: {e}"
                outcomes = {}
                bad = [f"mutator: {type(e).__name__}: {e}"]
            note = _scrub(note, scrub_roots)
            bad = [_scrub(b, scrub_roots) for b in bad]
            pc = per_class[cls]
            pc["fired"] += 1
            pc["violations"] += len(bad)
            for name, oc in outcomes.items():
                key = f"{name}:{oc['outcome']}"
                pc["outcomes"][key] = pc["outcomes"].get(key, 0) + 1
            summary = {name: oc["outcome"]
                       for name, oc in sorted(outcomes.items())}
            iterations.append({"iter": i, "class": cls, "note": note,
                               "outcomes": summary,
                               "violations": bad})
            for b in bad:
                violations.append({"iter": i, "class": cls, "detail": b})
            if log is not None:
                flag = " VIOLATION" if bad else ""
                log(f"[{i + 1:>3}/{iters}] {cls:<28} "
                    f"{'/'.join(summary.values()) or '-'}{flag}")
        silent = [n for n in classes if per_class[n]["fired"] == 0]
        report = {
            "gate": "graftfuzz",
            "seed": seed,
            "iters": iters,
            "lanes": sorted(lanes),
            "sanitizers": sorted(libs),
            "classes": per_class,
            "silent_classes": silent,
            "violations": violations,
            "iterations": iterations,
            "ok": not violations and not silent,
        }
        return report
    finally:
        if own_tmp:
            shutil.rmtree(tmp_root, ignore_errors=True)


# --- regression corpus -------------------------------------------------------
# Deterministic builders for the known-bad shapes (PR-12 crafted
# headers, graftchaos torn writes, compaction, codec refusal). The
# fixture (tests/fixtures/fuzz_corpus.py) references these by name and
# pins the EXPECTED per-reader disposition of each.

def _cb_with_rng(cls: str) -> Callable[[str], str]:
    def build(d: str) -> str:
        return CKPT_CLASSES[cls](random.Random(0), d)
    return build


def _cb_name_len(d: str) -> str:
    rng = random.Random(3)                    # picks 0xEEEE deterministically
    return _m_zip_name_len(rng, d)


def _cb_torn_final(d: str) -> str:
    """graftchaos torn_write shape: garbage mid-file in the newest
    entry (the exact damage tests/test_native_serving pins)."""
    m = _load_m(d)
    entry = m["chain"][-1]
    for name in sorted(entry["vars"]):
        p = os.path.join(d, entry["vars"][name]["file"])
        with open(p, "r+b") as f:
            f.seek(10)
            f.write(b"\xde\xad\xbe\xef")
    return f"seq {entry['seq']}: 4 garbage bytes at offset 10, all vars"


def _cb_torn_midchain(d: str) -> str:
    m = _load_m(d)
    entry = m["chain"][0]
    for name in sorted(entry["vars"]):
        os.remove(os.path.join(d, entry["vars"][name]["file"]))
    return f"seq {entry['seq']}: files deleted (mid-chain)"


def _cb_compacted(d: str) -> str:
    from .. import checkpoint_delta as cd
    out = cd.compact(d, background=False)
    assert out["compacted"], out
    return "chain compacted into the base (content_seq carries version)"


def _cb_deflated(d: str) -> str:
    """Re-write the newest arr payload DEFLATED (np.savez_compressed):
    valid bytes the Python reader handles, a codec the dependency-free
    native reader documents as refused — the canonical allowed
    divergence (refusal, never wrong rows)."""
    import io
    import numpy as np
    m = _load_m(d)
    rec = m["chain"][-1]["vars"][sorted(m["chain"][-1]["vars"])[0]]
    p = os.path.join(d, rec["file"])
    with open(p, "rb") as f:
        payload = dict(np.load(io.BytesIO(f.read())))
    bio = io.BytesIO()
    np.savez_compressed(bio, **payload)
    raw = bio.getvalue()
    with open(p, "wb") as f:
        f.write(raw)
    rec["crc32"] = int(zlib.crc32(raw))
    rec["bytes"] = len(raw)
    _store_m(d, m)
    return f"{rec['file']}: re-written deflated, crc re-stamped"


def _cb_deep_json(d: str) -> str:
    n = 2000
    with open(os.path.join(d, MANIFEST), "w") as f:
        f.write('{"format": 1, "chain": ' + "[" * n + "]" * n + "}")
    return "manifest chain nested 2000 deep"


def _cb_chunk_crc(d: str) -> str:
    m = _load_m(d)
    rec = m["chain"][-1]["vars"]["arr"]
    rec["chunk_crc"][0] = int(rec["chunk_crc"][0]) ^ 0xA5
    _store_m(d, m)
    return "final arr chunk_crc[0] perturbed"


def _cb_payload_swap_crc_preserved(d: str) -> str:
    m = _load_m(d)
    entry = m["chain"][-1]
    names = sorted(entry["vars"])
    fa = entry["vars"][names[0]]["file"]
    fb = entry["vars"][names[1]]["file"]
    pa, pb = os.path.join(d, fa), os.path.join(d, fb)
    with open(pa, "rb") as f:
        ba = f.read()
    with open(pb, "rb") as f:
        bb = f.read()
    with open(pa, "wb") as f:
        f.write(bb)
    with open(pb, "wb") as f:
        f.write(ba)
    _refresh_crc(d, m, fa)
    _refresh_crc(d, m, fb)
    _store_m(d, m)
    return f"final entry: {fa} <-> {fb} bytes swapped, crcs re-stamped"


def _cb_seq_overflow(d: str) -> str:
    m = _load_m(d)
    m["chain"][-1]["seq"] = 10 ** 300
    _store_m(d, m)
    return "final seq = 1e300 (past int64)"


CORPUS_BUILDERS: Dict[str, Callable[[str], str]] = {
    "name_len_overflow": _cb_name_len,
    "offset_overflow": _cb_with_rng("zip_offset_overflow"),
    "zip64_marker": _cb_with_rng("zip_zip64_marker"),
    "deflate_refusal": _cb_deflated,
    "torn_final": _cb_torn_final,
    "torn_midchain": _cb_torn_midchain,
    "compacted_dir": _cb_compacted,
    "deep_json_manifest": _cb_deep_json,
    "chunk_crc_corrupt": _cb_chunk_crc,
    "payload_swap_crc_preserved": _cb_payload_swap_crc_preserved,
    "seq_int64_overflow": _cb_seq_overflow,
}


def build_corpus_dir(name: str, ctx: SeedContext, work_dir: str) -> str:
    """Materialize corpus entry ``name`` as a fresh mutated copy of the
    seed dir; returns the directory path."""
    d = os.path.join(work_dir, f"corpus-{name}")
    if os.path.exists(d):
        shutil.rmtree(d)
    shutil.copytree(ctx.seed_dir, d)
    CORPUS_BUILDERS[name](d)
    return d


def _check_disposition(reader: str, oc: Dict[str, Any],
                       want: Dict[str, Any]) -> Optional[str]:
    if oc["outcome"] != want["outcome"]:
        return (f"{reader}: got {oc['outcome']} "
                f"({oc.get('error', '')}), pinned {want['outcome']}")
    if want["outcome"] == "refuse":
        if want["match"].lower() not in oc.get("error", "").lower():
            return (f"{reader}: refusal {oc.get('error', '')!r} does not "
                    f"match pinned substring {want['match']!r}")
    else:
        if "version" in want and oc.get("version") != want["version"]:
            return (f"{reader}: loaded version {oc.get('version')}, "
                    f"pinned {want['version']}")
        if "deltas" in want and oc.get("deltas") != want["deltas"]:
            return (f"{reader}: {oc.get('deltas')} deltas, "
                    f"pinned {want['deltas']}")
        if "seqs" in want and oc.get("seqs") != want["seqs"]:
            return (f"{reader}: seqs {oc.get('seqs')}, "
                    f"pinned {want['seqs']}")
    return None


def run_regress(ctx: SeedContext, libs: Dict[str, str], work_dir: str, *,
                deadline: float = DEADLINE_S,
                log: Optional[Callable[[str], None]] = None
                ) -> Dict[str, Any]:
    """Every corpus entry through all three readers; each must produce
    EXACTLY its pinned disposition (refusal substring or
    load/recover-to version). The corpus is how fuzzer-found bugs stay
    fixed: each fix lands with its triggering shape pinned here."""
    import importlib.util
    from ..serving import native as native_mod
    fixture_path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "tests", "fixtures",
        "fuzz_corpus.py")
    spec = importlib.util.spec_from_file_location("_graftfuzz_corpus",
                                                  fixture_path)
    fuzz_corpus = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(fuzz_corpus)
    failures: List[Dict[str, str]] = []
    checked = 0
    plain_lib = native_mod.build_library()
    for entry in fuzz_corpus.iter_corpus():
        name = entry["name"]
        if name not in CORPUS_BUILDERS:
            failures.append({"entry": name,
                             "detail": "unknown corpus builder"})
            continue
        d = build_corpus_dir(name, ctx, work_dir)
        expect = entry["expect"]
        outcomes = {
            "python_full": probe_python_full(ctx, d, deadline=deadline),
            "python_delta": probe_python_delta(ctx, d, deadline=deadline),
        }
        # the pinned native disposition must hold under every build —
        # plain, ASan and UBSan (the sanitizer matrix)
        native_runs = [("native[plain]", plain_lib, "")]
        native_runs += [(f"native[{v}]", libs[v], v) for v in sorted(libs)]
        for label, lib, sanitizer in native_runs:
            oc = probe_native(d, lib, ctx.native_vars, deadline=deadline,
                              sanitizer=sanitizer)
            bad = _check_disposition(label, oc, expect["native"])
            if bad:
                failures.append({"entry": name,
                                 "detail": _scrub(bad, [ctx.tmp_root, d])})
        for reader in ("python_full", "python_delta"):
            bad = _check_disposition(reader, outcomes[reader],
                                     expect[reader])
            if bad:
                failures.append({"entry": name,
                                 "detail": _scrub(bad, [ctx.tmp_root, d])})
        checked += 1
        if log is not None:
            n_bad = sum(1 for f in failures if f["entry"] == name)
            log(f"corpus {name:<28} "
                f"{'FAIL' if n_bad else 'ok'} ({entry['why']})")
    return {"gate": "graftfuzz-regress", "entries": checked,
            "failures": failures, "ok": not failures}


if __name__ == "__main__":
    if "--native-probe" in sys.argv:
        sys.exit(_native_probe_main())
    sys.stderr.write("run the harness via: python -m tools.graftfuzz\n")
    sys.exit(2)
