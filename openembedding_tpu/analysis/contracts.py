"""Compiled-program contract registry: per-plane HLO audits.

The framework's core guarantee is structural, not numerical: per-device
ICI bytes on the a2a planes scale as O(slack * batch_slice * dim), never
O(global_batch * dim) or O(table) (SURVEY §1; the reference's
exchange-not-broadcast design, EmbeddingPullOperator.cpp:60-112). That
property lives in the COMPILED program — a sharding-annotation regression
shows up as an oversized ``all-gather`` in the pull HLO long before it
shows up as a 10x ICI blowup on a real mesh. This module generalizes the
original ``utils/hlocheck.py`` (still re-exported there) into a
declarative registry: each (plane, program) pair declares its expected
collective inventory and byte bounds, checked against compiled HLO text.

Cross-cutting audits (any program):

* :func:`check_no_f64` — no ``f64`` op anywhere (an x64 leak doubles
  every table byte and halves MXU throughput);
* :func:`check_donation` — the step program's ``input_output_alias``
  header actually aliases the donated table buffers;
* :func:`max_copy_bytes` — no full-table ``copy`` op (donation that XLA
  silently declined);
* :func:`check_no_host_transfers` — no infeed/outfeed/host-callback
  custom-calls inside the jitted step (the hot-cache admission sketch
  and the observability accumulators must stay host-side; a stray
  callback stalls TPU pipelining every step).

Byte semantics follow hlocheck: bounds apply to the largest SINGLE
buffer of a collective (async ``-start`` tuples carry operand AND result
buffers — summing would double-count), ops inside a ``while`` body count
once (static program size), and ``-done`` ops are skipped (their result
aliases the ``-start`` tuple).

This module imports only the stdlib so every other module (including
``parallel/*``) can import it without cycles.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "bf16": 2,
                "f16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8,
                "f64": 8}

_COLLECTIVES = ("all-to-all", "all-gather", "all-reduce",
                "collective-permute", "reduce-scatter")

# post-optimization TPU HLO splits collectives into async -start/-done
# pairs (`%x = (...) all-gather-start(...)`); match either form under the
# base name, and skip -done ops (their result aliases the -start tuple —
# counting both would double every byte)
_OP_RE = re.compile(
    r"= (?P<type>.*?) (?P<op>" + "|".join(_COLLECTIVES)
    + r")(?P<suffix>-start|-done)?\(")
_SHAPE_RE = re.compile(r"(" + "|".join(_DTYPE_BYTES) + r")\[([\d,]*)\]")

# the one legitimate all-gather in a pull program re-assembles each data
# slice's pulled rows on its model-axis peers; the partitioner may pad
# the gathered dim, so bounds carry this slack factor
ROW_ASSEMBLY_SLACK = 1.0625


class ContractViolation(AssertionError):
    """A compiled program broke its plane's declared contract."""


# --- HLO text parsing (absorbed from utils/hlocheck.py) ----------------------

def _type_bytes(type_str: str) -> Tuple[int, int]:
    """(total bytes, largest single buffer bytes) of one HLO type string."""
    total = largest = 0
    for dtype, dims in _SHAPE_RE.findall(type_str):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        b = n * _DTYPE_BYTES[dtype]
        total += b
        largest = max(largest, b)
    return total, largest


def collect_collectives(hlo_text: str) -> List[Tuple[str, int, int]]:
    """Collective ops in a compiled HLO dump as (op, bytes, max_buffer).

    ``bytes`` sums the result type's buffers (all-to-all emits one per
    peer); ``max_buffer`` is the largest SINGLE buffer — the size-bound
    checks use it because async -start tuples carry operand AND result
    buffers (summing would double-count). Ops inside a ``while`` body are
    counted once (static program size): per-invocation shapes, not
    dynamic step totals — exactly what the scaling contract is about.
    """
    out = []
    for line in hlo_text.splitlines():
        m = _OP_RE.search(line)
        if m and m.group("suffix") != "-done":
            total, largest = _type_bytes(m.group("type"))
            out.append((m.group("op"), total, largest))
    return out


EXCHANGE_BYTE_OPS = ("all-to-all", "all-gather")
# the compressed-exchange promise (ROADMAP item 6): a bf16/int8 plane's
# exchange collectives move at most this fraction of the f32 plane's
# bytes — asserted against BOTH compiled programs, not computed from a
# formula, so partitioner padding/decomposition drift cannot fake it
COMPRESSED_BYTE_RATIO = 0.55


def exchange_collective_bytes(hlo_text: str,
                              ops: Tuple[str, ...] = EXCHANGE_BYTE_OPS
                              ) -> int:
    """Total exchange bytes of one compiled program: the sum over every
    ``ops`` collective instance of its largest single buffer (the
    async-safe accounting summarize/largest uses — ``-start`` tuples
    carry operand AND result). This is the quantity the byte-halving
    contract compares between a compressed plane and its f32 baseline;
    scalar all-reduces (residue-loop counts) are excluded by default."""
    return sum(big for op, _total, big in collect_collectives(hlo_text)
               if op in ops)


def check_byte_halving(compressed_hlo: str, baseline_hlo: str, *,
                       ratio: float = COMPRESSED_BYTE_RATIO,
                       label: str = "") -> Tuple[int, int]:
    """Enforce compressed exchange bytes <= ratio * f32 exchange bytes.

    Both arguments are compiled HLO text of the SAME program shape
    (same mesh/batch/dim — the callers lower them side by side).
    Returns (compressed_bytes, baseline_bytes); raises
    :class:`ContractViolation` when the claimed halving is not in the
    compiled program — including when the "compressed" program is
    secretly the f32 one (ratio 1.0), the negative the tests pin.
    """
    where = f"{label}: " if label else ""
    got = exchange_collective_bytes(compressed_hlo)
    base = exchange_collective_bytes(baseline_hlo)
    if base <= 0:
        raise ContractViolation(
            f"{where}baseline f32 program has no exchange collectives — "
            "nothing to compare the compressed plane against")
    if got > ratio * base:
        raise ContractViolation(
            f"{where}compressed exchange moves {got} bytes > "
            f"{ratio:.2f} x f32 baseline {base} bytes "
            f"(ratio {got / base:.3f}) — the wire is NOT compressed "
            "(rows crossing the exchange at full precision?)")
    return got, base


def summarize(hlo_text: str, *,
              largest: bool = False) -> Dict[str, Tuple[int, int]]:
    """op -> (count, bytes). Default bytes sum every result buffer;
    ``largest=True`` sums each instance's LARGEST single buffer instead —
    the async-safe accounting (``-start`` tuples carry operand AND
    result) shared by the contract byte bounds and the graftscope
    ledger. One fold so the accounting rule lives in one place."""
    out: Dict[str, Tuple[int, int]] = {}
    for op, b, big in collect_collectives(hlo_text):
        c, t = out.get(op, (0, 0))
        out[op] = (c + 1, t + (big if largest else b))
    return out


# --- cross-cutting audits ----------------------------------------------------

def find_f64(hlo_text: str) -> List[str]:
    """Lines carrying an f64 buffer — an x64 leak into the compiled plane."""
    return [ln.strip() for ln in hlo_text.splitlines() if "f64[" in ln]


def check_no_f64(hlo_text: str) -> None:
    bad = find_f64(hlo_text)
    if bad:
        raise ContractViolation(
            f"{len(bad)} f64 op(s) in the compiled program (x64 leak) — "
            f"first: {bad[0][:200]}")


_ALIAS_RE = re.compile(r"\((\d+),\s*\{[^}]*\},\s*(?:may|must)-alias\)")


def donated_params(hlo_text: str) -> Tuple[int, ...]:
    """Parameter numbers the ``input_output_alias`` header aliases.

    Donation declared at the jit boundary is a *request*; the header in
    the post-optimization module is what XLA actually honored.
    """
    header = hlo_text.splitlines()[0] if hlo_text else ""
    m = re.search(r"input_output_alias=\{(.*?)\},\s*\w+=", header)
    blob = m.group(1) if m else header
    return tuple(sorted({int(p) for p in _ALIAS_RE.findall(blob)}))


def check_donation(hlo_text: str, min_aliased: int = 1) -> Tuple[int, ...]:
    """The compiled module aliases at least ``min_aliased`` inputs to
    outputs (table buffers updated in place, not copied per step)."""
    aliased = donated_params(hlo_text)
    if len(aliased) < min_aliased:
        raise ContractViolation(
            f"input_output_alias covers {len(aliased)} parameter(s) "
            f"({aliased}) < required {min_aliased} — donation of the "
            "table/state buffers was declined or never declared")
    return aliased


# the type is captured lazily like _OP_RE: async copy-start (and TPU
# send/recv/infeed below) carry TUPLE result types with spaces — a \S+
# capture would silently skip exactly the ops these audits exist for
_COPY_RE = re.compile(r"= (?P<type>.*?) copy(?:-start)?\(")


def max_copy_bytes(hlo_text: str) -> int:
    """Largest single ``copy`` result buffer (0 if the program has none).

    A copy the size of a table shard means XLA materialized a second
    table per step — donation silently declined. The backend may insert
    legitimate large copies of REPLICATED buffers (dense params), so
    callers enforce ``max_copy_bytes(txt) < table_shard_bytes`` with a
    model sized so table shards dominate every dense buffer
    (``tests/test_analysis_contracts.py::test_train_step_contract`` and
    the ``tools/graftcheck.py`` step audit both do).
    """
    worst = 0
    for line in hlo_text.splitlines():
        m = _COPY_RE.search(line)
        if m:
            _total, largest = _type_bytes(m.group("type"))
            worst = max(worst, largest)
    return worst


_HOST_TRANSFER_RE = re.compile(
    r"= .*? (infeed|outfeed|send|send-done|recv|recv-done)\(")


def host_transfer_ops(hlo_text: str) -> List[str]:
    """Host<->device transfer ops inside the program: infeed/outfeed,
    HOST-side send/recv, and host-callback custom-calls
    (jax.debug.callback / io_callback lower to
    ``custom_call_target="xla_python_cpu_callback"`` and friends).

    send/recv are also device-to-device channel ops (SPMD partitioners
    decompose collective-permute into them), so those two only count
    when the op carries ``is_host_transfer=true``.
    """
    out = []
    for line in hlo_text.splitlines():
        m = _HOST_TRANSFER_RE.search(line)
        if m:
            op = m.group(1)
            if op.startswith(("send", "recv")) \
                    and "is_host_transfer=true" not in line:
                continue
            out.append(op)
            continue
        if "custom-call" in line and re.search(
                r'custom_call_target="[^"]*(callback|host)[^"]*"', line):
            out.append("host-callback")
    return out


def check_no_host_transfers(hlo_text: str) -> None:
    ops = host_transfer_ops(hlo_text)
    if ops:
        raise ContractViolation(
            f"compiled program contains host transfer op(s) {ops[:4]} — "
            "host state (admission sketches, counters) must stay outside "
            "the jitted step; a per-step callback stalls device "
            "pipelining")


# --- overlap contract (pipelined step programs) ------------------------------

# The pipelined plane's promise is a SCHEDULING property of the compiled
# step program (parallel/pipelined.py): the dense fwd/bwd consumes a
# prefetched row buffer (an input), so no dense op waits on an exchange
# collective, while the NEXT batch's exchange rides the same program —
# its index/key legs free of any dense dependency (overlappable) and its
# row resolution committed behind the push (the version barrier). These
# are def-use-graph facts, checkable on any backend's HLO text; the
# async -start/-done pairing leg only binds on backends that emit async
# collective forms (TPU post-optimization dumps).

_DOT_OPS = frozenset({"dot", "convolution"})
_EXCHANGE_OPS = frozenset({"all-to-all", "all-to-all-start"})
# attributes whose %refs name CALLED COMPUTATIONS, not data operands
_CALL_ATTRS = ("calls", "to_apply", "body", "condition",
               "branch_computations", "called_computations")
_CALL_ATTR_RE = re.compile(
    r"(?:" + "|".join(_CALL_ATTRS) + r")=(\{[^}]*\}|%[\w.\-]+)")
_REF_RE = re.compile(r"%([\w.\-]+)")
_CTRL_RE = re.compile(r"control-predecessors=\{([^}]*)\}")
_OP_NAME_RE = re.compile(r'op_name="([^"]*)"')
_COMP_HDR_RE = re.compile(r"^\s*(?P<entry>ENTRY\s+)?%?(?P<name>[\w.\-]+)"
                          r"\s*\(.*\{\s*$")
_INSTR_RE = re.compile(r"^\s*(?:ROOT\s+)?%(?P<name>[\w.\-]+)\s*=\s*"
                       r"(?P<rest>.+)$")


@dataclasses.dataclass(frozen=True)
class HloInstr:
    """One parsed instruction: data operands, called computations, its
    opcode and trace scope — enough for class-level reachability."""

    name: str
    opcode: str
    operands: Tuple[str, ...]
    calls: Tuple[str, ...]
    line_no: int
    op_name: str = ""                # metadata trace path (may be "")


def _split_instr(rest: str) -> Tuple[str, str, str]:
    """(opcode, operand_blob, attr_blob) of an instruction's RHS.

    The RHS is ``<type> <opcode>(<operands>), <attrs>`` where the type
    may be a parenthesized tuple — skip it by balance, then take the
    first identifier followed by ``(``.
    """
    i = 0
    if rest.startswith("("):
        depth = 0
        for i, ch in enumerate(rest):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    i += 1
                    break
    m = re.search(r"([a-z][\w\-]*)\(", rest[i:])
    if not m:
        return "", "", rest
    opcode = m.group(1)
    start = i + m.end()          # first char after the opening paren
    depth = 1
    j = start
    while j < len(rest) and depth:
        if rest[j] == "(":
            depth += 1
        elif rest[j] == ")":
            depth -= 1
        j += 1
    return opcode, rest[start:j - 1], rest[j:]


def parse_hlo_computations(hlo_text: str
                           ) -> Tuple[str, Dict[str, List[HloInstr]]]:
    """(entry_name, computation -> instructions) of one HLO module."""
    comps: Dict[str, List[HloInstr]] = {}
    entry = ""
    current: Optional[List[HloInstr]] = None
    for ln, line in enumerate(hlo_text.splitlines()):
        hdr = _COMP_HDR_RE.match(line)
        if hdr and "=" not in line.split("(")[0]:
            comps[hdr.group("name")] = current = []
            if hdr.group("entry"):
                entry = hdr.group("name")
            continue
        if current is None:
            continue
        m = _INSTR_RE.match(line)
        if not m:
            continue
        opcode, operand_blob, attr_blob = _split_instr(m.group("rest"))
        if not opcode:
            continue
        calls = []
        for blob in _CALL_ATTR_RE.findall(m.group("rest")):
            calls.extend(_REF_RE.findall(blob))
        operands = [r for r in _REF_RE.findall(operand_blob)
                    if r not in calls]
        ctrl = _CTRL_RE.search(attr_blob)
        if ctrl:
            operands.extend(_REF_RE.findall(ctrl.group(1)))
        meta = _OP_NAME_RE.search(attr_blob)
        current.append(HloInstr(name=m.group("name"), opcode=opcode,
                                operands=tuple(operands),
                                calls=tuple(calls), line_no=ln,
                                op_name=meta.group(1) if meta else ""))
    return entry, comps


def _comp_contains(comps: Dict[str, List[HloInstr]],
                   ops: frozenset) -> Dict[str, bool]:
    """computation -> does it (transitively) contain one of ``ops``."""
    out = {name: any(i.opcode in ops for i in instrs)
           for name, instrs in comps.items()}
    changed = True
    while changed:
        changed = False
        for name, instrs in comps.items():
            if out[name]:
                continue
            if any(out.get(c, False) for i in instrs for c in i.calls):
                out[name] = changed = True
    return out


@dataclasses.dataclass(frozen=True)
class OverlapReport:
    """Def-use facts of one step program the overlap contract audits."""

    pull_exchanges: int             # pull-scoped exchange nodes (entry)
    free_pull_exchanges: int        # ... with NO dense dependency
    push_exchanges: int             # push-scoped exchange nodes
    committed_push_exchanges: int   # ... depending on the dense grads
    dense_nodes: int                # dot/convolution-carrying nodes
    dense_waiting_on_exchange: int  # dense nodes downstream of an exchange
    async_pairs: int                # -start/-done collective pairs
    async_pairs_spanning_dense: int  # pairs with dense scheduled between


def analyze_overlap(hlo_text: str) -> OverlapReport:
    """Classify the entry computation's nodes and their reachability.

    A node is *dense* if it is (or calls a computation containing) a
    dot/convolution; an *exchange* if it is (or contains) an
    all-to-all. Taint flows along data operands and control
    predecessors within the entry computation (called computations are
    atomic nodes — a while-loop residue round or a conditional push
    branch counts as one exchange node). Exchange nodes are scoped
    pull/push by their ``op_name`` trace paths — the plane-identifiable
    ``jit(pull_*)`` / ``jit(push_*)`` scopes every data-plane program
    carries (``sharded_table``/``sharded_hash``/``grouped``).
    """
    entry, comps = parse_hlo_computations(hlo_text)
    instrs = comps.get(entry, [])
    has_dot = _comp_contains(comps, _DOT_OPS)
    has_a2a = _comp_contains(comps, _EXCHANGE_OPS)

    def _is_dense(i: HloInstr) -> bool:
        return i.opcode in _DOT_OPS or any(has_dot.get(c, False)
                                           for c in i.calls)

    def _is_exchange(i: HloInstr) -> bool:
        return i.opcode in _EXCHANGE_OPS or any(has_a2a.get(c, False)
                                                for c in i.calls)

    def _scopes(i: HloInstr) -> set:
        """{"pull", "push"} memberships of one exchange node, from its
        own trace path plus those of the collectives inside any called
        computation (a residue while-loop's scope lives on its body's
        ops, not on the while node itself)."""
        names = [i.op_name]
        seen = set()
        stack = list(i.calls)
        while stack:
            c = stack.pop()
            if c in seen or c not in comps:
                continue
            seen.add(c)
            for j in comps[c]:
                if j.opcode in _EXCHANGE_OPS:
                    names.append(j.op_name)
                stack.extend(j.calls)
        out = set()
        for n in names:
            if "pull" in n:
                out.add("pull")
            if "push" in n:
                out.add("push")
        return out

    def _taint(sources) -> set:
        tainted = set(sources)
        changed = True
        while changed:
            changed = False
            for i in instrs:
                if i.name not in tainted and \
                        any(op in tainted for op in i.operands):
                    tainted.add(i.name)
                    changed = True
        return tainted

    dense = [i for i in instrs if _is_dense(i)]
    exchange = [i for i in instrs if _is_exchange(i)]
    scopes = {i.name: _scopes(i) for i in exchange}
    dot_downstream = _taint({i.name for i in dense})
    a2a_downstream = _taint({i.name for i in exchange})
    pulls = [i for i in exchange if "pull" in scopes[i.name]]
    pushes = [i for i in exchange if "push" in scopes[i.name]]
    free = [i for i in pulls if i.name not in dot_downstream]
    committed = [i for i in pushes
                 if i.name in dot_downstream and i.name
                 not in {d.name for d in dense}]
    waiting = [i for i in dense if i.name in a2a_downstream
               and i.name not in {e.name for e in exchange}]

    # async pairing: every exchange -start needs a -done consuming it;
    # "spanning dense" = a dense node sits between them in schedule
    # order (the module prints is_scheduled post-optimization). ONLY
    # exchange ops count — the dense-grad all-reduce's pair brackets
    # dense by construction and would satisfy the check vacuously
    starts = {i.name: i for i in instrs
              if i.opcode in _EXCHANGE_OPS
              and i.opcode.endswith("-start")}
    pairs = spanning = 0
    dense_lines = sorted(i.line_no for i in dense)
    import bisect
    for i in instrs:
        if i.opcode.endswith("-done"):
            for op in i.operands:
                if op in starts:
                    pairs += 1
                    lo = starts[op].line_no
                    k = bisect.bisect_right(dense_lines, lo)
                    if k < len(dense_lines) and dense_lines[k] < i.line_no:
                        spanning += 1
                    break
    return OverlapReport(
        pull_exchanges=len(pulls), free_pull_exchanges=len(free),
        push_exchanges=len(pushes),
        committed_push_exchanges=len(committed), dense_nodes=len(dense),
        dense_waiting_on_exchange=len(waiting), async_pairs=pairs,
        async_pairs_spanning_dense=spanning)


def check_overlap(hlo_text: str, label: str = "") -> OverlapReport:
    """Enforce the pipelined step's overlap contract; returns the report.

    * pull-scoped AND push-scoped exchange nodes both present: the
      prefetch pull and the push commit compiled into ONE program (the
      fused schedule exists at all);
    * >= 1 *free* pull-scoped exchange (no dense dependency): the
      prefetch index/key legs are schedulable concurrently with the
      dense dots — a forced dense->prefetch dependency (the
      serialization regression) taints every pull leg and fails here;
    * >= 1 push-scoped exchange downstream of the dense grads: the push
      commits inside the program — the version barrier that keeps the
      plane bit-identical was not optimized away;
    * NO dense node downstream of an exchange: the dense compute reads
      the prefetched row buffer, never this program's exchange — the
      serial schedule (dense waiting on its own pull) fails here;
    * on backends emitting async collective forms: every ``-start``
      pairs with a ``-done``, and at least one pair BRACKETS dense HLO
      in schedule order — overlap in the scheduled program, not just in
      the dependence structure.
    """
    r = analyze_overlap(hlo_text)
    where = f"{label}: " if label else ""
    if r.dense_nodes < 1:
        raise ContractViolation(
            f"{where}no dense dot/convolution in the step program — the "
            f"overlap audit has nothing to overlap against ({r})")
    if r.pull_exchanges < 1 or r.push_exchanges < 1:
        raise ContractViolation(
            f"{where}prefetch pull and push must both ride ONE step "
            f"program (pull={r.pull_exchanges}, "
            f"push={r.push_exchanges} exchange nodes) ({r})")
    if r.free_pull_exchanges < 1:
        raise ContractViolation(
            f"{where}every pull-scoped exchange collective depends on "
            f"the dense compute — the prefetch was serialized behind "
            f"the dots (forced dependency?) and cannot overlap ({r})")
    if r.committed_push_exchanges < 1:
        raise ContractViolation(
            f"{where}no push-scoped exchange depends on the dense grads "
            f"— the push commit is missing from the step program ({r})")
    if r.dense_waiting_on_exchange:
        raise ContractViolation(
            f"{where}{r.dense_waiting_on_exchange} dense node(s) wait on "
            f"an exchange collective — the dense compute must consume "
            f"the prefetched row buffer, not this program's pull ({r})")
    if r.async_pairs and r.async_pairs_spanning_dense < 1:
        raise ContractViolation(
            f"{where}async collective pairs present but none brackets "
            f"dense HLO in schedule order — the scheduler serialized "
            f"the exchange ({r})")
    return r


# --- peak-temp-bytes audit (the memory-level copy check) ---------------------

# calibrated against the shipped planes on the cpu8 mesh (graftwatch
# memory ledger, vocab sized so a table shard dwarfs batch scratch):
# batch scratch covers index widening / sort perms / routed buckets
# (scales with the stream AND the shard count on the owner-dispatch
# paths), the state term covers the one legitimate state materialization
# a DECLINED donation forces (CPU never aliases; on TPU alias_bytes
# covers the state and the term collapses)
TEMP_FLOOR_BYTES = 1 << 18
TEMP_BATCH_FACTOR = 2
TEMP_STATE_SLACK = 1.1
# a whole STEP program holds several exchange pipelines' scratch live at
# once (one pull + one push per sparse variable, vs the single pipeline
# a pull/push program audits); its batch term scales by the pipeline
# count at a tighter per-pipeline factor (calibrated on the cpu8
# pipelined deepfm step: 8 pipelines, temp ~10.7 scratch units)
TEMP_STEP_PIPELINE_FACTOR = 1.5


def peak_temp_bound(params: Mapping[str, int], program: str,
                    alias_bytes: int = 0) -> int:
    """Allowed compiled temp bytes for one plane program.

    Pull programs are read-only: temp must stay batch-scale scratch. A
    push/step program whose donation the backend declined legitimately
    materializes the updated state once in temp — that is the
    ``state_shard_bytes - alias_bytes`` term. Anything beyond is an
    accidental extra materialization (a table-shard-sized gather or a
    second state copy) — the memory-level twin of :func:`max_copy_bytes`.
    Like that audit, detection power depends on the harness sizing the
    table so one shard dwarfs batch scratch (``memwatch.AUDIT_VOCAB``).
    """
    unit = int(params["global_batch"]) * (int(params["dim"]) + 2) \
        * int(params.get("itemsize", 4)) \
        * int(params.get("num_shards", 1))
    if program == "step":
        scratch = int(TEMP_STEP_PIPELINE_FACTOR
                      * int(params.get("num_exchange_pipelines", 2))
                      * unit)
    else:
        scratch = TEMP_BATCH_FACTOR * unit
    bound = TEMP_FLOOR_BYTES + scratch
    if program != "pull":
        unaliased = max(0, int(params.get("state_shard_bytes", 0))
                        - int(alias_bytes))
        bound += int(TEMP_STATE_SLACK * unaliased)
    # a pipelined step earns EXACTLY one extra pulled-row buffer (the
    # prefetched double buffer, batch-scale; the harness passes the
    # primed buffer's byte size in pipeline_rows_bytes) plus — on a
    # backend that does not alias in place — ONE weights-shard
    # materialization per pipelined table (the version barrier's cost:
    # the push-updated weights live in temp between the in-place update
    # and the prefetch's read; measured +1 shard/table vs the serial
    # step on cpu8). step_weight_shards caps that count; anything past
    # it is an accidental extra table-sized buffer and busts the bound.
    bound += int(TEMP_STATE_SLACK
                 * (int(params.get("pipeline_rows_bytes", 0))
                    + int(params.get("step_weight_shards", 0))
                    * int(params.get("table_shard_bytes", 0))))
    return bound


def check_peak_temp_bytes(mem: Mapping[str, int], params: Mapping[str, int],
                          *, program: str, label: str = "") -> int:
    """Audit one compiled program's ``memory_analysis`` temp bytes
    against :func:`peak_temp_bound`; returns the bound. ``mem`` is the
    normalized dict from ``utils.jaxcompat.compiled_memory_stats``.
    Complements :func:`max_copy_bytes`: a materialization XLA performs
    without an explicit ``copy`` op (fusion output buffers, gather
    results) never shows in the HLO-text audit but always lands in
    temp."""
    temp = int(mem.get("temp_bytes", 0))
    bound = peak_temp_bound(params, program,
                            int(mem.get("alias_bytes", 0)))
    if temp > bound:
        raise ContractViolation(
            f"{label or program}: compiled temp allocation of {temp} "
            f"bytes > peak-temp bound {bound} (params {dict(params)}, "
            f"alias_bytes={mem.get('alias_bytes', 0)}) — an accidental "
            "table-shard-sized materialization (or a second state copy) "
            "is live inside the program")
    return bound


# --- the per-plane registry --------------------------------------------------

# A bound is a function of the program's static parameters. Every bound
# receives the same params dict; the keys each plane consumes:
#   batch_slice  entries per data-axis slice (global_batch / data axis)
#   global_batch entries in the whole batch
#   dim          embedding dim
#   itemsize     row element bytes (4 for f32)
#   cache_k      hot-row replica slots ("a2a+cache" only)
#   num_shards   table shards (= mesh size on the a2a planes)
Bound = Callable[[Mapping[str, int]], int]


def _row_assembly(p: Mapping[str, int]) -> int:
    # each data slice's pulled rows returned to its model-axis peers
    return int(p["batch_slice"] * p["dim"] * p["itemsize"]
               * ROW_ASSEMBLY_SLACK)


def _wire(p: Mapping[str, int]) -> int:
    # per-element bytes of ROW/GRAD payload on the wire: the compressed
    # planes' params carry wire_itemsize (2 = bf16, 1 = int8); absent
    # (uncompressed planes) it equals the storage itemsize
    return int(p.get("wire_itemsize", p["itemsize"]))


def _row_assembly_wire(p: Mapping[str, int]) -> int:
    # compressed pull: the row-assembly gather moves WIRE-dtype rows
    return int(p["batch_slice"] * p["dim"] * _wire(p)
               * ROW_ASSEMBLY_SLACK)


def _global_prereduce_wire(p: Mapping[str, int]) -> int:
    # compressed push overflow fallback: grads gather at wire width,
    # keys/scales/counts gather as separate int32/pair buffers — the
    # +8 covers the widest of those per entry
    return int(p["global_batch"] * (p["dim"] * _wire(p) + 8)
               * ROW_ASSEMBLY_SLACK)


def _global_prereduce(p: Mapping[str, int]) -> int:
    # the push overflow fallback all_gathers every peer's pre-reduced
    # slice: O(global_batch * dim) — paid only when structured key skew
    # overflows the routed buckets, but the branch is compiled in
    return int(p["global_batch"] * (p["dim"] + 2) * p["itemsize"]
               * ROW_ASSEMBLY_SLACK)


def _cache_psum(p: Mapping[str, int]) -> int:
    # the K-row (grad sum, count) merge — O(cache_k * dim), batch-free
    return int((p["cache_k"] + 1) * (p["dim"] + 1) * p["itemsize"]
               * ROW_ASSEMBLY_SLACK)


def _scalar(p: Mapping[str, int]) -> int:
    # residue-loop pending counts / overflow flags: a few scalars
    return 256


def _batch_rows(p: Mapping[str, int]) -> int:
    # psum-plane pull: rows for this device's batch slice, psum'd over
    # the model axis — the plane's O(batch_slice * dim) broadcast cost
    return int(p["batch_slice"] * (p["dim"] + 1) * p["itemsize"]
               * ROW_ASSEMBLY_SLACK)


def _global_batch_rows(p: Mapping[str, int]) -> int:
    # psum-plane push: the full global batch gathered to every shard —
    # the O(global_batch * dim) signature the a2a plane exists to kill
    return int(p["global_batch"] * (p["dim"] + 2) * p["itemsize"]
               * ROW_ASSEMBLY_SLACK)


def _grouped_a2a_ops(p: Mapping[str, int]) -> int:
    # THE grouped-plane claim: the collective launch count is
    # O(#groups), not O(#tables). ``a2a_ops_per_exchange`` is counted
    # empirically from a single-table a2a program on the same mesh
    # (programs.count_exchange_a2a) — a per-table loop would compile
    # num_tables * that many all-to-alls and fail this cap.
    return int(p["num_groups"] * p["a2a_ops_per_exchange"])


def _grouped_row_assembly(p: Mapping[str, int]) -> int:
    # grouped pull re-assembly: the concatenated stream carries every
    # member table's entries at the group's padded bucket dim
    return int(p["num_tables"] * p["batch_slice"] * p["dim_bucket"]
               * p["itemsize"] * ROW_ASSEMBLY_SLACK)


def _grouped_prereduce(p: Mapping[str, int]) -> int:
    # grouped push overflow fallback: every peer's pre-reduced
    # concatenated slice — entries gain up to 3 key words (lo, hi, tag)
    # next to the padded-dim grad row
    return int(p["num_tables"] * p["global_batch"] * (p["dim_bucket"] + 4)
               * p["itemsize"] * ROW_ASSEMBLY_SLACK)


@dataclasses.dataclass(frozen=True)
class OpBudget:
    """Inventory entry for one collective op within one program."""

    min_count: int = 0
    # static cap, or a Bound of the program params (the grouped plane's
    # cap is num_groups * per-exchange ops — param-dependent)
    max_count: Optional[Any] = None
    max_buffer: Optional[Bound] = None   # bound on the largest single buffer
    # bound on the SUMMED bytes across all ops of this type: catches a
    # regression that splits O(global) traffic into many small buffers
    # (e.g. one per-table gather each below the single-buffer bound)
    max_total: Optional[Bound] = None


@dataclasses.dataclass(frozen=True)
class ProgramContract:
    """Declarative contract for one (plane, program) compiled HLO."""

    plane: str
    program: str                      # "pull" | "push" | "step"
    ops: Mapping[str, OpBudget] = dataclasses.field(default_factory=dict)
    forbid: Tuple[str, ...] = ()
    no_f64: bool = True
    no_host_transfers: bool = True
    min_aliased: int = 0              # donation floor (step programs)
    overlap: bool = False             # enforce :func:`check_overlap`
    # compressed planes: exchange bytes <= byte_ratio x the baseline
    # plane's compiled program (enforced by check_compressed_program,
    # which needs BOTH HLO texts; check() alone cannot see the baseline)
    baseline_plane: Optional[str] = None
    byte_ratio: Optional[float] = None

    def check(self, hlo_text: str,
              params: Mapping[str, int]) -> Dict[str, Tuple[int, int]]:
        """Audit ``hlo_text`` against this contract; returns the
        collective summary. Raises :class:`ContractViolation`."""
        # one parse: summary and per-op largest buffer both derive from it
        collected = collect_collectives(hlo_text)
        summary: Dict[str, Tuple[int, int]] = {}
        largest: Dict[str, int] = {}
        # per-op sum of each instance's LARGEST buffer: async -start
        # tuples carry operand AND result, so summing all buffers
        # (summary's total) would double-count on async backends; the
        # largest single buffer equals the result for both sync and
        # async forms, and its sum still exposes O(table) traffic split
        # across many individually-small buffers
        big_sum: Dict[str, int] = {}
        for op, b, big in collected:
            c, t = summary.get(op, (0, 0))
            summary[op] = (c + 1, t + b)
            largest[op] = max(largest.get(op, 0), big)
            big_sum[op] = big_sum.get(op, 0) + big
        label = f"{self.plane}/{self.program}"
        for op in self.forbid:
            if op in summary:
                raise ContractViolation(
                    f"{label}: forbidden collective {op!r} present "
                    f"(inventory: {summary})")
        for op, budget in self.ops.items():
            count = summary.get(op, (0, 0))[0]
            if count < budget.min_count:
                raise ContractViolation(
                    f"{label}: expected >= {budget.min_count} {op!r} "
                    f"op(s), found {count} (inventory: {summary}) — the "
                    "plane's exchange structure is gone")
            if budget.max_count is not None:
                cap = budget.max_count(params) if callable(budget.max_count) \
                    else budget.max_count
                if count > cap:
                    raise ContractViolation(
                        f"{label}: {count} {op!r} op(s) > allowed {cap} "
                        f"(inventory: {summary}, params {dict(params)})")
            if budget.max_buffer is not None and op in largest:
                bound = budget.max_buffer(params)
                if largest[op] > bound:
                    raise ContractViolation(
                        f"{label}: {op!r} buffer of {largest[op]} bytes "
                        f"> bound {bound} (params "
                        f"{dict(params)}) — O(global_batch)/O(table) "
                        "traffic has reappeared")
            if budget.max_total is not None and op in big_sum:
                bound = budget.max_total(params)
                total = big_sum[op]
                if total > bound:
                    raise ContractViolation(
                        f"{label}: {op!r} ops total {total} bytes "
                        f"> bound {bound} (params {dict(params)}) — "
                        "O(global_batch)/O(table) traffic has reappeared "
                        "split across buffers")
        if self.no_f64:
            check_no_f64(hlo_text)
        if self.no_host_transfers:
            check_no_host_transfers(hlo_text)
        if self.min_aliased:
            check_donation(hlo_text, self.min_aliased)
        if self.overlap:
            check_overlap(hlo_text, label)
        return summary


REGISTRY: Dict[Tuple[str, str], ProgramContract] = {}


def _register(c: ProgramContract) -> ProgramContract:
    REGISTRY[(c.plane, c.program)] = c
    return c


# The a2a planes: owner exchange present, all-gather bounded by the row
# re-assembly, all-reduce bounded by residue-loop scalars (pull) or the
# K-row cache merge (cached push). The psum plane: NO all-to-all (that's
# the point of the ablation), all-reduce/all-gather carry the
# broadcast-style O(batch) signatures — inventoried so the baseline's
# own shape is pinned too.
_register(ProgramContract(
    plane="a2a", program="pull",
    ops={"all-to-all": OpBudget(min_count=1),
         "all-gather": OpBudget(max_buffer=_row_assembly),
         "all-reduce": OpBudget(max_buffer=_scalar)}))
_register(ProgramContract(
    plane="a2a", program="push",
    ops={"all-to-all": OpBudget(min_count=1),
         "all-gather": OpBudget(max_buffer=_global_prereduce),
         "all-reduce": OpBudget(max_buffer=_scalar)}))
_register(ProgramContract(
    plane="a2a+cache", program="pull",
    ops={"all-to-all": OpBudget(min_count=1),
         "all-gather": OpBudget(max_buffer=_row_assembly),
         "all-reduce": OpBudget(max_buffer=_scalar)}))
_register(ProgramContract(
    plane="a2a+cache", program="push",
    ops={"all-to-all": OpBudget(min_count=1),
         "all-gather": OpBudget(max_buffer=_global_prereduce),
         "all-reduce": OpBudget(max_buffer=_cache_psum)}))
# The grouped plane: its EXTRA promise over plain a2a is the collective
# LAUNCH COUNT — one exchange set per GROUP of same-shape tables, never
# one per table (params carry num_groups and the empirically-counted
# per-exchange op count; a per-table-loop regression multiplies the
# all-to-all inventory by num_tables and fails the cap).
_register(ProgramContract(
    plane="a2a+grouped", program="pull",
    ops={"all-to-all": OpBudget(min_count=1, max_count=_grouped_a2a_ops),
         # max_total (not just max_buffer): a broken output annotation
         # re-gathers each table's rows in a SEPARATE buffer, each below
         # the concatenated-stream bound — the sum is what gives it away
         "all-gather": OpBudget(max_buffer=_grouped_row_assembly,
                                max_total=_grouped_row_assembly),
         "all-reduce": OpBudget(max_buffer=_scalar)}))
_register(ProgramContract(
    plane="a2a+grouped", program="push",
    ops={"all-to-all": OpBudget(min_count=1, max_count=_grouped_a2a_ops),
         "all-gather": OpBudget(max_buffer=_grouped_prereduce,
                                max_total=_grouped_prereduce),
         "all-reduce": OpBudget(max_buffer=_scalar)}))
# The pipelined plane: per-table pull/push entry points run the PLAIN
# a2a programs (pipelining only changes the Trainer's step schedule) so
# they inherit a2a's exchange contract verbatim; the plane's own promise
# — dense never waits on an exchange, prefetch legs schedulable under
# the dots, push committed in-program — is the STEP program's overlap
# contract below.
_register(ProgramContract(
    plane="a2a+pipelined", program="pull",
    ops={"all-to-all": OpBudget(min_count=1),
         "all-gather": OpBudget(max_buffer=_row_assembly),
         "all-reduce": OpBudget(max_buffer=_scalar)}))
_register(ProgramContract(
    plane="a2a+pipelined", program="push",
    ops={"all-to-all": OpBudget(min_count=1),
         "all-gather": OpBudget(max_buffer=_global_prereduce),
         "all-reduce": OpBudget(max_buffer=_scalar)}))
_register(ProgramContract(
    plane="a2a+pipelined", program="step",
    min_aliased=1, overlap=True))
# The compressed-exchange planes (parallel/precision.py): same owner
# exchange as a2a, but the row/grad payloads cross the wire narrowed —
# bf16 rows both directions ("a2a+bf16"), or bf16 pull + per-row-scale
# int8 error-feedback push ("a2a+int8"). Two teeth per program: (1) the
# inventory bounds below, with the all-gather legs bounded at the WIRE
# itemsize (an f32 row-assembly gather under a compressed contract
# busts _row_assembly_wire — the "f32 plane registered as compressed"
# negative); (2) the byte-halving ratio vs the f32 baseline's compiled
# program, enforced by check_compressed_program/graftcheck. The ratio
# binds at the audit shape (dim >= 32): keys/counts stay int32, so
# total-bytes/f32 asymptotes to 0.5 as dim grows and crosses 0.55 from
# above near dim 16 — the audit pins dim 64, where pull ≈ 0.51 and
# int8 push ≈ 0.30.
_register(ProgramContract(
    plane="a2a+bf16", program="pull",
    baseline_plane="a2a", byte_ratio=COMPRESSED_BYTE_RATIO,
    ops={"all-to-all": OpBudget(min_count=1),
         "all-gather": OpBudget(max_buffer=_row_assembly_wire),
         "all-reduce": OpBudget(max_buffer=_scalar)}))
_register(ProgramContract(
    plane="a2a+bf16", program="push",
    baseline_plane="a2a", byte_ratio=COMPRESSED_BYTE_RATIO,
    ops={"all-to-all": OpBudget(min_count=1),
         "all-gather": OpBudget(max_buffer=_global_prereduce_wire),
         "all-reduce": OpBudget(max_buffer=_scalar)}))
# "a2a+int8" pulls ride the bf16 wire (the token selects exchange bf16
# + push int8_ef); its push payload is int8 with the f32 scales bitcast
# into the integer key/count exchange buffer
_register(ProgramContract(
    plane="a2a+int8", program="pull",
    baseline_plane="a2a", byte_ratio=COMPRESSED_BYTE_RATIO,
    ops={"all-to-all": OpBudget(min_count=1),
         "all-gather": OpBudget(max_buffer=_row_assembly_wire),
         "all-reduce": OpBudget(max_buffer=_scalar)}))
_register(ProgramContract(
    plane="a2a+int8", program="push",
    baseline_plane="a2a", byte_ratio=COMPRESSED_BYTE_RATIO,
    ops={"all-to-all": OpBudget(min_count=1),
         "all-gather": OpBudget(max_buffer=_global_prereduce_wire),
         "all-reduce": OpBudget(max_buffer=_scalar)}))
_register(ProgramContract(
    plane="psum", program="pull",
    forbid=("all-to-all",),
    ops={"all-reduce": OpBudget(min_count=1, max_buffer=_batch_rows)}))
_register(ProgramContract(
    plane="psum", program="push",
    forbid=("all-to-all",),
    ops={"all-gather": OpBudget(min_count=1,
                                max_buffer=_global_batch_rows)}))
# the whole train step: cross-cutting only (its collective inventory is
# the union of its planes' + the dense-grad all-reduce); what the step
# must prove is donation (tables updated in place) and host purity
_register(ProgramContract(plane="any", program="step", min_aliased=1))


# --- the per-plane COST registry (graftplan) ---------------------------------

# Every plane above also declares its cost terms here, next to its HLO
# contract, so a new plane is automatically *plannable* the day it is
# registered (ROADMAP item 5) instead of becoming hand-tuning folklore.
# Two different kinds of number live in one PlaneSpec:
#
# * ``exchange_bytes`` — the per-device wire bytes of the COMPILED
#   pull/push program as a closed form over the lowering params
#   (global_batch, dim, itemsize, wire_itemsize, num_tables,
#   dim_bucket). These are audited: ``tools.graftcheck``'s cost-audit
#   section lowers every plane and fails if a declaration disagrees
#   with ``exchange_collective_bytes`` of the real HLO by more than
#   :data:`COST_MODEL_TOLERANCE`. The forms are calibrated in the
#   contract-audit regime (batch >= 512; at smaller shapes XLA elides
#   the residue/overflow legs and the small additive terms drift).
# * planner-only terms — ``workload_factor`` (how observed
#   unique_ratio / key_skew / cache hit-ratio scale the EFFECTIVE
#   cost; the compiled program is static, the workload is not),
#   ``launches`` (collective launch count per program — the per-launch
#   overhead proxy), ``hbm_overhead_bytes`` (resident bytes the plane
#   costs beyond the table shards). These feed ``analysis/plan.py``
#   and are NOT HLO-auditable; they are documented estimates.
#
# ``wire_ops`` names which collective ops carry the plane's exchange:
# the a2a family moves payload on all-to-all/all-gather (scalar
# all-reduces excluded, as in the byte-halving audit); the psum
# baseline's pull cost IS its all-reduce broadcast, so its spec widens
# the op set — the audit then compares against the same accounting.

COST_MODEL_TOLERANCE = 0.10
PSUM_WIRE_OPS = ("all-to-all", "all-gather", "all-reduce")


def _a2a_pull_bytes(p: Mapping[str, Any]) -> int:
    # row re-assembly gather (batch * dim * itemsize) + two int32
    # index/offset exchanges + residue-round scalars
    return int(p["global_batch"] * (p["dim"] * p["itemsize"] + 8) + 256)


def _a2a_push_bytes(p: Mapping[str, Any]) -> int:
    # grad+count prereduce gather ((dim+1) words) + one int32 key
    # exchange + residue scalars
    return int(p["global_batch"]
               * ((p["dim"] + 1) * p["itemsize"] + 4) + 256)


def _compressed_pull_bytes(p: Mapping[str, Any]) -> int:
    # rows cross at the wire width; ONE int32 index exchange (the key
    # leg rides the compressed payload)
    return int(p["global_batch"] * (p["dim"] * _wire(p) + 4) + 256)


def _bf16_push_bytes(p: Mapping[str, Any]) -> int:
    # bf16 grads + int32 keys on the gather, narrow a2a legs
    return int(p["global_batch"] * (p["dim"] * _wire(p) + 6) + 256)


def _int8_push_bytes(p: Mapping[str, Any]) -> int:
    # int8 grads + per-row f32 scale + int32 keys (+8), plus the
    # int8-width a2a leg (+wire)
    return int(p["global_batch"]
               * (p["dim"] * _wire(p) + 8 + _wire(p)) + 384)


def _psum_pull_bytes(p: Mapping[str, Any]) -> int:
    # the broadcast-style baseline: one O(batch * dim) all-reduce
    return int(p["global_batch"] * p["dim"] * p["itemsize"])


def _psum_push_bytes(p: Mapping[str, Any]) -> int:
    # full global batch gathered to every shard — the O(global) cost
    # the a2a plane exists to kill
    return int(p["global_batch"] * (p["dim"] + 1) * p["itemsize"])


def _grouped_pull_bytes(p: Mapping[str, Any]) -> int:
    # concatenated stream: every member table at the padded bucket dim
    return int(p["num_tables"] * p["global_batch"]
               * (p["dim_bucket"] * p["itemsize"] + 4) + 384)


def _grouped_push_bytes(p: Mapping[str, Any]) -> int:
    return int(p["num_tables"] * p["global_batch"]
               * ((p["dim_bucket"] + 1) * p["itemsize"] + 4) + 384)


def _unit_factor(stats: Mapping[str, Any]) -> float:
    # the compiled exchange moves the FULL index stream — dedup happens
    # host-side on the serving path, not in the device program
    return 1.0


def _cache_factor(stats: Mapping[str, Any]) -> float:
    # hot rows served from the replicated K-row cache skip the owner
    # exchange payload; the index legs still cross. Floor keeps the
    # model honest when the scraped hit ratio is noisy.
    hit = float(stats.get("cache_hit_ratio", 0.0))
    return max(0.15, 1.0 - hit)


def _no_overhead(p: Mapping[str, Any]) -> int:
    return 0


def _cache_hbm(p: Mapping[str, Any]) -> int:
    # K replicated hot rows + their grad-merge slot, per device
    return int(p.get("cache_k", 128) * (p["dim"] + 1) * p["itemsize"])


def _pipelined_hbm(p: Mapping[str, Any]) -> int:
    # the prefetched double buffer: one extra pulled-row batch resident
    return int(p["global_batch"] * p["dim"] * p["itemsize"])


def _grouped_hbm(p: Mapping[str, Any]) -> int:
    # bucket-padding waste across the concatenated stream
    return int(p["num_tables"] * p["global_batch"]
               * max(0, p["dim_bucket"] - p["dim"]) * p["itemsize"])


@dataclasses.dataclass(frozen=True)
class PlaneSpec:
    """Declared cost model for one exchange plane (graftplan).

    ``exchange_bytes`` maps program -> declared per-device wire bytes
    (audited against compiled HLO by the graftcheck cost-audit);
    ``launches`` maps program -> collective launch count at the audit
    shape; ``workload_factor`` scales the effective exchange cost by
    observed workload stats; ``hbm_overhead_bytes`` is the plane's
    resident-memory overhead beyond the table shards;
    ``host_step_units`` is a relative host-side CPU dispatch cost per
    step (per-table program dispatches the host must issue).
    """

    plane: str
    exchange_bytes: Mapping[str, Bound]
    launches: Mapping[str, int]
    wire_ops: Tuple[str, ...] = EXCHANGE_BYTE_OPS
    workload_factor: Callable[[Mapping[str, Any]], float] = _unit_factor
    hbm_overhead_bytes: Bound = _no_overhead
    host_step_units: float = 1.0


PLANE_SPECS: Dict[str, PlaneSpec] = {}


def _register_spec(s: PlaneSpec) -> PlaneSpec:
    PLANE_SPECS[s.plane] = s
    return s


_register_spec(PlaneSpec(
    plane="a2a",
    exchange_bytes={"pull": _a2a_pull_bytes, "push": _a2a_push_bytes},
    launches={"pull": 7, "push": 5}))
_register_spec(PlaneSpec(
    plane="a2a+cache",
    exchange_bytes={"pull": _a2a_pull_bytes, "push": _a2a_push_bytes},
    launches={"pull": 7, "push": 7},
    workload_factor=_cache_factor, hbm_overhead_bytes=_cache_hbm))
_register_spec(PlaneSpec(
    plane="a2a+grouped",
    exchange_bytes={"pull": _grouped_pull_bytes,
                    "push": _grouped_push_bytes},
    # THE grouped claim priced in: launch count is per GROUP, so the
    # per-step host dispatch cost stays ~one table's worth
    launches={"pull": 7, "push": 5},
    hbm_overhead_bytes=_grouped_hbm, host_step_units=0.5))
_register_spec(PlaneSpec(
    plane="a2a+pipelined",
    exchange_bytes={"pull": _a2a_pull_bytes, "push": _a2a_push_bytes},
    launches={"pull": 7, "push": 5},
    hbm_overhead_bytes=_pipelined_hbm,
    # the fused step hides exchange latency under the dense compute —
    # modelled as a host/launch discount, not a byte discount
    host_step_units=0.75))
_register_spec(PlaneSpec(
    plane="a2a+bf16",
    exchange_bytes={"pull": _compressed_pull_bytes,
                    "push": _bf16_push_bytes},
    launches={"pull": 7, "push": 5}))
_register_spec(PlaneSpec(
    plane="a2a+int8",
    exchange_bytes={"pull": _compressed_pull_bytes,
                    "push": _int8_push_bytes},
    launches={"pull": 7, "push": 6}))
_register_spec(PlaneSpec(
    plane="psum",
    exchange_bytes={"pull": _psum_pull_bytes, "push": _psum_push_bytes},
    launches={"pull": 1, "push": 2},
    wire_ops=PSUM_WIRE_OPS))

# completeness: every plane with a registered pull/push contract MUST
# carry a cost declaration — a new plane that forgets one fails at
# import, not at planning time
for _plane, _prog in REGISTRY:
    if _prog in ("pull", "push") and _plane not in PLANE_SPECS:
        raise AssertionError(
            f"plane {_plane!r} has a ProgramContract but no PlaneSpec "
            "cost declaration — register one next to its contract so "
            "graftplan can price it")


def declared_exchange_bytes(plane: str, program: str,
                            params: Mapping[str, Any]) -> int:
    """The PlaneSpec-declared wire bytes of one (plane, program) at
    ``params`` — the number the graftcheck cost-audit holds against
    the compiled HLO."""
    spec = PLANE_SPECS.get(plane)
    if spec is None or program not in spec.exchange_bytes:
        raise KeyError(f"no PlaneSpec cost declaration for "
                       f"({plane!r}, {program!r}); known: "
                       f"{sorted(PLANE_SPECS)}")
    return int(spec.exchange_bytes[program](params))


def check_cost_model(hlo_text: str, plane: str, program: str,
                     params: Mapping[str, Any], *,
                     tolerance: float = COST_MODEL_TOLERANCE,
                     spec: Optional[PlaneSpec] = None
                     ) -> Dict[str, Any]:
    """Audit one plane's declared exchange bytes against its compiled
    HLO: |declared - actual| must stay within ``tolerance`` of the
    actual ``exchange_collective_bytes`` over the spec's wire ops.
    ``spec`` overrides the registered one (the negative tests inject a
    deliberately-wrong declaration). Returns the comparison; raises
    :class:`ContractViolation` on disagreement."""
    spec = spec if spec is not None else PLANE_SPECS.get(plane)
    if spec is None or program not in spec.exchange_bytes:
        raise KeyError(f"no PlaneSpec cost declaration for "
                       f"({plane!r}, {program!r})")
    declared = int(spec.exchange_bytes[program](params))
    actual = exchange_collective_bytes(hlo_text, ops=spec.wire_ops)
    scale = max(actual, 1)
    err = abs(declared - actual) / scale
    if err > tolerance:
        raise ContractViolation(
            f"{plane}/{program}: declared exchange cost {declared} B "
            f"disagrees with compiled HLO {actual} B by "
            f"{err * 100:.1f}% > {tolerance * 100:.0f}% "
            f"(params {dict(params)}) — the PlaneSpec cost model is "
            "stale; recalibrate the declaration next to the plane's "
            "contract")
    return {"plane": plane, "program": program, "declared": declared,
            "actual": actual, "rel_err": err, "tolerance": tolerance}


def check_program(hlo_text: str, plane: str, program: str,
                  **params) -> Dict[str, Tuple[int, int]]:
    """Audit one compiled program against its registered contract.

    ``params``: batch_slice, global_batch, dim, itemsize (default 4),
    cache_k (cached plane), num_shards — whatever the plane's bounds
    consume. Returns the collective summary; raises
    :class:`ContractViolation` on any breach.
    """
    key = (plane, program)
    if key not in REGISTRY:
        raise KeyError(f"no contract registered for {key}; known: "
                       f"{sorted(REGISTRY)}")
    params.setdefault("itemsize", 4)
    if program == "push" and "global_batch" not in params:
        # never guess it from batch_slice: on a data>1 mesh that
        # understates the overflow-fallback bound and raises spurious
        # violations (programs.contract_params supplies both)
        raise KeyError(
            "push contracts need global_batch (the overflow-fallback "
            "all-gather is O(global_batch * dim)); pass it explicitly "
            "or use analysis.programs.contract_params")
    return REGISTRY[key].check(hlo_text, params)


def check_compressed_program(hlo_text: str, baseline_hlo: str, plane: str,
                             program: str, **params) -> Dict[str, Any]:
    """Full audit of one COMPRESSED plane program: its registered
    inventory contract (wire-width byte bounds) PLUS the byte-halving
    ratio against the f32 baseline's compiled HLO. ``baseline_hlo``
    must be the registered ``baseline_plane``'s program lowered at the
    same mesh/batch/dim. Returns a summary dict; raises
    :class:`ContractViolation` on any breach."""
    summary = check_program(hlo_text, plane, program, **params)
    contract = REGISTRY[(plane, program)]
    if contract.byte_ratio is None or contract.baseline_plane is None:
        raise KeyError(
            f"({plane}, {program}) is not a compressed contract — no "
            "byte_ratio/baseline_plane registered")
    got, base = check_byte_halving(
        hlo_text, baseline_hlo, ratio=contract.byte_ratio,
        label=f"{plane}/{program} vs {contract.baseline_plane}")
    return {"collectives": summary, "exchange_bytes": got,
            "baseline_bytes": base, "ratio": got / base,
            "max_ratio": contract.byte_ratio}


# --- the original hlocheck entry point (kept verbatim for callers) -----------

def check_a2a_pull_hlo(hlo_text: str, *, batch_slice: int, dim: int,
                       itemsize: int = 4) -> Dict[str, Tuple[int, int]]:
    """Enforce the a2a pull program's ICI contract; returns the summary.

    * >= 1 ``all-to-all`` (the owner exchange actually compiled in — if
      XLA or a plane regression replaced it with broadcast-style
      collectives, the plane's whole point is gone);
    * every ``all-gather`` result is bounded by the ROW-ASSEMBLY size
      ``batch_slice * dim * itemsize`` (+6.25% partitioner padding slack):
      the one legitimate gather returns each data-slice's pulled rows to
      its model-axis peers. A table-sized or global-batch-sized gather
      (the psum plane's O(global_batch * dim) signature) fails here.
    """
    summary = summarize(hlo_text)
    if "all-to-all" not in summary:
        raise AssertionError(
            "a2a pull program compiled WITHOUT an all-to-all — the owner "
            f"exchange is gone (collectives: {summary})")
    bound = int(batch_slice * dim * itemsize * ROW_ASSEMBLY_SLACK)
    for op, _total, largest in collect_collectives(hlo_text):
        if op == "all-gather" and largest > bound:
            raise AssertionError(
                f"a2a pull program contains an all-gather buffer of "
                f"{largest} bytes > row-assembly bound {bound} "
                f"(batch_slice={batch_slice}, dim={dim}) — "
                "O(global_batch)/O(table) traffic has reappeared on the "
                "pull path")
    return summary
