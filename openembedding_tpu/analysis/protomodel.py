"""graftproto: exhaustive protocol model checking for the host protocols.

The durability and HA protocols rebuilt from the reference — the delta-
checkpoint chain with its background compactor (``checkpoint_delta.py``),
strict-seq serving hot-swap (``serving/registry.py apply_delta``), the
``DirtyTracker`` claim discipline (``dirty.py``), and the HA registry's
CREATING window under replica kills (``serving/ha.py``) — are concurrent
state machines whose bug class (torn tails, seq gaps, lost dirty marks,
mixed-version reads) hides in interleavings no example-based test
enumerates. This module is the fourth static-analysis leg beside
graftcheck/graftlint/graftrace: a small EXPLICIT-STATE model checker plus
faithful models of the shipped protocols (five today — the serving
lookup micro-batcher joined in the batched-serving round), explored
exhaustively.

Checker (stdlib-only, like :mod:`.concurrency`, so ``tools/graftproto.py``
loads it standalone):

* states are FLAT dicts of hashable values (ints, strs, tuples,
  frozensets) — frozen to sorted item-tuples for dedup;
* :class:`Action` = one named guarded atomic step of one process role;
  ``apply`` receives a fresh copy and returns one successor (mutate in
  place / return a dict) or several (return a list — nondeterministic
  outcomes like a write that may fail);
* :func:`check` runs BFS from the initial state with full state dedup, so
  the FIRST violation found has a minimal-length action trace;
* every invariant is checked at every reachable state; a state with no
  enabled action that ``is_done`` does not accept is a DEADLOCK;
* counterexamples pretty-print as an action trace with per-step state
  diffs (:func:`format_result`).

Model fidelity is the whole game, so the models are BRIDGED to the code
two ways: (1) every action carries the ``sync_point`` names
(``analysis/concurrency.py``) the real implementation emits at that
protocol step — :func:`missing_sync_points` greps the package source and
fails if a model references a point the code no longer has; (2)
:func:`trace_schedule` exports any explored trace (including every seeded
mutation's counterexample) as the ordered sync-point list a
``SerialSchedule``/``PointGate`` replay drives against the real
implementation (``tests/test_graftproto_replay.py``,
``tools/graftproto.py --emit-schedules``).

Scope and honesty — what is NOT modeled:

* multi-HOST elastic training (several trainers sharing one chain).
  Whole-process trainer crash + resume IS modeled now: the
  :func:`delta_chain` ``trainer_restart`` role (the graftchaos round)
  covers autosave -> SIGKILL -> ``fit(resume_from=)`` -> continue, with
  the resumed stream cursor re-derived from the committed manifest
  ``extra`` — closing the gap this section named since PR 11;
* unarmed (manifest-less) checkpoint directories — plain full dumps have
  no chain protocol to check (and the trainer_restart role accordingly
  treats a crash mid-full-save, before the re-arm, as unresumable);
* byte-level payload corruption beyond one torn tail per run (the
  ``tear`` budget), and chain/seq counts past the per-model bounds
  stated in each builder's docstring. Bounds are exhaustive WITHIN the
  budget, which is exactly the regime the hand-written interleaving
  tests sample one schedule of.

Two true positives surfaced while writing these models (both fixed in
the same PR, regression-tested in ``tests/test_delta_checkpoint.py``):
a full save over an armed chain re-armed with ``last_seq=0``, REUSING
burned seqs (serving replicas then ack the next real delta as stale and
silently stop updating — the :func:`delta_chain` ``full_save_resets_seq``
mutation is the pre-fix behavior), and ``applied_seq`` returned 0 after a
compaction emptied the chain (no content-version field in the manifest),
so freshly loaded serving models refused every subsequent delta as a gap
(the ``compact_zero_version`` mutation).
"""

from __future__ import annotations

import dataclasses
import os
import re
import time
from collections import deque
from typing import (Any, Callable, Dict, List, Optional,
                    Sequence, Tuple)

State = Dict[str, Any]
_CORRUPT = -99          # content marker: rows overwritten out of order


@dataclasses.dataclass(frozen=True)
class Action:
    """One named guarded atomic step of one process role.

    ``guard(state) -> bool`` reads a thawed state; ``apply(state)`` gets
    a FRESH copy it may mutate in place (return ``None``), replace
    (return a dict), or branch (return a list of dicts — each successor
    is labeled ``name#i``). ``syncs`` are the ``sync_point`` names the
    real implementation emits at this step (the model<->code bridge).
    """

    name: str
    role: str
    guard: Callable[[State], bool]
    apply: Callable[[State], Any]
    syncs: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class Model:
    name: str
    init: Tuple[Tuple[str, Any], ...]
    actions: Tuple[Action, ...]
    invariants: Tuple[Tuple[str, Callable[[State], bool]], ...]
    # accepting predicate for quiescent states: a state with NO enabled
    # action is a deadlock unless is_done(state)
    is_done: Callable[[State], bool]
    notes: str = ""

    def action(self, name: str) -> Action:
        for a in self.actions:
            if a.name == name:
                return a
        raise KeyError(name)


def make_model(name, init: State, actions, invariants, is_done,
               notes: str = "") -> Model:
    return Model(name=name, init=_freeze(init), actions=tuple(actions),
                 invariants=tuple(invariants), is_done=is_done,
                 notes=notes)


@dataclasses.dataclass
class Counterexample:
    kind: str                      # "invariant" | "deadlock" | "error"
    invariant: str                 # violated invariant name (or "")
    trace: List[Tuple[str, State]]  # [("<init>", s0), (action, s1), ...]


@dataclasses.dataclass
class Result:
    model: str
    ok: bool
    complete: bool                 # frontier exhausted under max_states
    explored: int
    transitions: int
    elapsed_s: float
    counterexample: Optional[Counterexample] = None


def _freeze(state: State) -> Tuple[Tuple[str, Any], ...]:
    """Flat dict of hashable values -> canonical hashable form. Raises
    on unhashable values — models must use ints/strs/tuples/frozensets,
    never lists/sets/dicts as values."""
    items = tuple(sorted(state.items()))
    hash(items)                    # fail fast on an unhashable value
    return items


def _violated(model: Model, state: State) -> Optional[str]:
    for name, pred in model.invariants:
        if not pred(state):
            return name
    return None


def _trace_of(parents, frozen) -> List[Tuple[str, State]]:
    steps = []
    cur = frozen
    while cur is not None:
        parent, label = parents[cur]
        steps.append((label or "<init>", dict(cur)))
        cur = parent
    steps.reverse()
    return steps


def _successors(model: Model, state: State):
    """Expand one thawed state: ``(enabled, [(label, successor), ...])``.

    The single home of the Action.apply return contract (None = mutated
    in place, dict = replacement, list = nondeterministic branches
    labeled ``name#i``) — check() and sample_traces() both walk through
    here so exported schedules can never diverge from what was checked.
    """
    enabled = False
    out = []
    for action in model.actions:
        if not action.guard(state):
            continue
        enabled = True
        succ = dict(state)
        ret = action.apply(succ)
        if ret is None:
            branches = [succ]
        elif isinstance(ret, dict):
            branches = [ret]
        else:
            branches = list(ret)
        for i, b in enumerate(branches):
            label = action.name if len(branches) == 1 \
                else f"{action.name}#{i}"
            out.append((label, b))
    return enabled, out


def check(model: Model, max_states: int = 500_000) -> Result:
    """Exhaustive BFS over the model's reachable states.

    Returns the first (minimal-trace) invariant violation or deadlock;
    ``complete=False`` means the ``max_states`` budget cut exploration
    short (the CLI treats that as a failure for shipped models — an
    unexplored protocol is an unchecked one)."""
    t0 = time.perf_counter()
    f0 = model.init
    parents: Dict[Any, Tuple[Any, Optional[str]]] = {f0: (None, None)}
    bad = _violated(model, dict(f0))
    if bad is not None:
        return Result(model.name, False, True, 1, 0,
                      time.perf_counter() - t0,
                      Counterexample("invariant", bad, _trace_of(parents, f0)))
    queue = deque([f0])
    explored = 0
    transitions = 0
    while queue:
        fs = queue.popleft()
        explored += 1
        state = dict(fs)
        enabled, succs = _successors(model, state)
        for label, succ in succs:
            fsucc = _freeze(succ)
            transitions += 1
            if fsucc in parents:
                continue
            parents[fsucc] = (fs, label)
            bad = _violated(model, succ)
            if bad is not None:
                return Result(model.name, False, True,
                              explored, transitions,
                              time.perf_counter() - t0,
                              Counterexample("invariant", bad,
                                             _trace_of(parents, fsucc)))
            if len(parents) >= max_states:
                return Result(model.name, True, False,
                              explored, transitions,
                              time.perf_counter() - t0)
            queue.append(fsucc)
        if not enabled and not model.is_done(state):
            return Result(model.name, False, True, explored, transitions,
                          time.perf_counter() - t0,
                          Counterexample("deadlock", "",
                                         _trace_of(parents, fs)))
    return Result(model.name, True, True, explored, transitions,
                  time.perf_counter() - t0)


def format_result(res: Result, model: Optional[Model] = None) -> str:
    """Human-readable verdict; counterexamples print the minimal action
    trace with per-step state diffs (and each action's sync points, so
    the trace reads as a replayable schedule)."""
    head = (f"[{res.model}] explored {res.explored} states / "
            f"{res.transitions} transitions in {res.elapsed_s:.2f}s")
    if res.ok and res.complete:
        return head + " — all invariants hold, no deadlock"
    if res.ok:
        return head + f" — INCOMPLETE (state budget hit)"
    cex = res.counterexample
    what = ("DEADLOCK (no enabled action, not an accepting state)"
            if cex.kind == "deadlock"
            else f"INVARIANT VIOLATED: {cex.invariant}")
    lines = [head + f" — {what}", "  counterexample "
             f"({len(cex.trace) - 1} steps):"]
    prev: State = {}
    for label, state in cex.trace:
        if label == "<init>":
            lines.append("    <init>")
            prev = state
            continue
        diff = [f"{k}: {prev.get(k)!r}->{v!r}"
                for k, v in sorted(state.items()) if prev.get(k) != v]
        syncs = ""
        if model is not None:
            base = label.split("#", 1)[0]
            try:
                pts = model.action(base).syncs
            except KeyError:
                pts = ()
            if pts:
                syncs = f"  [sync: {', '.join(pts)}]"
        lines.append(f"    {label}{syncs}  {{{'; '.join(diff)}}}")
        prev = state
    return "\n".join(lines)


def trace_schedule(model: Model,
                   trace: Sequence[Tuple[str, State]]) -> List[str]:
    """Flatten one action trace into the ordered ``sync_point`` list a
    SerialSchedule/PointGate replay drives against the real code."""
    out: List[str] = []
    for label, _state in trace:
        if label == "<init>":
            continue
        base = label.split("#", 1)[0]
        try:
            out.extend(model.action(base).syncs)
        except KeyError:
            pass
    return out


def model_sync_points(model: Model) -> List[str]:
    out = sorted({p for a in model.actions for p in a.syncs})
    return out


def missing_sync_points(model: Model,
                        package_root: Optional[str] = None) -> List[str]:
    """Sync points a model references that the package source does not
    emit — the fidelity tripwire: a refactor that renames or drops a
    ``sync_point`` invalidates the model, and this makes that loud."""
    if package_root is None:
        package_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
    have = set()
    for root, _dirs, names in os.walk(package_root):
        if "__pycache__" in root:
            continue
        for n in names:
            if not n.endswith(".py"):
                continue
            with open(os.path.join(root, n), "r", encoding="utf-8") as fh:
                have.update(re.findall(r'sync_point\(\s*[fr]?"([^"]+)"',
                                       fh.read()))
    return [p for p in model_sync_points(model) if p not in have]


# ---------------------------------------------------------------------------
# Model 1: serving hot-swap (registry.apply_delta vs snapshotting readers)
# ---------------------------------------------------------------------------

def hot_swap(*, seq_gate: bool = True, atomic_publish: bool = True,
             max_seq: int = 3, readers: int = 2) -> Model:
    """``ModelRegistry.apply_delta`` strict seq gating against concurrent
    snapshotting lookups (``ServingModel.lookup``).

    Two variables (vA, vB) stand for the per-variable rows one delta
    patches; the published model is the triple (vA, vB, version) and
    ``applied`` is the set of delta seqs whose rows the served states
    contain. Deltas 1..max_seq are all in flight at once (a retrying
    publisher can present any of them in any order, stale and gapped
    included). Readers snapshot the published pair then read it — the
    one-reference-grab discipline of ``ServingModel.lookup``.

    Invariants: readers never observe a mixed version; ``applied_seq``
    is monotone; a model at version v serves exactly the deltas
    ``{1..v}`` (a dropped gate silently loses the skipped delta's rows).

    Mutations: ``seq_gate=False`` removes the gap refusal (the seeded
    ``drop_seq_gate``); ``atomic_publish=False`` patches the two
    variables in place in two steps instead of building functionally and
    publishing one reference under the lock.
    """
    init: State = {"version": 0, "vA": 0, "vB": 0,
                   "applied": frozenset(), "pending":
                   frozenset(range(1, max_seq + 1)),
                   "build": 0, "monotone_ok": True,
                   "redeliver_left": 1}
    for i in range(readers):
        init[f"r{i}_pc"] = "idle"
        init[f"r{i}_snap"] = (0, 0)

    actions: List[Action] = []

    def redeliver(seq):
        # a retrying publisher re-presents an ALREADY-applied delta
        # (network retry / replica catch-up overlap) — this is what
        # makes the stale-ack branch reachable at all
        def guard(s):
            return s["redeliver_left"] > 0 and seq <= s["version"] \
                and seq not in s["pending"]

        def apply(s):
            s["redeliver_left"] -= 1
            s["pending"] = s["pending"] | {seq}
        return Action(f"redeliver({seq})", "publisher", guard, apply)

    def ack_stale(seq):
        def guard(s):
            return seq in s["pending"] and seq <= s["version"] \
                and s["build"] == 0

        def apply(s):
            s["pending"] = s["pending"] - {seq}
        # the real stale path returns BEFORE any swap sync point: only
        # find_model's registry.find fires (registry.py apply_delta)
        return Action(f"ack_stale({seq})", "applier", guard, apply,
                      syncs=("registry.find",))

    def publish(s, seq):
        if seq < s["version"]:
            s["monotone_ok"] = False
        s["vA"] = s["vB"] = s["version"] = seq
        s["applied"] = s["applied"] | {seq}
        s["pending"] = s["pending"] - {seq}

    def apply_next(seq):
        def guard(s):
            return seq in s["pending"] and seq == s["version"] + 1 \
                and s["build"] == 0

        if atomic_publish:
            def apply(s):
                publish(s, seq)
            return Action(f"apply({seq})", "applier", guard, apply,
                          syncs=("registry.find",
                                 "registry.swap.build",
                                 "registry.swap.commit"))

        def apply_start(s):
            s["build"] = seq
            s["vA"] = seq              # first variable patched IN PLACE
        start = Action(f"apply_start({seq})", "applier", guard,
                       apply_start, syncs=("registry.find",
                                           "registry.swap.build"))

        def fin_guard(s):
            return s["build"] == seq

        def apply_finish(s):
            s["build"] = 0
            publish(s, seq)
        finish = Action(f"apply_finish({seq})", "applier", fin_guard,
                        apply_finish, syncs=("registry.swap.commit",))
        return [start, finish]

    def apply_gapped(seq):
        # the dropped gate: any pending newer seq applies directly
        def guard(s):
            return seq in s["pending"] and seq > s["version"] + 1 \
                and s["build"] == 0

        def apply(s):
            publish(s, seq)
        return Action(f"apply_gapped({seq})", "applier", guard, apply,
                      syncs=("registry.find",
                             "registry.swap.build",
                             "registry.swap.commit"))

    for seq in range(1, max_seq + 1):
        actions.append(redeliver(seq))
        actions.append(ack_stale(seq))
        nxt = apply_next(seq)
        actions.extend(nxt if isinstance(nxt, list) else [nxt])
        if not seq_gate:
            actions.append(apply_gapped(seq))

    for i in range(readers):
        def snap_guard(s, i=i):
            return s[f"r{i}_pc"] == "idle"

        def snap_apply(s, i=i):
            s[f"r{i}_pc"] = "reading"
            s[f"r{i}_snap"] = (s["vA"], s["vB"])
        actions.append(Action(f"r{i}_snapshot", f"reader{i}", snap_guard,
                              snap_apply,
                              syncs=("serving.lookup.snapshot",)))

        def read_guard(s, i=i):
            return s[f"r{i}_pc"] == "reading"

        def read_apply(s, i=i):
            s[f"r{i}_pc"] = "idle"
            s[f"r{i}_snap"] = (0, 0)
        actions.append(Action(f"r{i}_read", f"reader{i}", read_guard,
                              read_apply, syncs=("registry.find",)))

    def inv_consistent(s):
        return all(s[f"r{i}_snap"][0] == s[f"r{i}_snap"][1]
                   for i in range(readers))

    def inv_no_lost(s):
        return s["applied"] == frozenset(range(1, s["version"] + 1))

    def inv_monotone(s):
        return s["monotone_ok"]

    def is_done(s):
        return not s["pending"] and s["build"] == 0 \
            and all(s[f"r{i}_pc"] == "idle" for i in range(readers))

    return make_model(
        "hot_swap", init, actions,
        [("reader_sees_one_version", inv_consistent),
         ("version_covers_exactly_applied_deltas", inv_no_lost),
         ("applied_seq_monotone", inv_monotone)],
        is_done,
        notes="registry.apply_delta seq gate + one-reference-swap vs "
              "snapshotting ServingModel.lookup readers")


# ---------------------------------------------------------------------------
# Model 2: DirtyTracker claim discipline (dirty.py + save_delta's writer)
# ---------------------------------------------------------------------------

def dirty_tracker(*, restore_on_failure: bool = True, chunks: int = 2,
                  marks: int = 3) -> Model:
    """``DirtyTracker.snapshot_clear``/``restore`` claims under
    concurrent ``mark_dirty`` and a failing writer (``save_delta``'s
    claim/commit/restore protocol around ``ckpt.delta.commit``).

    Per chunk, ``pend`` counts change epochs (a mark bumps it), ``cov``
    the highest epoch a COMMITTED save chain covers. The saver claims
    the dirty set atomically (``snapshot_clear``), writes (which may
    fail), then commits or restores the claim.

    Invariant (the one that matters for durability): no dirty chunk is
    ever lost to a completed save chain — at every state, a chunk with
    uncovered changes is either still marked dirty or claimed by the
    in-flight writer whose claim covers those changes.

    Mutation: ``restore_on_failure=False`` drops the claim restore on a
    failed write (the seeded ``skip_claim_restore``) — the chunk's
    changes vanish from both the bitmap and the chain.
    """
    init: State = {
        "pend": (0,) * chunks, "cov": (0,) * chunks,
        "dirty": (False,) * chunks,
        "claim": None,            # tuple per chunk: claimed epoch | None
        "saver": "idle",          # idle | claimed | written | failed
        "marks_left": marks,
    }

    def _set(t, i, v):
        return t[:i] + (v,) + t[i + 1:]

    actions: List[Action] = []

    def mark(c):
        def guard(s):
            return s["marks_left"] > 0 and s["pend"][c] < 2

        def apply(s):
            s["pend"] = _set(s["pend"], c, s["pend"][c] + 1)
            s["dirty"] = _set(s["dirty"], c, True)
            s["marks_left"] -= 1
        return Action(f"mark({c})", "trainer", guard, apply,
                      syncs=("dirty.mark",))

    for c in range(chunks):
        actions.append(mark(c))

    def snap_guard(s):
        return s["saver"] == "idle" and any(s["dirty"])

    def snap_apply(s):
        s["claim"] = tuple(s["pend"][c] if s["dirty"][c] else None
                           for c in range(chunks))
        s["dirty"] = (False,) * chunks
        s["saver"] = "claimed"
    actions.append(Action("snapshot_clear", "saver", snap_guard,
                          snap_apply, syncs=("dirty.snapshot",)))

    def write_guard(s):
        return s["saver"] == "claimed"

    def write_apply(s):
        ok = dict(s, saver="written")
        fail = dict(s, saver="failed")
        return [ok, fail]
    actions.append(Action("write", "saver", write_guard, write_apply,
                          syncs=("ckpt.delta.write",)))

    def commit_guard(s):
        return s["saver"] == "written"

    def commit_apply(s):
        s["cov"] = tuple(max(s["cov"][c], s["claim"][c] or 0)
                         for c in range(chunks))
        s["claim"] = None
        s["saver"] = "idle"
    actions.append(Action("commit", "saver", commit_guard, commit_apply,
                          syncs=("ckpt.delta.commit",)))

    def fail_guard(s):
        return s["saver"] == "failed"

    def restore_apply(s):
        if restore_on_failure:
            s["dirty"] = tuple(s["dirty"][c] or s["claim"][c] is not None
                               for c in range(chunks))
        s["claim"] = None
        s["saver"] = "idle"
    actions.append(Action("restore", "saver", fail_guard, restore_apply,
                          syncs=("dirty.restore",)))

    def inv_no_lost(s):
        for c in range(len(s["pend"])):
            bound = s["cov"][c]
            if s["claim"] is not None and s["claim"][c] is not None:
                bound = max(bound, s["claim"][c])
            if s["pend"][c] > bound and not s["dirty"][c]:
                return False
        return True

    def is_done(s):
        return s["saver"] == "idle" and s["claim"] is None

    return make_model(
        "dirty_tracker", init, actions,
        [("no_dirty_chunk_lost_to_completed_chain", inv_no_lost)],
        is_done,
        notes="DirtyTracker snapshot_clear/restore claims vs concurrent "
              "mark_dirty and a failing delta writer")


# ---------------------------------------------------------------------------
# Model 3: HA registry load / CREATING window with replica kill
# ---------------------------------------------------------------------------

def ha_registry(*, atomic_commit: bool = True, kills: int = 1,
                serves: int = 2) -> Model:
    """The serving registry's async-load CREATING window (``create_model``
    -> loader thread -> one-lock commit), a failover routing client, and
    a killer SIGKILLing replicas (``serving/ha.py``).

    Two replicas serve one model sign. r0 boots with the model NORMAL
    (the ``--load`` path); r1 restores from a living peer's catalog
    (``restore_from_peers``: only NORMAL entries restore — a CREATING
    peer is polled, modeled as the guard). A killed replica loses
    everything and respawns through restore-from-peer, or from the dump
    when no peer serves (the ``--load``/URI fallback), so the system
    always recovers. The client rotates over replicas like
    ``RoutingClient._rotate``.

    Invariants: NORMAL status implies the model object is installed
    (status and install commit under ONE lock hold — the reader-visible
    pair can never be half-published); a lookup is served only from an
    installed NORMAL model (no CREATING/partial model ever serves rows).

    Mutation: ``atomic_commit=False`` publishes status=NORMAL one step
    before installing the model object — ``find_model`` then hands a
    lookup a missing/partial model inside the window.
    """
    R = ("r0", "r1")
    init: State = {"kill_left": kills, "serves_left": serves,
                   "cl": "idle", "cl_tried": frozenset(),
                   "served_uninstalled": False}
    init.update({"r0_alive": True, "r0_status": "normal",
                 "r0_inst": True, "r0_boot": 0,
                 "r1_alive": True, "r1_status": "absent",
                 "r1_inst": False, "r1_boot": 1})

    actions: List[Action] = []

    def peer_of(r):
        return "r1" if r == "r0" else "r0"

    def restore_start(r):
        # restore_from_peers: a living peer serves NORMAL -> re-create
        def guard(s):
            p = peer_of(r)
            return s[f"{r}_alive"] and s[f"{r}_status"] == "absent" \
                and s[f"{p}_alive"] and s[f"{p}_status"] == "normal"

        def apply(s):
            s[f"{r}_status"] = "creating"
        return Action(f"{r}_restore_start", r, guard, apply,
                      syncs=("ha.restore.model", "registry.load.start"))

    def boot_load(r):
        # the dump-URI path: available even with no living peer
        def guard(s):
            p = peer_of(r)
            no_peer = not (s[f"{p}_alive"]
                           and s[f"{p}_status"] == "normal")
            return s[f"{r}_alive"] and s[f"{r}_status"] == "absent" \
                and s[f"{r}_boot"] > 0 and no_peer

        def apply(s):
            s[f"{r}_boot"] -= 1
            s[f"{r}_status"] = "creating"
        return Action(f"{r}_boot_load", r, guard, apply,
                      syncs=("registry.load.start",))

    def load_commit(r):
        def guard(s):
            return s[f"{r}_alive"] and s[f"{r}_status"] == "creating"

        if atomic_commit:
            def apply(s):
                s[f"{r}_inst"] = True
                s[f"{r}_status"] = "normal"
            return [Action(f"{r}_load_commit", r, guard, apply,
                           syncs=("registry.load.commit",))]

        def apply_status(s):
            s[f"{r}_status"] = "normal"    # published BEFORE the install
        first = Action(f"{r}_commit_status", r, guard, apply_status,
                       syncs=("registry.load.commit",))

        def inst_guard(s):
            return s[f"{r}_alive"] and s[f"{r}_status"] == "normal" \
                and not s[f"{r}_inst"]

        def apply_inst(s):
            s[f"{r}_inst"] = True
        second = Action(f"{r}_install", r, inst_guard, apply_inst)
        return [first, second]

    def kill(r):
        def guard(s):
            # any alive replica may die; liveness is preserved not by a
            # guard here but by respawn() plus each replica's dump-URI
            # boot budget — a respawned replica with no NORMAL peer
            # boot-loads, so the state space has no stranded deadlock
            return s["kill_left"] > 0 and s[f"{r}_alive"]

        def apply(s):
            s["kill_left"] -= 1
            s[f"{r}_alive"] = False
            s[f"{r}_status"] = "absent"
            s[f"{r}_inst"] = False
        return Action(f"kill({r})", "chaos", guard, apply)

    def respawn(r):
        def guard(s):
            return not s[f"{r}_alive"]

        def apply(s):
            s[f"{r}_alive"] = True
        return Action(f"respawn({r})", "chaos", guard, apply,
                      syncs=("ha.restore.catalog",))

    for r in R:
        actions.append(restore_start(r))
        actions.append(boot_load(r))
        actions.extend(load_commit(r))
        actions.append(kill(r))
        actions.append(respawn(r))

    # client: rotate over untried replicas; serve from a NORMAL one
    def try_replica(r):
        def guard(s):
            return s["serves_left"] > 0 and s["cl"] == "idle" \
                and r not in s["cl_tried"]

        def apply(s):
            if s[f"{r}_alive"] and s[f"{r}_status"] == "normal":
                # served: record AT THE SERVE INSTANT whether find_model
                # handed out an uninstalled model (the lookup keeps its
                # reference afterwards — a later kill cannot corrupt it,
                # so this is a point check, not a lingering predicate)
                s["cl"] = f"served:{r}"
                if not s[f"{r}_inst"]:
                    s["served_uninstalled"] = True
            else:
                s["cl_tried"] = s["cl_tried"] | {r}
        return Action(f"cl_try({r})", "client", guard, apply,
                      syncs=("routing.attempt", "registry.find"))

    def served_done(r):
        def guard(s):
            return s["cl"] == f"served:{r}"

        def apply(s):
            s["cl"] = "idle"
            s["cl_tried"] = frozenset()
            s["serves_left"] -= 1
        return Action(f"cl_done({r})", "client", guard, apply,
                      syncs=("serving.lookup.snapshot",))

    def all_failed_guard(s):
        return s["cl"] == "idle" and s["cl_tried"] == frozenset(R)

    def all_failed_apply(s):
        # every replica bounced: the caller sees the error and retries
        s["cl_tried"] = frozenset()
    for r in R:
        actions.append(try_replica(r))
        actions.append(served_done(r))
    actions.append(Action("cl_all_failed", "client", all_failed_guard,
                          all_failed_apply))

    def inv_normal_installed(s):
        return all(not (s[f"{r}_alive"] and s[f"{r}_status"] == "normal")
                   or s[f"{r}_inst"] for r in R)

    def inv_served_installed(s):
        return not s["served_uninstalled"]

    def is_done(s):
        return s["serves_left"] == 0

    return make_model(
        "ha_registry", init, actions,
        [("normal_status_implies_model_installed", inv_normal_installed),
         ("lookup_served_only_from_installed_model", inv_served_installed)],
        is_done,
        notes="create_model CREATING window + restore_from_peers + "
              "RoutingClient rotation under replica SIGKILL")


# ---------------------------------------------------------------------------
# Model 4: delta-checkpoint chain (writer, manifest commit, compactor,
# crash-at-any-step, torn tails, loads racing everything)
# ---------------------------------------------------------------------------

def delta_chain(*, commit_order: str = "payload_first",
                carry_seq_on_full: bool = True,
                compact_content_seq: bool = True,
                resume_cursor: str = "exact",
                max_seq: int = 3, fulls: int = 1, crashes: int = 1,
                tears: int = 1, loads: int = 1,
                trainer_steps: int = 3,
                trainer_crashes: int = 1) -> Model:
    """The ``checkpoint_delta.py`` chain protocol end to end.

    One variable whose base is TWO field files (weights + a slot — the
    granularity at which the compactor folds and a crash interleaves).
    Content versions count as "reflects committed deltas <= v";
    applying a delta whose seq is neither idempotent (<= v) nor the
    successor (v+1) poisons the field (``_CORRUPT`` — rows from the
    wrong epoch overwrote newer rows), which is exactly what replaying
    a stale chain over a half-new base does.

    Protocol steps modeled 1:1 with the code: delta save = write the
    payload file, then commit the manifest (``ckpt.delta.commit``, the
    one atomic rename); full save = reset_chain FIRST, write the two
    base fields, then re-arm (``ckpt.full.reset``/``ckpt.full.arm``),
    carrying ``last_seq`` so burned seqs are never reused; the
    background compactor (never concurrent with the saver —
    ``join_compactor``) folds verified entries field-by-field, commits
    a fresh manifest (new base_id, ``last_seq`` preserved,
    ``content_seq`` = folded content), then GCs the chain; a crash
    budget kills the writer/compactor thread between any two steps; a
    tear budget corrupts the FINAL committed payload (the dying-disk
    case); the loader snapshots the manifest, reads fields and chain
    files in any interleaving, drops a bad FINAL entry, errors on a bad
    middle, and retries once when ``base_id`` moved under it — the
    ``load_checkpoint`` retry loop.

    Invariants (checked at every reachable state):

    * ``load_is_committed_consistent`` — a PUBLISHED load is never
      mixed/corrupt and equals a content version that was actually
      committed ("a load never observes a mid-chain tear as success";
      "torn FINAL recovers to the last complete delta");
    * ``no_silent_commit_loss`` — a load only ever drops a committed
      entry whose payload a TEAR destroyed, never one whose payload
      simply was not written yet;
    * ``seqs_never_reused`` — burned seqs never reappear;
    * ``load_version_matches_content`` — the version a load reports
      (``applied_seq``) equals the content it loaded (the serving
      hot-swap gate depends on this).

    The ``trainer_restart`` role (the elastic-recovery round): the
    trainer is the process every other role lives inside. It consumes
    stream batches 1..``trainer_steps`` in order (``Trainer.fit``'s
    loop; ``t_hi`` = the highest step whose rows its in-memory state
    holds, ``t_next`` = the stream cursor), and every delta/full save
    records the cursor at its commit (``save_delta(extra=...)`` — the
    manifest channel ``fit(autosave_every=)`` writes). A whole-process
    crash (``trainer_crashes`` budget, distinct from the thread-level
    ``crashes``) kills the saver AND compactor mid-anything; restore
    (``fit(resume_from=)`` -> ``load_checkpoint`` + ``ShardStream``
    ``skip_batches``) re-derives both the state and the stream position
    from the last COMMITTED manifest entry the load verifies — a torn
    tail resumes one autosave earlier, exactly like the load does.

    Invariant ``trainer_neither_reapplies_nor_skips_rows``: every batch
    the (possibly resumed) trainer applies is the successor of its
    in-memory content — it never re-applies a step whose rows the
    restored checkpoint already holds and never skips one (the
    bit-identical-resume contract).

    Mutations: ``commit_order="manifest_first"`` commits the manifest
    before the payload (seeded ``manifest_before_payload``);
    ``carry_seq_on_full=False`` re-arms full saves at ``last_seq=0``
    (seq reuse; pre-fix shipped behavior); ``compact_content_seq=False``
    drops the compacted manifest's content version (``applied_seq``
    reports 0; also pre-fix shipped behavior);
    ``resume_cursor="zero"`` restores the model state but re-reads the
    stream from position zero (the dead-reader/naive-restart behavior
    the ``ShardStream.skip_batches`` contract exists to prevent —
    seeded ``resume_cursor_from_zero``), ``resume_cursor="skip"``
    resumes one batch past the cursor (an off-by-one skip — seeded
    ``resume_cursor_skips_a_step``).

    Bounds: ``max_seq`` deltas, one full save, one crash, one tear, one
    load (with one retry), ``trainer_steps`` stream batches, one
    whole-process trainer crash, compaction past 2 chain entries —
    exhaustive within the budgets (~130k states at the defaults).
    """
    if resume_cursor not in ("exact", "zero", "skip"):
        raise ValueError(f"resume_cursor must be exact|zero|skip, "
                         f"got {resume_cursor!r}")
    init: State = {
        # manifest: None | (gen, last_seq, content_seq, chain tuple)
        "mf": (0, 0, 0, ()),
        "gen_next": 1,
        "files": (),          # ((seq, "ok"|"torn"), ...) committed+orphans
        "f0": 0, "f1": 0,     # base field content versions
        "saver": ("idle",),
        "comp": ("off",),
        "loader": ("off",),
        "burned": frozenset(), "reused": False,
        "truths": frozenset([0]),
        "crash_left": crashes, "tear_left": tears,
        "full_left": fulls, "load_left": loads, "retry_left": 1,
        # trainer_restart role: program counter, in-memory content
        # high-water step, stream cursor, committed-cursor bookkeeping
        # (seq -> cursor pairs mirror the manifest ``extra`` channel;
        # base_cursor is what a chainless manifest's base reflects)
        "t_pc": "run", "t_hi": 0, "t_next": 1,
        "t_crash_left": trainer_crashes, "t_flag": False,
        "cursors": (), "base_cursor": 0,
    }

    def files_get(s, seq):
        for q, st in s["files"]:
            if q == seq:
                return st
        return None

    def files_set(s, seq, st):
        rest = tuple((q, x) for q, x in s["files"] if q != seq)
        s["files"] = tuple(sorted(rest + ((seq, st),)))

    def apply_seq(content, seq):
        """Newest-wins row overwrite of one delta over one field."""
        if content == _CORRUPT:
            return _CORRUPT
        if seq <= content:
            return content             # idempotent re-apply
        if seq == content + 1:
            return seq
        return _CORRUPT                # gap: rows from the wrong epoch

    def live(s):
        # the trainer's in-memory content = every committed delta
        return max(s["burned"], default=0)

    def committed_cursor(s):
        """Stream cursor the last committed manifest entry records
        (the ``extra`` channel) — the base's when the chain is empty."""
        return s["cursors"][-1][1] if s["cursors"] else s["base_cursor"]

    actions: List[Action] = []

    # -- delta save ---------------------------------------------------------
    def dw_guard(s):
        # the saver is the trainer's own thread (fit's blocking
        # autosave): no save from a dead process, and no empty delta —
        # a save needs rows the last commit does not cover
        return s["mf"] is not None and s["saver"] == ("idle",) \
            and s["comp"] == ("off",) and s["mf"][1] < max_seq \
            and s["t_pc"] == "run" and s["t_hi"] > committed_cursor(s)

    def commit_seq(s, seq):
        gen, _last, cseq, chain = s["mf"]
        if seq in s["burned"]:
            s["reused"] = True
        s["burned"] = s["burned"] | {seq}
        s["mf"] = (gen, seq, cseq, chain + (seq,))
        s["truths"] = s["truths"] | {seq}
        # the manifest entry's extra records the trainer cursor at the
        # save (t_hi cannot move mid-save: fit's autosave is blocking)
        s["cursors"] = s["cursors"] + ((seq, s["t_hi"]),)

    def write_branches(s, seq):
        """A payload lands whole, or — tear budget — torn: fs.open_atomic
        fsyncs file and directory, so a file ever observed whole can
        never tear LATER; the torn-from-birth branch models the
        dying-disk partial rename the PR-8 recovery lane exists for
        (the writer computed its crc from memory and never re-reads,
        so the commit can still follow a torn payload)."""
        ok = dict(s)
        files_set(ok, seq, "ok")
        ok["saver"] = ("dw", seq)
        out = [ok]
        if s["tear_left"] > 0:
            torn = dict(s)
            files_set(torn, seq, "torn")
            torn["tear_left"] -= 1
            torn["saver"] = ("dw", seq)
            out.append(torn)
        return out

    if commit_order == "payload_first":
        def dw_apply(s):
            return write_branches(s, s["mf"][1] + 1)
        actions.append(Action("delta_write", "saver", dw_guard, dw_apply,
                              syncs=("ckpt.delta.write",)))

        def dc_guard(s):
            return s["saver"][0] == "dw"

        def dc_apply(s):
            commit_seq(s, s["saver"][1])
            s["saver"] = ("idle",)
        actions.append(Action("delta_commit", "saver", dc_guard,
                              dc_apply, syncs=("ckpt.delta.commit",)))
    else:                              # mutated: manifest before payload
        def dce_apply(s):
            seq = s["mf"][1] + 1
            commit_seq(s, seq)
            s["saver"] = ("dw", seq)
        actions.append(Action("delta_commit_early", "saver", dw_guard,
                              dce_apply, syncs=("ckpt.delta.commit",)))

        def dwl_guard(s):
            return s["saver"][0] == "dw"

        def dwl_apply(s):
            out = write_branches(s, s["saver"][1])
            for b in out:
                b["saver"] = ("idle",)
            return out
        actions.append(Action("delta_write_late", "saver", dwl_guard,
                              dwl_apply, syncs=("ckpt.delta.write",)))

    def crash_saver_guard(s):
        return s["saver"] != ("idle",) and s["crash_left"] > 0

    def crash_saver_apply(s):
        # the writer thread dies between steps: an uncommitted payload
        # stays an orphan (GC'd later, never read); a committed-but-
        # unwritten one stays MISSING — the mutated order's poison
        s["saver"] = ("idle",)
        s["crash_left"] -= 1
    actions.append(Action("crash_saver", "chaos", crash_saver_guard,
                          crash_saver_apply))

    # -- full save ----------------------------------------------------------
    def fs_guard(s):
        return s["saver"] == ("idle",) and s["comp"] == ("off",) \
            and s["full_left"] > 0 and s["mf"] is not None \
            and s["t_pc"] == "run"

    def fs_reset_apply(s):
        carried = s["mf"][1] if carry_seq_on_full else 0
        s["mf"] = None
        s["files"] = ()            # reset_chain GCs every delta file
        s["cursors"] = ()          # the chain entries' extras go with it
        s["full_left"] -= 1
        # the dump will hold every in-memory row: capture the cursor
        # the re-armed manifest records (t_hi frozen — blocking save)
        s["saver"] = ("fr", carried, s["t_hi"])
    actions.append(Action("full_reset_chain", "saver", fs_guard,
                          fs_reset_apply, syncs=("ckpt.full.reset",)))

    def fw0_guard(s):
        return s["saver"][0] == "fr"

    def fw0_apply(s):
        s["f0"] = live(s)
        s["saver"] = ("f0",) + s["saver"][1:]
    actions.append(Action("full_write_f0", "saver", fw0_guard, fw0_apply,
                          syncs=("ckpt.writer.run",)))

    def fw1_guard(s):
        return s["saver"][0] == "f0"

    def fw1_apply(s):
        s["f1"] = live(s)
        s["saver"] = ("f1",) + s["saver"][1:]
    actions.append(Action("full_write_f1", "saver", fw1_guard, fw1_apply,
                          syncs=("ckpt.writer.run",)))

    def fa_guard(s):
        return s["saver"][0] == "f1"

    def fa_apply(s):
        carried = s["saver"][1]
        s["mf"] = (s["gen_next"], carried, carried, ())
        s["gen_next"] += 1
        s["base_cursor"] = s["saver"][2]
        s["saver"] = ("idle",)
    actions.append(Action("full_arm", "saver", fa_guard, fa_apply,
                          syncs=("ckpt.full.arm",)))

    # -- background compactor ----------------------------------------------
    def verified_tail(s):
        """Last verified chain seq (bad FINAL dropped), or None when a
        bad MIDDLE makes the chain unfoldable/unloadable."""
        chain = s["mf"][3]
        tail = None
        for i, seq in enumerate(chain):
            if files_get(s, seq) == "ok":
                tail = seq
            elif i == len(chain) - 1:
                return tail            # bad final: fold/load the prefix
            else:
                return None            # bad middle
        return tail

    def comp_start_guard(s):
        # the compactor REFUSES a chain that does not fully verify
        # (true positive found by this model: folding around a torn
        # committed entry and GC'ing it converts the documented loud
        # mid-chain refusal into silent permanent data loss — the torn
        # delta's chunks were already claim-cleared, nothing re-covers
        # them; checkpoint_delta._compact_impl now aborts instead)
        chain = s["mf"][3] if s["mf"] is not None else ()
        return s["comp"] == ("off",) and s["saver"] == ("idle",) \
            and s["t_pc"] == "run" \
            and len(chain) >= 2 and verified_tail(s) == chain[-1]

    def comp_start_apply(s):
        s["comp"] = ("run", verified_tail(s))
    actions.append(Action("compact_start", "compactor", comp_start_guard,
                          comp_start_apply, syncs=("ckpt.compact.run",)))

    def fold_field(s, field, upto):
        v = s[field]
        for seq in s["mf"][3]:
            if seq > upto:
                break
            if files_get(s, seq) == "ok":
                v = apply_seq(v, seq)
        s[field] = v

    def comp_fold0_guard(s):
        return s["comp"][0] == "run"

    def comp_fold0_apply(s):
        fold_field(s, "f0", s["comp"][1])
        s["comp"] = ("c0", s["comp"][1])
    actions.append(Action("compact_fold_f0", "compactor",
                          comp_fold0_guard, comp_fold0_apply))

    def comp_fold1_guard(s):
        return s["comp"][0] == "c0"

    def comp_fold1_apply(s):
        fold_field(s, "f1", s["comp"][1])
        s["comp"] = ("c1", s["comp"][1])
    actions.append(Action("compact_fold_f1", "compactor",
                          comp_fold1_guard, comp_fold1_apply))

    def comp_commit_guard(s):
        return s["comp"][0] == "c1"

    def comp_commit_apply(s):
        folded = s["comp"][1]
        cseq = folded if compact_content_seq else 0
        s["mf"] = (s["gen_next"], s["mf"][1], cseq, ())
        s["gen_next"] += 1
        # the folded base now reflects the folded tail's cursor; the
        # chain (and its per-entry extras) is gone
        s["base_cursor"] = dict(s["cursors"]).get(folded,
                                                  s["base_cursor"])
        s["cursors"] = ()
        s["comp"] = ("gc",)
    actions.append(Action("compact_commit", "compactor",
                          comp_commit_guard, comp_commit_apply,
                          syncs=("ckpt.compact.commit",)))

    def comp_gc_guard(s):
        return s["comp"] == ("gc",)

    def comp_gc_apply(s):
        s["files"] = ()
        s["comp"] = ("off",)
    actions.append(Action("compact_gc", "compactor", comp_gc_guard,
                          comp_gc_apply))

    def crash_comp_guard(s):
        return s["comp"] != ("off",) and s["crash_left"] > 0

    def crash_comp_apply(s):
        # fields may be partially folded under the OLD manifest — replay
        # idempotence must make any later load correct anyway
        s["comp"] = ("off",)
        s["crash_left"] -= 1
    actions.append(Action("crash_compactor", "chaos", crash_comp_guard,
                          crash_comp_apply))

    # -- trainer_restart role ----------------------------------------------
    def t_step_guard(s):
        # fit's loop: one batch at a time, never while its own blocking
        # autosave is in flight
        return s["t_pc"] == "run" and s["saver"] == ("idle",) \
            and s["t_next"] <= trainer_steps

    def t_step_apply(s):
        k = s["t_next"]
        if k <= s["t_hi"] or k > s["t_hi"] + 1:
            # the batch is not the successor of the in-memory content:
            # a re-applied committed step (k <= t_hi) or a skipped one
            s["t_flag"] = True
        s["t_hi"] = max(s["t_hi"], k)
        s["t_next"] = k + 1
    actions.append(Action("trainer_step", "trainer", t_step_guard,
                          t_step_apply, syncs=("trainer.fit.step",)))

    def t_crash_guard(s):
        return s["t_pc"] == "run" and s["t_crash_left"] > 0

    def t_crash_apply(s):
        # whole-PROCESS death (SIGKILL at any sync point): the saver
        # and the background compactor die with it — uncommitted
        # payloads stay orphans, a mid-full-save dir stays unarmed, a
        # mid-fold compactor leaves partially-folded fields under the
        # old manifest. In-memory rows past the last commit are gone.
        s["t_crash_left"] -= 1
        s["t_pc"] = "dead"
        s["saver"] = ("idle",)
        s["comp"] = ("off",)
    actions.append(Action("trainer_crash", "chaos", t_crash_guard,
                          t_crash_apply))

    def t_loadable(s):
        # what load_checkpoint accepts: every non-final chain entry
        # verifies (a bad FINAL is dropped whole, a bad middle raises)
        chain = s["mf"][3]
        return all(files_get(s, q) == "ok" for q in chain[:-1])

    def t_restore_guard(s):
        # fit(resume_from=): a committed manifest must exist and load —
        # a crash mid-full-save (mf None) has nothing to resume from
        # and the dead trainer is an accepted end state
        return s["t_pc"] == "dead" and s["mf"] is not None \
            and t_loadable(s)

    def t_restore_apply(s):
        # the restored content and the stream cursor BOTH come from the
        # entry the load actually applies: a torn tail resumes one
        # autosave earlier, exactly like the load recovers
        tail = verified_tail(s)
        cur = (dict(s["cursors"]).get(tail, s["base_cursor"])
               if tail is not None else s["base_cursor"])
        s["t_pc"] = "run"
        s["t_hi"] = cur
        if resume_cursor == "exact":
            s["t_next"] = cur + 1
        elif resume_cursor == "zero":
            s["t_next"] = 1            # naive restart: stream from 0
        else:
            s["t_next"] = cur + 2      # off-by-one: skips a batch
    actions.append(Action("trainer_restore", "trainer", t_restore_guard,
                          t_restore_apply,
                          syncs=("trainer.resume.restore",)))

    # -- loader -------------------------------------------------------------
    def lm_guard(s):
        return s["loader"] == ("off",) and s["load_left"] > 0 \
            and s["mf"] is not None

    def lm_apply(s):
        gen, _last, cseq, chain = s["mf"]
        s["load_left"] -= 1
        s["loader"] = ("mf", gen, cseq, chain)
    actions.append(Action("load_read_manifest", "loader", lm_guard,
                          lm_apply, syncs=("registry.load.start",)))

    def lf0_guard(s):
        return s["loader"][0] == "mf"

    def lf0_apply(s):
        s["loader"] = ("lf0",) + s["loader"][1:] + (s["f0"],)
    actions.append(Action("load_read_f0", "loader", lf0_guard, lf0_apply))

    def lf1_guard(s):
        return s["loader"][0] == "lf0"

    def lf1_apply(s):
        s["loader"] = ("lf1",) + s["loader"][1:] + (s["f1"],)
    actions.append(Action("load_read_f1", "loader", lf1_guard, lf1_apply))

    def lc_guard(s):
        return s["loader"][0] == "lf1"

    def lc_apply(s):
        # the replay re-reads the manifest AFTER the base fields
        # (load_checkpoint line order: fields stream first, then
        # read_manifest -> replay_chain) — together with newest-wins
        # idempotence this is what makes loads racing a mid-fold
        # compactor converge instead of publishing a mixed base; the
        # version is computed from the SAME verify pass the replay
        # performs (the registry version-coherence fix this PR)
        _pc, gen0, _cseq0, _chain0, v0, v1 = s["loader"]
        if s["mf"] is None:
            # manifest vanished (racing full-save reset): no replay;
            # the base_id check at finish forces the retry
            s["loader"] = ("fin", gen0, 0, v0, v1, False)
            return
        chain = s["mf"][3]
        cseq = s["mf"][2]
        tail = None
        missing_drop = False
        bad_middle = False
        for i, seq in enumerate(chain):
            st = files_get(s, seq)
            if st == "ok":
                v0 = apply_seq(v0, seq)
                v1 = apply_seq(v1, seq)
                tail = seq
            elif i == len(chain) - 1:
                # verify_chain: bad FINAL entry discarded whole
                missing_drop = st is None
            else:
                bad_middle = True       # refuse: later deltas build on it
                break
        if bad_middle:
            s["loader"] = ("cerr", gen0)
        else:
            version = tail if tail is not None else cseq
            s["loader"] = ("fin", gen0, version, v0, v1, missing_drop)
    actions.append(Action("load_read_chain", "loader", lc_guard,
                          lc_apply))

    def _retry(s, gen0):
        cur_gen = s["mf"][0] if s["mf"] is not None else -1
        if cur_gen != gen0 and s["retry_left"] > 0:
            s["retry_left"] -= 1
            s["load_left"] += 1
            s["loader"] = ("off",)
            return True
        return False

    def lfin_guard(s):
        return s["loader"][0] == "fin"

    def lfin_apply(s):
        _pc, gen0, version, v0, v1, miss = s["loader"]
        cur_gen = s["mf"][0] if s["mf"] is not None else -1
        if cur_gen != gen0:
            if not _retry(s, gen0):
                s["loader"] = ("err",)
            return
        s["loader"] = ("done", version, v0, v1, miss)
    actions.append(Action("load_finish", "loader", lfin_guard,
                          lfin_apply, syncs=("registry.load.commit",)))

    def lerr_guard(s):
        return s["loader"][0] == "cerr"

    def lerr_apply(s):
        # mid-chain damage: load_checkpoint raises unless base_id moved
        if not _retry(s, s["loader"][1]):
            s["loader"] = ("err",)
    actions.append(Action("load_chain_error", "loader", lerr_guard,
                          lerr_apply))

    # -- invariants ---------------------------------------------------------
    def inv_consistent(s):
        if s["loader"][0] != "done":
            return True
        _pc, _version, v0, v1, _miss = s["loader"]
        return v0 == v1 and v0 != _CORRUPT and v0 in s["truths"]

    def inv_no_silent_loss(s):
        return s["loader"][0] != "done" or not s["loader"][4]

    def inv_no_reuse(s):
        return not s["reused"]

    def inv_version(s):
        if s["loader"][0] != "done":
            return True
        _pc, version, v0, _v1, _miss = s["loader"]
        return version == v0

    def inv_trainer_rows(s):
        return not s["t_flag"]

    def is_done(s):
        # a dead trainer with nothing to resume from is an accepted end
        # (the crash-and-never-restart run); everything else quiesces
        # as before
        return s["saver"] == ("idle",) and s["comp"] == ("off",) \
            and s["loader"][0] in ("off", "done", "err")

    return make_model(
        "delta_chain", init, actions,
        [("load_is_committed_consistent", inv_consistent),
         ("no_silent_commit_loss", inv_no_silent_loss),
         ("seqs_never_reused", inv_no_reuse),
         ("load_version_matches_content", inv_version),
         ("trainer_neither_reapplies_nor_skips_rows", inv_trainer_rows)],
        is_done,
        notes="delta save -> atomic manifest commit, full-save chain "
              "reset, background compaction, crash/tear budgets, loads "
              "racing everything (checkpoint_delta.py + "
              "checkpoint.load_checkpoint retry) + trainer_restart: "
              "autosave cursor extras, whole-process crash, "
              "fit(resume_from=) cursor-exact resume")


# ---------------------------------------------------------------------------
# Model 5: serving lookup micro-batcher (serving/batcher.py LookupBatcher)
# ---------------------------------------------------------------------------

def serving_batcher(*, snapshot_per_flush: bool = True,
                    drain_on_shutdown: bool = True,
                    requests: int = 3, queue_cap: int = 2,
                    swaps: int = 2) -> Model:
    """The micro-batching lookup scheduler's enqueue/flush/swap/shutdown
    protocol (``serving/batcher.py`` ``LookupBatcher`` vs
    ``registry.apply_delta`` hot-swaps and ``close()``).

    Clients offer ``requests`` lookups into a bounded queue
    (``queue_cap`` — a full or closed queue rejects with a busy
    response, exactly one response either way). The batcher thread runs
    one flush at a time: COLLECT the queued batch, SNAPSHOT the
    published model reference ONCE (the one-reference-grab discipline
    ``ServingModel.lookup`` already pins for single lookups), then
    resolve the batch in two pull sub-steps (the per-variable-group
    pulls of a mixed batch — the window a concurrent hot-swap can land
    in), then respond to every member. A publisher applies deltas
    (``swaps`` budget) at any interleaving, including mid-flush. A
    shutdown stops the queue accepting and DRAINS what was already
    accepted before stopping.

    Invariants:

    * ``batch_serves_one_version`` — every request of one batch is
      answered from the SAME model version: the flush's single
      snapshot. This is the batched-equals-unbatched parity guarantee
      under a delta hot-swap landing mid-batch ("a batch snapshots
      exactly one version").
    * ``no_request_lost_at_shutdown`` — once the batcher is stopped and
      idle with an empty queue, no accepted request is still waiting:
      every enqueued request got exactly one response (rows or busy).

    Mutations: ``snapshot_per_flush=False`` re-reads the live model
    reference at every pull sub-step instead of snapshotting once (the
    seeded ``resnapshot_per_pull`` — a swap between two variable
    groups' pulls hands one batch rows from two versions);
    ``drain_on_shutdown=False`` discards the queue at shutdown without
    responding (the seeded ``drop_queue_on_shutdown`` — accepted
    requests hang forever).

    Bounds: ``requests`` offers, ``queue_cap`` queue slots, ``swaps``
    hot-swaps, one in-flight flush — exhaustive within the budget.
    """
    init: State = {"version": 0, "swaps_left": swaps,
                   "accepting": True, "queue": (),
                   "batcher": ("idle",), "mixed": False}
    for i in range(requests):
        init[f"q{i}"] = "new"          # new|queued|rejected|served
        init[f"q{i}_ver"] = -1

    actions: List[Action] = []

    def offer_ok(i):
        def guard(s):
            return s[f"q{i}"] == "new" and s["accepting"] \
                and len(s["queue"]) < queue_cap

        def apply(s):
            s[f"q{i}"] = "queued"
            s["queue"] = s["queue"] + (i,)
        return Action(f"offer_ok({i})", f"client{i}", guard, apply,
                      syncs=("serving.batch.enqueue",))

    def offer_busy(i):
        def guard(s):
            return s[f"q{i}"] == "new" and \
                (not s["accepting"] or len(s["queue"]) >= queue_cap)

        def apply(s):
            s[f"q{i}"] = "rejected"     # the 429-busy response
        return Action(f"offer_busy({i})", f"client{i}", guard, apply,
                      syncs=("serving.batch.reject",))

    for i in range(requests):
        actions.append(offer_ok(i))
        actions.append(offer_busy(i))

    # -- the flush state machine -------------------------------------------
    def collect_guard(s):
        return s["batcher"] == ("idle",) and s["queue"] != ()

    def collect_apply(s):
        s["batcher"] = ("col", s["queue"])
        s["queue"] = ()
    actions.append(Action("collect", "batcher", collect_guard,
                          collect_apply,
                          syncs=("serving.batch.collect",)))

    def snap_guard(s):
        return s["batcher"][0] == "col"

    def snap_apply(s):
        # the ONE reference grab; the mutation defers reading to the
        # pulls (snapshot value -1 = "no snapshot taken")
        snap = s["version"] if snapshot_per_flush else -1
        s["batcher"] = ("p0", s["batcher"][1], snap)
    actions.append(Action("snapshot", "batcher", snap_guard, snap_apply,
                          syncs=("serving.batch.snapshot",)))

    def serve(s, members, snap):
        ver = snap if snap >= 0 else s["version"]
        for i in members:
            s[f"q{i}"] = "served"
            s[f"q{i}_ver"] = ver

    def pull0_guard(s):
        return s["batcher"][0] == "p0"

    def pull0_apply(s):
        _pc, batch, snap = s["batcher"]
        serve(s, batch[:1], snap)       # first variable group
        s["batcher"] = ("p1", batch, snap)
    actions.append(Action("pull_group_a", "batcher", pull0_guard,
                          pull0_apply, syncs=("serving.batch.pull",)))

    def pull1_guard(s):
        return s["batcher"][0] == "p1"

    def pull1_apply(s):
        _pc, batch, snap = s["batcher"]
        serve(s, batch[1:], snap)       # remaining variable groups
        vers = {s[f"q{i}_ver"] for i in batch}
        if len(vers) > 1:
            s["mixed"] = True
        s["batcher"] = ("resp", batch)
    actions.append(Action("pull_group_b", "batcher", pull1_guard,
                          pull1_apply, syncs=("serving.batch.pull",)))

    def resp_guard(s):
        return s["batcher"][0] == "resp"

    def resp_apply(s):
        s["batcher"] = ("idle",)
    actions.append(Action("respond", "batcher", resp_guard, resp_apply,
                          syncs=("serving.batch.respond",)))

    # -- hot-swap publisher (registry.apply_delta order) --------------------
    def swap_guard(s):
        return s["swaps_left"] > 0

    def swap_apply(s):
        s["swaps_left"] -= 1
        s["version"] += 1
    actions.append(Action("apply_delta", "publisher", swap_guard,
                          swap_apply,
                          syncs=("registry.find", "registry.swap.build",
                                 "registry.swap.commit")))

    # -- shutdown -----------------------------------------------------------
    def stop_guard(s):
        return s["accepting"]

    def stop_apply(s):
        s["accepting"] = False
        if not drain_on_shutdown:
            s["queue"] = ()             # mutated: accepted requests dropped
    actions.append(Action("shutdown", "control", stop_guard, stop_apply,
                          syncs=("serving.batch.shutdown",)))

    # -- invariants ---------------------------------------------------------
    def inv_one_version(s):
        return not s["mixed"]

    def inv_no_lost(s):
        # stopped + idle + empty queue, yet an accepted request still
        # waits: it will never be answered
        if s["accepting"] or s["queue"] != () \
                or s["batcher"] != ("idle",):
            return True
        return all(s[f"q{i}"] != "queued" for i in range(requests))

    def is_done(s):
        return s["batcher"] == ("idle",) and s["queue"] == () \
            and all(s[f"q{i}"] in ("served", "rejected")
                    for i in range(requests))

    return make_model(
        "serving_batcher", init, actions,
        [("batch_serves_one_version", inv_one_version),
         ("no_request_lost_at_shutdown", inv_no_lost)],
        is_done,
        notes="LookupBatcher bounded enqueue -> collect/snapshot/pull/"
              "respond flush vs apply_delta hot-swaps and drain-on-"
              "shutdown (serving/batcher.py)")


# ---------------------------------------------------------------------------
# shipped registry + schedule export
# ---------------------------------------------------------------------------

def shipped_models() -> List[Model]:
    """The five shipped-protocol models the CLI checks exhaustively."""
    return [delta_chain(), hot_swap(), dirty_tracker(), ha_registry(),
            serving_batcher()]


def sample_traces(model: Model, k: int = 2
                  ) -> List[List[Tuple[str, State]]]:
    """Up to ``k`` representative full traces of a CLEAN model (the
    shortest accepted quiescent run and the deepest state's run) — the
    sampled schedules ``--emit-schedules`` exports for replay."""
    parents: Dict[Any, Tuple[Any, Optional[str]]] = {
        model.init: (None, None)}
    queue = deque([model.init])
    done_states: List[Any] = []
    last = model.init
    while queue:
        fs = queue.popleft()
        last = fs
        state = dict(fs)
        if model.is_done(state) and len(done_states) < 1:
            done_states.append(fs)
        _enabled, succs = _successors(model, state)
        for label, b in succs:
            fb = _freeze(b)
            if fb not in parents:
                parents[fb] = (fs, label)
                queue.append(fb)
    picks = done_states + [last]
    traces = []
    seen = set()
    for fs in picks:
        if fs in seen:
            continue
        seen.add(fs)
        traces.append(_trace_of(parents, fs))
        if len(traces) >= k:
            break
    return traces
