"""graftproto: exhaustive protocol model checking for the host protocols.

The durability and HA protocols rebuilt from the reference — the delta-
checkpoint chain with its background compactor (``checkpoint_delta.py``),
strict-seq serving hot-swap (``serving/registry.py apply_delta``), the
``DirtyTracker`` claim discipline (``dirty.py``), and the HA registry's
CREATING window under replica kills (``serving/ha.py``) — are concurrent
state machines whose bug class (torn tails, seq gaps, lost dirty marks,
mixed-version reads) hides in interleavings no example-based test
enumerates. This module is the fourth static-analysis leg beside
graftcheck/graftlint/graftrace: a small EXPLICIT-STATE model checker plus
faithful models of the shipped protocols (five today — the serving
lookup micro-batcher joined in the batched-serving round), explored
exhaustively.

Checker (stdlib-only, like :mod:`.concurrency`, so ``tools/graftproto.py``
loads it standalone):

* states are FLAT dicts of hashable values (ints, strs, tuples,
  frozensets) — frozen to sorted item-tuples for dedup;
* :class:`Action` = one named guarded atomic step of one process role;
  ``apply`` receives a fresh copy and returns one successor (mutate in
  place / return a dict) or several (return a list — nondeterministic
  outcomes like a write that may fail);
* :func:`check` runs BFS from the initial state with full state dedup, so
  the FIRST violation found has a minimal-length action trace;
* every invariant is checked at every reachable state; a state with no
  enabled action that ``is_done`` does not accept is a DEADLOCK;
* counterexamples pretty-print as an action trace with per-step state
  diffs (:func:`format_result`).

Model fidelity is the whole game, so the models are BRIDGED to the code
two ways: (1) every action carries the ``sync_point`` names
(``analysis/concurrency.py``) the real implementation emits at that
protocol step — :func:`missing_sync_points` greps the package source and
fails if a model references a point the code no longer has; (2)
:func:`trace_schedule` exports any explored trace (including every seeded
mutation's counterexample) as the ordered sync-point list a
``SerialSchedule``/``PointGate`` replay drives against the real
implementation (``tests/test_graftproto_replay.py``,
``tools/graftproto.py --emit-schedules``).

Scope and honesty — what is NOT modeled:

* multi-HOST elastic training (several trainers sharing one chain).
  Whole-process trainer crash + resume IS modeled now: the
  :func:`delta_chain` ``trainer_restart`` role (the graftchaos round)
  covers autosave -> SIGKILL -> ``fit(resume_from=)`` -> continue, with
  the resumed stream cursor re-derived from the committed manifest
  ``extra`` — closing the gap this section named since PR 11;
* unarmed (manifest-less) checkpoint directories — plain full dumps have
  no chain protocol to check (and the trainer_restart role accordingly
  treats a crash mid-full-save, before the re-arm, as unresumable);
* byte-level payload corruption beyond one torn tail per run (the
  ``tear`` budget), and chain/seq counts past the per-model bounds
  stated in each builder's docstring. Bounds are exhaustive WITHIN the
  budget, which is exactly the regime the hand-written interleaving
  tests sample one schedule of.

Two true positives surfaced while writing these models (both fixed in
the same PR, regression-tested in ``tests/test_delta_checkpoint.py``):
a full save over an armed chain re-armed with ``last_seq=0``, REUSING
burned seqs (serving replicas then ack the next real delta as stale and
silently stop updating — the :func:`delta_chain` ``full_save_resets_seq``
mutation is the pre-fix behavior), and ``applied_seq`` returned 0 after a
compaction emptied the chain (no content-version field in the manifest),
so freshly loaded serving models refused every subsequent delta as a gap
(the ``compact_zero_version`` mutation).
"""

from __future__ import annotations

import dataclasses
import os
import re
import time
from collections import deque
from typing import (Any, Callable, Dict, List, Optional,
                    Sequence, Tuple)

State = Dict[str, Any]
_CORRUPT = -99          # content marker: rows overwritten out of order


@dataclasses.dataclass(frozen=True)
class Action:
    """One named guarded atomic step of one process role.

    ``guard(state) -> bool`` reads a thawed state; ``apply(state)`` gets
    a FRESH copy it may mutate in place (return ``None``), replace
    (return a dict), or branch (return a list of dicts — each successor
    is labeled ``name#i``). ``syncs`` are the ``sync_point`` names the
    real implementation emits at this step (the model<->code bridge).

    Reduction metadata (all OPTIONAL — an action that declares nothing
    is treated maximally conservatively: it conflicts with everything,
    so partial-order reduction around it degrades to full expansion):

    * ``pc`` — the guard's program-counter conjuncts as ``(key, head)``
      pairs: the conjunct holds iff ``state[key] == head`` or
      ``state[key]`` is a tuple whose first element is ``head``
      (``"!head"`` negates). These are the structured part of the guard
      the ample rule can reason about: an action whose pc conjunct is
      false stays disabled until some explored action writes that key.
    * ``greads`` — DATA keys the guard reads beyond ``pc`` keys (and
      beyond ``dead``'s keys). Audited by :func:`audit_footprints`.
    * ``reads`` / ``writes`` — keys ``apply`` reads to compute its
      effect / may write. ``writes`` must be a superset of every
      reachable diff (audited); ``reads`` is the declared data
      dependency the independence relation uses.
    * ``dead(state)`` — a MONOTONE predicate: once true it stays true
      on every path (budget exhaustion). Dead actions are excluded
      from the ample rule's interference closure.
    """

    name: str
    role: str
    guard: Callable[[State], bool]
    apply: Callable[[State], Any]
    syncs: Tuple[str, ...] = ()
    pc: Tuple[Tuple[str, str], ...] = ()
    greads: Optional[frozenset] = None
    reads: Optional[frozenset] = None
    writes: Optional[frozenset] = None
    dead: Optional[Callable[[State], bool]] = None

    def __post_init__(self):
        for f in ("greads", "reads", "writes"):
            v = getattr(self, f)
            if v is not None and not isinstance(v, frozenset):
                object.__setattr__(self, f, frozenset(v))

    @property
    def declared(self) -> bool:
        """Full footprint declared — eligible for the ample rule."""
        return (self.greads is not None and self.reads is not None
                and self.writes is not None)

    def reads_all(self) -> frozenset:
        """Every key this action's guard or apply may read."""
        out = set(k for k, _h in self.pc)
        if self.greads:
            out |= self.greads
        if self.reads:
            out |= self.reads
        return frozenset(out)


def _pc_holds(state: State, key: str, head: str) -> bool:
    neg = head.startswith("!")
    if neg:
        head = head[1:]
    v = state[key]
    hit = (v == head) or (isinstance(v, tuple) and len(v) > 0
                          and v[0] == head)
    return hit != neg


@dataclasses.dataclass(frozen=True)
class Obligation:
    """Bounded-liveness obligation: from every reachable TRIGGER state
    — the states where ``after`` holds, or just the initial state when
    ``after`` is None — every maximal run must reach a state satisfying
    ``pred`` within ``within`` transitions.

    Checked by :func:`check_liveness` on the FULL (unreduced) graph —
    three counterexample shapes: a ``within``-step path that never
    satisfies ``pred`` (bound), a reachable cycle avoiding ``pred``
    (lasso — the run can postpone the eventuality forever), and a
    terminal state where the run simply ends without it.
    """

    name: str
    pred: Callable[[State], bool]
    within: int
    after: Optional[Callable[[State], bool]] = None


@dataclasses.dataclass(frozen=True)
class Model:
    name: str
    init: Tuple[Tuple[str, Any], ...]
    actions: Tuple[Action, ...]
    invariants: Tuple[Tuple[str, Callable[[State], bool]], ...]
    # accepting predicate for quiescent states: a state with NO enabled
    # action is a deadlock unless is_done(state)
    is_done: Callable[[State], bool]
    notes: str = ""
    # keys the invariants read (the ample rule's visibility set): an
    # action writing one of these may create or mask a violation, so it
    # never leads a reduced expansion. None = unknown = POR disabled.
    inv_reads: Optional[frozenset] = None
    # interchangeable process identities: groups of key-prefix /
    # identity-value names ((("h0","h1","h2"),) — states canonicalize
    # to the lexicographically smallest identity permutation before
    # dedup. Invariants/is_done MUST be symmetric under the permutation
    # (the cross_check harness is the empirical backstop).
    symmetry: Tuple[Tuple[str, ...], ...] = ()
    obligations: Tuple[Obligation, ...] = ()
    # monotone poison flags: inv-read keys written ONLY upward (bool
    # False->True, or frozenset growing) whose invariants fail exactly
    # when the flag is set. An action whose only inv-read writes are
    # such flags stays ample-eligible: on any deferred path the skipped
    # pre-states carry a SUBSET of the flags of their visited, shifted
    # counterparts, so every violation reachable there is still
    # reported (audit_footprints checks the upward-only discipline
    # dynamically; cross_check is the verdict-equality backstop).
    monotone_flags: frozenset = frozenset()
    # quiescent-payload collapse: (key, head) pairs declaring that once
    # ``state[key]`` is a tuple with this head, its payload elements are
    # dead — no guard, apply, or invariant ever reads past the head
    # again — so dedup may canonicalize the value to ``(head,)``.
    # Validated statically against the declared footprints (see
    # _collapse_problems); states merged this way are bisimilar, since
    # every read of the key in that head is head-only by construction.
    collapse: Tuple[Tuple[str, str], ...] = ()

    def __post_init__(self):
        if self.inv_reads is not None \
                and not isinstance(self.inv_reads, frozenset):
            object.__setattr__(self, "inv_reads",
                               frozenset(self.inv_reads))
        if not isinstance(self.monotone_flags, frozenset):
            object.__setattr__(self, "monotone_flags",
                               frozenset(self.monotone_flags))

    def action(self, name: str) -> Action:
        for a in self.actions:
            if a.name == name:
                return a
        raise KeyError(name)


def make_model(name, init: State, actions, invariants, is_done,
               notes: str = "", inv_reads=None, symmetry=(),
               obligations=(), monotone_flags=(),
               collapse=()) -> Model:
    return Model(name=name, init=_freeze(init), actions=tuple(actions),
                 invariants=tuple(invariants), is_done=is_done,
                 notes=notes, inv_reads=inv_reads,
                 symmetry=tuple(tuple(g) for g in symmetry),
                 obligations=tuple(obligations),
                 monotone_flags=frozenset(monotone_flags),
                 collapse=tuple(tuple(c) for c in collapse))


@dataclasses.dataclass
class Counterexample:
    kind: str                      # "invariant" | "deadlock" | "error"
    invariant: str                 # violated invariant name (or "")
    trace: List[Tuple[str, State]]  # [("<init>", s0), (action, s1), ...]


@dataclasses.dataclass
class Result:
    model: str
    ok: bool
    complete: bool                 # frontier exhausted under max_states
    explored: int
    transitions: int
    elapsed_s: float
    counterexample: Optional[Counterexample] = None
    # reduction bookkeeping: {"reduce": bool, "ample": n states expanded
    # through a singleton ample set, "fused": n forced steps compressed,
    # "sym": n symmetry-canonicalization dedup hits}
    stats: Optional[Dict[str, Any]] = None


def _freeze(state: State) -> Tuple[Tuple[str, Any], ...]:
    """Flat dict of hashable values -> canonical hashable form. Raises
    on unhashable values — models must use ints/strs/tuples/frozensets,
    never lists/sets/dicts as values."""
    items = tuple(sorted(state.items()))
    hash(items)                    # fail fast on an unhashable value
    return items


# ---------------------------------------------------------------------------
# symmetry reduction: canonicalize under identity permutation
# ---------------------------------------------------------------------------

def _permutations(seq):
    if len(seq) <= 1:
        yield tuple(seq)
        return
    for i, head in enumerate(seq):
        for rest in _permutations(seq[:i] + seq[i + 1:]):
            yield (head,) + rest


def _sym_maps(symmetry) -> List[Dict[str, str]]:
    """Every identity-renaming map the symmetry groups generate (the
    cartesian product of each group's permutations)."""
    maps: List[Dict[str, str]] = [{}]
    for group in symmetry:
        nxt = []
        for perm in _permutations(tuple(group)):
            ren = dict(zip(group, perm))
            nxt.extend({**m, **ren} for m in maps)
        maps = nxt
    return maps


def _remap_value(v, ren):
    if isinstance(v, str):
        return ren.get(v, v)
    if isinstance(v, tuple):
        return tuple(_remap_value(x, ren) for x in v)
    if isinstance(v, frozenset):
        return frozenset(_remap_value(x, ren) for x in v)
    return v


def _remap_key(k: str, ren) -> str:
    if k in ren:
        return ren[k]
    head, sep, rest = k.partition("_")
    if sep and head in ren:
        return ren[head] + "_" + rest
    return k


def _canon(state: State, sym_maps) -> Tuple[Tuple[str, Any], ...]:
    """Freeze to the lexicographically-least form over every identity
    permutation: keys with a renamed ``<ident>_`` prefix move, and
    identity names appearing as values (including inside tuples and
    frozensets) are renamed consistently — so two states that differ
    only in which host plays which part dedup to one."""
    best = None
    best_key = None
    for ren in sym_maps:
        if ren:
            mapped = {_remap_key(k, ren): _remap_value(v, ren)
                      for k, v in state.items()}
        else:
            mapped = state
        frozen = _freeze(mapped)
        r = repr(frozen)           # total order over mixed value types
        if best is None or r < best_key:
            best, best_key = frozen, r
    return best


def _collapse_problems(model: Model) -> List[str]:
    """Statically validate the model's quiescent-payload ``collapse``
    declarations against the declared footprints. A ``(key, head)``
    collapse is sound when nothing can read past the head once the key
    carries it: the key is not an invariant read, and every action that
    reads the key's full value is pc-gated to a DIFFERENT head (so it
    is disabled — and stays disabled, every write produces a fresh
    value — in the collapsed head). ``is_done`` and guards validated
    here by the pc contract are head-only by construction;
    :func:`cross_check` is the end-to-end empirical backstop."""
    problems = []
    for key, head in model.collapse:
        if model.inv_reads is None:
            problems.append(f"collapse {key}/{head}: inv_reads unknown")
            continue
        if key in model.inv_reads:
            problems.append(
                f"collapse {key}/{head}: an invariant reads {key!r}")
        for a in model.actions:
            if not a.declared:
                problems.append(
                    f"collapse {key}/{head}: {a.name} has no declared "
                    f"footprint")
                continue
            if key not in (a.greads | a.reads):
                continue
            gated = any(k == key and not h.startswith("!") and h != head
                        for k, h in a.pc)
            if not gated:
                problems.append(
                    f"collapse {key}/{head}: {a.name} reads {key!r} "
                    f"without a pc gate on a different head")
    return problems


def _collapse_state(state: State, collapse) -> State:
    """Copy of ``state`` with every declared quiescent payload dropped
    (``(head, ...)`` -> ``(head,)``)."""
    out = dict(state)
    for key, head in collapse:
        v = out.get(key)
        if isinstance(v, tuple) and len(v) > 1 and v[0] == head:
            out[key] = (head,)
    return out


# ---------------------------------------------------------------------------
# partial-order reduction: SPIN-style singleton ample sets over declared
# footprints, with a dormancy closure for structured (pc-conjunct) guards
# ---------------------------------------------------------------------------

class _ReductionPlan:
    """Per-check() reduction tables for one model.

    The ample rule (documented inline below and in README): expanding
    ONLY action ``a`` at state ``s`` is sound when every action that
    could run before ``a`` on any full-graph path out of ``s`` is
    provably independent of ``a``, ``a`` cannot create or mask an
    invariant verdict the deferred actions would have exposed
    (visibility), and the reduced step does not close a cycle that
    would postpone the deferred actions forever (BFS proviso). Any
    doubt — an undeclared footprint, a guard the dormancy closure
    cannot bound, a nondeterministic candidate — falls back to full
    expansion.
    """

    def __init__(self, model: Model):
        self.acts = model.actions
        n = len(self.acts)
        self.n = n
        inv_reads = model.inv_reads
        # static per-action eligibility to LEAD an ample set: full
        # footprint declared + invisible (writes cannot touch any key
        # an invariant reads — so deferring other actions past it can
        # neither fabricate nor hide a verdict). Writes to declared
        # monotone poison flags are exempt: a flag only moves upward
        # and its invariant fails exactly when set, so the skipped
        # pre-states (subset flags) can only hide violations that the
        # visited, flag-applied states still report.
        self.eligible = []
        for a in self.acts:
            ok = a.declared and inv_reads is not None \
                and (a.writes & inv_reads) <= model.monotone_flags
            self.eligible.append(ok)
        self.por_on = inv_reads is not None and any(self.eligible)
        # static pairwise independence: a's effect and b's effect/guard
        # cannot interact in either order. Undeclared = conflicts.
        self.indep = [set() for _ in range(n)]
        for i, a in enumerate(self.acts):
            if not a.declared:
                continue
            ra = a.reads_all()
            for j, b in enumerate(self.acts):
                if i == j or not b.declared:
                    continue
                if not (a.writes & (b.writes | b.reads_all())) \
                        and not (ra & b.writes):
                    self.indep[i].add(j)
        self.pc_keys = [frozenset(k for k, _h in a.pc)
                        for a in self.acts]
        # ample decisions depend only on (enabled, dead, false-pc-
        # conjunct) masks — memoized across states
        self.cache: Dict[Any, int] = {}

    def _awake(self, ai: int, enabled: frozenset,
               dead: frozenset, false_pc) -> Optional[set]:
        """The interference closure: every action that could fire
        before candidate ``ai`` does on some full-graph path. Starts
        from the other enabled actions; a disabled action joins when
        the closure's writes could flip its false pc conjuncts (ALL of
        them — each must flip for the guard's structured part to hold)
        or, for a pc-satisfied-but-data-disabled action, its declared
        guard data reads. Unknown structure joins unconditionally."""
        A = set(enabled) - {ai}
        while True:
            W: set = set()
            unknown_w = False
            for j in A:
                wj = self.acts[j].writes
                if wj is None:
                    unknown_w = True
                    break
                W |= wj
            grew = False
            for c in range(self.n):
                if c == ai or c in A or c in dead or c in enabled:
                    continue
                fk = false_pc[c]
                if unknown_w:
                    join = True
                elif fk:
                    join = fk <= W
                else:
                    g = self.acts[c].greads
                    join = g is None or bool(g & W)
                if join:
                    A.add(c)
                    grew = True
            if not grew:
                return A

    def candidates(self, state: State, enabled_idx) -> Tuple[int, ...]:
        """All ample-singleton candidates for this state, in model
        action order (deterministic). Empty tuple means full expansion.
        The BFS tries them in order until one satisfies the queue
        proviso; any branches stored while probing a candidate that
        then fails the proviso are genuine successors (a superset of a
        sound ample set is itself sound), so no rollback is needed."""
        if not self.por_on or len(enabled_idx) < 2:
            return ()
        enabled = frozenset(enabled_idx)
        dead = frozenset(
            i for i, a in enumerate(self.acts)
            if a.dead is not None and i not in enabled and a.dead(state))
        false_pc = []
        for i, a in enumerate(self.acts):
            if i in enabled or i in dead or not a.pc:
                false_pc.append(frozenset())
                continue
            false_pc.append(frozenset(
                k for k, h in a.pc if not _pc_holds(state, k, h)))
        key = (enabled, dead, tuple(false_pc))
        hit = self.cache.get(key)
        if hit is not None:
            return hit
        picks = []
        for ai in enabled_idx:
            if not self.eligible[ai]:
                continue
            A = self._awake(ai, enabled, dead, false_pc)
            if _AMPLE_SKIP_DEPENDENCE or A <= self.indep[ai]:
                picks.append(ai)
        hit = tuple(picks)
        self.cache[key] = hit
        return hit

    def select(self, state: State, enabled_idx) -> Optional[int]:
        """First ample candidate, or None — used by chain fusion,
        which only follows a deterministic singleton anyway."""
        c = self.candidates(state, enabled_idx)
        return c[0] if c else None


# Negative-test seam (tests/test_graftproto.py): True disables the
# dependence check — the "naive" reduction that hides counterexamples.
# NEVER true outside the seeded POR-unsoundness test.
_AMPLE_SKIP_DEPENDENCE = False

# Bound + cycle guard for forced-sequence fusion (a chain of states
# with exactly one enabled deterministic action compresses into one
# stored state; every traversed state is still invariant-checked).
_FUSE_LIMIT = 64


def _violated(model: Model, state: State) -> Optional[str]:
    for name, pred in model.invariants:
        if not pred(state):
            return name
    return None


def _trace_of(parents, frozen) -> List[Tuple[str, State]]:
    steps = []
    cur = frozen
    while cur is not None:
        parent, label = parents[cur]
        steps.append((label or "<init>", dict(cur)))
        cur = parent
    steps.reverse()
    return steps


def _branches(action: Action, state: State) -> List[State]:
    """Apply one action to a copy of ``state`` under the Action.apply
    return contract (None = mutated in place, dict = replacement, list
    = nondeterministic branches)."""
    succ = dict(state)
    ret = action.apply(succ)
    if ret is None:
        return [succ]
    if isinstance(ret, dict):
        return [ret]
    return list(ret)


def _successors(model: Model, state: State):
    """Expand one thawed state FULLY: ``(enabled, [(label, succ), ...])``.

    The single home of the Action.apply return contract — check() (via
    :func:`_branches`) and sample_traces() both walk through here, so
    exported schedules can never diverge from what was checked.
    Branches of a nondeterministic action are labeled ``name#i``.
    """
    enabled = False
    out = []
    for action in model.actions:
        if not action.guard(state):
            continue
        enabled = True
        branches = _branches(action, state)
        for i, b in enumerate(branches):
            label = action.name if len(branches) == 1 \
                else f"{action.name}#{i}"
            out.append((label, b))
    return enabled, out


def check(model: Model, max_states: int = 500_000, *,
          reduce: bool = True, _rerun: bool = True) -> Result:
    """Exhaustive BFS over the model's reachable states.

    ``reduce=True`` (the default) enables the three sound reductions —
    symmetry canonicalization (models declaring ``symmetry``),
    singleton ample sets (models declaring action footprints +
    ``inv_reads``), and forced-sequence fusion (a run of states with
    exactly one enabled deterministic action stores only its endpoint;
    every traversed state is still invariant-checked) — and, on any
    counterexample, automatically re-runs unreduced so the reported
    trace is the minimal full-graph one. ``reduce=False`` is the plain
    PR-11 BFS: full expansion, every reachable state stored.

    Returns the first invariant violation or deadlock; ``complete=False``
    means the ``max_states`` budget cut exploration short (the CLI
    treats that as a failure for shipped models — an unexplored
    protocol is an unchecked one)."""
    t0 = time.perf_counter()
    sym_maps = _sym_maps(model.symmetry) \
        if (reduce and model.symmetry) else [{}]
    use_sym = len(sym_maps) > 1
    collapse = model.collapse if reduce else ()
    if collapse:
        bad_decl = _collapse_problems(model)
        if bad_decl:
            raise ValueError(f"{model.name}: unsound collapse "
                             f"declaration: {'; '.join(bad_decl)}")

    def canon(s: State):
        if collapse:
            s = _collapse_state(s, collapse)
        return _canon(s, sym_maps) if use_sym else _freeze(s)

    plan = _ReductionPlan(model) if reduce else None
    stats = {"reduce": reduce, "ample": 0, "fused": 0, "sym": 0}

    def finish(ok, complete, cex=None):
        return Result(model.name, ok, complete, explored, transitions,
                      time.perf_counter() - t0, cex, stats)

    def confirmed(cex_kind):
        """A counterexample under reduction: re-run the plain BFS so
        the user sees the minimal full-graph trace (and the reduced
        verdict is cross-confirmed). Falls back to the reduced trace if
        the full run cannot reproduce it inside the budget."""
        if not (reduce and _rerun):
            return None
        full = check(model, max_states, reduce=False, _rerun=False)
        if not full.ok:
            full.stats = dict(full.stats or {},
                              confirmed_reduced=True, **{
                                  k: v for k, v in stats.items()
                                  if k != "reduce"})
            return full
        return None

    f0 = canon(dict(model.init))
    parents: Dict[Any, Tuple[Any, Optional[str]]] = {f0: (None, None)}
    explored = 0
    transitions = 0
    bad = _violated(model, dict(f0))
    if bad is not None:
        return finish(False, True,
                      Counterexample("invariant", bad,
                                     _trace_of(parents, f0)))
    queue = deque([f0])
    closed = set()      # popped + expanded (the BFS queue proviso set)
    while queue:
        fs = queue.popleft()
        closed.add(fs)
        explored += 1
        state = dict(fs)
        enabled_idx = [i for i, a in enumerate(model.actions)
                       if a.guard(state)]
        if not enabled_idx:
            if not model.is_done(state):
                cex = Counterexample("deadlock", "",
                                     _trace_of(parents, fs))
                return confirmed("deadlock") or finish(False, True, cex)
            continue

        def process_edge(label: str, succ: State):
            """Store one successor, fusing forced chains first.

            A chain state with exactly one enabled deterministic action
            fuses unconditionally (nothing is deferred there). A chain
            state where the ample rule picks a deterministic singleton
            fuses too, with the BFS queue proviso guarding cycles: an
            endpoint hitting an OPEN stored state is safe (that state
            will still be expanded from the queue), but an endpoint
            hitting a CLOSED one could postpone the deferred actions
            around a cycle forever — then the state where the first
            ample fusion happened is stored instead, so its deferred
            actions get a full chance from the queue ("dedup_closed"
            when there was no ample fusion to roll back to: the caller
            must fall back itself if IT deferred anything).
            Returns ("stored"|"dedup"|"dedup_closed"|"done", result)."""
            nonlocal transitions
            cur, cur_label = succ, label
            chain_seen = set()
            pre_ample = None   # (frozen state, label) at first ample fuse
            transitions += 1
            while True:
                fcur = canon(cur)
                if fcur in parents:
                    if use_sym and fcur != _freeze(cur):
                        stats["sym"] += 1
                    if fcur not in closed:
                        return "dedup", None
                    if pre_ample is not None:
                        fpa, pa_label = pre_ample
                        parents[fpa] = (fs, pa_label)
                        if len(parents) >= max_states:
                            return "done", finish(True, False)
                        queue.append(fpa)
                        return "stored", None
                    return "dedup_closed", None
                bad = _violated(model, cur)
                if bad is not None:
                    parents[fcur] = (fs, cur_label)
                    cex = Counterexample("invariant", bad,
                                         _trace_of(parents, fcur))
                    return "done", (confirmed("invariant")
                                    or finish(False, True, cex))
                if plan is None:
                    break
                en = [i for i, a in enumerate(model.actions)
                      if a.guard(cur)]
                if not en:
                    break
                if len(en) == 1:
                    step = en[0]
                else:
                    step = plan.select(cur, en)
                    if step is None:
                        break
                nxt = _branches(model.actions[step], cur)
                if len(nxt) != 1:
                    break
                if fcur in chain_seen or len(chain_seen) >= _FUSE_LIMIT:
                    break
                if len(en) > 1 and pre_ample is None:
                    pre_ample = (fcur, cur_label)
                chain_seen.add(fcur)
                stats["fused"] += 1
                if len(en) > 1:
                    stats["ample"] += 1
                transitions += 1
                cur = nxt[0]
                cur_label = cur_label + "+" + model.actions[step].name
            parents[fcur] = (fs, cur_label)
            if len(parents) >= max_states:
                return "done", finish(True, False)
            queue.append(fcur)
            return "stored", None

        accepted = False
        for choice in (plan.candidates(state, enabled_idx)
                       if plan else ()):
            action = model.actions[choice]
            branches = _branches(action, state)
            all_safe = True
            for bi, b in enumerate(branches):
                label = action.name if len(branches) == 1 \
                    else f"{action.name}#{bi}"
                status, res = process_edge(label, b)
                if status == "done":
                    return res
                if status not in ("stored", "dedup"):
                    all_safe = False
            if all_safe:
                # ample accepted (every branch of the one chosen
                # action): the deferred actions re-appear, still
                # enabled, at each stored (or still-open deduped)
                # successor
                stats["ample"] += 1
                accepted = True
                break
            # some branch dedup-hit a CLOSED state = the BFS queue
            # proviso: taking only this ample step could postpone the
            # deferred actions around a cycle forever — try the next
            # candidate; branches already processed were genuine
            # successors (superset of a sound ample set = sound), and
            # with no candidate left, expand fully
        if accepted:
            continue
        for i in enabled_idx:
            action = model.actions[i]
            branches = _branches(action, state)
            for bi, b in enumerate(branches):
                label = action.name if len(branches) == 1 \
                    else f"{action.name}#{bi}"
                status, res = process_edge(label, b)
                if status == "done":
                    return res
    return finish(True, True)


def check_liveness(model: Model, max_states: int = 500_000) -> Result:
    """Check the model's bounded-liveness :class:`Obligation`s.

    Runs on the FULL (unreduced, uncanonicalized) graph: ample sets
    preserve safety, not eventualities — a reduced graph may drop
    exactly the postponing schedule an obligation exists to catch — so
    liveness obligations belong on models small enough to expand fully
    (the multi-host models are budgeted to stay so). For each
    obligation, every maximal run out of a trigger state must satisfy
    ``pred`` within ``within`` transitions; counterexamples are a
    ``within``-long avoiding path (bound), a reachable avoiding cycle
    (lasso), or a terminal avoiding state (the run just ends).
    """
    t0 = time.perf_counter()
    f0 = model.init
    parents: Dict[Any, Tuple[Any, Optional[str]]] = {f0: (None, None)}
    succs: Dict[Any, List[Tuple[str, Any]]] = {}
    queue = deque([f0])
    explored = 0
    transitions = 0
    while queue:
        fs = queue.popleft()
        explored += 1
        _en, out = _successors(model, dict(fs))
        edges = []
        for label, b in out:
            fb = _freeze(b)
            transitions += 1
            edges.append((label, fb))
            if fb not in parents:
                parents[fb] = (fs, label)
                if len(parents) >= max_states:
                    return Result(model.name, True, False, explored,
                                  transitions,
                                  time.perf_counter() - t0,
                                  stats={"liveness": "budget"})
                queue.append(fb)
        succs[fs] = edges

    def _cex(ob, trigger, path_edges, shape):
        # trace: init -> trigger via BFS parents, then the avoiding path
        trace = _trace_of(parents, trigger)
        for label, f in path_edges:
            trace.append((label, dict(f)))
        if trace:
            lab, st = trace[-1]
            trace[-1] = (f"{lab} ({shape})", st)
        return Result(model.name, False, True, explored, transitions,
                      time.perf_counter() - t0,
                      Counterexample("liveness", ob.name, trace),
                      stats={"liveness": shape})

    for ob in model.obligations:
        if ob.after is None:
            triggers = [f0] if not ob.pred(dict(f0)) else []
        else:
            triggers = [f for f in succs
                        if ob.after(dict(f)) and not ob.pred(dict(f))]
        # BFS the pred-avoiding subgraph from every trigger at once:
        # depth = transitions taken while avoiding pred
        depth: Dict[Any, int] = {}
        back: Dict[Any, Tuple[Any, str]] = {}
        trig_of: Dict[Any, Any] = {}
        dq = deque()
        for t in triggers:
            if t not in depth:
                depth[t] = 0
                trig_of[t] = t
                dq.append(t)

        def _avoid_path(end):
            edges = []
            cur = end
            while cur in back:
                prev, label = back[cur]
                edges.append((label, cur))
                cur = prev
            edges.reverse()
            return trig_of.get(end, cur), edges

        while dq:
            f = dq.popleft()
            d = depth[f]
            out = succs.get(f, [])
            if not out:
                trig, edges = _avoid_path(f)
                return _cex(ob, trig, edges, "run ends")
            for label, fb in out:
                if ob.pred(dict(fb)):
                    continue
                if fb in depth:
                    continue           # cycles handled by DFS below
                depth[fb] = d + 1
                back[fb] = (f, label)
                trig_of[fb] = trig_of[f]
                if d + 1 >= ob.within:
                    trig, edges = _avoid_path(fb)
                    return _cex(ob, trig, edges, "bound")
                dq.append(fb)
        # lasso: any cycle inside the avoiding subgraph (states in
        # `depth` whose avoiding successors stay in `depth`)
        color: Dict[Any, int] = {}
        for start in depth:
            if color.get(start):
                continue
            stack = [(start, iter(succs.get(start, [])))]
            color[start] = 1
            while stack:
                f, it = stack[-1]
                adv = False
                for label, fb in it:
                    if fb not in depth:
                        continue
                    c = color.get(fb, 0)
                    if c == 1:
                        trig, edges = _avoid_path(f)
                        edges.append((label, fb))
                        return _cex(ob, trig, edges, "lasso")
                    if c == 0:
                        color[fb] = 1
                        stack.append((fb, iter(succs.get(fb, []))))
                        adv = True
                        break
                if not adv:
                    color[f] = 2
                    stack.pop()
    return Result(model.name, True, True, explored, transitions,
                  time.perf_counter() - t0,
                  stats={"liveness": "ok",
                         "obligations": len(model.obligations)})


def cross_check(model: Model, max_states: int = 500_000) -> Dict[str, Any]:
    """The reduction soundness harness: check the model reduced and
    unreduced, assert the verdicts agree exactly (ok/kind/invariant),
    and report the state reduction. Raises AssertionError on any
    divergence — this is what the weekly CI lane and the tests run over
    every shipped model."""
    red = check(model, max_states, reduce=True)
    full = check(model, max_states, reduce=False)
    assert red.complete and full.complete, \
        f"[{model.name}] budget cut: reduced={red.complete} " \
        f"full={full.complete}"
    assert red.ok == full.ok, \
        f"[{model.name}] verdict diverged: reduced ok={red.ok} " \
        f"full ok={full.ok}"
    if not red.ok:
        rk = (red.counterexample.kind, red.counterexample.invariant)
        fk = (full.counterexample.kind, full.counterexample.invariant)
        assert rk == fk, \
            f"[{model.name}] counterexample diverged: {rk} vs {fk}"
    assert red.explored <= full.explored, \
        f"[{model.name}] reduction EXPANDED the graph: " \
        f"{red.explored} > {full.explored}"
    return {"model": model.name, "reduced": red, "full": full,
            "ratio": (full.explored / red.explored
                      if red.explored else 1.0)}


class _TracingState(dict):
    """Records which keys a guard actually reads — the footprint audit."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.reads: set = set()

    def __getitem__(self, k):
        self.reads.add(k)
        return super().__getitem__(k)

    def get(self, k, default=None):
        self.reads.add(k)
        return super().get(k, default)


def audit_footprints(model: Model, max_states: int = 4_000) -> List[str]:
    """Empirically validate declared reduction metadata against up to
    ``max_states`` reachable states: a guard must read only
    ``pc``/``greads`` keys, an apply's observed diff must stay inside
    declared ``writes``, and a ``dead`` action must be disabled.
    Returns human-readable violations (tests assert it returns none).
    ``reads`` (apply's data reads) is the one declaration the audit
    must trust — :func:`cross_check` is its empirical backstop."""
    problems: List[str] = list(_collapse_problems(model))
    seen = {model.init}
    queue = deque([model.init])
    audited = 0
    while queue and audited < max_states:
        fs = queue.popleft()
        state = dict(fs)
        audited += 1
        for action in model.actions:
            if action.dead is not None and action.dead(state):
                if action.guard(state):
                    problems.append(
                        f"{model.name}.{action.name}: dead(s) true but "
                        f"guard(s) true — dead is not a disabledness "
                        f"witness")
                continue
            if action.greads is not None:
                ts = _TracingState(state)
                enabled = action.guard(ts)
                allowed = set(k for k, _h in action.pc) | action.greads
                extra = ts.reads - allowed
                if extra:
                    problems.append(
                        f"{model.name}.{action.name}: guard read "
                        f"undeclared keys {sorted(extra)}")
            else:
                enabled = action.guard(state)
            if not enabled:
                if action.pc and all(_pc_holds(state, k, h)
                                     for k, h in action.pc) \
                        and action.greads is not None \
                        and not action.greads:
                    problems.append(
                        f"{model.name}.{action.name}: disabled with all "
                        f"pc conjuncts true and no declared data reads")
                continue
            if action.pc and not all(_pc_holds(state, k, h)
                                     for k, h in action.pc):
                problems.append(
                    f"{model.name}.{action.name}: enabled with a false "
                    f"pc conjunct — pc is not part of the guard")
            if action.writes is not None:
                for b in _branches(action, state):
                    diff = {k for k in set(state) | set(b)
                            if state.get(k, _CORRUPT) is not
                            b.get(k, _CORRUPT)
                            and state.get(k) != b.get(k)}
                    extra = diff - action.writes
                    if extra:
                        problems.append(
                            f"{model.name}.{action.name}: wrote "
                            f"undeclared keys {sorted(extra)}")
                    for k in diff & model.monotone_flags:
                        old, new = state.get(k), b.get(k)
                        up = (old is False and new is True) \
                            or (isinstance(old, frozenset)
                                and isinstance(new, frozenset)
                                and old <= new)
                        if not up:
                            problems.append(
                                f"{model.name}.{action.name}: monotone "
                                f"flag {k!r} moved downward "
                                f"({old!r} -> {new!r})")
        if model.inv_reads is not None:
            ts = _TracingState(state)
            for name, pred in model.invariants:
                pred(ts)
            extra = ts.reads - model.inv_reads
            if extra:
                problems.append(
                    f"{model.name}: invariants read undeclared keys "
                    f"{sorted(extra)} (inv_reads incomplete)")
        for _label, b in _successors(model, state)[1]:
            fb = _freeze(b)
            if fb not in seen:
                seen.add(fb)
                queue.append(fb)
    return sorted(set(problems))


def format_result(res: Result, model: Optional[Model] = None) -> str:
    """Human-readable verdict; counterexamples print the minimal action
    trace with per-step state diffs (and each action's sync points, so
    the trace reads as a replayable schedule)."""
    head = (f"[{res.model}] explored {res.explored} states / "
            f"{res.transitions} transitions in {res.elapsed_s:.2f}s")
    if res.ok and res.complete:
        return head + " — all invariants hold, no deadlock"
    if res.ok:
        return head + f" — INCOMPLETE (state budget hit)"
    cex = res.counterexample
    if cex.kind == "deadlock":
        what = "DEADLOCK (no enabled action, not an accepting state)"
    elif cex.kind == "liveness":
        what = f"LIVENESS OBLIGATION VIOLATED: {cex.invariant}"
    else:
        what = f"INVARIANT VIOLATED: {cex.invariant}"
    lines = [head + f" — {what}", "  counterexample "
             f"({len(cex.trace) - 1} steps):"]
    prev: State = {}
    for label, state in cex.trace:
        if label == "<init>":
            lines.append("    <init>")
            prev = state
            continue
        diff = [f"{k}: {prev.get(k)!r}->{v!r}"
                for k, v in sorted(state.items()) if prev.get(k) != v]
        syncs = ""
        if model is not None:
            base = label.split("#", 1)[0]
            try:
                pts = model.action(base).syncs
            except KeyError:
                pts = ()
            if pts:
                syncs = f"  [sync: {', '.join(pts)}]"
        lines.append(f"    {label}{syncs}  {{{'; '.join(diff)}}}")
        prev = state
    return "\n".join(lines)


def trace_schedule(model: Model,
                   trace: Sequence[Tuple[str, State]]) -> List[str]:
    """Flatten one action trace into the ordered ``sync_point`` list a
    SerialSchedule/PointGate replay drives against the real code."""
    out: List[str] = []
    for label, _state in trace:
        if label == "<init>":
            continue
        base = label.split("#", 1)[0]
        try:
            out.extend(model.action(base).syncs)
        except KeyError:
            pass
    return out


def model_sync_points(model: Model) -> List[str]:
    out = sorted({p for a in model.actions for p in a.syncs})
    return out


# Design-only sync points: protocol steps the multi-host models pin
# down BEFORE the implementation lands (ROADMAP item 3 is models-first
# by mandate). Each name is the contract the implementing PR must emit
# at that step; missing_sync_points treats them as reserved rather than
# drifted, and `tools/graftproto --check-sync` reports them separately
# so they cannot silently rot into vocabulary nobody implements.
RESERVED_SYNC_POINTS = frozenset({
    # multi-host delta round: per-host shard-local write acknowledged
    # to the coordinator; coordinator verifies ALL payloads before the
    # single cross-host manifest commit
    "ckpt.multihost.ack",
    "ckpt.multihost.verify",
    # elastic membership: worker join/leave announcement and the
    # failure detector's sweep that orphans a dead worker's shards
    "train.member.join",
    "train.member.detect",
    # N->M reshard through the checkpoint path: one row-range handoff
    # (source release only after destination apply)
    "reshard.row.apply",
    "reshard.row.release",
})


def reserved_sync_points(model: Model) -> List[str]:
    """The subset of a model's sync points that are design-only
    (reserved for the implementing PR) rather than emitted today."""
    return [p for p in model_sync_points(model)
            if p in RESERVED_SYNC_POINTS]


def missing_sync_points(model: Model,
                        package_root: Optional[str] = None) -> List[str]:
    """Sync points a model references that the package source does not
    emit — the fidelity tripwire: a refactor that renames or drops a
    ``sync_point`` invalidates the model, and this makes that loud.
    Reserved (design-only) points are excluded; ``reserved_sync_points``
    lists those."""
    if package_root is None:
        package_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
    have = set()
    for root, _dirs, names in os.walk(package_root):
        if "__pycache__" in root:
            continue
        for n in names:
            if not n.endswith(".py"):
                continue
            with open(os.path.join(root, n), "r", encoding="utf-8") as fh:
                have.update(re.findall(r'sync_point\(\s*[fr]?"([^"]+)"',
                                       fh.read()))
    return [p for p in model_sync_points(model)
            if p not in have and p not in RESERVED_SYNC_POINTS]


# ---------------------------------------------------------------------------
# Model 1: serving hot-swap (registry.apply_delta vs snapshotting readers)
# ---------------------------------------------------------------------------

def hot_swap(*, seq_gate: bool = True, atomic_publish: bool = True,
             max_seq: int = 3, readers: int = 2) -> Model:
    """``ModelRegistry.apply_delta`` strict seq gating against concurrent
    snapshotting lookups (``ServingModel.lookup``).

    Two variables (vA, vB) stand for the per-variable rows one delta
    patches; the published model is the triple (vA, vB, version) and
    ``applied`` is the set of delta seqs whose rows the served states
    contain. Deltas 1..max_seq are all in flight at once (a retrying
    publisher can present any of them in any order, stale and gapped
    included). Readers snapshot the published pair then read it — the
    one-reference-grab discipline of ``ServingModel.lookup``.

    Invariants: readers never observe a mixed version; ``applied_seq``
    is monotone; a model at version v serves exactly the deltas
    ``{1..v}`` (a dropped gate silently loses the skipped delta's rows).

    Mutations: ``seq_gate=False`` removes the gap refusal (the seeded
    ``drop_seq_gate``); ``atomic_publish=False`` patches the two
    variables in place in two steps instead of building functionally and
    publishing one reference under the lock.
    """
    init: State = {"version": 0, "vA": 0, "vB": 0,
                   "applied": frozenset(), "pending":
                   frozenset(range(1, max_seq + 1)),
                   "build": 0, "monotone_ok": True,
                   "redeliver_left": 1}
    for i in range(readers):
        init[f"r{i}_pc"] = "idle"
        init[f"r{i}_snap"] = (0, 0)

    actions: List[Action] = []

    def redeliver(seq):
        # a retrying publisher re-presents an ALREADY-applied delta
        # (network retry / replica catch-up overlap) — this is what
        # makes the stale-ack branch reachable at all
        def guard(s):
            return s["redeliver_left"] > 0 and seq <= s["version"] \
                and seq not in s["pending"]

        def apply(s):
            s["redeliver_left"] -= 1
            s["pending"] = s["pending"] | {seq}
        return Action(f"redeliver({seq})", "publisher", guard, apply)

    def ack_stale(seq):
        def guard(s):
            return seq in s["pending"] and seq <= s["version"] \
                and s["build"] == 0

        def apply(s):
            s["pending"] = s["pending"] - {seq}
        # the real stale path returns BEFORE any swap sync point: only
        # find_model's registry.find fires (registry.py apply_delta)
        return Action(f"ack_stale({seq})", "applier", guard, apply,
                      syncs=("registry.find",))

    def publish(s, seq):
        if seq < s["version"]:
            s["monotone_ok"] = False
        s["vA"] = s["vB"] = s["version"] = seq
        s["applied"] = s["applied"] | {seq}
        s["pending"] = s["pending"] - {seq}

    def apply_next(seq):
        def guard(s):
            return seq in s["pending"] and seq == s["version"] + 1 \
                and s["build"] == 0

        if atomic_publish:
            def apply(s):
                publish(s, seq)
            return Action(f"apply({seq})", "applier", guard, apply,
                          syncs=("registry.find",
                                 "registry.swap.build",
                                 "registry.swap.commit"))

        def apply_start(s):
            s["build"] = seq
            s["vA"] = seq              # first variable patched IN PLACE
        start = Action(f"apply_start({seq})", "applier", guard,
                       apply_start, syncs=("registry.find",
                                           "registry.swap.build"))

        def fin_guard(s):
            return s["build"] == seq

        def apply_finish(s):
            s["build"] = 0
            publish(s, seq)
        finish = Action(f"apply_finish({seq})", "applier", fin_guard,
                        apply_finish, syncs=("registry.swap.commit",))
        return [start, finish]

    def apply_gapped(seq):
        # the dropped gate: any pending newer seq applies directly
        def guard(s):
            return seq in s["pending"] and seq > s["version"] + 1 \
                and s["build"] == 0

        def apply(s):
            publish(s, seq)
        return Action(f"apply_gapped({seq})", "applier", guard, apply,
                      syncs=("registry.find",
                             "registry.swap.build",
                             "registry.swap.commit"))

    for seq in range(1, max_seq + 1):
        actions.append(redeliver(seq))
        actions.append(ack_stale(seq))
        nxt = apply_next(seq)
        actions.extend(nxt if isinstance(nxt, list) else [nxt])
        if not seq_gate:
            actions.append(apply_gapped(seq))

    for i in range(readers):
        def snap_guard(s, i=i):
            return s[f"r{i}_pc"] == "idle"

        def snap_apply(s, i=i):
            s[f"r{i}_pc"] = "reading"
            s[f"r{i}_snap"] = (s["vA"], s["vB"])
        actions.append(Action(f"r{i}_snapshot", f"reader{i}", snap_guard,
                              snap_apply,
                              syncs=("serving.lookup.snapshot",)))

        def read_guard(s, i=i):
            return s[f"r{i}_pc"] == "reading"

        def read_apply(s, i=i):
            s[f"r{i}_pc"] = "idle"
            s[f"r{i}_snap"] = (0, 0)
        actions.append(Action(f"r{i}_read", f"reader{i}", read_guard,
                              read_apply, syncs=("registry.find",)))

    def inv_consistent(s):
        return all(s[f"r{i}_snap"][0] == s[f"r{i}_snap"][1]
                   for i in range(readers))

    def inv_no_lost(s):
        return s["applied"] == frozenset(range(1, s["version"] + 1))

    def inv_monotone(s):
        return s["monotone_ok"]

    def is_done(s):
        return not s["pending"] and s["build"] == 0 \
            and all(s[f"r{i}_pc"] == "idle" for i in range(readers))

    return make_model(
        "hot_swap", init, actions,
        [("reader_sees_one_version", inv_consistent),
         ("version_covers_exactly_applied_deltas", inv_no_lost),
         ("applied_seq_monotone", inv_monotone)],
        is_done,
        notes="registry.apply_delta seq gate + one-reference-swap vs "
              "snapshotting ServingModel.lookup readers",
        # the readers are interchangeable lookups: nothing distinguishes
        # which thread plays which, so states differing only in the
        # reader permutation canonicalize to one
        symmetry=(tuple(f"r{i}" for i in range(readers)),))


# ---------------------------------------------------------------------------
# Model 2: DirtyTracker claim discipline (dirty.py + save_delta's writer)
# ---------------------------------------------------------------------------

def dirty_tracker(*, restore_on_failure: bool = True, chunks: int = 2,
                  marks: int = 3) -> Model:
    """``DirtyTracker.snapshot_clear``/``restore`` claims under
    concurrent ``mark_dirty`` and a failing writer (``save_delta``'s
    claim/commit/restore protocol around ``ckpt.delta.commit``).

    Per chunk, ``pend`` counts change epochs (a mark bumps it), ``cov``
    the highest epoch a COMMITTED save chain covers. The saver claims
    the dirty set atomically (``snapshot_clear``), writes (which may
    fail), then commits or restores the claim.

    Invariant (the one that matters for durability): no dirty chunk is
    ever lost to a completed save chain — at every state, a chunk with
    uncovered changes is either still marked dirty or claimed by the
    in-flight writer whose claim covers those changes.

    Mutation: ``restore_on_failure=False`` drops the claim restore on a
    failed write (the seeded ``skip_claim_restore``) — the chunk's
    changes vanish from both the bitmap and the chain.
    """
    init: State = {
        "pend": (0,) * chunks, "cov": (0,) * chunks,
        "dirty": (False,) * chunks,
        "claim": None,            # tuple per chunk: claimed epoch | None
        "saver": "idle",          # idle | claimed | written | failed
        "marks_left": marks,
    }

    def _set(t, i, v):
        return t[:i] + (v,) + t[i + 1:]

    actions: List[Action] = []

    def mark(c):
        def guard(s):
            return s["marks_left"] > 0 and s["pend"][c] < 2

        def apply(s):
            s["pend"] = _set(s["pend"], c, s["pend"][c] + 1)
            s["dirty"] = _set(s["dirty"], c, True)
            s["marks_left"] -= 1
        return Action(f"mark({c})", "trainer", guard, apply,
                      syncs=("dirty.mark",))

    for c in range(chunks):
        actions.append(mark(c))

    def snap_guard(s):
        return s["saver"] == "idle" and any(s["dirty"])

    def snap_apply(s):
        s["claim"] = tuple(s["pend"][c] if s["dirty"][c] else None
                           for c in range(chunks))
        s["dirty"] = (False,) * chunks
        s["saver"] = "claimed"
    actions.append(Action("snapshot_clear", "saver", snap_guard,
                          snap_apply, syncs=("dirty.snapshot",)))

    def write_guard(s):
        return s["saver"] == "claimed"

    def write_apply(s):
        ok = dict(s, saver="written")
        fail = dict(s, saver="failed")
        return [ok, fail]
    actions.append(Action("write", "saver", write_guard, write_apply,
                          syncs=("ckpt.delta.write",)))

    def commit_guard(s):
        return s["saver"] == "written"

    def commit_apply(s):
        s["cov"] = tuple(max(s["cov"][c], s["claim"][c] or 0)
                         for c in range(chunks))
        s["claim"] = None
        s["saver"] = "idle"
    actions.append(Action("commit", "saver", commit_guard, commit_apply,
                          syncs=("ckpt.delta.commit",)))

    def fail_guard(s):
        return s["saver"] == "failed"

    def restore_apply(s):
        if restore_on_failure:
            s["dirty"] = tuple(s["dirty"][c] or s["claim"][c] is not None
                               for c in range(chunks))
        s["claim"] = None
        s["saver"] = "idle"
    actions.append(Action("restore", "saver", fail_guard, restore_apply,
                          syncs=("dirty.restore",)))

    def inv_no_lost(s):
        for c in range(len(s["pend"])):
            bound = s["cov"][c]
            if s["claim"] is not None and s["claim"][c] is not None:
                bound = max(bound, s["claim"][c])
            if s["pend"][c] > bound and not s["dirty"][c]:
                return False
        return True

    def is_done(s):
        return s["saver"] == "idle" and s["claim"] is None

    return make_model(
        "dirty_tracker", init, actions,
        [("no_dirty_chunk_lost_to_completed_chain", inv_no_lost)],
        is_done,
        notes="DirtyTracker snapshot_clear/restore claims vs concurrent "
              "mark_dirty and a failing delta writer")


# ---------------------------------------------------------------------------
# Model 3: HA registry load / CREATING window with replica kill
# ---------------------------------------------------------------------------

def ha_registry(*, atomic_commit: bool = True, kills: int = 1,
                serves: int = 2) -> Model:
    """The serving registry's async-load CREATING window (``create_model``
    -> loader thread -> one-lock commit), a failover routing client, and
    a killer SIGKILLing replicas (``serving/ha.py``).

    Two replicas serve one model sign. r0 boots with the model NORMAL
    (the ``--load`` path); r1 restores from a living peer's catalog
    (``restore_from_peers``: only NORMAL entries restore — a CREATING
    peer is polled, modeled as the guard). A killed replica loses
    everything and respawns through restore-from-peer, or from the dump
    when no peer serves (the ``--load``/URI fallback), so the system
    always recovers. The client rotates over replicas like
    ``RoutingClient._rotate``.

    Invariants: NORMAL status implies the model object is installed
    (status and install commit under ONE lock hold — the reader-visible
    pair can never be half-published); a lookup is served only from an
    installed NORMAL model (no CREATING/partial model ever serves rows).

    Mutation: ``atomic_commit=False`` publishes status=NORMAL one step
    before installing the model object — ``find_model`` then hands a
    lookup a missing/partial model inside the window.
    """
    R = ("r0", "r1")
    init: State = {"kill_left": kills, "serves_left": serves,
                   "cl": "idle", "cl_tried": frozenset(),
                   "served_uninstalled": False}
    init.update({"r0_alive": True, "r0_status": "normal",
                 "r0_inst": True, "r0_boot": 0,
                 "r1_alive": True, "r1_status": "absent",
                 "r1_inst": False, "r1_boot": 1})

    actions: List[Action] = []

    def peer_of(r):
        return "r1" if r == "r0" else "r0"

    def restore_start(r):
        # restore_from_peers: a living peer serves NORMAL -> re-create
        def guard(s):
            p = peer_of(r)
            return s[f"{r}_alive"] and s[f"{r}_status"] == "absent" \
                and s[f"{p}_alive"] and s[f"{p}_status"] == "normal"

        def apply(s):
            s[f"{r}_status"] = "creating"
        return Action(f"{r}_restore_start", r, guard, apply,
                      syncs=("ha.restore.model", "registry.load.start"))

    def boot_load(r):
        # the dump-URI path: available even with no living peer
        def guard(s):
            p = peer_of(r)
            no_peer = not (s[f"{p}_alive"]
                           and s[f"{p}_status"] == "normal")
            return s[f"{r}_alive"] and s[f"{r}_status"] == "absent" \
                and s[f"{r}_boot"] > 0 and no_peer

        def apply(s):
            s[f"{r}_boot"] -= 1
            s[f"{r}_status"] = "creating"
        return Action(f"{r}_boot_load", r, guard, apply,
                      syncs=("registry.load.start",))

    def load_commit(r):
        def guard(s):
            return s[f"{r}_alive"] and s[f"{r}_status"] == "creating"

        if atomic_commit:
            def apply(s):
                s[f"{r}_inst"] = True
                s[f"{r}_status"] = "normal"
            return [Action(f"{r}_load_commit", r, guard, apply,
                           syncs=("registry.load.commit",))]

        def apply_status(s):
            s[f"{r}_status"] = "normal"    # published BEFORE the install
        first = Action(f"{r}_commit_status", r, guard, apply_status,
                       syncs=("registry.load.commit",))

        def inst_guard(s):
            return s[f"{r}_alive"] and s[f"{r}_status"] == "normal" \
                and not s[f"{r}_inst"]

        def apply_inst(s):
            s[f"{r}_inst"] = True
        second = Action(f"{r}_install", r, inst_guard, apply_inst)
        return [first, second]

    def kill(r):
        def guard(s):
            # any alive replica may die; liveness is preserved not by a
            # guard here but by respawn() plus each replica's dump-URI
            # boot budget — a respawned replica with no NORMAL peer
            # boot-loads, so the state space has no stranded deadlock
            return s["kill_left"] > 0 and s[f"{r}_alive"]

        def apply(s):
            s["kill_left"] -= 1
            s[f"{r}_alive"] = False
            s[f"{r}_status"] = "absent"
            s[f"{r}_inst"] = False
        return Action(f"kill({r})", "chaos", guard, apply)

    def respawn(r):
        def guard(s):
            return not s[f"{r}_alive"]

        def apply(s):
            s[f"{r}_alive"] = True
        return Action(f"respawn({r})", "chaos", guard, apply,
                      syncs=("ha.restore.catalog",))

    for r in R:
        actions.append(restore_start(r))
        actions.append(boot_load(r))
        actions.extend(load_commit(r))
        actions.append(kill(r))
        actions.append(respawn(r))

    # client: rotate over untried replicas; serve from a NORMAL one
    def try_replica(r):
        def guard(s):
            return s["serves_left"] > 0 and s["cl"] == "idle" \
                and r not in s["cl_tried"]

        def apply(s):
            if s[f"{r}_alive"] and s[f"{r}_status"] == "normal":
                # served: record AT THE SERVE INSTANT whether find_model
                # handed out an uninstalled model (the lookup keeps its
                # reference afterwards — a later kill cannot corrupt it,
                # so this is a point check, not a lingering predicate)
                s["cl"] = f"served:{r}"
                if not s[f"{r}_inst"]:
                    s["served_uninstalled"] = True
            else:
                s["cl_tried"] = s["cl_tried"] | {r}
        return Action(f"cl_try({r})", "client", guard, apply,
                      syncs=("routing.attempt", "registry.find"))

    def served_done(r):
        def guard(s):
            return s["cl"] == f"served:{r}"

        def apply(s):
            s["cl"] = "idle"
            s["cl_tried"] = frozenset()
            s["serves_left"] -= 1
        return Action(f"cl_done({r})", "client", guard, apply,
                      syncs=("serving.lookup.snapshot",))

    def all_failed_guard(s):
        return s["cl"] == "idle" and s["cl_tried"] == frozenset(R)

    def all_failed_apply(s):
        # every replica bounced: the caller sees the error and retries
        s["cl_tried"] = frozenset()
    for r in R:
        actions.append(try_replica(r))
        actions.append(served_done(r))
    actions.append(Action("cl_all_failed", "client", all_failed_guard,
                          all_failed_apply))

    def inv_normal_installed(s):
        return all(not (s[f"{r}_alive"] and s[f"{r}_status"] == "normal")
                   or s[f"{r}_inst"] for r in R)

    def inv_served_installed(s):
        return not s["served_uninstalled"]

    def is_done(s):
        return s["serves_left"] == 0

    return make_model(
        "ha_registry", init, actions,
        [("normal_status_implies_model_installed", inv_normal_installed),
         ("lookup_served_only_from_installed_model", inv_served_installed)],
        is_done,
        notes="create_model CREATING window + restore_from_peers + "
              "RoutingClient rotation under replica SIGKILL")


# ---------------------------------------------------------------------------
# Model 4: delta-checkpoint chain (writer, manifest commit, compactor,
# crash-at-any-step, torn tails, loads racing everything)
# ---------------------------------------------------------------------------

def delta_chain(*, commit_order: str = "payload_first",
                carry_seq_on_full: bool = True,
                compact_content_seq: bool = True,
                resume_cursor: str = "exact",
                max_seq: int = 3, fulls: int = 1, crashes: int = 1,
                tears: int = 1, loads: int = 1,
                trainer_steps: int = 3,
                trainer_crashes: int = 1) -> Model:
    """The ``checkpoint_delta.py`` chain protocol end to end.

    One variable whose base is TWO field files (weights + a slot — the
    granularity at which the compactor folds and a crash interleaves).
    Content versions count as "reflects committed deltas <= v";
    applying a delta whose seq is neither idempotent (<= v) nor the
    successor (v+1) poisons the field (``_CORRUPT`` — rows from the
    wrong epoch overwrote newer rows), which is exactly what replaying
    a stale chain over a half-new base does.

    Protocol steps modeled 1:1 with the code: delta save = write the
    payload file, then commit the manifest (``ckpt.delta.commit``, the
    one atomic rename); full save = reset_chain FIRST, write the two
    base fields, then re-arm (``ckpt.full.reset``/``ckpt.full.arm``),
    carrying ``last_seq`` so burned seqs are never reused; the
    background compactor (never concurrent with the saver —
    ``join_compactor``) folds verified entries field-by-field, commits
    a fresh manifest (new base_id, ``last_seq`` preserved,
    ``content_seq`` = folded content), then GCs the chain; a crash
    budget kills the writer/compactor thread between any two steps; a
    tear budget corrupts the FINAL committed payload (the dying-disk
    case); the loader snapshots the manifest, reads fields and chain
    files in any interleaving, drops a bad FINAL entry, errors on a bad
    middle, and retries once when ``base_id`` moved under it — the
    ``load_checkpoint`` retry loop.

    Invariants (checked at every reachable state):

    * ``load_is_committed_consistent`` — a PUBLISHED load is never
      mixed/corrupt and equals a content version that was actually
      committed ("a load never observes a mid-chain tear as success";
      "torn FINAL recovers to the last complete delta");
    * ``no_silent_commit_loss`` — a load only ever drops a committed
      entry whose payload a TEAR destroyed, never one whose payload
      simply was not written yet;
    * ``seqs_never_reused`` — burned seqs never reappear;
    * ``load_version_matches_content`` — the version a load reports
      (``applied_seq``) equals the content it loaded (the serving
      hot-swap gate depends on this).

    The ``trainer_restart`` role (the elastic-recovery round): the
    trainer is the process every other role lives inside. It consumes
    stream batches 1..``trainer_steps`` in order (``Trainer.fit``'s
    loop; ``t_hi`` = the highest step whose rows its in-memory state
    holds, ``t_next`` = the stream cursor), and every delta/full save
    records the cursor at its commit (``save_delta(extra=...)`` — the
    manifest channel ``fit(autosave_every=)`` writes). A whole-process
    crash (``trainer_crashes`` budget, distinct from the thread-level
    ``crashes``) kills the saver AND compactor mid-anything; restore
    (``fit(resume_from=)`` -> ``load_checkpoint`` + ``ShardStream``
    ``skip_batches``) re-derives both the state and the stream position
    from the last COMMITTED manifest entry the load verifies — a torn
    tail resumes one autosave earlier, exactly like the load does.

    Invariant ``trainer_neither_reapplies_nor_skips_rows``: every batch
    the (possibly resumed) trainer applies is the successor of its
    in-memory content — it never re-applies a step whose rows the
    restored checkpoint already holds and never skips one (the
    bit-identical-resume contract).

    Mutations: ``commit_order="manifest_first"`` commits the manifest
    before the payload (seeded ``manifest_before_payload``);
    ``carry_seq_on_full=False`` re-arms full saves at ``last_seq=0``
    (seq reuse; pre-fix shipped behavior); ``compact_content_seq=False``
    drops the compacted manifest's content version (``applied_seq``
    reports 0; also pre-fix shipped behavior);
    ``resume_cursor="zero"`` restores the model state but re-reads the
    stream from position zero (the dead-reader/naive-restart behavior
    the ``ShardStream.skip_batches`` contract exists to prevent —
    seeded ``resume_cursor_from_zero``), ``resume_cursor="skip"``
    resumes one batch past the cursor (an off-by-one skip — seeded
    ``resume_cursor_skips_a_step``).

    Bounds: ``max_seq`` deltas, one full save, one crash, one tear, one
    load (with one retry), ``trainer_steps`` stream batches, one
    whole-process trainer crash, compaction past 2 chain entries —
    exhaustive within the budgets: 65,054 states reduced (the default
    gate) / 90,726 fully expanded at the defaults, down from the
    141,649 the PR-16 encoding cost plain BFS (footprint-driven payload
    hygiene + quiescent-payload collapse + ample fusion).
    """
    if resume_cursor not in ("exact", "zero", "skip"):
        raise ValueError(f"resume_cursor must be exact|zero|skip, "
                         f"got {resume_cursor!r}")
    init: State = {
        # manifest: None | (gen, last_seq, content_seq, chain tuple)
        "mf": (0, 0, 0, ()),
        "gen_next": 1,
        # ((seq, "ok"|"torn"), ...): payloads some manifest commit has
        # referenced. Uncommitted payloads live in "orphans" until
        # delta_commit moves them — the split keys the footprints need
        # to see that an in-flight write is invisible to every chain
        # reader (loads, restores, the compactor) until its commit.
        "files": (),
        "orphans": (),
        "f0": 0, "f1": 0,     # base field content versions
        "saver": ("idle",),
        "comp": ("off",),
        "loader": ("off",),
        "burned": frozenset(), "reused": False,
        # monitor key: the loader's publish step evaluates the three
        # load invariants ITSELF and poisons this set with the violated
        # names. Invariants then read ONLY {bad, reused, t_flag} —
        # which is what makes the loader's pc-stepping actions
        # invisible to the ample rule (the PR-18 reduction refactor;
        # verdicts are unchanged because the flags are written by the
        # same atomic step that used to create the "done" tuple the
        # old predicates inspected, and only ever grow)
        "bad": frozenset(),
        "truths": frozenset([0]),
        "crash_left": crashes, "tear_left": tears,
        "full_left": fulls, "load_left": loads, "retry_left": 1,
        # trainer_restart role: program counter, in-memory content
        # high-water step, stream cursor, committed-cursor bookkeeping
        # (seq -> cursor pairs mirror the manifest ``extra`` channel;
        # base_cursor is what a chainless manifest's base reflects)
        "t_pc": "run", "t_hi": 0, "t_next": 1,
        "t_crash_left": trainer_crashes, "t_flag": False,
        "cursors": (), "base_cursor": 0,
    }

    def files_get(s, seq):
        for q, st in s["files"]:
            if q == seq:
                return st
        return None

    def files_set(s, seq, st, key="files"):
        rest = tuple((q, x) for q, x in s[key] if q != seq)
        s[key] = tuple(sorted(rest + ((seq, st),)))

    def apply_seq(content, seq):
        """Newest-wins row overwrite of one delta over one field."""
        if content == _CORRUPT:
            return _CORRUPT
        if seq <= content:
            return content             # idempotent re-apply
        if seq == content + 1:
            return seq
        return _CORRUPT                # gap: rows from the wrong epoch

    def live(s):
        # the trainer's in-memory content = every committed delta
        return max(s["burned"], default=0)

    def committed_cursor(s):
        """Stream cursor the last committed manifest entry records
        (the ``extra`` channel) — the base's when the chain is empty."""
        return s["cursors"][-1][1] if s["cursors"] else s["base_cursor"]

    actions: List[Action] = []

    # -- delta save ---------------------------------------------------------
    def dw_guard(s):
        # the saver is the trainer's own thread (fit's blocking
        # autosave): no save from a dead process, and no empty delta —
        # a save needs rows the last commit does not cover
        return s["mf"] is not None and s["saver"] == ("idle",) \
            and s["comp"] == ("off",) and s["mf"][1] < max_seq \
            and s["t_pc"] == "run" and s["t_hi"] > committed_cursor(s)

    def commit_seq(s, seq):
        gen, _last, cseq, chain = s["mf"]
        if seq in s["burned"]:
            s["reused"] = True
        s["burned"] = s["burned"] | {seq}
        s["mf"] = (gen, seq, cseq, chain + (seq,))
        s["truths"] = s["truths"] | {seq}
        # the manifest entry's extra records the trainer cursor at the
        # save (t_hi cannot move mid-save: fit's autosave is blocking)
        s["cursors"] = s["cursors"] + ((seq, s["t_hi"]),)

    def write_branches(s, seq, key):
        """A payload lands whole, or — tear budget — torn: fs.open_atomic
        fsyncs file and directory, so a file ever observed whole can
        never tear LATER; the torn-from-birth branch models the
        dying-disk partial rename the PR-8 recovery lane exists for
        (the writer computed its crc from memory and never re-reads,
        so the commit can still follow a torn payload)."""
        ok = dict(s)
        files_set(ok, seq, "ok", key)
        ok["saver"] = ("dw", seq)
        out = [ok]
        if s["tear_left"] > 0:
            torn = dict(s)
            files_set(torn, seq, "torn", key)
            torn["tear_left"] -= 1
            torn["saver"] = ("dw", seq)
            out.append(torn)
        return out

    _dw_pc = (("saver", "idle"), ("comp", "off"), ("t_pc", "run"))
    _dw_greads = ("mf", "t_hi", "cursors", "base_cursor")
    if commit_order == "payload_first":
        def dw_apply(s):
            # the payload lands as an ORPHAN: no manifest references it
            # until delta_commit, so no chain reader can observe it —
            # which is exactly what the split files/orphans footprint
            # lets the ample rule exploit
            return write_branches(s, s["mf"][1] + 1, "orphans")
        actions.append(Action("delta_write", "saver", dw_guard, dw_apply,
                              syncs=("ckpt.delta.write",),
                              pc=_dw_pc, greads=_dw_greads,
                              reads=("mf", "orphans", "tear_left"),
                              writes=("orphans", "tear_left", "saver")))

        def dc_guard(s):
            return s["saver"][0] == "dw"

        def dc_apply(s):
            seq = s["saver"][1]
            # the commit publishes the orphan: the manifest now
            # references it, so it moves into the committed set
            st = None
            for q, x in s["orphans"]:
                if q == seq:
                    st = x
            s["orphans"] = tuple((q, x) for q, x in s["orphans"]
                                 if q != seq)
            if st is not None:
                files_set(s, seq, st)
            commit_seq(s, seq)
            s["saver"] = ("idle",)
        actions.append(Action("delta_commit", "saver", dc_guard,
                              dc_apply, syncs=("ckpt.delta.commit",),
                              pc=(("saver", "dw"),), greads=(),
                              reads=("saver", "mf", "burned", "truths",
                                     "cursors", "t_hi", "orphans",
                                     "files"),
                              writes=("mf", "burned", "reused",
                                      "truths", "cursors", "saver",
                                      "files", "orphans")))
    else:                              # mutated: manifest before payload
        def dce_apply(s):
            seq = s["mf"][1] + 1
            commit_seq(s, seq)
            s["saver"] = ("dw", seq)
        actions.append(Action("delta_commit_early", "saver", dw_guard,
                              dce_apply, syncs=("ckpt.delta.commit",),
                              pc=_dw_pc, greads=_dw_greads,
                              reads=("mf", "burned", "truths",
                                     "cursors", "t_hi"),
                              writes=("mf", "burned", "reused",
                                      "truths", "cursors", "saver")))

        def dwl_guard(s):
            return s["saver"][0] == "dw"

        def dwl_apply(s):
            # mutated order: the manifest ALREADY references this seq,
            # so the late payload is committed the instant it lands
            out = write_branches(s, s["saver"][1], "files")
            for b in out:
                b["saver"] = ("idle",)
            return out
        actions.append(Action("delta_write_late", "saver", dwl_guard,
                              dwl_apply, syncs=("ckpt.delta.write",),
                              pc=(("saver", "dw"),), greads=(),
                              reads=("saver", "files", "tear_left"),
                              writes=("files", "tear_left", "saver")))

    def crash_saver_guard(s):
        return s["saver"] != ("idle",) and s["crash_left"] > 0

    def crash_saver_apply(s):
        # the writer thread dies between steps: an uncommitted payload
        # stays an orphan (GC'd later, never read); a committed-but-
        # unwritten one stays MISSING — the mutated order's poison
        s["saver"] = ("idle",)
        s["crash_left"] -= 1
    actions.append(Action("crash_saver", "chaos", crash_saver_guard,
                          crash_saver_apply,
                          pc=(("saver", "!idle"),),
                          greads=("crash_left",), reads=(),
                          writes=("saver", "crash_left"),
                          dead=lambda s: s["crash_left"] == 0))

    # -- full save ----------------------------------------------------------
    def fs_guard(s):
        return s["saver"] == ("idle",) and s["comp"] == ("off",) \
            and s["full_left"] > 0 and s["mf"] is not None \
            and s["t_pc"] == "run"

    def fs_reset_apply(s):
        carried = s["mf"][1] if carry_seq_on_full else 0
        s["mf"] = None
        s["files"] = ()            # reset_chain GCs every delta file
        s["orphans"] = ()          # ... and every uncommitted payload
        s["cursors"] = ()          # the chain entries' extras go with it
        s["full_left"] -= 1
        # the dump will hold every in-memory row: capture the cursor
        # the re-armed manifest records (t_hi frozen — blocking save)
        s["saver"] = ("fr", carried, s["t_hi"])
    actions.append(Action("full_reset_chain", "saver", fs_guard,
                          fs_reset_apply, syncs=("ckpt.full.reset",),
                          pc=(("saver", "idle"), ("comp", "off"),
                              ("t_pc", "run")),
                          greads=("full_left", "mf"),
                          reads=("mf", "t_hi"),
                          writes=("mf", "files", "orphans", "cursors",
                                  "full_left", "saver"),
                          dead=lambda s: s["full_left"] == 0))

    def fw0_guard(s):
        return s["saver"][0] == "fr"

    def fw0_apply(s):
        s["f0"] = live(s)
        s["saver"] = ("f0",) + s["saver"][1:]
    actions.append(Action("full_write_f0", "saver", fw0_guard, fw0_apply,
                          syncs=("ckpt.writer.run",),
                          pc=(("saver", "fr"),), greads=(),
                          reads=("saver", "burned"),
                          writes=("f0", "saver")))

    def fw1_guard(s):
        return s["saver"][0] == "f0"

    def fw1_apply(s):
        s["f1"] = live(s)
        s["saver"] = ("f1",) + s["saver"][1:]
    actions.append(Action("full_write_f1", "saver", fw1_guard, fw1_apply,
                          syncs=("ckpt.writer.run",),
                          pc=(("saver", "f0"),), greads=(),
                          reads=("saver", "burned"),
                          writes=("f1", "saver")))

    def fa_guard(s):
        return s["saver"][0] == "f1"

    def fa_apply(s):
        carried = s["saver"][1]
        s["mf"] = (s["gen_next"], carried, carried, ())
        s["gen_next"] += 1
        s["base_cursor"] = s["saver"][2]
        s["saver"] = ("idle",)
    actions.append(Action("full_arm", "saver", fa_guard, fa_apply,
                          syncs=("ckpt.full.arm",),
                          pc=(("saver", "f1"),), greads=(),
                          reads=("saver", "gen_next"),
                          writes=("mf", "gen_next", "base_cursor",
                                  "saver")))

    # -- background compactor ----------------------------------------------
    def verified_tail(s):
        """Last verified chain seq (bad FINAL dropped), or None when a
        bad MIDDLE makes the chain unfoldable/unloadable."""
        chain = s["mf"][3]
        tail = None
        for i, seq in enumerate(chain):
            if files_get(s, seq) == "ok":
                tail = seq
            elif i == len(chain) - 1:
                return tail            # bad final: fold/load the prefix
            else:
                return None            # bad middle
        return tail

    def comp_start_guard(s):
        # the compactor REFUSES a chain that does not fully verify
        # (true positive found by this model: folding around a torn
        # committed entry and GC'ing it converts the documented loud
        # mid-chain refusal into silent permanent data loss — the torn
        # delta's chunks were already claim-cleared, nothing re-covers
        # them; checkpoint_delta._compact_impl now aborts instead)
        chain = s["mf"][3] if s["mf"] is not None else ()
        return s["comp"] == ("off",) and s["saver"] == ("idle",) \
            and s["t_pc"] == "run" \
            and len(chain) >= 2 and verified_tail(s) == chain[-1]

    def comp_start_apply(s):
        s["comp"] = ("run", verified_tail(s))
    actions.append(Action("compact_start", "compactor", comp_start_guard,
                          comp_start_apply, syncs=("ckpt.compact.run",),
                          pc=(("comp", "off"), ("saver", "idle"),
                              ("t_pc", "run")),
                          greads=("mf", "files"),
                          reads=("mf", "files"), writes=("comp",)))

    def fold_field(s, field, upto):
        v = s[field]
        for seq in s["mf"][3]:
            if seq > upto:
                break
            if files_get(s, seq) == "ok":
                v = apply_seq(v, seq)
        s[field] = v

    def comp_fold0_guard(s):
        return s["comp"][0] == "run"

    def comp_fold0_apply(s):
        fold_field(s, "f0", s["comp"][1])
        s["comp"] = ("c0", s["comp"][1])
    actions.append(Action("compact_fold_f0", "compactor",
                          comp_fold0_guard, comp_fold0_apply,
                          pc=(("comp", "run"),), greads=(),
                          reads=("comp", "mf", "files", "f0"),
                          writes=("f0", "comp")))

    def comp_fold1_guard(s):
        return s["comp"][0] == "c0"

    def comp_fold1_apply(s):
        fold_field(s, "f1", s["comp"][1])
        s["comp"] = ("c1", s["comp"][1])
    actions.append(Action("compact_fold_f1", "compactor",
                          comp_fold1_guard, comp_fold1_apply,
                          pc=(("comp", "c0"),), greads=(),
                          reads=("comp", "mf", "files", "f1"),
                          writes=("f1", "comp")))

    def comp_commit_guard(s):
        return s["comp"][0] == "c1"

    def comp_commit_apply(s):
        folded = s["comp"][1]
        cseq = folded if compact_content_seq else 0
        s["mf"] = (s["gen_next"], s["mf"][1], cseq, ())
        s["gen_next"] += 1
        # the folded base now reflects the folded tail's cursor; the
        # chain (and its per-entry extras) is gone
        s["base_cursor"] = dict(s["cursors"]).get(folded,
                                                  s["base_cursor"])
        s["cursors"] = ()
        s["comp"] = ("gc",)
    actions.append(Action("compact_commit", "compactor",
                          comp_commit_guard, comp_commit_apply,
                          syncs=("ckpt.compact.commit",),
                          pc=(("comp", "c1"),), greads=(),
                          reads=("comp", "mf", "gen_next", "cursors",
                                 "base_cursor"),
                          writes=("mf", "gen_next", "base_cursor",
                                  "cursors", "comp")))

    def comp_gc_guard(s):
        return s["comp"] == ("gc",)

    def comp_gc_apply(s):
        # everything the folded manifest no longer references goes —
        # committed chain payloads and crash orphans alike (no payload
        # can be mid-commit here: delta saves are disabled while the
        # compactor runs)
        s["files"] = ()
        s["orphans"] = ()
        s["comp"] = ("off",)
    actions.append(Action("compact_gc", "compactor", comp_gc_guard,
                          comp_gc_apply,
                          pc=(("comp", "gc"),), greads=(), reads=(),
                          writes=("files", "orphans", "comp")))

    def crash_comp_guard(s):
        return s["comp"] != ("off",) and s["crash_left"] > 0

    def crash_comp_apply(s):
        # fields may be partially folded under the OLD manifest — replay
        # idempotence must make any later load correct anyway
        s["comp"] = ("off",)
        s["crash_left"] -= 1
    actions.append(Action("crash_compactor", "chaos", crash_comp_guard,
                          crash_comp_apply,
                          pc=(("comp", "!off"),),
                          greads=("crash_left",), reads=(),
                          writes=("comp", "crash_left"),
                          dead=lambda s: s["crash_left"] == 0))

    # -- trainer_restart role ----------------------------------------------
    def t_step_guard(s):
        # fit's loop: one batch at a time, never while its own blocking
        # autosave is in flight
        return s["t_pc"] == "run" and s["saver"] == ("idle",) \
            and s["t_next"] <= trainer_steps

    def t_step_apply(s):
        k = s["t_next"]
        if k <= s["t_hi"] or k > s["t_hi"] + 1:
            # the batch is not the successor of the in-memory content:
            # a re-applied committed step (k <= t_hi) or a skipped one
            s["t_flag"] = True
        s["t_hi"] = max(s["t_hi"], k)
        s["t_next"] = k + 1
    actions.append(Action("trainer_step", "trainer", t_step_guard,
                          t_step_apply, syncs=("trainer.fit.step",),
                          pc=(("t_pc", "run"), ("saver", "idle")),
                          greads=("t_next",),
                          reads=("t_next", "t_hi"),
                          writes=("t_flag", "t_hi", "t_next")))

    def t_crash_guard(s):
        return s["t_pc"] == "run" and s["t_crash_left"] > 0

    def t_crash_apply(s):
        # whole-PROCESS death (SIGKILL at any sync point): the saver
        # and the background compactor die with it — uncommitted
        # payloads stay orphans, a mid-full-save dir stays unarmed, a
        # mid-fold compactor leaves partially-folded fields under the
        # old manifest. In-memory rows past the last commit are gone.
        s["t_crash_left"] -= 1
        s["t_pc"] = "dead"
        s["saver"] = ("idle",)
        s["comp"] = ("off",)
    actions.append(Action("trainer_crash", "chaos", t_crash_guard,
                          t_crash_apply,
                          pc=(("t_pc", "run"),),
                          greads=("t_crash_left",), reads=(),
                          writes=("t_crash_left", "t_pc", "saver",
                                  "comp"),
                          dead=lambda s: s["t_crash_left"] == 0))

    def t_loadable(s):
        # what load_checkpoint accepts: every non-final chain entry
        # verifies (a bad FINAL is dropped whole, a bad middle raises)
        chain = s["mf"][3]
        return all(files_get(s, q) == "ok" for q in chain[:-1])

    def t_restore_guard(s):
        # fit(resume_from=): a committed manifest must exist and load —
        # a crash mid-full-save (mf None) has nothing to resume from
        # and the dead trainer is an accepted end state
        return s["t_pc"] == "dead" and s["mf"] is not None \
            and t_loadable(s)

    def t_restore_apply(s):
        # the restored content and the stream cursor BOTH come from the
        # entry the load actually applies: a torn tail resumes one
        # autosave earlier, exactly like the load recovers
        tail = verified_tail(s)
        cur = (dict(s["cursors"]).get(tail, s["base_cursor"])
               if tail is not None else s["base_cursor"])
        s["t_pc"] = "run"
        s["t_hi"] = cur
        if resume_cursor == "exact":
            s["t_next"] = cur + 1
        elif resume_cursor == "zero":
            s["t_next"] = 1            # naive restart: stream from 0
        else:
            s["t_next"] = cur + 2      # off-by-one: skips a batch
    actions.append(Action("trainer_restore", "trainer", t_restore_guard,
                          t_restore_apply,
                          syncs=("trainer.resume.restore",),
                          pc=(("t_pc", "dead"),),
                          greads=("mf", "files"),
                          reads=("mf", "files", "cursors",
                                 "base_cursor"),
                          writes=("t_pc", "t_hi", "t_next")))

    # -- loader -------------------------------------------------------------
    def lm_guard(s):
        return s["loader"] == ("off",) and s["load_left"] > 0 \
            and s["mf"] is not None

    def lm_apply(s):
        # only the generation survives to the outcome: load_checkpoint
        # re-reads the manifest AFTER the field streams (see
        # load_read_chain), so the first read contributes nothing but
        # the base_id the finish-time coherence check compares
        s["load_left"] -= 1
        s["loader"] = ("mf", s["mf"][0])
    actions.append(Action("load_read_manifest", "loader", lm_guard,
                          lm_apply, syncs=("registry.load.start",),
                          pc=(("loader", "off"),),
                          greads=("load_left", "mf"),
                          reads=("mf", "load_left"),
                          writes=("load_left", "loader"),
                          dead=lambda s: (s["load_left"] == 0
                                          and s["retry_left"] == 0)))

    def lf0_guard(s):
        return s["loader"][0] == "mf"

    def lf0_apply(s):
        s["loader"] = ("lf0",) + s["loader"][1:] + (s["f0"],)
    actions.append(Action("load_read_f0", "loader", lf0_guard, lf0_apply,
                          pc=(("loader", "mf"),), greads=(),
                          reads=("loader", "f0"), writes=("loader",)))

    def lf1_guard(s):
        return s["loader"][0] == "lf0"

    def lf1_apply(s):
        s["loader"] = ("lf1",) + s["loader"][1:] + (s["f1"],)
    actions.append(Action("load_read_f1", "loader", lf1_guard, lf1_apply,
                          pc=(("loader", "lf0"),), greads=(),
                          reads=("loader", "f1"), writes=("loader",)))

    def lc_guard(s):
        return s["loader"][0] == "lf1"

    def lc_apply(s):
        # the replay re-reads the manifest AFTER the base fields
        # (load_checkpoint line order: fields stream first, then
        # read_manifest -> replay_chain) — together with newest-wins
        # idempotence this is what makes loads racing a mid-fold
        # compactor converge instead of publishing a mixed base; the
        # version is computed from the SAME verify pass the replay
        # performs (the registry version-coherence fix this PR)
        _pc, gen0, v0, v1 = s["loader"]
        if s["mf"] is None:
            # manifest vanished (racing full-save reset): no replay;
            # the base_id check at finish forces the retry
            s["loader"] = ("fin", gen0, 0, v0, v1, False)
            return
        chain = s["mf"][3]
        cseq = s["mf"][2]
        tail = None
        missing_drop = False
        bad_middle = False
        for i, seq in enumerate(chain):
            st = files_get(s, seq)
            if st == "ok":
                v0 = apply_seq(v0, seq)
                v1 = apply_seq(v1, seq)
                tail = seq
            elif i == len(chain) - 1:
                # verify_chain: bad FINAL entry discarded whole
                missing_drop = st is None
            else:
                bad_middle = True       # refuse: later deltas build on it
                break
        if bad_middle:
            s["loader"] = ("cerr", gen0)
        else:
            version = tail if tail is not None else cseq
            s["loader"] = ("fin", gen0, version, v0, v1, missing_drop)
    actions.append(Action("load_read_chain", "loader", lc_guard,
                          lc_apply,
                          pc=(("loader", "lf1"),), greads=(),
                          reads=("loader", "mf", "files"),
                          writes=("loader",)))

    def _retry(s, gen0):
        cur_gen = s["mf"][0] if s["mf"] is not None else -1
        if cur_gen != gen0 and s["retry_left"] > 0:
            s["retry_left"] -= 1
            s["load_left"] += 1
            s["loader"] = ("off",)
            return True
        return False

    def lfin_guard(s):
        return s["loader"][0] == "fin"

    def lfin_apply(s):
        _pc, gen0, version, v0, v1, miss = s["loader"]
        cur_gen = s["mf"][0] if s["mf"] is not None else -1
        if cur_gen != gen0:
            if not _retry(s, gen0):
                s["loader"] = ("err",)
            return
        s["loader"] = ("done", version, v0, v1, miss)
        # monitor-flag publish: evaluate the load invariants at the one
        # step that could first violate them (nothing mutates a "done"
        # loader afterwards, and truths only grows, so flag-here is
        # verdict-identical to predicate-at-every-state)
        bad = set()
        if not (v0 == v1 and v0 != _CORRUPT and v0 in s["truths"]):
            bad.add("load_is_committed_consistent")
        if miss:
            bad.add("no_silent_commit_loss")
        if version != v0:
            bad.add("load_version_matches_content")
        if bad:
            s["bad"] = s["bad"] | bad
    actions.append(Action("load_finish", "loader", lfin_guard,
                          lfin_apply, syncs=("registry.load.commit",),
                          pc=(("loader", "fin"),), greads=(),
                          reads=("loader", "mf", "retry_left",
                                 "load_left", "truths", "bad"),
                          writes=("loader", "retry_left", "load_left",
                                  "bad")))

    def lerr_guard(s):
        return s["loader"][0] == "cerr"

    def lerr_apply(s):
        # mid-chain damage: load_checkpoint raises unless base_id moved
        if not _retry(s, s["loader"][1]):
            s["loader"] = ("err",)
    actions.append(Action("load_chain_error", "loader", lerr_guard,
                          lerr_apply,
                          pc=(("loader", "cerr"),), greads=(),
                          reads=("loader", "mf", "retry_left",
                                 "load_left"),
                          writes=("loader", "retry_left",
                                  "load_left")))

    # -- invariants ---------------------------------------------------------
    # monitor-flag style (see the ``bad`` key above): every invariant
    # reads only a flag the violating action itself set, which is what
    # lets the ample rule treat the protocol's pc-stepping actions as
    # invisible. Names are unchanged from PR 11 — every seeded mutation
    # fires exactly the invariant it always fired.
    def inv_consistent(s):
        return "load_is_committed_consistent" not in s["bad"]

    def inv_no_silent_loss(s):
        return "no_silent_commit_loss" not in s["bad"]

    def inv_no_reuse(s):
        return not s["reused"]

    def inv_version(s):
        return "load_version_matches_content" not in s["bad"]

    def inv_trainer_rows(s):
        return not s["t_flag"]

    def is_done(s):
        # a dead trainer with nothing to resume from is an accepted end
        # (the crash-and-never-restart run); everything else quiesces
        # as before
        return s["saver"] == ("idle",) and s["comp"] == ("off",) \
            and s["loader"][0] in ("off", "done", "err")

    return make_model(
        "delta_chain", init, actions,
        [("load_is_committed_consistent", inv_consistent),
         ("no_silent_commit_loss", inv_no_silent_loss),
         ("seqs_never_reused", inv_no_reuse),
         ("load_version_matches_content", inv_version),
         ("trainer_neither_reapplies_nor_skips_rows", inv_trainer_rows)],
        is_done,
        inv_reads=("bad", "reused", "t_flag"),
        monotone_flags=("bad", "reused", "t_flag"),
        # a finished load's observations are published into ``bad`` at
        # load_finish; the "done" tuple payload is never read again
        collapse=(("loader", "done"),),
        notes="delta save -> atomic manifest commit, full-save chain "
              "reset, background compaction, crash/tear budgets, loads "
              "racing everything (checkpoint_delta.py + "
              "checkpoint.load_checkpoint retry) + trainer_restart: "
              "autosave cursor extras, whole-process crash, "
              "fit(resume_from=) cursor-exact resume")


# ---------------------------------------------------------------------------
# Model 5: serving lookup micro-batcher (serving/batcher.py LookupBatcher)
# ---------------------------------------------------------------------------

def serving_batcher(*, snapshot_per_flush: bool = True,
                    drain_on_shutdown: bool = True,
                    requests: int = 3, queue_cap: int = 2,
                    swaps: int = 2) -> Model:
    """The micro-batching lookup scheduler's enqueue/flush/swap/shutdown
    protocol (``serving/batcher.py`` ``LookupBatcher`` vs
    ``registry.apply_delta`` hot-swaps and ``close()``).

    Clients offer ``requests`` lookups into a bounded queue
    (``queue_cap`` — a full or closed queue rejects with a busy
    response, exactly one response either way). The batcher thread runs
    one flush at a time: COLLECT the queued batch, SNAPSHOT the
    published model reference ONCE (the one-reference-grab discipline
    ``ServingModel.lookup`` already pins for single lookups), then
    resolve the batch in two pull sub-steps (the per-variable-group
    pulls of a mixed batch — the window a concurrent hot-swap can land
    in), then respond to every member. A publisher applies deltas
    (``swaps`` budget) at any interleaving, including mid-flush. A
    shutdown stops the queue accepting and DRAINS what was already
    accepted before stopping.

    Invariants:

    * ``batch_serves_one_version`` — every request of one batch is
      answered from the SAME model version: the flush's single
      snapshot. This is the batched-equals-unbatched parity guarantee
      under a delta hot-swap landing mid-batch ("a batch snapshots
      exactly one version").
    * ``no_request_lost_at_shutdown`` — once the batcher is stopped and
      idle with an empty queue, no accepted request is still waiting:
      every enqueued request got exactly one response (rows or busy).

    Mutations: ``snapshot_per_flush=False`` re-reads the live model
    reference at every pull sub-step instead of snapshotting once (the
    seeded ``resnapshot_per_pull`` — a swap between two variable
    groups' pulls hands one batch rows from two versions);
    ``drain_on_shutdown=False`` discards the queue at shutdown without
    responding (the seeded ``drop_queue_on_shutdown`` — accepted
    requests hang forever).

    Bounds: ``requests`` offers, ``queue_cap`` queue slots, ``swaps``
    hot-swaps, one in-flight flush — exhaustive within the budget.
    """
    init: State = {"version": 0, "swaps_left": swaps,
                   "accepting": True, "queue": (),
                   "batcher": ("idle",), "mixed": False}
    for i in range(requests):
        init[f"q{i}"] = "new"          # new|queued|rejected|served
        init[f"q{i}_ver"] = -1

    actions: List[Action] = []

    def offer_ok(i):
        def guard(s):
            return s[f"q{i}"] == "new" and s["accepting"] \
                and len(s["queue"]) < queue_cap

        def apply(s):
            s[f"q{i}"] = "queued"
            s["queue"] = s["queue"] + (i,)
        return Action(f"offer_ok({i})", f"client{i}", guard, apply,
                      syncs=("serving.batch.enqueue",))

    def offer_busy(i):
        def guard(s):
            return s[f"q{i}"] == "new" and \
                (not s["accepting"] or len(s["queue"]) >= queue_cap)

        def apply(s):
            s[f"q{i}"] = "rejected"     # the 429-busy response
        return Action(f"offer_busy({i})", f"client{i}", guard, apply,
                      syncs=("serving.batch.reject",))

    for i in range(requests):
        actions.append(offer_ok(i))
        actions.append(offer_busy(i))

    # -- the flush state machine -------------------------------------------
    def collect_guard(s):
        return s["batcher"] == ("idle",) and s["queue"] != ()

    def collect_apply(s):
        s["batcher"] = ("col", s["queue"])
        s["queue"] = ()
    actions.append(Action("collect", "batcher", collect_guard,
                          collect_apply,
                          syncs=("serving.batch.collect",)))

    def snap_guard(s):
        return s["batcher"][0] == "col"

    def snap_apply(s):
        # the ONE reference grab; the mutation defers reading to the
        # pulls (snapshot value -1 = "no snapshot taken")
        snap = s["version"] if snapshot_per_flush else -1
        s["batcher"] = ("p0", s["batcher"][1], snap)
    actions.append(Action("snapshot", "batcher", snap_guard, snap_apply,
                          syncs=("serving.batch.snapshot",)))

    def serve(s, members, snap):
        ver = snap if snap >= 0 else s["version"]
        for i in members:
            s[f"q{i}"] = "served"
            s[f"q{i}_ver"] = ver

    def pull0_guard(s):
        return s["batcher"][0] == "p0"

    def pull0_apply(s):
        _pc, batch, snap = s["batcher"]
        serve(s, batch[:1], snap)       # first variable group
        s["batcher"] = ("p1", batch, snap)
    actions.append(Action("pull_group_a", "batcher", pull0_guard,
                          pull0_apply, syncs=("serving.batch.pull",)))

    def pull1_guard(s):
        return s["batcher"][0] == "p1"

    def pull1_apply(s):
        _pc, batch, snap = s["batcher"]
        serve(s, batch[1:], snap)       # remaining variable groups
        vers = {s[f"q{i}_ver"] for i in batch}
        if len(vers) > 1:
            s["mixed"] = True
        s["batcher"] = ("resp", batch)
    actions.append(Action("pull_group_b", "batcher", pull1_guard,
                          pull1_apply, syncs=("serving.batch.pull",)))

    def resp_guard(s):
        return s["batcher"][0] == "resp"

    def resp_apply(s):
        s["batcher"] = ("idle",)
    actions.append(Action("respond", "batcher", resp_guard, resp_apply,
                          syncs=("serving.batch.respond",)))

    # -- hot-swap publisher (registry.apply_delta order) --------------------
    def swap_guard(s):
        return s["swaps_left"] > 0

    def swap_apply(s):
        s["swaps_left"] -= 1
        s["version"] += 1
    actions.append(Action("apply_delta", "publisher", swap_guard,
                          swap_apply,
                          syncs=("registry.find", "registry.swap.build",
                                 "registry.swap.commit")))

    # -- shutdown -----------------------------------------------------------
    def stop_guard(s):
        return s["accepting"]

    def stop_apply(s):
        s["accepting"] = False
        if not drain_on_shutdown:
            s["queue"] = ()             # mutated: accepted requests dropped
    actions.append(Action("shutdown", "control", stop_guard, stop_apply,
                          syncs=("serving.batch.shutdown",)))

    # -- invariants ---------------------------------------------------------
    def inv_one_version(s):
        return not s["mixed"]

    def inv_no_lost(s):
        # stopped + idle + empty queue, yet an accepted request still
        # waits: it will never be answered
        if s["accepting"] or s["queue"] != () \
                or s["batcher"] != ("idle",):
            return True
        return all(s[f"q{i}"] != "queued" for i in range(requests))

    def is_done(s):
        return s["batcher"] == ("idle",) and s["queue"] == () \
            and all(s[f"q{i}"] in ("served", "rejected")
                    for i in range(requests))

    return make_model(
        "serving_batcher", init, actions,
        [("batch_serves_one_version", inv_one_version),
         ("no_request_lost_at_shutdown", inv_no_lost)],
        is_done,
        notes="LookupBatcher bounded enqueue -> collect/snapshot/pull/"
              "respond flush vs apply_delta hot-swaps and drain-on-"
              "shutdown (serving/batcher.py)")


# ---------------------------------------------------------------------------
# Model 6: multi-host delta round (per-host shard-local writers + one
# cross-host manifest commit) — ROADMAP item 3, models-first
# ---------------------------------------------------------------------------

def multihost_delta(*, verify_all: bool = True, durable_ack: bool = True,
                    hosts: int = 3, rounds: int = 3) -> Model:
    """Per-host delta writers with a single cross-host manifest commit.

    ``hosts`` interchangeable writer hosts each persist a shard-local
    delta payload for the current round (``ckpt.delta.write``), then
    acknowledge to the coordinator (reserved ``ckpt.multihost.ack`` —
    ack strictly AFTER the durable write). The coordinator verifies it
    holds an ack from EVERY host (reserved ``ckpt.multihost.verify``)
    before the one manifest commit that publishes the cross-host
    version (``ckpt.delta.commit``). A host may crash at any point
    (one-crash budget): a crash before the ack may lose the un-synced
    payload; recovery re-enters the writer loop and re-pushes the
    current round idempotently (``ckpt.writer.run`` — re-writing an
    already-durable payload is a no-op union).

    Invariants (poison-flag form so the commit step stays
    ample-eligible): ``no_torn_cross_host_publish`` — the manifest
    never publishes a version some host's payload is missing for;
    ``committed_version_monotone``.

    Obligation: after every crash/recover detour the fleet still
    converges — ``mf_version`` reaches ``rounds`` on every run.

    Mutations: ``verify_all=False`` commits on a quorum of
    ``hosts - 1`` acks (the "one straggler can't hold the round"
    shortcut) — the missing host's payload is torn out of the
    published version; ``durable_ack=False`` lets a host ack from
    ``idle`` before its payload is durable (ack-before-fsync) — the
    coordinator counts an ack whose bytes never land.
    """
    names = [f"h{i}" for i in range(hosts)]
    init: State = {"round": 1, "mf_version": 0, "acks": frozenset(),
                   "c_pc": "collect", "crash_left": 1,
                   "torn": False, "mono_bad": False}
    for h in names:
        init[f"{h}_pc"] = "idle"
        init[f"{h}_wr"] = frozenset()

    actions: List[Action] = []
    for h in names:
        def wr_apply(s, h=h):
            s[f"{h}_pc"] = "written"
            s[f"{h}_wr"] = s[f"{h}_wr"] | {s["round"]}
        actions.append(Action(
            f"{h}_write", h,
            lambda s, h=h: s[f"{h}_pc"] == "idle"
            and s["c_pc"] == "collect",
            wr_apply, syncs=("ckpt.delta.write",),
            pc=((f"{h}_pc", "idle"), ("c_pc", "collect")),
            greads=(), reads=("round", f"{h}_wr"),
            writes=(f"{h}_pc", f"{h}_wr")))

        def ack_apply(s, h=h):
            s[f"{h}_pc"] = "acked"
            s["acks"] = s["acks"] | {h}
        actions.append(Action(
            f"{h}_ack", h,
            lambda s, h=h: s[f"{h}_pc"] == "written",
            ack_apply, syncs=("ckpt.multihost.ack",),
            pc=((f"{h}_pc", "written"),),
            greads=(), reads=("acks",), writes=(f"{h}_pc", "acks")))
        if not durable_ack:
            # mutated: the ack races the fsync — it can fire while the
            # payload write hasn't happened (and now never will: the
            # host sits in "acked" with nothing on disk)
            actions.append(Action(
                f"{h}_ack_early", h,
                lambda s, h=h: s[f"{h}_pc"] == "idle"
                and s["c_pc"] == "collect",
                ack_apply, syncs=("ckpt.multihost.ack",),
                pc=((f"{h}_pc", "idle"), ("c_pc", "collect")),
                greads=(), reads=("acks",), writes=(f"{h}_pc", "acks")))

        def crash_apply(s, h=h):
            # a crash between the write syscall and the ack may lose
            # the un-synced payload (branch) — once acked, the payload
            # was durable by protocol order, so it survives
            out = dict(s)
            out[f"{h}_pc"] = "dead"
            out["crash_left"] -= 1
            if s[f"{h}_pc"] == "written":
                lost = dict(out)
                lost[f"{h}_wr"] = out[f"{h}_wr"] - {s["round"]}
                return [out, lost]
            return out
        actions.append(Action(
            f"{h}_crash", h,
            lambda s, h=h: s["crash_left"] > 0
            and s[f"{h}_pc"] != "dead",
            crash_apply,
            pc=((f"{h}_pc", "!dead"),), greads=("crash_left",),
            reads=(f"{h}_pc", f"{h}_wr", "round", "crash_left"),
            writes=(f"{h}_pc", f"{h}_wr", "crash_left"),
            dead=lambda s: s["crash_left"] == 0))

        actions.append(Action(
            f"{h}_recover", h,
            lambda s, h=h: s[f"{h}_pc"] == "dead",
            lambda s, h=h: s.__setitem__(f"{h}_pc", "idle"),
            syncs=("ckpt.writer.run",),
            pc=((f"{h}_pc", "dead"),),
            greads=(), reads=(), writes=(f"{h}_pc",)))

    need = hosts if verify_all else hosts - 1

    actions.append(Action(
        "coord_verify", "coordinator",
        lambda s: s["c_pc"] == "collect" and len(s["acks"]) >= need,
        lambda s: s.__setitem__("c_pc", "commit"),
        syncs=("ckpt.multihost.verify",),
        pc=(("c_pc", "collect"),), greads=("acks",),
        reads=(), writes=("c_pc",)))

    def commit_apply(s):
        seq = s["round"]
        if any(seq not in s[f"{h}_wr"] for h in names):
            s["torn"] = True
        if seq <= s["mf_version"]:
            s["mono_bad"] = True
        s["mf_version"] = seq
        s["acks"] = frozenset()
        # the commit ENDS the round for every live host: writes and
        # acks are round-scoped, so a host still mid-write restarts
        # its loop for the new round (otherwise its stale pc would
        # let a round-N ack count toward round N+1)
        for h in names:
            if s[f"{h}_pc"] != "dead":
                s[f"{h}_pc"] = "idle"
        s["round"] = seq + 1
        s["c_pc"] = "collect" if s["round"] <= rounds else "done"
    actions.append(Action(
        "coord_commit", "coordinator",
        lambda s: s["c_pc"] == "commit",
        commit_apply, syncs=("ckpt.delta.commit",),
        pc=(("c_pc", "commit"),), greads=(),
        reads=tuple(["round", "mf_version"]
                    + [f"{h}_wr" for h in names]
                    + [f"{h}_pc" for h in names]),
        writes=tuple(["torn", "mono_bad", "mf_version", "acks",
                      "round", "c_pc"] + [f"{h}_pc" for h in names])))

    return make_model(
        "multihost_delta", init, actions,
        [("no_torn_cross_host_publish", lambda s: not s["torn"]),
         ("committed_version_monotone", lambda s: not s["mono_bad"])],
        lambda s: s["c_pc"] == "done",
        notes="N-host shard-local delta writers, ack-after-durable-"
              "write, verify-all-acks before the single cross-host "
              "manifest commit; crash mid-round recovers by idempotent "
              "re-push (ROADMAP item 3, models-first)",
        inv_reads=("torn", "mono_bad"),
        monotone_flags=("torn", "mono_bad"),
        symmetry=(tuple(names),),
        obligations=(Obligation(
            "fleet_converges_after_idempotent_repush",
            lambda s: s["mf_version"] >= rounds, within=40),))


# ---------------------------------------------------------------------------
# Model 7: elastic training membership (join/leave/failure-detect vs
# barrier-free shard reassignment) — ROADMAP item 3, models-first
# ---------------------------------------------------------------------------

def training_membership(*, fenced_reassign: bool = True,
                        failure_detect: bool = True,
                        workers: int = 2, shards: int = 2,
                        steps: int = 3) -> Model:
    """Worker join/leave/failure-detect against barrier-free resume.

    ``workers`` interchangeable trainer workers own disjoint shard
    sets; worker 0 starts up owning every shard, the rest start out.
    A worker joins by restoring from the committed chain (reserved
    ``train.member.join`` + the real ``trainer.resume.restore``),
    steps on the shards it owns (``trainer.fit.step``), may leave
    gracefully once it owns nothing, and may fail. The failure
    detector (reserved ``train.member.detect``) suspects dead workers
    — and, like any timeout detector, can FALSELY suspect a slow live
    one. The controller grants a suspect's shard to a live worker only
    after fencing: the old owner must be confirmed dead, and the grant
    atomically releases before assigning.

    Invariant: ``shard_never_trained_by_two_live_workers`` — a step
    never writes a shard another live worker also owns (poison flag:
    concurrent optimizer writes on one shard corrupt rows silently).

    Obligation: from every state where some shard has no live owner,
    every run re-establishes a live owner for every shard within the
    bound (detect -> grant -> the grantee is stepping again).

    Mutations: ``fenced_reassign=False`` grants on mere suspicion
    without releasing (the suspect may be alive and still stepping) —
    two live workers train the same shard; ``failure_detect=False``
    drops the detector, so a dead worker's shards are never granted:
    the liveness obligation fires (runs end with an orphaned shard).
    """
    wnames = [f"w{i}" for i in range(workers)]
    snames = tuple(f"s{k}" for k in range(shards))
    init: State = {"suspect": frozenset(), "fail_left": 1,
                   "slow_left": 1, "leave_left": 1,
                   "steps_left": steps, "double": False}
    for w in wnames:
        init[f"{w}_pc"] = "out"
        init[f"{w}_own"] = frozenset()
    init["w0_pc"] = "up"
    init["w0_own"] = frozenset(snames)

    own_keys = tuple(f"{w}_own" for w in wnames)
    pc_keys = tuple(f"{w}_pc" for w in wnames)
    actions: List[Action] = []

    for w in wnames:
        def join_apply(s, w=w):
            s[f"{w}_pc"] = "up"
            s["suspect"] = s["suspect"] - {w}
        actions.append(Action(
            f"{w}_join", w,
            lambda s, w=w: s[f"{w}_pc"] == "out",
            join_apply,
            syncs=("train.member.join", "trainer.resume.restore"),
            pc=((f"{w}_pc", "out"),),
            greads=(), reads=("suspect",),
            writes=(f"{w}_pc", "suspect")))

        def step_apply(s, w=w):
            s["steps_left"] -= 1
            mine = s[f"{w}_own"]
            for o in wnames:
                if o != w and s[f"{o}_pc"] == "up" \
                        and mine & s[f"{o}_own"]:
                    s["double"] = True
        actions.append(Action(
            f"{w}_step", w,
            lambda s, w=w: s[f"{w}_pc"] == "up"
            and s["steps_left"] > 0 and s[f"{w}_own"],
            step_apply, syncs=("trainer.fit.step",),
            pc=((f"{w}_pc", "up"),),
            greads=("steps_left", f"{w}_own"),
            reads=own_keys + pc_keys + ("steps_left",),
            writes=("steps_left", "double"),
            dead=lambda s: s["steps_left"] == 0))

        def fail_apply(s, w=w):
            s[f"{w}_pc"] = "dead"
            s["fail_left"] -= 1
        actions.append(Action(
            f"{w}_fail", w,
            lambda s, w=w: s[f"{w}_pc"] == "up" and s["fail_left"] > 0,
            fail_apply,
            pc=((f"{w}_pc", "up"),), greads=("fail_left",),
            reads=("fail_left",), writes=(f"{w}_pc", "fail_left"),
            dead=lambda s: s["fail_left"] == 0))

        def leave_apply(s, w=w):
            s[f"{w}_pc"] = "out"
            s["leave_left"] -= 1
        actions.append(Action(
            f"{w}_leave", w,
            lambda s, w=w: s[f"{w}_pc"] == "up"
            and not s[f"{w}_own"] and s["leave_left"] > 0,
            leave_apply,
            pc=((f"{w}_pc", "up"),),
            greads=(f"{w}_own", "leave_left"),
            reads=("leave_left",), writes=(f"{w}_pc", "leave_left"),
            dead=lambda s: s["leave_left"] == 0))

        if failure_detect:
            def det_apply(s, w=w):
                s["suspect"] = s["suspect"] | {w}
            actions.append(Action(
                f"detect_dead_{w}", "detector",
                lambda s, w=w: s[f"{w}_pc"] == "dead"
                and w not in s["suspect"],
                det_apply, syncs=("train.member.detect",),
                pc=((f"{w}_pc", "dead"),), greads=("suspect",),
                reads=("suspect",), writes=("suspect",)))
            # the timeout detector's false positive: a live worker
            # suspected for being slow (bounded so the clean model's
            # fencing is what prevents the double-train, not luck)
            # a falsely suspected LIVE worker heartbeats again and
            # clears itself — without this the controller can wedge:
            # a suspected grantee is ineligible for grants forever
            def hb_apply(s, w=w):
                s["suspect"] = s["suspect"] - {w}
            actions.append(Action(
                f"{w}_heartbeat", w,
                lambda s, w=w: s[f"{w}_pc"] == "up"
                and w in s["suspect"],
                hb_apply, syncs=("train.member.detect",),
                pc=((f"{w}_pc", "up"),), greads=("suspect",),
                reads=("suspect",), writes=("suspect",)))

            def det_slow_apply(s, w=w):
                s["suspect"] = s["suspect"] | {w}
                s["slow_left"] -= 1
            actions.append(Action(
                f"detect_slow_{w}", "detector",
                lambda s, w=w: s[f"{w}_pc"] == "up"
                and s["slow_left"] > 0 and w not in s["suspect"],
                det_slow_apply,
                syncs=("train.member.detect",),
                pc=((f"{w}_pc", "up"),),
                greads=("slow_left", "suspect"),
                reads=("suspect", "slow_left"),
                writes=("suspect", "slow_left"),
                dead=lambda s: s["slow_left"] == 0))

    for sk in snames:
        for o in wnames:
            for j in wnames:
                if o == j:
                    continue

                def grant_guard(s, sk=sk, o=o, j=j):
                    if sk not in s[f"{o}_own"] or o not in s["suspect"]:
                        return False
                    if s[f"{j}_pc"] != "up" or j in s["suspect"]:
                        return False
                    if fenced_reassign and s[f"{o}_pc"] != "dead":
                        return False      # the fence: confirmed dead
                    return True

                def grant_apply(s, sk=sk, o=o, j=j):
                    if fenced_reassign:
                        s[f"{o}_own"] = s[f"{o}_own"] - {sk}
                    # mutated: assign WITHOUT release — the suspect
                    # (possibly alive) still owns and steps on it
                    s[f"{j}_own"] = s[f"{j}_own"] | {sk}
                actions.append(Action(
                    f"grant_{sk}_{o}_to_{j}", "controller",
                    grant_guard, grant_apply,
                    pc=((f"{j}_pc", "up"),),
                    greads=(f"{o}_own", "suspect", f"{o}_pc"),
                    reads=(f"{o}_own", f"{j}_own"),
                    writes=(f"{o}_own", f"{j}_own")))

    def covered(s):
        return all(any(sk in s[f"{w}_own"] and s[f"{w}_pc"] == "up"
                       for w in wnames) for sk in snames)

    def inv_single_writer(s):
        return not s["double"]

    def is_done(s):
        owners = [w for sk in snames for w in wnames
                  if sk in s[f"{w}_own"] and s[f"{w}_pc"] == "up"]
        return len(owners) == len(snames) and covered(s)

    return make_model(
        "training_membership", init, actions,
        [("shard_never_trained_by_two_live_workers",
          inv_single_writer)],
        is_done,
        notes="elastic worker join/leave/fail + timeout detector with "
              "false positives; fenced release-then-grant shard "
              "reassignment vs barrier-free resume (ROADMAP item 3, "
              "models-first)",
        inv_reads=("double",), monotone_flags=("double",),
        symmetry=(tuple(wnames),),
        obligations=(Obligation(
            "every_shard_regains_a_live_owner",
            covered, within=24,
            after=lambda s: not covered(s)),))


# ---------------------------------------------------------------------------
# Model 8: N->M reshard through the checkpoint path — ROADMAP item 3,
# models-first
# ---------------------------------------------------------------------------

def reshard(*, apply_before_release: bool = True,
            idempotent_apply: bool = True) -> Model:
    """2 -> 3 host resize migrating embedding rows through the
    checkpoint path.

    Four abstract row ranges: ``r0`` stays on ``h0``; ``r1``
    (h0 -> h2) and ``r3`` (h1 -> h2) migrate to the new host, and
    ``r2`` (h1 -> h0) rebalances between the surviving old hosts —
    three concurrent migrations with two distinct destinations. Per
    row the protocol is copy-then-release: the
    destination persists the row (reserved ``reshard.row.apply``),
    and only then does the source drop its copy and the ownership map
    flip (reserved ``reshard.row.release``). The new host may crash
    once mid-migration: an in-flight (staged, un-released) row
    restarts its migration; the re-apply is idempotent — an
    already-persisted row is recognized and NOT folded a second time.

    Invariants (poison flags): ``no_row_lost`` — at no point is a row
    absent from every host (the release-before-apply crash window);
    ``no_row_double_applied`` — recovery never folds a row into the
    destination twice (double optimizer state corrupts the row).
    End-state: ``resize_publishes_target_ownership`` — once both
    migrations are done the ownership map equals the target exactly.

    Obligation: the resize completes on every run within the bound.

    Mutations: ``apply_before_release=False`` releases the source
    before the destination persisted (a crash in the window leaves
    the row in NO host); ``idempotent_apply=False`` re-folds an
    already-applied row after crash recovery.
    """
    target = ("h0", "h2", "h0", "h2")
    init: State = {"owner": ("h0", "h0", "h1", "h1"),
                   "crash_left": 1, "dup": False, "lost": False,
                   "final_bad": False, "resize": "run"}
    migrations = {"r1": (1, "h0", "h2"), "r3": (3, "h1", "h2"),
                  "r2": (2, "h1", "h0")}
    for m in migrations:
        init[f"{m}_pc"] = "pending"
        init[f"{m}_applied"] = False

    actions: List[Action] = []
    for m, (idx, src, dst) in migrations.items():
        def apply_apply(s, m=m, idx=idx, src=src, dst=dst):
            if s[f"{m}_applied"] and not idempotent_apply:
                s["dup"] = True           # re-folded after recovery
            s[f"{m}_applied"] = True
            if apply_before_release:
                s[f"{m}_pc"] = "staged"
            else:
                # mutated order: this is the SECOND step
                s[f"{m}_pc"] = "done"
        if apply_before_release:
            actions.append(Action(
                f"{m}_apply", dst,
                lambda s, m=m: s[f"{m}_pc"] == "pending",
                apply_apply, syncs=("reshard.row.apply",),
                pc=((f"{m}_pc", "pending"),), greads=(),
                reads=(f"{m}_applied",),
                writes=(f"{m}_pc", f"{m}_applied", "dup")))
        else:
            actions.append(Action(
                f"{m}_apply", dst,
                lambda s, m=m: s[f"{m}_pc"] == "staged",
                apply_apply, syncs=("reshard.row.apply",),
                pc=((f"{m}_pc", "staged"),), greads=(),
                reads=(f"{m}_applied",),
                writes=(f"{m}_pc", f"{m}_applied", "dup")))

        def release_apply(s, m=m, idx=idx, dst=dst):
            ow = list(s["owner"])
            ow[idx] = dst
            s["owner"] = tuple(ow)
            if apply_before_release:
                s[f"{m}_pc"] = "done"
            else:
                s[f"{m}_pc"] = "staged"   # source gone, not yet applied
        if apply_before_release:
            actions.append(Action(
                f"{m}_release", src,
                lambda s, m=m: s[f"{m}_pc"] == "staged",
                release_apply, syncs=("reshard.row.release",),
                pc=((f"{m}_pc", "staged"),), greads=(),
                reads=("owner",), writes=("owner", f"{m}_pc")))
        else:
            actions.append(Action(
                f"{m}_release", src,
                lambda s, m=m: s[f"{m}_pc"] == "pending",
                release_apply, syncs=("reshard.row.release",),
                pc=((f"{m}_pc", "pending"),), greads=(),
                reads=("owner",), writes=("owner", f"{m}_pc")))

    # a destination host crash restarts every migration staged INTO
    # it (its un-released staging area is gone); migrations into the
    # other destination are untouched
    for dst in sorted({d for _i, _s, d in migrations.values()}):
        mine = sorted(m for m, (_i, _s, d) in migrations.items()
                      if d == dst)

        def crash_apply(s, mine=mine):
            s["crash_left"] -= 1
            for m in mine:
                if s[f"{m}_pc"] == "staged":
                    if not s[f"{m}_applied"]:
                        # source already released, destination never
                        # persisted: the row is in NO host
                        s["lost"] = True
                    s[f"{m}_pc"] = "pending"
        actions.append(Action(
            f"{dst}_crash", dst,
            lambda s, mine=mine: s["crash_left"] > 0
            and any(s[f"{m}_pc"] == "staged" for m in mine),
            crash_apply,
            greads=tuple(["crash_left"] + [f"{m}_pc" for m in mine]),
            reads=tuple([f"{m}_pc" for m in mine]
                        + [f"{m}_applied" for m in mine]
                        + ["crash_left"]),
            writes=tuple([f"{m}_pc" for m in mine]
                         + ["lost", "crash_left"]),
            dead=lambda s: s["crash_left"] == 0))

    def finish_apply(s):
        if s["owner"] != target:
            s["final_bad"] = True
        s["resize"] = "done"
    actions.append(Action(
        "resize_finish", "coordinator",
        lambda s: s["resize"] == "run"
        and all(s[f"{m}_pc"] == "done" for m in migrations),
        finish_apply,
        pc=(("resize", "run"),),
        greads=tuple(f"{m}_pc" for m in migrations),
        reads=("owner",), writes=("final_bad", "resize")))

    return make_model(
        "reshard", init, actions,
        [("no_row_lost", lambda s: not s["lost"]),
         ("no_row_double_applied", lambda s: not s["dup"]),
         ("resize_publishes_target_ownership",
          lambda s: not s["final_bad"])],
        lambda s: s["resize"] == "done",
        notes="2->3 host resize: per-row copy-then-release through the "
              "checkpoint path, idempotent re-apply after a crash of "
              "the new host (ROADMAP item 3, models-first)",
        inv_reads=("lost", "dup", "final_bad"),
        monotone_flags=("lost", "dup", "final_bad"),
        obligations=(Obligation(
            "resize_completes",
            lambda s: s["resize"] == "done", within=16),))


# ---------------------------------------------------------------------------
# shipped registry + schedule export
# ---------------------------------------------------------------------------

def shipped_models() -> List[Model]:
    """The eight protocol models the CLI checks exhaustively: five
    shipped-code roles plus the three models-first multi-host designs
    (ROADMAP item 3 — their reserved sync points name the contract the
    implementing PR must emit)."""
    return [delta_chain(), hot_swap(), dirty_tracker(), ha_registry(),
            serving_batcher(), multihost_delta(), training_membership(),
            reshard()]


def sample_traces(model: Model, k: int = 2
                  ) -> List[List[Tuple[str, State]]]:
    """Up to ``k`` representative full traces of a CLEAN model (the
    shortest accepted quiescent run and the deepest state's run) — the
    sampled schedules ``--emit-schedules`` exports for replay."""
    parents: Dict[Any, Tuple[Any, Optional[str]]] = {
        model.init: (None, None)}
    queue = deque([model.init])
    done_states: List[Any] = []
    last = model.init
    while queue:
        fs = queue.popleft()
        last = fs
        state = dict(fs)
        if model.is_done(state) and len(done_states) < 1:
            done_states.append(fs)
        _enabled, succs = _successors(model, state)
        for label, b in succs:
            fb = _freeze(b)
            if fb not in parents:
                parents[fb] = (fs, label)
                queue.append(fb)
    picks = done_states + [last]
    traces = []
    seen = set()
    for fs in picks:
        if fs in seen:
            continue
        seen.add(fs)
        traces.append(_trace_of(parents, fs))
        if len(traces) >= k:
            break
    return traces
