"""graftscope: span tracing, latency histograms, and the byte ledger.

The reference dedicates a whole plane to performance accounting
(``evaluate_performance``, per-op pull/push timing, the TF-Serving
metrics exporter — SURVEY §5.1); our observability plane was flat
counter sums plus a per-plane wall-time split. This module is the
measurement substrate underneath it, in three parts:

**1. Span API** — ``with span("pull", plane="a2a"): ...`` records one
timed interval into a lock-free-per-thread ring buffer (each thread
appends only to its own ring; a registry lock is taken once per thread,
at ring creation) and into the histogram registry. Spans are
``under_trace``-guarded: a span opened while JAX is tracing records the
event once, tagged ``trace_time`` (the body runs per COMPILE there, and
a trace-time duration must never pollute the per-step latency
histograms). When a ``jax.profiler`` trace is active the span also
enters a ``TraceAnnotation`` (``step_span`` a ``StepTraceAnnotation``),
so host spans nest inside device profiles. ``export_chrome_trace``
writes the rings as Chrome-trace/Perfetto JSON (open in
https://ui.perfetto.dev or ``chrome://tracing``).

**2. Histogram metrics** — fixed log-spaced buckets
(:data:`BUCKET_BOUNDS`, 4 per decade over 1e-7..1e8) shared by every
series, with p50/p95/p99 estimates by geometric interpolation inside
the hit bucket (error bounded by one bucket ratio,
:data:`BUCKET_RATIO`). Every closed span feeds
``span_<kind>_seconds``; ``utils/observability.record_batch_stats``
feeds the per-table ``pull_rows`` / ``pull_unique_ratio`` /
``pull_key_skew`` distributions. ``prometheus_lines()`` renders proper
``_bucket``/``_sum``/``_count`` series — surfaced on the serving
``GET /metrics`` endpoint through ``observability.prometheus_text``.

**3. Expected-vs-measured byte ledger** — reuse the
:mod:`.programs` lowering + :mod:`.contracts` HLO cost analysis to
compute each plane's per-step expected collective bytes (the same
numbers the contracts bound), pair them with the measured pull/push
span quantiles, and report achieved GB/s per exchange.
``python -m tools.graftscope`` drives an N-step capture and prints the
per-plane/per-stage table.

Import discipline: stdlib + :mod:`.concurrency` only at module level;
jax is looked up lazily (and only if something else already imported
it), so the graftlint/graftrace CLIs and host-only callers never pay
for it.
"""

from __future__ import annotations

import bisect
import contextlib
import contextvars
import dataclasses
import json
import os
import re
import sys
import threading
import time
import uuid
import weakref
from collections import deque
from typing import Any, Dict, List, Mapping, Optional, Tuple

from .concurrency import make_lock

# ---------------------------------------------------------------------------
# enablement
# ---------------------------------------------------------------------------

_TRACE_ENV = "OE_SCOPE_TRACE"
_tracing_forced: Optional[bool] = None


def set_tracing(on: Optional[bool]) -> None:
    """Force span-ring recording on/off; ``None`` restores the
    environment default (``OE_SCOPE_TRACE``). Histograms are always fed
    (they are aggregate metrics, one bucket bump per span); only the
    per-event ring buffers are gated."""
    global _tracing_forced
    _tracing_forced = on


def tracing_enabled() -> bool:
    if _tracing_forced is not None:
        return _tracing_forced
    return os.environ.get(_TRACE_ENV, "").lower() in ("1", "true", "yes",
                                                      "on")


def _trace_state_clean() -> bool:
    """False while JAX is tracing (the span is running at trace time,
    once per compile — not once per step). True when jax was never even
    imported: host-only processes cannot be under a trace."""
    jax = sys.modules.get("jax")
    if jax is None:
        return True
    try:
        return jax.core.trace_state_clean()
    except Exception:  # noqa: BLE001 — any API drift reads as "clean"
        return True


def _profiler():
    """``jax.profiler`` iff jax is already imported, else None — the
    TraceAnnotation pass-through must never be the thing that drags jax
    into a host-only process."""
    jax = sys.modules.get("jax")
    return getattr(jax, "profiler", None) if jax is not None else None


# ---------------------------------------------------------------------------
# request-scoped trace ids
# ---------------------------------------------------------------------------

# the active trace/request id: set by the serving clients at request
# entry and by the REST handlers from the X-OE-Trace header, read by
# record_span so every span closed on the request path carries the same
# ``trace`` arg in the exported Perfetto trace. A contextvar (not a
# bare thread-local) so async frameworks hosting the client still
# scope it per task; plain threads each start with the default (None).
_TRACE_ID: "contextvars.ContextVar[Optional[str]]" = \
    contextvars.ContextVar("oe_trace_id", default=None)

# trace ids are for stitching, not identity — 16 hex chars keep trace
# args short while collisions stay vanishingly rare per capture window
TRACE_ID_CHARS = 16


def new_trace_id() -> str:
    return uuid.uuid4().hex[:TRACE_ID_CHARS]


def current_trace_id() -> Optional[str]:
    """The trace id of the enclosing :func:`trace_context`, or None."""
    return _TRACE_ID.get()


@contextlib.contextmanager
def trace_context(trace_id: Optional[str] = None):
    """Scope a trace/request id: spans recorded inside carry it as the
    ``trace`` arg in the exported trace, so one request's client span,
    router fan-out spans, and server-side lookup spans stitch into one
    story. With no argument, the ENCLOSING id is reused if one is
    active (a sharded fan-out keeps its parent's id) and a fresh id is
    minted otherwise. Propagate across processes via the ``X-OE-Trace``
    HTTP header (serving/rest.py reads it back into this context)."""
    tid = str(trace_id) if trace_id else (_TRACE_ID.get() or new_trace_id())
    token = _TRACE_ID.set(tid)
    try:
        yield tid
    finally:
        _TRACE_ID.reset(token)


# ---------------------------------------------------------------------------
# per-thread span rings
# ---------------------------------------------------------------------------

RING_CAPACITY = 65536

# module-level time origin: every ring's timestamps share it, so the
# exported trace is cross-thread consistent
_EPOCH = time.perf_counter()

_REG_LOCK = make_lock("scope.rings")
_RINGS: List["_Ring"] = []
# events of rings whose owner thread has exited (the Trainer's per-batch
# lookahead threads, HTTP handler threads): their spans must survive
# into the export, but the ring OBJECTS must not accumulate forever —
# dead rings are folded into this bounded deque as (tid, name, event)
_RETIRED: "deque" = deque(maxlen=RING_CAPACITY)
_retired_total = 0       # ever retired — minus len(_RETIRED) = dropped
_TLS = threading.local()


class _Ring:
    """One thread's span events; only the owner thread appends (GIL
    makes the single-slot writes safe to snapshot from the exporter)."""

    __slots__ = ("buf", "n", "tid", "name", "owner")

    def __init__(self, owner: threading.Thread):
        self.buf: List[tuple] = []
        self.n = 0          # total appended (>= len(buf) once wrapped)
        self.tid = owner.ident or 0
        self.name = owner.name
        self.owner = weakref.ref(owner)

    def append(self, ev: tuple) -> None:
        # operate on a LOCAL snapshot of the buffer: a concurrent
        # reset() swaps self.buf out, and a check-then-index against the
        # live attribute could hit the freshly emptied list (a metrics
        # reset must never raise out of instrumented production code —
        # a write into the swapped-out buffer is simply discarded)
        buf = self.buf
        if len(buf) < RING_CAPACITY:
            buf.append(ev)
        else:
            try:
                buf[self.n % RING_CAPACITY] = ev
            except IndexError:
                buf.append(ev)
        self.n += 1

    @property
    def dropped(self) -> int:
        return max(0, self.n - RING_CAPACITY)


def _retire_dead_locked() -> None:
    """Fold rings of exited threads into the bounded retired deque
    (caller holds ``_REG_LOCK``). A dead thread can never append again,
    so its buffer snapshot is final."""
    global _retired_total
    alive = []
    for ring in _RINGS:
        t = ring.owner()
        if t is not None and t.is_alive():
            alive.append(ring)
        else:
            for ev in list(ring.buf):
                _RETIRED.append((ring.tid, ring.name, ev))
                _retired_total += 1
    _RINGS[:] = alive


def _my_ring() -> _Ring:
    ring = getattr(_TLS, "ring", None)
    if ring is None:
        ring = _TLS.ring = _Ring(threading.current_thread())
        with _REG_LOCK:
            _retire_dead_locked()
            _RINGS.append(ring)
    return ring


def reset() -> None:
    """Drop every recorded span event (test isolation). Rings stay
    registered — live threads still hold their thread-locals."""
    global _retired_total
    with _REG_LOCK:
        for ring in _RINGS:
            ring.buf = []
            ring.n = 0
        _RETIRED.clear()
        _retired_total = 0


# nominal bytes per buffered span event (7-tuple + small label dict):
# an estimate for the memory gauges, not an exact accounting — the
# rings are bounded (RING_CAPACITY) so the estimate's error is too
EVENT_NOMINAL_BYTES = 160


def ring_stats() -> Dict[str, int]:
    """Live span-ring memory gauges for ``observability.memory_stats``:
    buffered event count (live rings + the retired deque), events
    dropped by ring wrap/retirement eviction, and the approximate bytes
    those buffers hold."""
    with _REG_LOCK:
        events = sum(len(r.buf) for r in _RINGS) + len(_RETIRED)
        dropped = sum(r.dropped for r in _RINGS) \
            + (_retired_total - len(_RETIRED))
    return {"events": events, "dropped": max(0, dropped),
            "approx_bytes": events * EVENT_NOMINAL_BYTES}


# ---------------------------------------------------------------------------
# histogram registry
# ---------------------------------------------------------------------------

# fixed log-spaced bounds shared by every histogram: 4 buckets per
# decade over [1e-7, 1e12] — microsecond spans, multi-minute checkpoint
# saves, and BYTE-valued series (grouped exchanges reach hundreds of MB
# at production scale; a 1e8 cap would saturate them into +Inf)
BUCKET_RATIO = 10.0 ** 0.25
BUCKET_BOUNDS: Tuple[float, ...] = tuple(10.0 ** (e / 4.0)
                                         for e in range(-28, 49))


class _Hist:
    __slots__ = ("counts", "sum", "count")

    def __init__(self):
        self.counts = [0] * (len(BUCKET_BOUNDS) + 1)   # +1: overflow
        self.sum = 0.0
        self.count = 0


def _labels_key(labels: Mapping[str, Any]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(items: Tuple[Tuple[str, str], ...], extra: str = "") -> str:
    parts = [f'{k}="{_escape_label(v)}"' for k, v in items]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class HistogramRegistry:
    """Named histograms + labeled counters over the shared bucket grid.

    Thread-safe via one registry lock (observations are a dict lookup +
    a bisect + three adds — nanoseconds next to the spans they measure).
    """

    def __init__(self):
        self._lock = make_lock("scope.metrics")
        self._hists: Dict[Tuple[str, tuple], _Hist] = {}
        self._counters: Dict[Tuple[str, tuple], float] = {}

    def observe(self, name: str, value: float, **labels) -> None:
        key = (name, _labels_key(labels))
        idx = bisect.bisect_left(BUCKET_BOUNDS, value)
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = _Hist()
            h.counts[idx] += 1
            h.sum += value
            h.count += 1

    def inc(self, name: str, value: float = 1.0, **labels) -> None:
        key = (name, _labels_key(labels))
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + value

    def count(self, name: str, **labels) -> int:
        with self._lock:
            h = self._hists.get((name, _labels_key(labels)))
            return h.count if h is not None else 0

    def counter(self, name: str, **labels) -> float:
        """Current value of one labeled counter (0.0 when never bumped)
        — with no labels, the SUM across every label set of ``name``
        (the serving clients label connection/request counters by
        endpoint; callers usually want the fleet total)."""
        with self._lock:
            if labels:
                return self._counters.get((name, _labels_key(labels)), 0.0)
            return sum(v for (n, _l), v in self._counters.items()
                       if n == name)

    def sum(self, name: str, **labels) -> float:
        with self._lock:
            h = self._hists.get((name, _labels_key(labels)))
            return h.sum if h is not None else 0.0

    def quantile(self, name: str, q: float, **labels) -> float:
        """Quantile estimate by geometric interpolation inside the hit
        bucket — error bounded by one :data:`BUCKET_RATIO` factor. NaN
        when the series is empty or unknown."""
        with self._lock:
            h = self._hists.get((name, _labels_key(labels)))
            if h is None or h.count == 0:
                return float("nan")
            counts = list(h.counts)
            total = h.count
        target = max(1.0, q * total)
        cum = 0.0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if cum + c >= target:
                if i >= len(BUCKET_BOUNDS):      # overflow bucket
                    return BUCKET_BOUNDS[-1]
                hi = BUCKET_BOUNDS[i]
                lo = (BUCKET_BOUNDS[i - 1] if i > 0
                      else BUCKET_BOUNDS[0] / BUCKET_RATIO)
                frac = (target - cum) / c
                return lo * (hi / lo) ** frac
            cum += c
        return BUCKET_BOUNDS[-1]

    def series(self) -> List[Tuple[str, Dict[str, str]]]:
        with self._lock:
            return [(name, dict(labels))
                    for name, labels in sorted(self._hists)]

    def reset(self) -> None:
        with self._lock:
            self._hists.clear()
            self._counters.clear()

    def prometheus_lines(self, prefix: str = "oe") -> List[str]:
        """Render every histogram as ``_bucket``/``_sum``/``_count``
        series and every counter as a ``_total``. Zero-count buckets are
        elided (the cumulative values present are complete information);
        the ``+Inf`` bucket is always emitted."""
        with self._lock:
            hists = {k: (list(h.counts), h.sum, h.count)
                     for k, h in self._hists.items()}
            counters = dict(self._counters)
        lines: List[str] = []
        last_name = None
        for (name, labels) in sorted(hists):
            counts, total_sum, total_count = hists[(name, labels)]
            base = f"{prefix}_{name}"
            if name != last_name:
                lines.append(f"# HELP {base} graftscope histogram "
                             f"`{name}` (log-spaced buckets)")
                lines.append(f"# TYPE {base} histogram")
                last_name = name
            cum = 0
            for i, c in enumerate(counts[:len(BUCKET_BOUNDS)]):
                if c == 0:
                    continue
                cum += c
                lab = _fmt_labels(labels,
                                  f'le="{BUCKET_BOUNDS[i]:.4g}"')
                lines.append(f"{base}_bucket{lab} {cum}")
            lab = _fmt_labels(labels, 'le="+Inf"')
            lines.append(f"{base}_bucket{lab} {total_count}")
            lab = _fmt_labels(labels)
            lines.append(f"{base}_sum{lab} {total_sum:.10g}")
            lines.append(f"{base}_count{lab} {total_count}")
        last_name = None
        for (name, labels) in sorted(counters):
            base = f"{prefix}_{name}_total"
            if name != last_name:
                lines.append(f"# HELP {base} graftscope counter "
                             f"`{name}`")
                lines.append(f"# TYPE {base} counter")
                last_name = name
            lines.append(f"{base}{_fmt_labels(labels)} "
                         f"{counters[(name, labels)]:.10g}")
        return lines


HISTOGRAMS = HistogramRegistry()


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

def _hist_name(kind: str) -> str:
    return "span_" + re.sub(r"[^0-9A-Za-z]", "_", kind) + "_seconds"


def record_span(kind: str, t0: float, dt: float,
                labels: Optional[Mapping[str, Any]] = None, *,
                error: Optional[str] = None,
                trace_time: bool = False,
                detail: Optional[Mapping[str, Any]] = None) -> None:
    """Record one finished interval: histogram sample (skipped for
    trace-time spans — compile time is not step latency) + ring event
    when tracing is on. The direct entry point for callers that already
    timed the work themselves (``observability.plane_timed``)."""
    labels = labels or {}
    if not trace_time:
        HISTOGRAMS.observe(_hist_name(kind), dt, **labels)
        if error is not None:
            HISTOGRAMS.inc("span_errors", kind=kind, **labels)
    if tracing_enabled():
        det = dict(detail) if detail else None
        # the active request trace id rides in the trace args ONLY —
        # per-request ids in histogram labels would explode the registry
        tid = _TRACE_ID.get()
        if tid is not None and (det is None or "trace" not in det):
            det = dict(det or {})
            det["trace"] = tid
        _my_ring().append((kind, t0, dt, dict(labels) or None, error,
                           trace_time, det))


class Span:
    """Context manager for one timed interval (see :func:`span`)."""

    __slots__ = ("kind", "labels", "detail", "t0", "_ann", "_trace_time")

    def __init__(self, kind: str, labels: Optional[dict] = None,
                 detail: Optional[dict] = None,
                 annotation: Optional[Any] = None):
        self.kind = kind
        self.labels = labels
        self.detail = detail
        self._ann = annotation

    def set_label(self, key: str, value: Any) -> "Span":
        """Attach/overwrite one histogram label BEFORE the span closes
        (labels are read at exit) — how the HTTP handlers stamp the
        response status code onto the request span they run under."""
        if self.labels is None:
            self.labels = {}
        self.labels[str(key)] = value
        return self

    def __enter__(self) -> "Span":
        self._trace_time = not _trace_state_clean()
        if self._ann is not None:
            # best-effort like construction: a profiler-session failure
            # must never take down the instrumented production path
            try:
                self._ann.__enter__()
            except Exception:  # noqa: BLE001
                self._ann = None
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        dt = time.perf_counter() - self.t0
        if self._ann is not None:
            try:
                self._ann.__exit__(exc_type, exc, tb)
            except Exception:  # noqa: BLE001 — the span record below
                pass           # must still land
        record_span(self.kind, self.t0, dt, self.labels,
                    error=exc_type.__name__ if exc_type else None,
                    trace_time=self._trace_time, detail=self.detail)
        return False


def span(kind: str, detail: Optional[Mapping[str, Any]] = None,
         **labels) -> Span:
    """Open a span: ``with span("pull", plane="a2a", table="user"): ...``

    ``labels`` become histogram labels AND trace args — keep them
    low-cardinality (plane, table, method). ``detail`` goes to the trace
    event only (signs, paths, step numbers). Error exits are recorded
    with the exception type and re-raised. Under a JAX trace the event
    is recorded once, tagged ``trace_time``, and skips the histograms.
    """
    ann = None
    prof = _profiler()
    if prof is not None:
        try:
            ann = prof.TraceAnnotation(kind)
        except Exception:  # noqa: BLE001 — annotation is best-effort
            ann = None
    return Span(kind, dict(labels) or None,
                dict(detail) if detail else None, ann)


def step_span(step: int, name: str = "step") -> Span:
    """Span for one whole train step, with ``StepTraceAnnotation``
    pass-through so device profilers attribute work to step numbers."""
    ann = None
    prof = _profiler()
    if prof is not None:
        try:
            ann = prof.StepTraceAnnotation(name, step_num=int(step))
        except Exception:  # noqa: BLE001 — annotation is best-effort
            ann = None
    return Span(name, None, {"step": int(step)}, ann)


# ---------------------------------------------------------------------------
# Chrome-trace / Perfetto export
# ---------------------------------------------------------------------------

def export_chrome_trace(path: Optional[str] = None, *,
                        process_name: Optional[str] = None
                        ) -> Dict[str, Any]:
    """Snapshot every thread's ring as Chrome-trace JSON (Perfetto- and
    ``chrome://tracing``-loadable). Returns the trace dict; writes it to
    ``path`` when given. Timestamps are microseconds from the module's
    load-time origin; per-thread metadata events carry thread names,
    and ``process_name`` labels this process in the viewer. The
    ``oeEpoch`` key records the origin on the system-wide monotonic
    clock so multi-process captures (serving replicas + load
    generator) merge onto ONE timeline (``merge_chrome_traces``)."""
    pid = os.getpid()
    events: List[Dict[str, Any]] = []
    if process_name:
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": str(process_name)}})

    def _event(tid: int, ev: tuple) -> Dict[str, Any]:
        kind, t0, dt, labels, error, trace_time, detail = ev
        args: Dict[str, Any] = dict(labels or {})
        if detail:
            args.update(detail)
        if error:
            args["error"] = error
        if trace_time:
            args["trace_time"] = True
        return {"name": kind, "ph": "X", "cat": "graftscope",
                "ts": (t0 - _EPOCH) * 1e6, "dur": dt * 1e6,
                "pid": pid, "tid": tid, "args": args}

    with _REG_LOCK:
        _retire_dead_locked()
        rings = [(r.tid, r.name, r.dropped, list(r.buf)) for r in _RINGS]
        retired = list(_RETIRED)
        retired_dropped = _retired_total - len(_RETIRED)
    if retired_dropped > 0:
        # the bounded retired deque evicted old dead-thread spans — the
        # trace must say so, like the per-ring dropped markers below
        events.append({"ph": "M", "name": "graftscope_dropped",
                       "pid": pid, "tid": 0,
                       "args": {"retired_dropped": retired_dropped}})
    named = set()
    for tid, name, dropped, buf in rings:
        named.add((tid, name))
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": tid, "args": {"name": name}})
        if dropped:
            events.append({"ph": "M", "name": "graftscope_dropped",
                           "pid": pid, "tid": tid,
                           "args": {"dropped": dropped}})
        events.extend(_event(tid, ev) for ev in buf)
    for tid, name, ev in retired:
        if (tid, name) not in named:
            named.add((tid, name))
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": tid, "args": {"name": name}})
        events.append(_event(tid, ev))
    events.sort(key=lambda e: e.get("ts", -1.0))
    trace = {"traceEvents": events, "displayTimeUnit": "ms",
             "oeEpoch": _EPOCH}
    if path:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(trace, f)
    return trace


def merge_chrome_traces(base: Dict[str, Any],
                        others: List[Dict[str, Any]],
                        path: Optional[str] = None) -> Dict[str, Any]:
    """Fold traces captured by OTHER processes (serving replicas) into
    ``base`` (the client's capture) on one timeline: each process's
    ``oeEpoch`` offsets its microsecond timestamps onto the base
    origin. ``time.perf_counter`` is the system-wide monotonic clock on
    Linux, so cross-process spans line up for real — a request's
    server-side span sits inside its client span in Perfetto. Distinct
    pids keep per-process tracks separate; the shared ``trace`` args
    stitch one request's story across them."""
    base_epoch = float(base.get("oeEpoch", 0.0))
    events = list(base.get("traceEvents", []))
    for tr in others:
        off_us = (float(tr.get("oeEpoch", base_epoch)) - base_epoch) * 1e6
        for e in tr.get("traceEvents", []):
            e = dict(e)
            if "ts" in e:
                e["ts"] = e["ts"] + off_us
            events.append(e)
    events.sort(key=lambda e: e.get("ts", -1.0))
    merged = {"traceEvents": events, "displayTimeUnit": "ms",
              "oeEpoch": base_epoch}
    if path:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(merged, f)
    return merged


# ---------------------------------------------------------------------------
# expected-vs-measured byte ledger
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ExpectedBytes:
    """One plane program's HLO-derived per-device collective cost."""

    plane: str
    program: str                       # "pull" | "push"
    total: int                         # sum of per-op largest buffers
    per_op: Mapping[str, Tuple[int, int]]   # op -> (count, bytes)
    params: Mapping[str, int]
    # compiled memory ledger (graftwatch: jaxcompat.compiled_memory_stats
    # of the SAME program) — None when the backend exposes no analysis
    memory: Optional[Mapping[str, int]] = None


def expected_collective_bytes(hlo_text: str
                              ) -> Tuple[int, Dict[str, Tuple[int, int]]]:
    """(total, per-op) expected collective bytes of one compiled
    program: per instance the LARGEST single buffer (async ``-start``
    tuples carry operand and result — summing every buffer would
    double-count), summed per op via ``contracts.summarize(largest=
    True)`` — the same accounting ``contracts.OpBudget.max_total``
    bounds."""
    from . import contracts
    per_op = contracts.summarize(hlo_text, largest=True)
    return sum(b for _c, b in per_op.values()), per_op


def plane_expected_bytes(mesh, plane: str, program: str, *,
                         batch: int = 1024, dim: int = 16,
                         use_hash: bool = False, tables: int = 3,
                         check: bool = True) -> ExpectedBytes:
    """Lower one plane's pull/push exactly as the training path runs it
    (:mod:`.programs`) and cost-account its collectives. With ``check``
    the program is also audited against its registered contract, so the
    ledger's expected bytes provably sit inside the bounds
    ``contracts.py`` enforces."""
    from . import contracts, programs
    from ..utils import jaxcompat
    if plane == "a2a+grouped":
        build = (programs.compile_grouped_pull if program == "pull"
                 else programs.compile_grouped_push)
        compiled, params = build(mesh, tables=tables, batch=batch,
                                 dim=dim, use_hash=use_hash)
    else:
        build = (programs.compile_pull if program == "pull"
                 else programs.compile_push)
        compiled, params = build(mesh, plane, batch=batch, dim=dim,
                                 use_hash=use_hash)
    txt = compiled.as_text()
    if check:
        contracts.check_program(txt, plane, program, **params)
    total, per_op = expected_collective_bytes(txt)
    return ExpectedBytes(plane=plane, program=program, total=total,
                         per_op=per_op, params=params,
                         memory=jaxcompat.compiled_memory_stats(compiled))


def ledger_rows(expected: List[ExpectedBytes]) -> List[Dict[str, Any]]:
    """Join expected bytes with the measured pull/push span histograms
    (``span_pull_seconds{plane=...}`` etc.): per row calls, p50/p95
    latency, expected collective bytes, achieved GB/s at the p50, and
    the program's expected per-device HBM peak (graftwatch memory
    ledger; None when the backend exposes no memory analysis)."""
    rows = []
    for e in expected:
        name = _hist_name(e.program)
        calls = HISTOGRAMS.count(name, plane=e.plane)
        p50 = HISTOGRAMS.quantile(name, 0.5, plane=e.plane)
        p95 = HISTOGRAMS.quantile(name, 0.95, plane=e.plane)
        gbps = (e.total / p50 / 1e9) if calls and p50 == p50 and p50 > 0 \
            else float("nan")
        rows.append({"plane": e.plane, "stage": e.program,
                     "calls": calls, "p50_ms": p50 * 1e3,
                     "p95_ms": p95 * 1e3, "expected_bytes": e.total,
                     "per_op": dict(e.per_op), "gbps_p50": gbps,
                     "hbm_peak_bytes": (e.memory or {}).get("peak_bytes"),
                     "temp_bytes": (e.memory or {}).get("temp_bytes")})
    return rows


def format_ledger(rows: List[Dict[str, Any]]) -> str:
    """Fixed-width per-plane/per-stage table for terminals and logs."""
    head = (f"{'plane':<14}{'stage':<7}{'calls':>6}{'p50_ms':>10}"
            f"{'p95_ms':>10}{'expected_B':>12}{'GB/s@p50':>10}"
            f"{'HBM_MiB':>9}")
    out = [head, "-" * len(head)]
    for r in rows:
        peak = r.get("hbm_peak_bytes")
        hbm = f"{peak / (1 << 20):.2f}" if peak is not None else "n/a"
        out.append(
            f"{r['plane']:<14}{r['stage']:<7}{r['calls']:>6}"
            f"{r['p50_ms']:>10.3f}{r['p95_ms']:>10.3f}"
            f"{r['expected_bytes']:>12}{r['gbps_p50']:>10.4f}"
            f"{hbm:>9}")
    return "\n".join(out)
