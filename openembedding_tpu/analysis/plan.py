"""graftplan core: observed stats window -> EnvConfig + rationale.

The offline planner (``tools/graftplan``) replaces folklore tuning
("use a2a+cache when the workload looks skewed, size batch_rows to a
few x p99") with one deterministic function from OBSERVED numbers to
an :class:`~openembedding_tpu.utils.envconfig.EnvConfig`:

* a **stats window** (:func:`collect_window`, exported by
  ``tools/graftscope --export-stats``) carries the per-table
  ``pull_unique_ratio`` / ``pull_key_skew`` gauges, the
  ``serving_lookup_rows`` histogram, cache hit counters and the ingest
  stall accounting out of a live run;
* **trajectory records** (``tools/graftwatch --record``) matching the
  window's device fingerprint calibrate the two hardware constants of
  the cost model — seconds per exchanged byte and seconds per
  collective launch (:func:`calibrate`);
* every registered plane's :class:`~.contracts.PlaneSpec` prices the
  observed workload under that calibration (:func:`plane_costs`), and
  the serving / ingest sections pick their knobs from the measured
  distributions (:func:`build_plan`).

Everything here is pure arithmetic over the window dict: no wall
clock, no RNG, no environment reads — the same window + trajectory
bytes always produce a byte-identical EnvConfig (asserted by
``tests/test_graftplan.py``), so a plan can be reviewed in a PR diff.

Honest caveat, printed in the rationale: a cpu-mesh calibration prices
XLA's CPU collectives, not ICI. The RELATIVE plane ranking transfers
(byte and launch counts are contract-audited per plane); the absolute
seconds do not.
"""

from __future__ import annotations

import dataclasses
import json
import math
from typing import (Any, Dict, Iterable, List, Mapping, Optional,
                    Sequence, Tuple)

from ..utils import envconfig
from . import contracts

STATS_SCHEMA_VERSION = 1
STATS_KIND = "stats_window"

# calibration fallbacks when no fingerprint-matched trajectory record
# exists: an effective 2 GB/s per-device exchange and 50us per
# collective launch — the right ORDER for the cpu8 dev mesh, and only
# the relative plane ranking is consumed anyway (see module docs)
DEFAULT_PER_BYTE_S = 1.0 / 2e9
DEFAULT_PER_LAUNCH_S = 50e-6

# planning defaults where the window is silent
DEFAULT_TRAIN_BATCH = 1024
DEFAULT_DIM = 16
ITEMSIZE = 4

# cache-K ladder: observed top-key share of the pull stream -> the
# replicated hot-row cache size worth paying HBM for (0 = no cache)
CACHE_K_LADDER: Tuple[Tuple[float, int], ...] = (
    (0.02, 0), (0.10, 64), (0.25, 128), (1.01, 256))

# serving-knob sizing rules (README "graftplan"): coalesce ~4 p95
# requests per flush, wait ~4 mean interarrivals, queue 8 flushes deep.
# The flush width is deliberately conservative — it is sized from the
# REQUEST-SHAPE window only (the planner cannot see saturation
# dynamics in a request-size histogram); under sustained overload the
# online tuner walks rows up toward the envelope ceiling (4x this)
# where flush amortization peaks — that gap is exactly what the
# tools/graftload --drift A/B measures.
ROWS_PER_FLUSH_P95 = 4
WAIT_INTERARRIVALS = 4
QUEUE_FLUSHES = 8


def _pow2ceil(v: float) -> int:
    return 1 << max(0, math.ceil(math.log2(max(1.0, v))))


def _clamp(v: int, lo: int, hi: int) -> int:
    return max(lo, min(hi, v))


# --- the stats window --------------------------------------------------------

def collect_window(*, window_s: float, fingerprint: str = "unknown",
                   device: Optional[Mapping[str, Any]] = None,
                   table_dims: Optional[Mapping[str, int]] = None
                   ) -> Dict[str, Any]:
    """Snapshot the live observability state into one stats-window
    dict (the schema :func:`build_plan` consumes and
    ``tools/graftscope --export-stats`` serialises).

    ``window_s`` is the wall duration the counters cover (the caller
    measured it; this module never reads a clock). ``table_dims``
    annotates embedding dims the metrics plane cannot see.
    """
    from ..utils import observability
    from . import scope

    dims = dict(table_dims or {})

    # per-table workload gauges (always-on) + the gated pull histograms
    tables: Dict[str, Dict[str, Any]] = {}
    gauges = observability.labeled_gauges()

    def _gauge(name: str, table: str) -> Optional[float]:
        series = gauges.get(name, {})
        return series.get((("table", table),))

    names = set()
    for key in gauges.get("pull_unique_ratio_last", {}):
        labels = dict(key)
        if "table" in labels:
            names.add(labels["table"])
    for name, labels in scope.HISTOGRAMS.series():
        if name == "pull_rows" and "table" in labels:
            names.add(labels["table"])
    names.update(dims)
    for t in sorted(names):
        entry: Dict[str, Any] = {
            "pull_unique_ratio": _gauge("pull_unique_ratio_last", t),
            "pull_key_skew": _gauge("pull_key_skew_last", t),
            "dim": int(dims[t]) if t in dims else None,
        }
        n = scope.HISTOGRAMS.count("pull_rows", table=t)
        entry["pull_rows_count"] = n
        entry["pull_rows_p50"] = (
            scope.HISTOGRAMS.quantile("pull_rows", 0.5, table=t)
            if n else None)
        tables[t] = entry

    # serving request-size distribution, pooled conservatively across
    # table series (max over per-table quantiles — knob sizing wants
    # the widest table, not the average)
    lookup = {"count": 0, "p50": None, "p95": None, "p99": None,
              "sum": 0.0}
    for name, labels in scope.HISTOGRAMS.series():
        if name != "serving_lookup_rows":
            continue
        n = scope.HISTOGRAMS.count(name, **labels)
        lookup["count"] += n
        lookup["sum"] += scope.HISTOGRAMS.sum(name, **labels)
        for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
            v = scope.HISTOGRAMS.quantile(name, q, **labels)
            if v == v:  # not NaN
                cur = lookup[key]
                lookup[key] = v if cur is None else max(cur, v)

    stalls_n = scope.HISTOGRAMS.count("ingest_stall_ms")
    ingest = {
        "pops": stalls_n,
        "stall_ms_sum": scope.HISTOGRAMS.sum("ingest_stall_ms"),
        "stall_ms_p95": (
            scope.HISTOGRAMS.quantile("ingest_stall_ms", 0.95)
            if stalls_n else None),
    }

    return {
        "schema_version": STATS_SCHEMA_VERSION,
        "kind": STATS_KIND,
        "fingerprint": fingerprint,
        "device": dict(device) if device else None,
        "window_s": float(window_s),
        "tables": tables,
        "serving": {"lookup_rows": lookup},
        "cache": observability.cache_stats(),
        "ingest": ingest,
    }


def validate_window(window: Any) -> List[str]:
    """Schema problems with one stats window ([] == valid)."""
    if not isinstance(window, Mapping):
        return [f"window: expected a dict, got {type(window).__name__}"]
    p: List[str] = []
    if window.get("kind") != STATS_KIND:
        p.append(f"kind: expected {STATS_KIND!r}, "
                 f"got {window.get('kind')!r}")
    if window.get("schema_version") != STATS_SCHEMA_VERSION:
        p.append(f"schema_version: expected {STATS_SCHEMA_VERSION}, "
                 f"got {window.get('schema_version')!r}")
    if not isinstance(window.get("window_s"), (int, float)) \
            or window.get("window_s", 0) <= 0:
        p.append("window_s: must be a positive number")
    if not isinstance(window.get("fingerprint"), str):
        p.append("fingerprint: must be a string")
    for key, typ in (("tables", Mapping), ("serving", Mapping),
                     ("cache", Mapping), ("ingest", Mapping)):
        if not isinstance(window.get(key), typ):
            p.append(f"{key}: missing or not a mapping")
    return p


def load_window(path: str) -> Dict[str, Any]:
    """Read + validate one stats-window JSON file."""
    with open(path, "r", encoding="utf-8") as f:
        window = json.load(f)
    problems = validate_window(window)
    if problems:
        raise ValueError(
            f"{path}: not a graftplan stats window:\n  "
            + "\n  ".join(problems))
    return window


# --- hardware calibration ----------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Calibration:
    """The cost model's two hardware constants + their provenance."""

    per_byte_s: float
    per_launch_s: float
    n_records: int
    source: str          # "trajectory" | "defaults"


def _record_params(plane: str, batch: int, dim: int) -> Dict[str, Any]:
    p: Dict[str, Any] = {"global_batch": batch, "dim": dim,
                         "itemsize": ITEMSIZE}
    if plane == "a2a+bf16":
        p["wire_itemsize"] = 2
    elif plane == "a2a+int8":
        # pull rides the bf16 leg, push the int8 one; the int8 push
        # form reads wire_itemsize=1
        p["wire_itemsize"] = 1
    if plane == "a2a+grouped":
        # graftwatch --record benches the 3-table grouped collection
        p.update(num_tables=3, dim_bucket=_pow2ceil(dim))
    return p


def _record_cost_terms(rec: Mapping[str, Any]
                       ) -> Optional[Tuple[float, float, float]]:
    """(bytes, launches, seconds_per_step) of one trajectory record
    under its plane's declared cost model, or None when unusable."""
    plane = rec.get("plane")
    spec = contracts.PLANE_SPECS.get(plane)
    cfg = rec.get("config") or {}
    eps = rec.get("eps")
    try:
        batch, dim = int(cfg["batch"]), int(cfg["dim"])
    except (KeyError, TypeError, ValueError):
        return None
    if spec is None or not isinstance(eps, (int, float)) or eps <= 0 \
            or batch <= 0 or dim <= 0:
        return None
    params = _record_params(plane, batch, dim)
    if plane == "a2a+int8":
        pull = contracts.declared_exchange_bytes(
            plane, "pull", dict(params, wire_itemsize=2))
        push = contracts.declared_exchange_bytes(plane, "push", params)
        nbytes = float(pull + push)
    else:
        nbytes = float(sum(
            contracts.declared_exchange_bytes(plane, prog, params)
            for prog in ("pull", "push")))
    launches = float(spec.launches["pull"] + spec.launches["push"])
    return nbytes, launches, batch / float(eps)


def calibrate(records: Iterable[Mapping[str, Any]],
              fingerprint: str) -> Calibration:
    """Fit seconds = per_byte * bytes + per_launch * launches over the
    fingerprint-matched trajectory records (least squares through the
    declared byte/launch counts). Falls back to the documented
    defaults when the trajectory has nothing usable for this hardware
    — the planner stays deterministic either way.
    """
    rows: List[Tuple[float, float, float]] = []
    for rec in records:
        if not isinstance(rec, Mapping):
            continue
        if rec.get("fingerprint") != fingerprint:
            continue
        terms = _record_cost_terms(rec)
        if terms is not None:
            rows.append(terms)
    if len(rows) < 2:
        return Calibration(DEFAULT_PER_BYTE_S, DEFAULT_PER_LAUNCH_S,
                           len(rows), "defaults")
    # 2x2 normal equations for t ~ a*bytes + b*launches
    sbb = sum(b * b for b, _, _ in rows)
    sll = sum(l * l for _, l, _ in rows)
    sbl = sum(b * l for b, l, _ in rows)
    sbt = sum(b * t for b, _, t in rows)
    slt = sum(l * t for _, l, t in rows)
    det = sbb * sll - sbl * sbl
    if det > 0:
        a = (sbt * sll - slt * sbl) / det
        b = (slt * sbb - sbt * sbl) / det
        if a > 0 and b > 0:
            return Calibration(a, b, len(rows), "trajectory")
    # collinear or non-physical fit: pin the launch constant and take
    # the median implied byte cost
    implied = sorted(
        max(0.0, (t - l * DEFAULT_PER_LAUNCH_S)) / nb
        for nb, l, t in rows if nb > 0)
    if implied and implied[len(implied) // 2] > 0:
        return Calibration(implied[len(implied) // 2],
                           DEFAULT_PER_LAUNCH_S, len(rows),
                           "trajectory")
    return Calibration(DEFAULT_PER_BYTE_S, DEFAULT_PER_LAUNCH_S,
                       len(rows), "defaults")


def load_trajectory(path: str) -> List[Dict[str, Any]]:
    """Best-effort jsonl reader (missing file -> []); schema noise is
    skipped record-wise by :func:`calibrate`."""
    records: List[Dict[str, Any]] = []
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict):
                    records.append(rec)
    except OSError:
        return []
    return records


# --- plane pricing -----------------------------------------------------------

def _table_batch(entry: Mapping[str, Any]) -> int:
    v = entry.get("pull_rows_p50")
    if isinstance(v, (int, float)) and v > 0:
        return max(1, int(round(v)))
    return DEFAULT_TRAIN_BATCH


def _table_dim(entry: Mapping[str, Any]) -> int:
    v = entry.get("dim")
    if isinstance(v, int) and v > 0:
        return v
    return DEFAULT_DIM


def _mean(values: Sequence[float], default: float) -> float:
    vals = [v for v in values if isinstance(v, (int, float))]
    return sum(vals) / len(vals) if vals else default


def choose_cache_k(key_skew: float) -> int:
    """The cache-K ladder over the observed top-key share."""
    for bound, k in CACHE_K_LADDER:
        if key_skew < bound:
            return k
    return CACHE_K_LADDER[-1][1]


def plane_costs(window: Mapping[str, Any], calib: Calibration
                ) -> Dict[str, Dict[str, Any]]:
    """Price every registered plane for the window's observed workload:
    effective step seconds = workload_factor * wire bytes * per_byte
    + launches * host_step_units * per_launch, plus the declared HBM
    overhead (reported, not scored — it is a budget, not a latency).
    Per-table planes dispatch one program pair per table; the grouped
    plane dispatches one pair per GROUP.
    """
    tables = window.get("tables") or {}
    entries = list(tables.values()) or [{}]
    skew = _mean([e.get("pull_key_skew") for e in entries], 0.0)
    uniq = _mean([e.get("pull_unique_ratio") for e in entries], 1.0)
    cache = window.get("cache") or {}
    hits = float(cache.get("cache_hits", 0) or 0)
    misses = float(cache.get("cache_misses", 0) or 0)
    cache_k = choose_cache_k(skew)
    if hits + misses > 0:
        hit_ratio = hits / (hits + misses)
    elif cache_k > 0:
        # prospective: a K-row cache on a skewed stream lands roughly
        # a couple of top-key shares' worth of traffic
        hit_ratio = min(0.8, 2.0 * skew)
    else:
        hit_ratio = 0.0
    stats = {"unique_ratio": uniq, "key_skew": skew,
             "cache_hit_ratio": hit_ratio}

    dims = [_table_dim(e) for e in entries]
    batches = [_table_batch(e) for e in entries]
    bucket = _pow2ceil(max(dims))
    out: Dict[str, Dict[str, Any]] = {}
    for plane in sorted(contracts.PLANE_SPECS):
        spec = contracts.PLANE_SPECS[plane]
        if plane == "a2a+grouped":
            params = {"global_batch": max(batches), "dim": max(dims),
                      "itemsize": ITEMSIZE,
                      "num_tables": len(entries), "dim_bucket": bucket,
                      "cache_k": cache_k}
            nbytes = sum(
                int(spec.exchange_bytes[prog](params))
                for prog in ("pull", "push"))
            dispatches = 1
            hbm = int(spec.hbm_overhead_bytes(params))
        else:
            nbytes, hbm = 0, 0
            for dim, batch in zip(dims, batches):
                params = {"global_batch": batch, "dim": dim,
                          "itemsize": ITEMSIZE, "cache_k": cache_k,
                          "wire_itemsize":
                          1 if plane == "a2a+int8" else 2}
                if plane == "a2a+int8":
                    nbytes += int(spec.exchange_bytes["pull"](
                        dict(params, wire_itemsize=2)))
                    nbytes += int(spec.exchange_bytes["push"](params))
                else:
                    nbytes += sum(
                        int(spec.exchange_bytes[prog](params))
                        for prog in ("pull", "push"))
                hbm += int(spec.hbm_overhead_bytes(params))
            dispatches = len(entries)
        launches = (spec.launches["pull"] + spec.launches["push"]) \
            * dispatches
        factor = spec.workload_factor(stats)
        wire_s = factor * nbytes * calib.per_byte_s
        launch_s = launches * spec.host_step_units * calib.per_launch_s
        out[plane] = {
            "bytes": nbytes, "launches": launches,
            "workload_factor": round(factor, 4),
            "hbm_overhead_bytes": hbm,
            "wire_s": wire_s, "launch_s": launch_s,
            "step_s": wire_s + launch_s,
        }
    # multi-table grouping needs >= 2 member tables to exist at all
    if len(entries) < 2:
        out.pop("a2a+grouped", None)
    return out


# --- the plan ----------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Decision:
    """One planner choice, with the observed basis that drove it."""

    knob: str
    value: Any
    basis: str
    rationale: str


@dataclasses.dataclass(frozen=True)
class Plan:
    config: envconfig.EnvConfig
    decisions: Tuple[Decision, ...]
    scores: Mapping[str, Mapping[str, Any]]
    calibration: Calibration


_COMPRESSED_EXCHANGE = {
    "a2a+bf16": ("bf16", "bf16"),
    "a2a+int8": ("bf16", "int8_ef"),
}


def build_plan(window: Mapping[str, Any],
               records: Iterable[Mapping[str, Any]] = (),
               *, base: Optional[envconfig.EnvConfig] = None,
               allow_compressed: bool = True) -> Plan:
    """The planner proper: window + trajectory -> :class:`Plan`.

    Pure and deterministic — see module docs. ``allow_compressed``
    gates the bf16/int8 rungs out of plane selection for workloads
    that cannot take the precision hit (they still appear, priced, in
    the score table).
    """
    problems = validate_window(window)
    if problems:
        raise ValueError("invalid stats window:\n  "
                         + "\n  ".join(problems))
    base = base if base is not None else envconfig.EnvConfig()
    calib = calibrate(records, str(window["fingerprint"]))
    scores = plane_costs(window, calib)
    decisions: List[Decision] = []

    tables = window.get("tables") or {}
    entries = list(tables.values())
    skew = _mean([e.get("pull_key_skew") for e in entries], 0.0)
    uniq = _mean([e.get("pull_unique_ratio") for e in entries], 1.0)

    # 1. exchange plane (training): cheapest effective step
    eligible = {p: s for p, s in scores.items()
                if allow_compressed or p not in _COMPRESSED_EXCHANGE}
    plane = min(sorted(eligible), key=lambda p: eligible[p]["step_s"])
    s = eligible[plane]
    decisions.append(Decision(
        "plane", plane,
        f"unique_ratio={uniq:.3f} key_skew={skew:.3f} "
        f"tables={len(entries)}",
        f"cheapest effective step {s['step_s'] * 1e3:.3f} ms "
        f"({s['bytes']} B wire x{s['workload_factor']}, "
        f"{s['launches']} launches) under {calib.source} "
        "calibration"))

    # 2. wire precision: only a compressed winner rewrites the
    # exchange section (numerics are a policy choice, not a perf one)
    exchange = base.exchange
    if plane in _COMPRESSED_EXCHANGE:
        prec, push_prec = _COMPRESSED_EXCHANGE[plane]
        exchange = dataclasses.replace(
            base.exchange, precision=prec, push_precision=push_prec)
        decisions.append(Decision(
            "exchange.precision", f"{prec}/{push_prec}",
            f"plane={plane}",
            "compressed rung won on wire bytes; spec-level override "
            "still available per table"))

    # 3. cache K (spec-level; the EnvConfig has no cache_k field, so
    # this decision is advisory output for make_*_specs callers)
    cache_k = choose_cache_k(skew)
    decisions.append(Decision(
        "cache_k", cache_k, f"key_skew={skew:.3f}",
        "top-key share ladder "
        + "/".join(f"<{b:g}->{k}" for b, k in CACHE_K_LADDER)))

    # 4. serving batcher knobs from the measured request distribution
    lookup = (window.get("serving") or {}).get("lookup_rows") or {}
    count = int(lookup.get("count") or 0)
    window_s = float(window["window_s"])
    plan_cfg = base.plan
    serving = base.serving
    if count > 0 and lookup.get("p95"):
        p95 = float(lookup["p95"])
        p50 = float(lookup.get("p50") or p95)
        clamp_lo, clamp_hi = plan_cfg.rows_floor, plan_cfg.rows_ceiling
        rows = _clamp(_pow2ceil(ROWS_PER_FLUSH_P95 * p95),
                      clamp_lo, clamp_hi)
        rate = count / window_s
        interarrival_us = 1e6 / rate
        wait = _clamp(
            int(round(WAIT_INTERARRIVALS * interarrival_us / 10.0))
            * 10,
            plan_cfg.wait_floor_us, plan_cfg.wait_ceiling_us)
        queue = QUEUE_FLUSHES * rows
        serving = dataclasses.replace(
            base.serving, batch_rows=rows, batch_wait_us=wait,
            batch_queue_rows=queue)
        floor = _clamp(_pow2ceil(p50), 64, rows)
        ceiling = _clamp(_pow2ceil(4 * rows), rows, 8192)
        plan_cfg = dataclasses.replace(
            plan_cfg, rows_floor=floor, rows_ceiling=ceiling)
        decisions.append(Decision(
            "serving.batch_rows", rows,
            f"lookup_rows p95={p95:.0f} n={count}",
            f"{ROWS_PER_FLUSH_P95} x p95 request rows, pow2, clamped "
            f"to [{clamp_lo}, {clamp_hi}]"))
        decisions.append(Decision(
            "serving.batch_wait_us", wait,
            f"arrival rate {rate:.1f}/s "
            f"(interarrival {interarrival_us:.0f} us)",
            f"{WAIT_INTERARRIVALS} x mean interarrival, clamped to "
            f"[{plan_cfg.wait_floor_us}, {plan_cfg.wait_ceiling_us}]"))
        decisions.append(Decision(
            "serving.batch_queue_rows", queue,
            f"batch_rows={rows}",
            f"{QUEUE_FLUSHES} flushes of backlog before rejecting"))
        decisions.append(Decision(
            "plan.rows_envelope", f"[{floor}, {ceiling}]",
            f"p50={p50:.0f} p95={p95:.0f}",
            "adaptive batcher floor=pow2(p50), ceiling=4x the static "
            "choice — the online tuner moves only inside this"))
    else:
        decisions.append(Decision(
            "serving.batch_rows", serving.batch_rows,
            "no serving_lookup_rows samples in the window",
            "kept the base config; capture a window under real load "
            "to size the batcher"))

    # 5. ingest reader width from the stall accounting
    ingest = window.get("ingest") or {}
    pops = int(ingest.get("pops") or 0)
    stall_p95 = ingest.get("stall_ms_p95")
    readers = plan_cfg.readers
    if pops > 0 and isinstance(stall_p95, (int, float)) \
            and stall_p95 > 1.0:
        readers = 4
        plan_cfg = dataclasses.replace(plan_cfg, readers=readers)
        decisions.append(Decision(
            "plan.readers", readers,
            f"ingest_stall_ms p95={stall_p95:.1f} over {pops} pops",
            "steps block on data; widen the shard reader pool"))
    else:
        decisions.append(Decision(
            "plan.readers", readers or "(stream default)",
            f"ingest_stall_ms p95="
            f"{stall_p95 if stall_p95 is not None else 'n/a'}",
            "ingest keeps up; no reader-pool override"))

    cfg = dataclasses.replace(base, exchange=exchange,
                              serving=serving, plan=plan_cfg)
    return Plan(config=cfg, decisions=tuple(decisions),
                scores=scores, calibration=calib)


# --- rendering ---------------------------------------------------------------

def render_config(cfg: envconfig.EnvConfig) -> str:
    """The EnvConfig as canonical JSON text — key-sorted, newline
    terminated, byte-stable for identical plans."""
    return json.dumps(cfg.to_json(), indent=2, sort_keys=True) + "\n"


def format_rationale(plan: Plan) -> str:
    """The per-decision rationale table + plane score table, one
    deterministic string (printed by tools/graftplan, uploaded as a CI
    artifact)."""
    lines: List[str] = []
    c = plan.calibration
    lines.append("graftplan rationale")
    lines.append(
        f"calibration: {c.source} (n={c.n_records}) "
        f"per_byte={c.per_byte_s:.3e} s/B "
        f"per_launch={c.per_launch_s:.3e} s")
    if c.source == "defaults":
        lines.append(
            "  (no fingerprint-matched trajectory records — absolute "
            "costs are placeholders; the plane RANKING still follows "
            "the audited byte/launch counts)")
    lines.append("")
    lines.append(f"{'plane':<14} {'wire B':>12} {'xfactor':>8} "
                 f"{'launches':>8} {'hbm B':>12} {'step ms':>10}")
    for plane in sorted(plan.scores,
                        key=lambda p: plan.scores[p]["step_s"]):
        s = plan.scores[plane]
        lines.append(
            f"{plane:<14} {s['bytes']:>12} "
            f"{s['workload_factor']:>8} {s['launches']:>8} "
            f"{s['hbm_overhead_bytes']:>12} "
            f"{s['step_s'] * 1e3:>10.4f}")
    lines.append("")
    w = max(len(str(d.knob)) for d in plan.decisions)
    for d in plan.decisions:
        lines.append(f"{d.knob:<{w}}  = {d.value}")
        lines.append(f"{'':<{w}}    basis: {d.basis}")
        lines.append(f"{'':<{w}}    why:   {d.rationale}")
    return "\n".join(lines) + "\n"
