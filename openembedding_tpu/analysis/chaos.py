"""graftchaos: deterministic fault injection keyed on sync-point names.

The repo's concurrency harness (:mod:`.concurrency`) already names every
interleaving that matters — ``ckpt.delta.commit``, ``ingest.ring.put``,
``routing.attempt``, ... — and routes each arrival through the ONE
global schedule slot. A :class:`FaultPlan` plugs into that same slot
(it implements the schedule protocol, ``sync(key, point)``), so faults
inject at EXISTING markers with zero new call sites: the n-th arrival
at a named point raises, sleeps, dies, tears its next atomic write, or
drops the network — deterministically, replayable from the plan alone.

Fault classes (:data:`ACTIONS`):

``raise``
    Raise :class:`ChaosError` (a ``RuntimeError``) — a recoverable
    component fault; normal ``except Exception`` handling sees it.
``delay_ms``
    Sleep ``ms`` milliseconds — a stall, not a failure; exercises
    timeout/deadline paths without killing anything.
``kill_thread``
    Raise :class:`ChaosKill` (a ``BaseException``) — unwinds the
    arriving thread past ``except Exception`` blocks, the closest
    in-process analogue of SIGKILLing it mid-critical-section.
``torn_write``
    Arm the arriving THREAD's next :func:`utils.fs.open_atomic` commit
    to die mid-write: the tmp file is flushed, truncated to HALF its
    bytes, and the writer is killed (:class:`ChaosKill`) BEFORE the
    atomic rename — the exact crash the tmp+rename protocol defends
    against. The committed file under the final name must stay the old
    version, which is precisely what the graftchaos sweep asserts
    (recovery always lands on a committed manifest; the half-written
    tmp is debris for the next save's GC). The graftproto
    ``delta_chain`` model's ``(seq, "torn")`` payload state — a
    COMMITTED entry over corrupt bytes — models media damage past the
    crash protocol and stays the crc/verify plane's job.
``drop_net``
    Raise :class:`ChaosNetError` (a ``ConnectionError``) — the serving
    failover classes treat it as a dead/unreachable replica and rotate.

Arming:

* in-process: ``install_plan(plan)`` / ``clear_plan()`` or the
  :func:`active_plan` context manager;
* cross-process: ``OE_CHAOS_PLAN`` (inline JSON or ``@/path/plan.json``)
  — :func:`install_from_env` is called by the serving replica daemon at
  boot, and flows through ``EnvConfig`` as the ``chaos`` section.

Every injection is counted on /metrics as
``oe_chaos_injected_total{point=,action=}``, recorded as a
``chaos.inject`` span (trace-visible next to the work it broke), and
appended to ``plan.injected`` for the harness to assert on.

A plan occupies the one schedule slot, so chaos composes with
``SerialSchedule``/``PointGate`` only by nesting: wrap the other
schedule with ``FaultPlan(..., inner=other)`` and arrivals flow
fault-check first, then into the inner schedule.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from . import scope
from . import concurrency
from ..utils import fs

ACTIONS = ("raise", "delay_ms", "kill_thread", "torn_write", "drop_net")

#: counter name — renders as ``oe_chaos_injected_total{action=,point=}``
COUNTER = "chaos_injected"


class ChaosError(RuntimeError):
    """Injected recoverable fault (action ``raise``)."""


class ChaosNetError(ChaosError, ConnectionError):
    """Injected network drop (action ``drop_net``) — a
    ``ConnectionError``, so failover rotations classify it as a dead
    replica, not a logic error."""


class ChaosKill(BaseException):
    """Injected thread death (actions ``kill_thread`` / ``torn_write``).

    A ``BaseException`` on purpose: ordinary ``except Exception``
    recovery must NOT see it — the thread unwinds the way a kill would,
    and only harness-level ``except ChaosKill`` (or ``finally``) runs.
    """


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One deterministic fault: at the ``hit``-th matching arrival of
    ``point``, perform ``action`` (one-shot). ``thread`` is an fnmatch
    pattern over the arriving thread's name — pin a fault to one worker
    of a pool when global arrival order across threads is racy."""

    point: str
    action: str
    hit: int = 1
    ms: float = 10.0          # delay_ms budget
    thread: str = "*"

    def __post_init__(self):
        if not self.point:
            raise ValueError("point must be a sync-point name")
        if self.action not in ACTIONS:
            raise ValueError(f"unknown chaos action {self.action!r}; "
                             f"known: {ACTIONS}")
        if self.hit < 1:
            raise ValueError(f"hit must be >= 1 (1-based), got {self.hit}")
        if self.ms < 0:
            raise ValueError(f"ms must be >= 0, got {self.ms}")


class FaultPlan:
    """A seeded set of :class:`FaultSpec` implementing the schedule
    protocol. Install with :func:`install_plan`; every ``sync_point``
    arrival is matched against the specs and the ``hit``-th match fires
    its action exactly once. ``seed`` is carried for provenance (sweep
    tools derive their scenario ordering from it) — matching itself is
    count-based and needs no randomness."""

    def __init__(self, faults: Sequence[FaultSpec], seed: int = 0,
                 inner: Optional[Any] = None):
        self.faults = list(faults)
        self.seed = int(seed)
        self.inner = inner
        self._lock = threading.Lock()
        self._counts = [0] * len(self.faults)
        self._fired = [False] * len(self.faults)
        # armed torn commits: thread ident -> FaultSpec (consumed by
        # the fs commit hook on that thread's next atomic commit)
        self._torn: Dict[int, FaultSpec] = {}
        #: injection log: [{"point","action","hit","thread"}...]
        self.injected: List[Dict[str, Any]] = []

    # -- construction --------------------------------------------------------
    @classmethod
    def from_json(cls, obj: Dict[str, Any]) -> "FaultPlan":
        if not isinstance(obj, dict):
            raise ValueError("chaos plan must be a JSON object")
        unknown = set(obj) - {"faults", "seed"}
        if unknown:
            raise ValueError(f"unknown chaos plan keys {sorted(unknown)}")
        faults = []
        for i, f in enumerate(obj.get("faults", [])):
            if not isinstance(f, dict):
                raise ValueError(f"faults[{i}] must be an object")
            known = {fl.name for fl in dataclasses.fields(FaultSpec)}
            bad = set(f) - known
            if bad:
                raise ValueError(f"faults[{i}]: unknown keys {sorted(bad)}; "
                                 f"known: {sorted(known)}")
            faults.append(FaultSpec(**f))
        return cls(faults, seed=int(obj.get("seed", 0)))

    def to_json(self) -> Dict[str, Any]:
        return {"seed": self.seed,
                "faults": [dataclasses.asdict(f) for f in self.faults]}

    # -- schedule protocol ---------------------------------------------------
    def sync(self, key: str, point: str) -> None:
        tname = key[: -(len(point) + 1)] if key.endswith("/" + point) \
            else key
        fire: Optional[FaultSpec] = None
        with self._lock:
            for i, spec in enumerate(self.faults):
                if self._fired[i] or spec.point != point:
                    continue
                if not fnmatch.fnmatchcase(tname, spec.thread):
                    continue
                self._counts[i] += 1
                if self._counts[i] == spec.hit:
                    self._fired[i] = True
                    fire = spec
                    break
            if fire is not None:
                self.injected.append({"point": point, "action": fire.action,
                                      "hit": fire.hit, "thread": tname})
        if fire is not None:
            self._fire(fire, point)
        if self.inner is not None:
            self.inner.sync(key, point)

    def _fire(self, spec: FaultSpec, point: str) -> None:
        scope.HISTOGRAMS.inc(COUNTER, point=point, action=spec.action)
        with scope.span("chaos.inject", point=point, action=spec.action):
            if spec.action == "raise":
                raise ChaosError(
                    f"chaos: injected fault at {point!r} (hit {spec.hit})")
            if spec.action == "delay_ms":
                time.sleep(spec.ms / 1e3)
                return
            if spec.action == "kill_thread":
                raise ChaosKill(
                    f"chaos: thread killed at {point!r} (hit {spec.hit})")
            if spec.action == "drop_net":
                raise ChaosNetError(
                    f"chaos: network dropped at {point!r} (hit {spec.hit})")
            # torn_write: arm this thread's next atomic commit to tear
            with self._lock:
                self._torn[threading.get_ident()] = spec

    # -- fs commit hook ------------------------------------------------------
    def commit_hook(self, path: str, tmp: str, f) -> bool:
        """Installed as ``fs.set_commit_hook`` while the plan is active.
        Returns False (commit proceeds normally) unless THIS thread has
        an armed tear; then: flush, truncate the tmp to half its bytes,
        and die BEFORE the atomic rename — the writer crashed mid-write,
        the old committed file survives under the final name, and the
        half-written tmp is debris. Recovery from the last committed
        version is exactly the guarantee the tmp+rename protocol makes
        for this crash, so the graftchaos sweep asserts it."""
        with self._lock:
            spec = self._torn.pop(threading.get_ident(), None)
        if spec is None:
            return False
        f.flush()
        size = f.tell()
        f.close()
        with open(tmp, "r+b") as t:
            t.truncate(max(1, size // 2))
        scope.HISTOGRAMS.inc(COUNTER, point="fs.commit",
                             action="torn_write_commit")
        raise ChaosKill(
            f"chaos: writer killed mid-write of {path!r} "
            f"({max(1, size // 2)}/{size} bytes in tmp, rename never "
            "ran)")


# --- global arming -----------------------------------------------------------

_ACTIVE: Optional[FaultPlan] = None


def install_plan(plan: FaultPlan) -> FaultPlan:
    """Arm ``plan``: occupy the sync-point schedule slot and the atomic-
    commit hook. Returns the plan (for ``plan.injected`` assertions)."""
    global _ACTIVE
    concurrency.install_schedule(plan)
    fs.set_commit_hook(plan.commit_hook)
    _ACTIVE = plan
    return plan


def clear_plan() -> None:
    global _ACTIVE
    concurrency.clear_schedule()
    fs.set_commit_hook(None)
    _ACTIVE = None


def current_plan() -> Optional[FaultPlan]:
    return _ACTIVE


class active_plan:
    """``with active_plan(plan) as p: ...`` — arm for the block only."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan

    def __enter__(self) -> FaultPlan:
        return install_plan(self.plan)

    def __exit__(self, *exc) -> bool:
        clear_plan()
        return False


def plan_from_text(text: str) -> FaultPlan:
    """Parse a plan from inline JSON or an ``@/path/plan.json`` ref —
    the ``OE_CHAOS_PLAN`` / EnvConfig ``chaos.plan`` wire format."""
    text = text.strip()
    if text.startswith("@"):
        with open(text[1:]) as fh:
            text = fh.read()
    return FaultPlan.from_json(json.loads(text))


def install_from_env(env: Optional[Dict[str, str]] = None
                     ) -> Optional[FaultPlan]:
    """Arm the ``OE_CHAOS_PLAN`` plan when set; None otherwise. Called
    by daemon entry points (serving replica boot) so a parent process
    can chaos a child it cannot reach in-process."""
    raw = (os.environ if env is None else env).get("OE_CHAOS_PLAN", "")
    if not raw:
        return None
    return install_plan(plan_from_text(raw))


# --- sync-point discovery ----------------------------------------------------

#: subsystem buckets for sweep tools, by point-name prefix
SUBSYSTEMS: Dict[str, Sequence[str]] = {
    "ckpt": ("ckpt.", "dirty.", "trainer."),
    "ingest": ("ingest.",),
    "serving": ("registry.", "serving.", "routing.", "ha."),
    "offload": ("offload.",),
    "report": ("reporter.",),
}


def discover_sync_points(root: Optional[str] = None) -> List[str]:
    """Every ``sync_point("...")`` name in the package source, sorted —
    scanned live so the sweep can never silently drift from the code."""
    import re
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # real point names are dotted lower_snake segments; the shape filter
    # drops doc-text matches like ``sync_point("...")``
    pat = re.compile(r'sync_point\(\s*"([a-z0-9_]+(?:\.[a-z0-9_]+)+)"')
    found = set()
    for dirpath, _dirs, files in os.walk(root):
        for name in files:
            if not name.endswith(".py"):
                continue
            try:
                with open(os.path.join(dirpath, name)) as fh:
                    found.update(pat.findall(fh.read()))
            except OSError:
                continue
    return sorted(found)


def subsystem_of(point: str) -> str:
    for sub, prefixes in SUBSYSTEMS.items():
        if any(point.startswith(p) for p in prefixes):
            return sub
    return "other"
