"""graftlint: a jit-purity AST linter for the package's own source.

The compiled-program contracts (:mod:`.contracts`) catch structural
regressions after the fact; this linter catches the *source* patterns
that produce them — host state mutated under trace (a counter bump or
FreqSketch touch inside a jitted step silently becomes a trace-time
no-op or a per-step host callback), tracers materialized to Python
(``.item()``/``np.*`` force a device sync per step), Python branches on
traced values (one recompile per distinct value), and step functions
jitted without donation (a full table copy per step).

Scope and honesty: the linter reasons per-module and marks a function
"traced" only when the module itself hands it to a tracing entry point —
``jax.jit``, ``shard_map``, ``lax.cond/while_loop/scan/fori_loop/
switch``, ``jax.grad/value_and_grad/vmap/checkpoint`` — directly, via a
simple alias assignment, or by lexical nesting inside a traced function.
Functions handed to ``jax.debug.callback`` / ``jax.pure_callback`` /
``io_callback`` are host functions by construction and are exempt, as is
anything decorated with :func:`host_fn`.

Rules (each suppressible inline)::

    JG001  host-state mutation inside a traced function
    JG002  tracer materialized to host (.item()/.tolist()/np.* call)
    JG003  Python control flow on a traced function's array argument
    JG004  step function jitted without donate_argnums

Suppression syntax — on the offending line or its enclosing ``def``
line::

    counters["steps"] += 1   # graftlint: disable=JG001
    def step_fn(state):      # graftlint: disable=JG001,JG003

CLI: ``python -m tools.graftlint openembedding_tpu/`` (nonzero exit on
violations) — wired into the tier-1 lane.

Stdlib-only on purpose: any module in the package (including
``parallel/*``) may import :func:`host_fn` without cycles.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple


def host_fn(fn):
    """Mark a function as host-side by contract (never traced).

    A documentation-grade no-op at runtime; the linter skips functions
    carrying this decorator even when they are handed to a tracing entry
    point, and the marker tells readers the function may freely touch
    numpy / Python state (e.g. ``FusedMapper.fuse``,
    ``FreqSketch.update``).
    """
    fn.__graftlint_host__ = True
    return fn


RULES: Dict[str, str] = {
    "JG000": "file fails to parse (linted zero lines)",
    "JG001": "host-state mutation inside a jit-traced function",
    "JG002": "tracer materialized to host (.item()/.tolist()/np.*) "
             "inside a jit-traced function",
    "JG003": "Python control flow on an array argument of a jit-traced "
             "function (retrace / concretization risk)",
    "JG004": "step function jitted without donate_argnums "
             "(full state copy per step)",
}

# entry points whose FUNCTION-VALUED argument positions are traced —
# only those positions: marking every argument would catch carries and
# operands that happen to share a name with a module-level def (a local
# `init` passed to scan must not mark a host-side `def init`)
_TRACE_ENTRIES: Dict[str, Tuple[int, ...]] = {
    "jit": (0,), "shard_map": (0,), "grad": (0,),
    "value_and_grad": (0,), "vmap": (0,), "pmap": (0,),
    "checkpoint": (0,), "custom_vjp": (0,), "custom_jvp": (0,),
    "eval_shape": (0,), "named_call": (0,), "scan": (0,),
    "while_loop": (0, 1), "cond": (1, 2), "fori_loop": (2,),
    "switch": (1,),   # branches: ONE sequence at position 1
}
# keyword names that carry functions into those entries
_TRACE_KWARGS = {"f", "fun", "body_fun", "cond_fun", "true_fun",
                 "false_fun"}
# entry points whose FIRST argument runs on HOST
_HOST_ENTRIES = {"callback", "pure_callback", "io_callback",
                 "host_callback"}

# mutating method names; receivers resolving to non-local state trip
# JG001. `.at[...].add/.set` (the functional-update idiom) is excluded
# structurally, not by name.
_MUTATORS = {"add", "add_time", "append", "extend", "update", "insert",
             "setdefault", "pop", "popleft", "remove", "discard",
             "clear", "observe", "increment", "write", "put"}

# np.* members that are trace-safe metadata helpers, not materializers
_NP_ALLOWED = {"dtype", "iinfo", "finfo", "ndim", "shape", "newaxis",
               "pi", "inf", "nan", "float32", "float64", "int32",
               "int64", "uint32", "uint64", "bool_", "integer",
               "floating", "number", "ndarray"}

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable(?:=(?P<rules>[A-Z0-9, ]+))?")


@dataclasses.dataclass(frozen=True)
class LintViolation:
    path: str
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message} " \
               f"[{RULES[self.rule]}]"


def _suppressions(source: str) -> Dict[int, Optional[Set[str]]]:
    """line -> suppressed rule set (None = all rules) from comments."""
    out: Dict[int, Optional[Set[str]]] = {}
    try:
        toks = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in toks:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            rules = m.group("rules")
            out[tok.start[0]] = (
                {r.strip() for r in rules.split(",") if r.strip()}
                if rules else None)
    except (tokenize.TokenError, SyntaxError):
        # IndentationError (a SyntaxError) escapes tokenize on malformed
        # source — swallow it here so ast.parse gets to report JG000
        pass
    return out


def _call_target(func: ast.expr) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Call):
        return _effective_target(func)[0]
    return ""


def _effective_target(expr: ast.expr) -> Tuple[str, Optional[ast.Call]]:
    """(target name, kwarg-bearing Call or None) of a decorator/callee,
    looking through ``partial``: ``@partial(jax.jit, donate_argnums=...)``
    resolves to ('jit', <the partial Call>) — partial forwards its
    kwargs, so donation checks read them off that Call."""
    if isinstance(expr, ast.Call):
        inner = _call_target(expr.func)
        if inner == "partial" and expr.args:
            return _call_target(expr.args[0]), expr
        return inner, expr
    return (expr.attr if isinstance(expr, ast.Attribute)
            else expr.id if isinstance(expr, ast.Name) else ""), None


def _has_host_decorator(node: ast.AST) -> bool:
    for dec in getattr(node, "decorator_list", []):
        if _call_target(dec) == "host_fn" or (
                isinstance(dec, ast.Name) and dec.id == "host_fn"):
            return True
    return False


def _has_trace_decorator(node: ast.AST) -> bool:
    for dec in getattr(node, "decorator_list", []):
        if _effective_target(dec)[0] in _TRACE_ENTRIES:
            return True
    return False


class _ModuleIndex(ast.NodeVisitor):
    """First pass: function defs, alias edges, traced/host name seeds."""

    def __init__(self):
        self.defs: Dict[str, List[ast.AST]] = {}
        self.aliases: Dict[str, Set[str]] = {}
        self.traced_names: Set[str] = set()
        self.host_names: Set[str] = set()
        self.jit_calls: List[ast.Call] = []
        # (def node, decorator node) for every @jit / @partial(jit, ...)
        # decorated function — JG004 must see these too, not just
        # jit(step_fn) call sites
        self.jit_decorated: List[Tuple[ast.AST, ast.AST]] = []

    def visit_FunctionDef(self, node):
        self.defs.setdefault(node.name, []).append(node)
        if _has_host_decorator(node):
            self.host_names.add(node.name)
        if _has_trace_decorator(node):
            self.traced_names.add(node.name)
        for dec in node.decorator_list:
            name, call = _effective_target(dec)
            if name == "jit":
                self.jit_decorated.append((node, call or dec))
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Assign(self, node):
        # alias edges: `_pull = _pull_core` makes marking transitive
        if isinstance(node.value, ast.Name):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.aliases.setdefault(t.id, set()).add(node.value.id)
                    self.aliases.setdefault(node.value.id, set()).add(t.id)
        self.generic_visit(node)

    @staticmethod
    def _mark(arg: ast.expr, into: Set[str]) -> None:
        if isinstance(arg, ast.Name):
            into.add(arg.id)
        elif isinstance(arg, ast.Attribute):
            # `lax.scan(loop.body, ...)`: mark by method name
            into.add(arg.attr)
        elif isinstance(arg, (ast.List, ast.Tuple)):
            # `lax.switch(i, [fa, fb], ...)`: branches ride a sequence
            for e in arg.elts:
                _ModuleIndex._mark(e, into)

    def visit_Call(self, node):
        target = _call_target(node.func)
        if target in _TRACE_ENTRIES:
            if target == "jit":
                self.jit_calls.append(node)
            for pos in _TRACE_ENTRIES[target]:
                if pos < len(node.args):
                    self._mark(node.args[pos], self.traced_names)
            for kw in node.keywords:
                if kw.arg in _TRACE_KWARGS:
                    self._mark(kw.value, self.traced_names)
        elif target in _HOST_ENTRIES:
            if node.args:
                self._mark(node.args[0], self.host_names)
        self.generic_visit(node)


def _close_over_aliases(names: Set[str], aliases: Dict[str, Set[str]]
                        ) -> Set[str]:
    work, seen = list(names), set(names)
    while work:
        n = work.pop()
        for other in aliases.get(n, ()):
            if other not in seen:
                seen.add(other)
                work.append(other)
    return seen


def _bound_names(target: ast.expr) -> Iterable[str]:
    """Names a target expression actually BINDS: plain names and their
    tuple/list/star destructurings — NOT the base of ``x[i] = ...`` or
    ``x.a = ...`` (those mutate an existing object)."""
    if isinstance(target, ast.Name):
        yield target.id
    elif isinstance(target, (ast.Tuple, ast.List)):
        for e in target.elts:
            yield from _bound_names(e)
    elif isinstance(target, ast.Starred):
        yield from _bound_names(target.value)


def _local_bindings(fn: ast.AST) -> Set[str]:
    """Names bound inside the function: params + assignments + defs."""
    out: Set[str] = set()
    args = fn.args
    for a in (args.posonlyargs + args.args + args.kwonlyargs):
        out.add(a.arg)
    if args.vararg:
        out.add(args.vararg.arg)
    if args.kwarg:
        out.add(args.kwarg.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                out.update(_bound_names(t))
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign, ast.For)):
            out.update(_bound_names(node.target))
        elif isinstance(node, ast.comprehension):
            out.update(_bound_names(node.target))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.add(node.name)
        elif isinstance(node, ast.withitem) and node.optional_vars:
            out.update(_bound_names(node.optional_vars))
    return out


def _array_params(fn: ast.AST) -> Set[str]:
    """Parameters likely to be tracers: everything except ``self``/
    ``cls`` and ``*``/``**`` catch-alls."""
    args = fn.args
    names = [a.arg for a in (args.posonlyargs + args.args
                             + args.kwonlyargs)]
    return {n for n in names if n not in ("self", "cls")}


def _receiver_base(expr: ast.expr) -> Optional[ast.expr]:
    """Innermost base of a dotted/subscripted receiver chain."""
    while isinstance(expr, (ast.Attribute, ast.Subscript)):
        expr = expr.value
    return expr


def _is_functional_at(expr: ast.expr) -> bool:
    """True for `x.at[...]` receivers (the jnp functional-update idiom)."""
    return (isinstance(expr, ast.Subscript)
            and isinstance(expr.value, ast.Attribute)
            and expr.value.attr == "at")


class Linter:
    """Single-file linter; :func:`lint_source` is the functional entry."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.violations: List[LintViolation] = []
        self.suppress = _suppressions(source)

    # -- suppression ---------------------------------------------------------
    def _suppressed(self, rule: str, line: int,
                    def_line: Optional[int]) -> bool:
        for ln in (line, def_line):
            if ln is None or ln not in self.suppress:
                continue
            rules = self.suppress[ln]
            if rules is None or rule in rules:
                return True
        return False

    def _emit(self, rule: str, node: ast.AST, msg: str,
              def_line: Optional[int] = None) -> None:
        line = getattr(node, "lineno", 0)
        if not self._suppressed(rule, line, def_line):
            self.violations.append(
                LintViolation(self.path, line, rule, msg))

    # -- main ----------------------------------------------------------------
    def run(self) -> List[LintViolation]:
        try:
            tree = ast.parse(self.source)
        except SyntaxError as e:
            self.violations.append(LintViolation(
                self.path, e.lineno or 0, "JG000",
                f"file does not parse: {e.msg}"))
            return self.violations
        index = _ModuleIndex()
        index.visit(tree)
        traced = _close_over_aliases(index.traced_names, index.aliases)
        hosted = _close_over_aliases(index.host_names, index.aliases)
        traced -= hosted

        # collect traced def nodes (+ their lexical children)
        traced_defs: List[ast.AST] = []
        seen: Set[int] = set()

        def add_with_children(fn: ast.AST):
            if id(fn) in seen or _has_host_decorator(fn):
                return
            seen.add(id(fn))
            traced_defs.append(fn)
            for child in ast.walk(fn):
                if (child is not fn
                        and isinstance(child, (ast.FunctionDef,
                                               ast.AsyncFunctionDef))
                        and child.name not in hosted):
                    add_with_children(child)

        for name in traced:
            for fn in index.defs.get(name, ()):
                add_with_children(fn)

        for fn in traced_defs:
            self._check_traced_fn(fn)
        for call in index.jit_calls:
            self._check_jit_donation(call)
        for fn, dec in index.jit_decorated:
            self._check_decorator_donation(fn, dec)
        return self.violations

    # -- per-rule checks -----------------------------------------------------
    def _check_traced_fn(self, fn: ast.AST) -> None:
        local = _local_bindings(fn)
        params = _array_params(fn)
        own_nodes = self._own_statements(fn)
        for node in own_nodes:
            self._check_mutation(node, fn, local)
            self._check_materialize(node, fn)
            self._check_branch(node, fn, params)

    def _own_statements(self, fn: ast.AST) -> List[ast.AST]:
        """All nodes of ``fn`` excluding nested function bodies (they are
        checked separately iff they are themselves traced)."""
        out: List[ast.AST] = []

        def walk(node: ast.AST):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                out.append(child)
                walk(child)

        walk(fn)
        return out

    def _check_mutation(self, node: ast.AST, fn: ast.AST,
                        local: Set[str]) -> None:
        def_line = fn.lineno
        # assignment to non-local state: self.x = / module.attr = /
        # GLOBAL[...] = — a local object's attribute/item is fine
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if not isinstance(t, (ast.Attribute, ast.Subscript)):
                    continue
                base = _receiver_base(t)
                if isinstance(base, ast.Name) \
                        and base.id not in ("self", "cls") \
                        and base.id in local:
                    continue
                self._emit(
                    "JG001", node,
                    "assignment to non-local state "
                    f"`{ast.unparse(t)}` under trace runs once at "
                    "trace time, not per step", def_line)
        if isinstance(node, (ast.Global, ast.Nonlocal)):
            self._emit("JG001", node,
                       f"`{'global' if isinstance(node, ast.Global) else 'nonlocal'} "
                       f"{', '.join(node.names)}` inside a traced "
                       "function mutates host state", def_line)
        # a mutator call whose RESULT is discarded: `sketch.update(k)`,
        # `GLOBAL.add(...)`. When the return value is consumed
        # (`u, s = tx.update(...)`) the call is the functional idiom and
        # makes no claim of side effect — skip it.
        if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call) \
                and isinstance(node.value.func, ast.Attribute):
            node = node.value
            method = node.func.attr
            if method not in _MUTATORS:
                return
            recv = node.func.value
            if _is_functional_at(recv):
                return                      # x.at[i].add(...) is pure
            base = _receiver_base(recv)
            if not isinstance(base, ast.Name):
                return                      # chained receiver: no claim
            if base.id not in ("self", "cls") and base.id in local:
                return                      # local object, local effect
            self._emit(
                "JG001", node,
                f"`{ast.unparse(node.func)}(...)` mutates host state "
                "under trace (counters/sketches belong outside the "
                "jitted step — see parallel/hot_cache.py)", def_line)

    def _check_materialize(self, node: ast.AST, fn: ast.AST) -> None:
        if not isinstance(node, ast.Call):
            return
        def_line = fn.lineno
        if isinstance(node.func, ast.Attribute):
            if node.func.attr in ("item", "tolist", "tobytes") \
                    and not node.args:
                self._emit(
                    "JG002", node,
                    f"`.{node.func.attr}()` forces a device sync per "
                    "step inside a traced function", def_line)
                return
            base = node.func.value
            if isinstance(base, ast.Name) and base.id in ("np", "numpy") \
                    and node.func.attr not in _NP_ALLOWED:
                self._emit(
                    "JG002", node,
                    f"`{ast.unparse(node.func)}(...)` runs on host; on "
                    "a tracer it either fails or silently constant-"
                    "folds at trace time", def_line)

    def _check_branch(self, node: ast.AST, fn: ast.AST,
                      params: Set[str]) -> None:
        if not isinstance(node, (ast.If, ast.While)):
            return
        # only BARE argument names used directly as the condition or as
        # a comparison operand trip the rule: `if x:`, `while x > 0:`.
        # `if x.ndim == 2:` or `if is_wide(x):` are shape/metadata
        # predicates — static at trace time, the supported config idiom.
        hit: Set[str] = set()

        def direct_names(expr: ast.expr):
            if isinstance(expr, ast.Name):
                hit.add(expr.id)
            elif isinstance(expr, ast.BoolOp):
                for v in expr.values:
                    direct_names(v)
            elif isinstance(expr, ast.UnaryOp):
                direct_names(expr.operand)
            elif isinstance(expr, ast.Compare):
                for v in [expr.left] + list(expr.comparators):
                    if isinstance(v, ast.Name):
                        hit.add(v.id)

        direct_names(node.test)
        hit &= params
        if hit:
            kind = "if" if isinstance(node, ast.If) else "while"
            self._emit(
                "JG003", node,
                f"`{kind}` on argument(s) {sorted(hit)} of a traced "
                "function: concretization error or one recompile per "
                "distinct value — use lax.cond/jnp.where, or hoist the "
                "static config out of the traced signature", fn.lineno)

    @staticmethod
    def _is_step_name(name: str) -> bool:
        # step / step_fn / train_step / step_impl — but NOT
        # steps_per_epoch (anchored `^step($|_)`) and not eval steps
        return bool(re.search(r"^step($|_)|(^|_)step(_fn)?$", name)) \
            and not name.startswith("eval")

    def _check_jit_donation(self, call: ast.Call) -> None:
        if not call.args:
            return
        arg = call.args[0]
        if not isinstance(arg, ast.Name) \
                or not self._is_step_name(arg.id):
            return
        kwargs = {kw.arg for kw in call.keywords}
        if "donate_argnums" in kwargs or "donate_argnames" in kwargs:
            return
        self._emit(
            "JG004", call,
            f"jax.jit({arg.id}) without donate_argnums: a step function "
            "updating table state copies every table buffer each step",
            None)

    def _check_decorator_donation(self, fn: ast.AST,
                                  dec: ast.AST) -> None:
        """`@jax.jit` / `@partial(jax.jit, ...)` / `@jax.jit(...)` above a
        step-named def: same donation requirement as the call form."""
        if not self._is_step_name(fn.name):
            return
        kwargs = ({kw.arg for kw in dec.keywords}
                  if isinstance(dec, ast.Call) else set())
        if "donate_argnums" in kwargs or "donate_argnames" in kwargs:
            return
        self._emit(
            "JG004", dec,
            f"@jit on {fn.name} without donate_argnums: a step function "
            "updating table state copies every table buffer each step",
            fn.lineno)


def lint_source(source: str, path: str = "<string>") -> List[LintViolation]:
    """Lint one module's source text."""
    return Linter(path, source).run()


def lint_paths(paths: Sequence[str]) -> List[LintViolation]:
    """Lint files and/or directory trees (``.py`` files, recursively)."""
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, names in os.walk(p):
                if "__pycache__" in root:
                    continue
                files.extend(os.path.join(root, n) for n in sorted(names)
                             if n.endswith(".py"))
        else:
            files.append(p)
    out: List[LintViolation] = []
    for f in files:
        with open(f, "r", encoding="utf-8") as fh:
            out.extend(lint_source(fh.read(), f))
    return out
