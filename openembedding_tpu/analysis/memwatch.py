"""graftwatch memory ledger: per-plane compiled-program memory audits.

graftscope (``scope.py``) made latency and collective bytes observable;
this module covers the third cost axis — memory. Every registered
plane's pull/push program is lowered exactly as the training path runs
it (:mod:`.programs` ``compile_*``) and its XLA memory analysis is
extracted through ``utils.jaxcompat.compiled_memory_stats`` (the
0.4.x/0.5.x API shapes differ; backends without the analysis yield
None, never a crash): per-device argument / output / temp / alias
bytes, plus the derived peak estimate. Two consumers:

* **The peak-temp contract** (:func:`..analysis.contracts.
  check_peak_temp_bytes`): compiled temp must stay batch-scale scratch
  (pull) plus at most one declined-donation state materialization
  (push/step). This catches what the HLO-text ``copy`` audit cannot —
  XLA materializations that never appear as an explicit ``copy`` op
  (fusion outputs, gather results) still land in the temp allocation.
  Enforced by ``python -m tools.graftcheck`` per plane.
* **The bench trajectory** (``tools/graftwatch.py``): every recorded
  run carries its planes' memory-ledger numbers, so an HBM regression
  (a new buffer the size of a table shard) is diffable across PRs like
  a latency regression.

Audit sizing: like ``max_copy_bytes``, detection power needs the table
shard to dwarf batch scratch — the default audit sizes below put one
weights shard at 8 MiB against ~1 MiB of scratch, so a single stray
shard-sized materialization busts the bound instead of hiding in slack.

Import discipline: jax only inside functions (this module is lazy in
``analysis.__init__`` next to ``programs``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Mapping, Optional, Tuple

# audit sizes: weights shard = vocab*dim*4/8 = 8 MiB (array planes),
# 4 MiB per grouped member table — both >> the ~1 MiB batch scratch at
# batch 512, so the peak-temp bound detects one extra shard
AUDIT_VOCAB = 1 << 20
AUDIT_GROUPED_VOCAB = 1 << 19
AUDIT_BATCH = 512
AUDIT_DIM = 16


@dataclasses.dataclass(frozen=True)
class MemoryRow:
    """One plane program's per-device compiled-memory ledger entry."""

    plane: str
    program: str                       # "pull" | "push" | "step"
    kind: str                          # "array" | "hash"
    mem: Optional[Mapping[str, int]]   # compiled_memory_stats dict or None
    params: Mapping[str, int]
    temp_bound: Optional[int] = None   # the enforced peak-temp cap

    def as_dict(self) -> Dict[str, Any]:
        out = {"plane": self.plane, "program": self.program,
               "kind": self.kind, "temp_bound": self.temp_bound}
        out.update(self.mem or {})
        return out


def plane_memory(mesh, plane: str, program: str, *,
                 batch: int = AUDIT_BATCH, dim: int = AUDIT_DIM,
                 vocab: Optional[int] = None, use_hash: bool = False,
                 tables: int = 3, check: bool = True) -> MemoryRow:
    """Memory-ledger row for one plane program on ``mesh``.

    ``check=True`` enforces the peak-temp contract
    (:class:`..analysis.contracts.ContractViolation` on breach); rows
    whose backend exposes no memory analysis carry ``mem=None`` and are
    never audited (absence of data is reported, not punished).
    """
    from . import contracts, programs
    from ..utils import jaxcompat
    if plane == "a2a+grouped":
        build = (programs.compile_grouped_pull if program == "pull"
                 else programs.compile_grouped_push)
        compiled, params = build(
            mesh, tables=tables, vocab=vocab or AUDIT_GROUPED_VOCAB,
            batch=batch, dim=dim, use_hash=use_hash)
    else:
        build = (programs.compile_pull if program == "pull"
                 else programs.compile_push)
        compiled, params = build(
            mesh, plane, vocab=vocab or AUDIT_VOCAB, batch=batch,
            dim=dim, use_hash=use_hash)
    mem = jaxcompat.compiled_memory_stats(compiled)
    bound = None
    if mem is not None:
        if check:
            bound = contracts.check_peak_temp_bytes(
                mem, params, program=program,
                label=f"{plane}/{program} ({'hash' if use_hash else 'array'})")
        else:
            bound = contracts.peak_temp_bound(
                params, program, int(mem.get("alias_bytes", 0)))
    return MemoryRow(plane=plane, program=program,
                     kind="hash" if use_hash else "array", mem=mem,
                     params=params, temp_bound=bound)


def pipelined_step_memory(mesh, *, batch: int = AUDIT_BATCH,
                          dim: int = AUDIT_DIM,
                          vocab: Optional[int] = None,
                          check: bool = True) -> MemoryRow:
    """Memory-ledger row for the PIPELINED STEP program
    (``parallel/pipelined.py``): the whole-step peak-temp bound plus
    exactly one extra pulled-row buffer (``pipeline_rows_bytes``,
    measured from the primed buffer itself) — never anything
    table-sized. The vocab defaults low enough that the deepfm harness
    compiles quickly; pass ``vocab=AUDIT_VOCAB`` for the
    shard-dominates-scratch sizing when hunting a regression."""
    from . import contracts, programs
    from ..utils import jaxcompat
    compiled, params = programs.compile_pipelined_step(
        mesh, vocab=vocab or (1 << 17), batch=batch, dim=dim)
    mem = jaxcompat.compiled_memory_stats(compiled)
    bound = None
    if mem is not None:
        if check:
            bound = contracts.check_peak_temp_bytes(
                mem, params, program="step",
                label="a2a+pipelined/step (deepfm)")
        else:
            bound = contracts.peak_temp_bound(
                params, "step", int(mem.get("alias_bytes", 0)))
    return MemoryRow(plane="a2a+pipelined", program="step", kind="array",
                     mem=mem, params=params, temp_bound=bound)


def registered_planes() -> List[str]:
    """Planes with a pull/push contract in the registry — the coverage
    set the graftcheck/graftwatch memory audits iterate."""
    from . import contracts
    return sorted({p for (p, prog) in contracts.REGISTRY
                   if prog in ("pull", "push")})


def memory_ledger(mesh, *, batch: int = AUDIT_BATCH, dim: int = AUDIT_DIM,
                  planes: Optional[Tuple[str, ...]] = None,
                  check: bool = True) -> List[MemoryRow]:
    """Memory rows for every registered plane's pull AND push (array
    tables; the a2a plane additionally in its hash form — hash scratch
    shapes differ enough to audit separately). Raises on the first
    contract breach when ``check``; lowering errors propagate (a plane
    whose ledger cannot be produced must fail the gate, same contract
    as the span coverage check in graftscope)."""
    rows = []
    for plane in (planes or registered_planes()):
        for program in ("pull", "push"):
            rows.append(plane_memory(mesh, plane, program, batch=batch,
                                     dim=dim, check=check))
            if plane == "a2a":
                rows.append(plane_memory(mesh, plane, program,
                                         batch=batch, dim=dim,
                                         use_hash=True, check=check))
    return rows


def format_memory_table(rows: List[MemoryRow]) -> str:
    """Fixed-width ledger table (MiB) for terminals and CI logs."""
    head = (f"{'plane':<14}{'stage':<7}{'kind':<7}{'arg_MiB':>9}"
            f"{'out_MiB':>9}{'temp_MiB':>9}{'alias_MiB':>10}"
            f"{'peak_MiB':>9}{'temp_cap':>9}")
    out = [head, "-" * len(head)]

    def mib(v) -> str:
        return f"{v / (1 << 20):.2f}" if v is not None else "n/a"

    for r in rows:
        m = r.mem or {}
        out.append(
            f"{r.plane:<14}{r.program:<7}{r.kind:<7}"
            f"{mib(m.get('argument_bytes')):>9}"
            f"{mib(m.get('output_bytes')):>9}"
            f"{mib(m.get('temp_bytes')):>9}"
            f"{mib(m.get('alias_bytes')):>10}"
            f"{mib(m.get('peak_bytes')):>9}"
            f"{mib(r.temp_bound):>9}")
    return "\n".join(out)
