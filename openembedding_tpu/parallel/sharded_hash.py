"""Hash-table embeddings sharded over the device mesh.

Same two data planes as ``sharded_table`` but for unbounded key spaces:

* ``"a2a"`` (default) — owner-routed exchange over the whole mesh (see
  ``parallel/alltoall.py``): each device owns one open-addressing shard,
  keys are partitioned ``key % num_shards`` (the reference's modulo shard
  layout, /root/reference/openembedding/server/EmbeddingPullOperator.cpp:73-78,
  applied to hashed keys, which are uniform by construction) and routed to
  their single owner.
* ``"psum"`` — shards along the model axis only (replicated over data):
  non-owned keys are masked to the EMPTY sentinel before the local table
  call (zero pull rows / dropped updates), so a psum over the model axis
  reconstructs the full batch exactly once.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..meta import EmbeddingVariableMeta
from ..utils import observability
from ..utils.jaxcompat import shard_map
from ..optim.initializers import make_initializer
from ..optim.optimizers import SparseOptimizer, make_optimizer
from .. import hash_table as hash_lib
from . import alltoall as a2a
from . import hot_cache
from . import precision
from . import sharded_table as st
from .mesh import DATA_AXIS, MODEL_AXIS


@dataclasses.dataclass(frozen=True)
class HashShardingSpec:
    """Static layout of one hash table over the mesh."""

    num_shards: int
    capacity_per_shard: int
    max_probes: int = hash_lib.DEFAULT_MAX_PROBES
    data_axis: str = DATA_AXIS
    model_axis: str = MODEL_AXIS
    plane: str = "a2a"   # sharded_table.PLANES member
    a2a_capacity: int = 0
    a2a_slack: float = 2.0
    key_width: int = 32  # 64 = [n, 2] int32 (lo, hi) pairs, x64-off
    cache_k: int = 0     # hot-row replica slots ("a2a+cache" plane)
    # compressed-exchange rungs (parallel/precision.py)
    exchange_precision: str = "f32"   # "f32" | "bf16"
    push_precision: str = "f32"       # "f32" | "bf16" | "int8_ef"

    @property
    def is_cached(self) -> bool:
        return self.plane == "a2a+cache"

    @property
    def plane_label(self) -> str:
        """Observable plane token incl. the precision suffix."""
        return precision.plane_label(self.plane, self.exchange_precision,
                                     self.push_precision)

    @property
    def pull_wire_dtype(self):
        return precision.wire_dtype(self.exchange_precision)

    @property
    def push_wire_dtype(self):
        return precision.wire_dtype(self.push_precision) \
            if self.push_precision == "bf16" else None

    @property
    def is_int8_ef(self) -> bool:
        return self.push_precision == "int8_ef"

    @property
    def is_grouped(self) -> bool:
        """Collection-level multi-table exchange (``parallel/grouped.py``)."""
        return self.plane in ("a2a+grouped", "a2a+grouped+pipelined")

    @property
    def is_pipelined(self) -> bool:
        """Trainer-level double-buffered exchange schedule
        (``parallel/pipelined.py``)."""
        return self.plane in ("a2a+pipelined", "a2a+grouped+pipelined")

    @property
    def shard_axes(self) -> tuple:
        if self.plane != "psum":
            return (self.data_axis, self.model_axis)
        return (self.model_axis,)

    @property
    def wide(self) -> bool:
        return self.key_width == 64

    def row_spec(self) -> P:
        return P(self.shard_axes)

    def owner_shard(self, keys: jnp.ndarray) -> jnp.ndarray:
        if hash_lib.is_wide(keys):
            # unsigned 64-bit key mod S computed in 32-bit arithmetic
            # (x64-off): (hi * 2^32 + lo) mod S with 2^32 mod S folded in.
            # Safe while S < 2^16 (S^2 fits uint32) — far beyond any mesh.
            s = self.num_shards
            c = jnp.uint32((1 << 32) % s)
            lo = keys[:, 0].astype(jnp.uint32)
            hi = keys[:, 1].astype(jnp.uint32)
            return (((hi % s) * c + lo % s) % s).astype(jnp.int32)
        # unsigned mod so negative (but valid) hashed keys still land on a
        # deterministic shard; jnp % already yields non-negative for positive
        # divisors, the cast keeps int64/int32 behavior identical.
        return (keys % jnp.asarray(self.num_shards, keys.dtype)).astype(jnp.int32)


def make_hash_sharding_spec(mesh: Mesh, total_capacity: int,
                            num_shards: int = -1,
                            max_probes: int = hash_lib.DEFAULT_MAX_PROBES,
                            plane: str = "a2a",
                            a2a_capacity: int = 0,
                            a2a_slack: float = 2.0,
                            key_width: int = 32,
                            cache_k: int = 0,
                            exchange_precision: str = "f32",
                            push_precision: str = "f32"
                            ) -> HashShardingSpec:
    """num_shards=-1 => one shard per device ("a2a") / per model slice ("psum").

    ``plane="a2a+cache"``: a2a layout plus a ``cache_k``-row hot-row replica
    on every device (``parallel/hot_cache.py``); 0 picks the default size.
    A ``+bf16``/``+int8`` plane suffix selects the compressed-exchange
    rungs (``parallel/precision.py``).
    """
    plane, exchange_precision, push_precision = st._resolve_precision(
        plane, exchange_precision, push_precision)
    if plane not in st.PLANES:
        raise ValueError(f"unknown plane {plane!r}")
    if key_width not in (32, 64):
        raise ValueError(f"key_width must be 32 or 64, got {key_width}")
    want = mesh.shape[MODEL_AXIS] if plane == "psum" else mesh.size
    if num_shards == -1:
        num_shards = want
    if num_shards != want:
        raise ValueError(
            f"num_shards={num_shards} must equal the {plane}-plane shard "
            f"count {want} for this mesh (or pass -1)")
    if plane == "a2a+cache" and cache_k <= 0:
        cache_k = hot_cache.DEFAULT_CACHE_K
    if plane != "a2a+cache":
        cache_k = 0
    cap = hash_lib.round_capacity(-(-total_capacity // num_shards))
    return HashShardingSpec(num_shards=num_shards, capacity_per_shard=cap,
                            max_probes=max_probes, plane=plane,
                            a2a_capacity=a2a_capacity, a2a_slack=a2a_slack,
                            key_width=key_width, cache_k=cache_k,
                            exchange_precision=exchange_precision,
                            push_precision=push_precision)


def table_state_specs(optimizer: SparseOptimizer, dim: int,
                      spec: HashShardingSpec):
    row = spec.row_spec()
    return hash_lib.HashTableState(
        keys=row, weights=row,
        slots={name: row for name in optimizer.slot_shapes(dim)},
        init_rng=P(), insert_failures=P())


def state_specs(optimizer: SparseOptimizer, dim: int, spec: HashShardingSpec):
    table = table_state_specs(optimizer, dim, spec)
    if spec.is_cached:
        return hot_cache.CachedState(
            table=table,
            cache=hot_cache.HotCacheState(
                keys=P(), rows=P(),
                slots={name: P() for name in table.slots}))
    return table


def create_sharded_hash_table(meta: EmbeddingVariableMeta,
                              optimizer: Any,
                              *,
                              mesh: Mesh,
                              spec: HashShardingSpec,
                              rng: Optional[jax.Array] = None,
                              key_dtype=jnp.int32,
                              wrap_cache: bool = True):
    """Allocate per-shard empty hash tables across the mesh.

    The per-key deterministic init uses the shared base rng (not folded per
    shard): a key has exactly one owner, and keeping the base rng global makes
    row init independent of shard count (checkpoints stay comparable when
    resharded).
    """
    optimizer = make_optimizer(optimizer)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    dim = meta.embedding_dim

    def _init(key):
        return hash_lib.create_hash_table(
            meta, optimizer,
            capacity=spec.capacity_per_shard, rng=key, key_dtype=key_dtype,
            key_width=spec.key_width)

    fn = shard_map(_init, mesh=mesh,
                   in_specs=(P(),),
                   out_specs=table_state_specs(optimizer, dim, spec),
                   check_vma=False)
    state = jax.jit(fn)(rng)
    if wrap_cache:
        # all-pad replica: zero hits (pure-a2a behavior) until the first
        # admission refresh (hot_cache.HotCacheManager / build_cache).
        # ``wrap_cache=False`` returns the bare table (callers composing
        # their own jitted init wrap eagerly afterwards).
        return hot_cache.attach_empty(state, spec, mesh)
    return state


def _mask_non_owned(spec: HashShardingSpec, flat: jnp.ndarray,
                    me: jnp.ndarray) -> jnp.ndarray:
    empty = hash_lib.empty_key(flat.dtype)
    if hash_lib.is_wide(flat):
        owned = (spec.owner_shard(flat) == me) & (flat[:, 1] != empty)
        return jnp.where(owned[:, None], flat, empty)
    owned = (spec.owner_shard(flat) == me) & (flat != empty)
    return jnp.where(owned, flat, empty)


def _my_shard(mesh: Mesh, spec: HashShardingSpec) -> jnp.ndarray:
    axes = spec.shard_axes
    return a2a.linear_shard_id(axes, tuple(mesh.shape[a] for a in axes))


@functools.lru_cache(maxsize=None)
def _insert_rows_program(mesh: Mesh, spec: HashShardingSpec,
                         slot_names: tuple, in_slot_names: tuple):
    """Cached jitted insert program: the checkpoint loader streams many
    same-shaped chunks, and rebuilding the shard_map closure per chunk would
    retrace (and on a remote-compile link, round-trip) every call."""

    def _insert(tkeys, tweights, tslots, init_rng, k, w, srows):
        local = hash_lib.HashTableState(
            keys=tkeys, weights=tweights, slots=tslots, init_rng=init_rng,
            insert_failures=jnp.zeros((), jnp.int32))
        flat = k.reshape(-1, 2) if spec.wide else k.ravel()
        masked = _mask_non_owned(spec, flat, _my_shard(mesh, spec))
        new = hash_lib.insert_rows(local, masked, w, srows or None,
                                   max_probes=spec.max_probes)
        failed = lax.psum(new.insert_failures, spec.shard_axes)
        return new.keys, new.weights, new.slots, failed

    row = spec.row_spec()
    slot_specs = {name: row for name in slot_names}
    in_slot_specs = {name: P() for name in in_slot_names}
    fn = shard_map(_insert, mesh=mesh,
                   in_specs=(row, row, slot_specs, P(), P(), P(),
                             in_slot_specs),
                   out_specs=(row, row, slot_specs, P()),
                   check_vma=False)
    return jax.jit(fn)


def insert_rows_sharded(state: hash_lib.HashTableState,
                        keys: jnp.ndarray,
                        weights: jnp.ndarray,
                        slot_rows=None,
                        *,
                        mesh: Mesh,
                        spec: HashShardingSpec) -> hash_lib.HashTableState:
    """Load-path row delivery: every shard inserts its owned keys verbatim.

    ``keys``/``weights``/``slot_rows`` are replicated host batches (the
    checkpoint loader streams chunks); non-owned keys are masked to EMPTY and
    skipped locally — the reference's owning-server delivery
    (EmbeddingLoadOperator.cpp:58-111).
    """
    slot_rows = slot_rows or {}
    fn = _insert_rows_program(mesh, spec, tuple(state.slots),
                              tuple(slot_rows))
    tkeys, tweights, tslots, failed = fn(
        state.keys, state.weights, state.slots, state.init_rng,
        keys, weights, slot_rows)
    return hash_lib.HashTableState(
        keys=tkeys, weights=tweights, slots=tslots,
        init_rng=state.init_rng,
        insert_failures=state.insert_failures + failed)


@functools.lru_cache(maxsize=None)
def _insert_packed_program(mesh: Mesh, spec: HashShardingSpec,
                           dim: int, layout: tuple):
    """Jitted insert taking ONE packed f32 buffer instead of the
    keys/weights/slots pytree: column 0 carries int32 keys bitcast to
    f32, columns [1, 1+dim) the weight row, the rest each slot's row
    (``layout`` = ((name, start_col, n_cols, row_shape), ...), static).

    Rationale: the offload tier ships an insert payload to the device
    EVERY step; one coalesced transfer replaces 2+len(slots) separate
    host->device arrays — fewer dispatches on any link, and on the
    tunneled bench chip per-transfer latency is the measurable cost
    (`python -m tools.offload_diag puts`). The unpack (slice + bitcast) fuses into
    the insert program."""

    def _insert(tkeys, tweights, tslots, init_rng, packed):
        local = hash_lib.HashTableState(
            keys=tkeys, weights=tweights, slots=tslots, init_rng=init_rng,
            insert_failures=jnp.zeros((), jnp.int32))
        n = packed.shape[0]
        k = lax.bitcast_convert_type(packed[:, 0], jnp.int32)
        w = packed[:, 1:1 + dim]
        srows = {name: packed[:, s:s + c].reshape((n,) + shape)
                 for name, s, c, shape in layout}
        masked = _mask_non_owned(spec, k, _my_shard(mesh, spec))
        new = hash_lib.insert_rows(local, masked, w, srows or None,
                                   max_probes=spec.max_probes)
        failed = lax.psum(new.insert_failures, spec.shard_axes)
        return new.keys, new.weights, new.slots, failed

    row = spec.row_spec()
    slot_specs = {name: row for name, _, _, _ in layout}
    fn = shard_map(_insert, mesh=mesh,
                   in_specs=(row, row, slot_specs, P(), P()),
                   out_specs=(row, row, slot_specs, P()),
                   check_vma=False)
    return jax.jit(fn)


def insert_rows_sharded_packed(state: hash_lib.HashTableState,
                               packed: jnp.ndarray,
                               layout: tuple,
                               *,
                               mesh: Mesh,
                               spec: HashShardingSpec
                               ) -> hash_lib.HashTableState:
    """:func:`insert_rows_sharded` behavior from ONE packed f32 buffer
    (int32 keys only — the offload cache's key plane; wide tables use
    the unpacked path). See :func:`_insert_packed_program`."""
    if spec.wide:
        raise ValueError("packed insert supports int32-key tables only")
    dim = state.weights.shape[-1]
    fn = _insert_packed_program(mesh, spec, dim, layout)
    tkeys, tweights, tslots, failed = fn(
        state.keys, state.weights, state.slots, state.init_rng, packed)
    return hash_lib.HashTableState(
        keys=tkeys, weights=tweights, slots=tslots,
        init_rng=state.init_rng,
        insert_failures=state.insert_failures + failed)


@functools.lru_cache(maxsize=None)
def _pull_program(mesh: Mesh, spec: HashShardingSpec, initializer: Any,
                  dim: int, batch_sharded: bool,
                  record_stats: bool = False):
    batch_spec = P(spec.data_axis) if batch_sharded else P()

    # a grouped-plane table addressed PER TABLE takes the plain a2a
    # program — grouping only exists at the collection level
    if (spec.plane != "psum" and spec.num_shards > 1) \
            or spec.is_cached:
        grid_axes, grid_sizes, split_axes, split_sizes = a2a.grid_info(
            mesh, spec.shard_axes, spec.model_axis, batch_sharded)

        def _pull_core(keys, weights, init_rng, flat):
            me = a2a.linear_shard_id(grid_axes, grid_sizes)
            local = hash_lib.HashTableState(
                keys=keys, weights=weights, slots={}, init_rng=init_rng,
                insert_failures=jnp.zeros((), jnp.int32))
            sentinel = hash_lib.empty_key(flat.dtype)

            def resolve(q):
                masked = _mask_non_owned(spec, q, me)
                return hash_lib.pull(local, masked, initializer,
                                     max_probes=spec.max_probes)

            def owner(q):
                valid = (q[:, 1] if spec.wide else q) != sentinel
                return jnp.where(valid, spec.owner_shard(q),
                                 spec.num_shards).astype(jnp.int32)

            return a2a.exchange_pull(
                flat, resolve, owner, sentinel=sentinel, dim=dim,
                num_shards=spec.num_shards, grid_axes=grid_axes,
                grid_sizes=grid_sizes, split_axes=split_axes,
                split_sizes=split_sizes, capacity=spec.a2a_capacity,
                slack=spec.a2a_slack, record_stats=record_stats,
                wire_dtype=spec.pull_wire_dtype)

        if spec.is_cached:
            def _pull(keys, weights, init_rng, ckeys, crows, idx):
                flat = idx.reshape(-1, 2) if spec.wide else idx.ravel()
                out_shape = (idx.shape[:-1] if spec.wide else idx.shape) \
                    + (dim,)
                sentinel = hash_lib.empty_key(flat.dtype)
                valid = (flat[:, 1] if spec.wide else flat) != sentinel
                pos, hit = hot_cache.lookup(ckeys, flat, valid)
                served = jnp.where(hit[:, None],
                                   jnp.take(crows, pos, axis=0),
                                   jnp.zeros((1, dim), crows.dtype))
                hot_cache.record_cache_stats(
                    hit, valid,
                    entry_bytes=dim * crows.dtype.itemsize
                    + (8 if spec.wide else 4),
                    split_axes=split_axes, split_sizes=split_sizes,
                    record=record_stats)
                resid = hot_cache.mask_hits(flat, hit, sentinel)
                rows = _pull_core(keys, weights, init_rng, resid)
                return (rows + served).reshape(out_shape)
        else:
            def _pull(keys, weights, init_rng, idx):
                flat = idx.reshape(-1, 2) if spec.wide else idx.ravel()
                out_shape = (idx.shape[:-1] if spec.wide else idx.shape) \
                    + (dim,)
                return _pull_core(keys, weights, init_rng,
                                  flat).reshape(out_shape)
    else:
        def _pull(keys, weights, init_rng, idx):
            local = hash_lib.HashTableState(
                keys=keys, weights=weights, slots={}, init_rng=init_rng,
                insert_failures=jnp.zeros((), jnp.int32))
            flat = idx.reshape(-1, 2) if spec.wide else idx.ravel()
            out_shape = (idx.shape[:-1] if spec.wide else idx.shape) \
                + (dim,)
            flat = _mask_non_owned(spec, flat,
                                   lax.axis_index(spec.model_axis))
            rows = hash_lib.pull(local, flat, initializer,
                                 max_probes=spec.max_probes)
            rows = lax.psum(rows, spec.model_axis)
            return rows.reshape(out_shape)

    row = spec.row_spec()
    if spec.is_cached:
        in_specs = (row, row, P(), P(), P(), batch_spec)
    else:
        in_specs = (row, row, P(), batch_spec)
    # plane-identifiable HLO module name for the contract audits
    # (analysis/contracts.py): failures name the plane that regressed
    _pull.__name__ = f"hash_pull_{spec.plane_label.replace('+', '_')}"
    fn = shard_map(_pull, mesh=mesh,
                   in_specs=in_specs,
                   out_specs=batch_spec,
                   check_vma=False)
    return jax.jit(fn)


def pull_sharded(state,
                 indices: jnp.ndarray,
                 initializer: Any,
                 *,
                 mesh: Mesh,
                 spec: HashShardingSpec,
                 batch_sharded: bool = True) -> jnp.ndarray:
    """Distributed hash lookup: the owner shard resolves each key.

    Missing-but-valid keys get their deterministic init row (computed only by
    the owner shard); EMPTY-sentinel keys return zero rows. ``initializer=
    None`` = read-only serving contract (missing keys -> zeros). On the
    ``"a2a+cache"`` plane ``state`` is a :class:`hot_cache.CachedState`;
    hot keys are served from the local replica (cached keys are always
    PRESENT in the table — admission rejects absent ones — so the replica
    can never shadow the deterministic-init contract).
    """
    record = observability.evaluate_performance()
    if initializer is not None:
        initializer = make_initializer(initializer)
    if spec.is_cached:
        table = state.table
        dim = table.weights.shape[-1]
        fn = _pull_program(mesh, spec, initializer, dim, batch_sharded,
                           record)
        return observability.plane_timed(
            "pull", spec.plane_label, record, fn, table.keys,
            table.weights, table.init_rng, state.cache.keys,
            state.cache.rows, indices)
    state = precision.unwrap(state)
    dim = state.weights.shape[-1]
    fn = _pull_program(mesh, spec, initializer, dim, batch_sharded, record)
    return observability.plane_timed(
        "pull", spec.plane_label, record, fn, state.keys, state.weights,
        state.init_rng, indices)


@functools.lru_cache(maxsize=None)
def _apply_program(mesh: Mesh, spec: HashShardingSpec,
                   optimizer: SparseOptimizer, initializer: Any, dim: int,
                   batch_sharded: bool, dedup_capacity: Optional[int],
                   slot_names: tuple, record_stats: bool = False):
    batch_spec = P(spec.data_axis) if batch_sharded else P()

    if (spec.plane != "psum" and spec.num_shards > 1) \
            or spec.is_cached:
        grid_axes, grid_sizes, split_axes, split_sizes = a2a.grid_info(
            mesh, spec.shard_axes, spec.model_axis, batch_sharded)

        def _push_core(keys, weights, slots, init_rng, flat, g2, ef=None):
            me = a2a.linear_shard_id(grid_axes, grid_sizes)
            sentinel = hash_lib.empty_key(
                flat.dtype if not spec.wide else jnp.int32)

            def owner(q):
                valid = (q[:, 1] if spec.wide else q) != sentinel
                return jnp.where(valid, spec.owner_shard(q),
                                 spec.num_shards).astype(jnp.int32)

            def apply_fn(st, q, grads, counts):
                tkeys, tweights, tslots, fails = st
                cur = hash_lib.HashTableState(
                    keys=tkeys, weights=tweights, slots=tslots,
                    init_rng=init_rng,
                    insert_failures=jnp.zeros((), jnp.int32))
                masked = _mask_non_owned(spec, q, me)
                new = hash_lib.apply_gradients(
                    cur, optimizer, initializer, masked, grads,
                    dedup_capacity=dedup_capacity,
                    max_probes=spec.max_probes, in_counts=counts)
                return (new.keys, new.weights, new.slots,
                        fails + new.insert_failures)

            return a2a.exchange_push(
                flat, g2,
                (keys, weights, slots, jnp.zeros((), jnp.int32)),
                apply_fn, owner,
                sentinel=sentinel, num_shards=spec.num_shards,
                grid_axes=grid_axes, grid_sizes=grid_sizes,
                split_axes=split_axes, split_sizes=split_sizes,
                capacity=spec.a2a_capacity, slack=spec.a2a_slack,
                record_stats=record_stats,
                wire_dtype=spec.push_wire_dtype, ef_state=ef)

        if spec.is_cached:
            def _apply(keys, weights, slots, init_rng, ckeys, crows,
                       cslots, idx, g):
                me = a2a.linear_shard_id(grid_axes, grid_sizes)
                flat = idx.reshape(-1, 2) if spec.wide else idx.ravel()
                g2 = g.reshape(-1, dim)
                sentinel = hash_lib.empty_key(flat.dtype)
                valid = (flat[:, 1] if spec.wide else flat) != sentinel
                pos, hit = hot_cache.lookup(ckeys, flat, valid)
                k = ckeys.shape[0]
                summed, counts = hot_cache.cache_pre_reduce(
                    pos, hit, g2, k, split_axes, split_sizes, grid_axes)
                hot_cache.record_cache_stats(
                    hit, valid,
                    entry_bytes=dim * crows.dtype.itemsize
                    + (12 if spec.wide else 8),
                    split_axes=split_axes, split_sizes=split_sizes,
                    record=record_stats)
                resid = hot_cache.mask_hits(flat, hit, sentinel)
                tkeys, tweights, tslots, fails = _push_core(
                    keys, weights, slots, init_rng, resid, g2)
                # identical psum'd totals on every device -> identical
                # replica update everywhere; the owner scatters its rows
                # back so the table stays authoritative
                cache = hot_cache.HotCacheState(keys=ckeys, rows=crows,
                                                slots=cslots)
                cache = hot_cache.update_replica(optimizer, cache, summed,
                                                 counts)
                # owner write-back: admitted keys are PRESENT, so the
                # probe hits; the scatter drops non-owned / untouched rows
                mine_keys = _mask_non_owned(spec, ckeys, me)
                slot = hash_lib.find_rows(tkeys, mine_keys,
                                          spec.max_probes)
                touched = (slot >= 0) & (counts > 0)
                oob = jnp.asarray(tweights.shape[0], jnp.int32)
                sc = jnp.where(touched, slot, oob)
                tweights = tweights.at[sc].set(
                    cache.rows.astype(tweights.dtype), mode="drop")
                tslots = {name: tslots[name].at[sc].set(
                    cache.slots[name].astype(tslots[name].dtype),
                    mode="drop") for name in tslots}
                return (tkeys, tweights, tslots, cache.rows, cache.slots,
                        lax.psum(fails, spec.shard_axes))
        elif spec.is_int8_ef:
            def _apply(keys, weights, slots, init_rng, ef_keys, ef_resid,
                       idx, g):
                flat = idx.reshape(-1, 2) if spec.wide else idx.ravel()
                res, (nek, ner) = _push_core(
                    keys, weights, slots, init_rng, flat,
                    g.reshape(-1, dim), ef=(ef_keys, ef_resid))
                tkeys, tweights, tslots, fails = res
                return (tkeys, tweights, tslots,
                        lax.psum(fails, spec.shard_axes), nek, ner)
        else:
            def _apply(keys, weights, slots, init_rng, idx, g):
                flat = idx.reshape(-1, 2) if spec.wide else idx.ravel()
                tkeys, tweights, tslots, fails = _push_core(
                    keys, weights, slots, init_rng, flat,
                    g.reshape(-1, dim))
                return (tkeys, tweights, tslots,
                        lax.psum(fails, spec.shard_axes))
    else:
        def _apply(keys, weights, slots, init_rng, idx, g):
            flat = idx.reshape(-1, 2) if spec.wide else idx.ravel()
            g2 = g.reshape(-1, dim)
            if batch_sharded:
                flat = lax.all_gather(flat, spec.data_axis, tiled=True)
                g2 = lax.all_gather(g2, spec.data_axis, tiled=True)
            flat = _mask_non_owned(spec, flat,
                                   lax.axis_index(spec.model_axis))
            local = hash_lib.HashTableState(
                keys=keys, weights=weights, slots=slots, init_rng=init_rng,
                insert_failures=jnp.zeros((), jnp.int32))
            new = hash_lib.apply_gradients(
                local, optimizer, initializer, flat, g2,
                dedup_capacity=dedup_capacity, max_probes=spec.max_probes)
            # per-shard failure deltas -> replicated global total
            failed = lax.psum(new.insert_failures, spec.model_axis)
            return new.keys, new.weights, new.slots, failed

    row = spec.row_spec()
    slot_specs = {name: row for name in slot_names}
    _apply.__name__ = f"hash_push_{spec.plane_label.replace('+', '_')}"
    if spec.is_cached:
        cache_slot_specs = {name: P() for name in slot_names}
        fn = shard_map(_apply, mesh=mesh,
                       in_specs=(row, row, slot_specs, P(), P(), P(),
                                 cache_slot_specs, batch_spec, batch_spec),
                       out_specs=(row, row, slot_specs, P(),
                                  cache_slot_specs, P()),
                       check_vma=False)
    elif spec.is_int8_ef and spec.num_shards > 1:
        ef_spec = P(spec.shard_axes)
        fn = shard_map(_apply, mesh=mesh,
                       in_specs=(row, row, slot_specs, P(), ef_spec,
                                 ef_spec, batch_spec, batch_spec),
                       out_specs=(row, row, slot_specs, P(), ef_spec,
                                  ef_spec),
                       check_vma=False)
    else:
        fn = shard_map(_apply, mesh=mesh,
                       in_specs=(row, row, slot_specs, P(),
                                 batch_spec, batch_spec),
                       out_specs=(row, row, slot_specs, P()),
                       check_vma=False)
    return jax.jit(fn)


def apply_gradients_sharded(state,
                            optimizer: SparseOptimizer,
                            initializer: Any,
                            indices: jnp.ndarray,
                            grads: jnp.ndarray,
                            *,
                            mesh: Mesh,
                            spec: HashShardingSpec,
                            batch_sharded: bool = True,
                            dedup_capacity: Optional[int] = None):
    """Distributed push+update: each key's grads reach its single owner
    shard. On the ``"a2a+cache"`` plane ``state`` is a
    :class:`hot_cache.CachedState`: hot keys pre-reduce locally + one psum
    over the K replica rows, and the owner writes the updated rows back."""
    optimizer = make_optimizer(optimizer)
    initializer = make_initializer(initializer) if initializer is not None \
        else None
    record = observability.evaluate_performance()
    if spec.is_cached:
        table = state.table
        dim = table.weights.shape[-1]
        fn = _apply_program(mesh, spec, optimizer, initializer, dim,
                            batch_sharded, dedup_capacity,
                            tuple(table.slots), record)
        keys, weights, slots, crows, cslots, failed = \
            observability.plane_timed(
                "push", spec.plane_label, record, fn,
                table.keys, table.weights, table.slots, table.init_rng,
                state.cache.keys, state.cache.rows, state.cache.slots,
                indices, grads)
        new_table = hash_lib.HashTableState(
            keys=keys, weights=weights, slots=slots,
            init_rng=table.init_rng,
            insert_failures=table.insert_failures + failed)
        return hot_cache.CachedState(
            table=new_table,
            cache=hot_cache.HotCacheState(keys=state.cache.keys,
                                          rows=crows, slots=cslots))
    if spec.is_int8_ef and spec.num_shards > 1:
        bare = precision.unwrap(state)
        dim = bare.weights.shape[-1]
        sentinel, key_dtype = precision.ef_key_space(
            use_hash=True, wide=spec.wide, key_dtype=bare.keys.dtype)
        n_flat = int(np.prod(indices.shape))
        if spec.wide:
            n_flat //= 2
        table, ef_keys, ef_resid = precision.ensure_ef(
            state, dim=dim, wide=spec.wide, sentinel=sentinel,
            n_flat=n_flat, data=mesh.shape[spec.data_axis],
            model=mesh.shape[spec.model_axis],
            batch_sharded=batch_sharded, key_dtype=key_dtype)
        fn = _apply_program(mesh, spec, optimizer, initializer, dim,
                            batch_sharded, dedup_capacity,
                            tuple(table.slots), record)
        keys, weights, slots, failed, nek, ner = \
            observability.plane_timed(
                "push", spec.plane_label, record, fn,
                table.keys, table.weights, table.slots, table.init_rng,
                ef_keys, ef_resid, indices, grads)
        new_table = hash_lib.HashTableState(
            keys=keys, weights=weights, slots=slots,
            init_rng=table.init_rng,
            insert_failures=table.insert_failures + failed)
        return precision.EFState(table=new_table, keys=nek, resid=ner)
    state = precision.unwrap(state)
    dim = state.weights.shape[-1]
    fn = _apply_program(mesh, spec, optimizer, initializer, dim,
                        batch_sharded, dedup_capacity, tuple(state.slots),
                        record)
    keys, weights, slots, failed = observability.plane_timed(
        "push", spec.plane_label, record, fn,
        state.keys, state.weights, state.slots, state.init_rng,
        indices, grads)
    return hash_lib.HashTableState(
        keys=keys, weights=weights, slots=slots,
        init_rng=state.init_rng,
        insert_failures=state.insert_failures + failed)
