"""Grouped multi-table exchange plane: one collective round per GROUP, not
per table (``plane="a2a+grouped"``).

The reference pays one pull RPC fan-out per PS variable per batch (SURVEY
§3.2) and the per-table translation inherits exactly that cost on TPU:
``EmbeddingCollection.pull`` / ``apply_gradients`` loop over specs, so a
model with T heterogeneous tables launches T independent dedup + bucketize
+ all-to-all + gather pipelines per step. ``fused.py`` rescues the
homogeneous case (same dim, same config -> literally one table); this
module is the heterogeneous counterpart — DLRM/FBGEMM-style table
batching — and stays EXACTLY equivalent to the per-table loop:

* a static planner groups the collection's grouped-plane tables by
  (dim-bucket, array/hash, key width, layout, shard count, dtype);
* each group's key streams are concatenated into ONE table-id-tagged
  index stream with static per-table segment offsets
  (``alltoall.segment_offsets``): array tables reuse the fused-table
  offset math (table t's id i rides as ``base[t] + i`` over the disjoint
  concatenation of padded vocabs — cf. ``fused.FusedMapper.offsets``),
  hash tables carry an explicit table-id column next to the key words
  (``[n, 2]`` int32 ``(key, tag)`` / ``[n, 3]`` ``(lo, hi, tag)`` rows,
  deduped lexicographically by ``ops.dedup.unique_rows``);
* ONE ``alltoall.exchange_pull`` (and one pre-reduced ``exchange_push``)
  routes the whole group per step. The owner carves the stream back into
  per-table rows on device (tag/offset dispatch is local index math) and
  applies each table's OWN optimizer server-side, so results match the
  per-table loop bit-for-bit up to float summation order.

Rows travel at the group's bucket dim (next power of two over member
dims); each table's ``dim_t`` columns are sliced back out after the
exchange — mixed dims share a round at the cost of column padding, the
standard table-batched-embedding trade.

On the owner, per-table dispatch over the received stream is WINDOWED,
not full-stream: the stream is sorted once by table tag (array offsets
sort tables contiguously by construction; sentinels are int32 min and
sort first), and each table gathers/probes/scatters only a
``dynamic_slice`` window of statically-bounded size — a single owner
can receive at most a table's global pre-dedup entry count, a
trace-time constant — so the owner-side work is O(stream · log), not
O(num_tables · stream). Without this, a 52-table group pays ~52x the
per-table loop's gather+scatter flops and the collective-launch win
drowns (measured: grouped push 8x the per-table wall on cpu8).

Equivalence argument, briefly: tagged keys from different tables are
distinct by construction (disjoint offset ranges / distinct tag columns),
so the group-level dedup merges exactly the duplicates the per-table
dedups merged; the exchange is exact for any key distribution (residue
rounds / overflow fallback, see ``alltoall.py``); and the owner applies
each table's optimizer once per key with the same merged (grad sum,
count) pre-reduces. Only the float ADD ORDER of duplicate-gradient
combines may differ — the same caveat the hot-row cache plane carries.

Per-table entry points (serving probes, the checkpoint loader,
``pull_sharded`` on a single grouped spec) fall back to the plain
``"a2a"`` program — grouping exists only at the collection level, so the
plane composes freely with ``"a2a+cache"`` variables in the same model
(cached tables keep their own replica path; grouped tables batch).
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .. import hash_table as hash_lib
from .. import table as table_lib
from ..analysis import scope
from ..ops import dedup
from ..utils import observability
from ..utils.jaxcompat import shard_map
from . import alltoall as a2a

GROUPED_PLANE = "a2a+grouped"
# the composed plane: grouped collection-level exchange AND the
# Trainer's pipelined step schedule (parallel/pipelined.py) — the
# prefetched exchange stays one collective round per group
GROUPED_PLANES = ("a2a+grouped", "a2a+grouped+pipelined")

# array offset streams are int32: a group's concatenated padded vocabs
# must stay addressable (the planner splits groups at this boundary)
_MAX_OFFSET_SPAN = 2**31 - 1


def dim_bucket(dim: int) -> int:
    """Rows travel at the next power of two >= dim (min 1): mixed dims
    share one exchange round at the cost of column padding."""
    return 1 << max(0, int(dim) - 1).bit_length() if dim > 1 else 1


@dataclasses.dataclass(frozen=True)
class ArrayMember:
    """Static per-table facts one array-table group member contributes."""

    name: str
    dim: int
    spec: Any                     # sharded_table.ShardingSpec
    optimizer: Any                # SparseOptimizer (push only)
    slot_names: Tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class HashMember:
    """Static per-table facts one hash-table group member contributes."""

    name: str
    dim: int
    spec: Any                     # sharded_hash.HashShardingSpec
    optimizer: Any
    initializer: Any              # None = read-only pull contract
    slot_names: Tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class GroupPlan:
    """One exchange group: every member shares bucket dim, kind, key
    shape, shard count and mesh axes, so one routed round serves all."""

    kind: str                     # "array" | "hash"
    bucket_dim: int
    key_dtype: str                # array: "int32" offsets; hash: key words
    members: tuple
    bases: Tuple[int, ...] = ()   # array only: fused-style offset bases

    @property
    def wide(self) -> bool:
        return self.kind == "hash" and self.key_dtype == "wide"


def plan_groups(collection, names, *, read_only: bool = False
                ) -> Tuple[GroupPlan, ...]:
    """Partition ``names`` (all on the grouped plane) into exchange groups.

    Grouping key: (kind, dim bucket, key shape, shard count, layout, mesh
    axes, exchange sizing, storage dtype) — everything that must agree for
    the streams to share one routed round. Members keep registration
    order; array groups split when the concatenated padded vocabs would
    overflow the int32 offset space.
    """
    ordered = sorted(names, key=collection.variable_id)
    buckets: Dict[tuple, list] = {}
    for name in ordered:
        spec = collection.specs[name]
        ss = collection.sharding_spec(name)
        if ss.plane not in GROUPED_PLANES:
            raise ValueError(f"{name!r} is not on a grouped plane "
                             f"({GROUPED_PLANES})")
        # ss.plane is part of the key: a plain-grouped and a
        # grouped+pipelined table must never share a plan — the
        # per-plan timing attribution labels by member plane, and the
        # Trainer pulls the two sets at different schedule points anyway
        if spec.use_hash:
            key = ("hash", ss.plane, spec.key_dtype,
                   dim_bucket(spec.output_dim),
                   ss.num_shards, ss.data_axis, ss.model_axis,
                   ss.a2a_capacity, ss.a2a_slack, spec.dtype,
                   ss.exchange_precision, ss.push_precision)
        else:
            key = ("array", ss.plane, dim_bucket(spec.output_dim),
                   ss.num_shards,
                   ss.layout, ss.data_axis, ss.model_axis,
                   ss.a2a_capacity, ss.a2a_slack, spec.dtype,
                   ss.exchange_precision, ss.push_precision)
        buckets.setdefault(key, []).append(name)

    plans = []
    for key, group_names in buckets.items():
        if key[0] == "hash":
            members = tuple(
                HashMember(
                    name=n, dim=collection.specs[n].output_dim,
                    spec=collection.sharding_spec(n),
                    optimizer=collection.optimizer(n),
                    initializer=(None if read_only
                                 else collection.initializer(n)),
                    slot_names=tuple(collection.optimizer(n).slot_shapes(
                        collection.specs[n].output_dim)))
                for n in group_names)
            plans.append(GroupPlan(kind="hash", bucket_dim=key[3],
                                   key_dtype=key[2], members=members))
            continue
        # array: accumulate members until the offset space would overflow
        run, span = [], 0
        for n in group_names:
            ss = collection.sharding_spec(n)
            if run and span + ss.padded_vocab > _MAX_OFFSET_SPAN:
                plans.append(_array_plan(collection, tuple(run), key[2]))
                run, span = [], 0
            run.append(n)
            span += ss.padded_vocab
        if run:
            plans.append(_array_plan(collection, tuple(run), key[2]))
    plans.sort(key=lambda p: collection.variable_id(p.members[0].name))
    return tuple(plans)


def _array_plan(collection, group_names, bucket: int) -> GroupPlan:
    members = tuple(
        ArrayMember(name=n, dim=collection.specs[n].output_dim,
                    spec=collection.sharding_spec(n),
                    optimizer=collection.optimizer(n),
                    slot_names=tuple(collection.optimizer(n).slot_shapes(
                        collection.specs[n].output_dim)))
        for n in group_names)
    bases = a2a.segment_offsets([m.spec.padded_vocab for m in members])
    return GroupPlan(kind="array", bucket_dim=bucket, key_dtype="int32",
                     members=members, bases=bases)


def _stream_bounds(plan: GroupPlan, idxs, grid_sizes, split_sizes
                   ) -> Tuple[int, ...]:
    """Static per-table caps on the entries ONE owner can receive for one
    table in one exchange. The senders jointly hold every data-row's
    stream exactly once (split peers partition it; per-sender dedup only
    shrinks it), so table t contributes at most its global pre-dedup
    entry count: data_rows * its per-device entries — a trace-time
    constant, which makes the owner-side per-table windows static."""
    data_rows = math.prod(grid_sizes) // math.prod(split_sizes)
    out = []
    for t in range(len(plan.members)):
        if plan.kind == "hash" and plan.wide:
            n_local = idxs[t].reshape(-1, 2).shape[0]
        else:
            n_local = idxs[t].ravel().shape[0]
        out.append(n_local * data_rows)
    return tuple(out)


def _window(start, size: int, *streams):
    """``dynamic_slice`` window [start, start+size) of each 1/2-D stream
    (start pre-clamped by the caller)."""
    return tuple(
        lax.dynamic_slice_in_dim(s, start, size, axis=0) for s in streams)


def _sorted_member_windows(col, bounds, thresholds, *streams):
    """Sorted-window dispatch core: ONE argsort of ``col`` (array offset
    keys / hash tag column — sentinels are int min and sort first, each
    member's rows land contiguous), then per member the
    statically-bounded window ``[start, start + min(n, bounds[t]))``
    with ``start = clamp(searchsorted(col_sorted, thresholds[t]))``.
    Yields ``(t, (col_w, order_w, *stream_w))`` — ``order_w`` maps
    window positions back to un-sorted stream positions (pull's
    scatter-back). The clamp keeps windows in range; spilling into a
    neighbor's rows is harmless because every caller masks foreign rows
    (disjoint offset ranges / distinct tags) before touching state, so
    overlapping windows contribute exact zeros outside their member."""
    n = col.shape[0]
    order = jnp.argsort(col)
    sorted_all = (col[order], order) + tuple(s[order] for s in streams)
    for t, (bound, thr) in enumerate(zip(bounds, thresholds)):
        size = min(n, bound)
        start = jnp.minimum(
            jnp.searchsorted(sorted_all[0],
                             jnp.asarray(thr, col.dtype)
                             ).astype(jnp.int32),
            jnp.int32(n - size))
        yield t, _window(start, size, *sorted_all)


# --- array groups: fused-style offset streams --------------------------------

def _array_owner_resolve(plan: GroupPlan, me):
    """(owner_fn, resolve_builder) over an offset-tagged array stream."""
    members = plan.members
    bases = plan.bases
    num_shards = members[0].spec.num_shards

    def owner(keys):
        own = jnp.full(keys.shape, num_shards, jnp.int32)
        for t, m in enumerate(members):
            in_t = (keys >= bases[t]) & (keys < bases[t + 1])
            shard, _ = m.spec.shard_and_local(keys - bases[t])
            own = jnp.where(in_t, shard.astype(jnp.int32), own)
        return own

    def resolve_with(weights, bounds):
        def resolve(keys):
            out = jnp.zeros((keys.shape[0], plan.bucket_dim),
                            weights[0].dtype)
            for t, (kw, ow) in _sorted_member_windows(
                    keys, bounds, bases[:-1]):
                m = members[t]
                shard, local = m.spec.shard_and_local(kw - bases[t])
                mine = ((kw >= bases[t]) & (kw < bases[t + 1])
                        & (shard == me))
                rows = jnp.take(weights[t], jnp.where(mine, local, 0),
                                axis=0, mode="clip")
                rows = jnp.where(mine[:, None], rows,
                                 jnp.zeros_like(rows))
                out = out.at[ow].add(jnp.pad(
                    rows, ((0, 0), (0, plan.bucket_dim - m.dim))))
            return out
        return resolve

    return owner, resolve_with


def _tag_array_streams(plan: GroupPlan, idxs) -> jnp.ndarray:
    """Per-table id columns -> one offset-tagged int32 stream. Ids a table
    would reject (negative / beyond its padded vocab) are masked to the
    sentinel BEFORE the offset shift so they can never alias into a
    neighbor table's range."""
    tagged = []
    for t, m in enumerate(plan.members):
        flat = idxs[t].ravel()
        ok = (flat >= 0) & (flat < m.spec.padded_vocab)
        safe = jnp.where(ok, flat, 0).astype(jnp.int32)
        tagged.append(jnp.where(ok, safe + jnp.int32(plan.bases[t]),
                                jnp.int32(dedup.FILL)))
    return jnp.concatenate(tagged)


@functools.lru_cache(maxsize=None)
def _array_pull_program(mesh: Mesh, plan: GroupPlan, batch_sharded: bool,
                        record_stats: bool = False):
    members = plan.members
    first = members[0].spec
    T = len(members)
    grid_axes, grid_sizes, split_axes, split_sizes = a2a.grid_info(
        mesh, first.shard_axes, first.model_axis, batch_sharded)
    batch_spec = P(first.data_axis) if batch_sharded else P()

    def _pull(*args):
        weights, idxs = args[:T], args[T:]
        me = a2a.linear_shard_id(grid_axes, grid_sizes)
        owner, resolve_with = _array_owner_resolve(plan, me)
        flat_all = _tag_array_streams(plan, idxs)
        bounds = _stream_bounds(plan, idxs, grid_sizes, split_sizes)
        rows = a2a.exchange_pull(
            flat_all, resolve_with(weights, bounds), owner,
            sentinel=dedup.FILL,
            dim=plan.bucket_dim, num_shards=first.num_shards,
            grid_axes=grid_axes, grid_sizes=grid_sizes,
            split_axes=split_axes, split_sizes=split_sizes,
            capacity=first.a2a_capacity, slack=first.a2a_slack,
            record_stats=record_stats,
            wire_dtype=first.pull_wire_dtype)
        segs = a2a.carve_segments(rows,
                                  [i.ravel().shape[0] for i in idxs])
        return tuple(
            seg[:, :m.dim].reshape(idxs[t].shape + (m.dim,))
            for t, (seg, m) in enumerate(zip(segs, members)))

    _pull.__name__ = "grouped_pull"
    fn = shard_map(_pull, mesh=mesh,
                   in_specs=(first.row_spec(),) * T + (batch_spec,) * T,
                   out_specs=(batch_spec,) * T,
                   check_vma=False)
    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _array_push_program(mesh: Mesh, plan: GroupPlan, batch_sharded: bool,
                        record_stats: bool = False):
    members = plan.members
    first = members[0].spec
    T = len(members)
    grid_axes, grid_sizes, split_axes, split_sizes = a2a.grid_info(
        mesh, first.shard_axes, first.model_axis, batch_sharded)
    batch_spec = P(first.data_axis) if batch_sharded else P()

    def _apply(*args):
        weights = args[:T]
        slots = args[T:2 * T]
        idxs = args[2 * T:3 * T]
        grads = args[3 * T:]
        me = a2a.linear_shard_id(grid_axes, grid_sizes)
        owner, _ = _array_owner_resolve(plan, me)
        flat_all = _tag_array_streams(plan, idxs)
        bounds = _stream_bounds(plan, idxs, grid_sizes, split_sizes)
        g_all = jnp.concatenate([
            jnp.pad(grads[t].reshape(-1, m.dim),
                    ((0, 0), (0, plan.bucket_dim - m.dim)))
            for t, m in enumerate(members)])

        def apply_fn(st, keys, g, counts):
            new = []
            for t, (kw, _ow, gw, cw) in _sorted_member_windows(
                    keys, bounds, plan.bases[:-1], g, counts):
                m = members[t]
                w_t, s_t = st[t]
                shard, local = m.spec.shard_and_local(
                    kw - plan.bases[t])
                mine = ((kw >= plan.bases[t])
                        & (kw < plan.bases[t + 1]) & (shard == me))
                masked = jnp.where(mine, local, -1)
                ns = table_lib.apply_gradients(
                    table_lib.TableState(weights=w_t, slots=s_t),
                    m.optimizer, masked, gw[:, :m.dim],
                    in_counts=cw)
                new.append((ns.weights, ns.slots))
            return tuple(new)

        return a2a.exchange_push(
            flat_all, g_all,
            tuple((weights[t], slots[t]) for t in range(T)),
            apply_fn, owner, sentinel=dedup.FILL,
            num_shards=first.num_shards, grid_axes=grid_axes,
            grid_sizes=grid_sizes, split_axes=split_axes,
            split_sizes=split_sizes, capacity=first.a2a_capacity,
            slack=first.a2a_slack, record_stats=record_stats,
            wire_dtype=first.push_wire_dtype)

    _apply.__name__ = "grouped_push"
    row = first.row_spec()
    slot_specs = tuple({name: row for name in m.slot_names}
                       for m in members)
    fn = shard_map(_apply, mesh=mesh,
                   in_specs=(row,) * T + slot_specs
                   + (batch_spec,) * 2 * T,
                   out_specs=tuple((row, slot_specs[t])
                                   for t in range(T)),
                   check_vma=False)
    return jax.jit(fn)


# --- hash groups: explicit table-id column next to the key words -------------

def _hash_key_dtype(plan: GroupPlan):
    return jnp.int32 if plan.wide else jnp.dtype(plan.key_dtype)


def _tag_hash_streams(plan: GroupPlan, idxs) -> jnp.ndarray:
    """Per-table key columns -> one [N, kw+1] (key..., tag) stream.
    Invalid keys (EMPTY sentinel) become all-sentinel rows, so their tag
    never marks them as any table's traffic."""
    empty = hash_lib.empty_key(_hash_key_dtype(plan))
    tagged = []
    for t, m in enumerate(plan.members):
        if plan.wide:
            flat = idxs[t].reshape(-1, 2)
            valid = flat[:, 1] != empty
            cols = flat
        else:
            flat = idxs[t].ravel()
            valid = flat != empty
            cols = flat[:, None]
        tag = jnp.where(valid, jnp.asarray(t, cols.dtype),
                        jnp.asarray(empty, cols.dtype))
        row = jnp.concatenate(
            [jnp.where(valid[:, None], cols,
                       jnp.asarray(empty, cols.dtype)), tag[:, None]],
            axis=1)
        tagged.append(row)
    return jnp.concatenate(tagged)


def _hash_owner(plan: GroupPlan, kw: int):
    members = plan.members
    num_shards = members[0].spec.num_shards

    def owner(q):
        keyc = q[:, :kw] if plan.wide else q[:, 0]
        tag = q[:, kw]
        valid = (tag >= 0) & (tag < len(members))
        own = members[0].spec.owner_shard(keyc)
        return jnp.where(valid, own,
                         jnp.int32(num_shards)).astype(jnp.int32)

    return owner


@functools.lru_cache(maxsize=None)
def _hash_pull_program(mesh: Mesh, plan: GroupPlan, batch_sharded: bool,
                       record_stats: bool = False):
    members = plan.members
    first = members[0].spec
    T = len(members)
    kw = 2 if plan.wide else 1
    grid_axes, grid_sizes, split_axes, split_sizes = a2a.grid_info(
        mesh, first.shard_axes, first.model_axis, batch_sharded)
    batch_spec = P(first.data_axis) if batch_sharded else P()
    empty = hash_lib.empty_key(_hash_key_dtype(plan))

    def _pull(*args):
        tkeys = args[:T]
        tweights = args[T:2 * T]
        rngs = args[2 * T:3 * T]
        idxs = args[3 * T:]
        me = a2a.linear_shard_id(grid_axes, grid_sizes)
        flat_all = _tag_hash_streams(plan, idxs)
        owner = _hash_owner(plan, kw)
        bounds = _stream_bounds(plan, idxs, grid_sizes, split_sizes)

        def resolve(q):
            keyc_all = q[:, :kw] if plan.wide else q[:, 0]
            out = jnp.zeros((q.shape[0], plan.bucket_dim),
                            tweights[0].dtype)
            for t, (tag, ow, keyc) in _sorted_member_windows(
                    q[:, kw], bounds, range(T), keyc_all):
                m = members[t]
                mine = (tag == t) & (m.spec.owner_shard(keyc) == me)
                if plan.wide:
                    masked = jnp.where(mine[:, None], keyc,
                                       jnp.asarray(empty, keyc.dtype))
                else:
                    masked = jnp.where(mine, keyc,
                                       jnp.asarray(empty, keyc.dtype))
                local = hash_lib.HashTableState(
                    keys=tkeys[t], weights=tweights[t], slots={},
                    init_rng=rngs[t],
                    insert_failures=jnp.zeros((), jnp.int32))
                rows = hash_lib.pull(local, masked, m.initializer,
                                     max_probes=m.spec.max_probes)
                out = out.at[ow].add(jnp.pad(
                    rows, ((0, 0), (0, plan.bucket_dim - m.dim))))
            return out

        rows = a2a.exchange_pull(
            flat_all, resolve, owner, sentinel=empty,
            dim=plan.bucket_dim, num_shards=first.num_shards,
            grid_axes=grid_axes, grid_sizes=grid_sizes,
            split_axes=split_axes, split_sizes=split_sizes,
            capacity=first.a2a_capacity, slack=first.a2a_slack,
            record_stats=record_stats,
            wire_dtype=first.pull_wire_dtype)
        sizes = [(i.reshape(-1, 2) if plan.wide else i.ravel()).shape[0]
                 for i in idxs]
        segs = a2a.carve_segments(rows, sizes)
        outs = []
        for t, (seg, m) in enumerate(zip(segs, members)):
            shape = (idxs[t].shape[:-1] if plan.wide else idxs[t].shape) \
                + (m.dim,)
            outs.append(seg[:, :m.dim].reshape(shape))
        return tuple(outs)

    _pull.__name__ = "grouped_hash_pull"
    row = first.row_spec()
    fn = shard_map(_pull, mesh=mesh,
                   in_specs=(row,) * 2 * T + (P(),) * T
                   + (batch_spec,) * T,
                   out_specs=(batch_spec,) * T,
                   check_vma=False)
    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _hash_push_program(mesh: Mesh, plan: GroupPlan, batch_sharded: bool,
                       record_stats: bool = False):
    members = plan.members
    first = members[0].spec
    T = len(members)
    kw = 2 if plan.wide else 1
    grid_axes, grid_sizes, split_axes, split_sizes = a2a.grid_info(
        mesh, first.shard_axes, first.model_axis, batch_sharded)
    batch_spec = P(first.data_axis) if batch_sharded else P()
    empty = hash_lib.empty_key(_hash_key_dtype(plan))

    def _apply(*args):
        tkeys = args[:T]
        tweights = args[T:2 * T]
        tslots = args[2 * T:3 * T]
        rngs = args[3 * T:4 * T]
        idxs = args[4 * T:5 * T]
        grads = args[5 * T:]
        me = a2a.linear_shard_id(grid_axes, grid_sizes)
        flat_all = _tag_hash_streams(plan, idxs)
        owner = _hash_owner(plan, kw)
        bounds = _stream_bounds(plan, idxs, grid_sizes, split_sizes)
        g_all = jnp.concatenate([
            jnp.pad(grads[t].reshape(-1, m.dim),
                    ((0, 0), (0, plan.bucket_dim - m.dim)))
            for t, m in enumerate(members)])

        def apply_fn(st, q, g, counts):
            keyc_all = q[:, :kw] if plan.wide else q[:, 0]
            new = []
            for t, (tag, _ow, keyc, gw, cw) in _sorted_member_windows(
                    q[:, kw], bounds, range(T), keyc_all, g, counts):
                m = members[t]
                k_t, w_t, s_t, fails = st[t]
                mine = (tag == t) & (m.spec.owner_shard(keyc) == me)
                if plan.wide:
                    masked = jnp.where(mine[:, None], keyc,
                                       jnp.asarray(empty, keyc.dtype))
                else:
                    masked = jnp.where(mine, keyc,
                                       jnp.asarray(empty, keyc.dtype))
                cur = hash_lib.HashTableState(
                    keys=k_t, weights=w_t, slots=s_t, init_rng=rngs[t],
                    insert_failures=jnp.zeros((), jnp.int32))
                ns = hash_lib.apply_gradients(
                    cur, m.optimizer, m.initializer, masked,
                    gw[:, :m.dim], max_probes=m.spec.max_probes,
                    in_counts=cw)
                new.append((ns.keys, ns.weights, ns.slots,
                            fails + ns.insert_failures))
            return tuple(new)

        res = a2a.exchange_push(
            flat_all, g_all,
            tuple((tkeys[t], tweights[t], tslots[t],
                   jnp.zeros((), jnp.int32)) for t in range(T)),
            apply_fn, owner, sentinel=empty,
            num_shards=first.num_shards, grid_axes=grid_axes,
            grid_sizes=grid_sizes, split_axes=split_axes,
            split_sizes=split_sizes, capacity=first.a2a_capacity,
            slack=first.a2a_slack, record_stats=record_stats,
            wire_dtype=first.push_wire_dtype)
        # per-shard failure deltas -> replicated global totals
        return tuple((k, w, s, lax.psum(f, first.shard_axes))
                     for k, w, s, f in res)

    _apply.__name__ = "grouped_hash_push"
    row = first.row_spec()
    slot_specs = tuple({name: row for name in m.slot_names}
                       for m in members)
    fn = shard_map(_apply, mesh=mesh,
                   in_specs=(row,) * 2 * T + slot_specs + (P(),) * T
                   + (batch_spec,) * 2 * T,
                   out_specs=tuple((row, row, slot_specs[t], P())
                                   for t in range(T)),
                   check_vma=False)
    return jax.jit(fn)


# --- collection-level dispatch -----------------------------------------------

def _record_group(plan: GroupPlan, idxs, itemsize: int) -> None:
    """Gated host counters: groups exchanged + an entry-granularity
    (pre-dedup) byte estimate of the group's routed traffic."""
    if plan.kind == "hash":
        kc = (2 if plan.wide else 1) + 1
        n = sum(int(i.size) // (2 if plan.wide else 1) for i in idxs)
    else:
        kc = 1
        n = sum(int(i.size) for i in idxs)
    nbytes = n * (plan.bucket_dim * itemsize + kc * 4)
    observability.GLOBAL.add("grouped_groups", 1)
    observability.GLOBAL.add("grouped_exchange_bytes", nbytes)
    # distribution next to the sum: the histogram separates "one huge
    # group" from "many small ones" — the sum alone cannot
    scope.HISTOGRAMS.observe("grouped_exchange_bytes", float(nbytes))


def pull_grouped(collection, states, idx_map: Dict[str, jnp.ndarray], *,
                 read_only: bool = False,
                 batch_sharded: bool = True) -> Dict[str, jnp.ndarray]:
    """Lookup rows for every grouped-plane column in ``idx_map`` — one
    routed exchange per GROUP. Called by ``EmbeddingCollection.pull``;
    returns raw (un-pooled) rows shaped like the per-table path's."""
    record = observability.evaluate_performance()
    # the in-program residue counters (record -> jax.debug.callback) fire
    # per step even under an outer jit; the HOST counters here run once
    # per COMPILE there, so they record only on eager dispatch
    host_record = record and not observability.under_trace(idx_map)
    mesh = collection.mesh
    out = {}
    for plan in plan_groups(collection, tuple(idx_map),
                            read_only=read_only):
        names = [m.name for m in plan.members]
        idxs = [idx_map[n] for n in names]
        if plan.kind == "array":
            fn = _array_pull_program(mesh, plan, batch_sharded, record)
            args = [states[n].weights for n in names] + idxs
        else:
            fn = _hash_pull_program(mesh, plan, batch_sharded, record)
            args = ([states[n].keys for n in names]
                    + [states[n].weights for n in names]
                    + [states[n].init_rng for n in names] + idxs)
        res = observability.plane_timed(
            "pull", plan.members[0].spec.plane_label, record, fn, *args)
        if host_record:
            _record_group(plan, idxs,
                          states[names[0]].weights.dtype.itemsize)
        out.update(zip(names, res))
    return out


def apply_gradients_grouped(collection, states,
                            idx_map: Dict[str, jnp.ndarray],
                            grads_map: Dict[str, jnp.ndarray], *,
                            batch_sharded: bool = True) -> Dict[str, Any]:
    """Push+update for every grouped-plane column — one pre-reduced
    routed exchange per GROUP, per-table optimizers applied server-side.
    Returns the new state per variable (same pytree types as the
    per-table path)."""
    record = observability.evaluate_performance()
    host_record = record and not observability.under_trace(idx_map)
    mesh = collection.mesh
    out = {}
    for plan in plan_groups(collection, tuple(idx_map)):
        names = [m.name for m in plan.members]
        idxs = [idx_map[n] for n in names]
        grads = [grads_map[n] for n in names]
        if plan.kind == "array":
            fn = _array_push_program(mesh, plan, batch_sharded, record)
            res = observability.plane_timed(
                "push", plan.members[0].spec.plane_label, record, fn,
                *([states[n].weights for n in names]
                  + [states[n].slots for n in names] + idxs + grads))
            for n, (w, s) in zip(names, res):
                out[n] = table_lib.TableState(weights=w, slots=s)
        else:
            fn = _hash_push_program(mesh, plan, batch_sharded, record)
            res = observability.plane_timed(
                "push", plan.members[0].spec.plane_label, record, fn,
                *([states[n].keys for n in names]
                  + [states[n].weights for n in names]
                  + [states[n].slots for n in names]
                  + [states[n].init_rng for n in names] + idxs + grads))
            for n, (k, w, s, f) in zip(names, res):
                out[n] = hash_lib.HashTableState(
                    keys=k, weights=w, slots=s,
                    init_rng=states[n].init_rng,
                    insert_failures=states[n].insert_failures + f)
        if host_record:
            _record_group(plan, idxs,
                          out[names[0]].weights.dtype.itemsize)
    return out
