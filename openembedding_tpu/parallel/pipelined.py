"""Pipelined exchange plane: overlap the sparse exchange with dense compute
(``plane="a2a+pipelined"``, ``"a2a+grouped+pipelined"``).

The reference dedicates a whole TF-op layer to hiding embedding-exchange
latency behind dense compute: ``PrefetchPullWeights`` issues the pull RPCs
for a FUTURE batch from the input pipeline while the current batch's dense
fwd/bwd runs, and the server's pending-pull queue holds each prefetched
pull until the previous batch's push has committed — a per-batch version
barrier, so prefetching never changes the numbers (SURVEY L5:
``exb_ops.cpp:109-205``, ``Prefetch.h``, ``EmbeddingPullOperator.cpp:
125-141``). Every plane here ran pull -> dense -> push strictly
serialized inside one jitted step, with the whole exchange on the
critical path.

This module is that prefetch layer, TPU-native: ONE jitted SPMD step
program per batch whose dataflow is re-cut so the exchange can overlap
the dense dots —

* **rows are double-buffered**: step N's dense fwd/bwd consumes the rows
  buffer pulled by step N-1's program (a :class:`PipelineState` input,
  donated — the in/out row buffers alternate in place), so the dense
  compute depends on NO collective of its own program;
* **the prefetch pull for batch N+1 rides step N's program**: its
  dedup/bucketize/key-leg collectives depend only on the (input) index
  stream, so XLA's scheduler is free to run them concurrently with the
  dense dots — the async-start/async-done overlap the contract audits;
* **the version barrier is an op dependency**: the prefetched pull's
  row RESOLUTION reads the tables produced by step N's push, exactly
  like the reference's server holding prefetched pulls until the
  previous batch commits. This is what keeps the plane bit-identical
  to ``"a2a"``: the op order on every table is
  ``..., push(N), pull(N+1), push(N+1), ...`` — the serial plane's
  order with the step boundaries cut one pull earlier.

Schedule of step N's program (steady state)::

      dense fwd/bwd(N)  ∥  pull(N+1) index+key legs     <- overlapped
              |                      |
         push(N) commit ------------>|                  <- version barrier
                                     v
                          pull(N+1) row resolution      -> rows buffer N+1

A deliberately *delayed* push (push(N-1) riding step N, the textbook
software-pipelining cut) would hide the push too — but then pull(N+1)
could never observe push(N) and every overlapping key trains on
one-step-stale rows: NOT equivalent to ``"a2a"``. The reference makes
the same call (the version barrier), so this plane does; the pending
gradients therefore never outlive their own step program and the only
pipeline state is the pulled-row double buffer.

Drain semantics: the tables are authoritative after EVERY step (no
pending pushes), so eval needs no drain at all and "draining" just
discards the prefetched row buffer (:func:`drain` /
``Trainer.drain_pipeline``). A warmup prologue (:func:`prime`) fills
the buffer for the first batch — the same eager pull program the plain
``"a2a"`` plane would have run, so results are bit-identical at any
drain point.

Composition matrix: ``"a2a+grouped+pipelined"`` variables batch their
prefetched exchange into one collective round per GROUP
(``parallel/grouped.py``); plain ``"a2a"``/``"psum"``/``"a2a+cache"``
variables in the same model keep their in-step serial pull (the cache
plane's host-side admission refresh rewrites replica state between
steps, which a prefetched buffer cannot see — the refresh is
value-preserving for the TABLE, so the two planes compose side by side
but do not stack). Offloaded variables must NOT be pipelined: their
host->HBM cache inserts mutate table state between the prefetch and the
consuming step.

Per-table entry points (serving probes, checkpoint paths,
``pull_sharded`` on a pipelined spec) run the plain ``"a2a"`` program —
like the grouped plane, pipelining exists only at the Trainer level.
"""

from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp
from flax import struct

PIPELINED_PLANES = ("a2a+pipelined", "a2a+grouped+pipelined")


@struct.dataclass
class PipelineState:
    """The pipeline's only cross-step state: the prefetched row buffer.

    ``rows[name]`` holds the (pooled, batch-sharded) rows the NEXT
    batch's dense compute will consume, pulled AFTER the producing
    step's push committed (the version barrier). Threaded through
    ``TrainState.pipe`` and donated with it, so the in/out buffers
    double-buffer in place. Derived state: never checkpointed — a
    restore re-primes from the authoritative tables.
    """

    rows: Dict[str, jnp.ndarray]


def split_columns(collection, inputs: Dict[str, Any]):
    """(pipelined, inline) partition of a batch's sparse columns."""
    pipelined = frozenset(collection.pipelined_names())
    pre = {n: v for n, v in inputs.items() if n in pipelined}
    inline = {n: v for n, v in inputs.items() if n not in pipelined}
    return pre, inline


def prefetch_pull(collection, states, inputs: Dict[str, Any], *,
                  batch_sharded: bool = True) -> PipelineState:
    """Pull the pipelined columns of ``inputs`` into a fresh row buffer.

    Called inside the step program (tables post-push: the version
    barrier) AND eagerly by the warmup prologue / re-prime path — both
    run the same ``EmbeddingCollection.pull`` the serial plane runs, so
    grouped members batch into group rounds and pooled members come
    back combined. Exactness follows: the buffer holds exactly what a
    serial step's own pull would have produced.
    """
    pre, _ = split_columns(collection, inputs)
    return PipelineState(rows=collection.pull(states, pre,
                                              batch_sharded=batch_sharded))


def drain(state):
    """Discard the prefetched row buffer (``TrainState.pipe`` -> None).

    The tables are authoritative after every step — draining loses no
    updates, it only forfeits the prefetch (the next step re-primes).
    """
    if getattr(state, "pipe", None) is None:
        return state
    return state.replace(pipe=None)
