"""Device-mesh helpers.

The reference's cluster topology (N workers + M parameter-server processes
over TCP/RDMA, reference client/Connection.cpp, entry/server.cc) maps
TPU-natively to a single SPMD program over a 2-D device mesh:

* ``data`` axis — the reference's workers (Horovod data parallelism): batch
  sharded, dense params replicated, dense grads all-reduced by XLA.
* ``model`` axis — the reference's PS shards: embedding tables sharded along
  the vocabulary dimension; pull/push become collectives over ICI.

A single axis can be 1 (pure DP or pure model parallel). Multi-host scaling
uses the same mesh spanning hosts (jax distributed init); ICI carries the
in-slice collectives, DCN the cross-slice ones — no custom RPC layer.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"


def create_mesh(data: int = 1, model: Optional[int] = None,
                devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a (data, model) mesh. ``model=None`` uses all remaining devices.

    Equivalent of the reference's worker_num / wait_num_servers bootstrap
    flags (openembedding/__init__.py:33-40): worker_num -> data axis size,
    server count -> model axis size, "server in each worker" (-1) -> the same
    devices appear on both axes of one mesh.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if model is None:
        if n % data:
            raise ValueError(f"{n} devices not divisible by data={data}")
        model = n // data
    if data * model != n:
        raise ValueError(f"mesh {data}x{model} != {n} devices")
    arr = np.asarray(devices).reshape(data, model)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def single_device_mesh(device: Optional[jax.Device] = None) -> Mesh:
    devices = [device] if device is not None else jax.devices()[:1]
    return create_mesh(1, 1, devices)
