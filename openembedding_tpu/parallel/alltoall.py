"""Owner-routed all-to-all exchange: the scale-grade sparse data plane.

The reference's pull/push pipeline is an *owner exchange*: dedup client-side,
partition keys by owning shard, send each shard only its own requests, scatter
the per-shard responses back
(/root/reference/openembedding/server/EmbeddingPullOperator.cpp:60-112,207-252,
EmbeddingPushOperator.cpp:29-104). The first TPU data plane here (the "psum"
plane in ``sharded_table``/``sharded_hash``) replaced that with gather + psum
(pull) and all_gather + masked local update (push) — simple and correct, but
its ICI traffic scales with *mesh size*, not with owned rows: the push
all_gathers the full global batch to every device.

This module is the owner exchange done TPU-natively, inside one shard_map
program ("a2a" plane):

* tables are sharded over the **whole mesh** (data x model axes = N shards),
  so HBM capacity scales with every chip and there are no table replicas to
  keep in sync;
* each device handles a distinct slice of the batch (the model-axis peers of
  a data slice split their common copy), dedups it, buckets the unique keys
  by owner shard into fixed-capacity blocks, and a grid all-to-all routes
  each block to its owner — indices out, rows (pull) or pre-reduced
  (grad, count) pairs (push) back;
* the owner resolves rows locally (array index math or hash probe) and, on
  push, merges the per-peer pre-reduces exactly like the reference's
  server-side MpscGradientReducer (counts are summed, not recounted).

Per-device ICI bytes per step are O(slack * batch_slice * dim) instead of
O(global_batch * dim) — the gap to the reference's per-owner exchange closed.

Static shapes: the per-destination bucket capacity must be fixed at trace
time. Keys are uniform across owners by construction ("mod" layout spreads
sequential ids; hash keys are avalanche-mixed), so the default capacity
``max(32, 2 * mean_bucket)`` fits everything in the first round with
overwhelming probability. The exchange is nevertheless EXACT for any key
distribution — like the reference's variable-size RPC exchange
(EmbeddingPullOperator.cpp:60-112): entries past a bucket's capacity stay
pending and a residue loop (``lax.while_loop``) re-routes them in further
fixed-capacity rounds until a globally psum'd pending count reaches zero.
Adversarial skew (e.g. every id congruent modulo the shard count) costs
extra rounds, never correctness. :func:`routing_overflow` remains as a
sizing diagnostic — it now predicts *extra rounds*, not data loss — and the
gated ``a2a_extra_entries_*`` accumulators count residue-routed entries
(the reference ships the same measurement methodology,
laboratory/benchmark/analyze.py). Raise ``a2a_capacity``/``a2a_slack`` if
your key distribution routinely needs more than one round.
"""

from __future__ import annotations

import math
from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..analysis.lint import host_fn
from ..ops import dedup
from ..utils import observability


def pin_wire(x: jnp.ndarray) -> jnp.ndarray:
    """Reinterpret a 16-bit float wire payload as uint16 bits.

    The compressed planes' promise is a BYTE property of the compiled
    collectives. A plain ``astype`` pair around the exchange is
    value-correct but not byte-stable: XLA's algebraic simplifier
    commutes converts across data-movement ops (and drops
    optimization_barrier on some backends), happily shipping f32 with a
    fused bf16 round-trip in front — same numbers, double the bytes,
    and the byte-halving contract fails. A bitcast is not a convert:
    the simplifier cannot move it across the collective, so the wire
    buffer is uint16 in the compiled program on every backend.
    """
    return lax.bitcast_convert_type(x, jnp.uint16)


def unpin_wire(x: jnp.ndarray, dtype) -> jnp.ndarray:
    """Inverse of :func:`pin_wire` (exact bit reinterpretation)."""
    return lax.bitcast_convert_type(x, dtype)


def record_stat(counter: str, local_value: jnp.ndarray,
                record: bool) -> None:
    """Gated host accumulation of routed-exchange statistics.

    ``record`` is the trace-time gate (callers thread
    ``observability.evaluate_performance()`` through their program-cache key
    so toggling it compiles the right program) — the same gate the reference
    puts on its pull_indices/pull_unique counters
    (EmbeddingPullOperator.cpp:208-209,244-248). Off by default: a host
    callback per step would stall TPU pipelining. The callback re-checks the
    gate at run time so a program traced with recording on goes quiet when
    the gate is turned off.
    """
    if record:
        # _cb runs on HOST via jax.debug.callback — the one sanctioned
        # escape hatch for counters (graftlint exempts callback
        # functions; the compiled-program audit sees the resulting
        # custom-call, which is why contracts are checked against the
        # default record-off programs)
        def _cb(d):
            if observability.evaluate_performance():
                observability.GLOBAL.add(counter, int(d))
        jax.debug.callback(_cb, local_value)


def record_float_stat(counter: str, local_value: jnp.ndarray,
                      record: bool) -> None:
    """:func:`record_stat` for float-valued quantization telemetry.

    Used by the int8_ef push path: ``quant_error_max`` (this device's
    largest absolute residual this step) and ``quant_residual_norm``
    (this device's residual L2 norm). The callback fires once per
    device shard, so the host accumulator SUMS locals across devices
    and steps — a cumulative drift series; the per-sample distribution
    additionally lands in the graftscope histogram registry, rendered
    on /metrics as an ``oe_quant_*`` series next to the counters.
    """
    if record:
        def _cb(d):
            if observability.evaluate_performance():
                v = float(d)
                observability.GLOBAL.add(counter, v)
                from ..analysis import scope
                scope.HISTOGRAMS.observe(counter, v)
        jax.debug.callback(_cb, local_value)


def linear_shard_id(axes: Sequence[str], sizes: Sequence[int]) -> jnp.ndarray:
    """This device's shard ordinal, row-major over ``axes`` (static sizes).

    Matches the block order of ``PartitionSpec((*axes,))`` on dim 0: the
    device at mesh position (i0, i1, ...) owns block i0*s1*... + i1*... .
    """
    idx = jnp.zeros((), jnp.int32)
    for ax, size in zip(axes, sizes):
        idx = idx * size + lax.axis_index(ax)
    return idx


def bucket_capacity(slice_size: int, num_shards: int,
                    capacity: int = 0, slack: float = 2.0) -> int:
    """Per-destination bucket size: explicit, or mean*slack with a floor.

    Slices of <= 32 entries (tests, serving probes) get ``capacity ==
    slice_size`` and finish in one round regardless of key skew. Larger
    slices rely on owner uniformity: binomial concentration makes ``2 *
    mean`` single-round for uniform owners (hashed keys, or sequential ids
    under the "mod" layout). *Structured* skew — e.g. ids all congruent
    modulo the shard count — overflows the first round, which only costs
    extra residue rounds (the exchange is exact either way). Monitor with
    :func:`routing_overflow` or the gated ``a2a_extra_entries_*``
    accumulators, and raise ``a2a_capacity``/``a2a_slack`` (up to
    ``slice_size`` = always one round) if your keys defeat the layout.
    """
    if capacity:
        return min(capacity, slice_size)
    mean = math.ceil(slice_size / num_shards)
    c = max(32, math.ceil(mean * slack))
    c = min(slice_size, -(-c // 8) * 8)
    return max(c, 1)


def bucketize(owner: jnp.ndarray, num_shards: int, capacity: int
              ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Assign each entry a flat send-buffer slot ``owner * capacity + pos``.

    ``owner`` is [m] with values in [0, num_shards) or >= num_shards for
    entries that must not be sent. Returns ``(dest [m], ok [m])``: ``dest``
    is the flat slot (== num_shards * capacity, i.e. out of range, when not
    sent this round), ``ok`` marks entries that made it into a bucket.
    Equivalent of the reference's per-shard request assembly
    (EmbeddingPullOperator.cpp:73-112) under XLA's static shapes: stable
    sort by owner, rank within group; past-capacity ranks stay pending for
    the caller's residue loop.
    """
    m = owner.shape[0]
    owner = owner.astype(jnp.int32)
    clamped = jnp.minimum(owner, num_shards)
    order = jnp.argsort(clamped, stable=True)
    sorted_owner = clamped[order]
    counts = jnp.zeros((num_shards + 1,), jnp.int32).at[clamped].add(1)
    starts = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts)[:-1]])
    pos_sorted = jnp.arange(m, dtype=jnp.int32) - starts[sorted_owner]
    pos = jnp.zeros((m,), jnp.int32).at[order].set(pos_sorted)
    ok = (owner < num_shards) & (pos < capacity)
    dest = jnp.where(ok, owner * capacity + pos, num_shards * capacity)
    return dest, ok


def fill_buckets(values: jnp.ndarray, dest: jnp.ndarray, num_shards: int,
                 capacity: int, fill) -> jnp.ndarray:
    """Scatter [m, ...] values into a [num_shards, capacity, ...] send buffer."""
    out = jnp.full((num_shards * capacity,) + values.shape[1:], fill,
                   dtype=values.dtype)
    out = out.at[dest].set(values, mode="drop")
    return out.reshape((num_shards, capacity) + values.shape[1:])


def grid_all_to_all(x: jnp.ndarray, axes: Sequence[str],
                    sizes: Sequence[int]) -> jnp.ndarray:
    """All-to-all over the product of mesh ``axes``.

    ``x`` is [N, ...] of per-destination blocks in row-major linear-shard
    order (N = prod(sizes)); the result is [N, ...] where row j is the block
    peer j destined for this device. Decomposed into one ``lax.all_to_all``
    per axis (a grid transpose): after routing over axis k, block (j0..jk..)
    holds data from the peer matching on later axes — the composition routes
    every block to exactly its (j0, ..., jn) owner.
    """
    n = x.shape[0]
    shape = tuple(sizes) + x.shape[1:]
    y = x.reshape(shape)
    for k, (ax, size) in enumerate(zip(axes, sizes)):
        if size > 1:
            y = lax.all_to_all(y, ax, split_axis=k, concat_axis=k)
    return y.reshape((n,) + x.shape[1:])


def grid_info(mesh, shard_axes: Sequence[str], model_axis: str,
              batch_sharded: bool):
    """(grid_axes, grid_sizes, split_axes, split_sizes) for one exchange.

    The batch is divided among the mesh axes it is *replicated* over (the
    model axis when batch-sharded over data; the whole shard grid when fully
    replicated), and routed to owners over all table shard axes.
    """
    grid_axes = tuple(shard_axes)
    grid_sizes = tuple(mesh.shape[a] for a in grid_axes)
    split_axes = (model_axis,) if batch_sharded else grid_axes
    split_sizes = tuple(mesh.shape[a] for a in split_axes)
    return grid_axes, grid_sizes, split_axes, split_sizes


def split_slice(flat: jnp.ndarray, num_parts: int, my_part: jnp.ndarray,
                fill) -> Tuple[jnp.ndarray, int]:
    """Pad ``flat`` [n] (or [n, kc] wide keys) to a multiple of
    ``num_parts`` and take slice ``my_part`` of size m = ceil(n /
    num_parts). Returns (slice, m)."""
    n = flat.shape[0]
    m = -(-n // num_parts)
    padded = jnp.full((m * num_parts,) + flat.shape[1:], fill,
                      dtype=flat.dtype)
    padded = padded.at[:n].set(flat)
    start = (my_part * m).astype(jnp.int32)
    starts = (start,) + (jnp.zeros((), jnp.int32),) * (flat.ndim - 1)
    return lax.dynamic_slice(padded, starts, (m,) + flat.shape[1:]), m


def split_slice_rows(rows: jnp.ndarray, num_parts: int, my_part: jnp.ndarray
                     ) -> jnp.ndarray:
    """Row variant of :func:`split_slice` (zero padding)."""
    n = rows.shape[0]
    m = -(-n // num_parts)
    padded = jnp.zeros((m * num_parts,) + rows.shape[1:], rows.dtype)
    padded = padded.at[:n].set(rows)
    start = (my_part * m).astype(jnp.int32)
    starts = (start,) + (jnp.zeros((), jnp.int32),) * (rows.ndim - 1)
    return lax.dynamic_slice(padded, starts, (m,) + rows.shape[1:])


def segment_offsets(sizes: Sequence[int]) -> Tuple[int, ...]:
    """Static exclusive prefix sums over per-segment entry counts.

    The grouped exchange concatenates several tables' key streams into one
    routed stream; these offsets carve each table's slice back out of the
    concatenated result (all sizes are trace-time constants, so the carves
    are static slices, not dynamic ops).
    """
    out = [0]
    for s in sizes:
        out.append(out[-1] + int(s))
    return tuple(out)


def carve_segments(rows: jnp.ndarray, sizes: Sequence[int]) -> list:
    """Split ``rows`` [sum(sizes), ...] back into per-segment blocks."""
    offs = segment_offsets(sizes)
    return [rows[offs[i]:offs[i + 1]] for i in range(len(sizes))]


def exchange_pull(flat_idx: jnp.ndarray,
                  resolve_fn: Callable[[jnp.ndarray], jnp.ndarray],
                  owner_fn: Callable[[jnp.ndarray], jnp.ndarray],
                  *,
                  sentinel,
                  dim: int,
                  num_shards: int,
                  grid_axes: Sequence[str],
                  grid_sizes: Sequence[int],
                  split_axes: Sequence[str],
                  split_sizes: Sequence[int],
                  capacity: int = 0,
                  slack: float = 2.0,
                  record_stats: bool = False,
                  wire_dtype=None) -> jnp.ndarray:
    """Owner-routed lookup of ``flat_idx`` [n] -> rows [n, dim]. EXACT.

    ``flat_idx`` must be identical on all ``split_axes`` peers (they divide
    the work); ``resolve_fn(keys [K]) -> [K, dim]`` runs on the owner and
    must return zero rows for keys it does not own (sentinel included).
    ``owner_fn(keys)`` maps keys to shard ordinals (>= num_shards = do not
    send). The result is replicated over ``split_axes`` again (all_gather).
    WIDE keys ride as [n, 2] int32 (lo, hi) pairs (x64-off 64-bit space);
    a pair is padding iff its hi word equals ``sentinel``. Composite keys
    generalize this to [n, K] rows (the grouped plane's table-tagged
    streams, ``parallel/grouped.py``): padding rows carry ``sentinel`` in
    every column and ``resolve_fn``/``owner_fn`` see the full K columns.

    Round 1 routes everything that fits the fixed-capacity buckets; any
    residue (structured key skew) loops through further rounds until the
    globally psum'd pending count is zero, so no key distribution can drop
    entries — parity with the reference's variable-size exchange
    (EmbeddingPullOperator.cpp:60-112).

    ``wire_dtype`` (``parallel/precision.py``): rows cross the response
    all-to-all AND the row-assembly all-gather in this dtype (bf16 =
    half the exchange bytes) and are upcast to the resolver's dtype
    after the last collective. Exactness caveat: each pulled row then
    carries ONE round-to-nearest cast (the residue accumulator fills
    every entry exactly once, so rounds never compound the error).
    ``None`` leaves the program byte-identical to the uncompressed one.
    """
    my_part = linear_shard_id(split_axes, split_sizes)
    n = flat_idx.shape[0]
    wide = flat_idx.ndim == 2
    kw = flat_idx.shape[1] if wide else 1  # key words per entry
    sl, m = split_slice(flat_idx, math.prod(split_sizes), my_part, sentinel)
    if wide:
        uniq, inverse, _valid = dedup.unique_rows(sl, m,
                                                  fill_value=sentinel)
    else:
        uniq, inverse, _valid = dedup.unique_indices(sl, m,
                                                     fill_value=sentinel)
    cap = bucket_capacity(m, num_shards, capacity, slack)
    owners = owner_fn(uniq)
    out_dtype = jax.eval_shape(resolve_fn, uniq).dtype
    acc_dtype = out_dtype if wire_dtype is None else jnp.dtype(wire_dtype)

    def one_round(pending, acc):
        dest, ok = bucketize(pending, num_shards, cap)
        send = fill_buckets(uniq, dest, num_shards, cap, sentinel)
        req = grid_all_to_all(send, grid_axes, grid_sizes)
        rows = resolve_fn(req.reshape((-1, kw)) if wide else req.ravel())
        if wire_dtype is not None:
            # the ONE lossy point of a compressed pull: owner-resolved
            # rows narrow to the wire dtype before the response leg,
            # bit-pinned to uint16 so the compiled collective really
            # carries 2-byte buffers (see pin_wire)
            rows = pin_wire(rows.astype(acc_dtype))
        resp = grid_all_to_all(rows.reshape((num_shards, cap, dim)),
                               grid_axes, grid_sizes)
        if wire_dtype is not None:
            resp = unpin_wire(resp, acc_dtype)
        flat_resp = resp.reshape((num_shards * cap, dim))
        got = jnp.take(flat_resp, jnp.where(ok, dest, 0), axis=0)
        acc = acc + jnp.where(ok[:, None], got, jnp.zeros_like(got))
        pending = jnp.where(ok, jnp.int32(num_shards), pending)
        left = lax.psum(jnp.sum(pending < num_shards).astype(jnp.int32),
                        tuple(grid_axes))
        return pending, acc, left

    pending0 = owners.astype(jnp.int32)
    acc0 = jnp.zeros((m, dim), dtype=acc_dtype)
    pending, uniq_rows, left = one_round(pending0, acc0)
    # record the per-device residue: the callback fires on every device
    # shard, so the host accumulator sums locals into the global total
    record_stat("a2a_extra_entries_pull",
                 jnp.sum(pending < num_shards).astype(jnp.int32),
                 record_stats)
    if cap < m:
        # residue loop: only reachable when round 1 could overflow
        pending, uniq_rows, _ = lax.while_loop(
            lambda c: c[2] > 0,
            lambda c: one_round(c[0], c[1]),
            (pending, uniq_rows, left))
    slice_rows = jnp.take(uniq_rows, inverse, axis=0)
    if wire_dtype is not None:
        # the row-assembly gather ships the pinned 16-bit wire form too;
        # the upcast after it is exact (bf16 -> f32 loses nothing)
        out = lax.all_gather(pin_wire(slice_rows), tuple(split_axes),
                             tiled=True)
        return unpin_wire(out[:n], acc_dtype).astype(out_dtype)
    out = lax.all_gather(slice_rows, tuple(split_axes), tiled=True)
    return out[:n]


def exchange_push(flat_idx: jnp.ndarray,
                  grads: jnp.ndarray,
                  state,
                  apply_fn: Callable,
                  owner_fn: Callable[[jnp.ndarray], jnp.ndarray],
                  *,
                  sentinel,
                  num_shards: int,
                  grid_axes: Sequence[str],
                  grid_sizes: Sequence[int],
                  split_axes: Sequence[str],
                  split_sizes: Sequence[int],
                  capacity: int = 0,
                  slack: float = 2.0,
                  record_stats: bool = False,
                  wire_dtype=None,
                  ef_state=None):
    """Owner-routed push: pre-reduce, route (key, grad sum, count) to owners.
    EXACT for any key distribution.

    ``apply_fn(state, keys [K], grads [K, dim], counts [K]) -> state`` runs
    on the owner with the merged per-peer pre-reduces and returns the updated
    local state (a pytree with stable structure/shapes/dtypes — it is
    threaded through ``lax.cond``). Entries with a sentinel key are padding
    and must be ignored by ``apply_fn`` (both built-in appliers drop them via
    the invalid-key contract; their count values are garbage by design).

    Unlike the pull (idempotent reads, residue rounds compose), a push must
    apply each key's optimizer update EXACTLY ONCE per step with all peer
    contributions merged — splitting a key across two apply calls is wrong
    for nonlinear optimizers (adam's moments would see two half-batches).
    So overflow is detected globally *before* anything is applied, and the
    program conditions on it:

    * no overflow (the overwhelmingly common case — capacity heuristics are
      sized for it): one routed fixed-capacity exchange, owner merges the
      per-peer (sum, count) pre-reduces via ``in_counts``;
    * overflow (structured key skew): fall back to an all_gather of every
      peer's pre-reduced slice over the grid — the psum-plane push, paid
      only when the routed plane can't hold the batch — so the owner still
      sees each key exactly once with all contributions.

    Both branches are exact; the reference gets the same guarantee from
    variable-size RPCs + server-side MpscGradientReducer
    (EmbeddingPushOperator.cpp:29-104). Note for appliers that dedup with a
    bounded capacity: the OWNED-UNIQUE count an applier sees is identical
    in both branches (each peer slice contributes a key at most once either
    way — the gathered batch is longer but not more unique), so capacity
    sizing is branch-independent. Keys and counts share one integer
    exchange buffer ([.., 2] channels) so a routed push costs two
    collectives per mesh axis, not three.

    Compressed wires (``parallel/precision.py``):

    * ``wire_dtype`` (bf16): the pre-reduced gradient rows cross the
      exchange (or the overflow all_gather) narrowed, upcast before the
      owner's f32 optimizer math — keys/counts stay int32.
    * ``ef_state = (prev_keys, prev_resid)``: int8 error-feedback push.
      Each sender adds the residual it stored for keys it also
      pre-reduced LAST step, quantizes the total per row (max-abs/127
      scale, int8 payload; the f32 scale rides the integer key/count
      buffer bitcast into one extra channel), and keeps the new
      quantization error for next step. Returns ``(result, (keys,
      resid))`` instead of ``result`` — both computed before the
      overflow branch, so feedback is branch-independent. Padding rows'
      scales are garbage on the routed wire (single-fill buffer);
      owners zero them by key validity so no NaN can reach an applier.
    """
    dim = grads.shape[-1]
    my_part = linear_shard_id(split_axes, split_sizes)
    parts = math.prod(split_sizes)
    wide = flat_idx.ndim == 2
    sl, m = split_slice(flat_idx, parts, my_part, sentinel)
    g2 = split_slice_rows(grads.reshape((-1, dim)), parts, my_part)
    if wide:
        uniq, inverse, _valid = dedup.unique_rows(sl, m,
                                                  fill_value=sentinel)
    else:
        uniq, inverse, _valid = dedup.unique_indices(sl, m,
                                                     fill_value=sentinel)
    summed, counts = dedup.combine_gradients(g2, inverse, m)
    cap = bucket_capacity(m, num_shards, capacity, slack)
    owners = owner_fn(uniq)
    dest, ok = bucketize(owners, num_shards, cap)
    kw = flat_idx.shape[1] if wide else 1  # key words per exchange entry

    quant = ef_state is not None
    new_ef = q8 = scale = None
    if quant:
        valid = (uniq[:, -1] != sentinel) if wide else (uniq != sentinel)
        summed, q8, scale, new_ef = _quantize_ef(
            uniq, summed, valid, ef_state, record_stats)

    def _key_valid(k):
        return (k[:, -1] != sentinel) if wide else (k != sentinel)

    def routed(st):
        ku = uniq if wide else uniq[:, None]
        cols = [ku, counts.astype(ku.dtype)[:, None]]
        if quant:
            # f32 scale bits ride the integer buffer as one extra channel
            cols.append(lax.bitcast_convert_type(
                scale, jnp.int32).astype(ku.dtype)[:, None])
        kc = jnp.concatenate(cols, axis=1)       # [m, kw+1(+1)]
        payload = q8 if quant else (
            summed if wire_dtype is None
            else pin_wire(summed.astype(wire_dtype)))
        send_kc = fill_buckets(kc, dest, num_shards, cap, sentinel)
        send_g = fill_buckets(payload, dest, num_shards, cap, 0)
        rkc = grid_all_to_all(send_kc, grid_axes, grid_sizes)
        rg = grid_all_to_all(send_g, grid_axes, grid_sizes)
        flat_kc = rkc.reshape((-1, kc.shape[1]))
        k = flat_kc[:, :kw] if wide else flat_kc[:, 0]
        rc = flat_kc[:, kw].astype(jnp.int32)
        g = rg.reshape((flat_kc.shape[0], dim))
        if quant:
            # padding slots carry the single fill value in the scale
            # channel — zero them by key validity (a garbage bitcast
            # could be NaN, and 0 * NaN contaminates)
            rscale = lax.bitcast_convert_type(
                flat_kc[:, kw + 1].astype(jnp.int32), jnp.float32)
            rscale = jnp.where(_key_valid(k), rscale, 0.0)
            g = g.astype(summed.dtype) * rscale[:, None]
        elif wire_dtype is not None:
            g = unpin_wire(g, wire_dtype).astype(summed.dtype)
        return apply_fn(st, k, g, rc)

    def gathered(st):
        ga = tuple(grid_axes)
        k = lax.all_gather(uniq, ga, tiled=True)  # [P*m] or [P*m, 2]
        c = lax.all_gather(counts, ga, tiled=True)
        if quant:
            gq = lax.all_gather(q8, ga, tiled=True)
            gs = lax.all_gather(scale, ga, tiled=True)
            g = gq.astype(summed.dtype) * gs[:, None]
        elif wire_dtype is not None:
            narrowed = pin_wire(summed.astype(wire_dtype))
            g = unpin_wire(lax.all_gather(narrowed, ga, tiled=True),
                           wire_dtype).astype(summed.dtype)
        else:
            g = lax.all_gather(summed, ga, tiled=True)
        return apply_fn(st, k, g, c)

    if cap >= m:
        # buckets can hold the whole slice: bucketize cannot overflow
        out = routed(state)
        return (out, new_ef) if quant else out
    local_spill = jnp.sum((owners < num_shards) & ~ok).astype(jnp.int32)
    spilled = lax.psum(local_spill, tuple(grid_axes))
    # per-device residue: the callback fires on every device shard, so the
    # host accumulator sums locals into the global total
    record_stat("a2a_extra_entries_push", local_spill, record_stats)
    out = lax.cond(spilled == 0, routed, gathered, state)
    return (out, new_ef) if quant else out


def _match_prev_keys(uniq, pk):
    """(candidate index into pk, exact-equality flag) per current key.

    Narrow keys: sort the previous step's keys once, binary-search each
    current key, verify exactly. Wide ``[m, 2]`` pair keys: sort by a
    32-bit multiplicative mix of (lo, hi) and verify BOTH words exactly
    — a mix collision between two previous keys can hide (never corrupt)
    one residual. O(m log m) compute, O(m) memory.
    """
    wide = uniq.ndim == 2

    def _mix(k):
        lo = k[:, 0].astype(jnp.uint32) * jnp.uint32(0x85EBCA6B)
        hi = k[:, 1].astype(jnp.uint32) * jnp.uint32(0xC2B2AE35)
        return (lo ^ hi).astype(jnp.int32)

    cur = _mix(uniq) if wide else uniq
    prev = _mix(pk) if wide else pk
    order = jnp.argsort(prev)
    pos = jnp.searchsorted(prev[order], cur)
    cand = order[jnp.clip(pos, 0, pk.shape[0] - 1)]
    hit_rows = jnp.take(pk, cand, axis=0)
    if wide:
        eq = jnp.all(hit_rows == uniq, axis=-1)
    else:
        eq = hit_rows == uniq
    return cand, eq


def _quantize_ef(uniq, summed, valid, ef_state, record_stats: bool):
    """int8 error-feedback quantization of one sender's pre-reduced rows.

    ``ef_state = (prev_keys, prev_resid)``: the PREVIOUS step's unique
    keys and quantization errors of THIS sender (positional — see
    ``precision.EFState``). Returns ``(summed_ef, q8, scale, (keys,
    resid))``: the residual-carried totals, their int8 payload, the
    per-row f32 scales, and the new residual to thread forward. Both
    wire branches dequantize ``q8 * scale``, so the stored residual is
    exactly the error the owner will see — recirculated next step.
    """
    pk, pr = ef_state
    total = summed
    if pk.shape[0]:
        # sort-based matching, O(m log m): a broadcast m x m0 equality
        # would cost O(m^2) compare/memory — 1.8e8 bools at the fused
        # deepfm stream size. Wide (pair) keys match on a 32-bit mix
        # with exact verification; a prev-side mix collision can at
        # worst hide one residual for one step (forfeited, not
        # corrupted — the verify is exact)
        cand, eq = _match_prev_keys(uniq, pk)
        # sentinel rows may "match" sentinel padding in pk — harmless
        # (padding residual is stored as exact zero), but gate on the
        # current row's validity anyway so padding stays all-zero
        hit = eq & valid
        carry = jnp.where(hit[:, None], jnp.take(pr, cand, axis=0), 0.0)
        total = summed + carry.astype(summed.dtype)
    absmax = jnp.max(jnp.abs(total.astype(jnp.float32)), axis=1)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0).astype(jnp.float32)
    q8 = jnp.clip(jnp.round(total.astype(jnp.float32) / scale[:, None]),
                  -127, 127).astype(jnp.int8)
    deq = q8.astype(jnp.float32) * scale[:, None]
    resid = jnp.where(valid[:, None],
                      total.astype(jnp.float32) - deq, 0.0)
    record_float_stat("quant_error_max", jnp.max(jnp.abs(resid)),
                      record_stats)
    record_float_stat("quant_residual_norm",
                      jnp.sqrt(jnp.sum(resid * resid)), record_stats)
    return total, q8, scale, (uniq, resid)


@host_fn
def routing_overflow(indices, num_shards: int, slice_parts: int,
                     owner_of, capacity: int = 0, slack: float = 2.0) -> int:
    """Host-side diagnostic: how many uniques spill past round 1's buckets?

    Sizes the bucket capacity for a sample batch the way the exchange does
    (dedup per slice, bucket by owner) and counts past-capacity uniques —
    the reference measures batch key-overlap the same way before sizing its
    dedup structures (laboratory/benchmark/analyze.py). 0 means the exchange
    finishes in one round for this batch shape + key distribution; a nonzero
    count is re-routed by the residue loop (extra rounds, never data loss).
    """
    import numpy as np
    flat = np.asarray(indices).ravel()
    n = flat.shape[0]
    m = -(-n // slice_parts)
    cap = bucket_capacity(m, num_shards, capacity, slack)
    dropped = 0
    for p in range(slice_parts):
        sl = flat[p * m:(p + 1) * m]
        uniq = np.unique(sl)
        owners = np.asarray(owner_of(uniq))
        keep = owners < num_shards
        counts = np.bincount(owners[keep], minlength=num_shards)
        dropped += int(np.maximum(counts - cap, 0).sum())
    return dropped
