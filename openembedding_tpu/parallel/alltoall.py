"""Owner-routed all-to-all exchange: the scale-grade sparse data plane.

The reference's pull/push pipeline is an *owner exchange*: dedup client-side,
partition keys by owning shard, send each shard only its own requests, scatter
the per-shard responses back
(/root/reference/openembedding/server/EmbeddingPullOperator.cpp:60-112,207-252,
EmbeddingPushOperator.cpp:29-104). The first TPU data plane here (the "psum"
plane in ``sharded_table``/``sharded_hash``) replaced that with gather + psum
(pull) and all_gather + masked local update (push) — simple and correct, but
its ICI traffic scales with *mesh size*, not with owned rows: the push
all_gathers the full global batch to every device.

This module is the owner exchange done TPU-natively, inside one shard_map
program ("a2a" plane):

* tables are sharded over the **whole mesh** (data x model axes = N shards),
  so HBM capacity scales with every chip and there are no table replicas to
  keep in sync;
* each device handles a distinct slice of the batch (the model-axis peers of
  a data slice split their common copy), dedups it, buckets the unique keys
  by owner shard into fixed-capacity blocks, and a grid all-to-all routes
  each block to its owner — indices out, rows (pull) or pre-reduced
  (grad, count) pairs (push) back;
* the owner resolves rows locally (array index math or hash probe) and, on
  push, merges the per-peer pre-reduces exactly like the reference's
  server-side MpscGradientReducer (counts are summed, not recounted).

Per-device ICI bytes per step are O(slack * batch_slice * dim) instead of
O(global_batch * dim) — the gap to the reference's per-owner exchange closed.

Static shapes: the per-destination bucket capacity must be fixed at trace
time. Keys are uniform across owners by construction ("mod" layout spreads
sequential ids; hash keys are avalanche-mixed), so the default capacity
``max(32, 2 * mean_bucket)`` overflows with vanishing probability; overflowed
entries are dropped (zero rows on pull, skipped updates on push) — measure
with :func:`routing_overflow` (the reference ships the same measurement
methodology, laboratory/benchmark/analyze.py) and raise
``a2a_capacity``/``a2a_slack`` if your key distribution defeats the layout.
"""

from __future__ import annotations

import math
from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops import dedup
from ..utils import observability


def _record_drops(counter: str, local_dropped: jnp.ndarray,
                  record: bool) -> None:
    """Gated host accumulation of routed-exchange drops.

    ``record`` is the trace-time gate (callers thread
    ``observability.evaluate_performance()`` through their program-cache key
    so toggling it compiles the right program) — the same gate the reference
    puts on its pull_indices/pull_unique counters
    (EmbeddingPullOperator.cpp:208-209,244-248). Off by default: a host
    callback per step would stall TPU pipelining. The callback re-checks the
    gate at run time so a program traced with recording on goes quiet when
    the gate is turned off.
    """
    if record:
        def _cb(d):
            if observability.evaluate_performance():
                observability.GLOBAL.add(counter, int(d))
        jax.debug.callback(_cb, local_dropped)


def linear_shard_id(axes: Sequence[str], sizes: Sequence[int]) -> jnp.ndarray:
    """This device's shard ordinal, row-major over ``axes`` (static sizes).

    Matches the block order of ``PartitionSpec((*axes,))`` on dim 0: the
    device at mesh position (i0, i1, ...) owns block i0*s1*... + i1*... .
    """
    idx = jnp.zeros((), jnp.int32)
    for ax, size in zip(axes, sizes):
        idx = idx * size + lax.axis_index(ax)
    return idx


def bucket_capacity(slice_size: int, num_shards: int,
                    capacity: int = 0, slack: float = 2.0) -> int:
    """Per-destination bucket size: explicit, or mean*slack with a floor.

    Slices of <= 32 entries (tests, serving probes) get ``capacity ==
    slice_size`` and are exact regardless of key skew. Larger slices rely on
    owner uniformity: binomial concentration makes ``2 * mean`` overflow-free
    for uniform owners (hashed keys, or sequential ids under the "mod"
    layout), but *structured* skew — e.g. ids all congruent modulo the shard
    count — can overflow. Monitor with :func:`routing_overflow` or the gated
    ``a2a_dropped_*`` accumulators, and raise ``a2a_capacity``/``a2a_slack``
    (up to ``slice_size`` = always exact) if your keys defeat the layout.
    """
    if capacity:
        return min(capacity, slice_size)
    mean = math.ceil(slice_size / num_shards)
    c = max(32, math.ceil(mean * slack))
    c = min(slice_size, -(-c // 8) * 8)
    return max(c, 1)


def bucketize(owner: jnp.ndarray, num_shards: int, capacity: int
              ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Assign each entry a flat send-buffer slot ``owner * capacity + pos``.

    ``owner`` is [m] with values in [0, num_shards) or >= num_shards for
    entries that must not be sent. Returns ``(dest [m], ok [m])``: ``dest``
    is the flat slot (== num_shards * capacity, i.e. out of range, when
    dropped), ``ok`` marks entries that made it into a bucket. Equivalent of
    the reference's per-shard request assembly (EmbeddingPullOperator.cpp:
    73-112) under XLA's static shapes: stable sort by owner, rank within
    group, drop past-capacity ranks.
    """
    m = owner.shape[0]
    owner = owner.astype(jnp.int32)
    clamped = jnp.minimum(owner, num_shards)
    order = jnp.argsort(clamped, stable=True)
    sorted_owner = clamped[order]
    counts = jnp.zeros((num_shards + 1,), jnp.int32).at[clamped].add(1)
    starts = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts)[:-1]])
    pos_sorted = jnp.arange(m, dtype=jnp.int32) - starts[sorted_owner]
    pos = jnp.zeros((m,), jnp.int32).at[order].set(pos_sorted)
    ok = (owner < num_shards) & (pos < capacity)
    dest = jnp.where(ok, owner * capacity + pos, num_shards * capacity)
    return dest, ok


def fill_buckets(values: jnp.ndarray, dest: jnp.ndarray, num_shards: int,
                 capacity: int, fill) -> jnp.ndarray:
    """Scatter [m, ...] values into a [num_shards, capacity, ...] send buffer."""
    out = jnp.full((num_shards * capacity,) + values.shape[1:], fill,
                   dtype=values.dtype)
    out = out.at[dest].set(values, mode="drop")
    return out.reshape((num_shards, capacity) + values.shape[1:])


def grid_all_to_all(x: jnp.ndarray, axes: Sequence[str],
                    sizes: Sequence[int]) -> jnp.ndarray:
    """All-to-all over the product of mesh ``axes``.

    ``x`` is [N, ...] of per-destination blocks in row-major linear-shard
    order (N = prod(sizes)); the result is [N, ...] where row j is the block
    peer j destined for this device. Decomposed into one ``lax.all_to_all``
    per axis (a grid transpose): after routing over axis k, block (j0..jk..)
    holds data from the peer matching on later axes — the composition routes
    every block to exactly its (j0, ..., jn) owner.
    """
    n = x.shape[0]
    shape = tuple(sizes) + x.shape[1:]
    y = x.reshape(shape)
    for k, (ax, size) in enumerate(zip(axes, sizes)):
        if size > 1:
            y = lax.all_to_all(y, ax, split_axis=k, concat_axis=k)
    return y.reshape((n,) + x.shape[1:])


def grid_info(mesh, shard_axes: Sequence[str], model_axis: str,
              batch_sharded: bool):
    """(grid_axes, grid_sizes, split_axes, split_sizes) for one exchange.

    The batch is divided among the mesh axes it is *replicated* over (the
    model axis when batch-sharded over data; the whole shard grid when fully
    replicated), and routed to owners over all table shard axes.
    """
    grid_axes = tuple(shard_axes)
    grid_sizes = tuple(mesh.shape[a] for a in grid_axes)
    split_axes = (model_axis,) if batch_sharded else grid_axes
    split_sizes = tuple(mesh.shape[a] for a in split_axes)
    return grid_axes, grid_sizes, split_axes, split_sizes


def split_slice(flat: jnp.ndarray, num_parts: int, my_part: jnp.ndarray,
                fill) -> Tuple[jnp.ndarray, int]:
    """Pad ``flat`` [n] to a multiple of ``num_parts`` and take slice
    ``my_part`` of size m = ceil(n / num_parts). Returns (slice, m)."""
    n = flat.shape[0]
    m = -(-n // num_parts)
    padded = jnp.full((m * num_parts,), fill, dtype=flat.dtype)
    padded = padded.at[:n].set(flat)
    start = (my_part * m).astype(jnp.int32)
    return lax.dynamic_slice(padded, (start,), (m,)), m


def split_slice_rows(rows: jnp.ndarray, num_parts: int, my_part: jnp.ndarray
                     ) -> jnp.ndarray:
    """Row variant of :func:`split_slice` (zero padding)."""
    n = rows.shape[0]
    m = -(-n // num_parts)
    padded = jnp.zeros((m * num_parts,) + rows.shape[1:], rows.dtype)
    padded = padded.at[:n].set(rows)
    start = (my_part * m).astype(jnp.int32)
    starts = (start,) + (jnp.zeros((), jnp.int32),) * (rows.ndim - 1)
    return lax.dynamic_slice(padded, starts, (m,) + rows.shape[1:])


def exchange_pull(flat_idx: jnp.ndarray,
                  resolve_fn: Callable[[jnp.ndarray], jnp.ndarray],
                  owner_fn: Callable[[jnp.ndarray], jnp.ndarray],
                  *,
                  sentinel,
                  dim: int,
                  num_shards: int,
                  grid_axes: Sequence[str],
                  grid_sizes: Sequence[int],
                  split_axes: Sequence[str],
                  split_sizes: Sequence[int],
                  capacity: int = 0,
                  slack: float = 2.0,
                  record_drops: bool = False) -> jnp.ndarray:
    """Owner-routed lookup of ``flat_idx`` [n] -> rows [n, dim].

    ``flat_idx`` must be identical on all ``split_axes`` peers (they divide
    the work); ``resolve_fn(keys [K]) -> [K, dim]`` runs on the owner and
    must return zero rows for keys it does not own (sentinel included).
    ``owner_fn(keys)`` maps keys to shard ordinals (>= num_shards = do not
    send). The result is replicated over ``split_axes`` again (all_gather).
    """
    my_part = linear_shard_id(split_axes, split_sizes)
    n = flat_idx.shape[0]
    sl, m = split_slice(flat_idx, math.prod(split_sizes), my_part, sentinel)
    uniq, inverse, _valid = dedup.unique_indices(sl, m, fill_value=sentinel)
    cap = bucket_capacity(m, num_shards, capacity, slack)
    owners = owner_fn(uniq)
    dest, ok = bucketize(owners, num_shards, cap)
    _record_drops("a2a_dropped_pull",
                  jnp.sum((owners < num_shards) & ~ok).astype(jnp.int32),
                  record_drops)
    send = fill_buckets(uniq, dest, num_shards, cap, sentinel)
    req = grid_all_to_all(send, grid_axes, grid_sizes)
    rows = resolve_fn(req.ravel())
    resp = grid_all_to_all(rows.reshape((num_shards, cap, dim)),
                           grid_axes, grid_sizes)
    flat_resp = resp.reshape((num_shards * cap, dim))
    uniq_rows = jnp.take(flat_resp, jnp.where(ok, dest, 0), axis=0)
    uniq_rows = jnp.where(ok[:, None], uniq_rows, jnp.zeros_like(uniq_rows))
    slice_rows = jnp.take(uniq_rows, inverse, axis=0)
    out = lax.all_gather(slice_rows, tuple(split_axes), tiled=True)
    return out[:n]


def exchange_push(flat_idx: jnp.ndarray,
                  grads: jnp.ndarray,
                  apply_fn: Callable[[jnp.ndarray, jnp.ndarray, jnp.ndarray],
                                     None],
                  owner_fn: Callable[[jnp.ndarray], jnp.ndarray],
                  *,
                  sentinel,
                  num_shards: int,
                  grid_axes: Sequence[str],
                  grid_sizes: Sequence[int],
                  split_axes: Sequence[str],
                  split_sizes: Sequence[int],
                  capacity: int = 0,
                  slack: float = 2.0,
                  record_drops: bool = False):
    """Owner-routed push: pre-reduce, route (key, grad sum, count) to owners.

    ``apply_fn(keys [K], grads [K, dim], counts [K])`` runs on the owner with
    the merged per-peer pre-reduces and returns its updated local state
    (whatever pytree it likes). Entries with a sentinel key are padding and
    must be ignored by ``apply_fn`` (both built-in appliers drop them via the
    invalid-key contract; their count values are garbage by design).

    Keys and counts share one integer exchange buffer ([.., 2] channels) so
    a push costs two collectives per mesh axis, not three.
    """
    dim = grads.shape[-1]
    my_part = linear_shard_id(split_axes, split_sizes)
    parts = math.prod(split_sizes)
    sl, m = split_slice(flat_idx, parts, my_part, sentinel)
    g2 = split_slice_rows(grads.reshape((-1, dim)), parts, my_part)
    uniq, inverse, _valid = dedup.unique_indices(sl, m, fill_value=sentinel)
    summed, counts = dedup.combine_gradients(g2, inverse, m)
    cap = bucket_capacity(m, num_shards, capacity, slack)
    owners = owner_fn(uniq)
    dest, ok = bucketize(owners, num_shards, cap)
    _record_drops("a2a_dropped_push",
                  jnp.sum((owners < num_shards) & ~ok).astype(jnp.int32),
                  record_drops)
    kc = jnp.stack([uniq, counts.astype(uniq.dtype)], axis=1)  # [m, 2]
    send_kc = fill_buckets(kc, dest, num_shards, cap, sentinel)
    send_g = fill_buckets(summed, dest, num_shards, cap, 0)
    rkc = grid_all_to_all(send_kc, grid_axes, grid_sizes)
    rg = grid_all_to_all(send_g, grid_axes, grid_sizes)
    k = rkc[..., 0].ravel()
    rc = rkc[..., 1].ravel().astype(jnp.int32)
    return apply_fn(k, rg.reshape((k.shape[0], dim)), rc)


def routing_overflow(indices, num_shards: int, slice_parts: int,
                     owner_of, capacity: int = 0, slack: float = 2.0) -> int:
    """Host-side diagnostic: how many batch entries would the a2a plane drop?

    Sizes the bucket capacity for a sample batch the way the exchange does
    (dedup per slice, bucket by owner) and counts past-capacity uniques —
    the reference measures batch key-overlap the same way before sizing its
    dedup structures (laboratory/benchmark/analyze.py). 0 means the default
    capacity is exact for this batch shape + key distribution.
    """
    import numpy as np
    flat = np.asarray(indices).ravel()
    n = flat.shape[0]
    m = -(-n // slice_parts)
    cap = bucket_capacity(m, num_shards, capacity, slack)
    dropped = 0
    for p in range(slice_parts):
        sl = flat[p * m:(p + 1) * m]
        uniq = np.unique(sl)
        owners = np.asarray(owner_of(uniq))
        keep = owners < num_shards
        counts = np.bincount(owners[keep], minlength=num_shards)
        dropped += int(np.maximum(counts - cap, 0).sum())
    return dropped
