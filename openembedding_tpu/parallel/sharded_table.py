"""Vocabulary-sharded embedding tables over a device mesh.

TPU-native replacement for the reference's parameter-server data plane:

* The reference shards each variable's key space ``index % global_shard_num``
  across PS processes and pulls rows by RPC
  (/root/reference/openembedding/server/EmbeddingPullOperator.cpp:60-112,
  key stored as ``index / shard_num``). Here the same modulo layout shards
  rows across TPU devices along the mesh ``model`` axis, and the pull is a
  shard_map region: local gather of owned rows + ``psum`` over the model
  axis — XLA collectives over ICI instead of TCP/RDMA round trips.
* The push + store pipeline (client pre-reduce -> MpscGradientReducer ->
  EmbeddingStoreOperator commit, EmbeddingPushOperator.cpp:29-161,
  EmbeddingStoreOperator.cpp:23-81) becomes: ``all_gather`` of (indices,
  row-grads) over the data axis, then every model shard dedups/combines the
  global batch, masks ownership, and applies its rows' optimizer update
  locally — one fused XLA program, synchronous per step (the reference's
  fake-gradient batch barrier is unnecessary: the SPMD step IS the barrier).
* ``num_shards`` semantics: the reference's shard-per-server default
  (WorkerContext.cpp:66-85) corresponds to one shard per mesh model slice.

Layouts:
* ``mod``   (default, reference parity): global row r -> shard r % S, local
  row r // S. Robust to frequency-skewed sequential ids.
* ``div``   (block): r -> shard r // rows_per_shard. Matches NamedSharding's
  natural blocking; best when keys are pre-hashed (uniform).

Data planes (``ShardingSpec.plane``):
* ``"a2a"`` (default) — owner-routed all-to-all exchange (see
  ``parallel/alltoall.py``): tables sharded over the WHOLE mesh (data x
  model), per-device traffic O(batch_slice * dim). The reference's
  dedup->shard->request->scatter pipeline, TPU-native.
* ``"psum"`` — tables sharded over the model axis only (replicated across
  the data axis); pull = gather + psum, push = all_gather + masked local
  update. Simpler program, more ICI bytes and D-fold HBM replication; kept
  as the ablation baseline and for meshes where replicas are wanted.
* ``"a2a+cache"`` — the a2a layout plus a frequency-tracked top-K hot-row
  replica in every device's HBM (``parallel/hot_cache.py``): pulls for hot
  keys are served locally with no exchange round, pushes pre-reduce
  locally and merge with one psum over the K cached rows — exactly
  equivalent to ``"a2a"``, built for Zipfian key streams.
* ``"a2a+grouped"`` — the a2a layout, but the COLLECTION batches all
  same-shape tables into one exchange per group per step
  (``parallel/grouped.py``): a T-table model pays O(#groups) collective
  rounds instead of O(T). Per-table calls on this plane (serving probes,
  checkpoint paths) behave exactly like ``"a2a"``.
* ``"a2a+pipelined"`` — the a2a layout, but the TRAINER double-buffers
  the exchange (``parallel/pipelined.py``): batch N+1's rows are pulled
  inside step N's jitted program (after step N's push commits, so
  results stay bit-identical to ``"a2a"``) and the pull's index/key-leg
  collectives overlap step N's dense compute. Per-table calls behave
  exactly like ``"a2a"`` — the plane only changes the step schedule.
* ``"a2a+grouped+pipelined"`` — both: grouped collection-level exchange
  AND the pipelined step schedule, so the prefetched exchange is one
  collective round per GROUP.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from functools import partial
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..meta import EmbeddingVariableMeta
from ..ops import dedup
from ..utils import observability
from ..utils.jaxcompat import shard_map
from ..optim.initializers import make_initializer
from ..optim.optimizers import SparseOptimizer, make_optimizer
from .. import table as table_lib
from . import alltoall as a2a
from . import hot_cache
from . import precision
from .mesh import DATA_AXIS, MODEL_AXIS


# every plane riding the owner-routed exchange layout (tables sharded
# over the whole mesh grid); "psum" is the lone broadcast-style ablation
A2A_PLANES = ("a2a", "a2a+cache", "a2a+grouped", "a2a+pipelined",
              "a2a+grouped+pipelined")
PLANES = A2A_PLANES + ("psum",)


@dataclasses.dataclass(frozen=True)
class ShardingSpec:
    """Static description of how one table is laid out on the mesh."""

    num_shards: int
    rows_per_shard: int
    layout: str = "mod"  # "mod" | "div"
    data_axis: str = DATA_AXIS
    model_axis: str = MODEL_AXIS
    plane: str = "a2a"   # "a2a" | "psum" | "a2a+cache" | "a2a+grouped"
                         # | "a2a+pipelined" | "a2a+grouped+pipelined"
    a2a_capacity: int = 0    # per-destination bucket rows; 0 = auto
    a2a_slack: float = 2.0   # auto capacity = slack * mean bucket size
    cache_k: int = 0         # hot-row replica slots ("a2a+cache" plane)
    # compressed-exchange rungs (parallel/precision.py): pulled rows /
    # pushed pre-reduced grads on the wire; master weights + optimizer
    # slots stay at the table's storage dtype in the shard
    exchange_precision: str = "f32"   # "f32" | "bf16"
    push_precision: str = "f32"       # "f32" | "bf16" | "int8_ef"

    @property
    def is_cached(self) -> bool:
        return self.plane == "a2a+cache"

    @property
    def plane_label(self) -> str:
        """Observable plane token incl. the precision suffix — keys the
        HLO module names, plane_timed spans, contract registry and the
        graftscope byte ledger (``precision.plane_label``)."""
        return precision.plane_label(self.plane, self.exchange_precision,
                                     self.push_precision)

    @property
    def pull_wire_dtype(self):
        return precision.wire_dtype(self.exchange_precision)

    @property
    def push_wire_dtype(self):
        # int8_ef carries its own int8 payload inside exchange_push
        return precision.wire_dtype(self.push_precision) \
            if self.push_precision == "bf16" else None

    @property
    def is_int8_ef(self) -> bool:
        return self.push_precision == "int8_ef"

    @property
    def is_grouped(self) -> bool:
        """Collection-level multi-table exchange (``parallel/grouped.py``)."""
        return self.plane in ("a2a+grouped", "a2a+grouped+pipelined")

    @property
    def is_pipelined(self) -> bool:
        """Trainer-level double-buffered exchange schedule
        (``parallel/pipelined.py``)."""
        return self.plane in ("a2a+pipelined", "a2a+grouped+pipelined")

    @property
    def shard_axes(self) -> tuple:
        """Mesh axes the table's row dimension is sharded over."""
        if self.plane != "psum":
            return (self.data_axis, self.model_axis)
        return (self.model_axis,)

    def row_spec(self) -> P:
        return P(self.shard_axes)

    @property
    def padded_vocab(self) -> int:
        return self.num_shards * self.rows_per_shard

    def shard_and_local(self, idx: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        if self.layout == "mod":
            return idx % self.num_shards, idx // self.num_shards
        return idx // self.rows_per_shard, idx % self.rows_per_shard

    def global_row(self, shard: jnp.ndarray, local: jnp.ndarray) -> jnp.ndarray:
        if self.layout == "mod":
            return local * self.num_shards + shard
        return shard * self.rows_per_shard + local


def make_sharding_spec(meta: EmbeddingVariableMeta, mesh: Mesh,
                       num_shards: int = -1, layout: str = "mod",
                       capacity: Optional[int] = None,
                       plane: str = "a2a",
                       a2a_capacity: int = 0,
                       a2a_slack: float = 2.0,
                       cache_k: int = 0,
                       exchange_precision: str = "f32",
                       push_precision: str = "f32") -> ShardingSpec:
    """num_shards=-1 => one shard per device ("a2a") / per model slice ("psum").

    The reference's shard-per-server default (WorkerContext.cpp:66-85): on
    the a2a plane every chip is a "server", on the psum plane every model
    slice is one (its data-axis replicas mirror each other).

    ``plane="a2a+cache"`` is the a2a layout plus a ``cache_k``-row hot-row
    replica on every device (``parallel/hot_cache.py``); 0 picks the
    default size.

    A ``+bf16``/``+int8`` plane suffix (``parallel/precision.py``) is
    shorthand for the compressed-exchange rungs: it is parsed off the
    base plane into ``exchange_precision``/``push_precision``.
    """
    if layout not in ("mod", "div"):
        raise ValueError(f"unknown layout {layout!r}")
    plane, exchange_precision, push_precision = _resolve_precision(
        plane, exchange_precision, push_precision)
    if plane not in PLANES:
        raise ValueError(f"unknown plane {plane!r}")
    want = mesh.shape[MODEL_AXIS] if plane == "psum" else mesh.size
    if num_shards == -1:
        num_shards = want
    if num_shards != want:
        raise ValueError(
            f"num_shards={num_shards} must equal the {plane}-plane shard "
            f"count {want} for this mesh (or pass -1)")
    if plane == "a2a+cache" and cache_k <= 0:
        cache_k = hot_cache.DEFAULT_CACHE_K
    if plane != "a2a+cache":
        cache_k = 0
    vocab = capacity if capacity is not None else meta.vocabulary_size
    rows_per_shard = math.ceil(vocab / num_shards)
    return ShardingSpec(num_shards=num_shards, rows_per_shard=rows_per_shard,
                        layout=layout, plane=plane,
                        a2a_capacity=a2a_capacity, a2a_slack=a2a_slack,
                        cache_k=cache_k,
                        exchange_precision=exchange_precision,
                        push_precision=push_precision)


def _resolve_precision(plane: str, exchange_precision: str,
                       push_precision: str):
    """Fold a ``+bf16``/``+int8`` plane suffix into the precision fields
    and validate the combination (shared by array and hash spec
    builders)."""
    base, sep, spp = precision.parse_plane(plane)
    if (sep, spp) != ("f32", "f32"):
        for given, suffixed, knob in (
                (exchange_precision, sep, "exchange_precision"),
                (push_precision, spp, "push_precision")):
            if given not in ("f32", suffixed):
                raise ValueError(
                    f"plane {plane!r} implies {knob}={suffixed!r} but "
                    f"{given!r} was passed explicitly")
        exchange_precision, push_precision = sep, spp
    precision.check_spec_precision(base, exchange_precision,
                                   push_precision)
    return base, exchange_precision, push_precision


def create_sharded_table(meta: EmbeddingVariableMeta,
                         optimizer: Any,
                         initializer: Any = None,
                         *,
                         mesh: Mesh,
                         spec: Optional[ShardingSpec] = None,
                         rng: Optional[jax.Array] = None,
                         wrap_cache: bool = True):
    """Materialize a table sharded over the mesh model axis.

    Each device initializes only its own rows (PRNG folded with the shard
    index) — no host-side full-table materialization, so tables bounded only
    by aggregate HBM, like the reference's tables bounded by aggregate PS RAM.
    """
    optimizer = make_optimizer(optimizer)
    initializer = make_initializer(initializer or table_lib.DEFAULT_INITIALIZER)
    if spec is None:
        spec = make_sharding_spec(meta, mesh)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    dtype = table_lib.resolve_dtype(meta)
    dim = meta.embedding_dim

    axes = spec.shard_axes
    sizes = tuple(mesh.shape[a] for a in axes)

    def _init(key):
        s = a2a.linear_shard_id(axes, sizes)
        k = jax.random.fold_in(key, s)
        weights = initializer.init(k, (spec.rows_per_shard, dim), dtype)
        slots = optimizer.init_slots(spec.rows_per_shard, dim, dtype)
        return table_lib.TableState(weights=weights, slots=slots)

    fn = shard_map(_init, mesh=mesh,
                   in_specs=(P(),),
                   out_specs=table_state_specs(optimizer, dim, spec),
                   check_vma=False)
    state = jax.jit(fn)(rng)
    if wrap_cache:
        # all-pad replica: zero hits (pure-a2a behavior) until the first
        # admission refresh (hot_cache.HotCacheManager / build_cache).
        # ``wrap_cache=False`` returns the bare table (callers composing
        # their own jitted init wrap eagerly afterwards).
        return hot_cache.attach_empty(state, spec, mesh)
    return state


def table_state_specs(optimizer: SparseOptimizer, dim: int,
                      spec: ShardingSpec):
    row = spec.row_spec()
    slot_spec = {name: row for name in optimizer.slot_shapes(dim)}
    return table_lib.TableState(weights=row, slots=slot_spec)


def state_specs(optimizer: SparseOptimizer, dim: int, spec: ShardingSpec):
    table = table_state_specs(optimizer, dim, spec)
    if spec.is_cached:
        # the replica is replicated on every device
        return hot_cache.CachedState(
            table=table,
            cache=hot_cache.HotCacheState(
                keys=P(), rows=P(),
                slots={name: P() for name in table.slots}))
    return table


def state_shardings(state_specs, mesh: Mesh):
    return jax.tree.map(lambda p: NamedSharding(mesh, p), state_specs,
                        is_leaf=lambda x: isinstance(x, P))


@functools.lru_cache(maxsize=None)
def _filled_program(mesh: Mesh, spec: ShardingSpec, tail: tuple,
                    fill: float, dtype):
    row = spec.row_spec()
    shape = (spec.padded_vocab,) + tail
    return jax.jit(
        lambda: jnp.full(shape, fill, dtype=dtype),
        out_shardings=NamedSharding(mesh, row))


def filled_sharded(mesh: Mesh, spec: ShardingSpec, tail: tuple,
                   fill, dtype) -> jnp.ndarray:
    """A constant-filled [padded_vocab, *tail] array sharded per ``spec`` —
    the blank canvas the streaming checkpoint loader delivers rows onto."""
    return _filled_program(mesh, spec, tuple(tail), float(fill),
                           np.dtype(dtype).name)()


@functools.lru_cache(maxsize=None)
def _deliver_program(mesh: Mesh, spec: ShardingSpec, tail: tuple, dtype,
                     donate: bool = True):
    """Cached scatter program: place replicated (phys_row, value) chunks
    onto the owning device shards — the array-table twin of the hash
    loader's ``insert_rows_sharded`` chunk delivery, so a REMOTE checkpoint
    (sequential chunk stream, no memmap) loads with bounded host memory.
    ``donate=False`` keeps the input buffers alive (the serving hot-swap
    patches a COPY while in-flight readers keep the published state)."""
    rps = spec.rows_per_shard
    axes = spec.shard_axes
    sizes = tuple(mesh.shape[a] for a in axes)

    def _deliver(arr, phys, rows):
        me = a2a.linear_shard_id(axes, sizes)
        loc = phys - me * rps
        ok = (phys >= 0) & (loc >= 0) & (loc < rps)
        idx = jnp.where(ok, loc, rps).astype(jnp.int32)
        return arr.at[idx].set(rows.astype(arr.dtype), mode="drop")

    row = spec.row_spec()
    fn = shard_map(_deliver, mesh=mesh, in_specs=(row, P(), P()),
                   out_specs=row, check_vma=False)
    return jax.jit(fn, donate_argnums=(0,) if donate else ())


def deliver_rows_sharded(arr: jnp.ndarray, phys: jnp.ndarray,
                         rows: jnp.ndarray, *, mesh: Mesh,
                         spec: ShardingSpec,
                         donate: bool = True) -> jnp.ndarray:
    """Scatter rows at PHYSICAL positions into a sharded array.

    ``phys``/``rows`` are replicated host chunks (phys = shard *
    rows_per_shard + local; -1 = padding). Chunks of one size reuse one
    compiled program. The checkpoint loader donates (the blank canvas is
    dead after delivery); the serving hot-swap passes ``donate=False`` so
    readers holding the pre-swap state never see a deleted buffer.
    """
    fn = _deliver_program(mesh, spec, tuple(rows.shape[1:]),
                          np.dtype(arr.dtype).name, donate)
    return fn(arr, phys, rows)


@functools.lru_cache(maxsize=None)
def _pull_program(mesh: Mesh, spec: ShardingSpec, dim: int,
                  batch_sharded: bool, record_stats: bool = False):
    """Cached jitted pull: eager callers (serving lookups, tests) would
    otherwise rebuild + retrace the shard_map closure every call."""
    batch_spec = P(spec.data_axis) if batch_sharded else P()

    # single shard => nothing to route; the masked-local body below (whose
    # collectives are free over size-1 axes) skips the bucketing machinery
    # (~25% faster on one chip for the headline config). The cached plane
    # always routes: its residue masking composes with the exchange. A
    # grouped-plane table addressed PER TABLE (serving probes, checkpoint
    # paths) takes the plain a2a program — grouping only exists at the
    # collection level.
    if (spec.plane != "psum" and spec.num_shards > 1) \
            or spec.is_cached:
        grid_axes, grid_sizes, split_axes, split_sizes = a2a.grid_info(
            mesh, spec.shard_axes, spec.model_axis, batch_sharded)
        sentinel = dedup.FILL

        def _pull_core(weights, idx):
            me = a2a.linear_shard_id(grid_axes, grid_sizes)

            def resolve(keys):
                shard, local = spec.shard_and_local(keys)
                mine = ((keys >= 0) & (keys < spec.padded_vocab)
                        & (shard == me))
                rows = jnp.take(weights, jnp.where(mine, local, 0), axis=0,
                                mode="clip")
                return jnp.where(mine[:, None], rows, jnp.zeros_like(rows))

            def owner(keys):
                shard, _ = spec.shard_and_local(keys)
                valid = (keys >= 0) & (keys < spec.padded_vocab)
                return jnp.where(valid, shard, spec.num_shards).astype(
                    jnp.int32)

            rows = a2a.exchange_pull(
                idx.ravel(), resolve, owner, sentinel=sentinel, dim=dim,
                num_shards=spec.num_shards, grid_axes=grid_axes,
                grid_sizes=grid_sizes, split_axes=split_axes,
                split_sizes=split_sizes, capacity=spec.a2a_capacity,
                slack=spec.a2a_slack, record_stats=record_stats,
                wire_dtype=spec.pull_wire_dtype)
            return rows.reshape(idx.shape + (dim,))

        if spec.is_cached:
            def _pull(weights, ckeys, crows, idx):
                flat = idx.ravel()
                valid = (flat >= 0) & (flat < spec.padded_vocab)
                pos, hit = hot_cache.lookup(ckeys, flat, valid)
                served = jnp.where(hit[:, None],
                                   jnp.take(crows, pos, axis=0),
                                   jnp.zeros((1, dim), crows.dtype))
                hot_cache.record_cache_stats(
                    hit, valid,
                    entry_bytes=dim * crows.dtype.itemsize + 4,
                    split_axes=split_axes, split_sizes=split_sizes,
                    record=record_stats)
                resid = hot_cache.mask_hits(flat, hit, sentinel)
                rows = _pull_core(weights, resid).reshape(-1, dim)
                return (rows + served).reshape(idx.shape + (dim,))
        else:
            _pull = _pull_core
    else:
        def _pull(weights, idx):
            s = lax.axis_index(spec.model_axis)
            flat = idx.ravel()
            shard, local = spec.shard_and_local(flat)
            # invalid indices (negative or beyond the padded vocab) are owned
            # by nobody -> psum returns zero rows, like table_lib.pull
            owned = (shard == s) & (flat >= 0) & (flat < spec.padded_vocab)
            rows = jnp.take(weights, jnp.where(owned, local, 0), axis=0,
                            mode="clip")
            rows = jnp.where(owned[:, None], rows, jnp.zeros_like(rows))
            rows = lax.psum(rows, spec.model_axis)
            return rows.reshape(idx.shape + (dim,))

    if spec.is_cached:
        in_specs = (spec.row_spec(), P(), P(), batch_spec)
    else:
        in_specs = (spec.row_spec(), batch_spec)
    # plane-identifiable HLO module name (jit names the module after the
    # callable): a contract-audit failure then says WHICH plane's
    # program regressed (analysis/contracts.py); compressed planes carry
    # their precision suffix (pull_a2a_bf16, ...)
    _pull.__name__ = f"pull_{spec.plane_label.replace('+', '_')}"
    fn = shard_map(_pull, mesh=mesh,
                   in_specs=in_specs,
                   out_specs=batch_spec,
                   check_vma=False)
    return jax.jit(fn)


def pull_sharded(state,
                 indices: jnp.ndarray,
                 *,
                 mesh: Mesh,
                 spec: ShardingSpec,
                 batch_sharded: bool = True) -> jnp.ndarray:
    """Distributed embedding lookup.

    ``indices``: any shape, sharded over the data axis on dim 0 when
    ``batch_sharded`` (the normal training path) else replicated. Returns
    rows with the same batch sharding. Equivalent to the reference's pull
    RPC fan-out + response scatter (EmbeddingPullOperator.cpp:40-252), as a
    gather + one psum over ICI. On the ``"a2a+cache"`` plane ``state`` is a
    :class:`hot_cache.CachedState`; hot keys are served from the local
    replica and only the residue rides the exchange.
    """
    record = observability.evaluate_performance()
    if spec.is_cached:
        dim = state.table.weights.shape[-1]
        fn = _pull_program(mesh, spec, dim, batch_sharded, record)
        return observability.plane_timed(
            "pull", spec.plane_label, record, fn, state.table.weights,
            state.cache.keys, state.cache.rows, indices)
    # int8_ef states wrap the table with the push residual; pulls read
    # through the wrapper (serving restores may hand a bare table)
    state = precision.unwrap(state)
    dim = state.weights.shape[-1]
    fn = _pull_program(mesh, spec, dim, batch_sharded, record)
    return observability.plane_timed("pull", spec.plane_label, record, fn,
                                     state.weights, indices)


@functools.lru_cache(maxsize=None)
def _apply_program(mesh: Mesh, spec: ShardingSpec,
                   optimizer: SparseOptimizer, dim: int,
                   batch_sharded: bool, dedup_capacity: Optional[int],
                   slot_names: tuple, record_stats: bool = False):
    batch_spec = P(spec.data_axis) if batch_sharded else P()

    if (spec.plane != "psum" and spec.num_shards > 1) \
            or spec.is_cached:
        grid_axes, grid_sizes, split_axes, split_sizes = a2a.grid_info(
            mesh, spec.shard_axes, spec.model_axis, batch_sharded)

        def _push_core(weights, slots, flat, g2, ef=None):
            me = a2a.linear_shard_id(grid_axes, grid_sizes)

            def owner(keys):
                shard, _ = spec.shard_and_local(keys)
                valid = (keys >= 0) & (keys < spec.padded_vocab)
                return jnp.where(valid, shard, spec.num_shards).astype(
                    jnp.int32)

            def apply_fn(st, keys, grads, counts):
                shard, local = spec.shard_and_local(keys)
                mine = ((keys >= 0) & (keys < spec.padded_vocab)
                        & (shard == me))
                masked = jnp.where(mine, local, -1)
                new = table_lib.apply_gradients(
                    table_lib.TableState(weights=st[0], slots=st[1]),
                    optimizer, masked, grads,
                    dedup_capacity=dedup_capacity, in_counts=counts)
                return new.weights, new.slots

            return a2a.exchange_push(
                flat, g2,
                (weights, slots), apply_fn, owner,
                sentinel=dedup.FILL, num_shards=spec.num_shards,
                grid_axes=grid_axes, grid_sizes=grid_sizes,
                split_axes=split_axes, split_sizes=split_sizes,
                capacity=spec.a2a_capacity, slack=spec.a2a_slack,
                record_stats=record_stats,
                wire_dtype=spec.push_wire_dtype, ef_state=ef)

        if spec.is_cached:
            def _apply(weights, slots, ckeys, crows, cslots, idx, g):
                me = a2a.linear_shard_id(grid_axes, grid_sizes)
                flat = idx.ravel()
                g2 = g.reshape(-1, dim)
                valid = (flat >= 0) & (flat < spec.padded_vocab)
                pos, hit = hot_cache.lookup(ckeys, flat, valid)
                k = ckeys.shape[0]
                summed, counts = hot_cache.cache_pre_reduce(
                    pos, hit, g2, k, split_axes, split_sizes, grid_axes)
                hot_cache.record_cache_stats(
                    hit, valid,
                    entry_bytes=dim * crows.dtype.itemsize + 8,
                    split_axes=split_axes, split_sizes=split_sizes,
                    record=record_stats)
                # residue rides the exchange with hits masked invalid
                resid = hot_cache.mask_hits(flat, hit, dedup.FILL)
                weights, slots = _push_core(weights, slots, resid, g2)
                # identical psum'd totals on every device -> identical
                # replica update everywhere; the owner scatters its rows
                # back so the table stays authoritative
                cache = hot_cache.HotCacheState(keys=ckeys, rows=crows,
                                                slots=cslots)
                cache = hot_cache.update_replica(optimizer, cache, summed,
                                                 counts)
                shard, local = spec.shard_and_local(ckeys)
                ckv = (ckeys >= 0) & (ckeys < spec.padded_vocab)
                mine = ckv & (shard == me) & (counts > 0)
                oob = jnp.asarray(spec.rows_per_shard, local.dtype)
                sc = jnp.where(mine, local, oob)
                weights = weights.at[sc].set(
                    cache.rows.astype(weights.dtype), mode="drop")
                slots = {name: slots[name].at[sc].set(
                    cache.slots[name].astype(slots[name].dtype),
                    mode="drop") for name in slots}
                return weights, slots, cache.rows, cache.slots
        elif spec.is_int8_ef:
            def _apply(weights, slots, ef_keys, ef_resid, idx, g):
                (weights, slots), (nek, ner) = _push_core(
                    weights, slots, idx.ravel(), g.reshape(-1, dim),
                    ef=(ef_keys, ef_resid))
                return weights, slots, nek, ner
        else:
            def _apply(weights, slots, idx, g):
                return _push_core(weights, slots, idx.ravel(),
                                  g.reshape(-1, dim))
    else:
        def _apply(weights, slots, idx, g):
            s = lax.axis_index(spec.model_axis)
            flat = idx.ravel()
            g2 = g.reshape(-1, dim)
            if batch_sharded:
                flat = lax.all_gather(flat, spec.data_axis, tiled=True)
                g2 = lax.all_gather(g2, spec.data_axis, tiled=True)
            shard, local = spec.shard_and_local(flat)
            owned = (shard == s) & (flat >= 0) & (flat < spec.padded_vocab)
            # non-owned entries become index -1 -> dropped in apply_gradients
            masked = jnp.where(owned, local, -1)
            local_state = table_lib.TableState(weights=weights, slots=slots)
            new_state = table_lib.apply_gradients(
                local_state, optimizer, masked, g2,
                dedup_capacity=dedup_capacity)
            return new_state.weights, new_state.slots

    slot_specs = {name: spec.row_spec() for name in slot_names}
    _apply.__name__ = f"push_{spec.plane_label.replace('+', '_')}"
    if spec.is_cached:
        cache_slot_specs = {name: P() for name in slot_names}
        fn = shard_map(_apply, mesh=mesh,
                       in_specs=(spec.row_spec(), slot_specs, P(), P(),
                                 cache_slot_specs, batch_spec, batch_spec),
                       out_specs=(spec.row_spec(), slot_specs, P(),
                                  cache_slot_specs),
                       check_vma=False)
    elif spec.is_int8_ef and spec.num_shards > 1:
        # the EF residual buffers shard over the exchange grid: each
        # device owns exactly its sender slice's block
        ef_spec = P(spec.shard_axes)
        fn = shard_map(_apply, mesh=mesh,
                       in_specs=(spec.row_spec(), slot_specs, ef_spec,
                                 ef_spec, batch_spec, batch_spec),
                       out_specs=(spec.row_spec(), slot_specs, ef_spec,
                                  ef_spec),
                       check_vma=False)
    else:
        fn = shard_map(_apply, mesh=mesh,
                       in_specs=(spec.row_spec(), slot_specs, batch_spec,
                                 batch_spec),
                       out_specs=(spec.row_spec(), slot_specs),
                       check_vma=False)
    return jax.jit(fn)


def apply_gradients_sharded(state,
                            optimizer: SparseOptimizer,
                            indices: jnp.ndarray,
                            grads: jnp.ndarray,
                            *,
                            mesh: Mesh,
                            spec: ShardingSpec,
                            batch_sharded: bool = True,
                            dedup_capacity: Optional[int] = None):
    """Distributed push+update: every shard applies its owned rows.

    Data-axis devices all_gather the global (indices, grads) so the update is
    computed identically on every data replica of a model shard — replacing
    the reference's single-owner store RPC (WorkerContext.cpp:115-123) with
    deterministic replicated application. On the ``"a2a+cache"`` plane
    ``state`` is a :class:`hot_cache.CachedState`: hot keys pre-reduce
    locally + one psum over the K replica rows (no exchange for them), and
    the owner writes the updated rows back so the table stays authoritative.
    """
    optimizer = make_optimizer(optimizer)
    record = observability.evaluate_performance()
    if spec.is_cached:
        table = state.table
        dim = table.weights.shape[-1]
        fn = _apply_program(mesh, spec, optimizer, dim, batch_sharded,
                            dedup_capacity, tuple(table.slots), record)
        weights, slots, crows, cslots = observability.plane_timed(
            "push", spec.plane_label, record, fn,
            table.weights, table.slots, state.cache.keys, state.cache.rows,
            state.cache.slots, indices, grads)
        return hot_cache.CachedState(
            table=table_lib.TableState(weights=weights, slots=slots),
            cache=hot_cache.HotCacheState(keys=state.cache.keys,
                                          rows=crows, slots=cslots))
    if spec.is_int8_ef and spec.num_shards > 1:
        dim = precision.unwrap(state).weights.shape[-1]
        sentinel, key_dtype = precision.ef_key_space(use_hash=False)
        table, ef_keys, ef_resid = precision.ensure_ef(
            state, dim=dim, wide=False, sentinel=sentinel,
            n_flat=int(np.prod(indices.shape)),
            data=mesh.shape[spec.data_axis],
            model=mesh.shape[spec.model_axis],
            batch_sharded=batch_sharded, key_dtype=key_dtype)
        fn = _apply_program(mesh, spec, optimizer, dim, batch_sharded,
                            dedup_capacity, tuple(table.slots), record)
        weights, slots, nek, ner = observability.plane_timed(
            "push", spec.plane_label, record, fn,
            table.weights, table.slots, ef_keys, ef_resid, indices, grads)
        return precision.EFState(
            table=table_lib.TableState(weights=weights, slots=slots),
            keys=nek, resid=ner)
    state = precision.unwrap(state)
    dim = state.weights.shape[-1]
    fn = _apply_program(mesh, spec, optimizer, dim, batch_sharded,
                        dedup_capacity, tuple(state.slots), record)
    weights, slots = observability.plane_timed(
        "push", spec.plane_label, record, fn,
        state.weights, state.slots, indices, grads)
    return table_lib.TableState(weights=weights, slots=slots)
