"""Frequency-aware hot-row replica cache: the ``"a2a+cache"`` data plane.

Rec-sys key streams are heavily Zipfian — the bench suite's own
zipf(a=1.08) workloads concentrate most lookups on a tiny head of rows —
yet the owner-routed exchange (``alltoall.py``) pays the full a2a round
for every entry. Systems like HET (VLDB '22) and Kraken replicate just
the hot rows on every worker and serve them locally; this module is that
idea layered on the sharded plane, kept **exactly equivalent** to the
uncached exchange:

* A host-side decayed frequency sketch (:class:`FreqSketch`) ranks keys;
  every N steps (outside the jitted step) the top-K set is admitted and
  its rows + optimizer slots are replicated into every device's HBM
  (:class:`HotCacheState`, carried next to the authoritative table in
  :class:`CachedState`).
* **Pull**: each batch is partitioned in-graph into cached/uncached
  halves (static shapes — a hit mask, never a dynamic split). Hits are
  served from the local replica with NO collective; the residue flows
  through the existing exchange with hits masked to the invalid
  sentinel, and the two row sets sum (the exchange returns zero rows for
  masked entries).
* **Push**: hits are pre-reduced locally into K bins over each device's
  distinct sub-slice (the same split the exchange uses), one ``psum``
  over the K cached rows merges the global (grad sum, count) per key —
  the same MpscGradientReducer-style merge the owner performs — and
  every device applies the identical optimizer update to its replica
  while the owner scatters the updated row back into its table shard.
  The table therefore stays authoritative at every step: a refresh only
  re-gathers rows, it never writes back.

Replica coherence argument: the psum result is identical on every
device, the optimizer update is deterministic, and cached keys are
excluded from the exchange (membership is a pure function of the key),
so each key's update is applied exactly once with the same totals as the
uncached plane — parameters match to float-summation-order tolerance.

Counters (gated like the a2a accumulators, see
``observability.set_evaluate_performance``): ``cache_hits`` /
``cache_misses`` count batch entries against the cached set on each
device's distinct sub-slice (host accumulation over shards sums to the
global total); ``ici_bytes_saved`` is the entry-granularity estimate of
exchange traffic the hits skipped (row + key/count words per entry,
pre-dedup — an upper bound on bucket bytes, the measurement the
reference takes pre-dedup too, laboratory/benchmark/analyze.py).
"""

from __future__ import annotations

import functools
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import hash_table as hash_lib
from .. import table as table_lib
from ..analysis import scope
from ..analysis.lint import host_fn
from ..utils.jaxcompat import shard_map
from . import alltoall as a2a


def _reject_tracer(x, where: str) -> None:
    """The admission plane is host-side BY CONTRACT: a tracer reaching it
    means someone moved sketch/counter maintenance inside a jitted step —
    the exact regression graftlint rule JG001 flags statically. Fail with
    the design pointer instead of numpy's opaque TracerArrayConversion."""
    if isinstance(x, jax.core.Tracer):
        raise TypeError(
            f"{where} received a JAX tracer: the frequency sketch must be "
            "fed OUTSIDE the jitted step (host-side admission is what "
            "keeps the cache plane's ICI contract — see the module "
            "docstring and analysis/lint.py JG001)")

DEFAULT_CACHE_K = 512


@struct.dataclass
class HotCacheState:
    """Replicated top-K row replica (every device holds the whole thing).

    ``keys`` is SORTED (ascending; signed order for narrow keys, unsigned
    u64 order for wide pairs — :func:`lookup` binary-searches it) and
    padded with the plane's invalid sentinel, which can never equal a
    valid query. ``rows``/``slots`` mirror the owner table's current
    values for those keys.
    """

    keys: jnp.ndarray                    # [K] or [K, 2] (wide), sorted
    rows: jnp.ndarray                    # [K, dim]
    slots: Dict[str, jnp.ndarray]        # each [K, ...]

    @property
    def k(self) -> int:
        return self.keys.shape[0]

    @property
    def wide(self) -> bool:
        return self.keys.ndim == 2


@struct.dataclass
class CachedState:
    """Authoritative table + its hot-row replica, threaded as one pytree."""

    table: Any                           # TableState | HashTableState
    cache: HotCacheState


def unwrap(state: Any) -> Any:
    """The authoritative table of a possibly-wrapped state (checkpoint
    and serving paths read through the wrapper — the hot-row replica and
    the int8_ef push residual are both derived state)."""
    from . import precision
    if isinstance(state, precision.EFState):
        return state.table
    return state.table if isinstance(state, CachedState) else state


# --- device-side lookup ------------------------------------------------------

def _pair_less(alo, ahi, blo, bhi) -> jnp.ndarray:
    """a < b in unsigned-u64 order over (lo, hi) int32 pairs (x64-off)."""
    au, bu = ahi.astype(jnp.uint32), bhi.astype(jnp.uint32)
    al, bl = alo.astype(jnp.uint32), blo.astype(jnp.uint32)
    return (au < bu) | ((au == bu) & (al < bl))


def lookup(cache_keys: jnp.ndarray, query: jnp.ndarray,
           valid: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Cache position + hit mask for each query key.

    ``cache_keys`` is the sorted [K] (or [K, 2]) replica key set; ``query``
    [n] (or [n, 2]); ``valid`` [n] masks entries that are valid keys at all
    (sentinel pads never hit). Returns ``(pos [n] int32, hit [n] bool)``.
    """
    k = cache_keys.shape[0]
    if cache_keys.ndim == 2:
        n = query.shape[0]
        lo = jnp.zeros((n,), jnp.int32)
        hi = jnp.full((n,), k, jnp.int32)
        for _ in range(max(1, int(k).bit_length())):
            active = lo < hi
            mid = (lo + hi) // 2
            km = jnp.take(cache_keys, jnp.minimum(mid, k - 1), axis=0)
            less = _pair_less(km[:, 0], km[:, 1], query[:, 0], query[:, 1])
            lo = jnp.where(active & less, mid + 1, lo)
            hi = jnp.where(active & ~less, mid, hi)
        pos = jnp.minimum(lo, k - 1)
        at = jnp.take(cache_keys, pos, axis=0)
        hit = (at[:, 0] == query[:, 0]) & (at[:, 1] == query[:, 1]) & valid
        return pos, hit
    ck = cache_keys.astype(query.dtype)
    pos = jnp.minimum(jnp.searchsorted(ck, query).astype(jnp.int32), k - 1)
    hit = (jnp.take(ck, pos) == query) & valid
    return pos, hit


def mask_hits(flat: jnp.ndarray, hit: jnp.ndarray, sentinel) -> jnp.ndarray:
    """Replace cache-served entries with the plane's invalid sentinel so the
    residue rides the existing exchange untouched (static shapes: the
    cached/uncached partition is a mask, never a dynamic split)."""
    s = jnp.asarray(sentinel, flat.dtype)
    if flat.ndim == 2:
        return jnp.where(hit[:, None], s, flat)
    return jnp.where(hit, s, flat)


def cache_pre_reduce(pos: jnp.ndarray, hit: jnp.ndarray, grads: jnp.ndarray,
                     k: int, split_axes, split_sizes, grid_axes
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-key (grad sum, count) over the GLOBAL batch for the K cached rows.

    Each device pre-reduces its distinct sub-slice (the same
    ``split_slice`` partition the exchange push uses, so no entry is
    counted twice across model-axis peers), then one psum over the shard
    grid merges the partials — the cached keys' replacement for the
    routed exchange, O(K * dim) ICI bytes regardless of batch size.
    """
    parts = math.prod(split_sizes)
    my_part = a2a.linear_shard_id(split_axes, split_sizes)
    binpos = jnp.where(hit, pos, jnp.int32(k))
    sl_bin, _m = a2a.split_slice(binpos, parts, my_part, k)
    sl_g = a2a.split_slice_rows(grads, parts, my_part)
    summed = jnp.zeros((k + 1, grads.shape[-1]), grads.dtype
                       ).at[sl_bin].add(sl_g)
    counts = jnp.zeros((k + 1,), jnp.int32).at[sl_bin].add(
        (sl_bin < k).astype(jnp.int32))
    summed = lax.psum(summed[:k], tuple(grid_axes))
    counts = lax.psum(counts[:k], tuple(grid_axes))
    return summed, counts


def update_replica(optimizer, cache: HotCacheState, summed: jnp.ndarray,
                   counts: jnp.ndarray) -> HotCacheState:
    """Apply the psum-merged update to the replica rows/slots.

    Rows with a zero count stay bit-identical (stateful optimizers like
    adam would otherwise decay untouched rows — the framework-wide
    touched-rows-only contract)."""
    new_w, new_s = table_lib.optimizer_block_update(
        optimizer, cache.rows, cache.slots, summed, counts)
    touched = counts > 0
    rows = jnp.where(touched[:, None], new_w, cache.rows)
    slots = {}
    for name, cur in cache.slots.items():
        m = touched.reshape((-1,) + (1,) * (cur.ndim - 1))
        slots[name] = jnp.where(m, new_s[name], cur)
    return HotCacheState(keys=cache.keys, rows=rows, slots=slots)


def record_cache_stats(hit: jnp.ndarray, valid: jnp.ndarray, *,
                       entry_bytes: int, split_axes, split_sizes,
                       record: bool) -> None:
    """Gated cache_hits / cache_misses / ici_bytes_saved accumulation.

    Counted on each device's distinct sub-slice so summing the per-device
    callbacks host-side yields the global totals (the a2a accumulators'
    convention). ``entry_bytes`` = exchange bytes one served entry skips
    (row + key/count words, pre-dedup)."""
    parts = math.prod(split_sizes)
    my_part = a2a.linear_shard_id(split_axes, split_sizes)
    h, _ = a2a.split_slice(hit.astype(jnp.int32), parts, my_part, 0)
    v, _ = a2a.split_slice(valid.astype(jnp.int32), parts, my_part, 0)
    hits = jnp.sum(h).astype(jnp.int32)
    a2a.record_stat("cache_hits", hits, record)
    a2a.record_stat("cache_misses", (jnp.sum(v) - hits).astype(jnp.int32),
                    record)
    a2a.record_stat("ici_bytes_saved", hits * jnp.int32(entry_bytes), record)


# --- cache construction (host side, outside the jitted step) -----------------

def empty_cache_like(table_state: Any, k: int, *, mesh: Mesh,
                     wide: bool = False,
                     key_dtype=jnp.int32) -> HotCacheState:
    """All-pad cache (zero hits — the plane behaves exactly like "a2a"
    until the first refresh admits keys)."""
    repl = NamedSharding(mesh, P())
    dim = table_state.weights.shape[-1]
    if wide:
        keys = np.full((k, 2), hash_lib.empty_key(np.int32), np.int32)
    else:
        kd = np.dtype(key_dtype)
        keys = np.full((k,), np.iinfo(kd).min, kd)
    rows = np.zeros((k, dim), np.dtype(table_state.weights.dtype))
    put = functools.partial(jax.device_put, device=repl)
    slots = {name: put(np.zeros((k,) + tuple(arr.shape[1:]),
                                np.dtype(arr.dtype)))
             for name, arr in table_state.slots.items()}
    return HotCacheState(keys=put(keys), rows=put(rows), slots=slots)


def attach_empty(table_state: Any, spec, mesh: Mesh):
    """Wrap a bare table in a :class:`CachedState` with an all-pad replica
    when ``spec`` is on the cached plane (pass-through otherwise) — THE
    one place the pad sentinel / replica key dtype are derived, shared by
    both plane creators and the collection/checkpoint wrappers."""
    if not getattr(spec, "is_cached", False) \
            or isinstance(table_state, CachedState):
        return table_state
    is_hash = hasattr(table_state, "keys")
    wide = bool(is_hash and table_state.keys.ndim == 2)
    return CachedState(
        table=table_state,
        cache=empty_cache_like(
            table_state, spec.cache_k, mesh=mesh, wide=wide,
            key_dtype=table_state.keys.dtype if is_hash and not wide
            else jnp.int32))


def _sort_for_device(keys: np.ndarray, wide: bool) -> np.ndarray:
    """Host sort matching the device comparator: signed ascending for
    narrow keys, unsigned-u64 for wide (joined int64) keys."""
    if wide:
        return keys[np.argsort(keys.view(np.uint64), kind="stable")]
    return np.sort(keys, kind="stable")


@functools.lru_cache(maxsize=None)
def _gather_table_program(mesh: Mesh, spec, slot_names: tuple):
    """keys [K] replicated -> (rows, slots, found) replicated: each shard
    contributes its owned rows, one psum merges (the K-row refresh pull)."""
    axes = spec.shard_axes
    sizes = tuple(mesh.shape[a] for a in axes)

    def _gather(weights, slots, keys):
        me = a2a.linear_shard_id(axes, sizes)
        shard, local = spec.shard_and_local(keys)
        mine = (keys >= 0) & (keys < spec.padded_vocab) & (shard == me)
        safe = jnp.where(mine, local, 0)
        rows = jnp.take(weights, safe, axis=0, mode="clip")
        rows = jnp.where(mine[:, None], rows, jnp.zeros_like(rows))
        srows = {}
        for name, v in slots.items():
            r = jnp.take(v, safe, axis=0, mode="clip")
            m = mine.reshape((-1,) + (1,) * (r.ndim - 1))
            srows[name] = lax.psum(jnp.where(m, r, jnp.zeros_like(r)), axes)
        rows = lax.psum(rows, axes)
        found = lax.psum(mine.astype(jnp.int32), axes) > 0
        return rows, srows, found

    row = spec.row_spec()
    slot_specs = {name: row for name in slot_names}
    fn = shard_map(_gather, mesh=mesh, in_specs=(row, slot_specs, P()),
                   out_specs=(P(), {name: P() for name in slot_names}, P()),
                   check_vma=False)
    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _gather_hash_program(mesh: Mesh, spec, slot_names: tuple):
    axes = spec.shard_axes
    sizes = tuple(mesh.shape[a] for a in axes)

    def _gather(tkeys, weights, slots, q):
        me = a2a.linear_shard_id(axes, sizes)
        empty = hash_lib.empty_key(tkeys.dtype)
        if hash_lib.is_wide(tkeys):
            owned = (spec.owner_shard(q) == me) & (q[:, 1] != empty)
            masked = jnp.where(owned[:, None], q, empty)
        else:
            owned = (spec.owner_shard(q) == me) & (q != empty)
            masked = jnp.where(owned, q, empty)
        slot = hash_lib.find_rows(tkeys, masked, spec.max_probes)
        hitv = slot >= 0
        safe = jnp.where(hitv, slot, 0)
        rows = jnp.take(weights, safe, axis=0, mode="clip")
        rows = jnp.where(hitv[:, None], rows, jnp.zeros_like(rows))
        srows = {}
        for name, v in slots.items():
            r = jnp.take(v, safe, axis=0, mode="clip")
            m = hitv.reshape((-1,) + (1,) * (r.ndim - 1))
            srows[name] = lax.psum(jnp.where(m, r, jnp.zeros_like(r)), axes)
        rows = lax.psum(rows, axes)
        found = lax.psum(hitv.astype(jnp.int32), axes) > 0
        return rows, srows, found

    row = spec.row_spec()
    slot_specs = {name: row for name in slot_names}
    fn = shard_map(_gather, mesh=mesh,
                   in_specs=(row, row, slot_specs, P()),
                   out_specs=(P(), {name: P() for name in slot_names}, P()),
                   check_vma=False)
    return jax.jit(fn)


def build_cache(table_state: Any, candidates: np.ndarray, k: int, *,
                mesh: Mesh, spec) -> HotCacheState:
    """Admit up to ``k`` candidate keys: pad, sort, gather rows + slots.

    ``candidates`` are host keys (int64 for wide tables — joined pairs;
    the table's key/index dtype otherwise), frequency-ranked by the
    caller. Hash-table candidates not yet PRESENT in the table are
    rejected (a replica must never shadow the deterministic-init contract
    for unseen keys); array-table keys are always present. The returned
    state's arrays are replicated over the mesh.
    """
    from . import sharded_hash as sh  # late: avoids a module cycle
    is_hash = isinstance(spec, sh.HashShardingSpec)
    wide = bool(is_hash and spec.wide)
    repl = NamedSharding(mesh, P())
    slot_names = tuple(table_state.slots)
    cand = np.asarray(candidates).ravel()[:k]

    if wide:
        pad = np.int64(np.uint64(0x8000000080000000))  # the EMPTY pair
    else:
        kd = np.dtype(table_state.keys.dtype) if is_hash \
            else np.dtype(np.int32)
        pad = np.iinfo(kd).min

    def _pack(keys64: np.ndarray):
        if wide:
            full = np.full((k,), pad, np.int64)
            full[:keys64.size] = keys64.astype(np.int64)
            full = _sort_for_device(full, wide=True)
            return full, hash_lib.split64(full)
        full = np.full((k,), pad, kd)
        full[:keys64.size] = keys64.astype(kd)
        full = _sort_for_device(full, wide=False)
        return full, full

    packed, device_keys = _pack(cand)
    program = (_gather_hash_program if is_hash else _gather_table_program)(
        mesh, spec, slot_names)
    for _ in range(2):
        dk = jax.device_put(device_keys, repl)
        if is_hash:
            rows, srows, found = program(table_state.keys,
                                         table_state.weights,
                                         table_state.slots, dk)
        else:
            rows, srows, found = program(table_state.weights,
                                         table_state.slots, dk)
        found_np = np.asarray(found)
        if (found_np | (packed == pad)).all():
            break
        # some candidates are absent from the table (hash keys never yet
        # pushed): drop them, re-pack, re-gather once — absent keys must
        # keep the uncached plane's deterministic-init contract
        packed, device_keys = _pack(packed[found_np])
    return HotCacheState(keys=dk, rows=rows, slots=srows)


# --- admission policy (host side) -------------------------------------------

# dense-mode cutoff: a bounded vocab up to this many rows keeps exact
# per-row float32 counts (<= 256 MB host RAM); bigger / unbounded key
# spaces fall back to the dict sketch
DENSE_SKETCH_MAX = 1 << 26


class FreqSketch:
    """Decayed per-key frequency counter driving cache admission.

    Two backings behind one interface:

    * ``dense_vocab`` set (bounded key spaces up to
      :data:`DENSE_SKETCH_MAX` rows): a flat float32 count array —
      ``update`` is one vectorized ``np.add.at`` per batch, the shape the
      per-STEP hot path needs (the dict loop costs milliseconds per batch
      at rec-sys batch sizes, which would out-bill a ~1.5 ms device
      step); ``topk`` is an argpartition, paid only at refresh.
    * otherwise (hash / unbounded keys): dict-backed exact counts.

    Both decay by ``decay`` once per refresh window (exponential
    forgetting). The dict backing prunes entries below ``prune_below``
    and hard-caps at ``max_entries`` (the coldest half is dropped when it
    trips — hot keys re-accumulate every window, the tail never does).
    """

    def __init__(self, decay: float = 0.8, prune_below: float = 0.5,
                 max_entries: int = 1 << 20,
                 dense_vocab: Optional[int] = None):
        if not 0.0 < decay <= 1.0:
            raise ValueError(f"decay must be in (0, 1], got {decay}")
        self.decay_factor = float(decay)
        self.prune_below = float(prune_below)
        self.max_entries = int(max_entries)
        self._counts: Dict[int, float] = {}
        self._sample_phase = 0
        self._dense: Optional[np.ndarray] = None
        if dense_vocab is not None and 0 < dense_vocab <= DENSE_SKETCH_MAX:
            self._dense = np.zeros(int(dense_vocab), np.float32)

    def __len__(self) -> int:
        if self._dense is not None:
            return int(np.count_nonzero(self._dense))
        return len(self._counts)

    # measured CPython cost of one dict entry (int key + float value +
    # table slot share) — an estimate for the memory gauges
    DICT_ENTRY_NOMINAL_BYTES = 100

    def approx_bytes(self) -> int:
        """Approximate host RAM this sketch holds (graftwatch memory
        ledger): exact for the dense backing, nominal-per-entry for the
        dict one."""
        if self._dense is not None:
            return int(self._dense.nbytes)
        return len(self._counts) * self.DICT_ENTRY_NOMINAL_BYTES

    # per-batch sample cap: scatter-adding every entry of a 4096x26 batch
    # costs ~7 ms of host time per step (np.add.at), which would out-bill
    # a ~1.5 ms device step; a uniform stride sample preserves frequency
    # RANKS (the only thing admission consumes) at ~0.5 ms
    SAMPLE_CAP = 16384

    @host_fn
    def update(self, keys: np.ndarray) -> None:
        """Count one batch's (valid, in-range) keys (stride-sampled past
        :attr:`SAMPLE_CAP` entries — ranking-preserving)."""
        _reject_tracer(keys, "FreqSketch.update")
        flat = np.asarray(keys).ravel()
        if flat.size > self.SAMPLE_CAP:
            stride = flat.size // self.SAMPLE_CAP + 1
            # rotate the phase per call: a fixed phase aliases with any
            # structured period in the flattened layout (e.g. the F
            # columns of a row-major [B, F] fused batch when
            # gcd(stride, F) > 1 would sample only a few features); over
            # a refresh window every residue class gets visited
            phase = self._sample_phase % stride
            self._sample_phase += 1
            flat = flat[phase::stride]
        if self._dense is not None:
            np.add.at(self._dense, flat.astype(np.int64), 1.0)
            return
        u, c = np.unique(flat, return_counts=True)
        counts = self._counts
        get = counts.get
        for key, n in zip(u.tolist(), c.tolist()):
            counts[key] = get(key, 0.0) + n
        if len(counts) > self.max_entries:
            # vectorized trim: a python sorted() over >1M dict items costs
            # ~1 s on the per-step path
            ks = np.fromiter(counts.keys(), np.int64, len(counts))
            vs = np.fromiter(counts.values(), np.float64, len(counts))
            keep = self.max_entries // 2
            sel = np.argpartition(-vs, keep - 1)[:keep]
            self._counts = dict(zip(ks[sel].tolist(), vs[sel].tolist()))

    def decay(self) -> None:
        f = self.decay_factor
        if self._dense is not None:
            self._dense *= f
            # prune like the dict backing: without zeroing, every key
            # ever touched stays nonzero for hundreds of windows and
            # topk's flatnonzero working set grows toward the full-array
            # argpartition cost this path exists to avoid
            self._dense[self._dense < self.prune_below] = 0.0
            return
        floor = self.prune_below
        self._counts = {key: v * f for key, v in self._counts.items()
                        if v * f >= floor}

    def topk(self, k: int) -> np.ndarray:
        """The ``k`` highest-count keys (count-desc, key-asc ties so
        refreshes are deterministic), as int64. Zero-count keys never
        qualify."""
        if self._dense is not None:
            d = self._dense
            # partition only the touched rows: argpartition over the full
            # array costs ~0.7 s at 2^26 rows; over the live working set
            # it is tens of ms (refresh-time only, amortized over N steps)
            nz = np.flatnonzero(d)
            k_eff = min(k, nz.size)
            if k_eff == 0:
                return np.empty((0,), np.int64)
            vals = d[nz]
            sel = np.argpartition(-vals, k_eff - 1)[:k_eff] \
                if k_eff < nz.size else np.arange(nz.size)
            idx = nz[sel]
            order = np.lexsort((idx, -d[idx]))
            return idx[order].astype(np.int64)
        items = sorted(self._counts.items(), key=lambda kv: (-kv[1], kv[0]))
        return np.asarray([key for key, _ in items[:k]], np.int64)


class HotCacheManager:
    """Per-variable refresh driver: observe batches, rebuild the replica
    every ``refresh_every`` steps (host-side, outside the jitted step).

    Typical wiring (the Trainer does this automatically for every
    ``plane="a2a+cache"`` variable)::

        mgr.observe(batch_ids)          # after each step
        if mgr.due:
            state = mgr.refresh(state)  # new CachedState, same table
    """

    def __init__(self, *, mesh: Mesh, spec, k: int = DEFAULT_CACHE_K,
                 refresh_every: int = 64, decay: float = 0.8,
                 name: str = ""):
        from . import sharded_hash as sh  # late: avoids a module cycle
        self.mesh = mesh
        self.spec = spec
        self.name = name
        self.k = int(k)
        self.refresh_every = max(1, int(refresh_every))
        self._is_hash = isinstance(spec, sh.HashShardingSpec)
        self._wide = bool(self._is_hash and spec.wide)
        # bounded vocabs get the vectorized dense counter (per-step cost
        # is one np.add.at); hash key spaces use the dict sketch
        self.sketch = FreqSketch(
            decay=decay,
            dense_vocab=None if self._is_hash else spec.padded_vocab)
        self._owns_sketch = True
        self._since = 0
        self.refreshes = 0
        # per-device bytes of the replica this manager last BUILT (the
        # CachedState itself lives in the training state; the manager
        # accounts what it created) — graftwatch memory ledger
        self.last_replica_bytes = 0
        from ..utils import observability
        observability.register_memory_source("hot_cache", name or "cache",
                                             self)

    def memory_stats(self) -> Dict[str, float]:
        """Host+replica memory gauges (``observability.memory_stats``):
        the admission sketch's host RAM and the per-device byte size of
        the replica built at the last refresh (keys + rows + optimizer
        slots, replicated on every device)."""
        return {
            "replica_bytes": float(self.last_replica_bytes),
            "sketch_bytes": float(self.sketch.approx_bytes()),
            "sketch_keys": float(len(self.sketch)),
            "refreshes": float(self.refreshes),
        }

    def share_sketch(self, other: "HotCacheManager") -> None:
        """Reuse ``other``'s frequency sketch: twin variables fed by the
        SAME id column (e.g. ``name`` and ``name:linear``) should pay the
        per-step count once. The sharer stops decaying (the owner's
        refresh does it) and advances its clock with :meth:`tick`."""
        self.sketch = other.sketch
        self._owns_sketch = False

    def tick(self) -> None:
        """Advance the refresh clock without re-counting (the column was
        already observed into a shared sketch this step)."""
        self._since += 1

    def _valid_keys(self, ids) -> np.ndarray:
        arr = np.asarray(ids)
        if self._wide and arr.ndim >= 2 and arr.shape[-1] == 2:
            # same ambiguity rule as embedding._widen: on a wide table a
            # trailing dim of 2 IS a (lo, hi) pair axis — the training
            # plane reads such a batch as pairs, so admission must too
            arr = hash_lib.join64(arr.reshape(-1, 2))
        arr = arr.ravel().astype(np.int64)
        if not self._is_hash:
            return arr[(arr >= 0) & (arr < self.spec.padded_vocab)]
        if self._wide:
            # the EMPTY band: hi word == INT32_MIN (hash_table.py contract)
            return arr[(arr >> np.int64(32))
                       != np.int64(np.iinfo(np.int32).min)]
        # narrow tables: the EMPTY sentinel is the key dtype's minimum;
        # dropping both widths' minima costs at most one 1-in-2^64 key
        return arr[(arr != np.iinfo(np.int32).min)
                   & (arr != np.iinfo(np.int64).min)]

    @host_fn
    def observe(self, ids) -> None:
        _reject_tracer(ids, "HotCacheManager.observe")
        keys = self._valid_keys(ids)
        if keys.size:
            self.sketch.update(keys)
        self._since += 1

    @property
    def due(self) -> bool:
        return self._since >= self.refresh_every

    def refresh(self, state: CachedState) -> CachedState:
        """New CachedState with the current top-K admitted (table rows are
        authoritative, so no writeback happens — this is a pure re-gather)."""
        with scope.span("cache.refresh"):
            self._since = 0
            self.refreshes += 1
            cand = self.sketch.topk(self.k)
            if self._owns_sketch:
                # a shared sketch decays once per window (at its owner's
                # refresh), not once per sharing variable
                self.sketch.decay()
            cache = build_cache(state.table, cand, self.k, mesh=self.mesh,
                                spec=self.spec)
            self.last_replica_bytes = int(
                cache.keys.nbytes + cache.rows.nbytes
                + sum(v.nbytes for v in cache.slots.values()))
            return CachedState(table=state.table, cache=cache)
