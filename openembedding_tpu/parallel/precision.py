"""Compressed exchange: the precision ladder of the sparse data planes.

The reference compresses its RPC payloads with byte codecs
(snappy/lz4/zlib, ``server.message_compress`` —
/root/reference/openembedding/client/EnvConfig.cpp:27-34, RpcView.h
compress path) to keep the pull/push exchange off the critical path. The
TPU-native analogue of wire compression is *precision*, not codecs:

* ``exchange.precision = "bf16"`` — pulled rows cross the all-to-all
  wire (and the row-assembly all-gather) as bfloat16 and are upcast
  after the row leg. Master weights and optimizer slots stay float32 in
  the shard; only the WIRE narrows, so the quantization is one
  round-to-nearest cast per pulled row (|err| <= 2^-9 · |x|).
* ``push.precision = "bf16"`` — the pre-reduced gradient rows ride the
  push exchange as bfloat16 (keys/counts stay int32), upcast before the
  owner's f32 optimizer math.
* ``push.precision = "int8_ef"`` — per-row max-abs scale int8
  quantization of the pre-reduced gradients with an **error-feedback
  residual**: the quantization error of each sent row is carried in
  :class:`EFState` (threaded through ``TrainState.emb``) and added back
  into the next gradient this sender pre-reduces for the same key, so
  the error is recirculated, not lost. Residuals are positional per
  (device, slice) — a key that hops to a different sender before
  recurring forfeits that one step's residual (bounded, never
  compounding: the residual is overwritten, not accumulated).

Plane token grammar: the ladder composes with the shipped planes as a
plane-string suffix — ``"a2a+bf16"`` = base ``"a2a"`` with bf16 wire
rows both directions; ``"a2a+int8"`` = bf16 pull + int8_ef push (the
fully-compressed plane). ``EmbeddingSpec.exchange_precision`` /
``push_precision`` select the rungs independently; the suffix is
shorthand for the canonical combinations (and the label the contract
registry, graftscope ledger and plane_timed spans all key on).

Where a program has no wire there is nothing to compress: single-shard
meshes and the ``psum`` ablation plane run at full precision regardless
(``psum`` + a compressed rung is rejected at spec construction), and
``precision = "f32"`` compiles byte-identical programs to the shipped
planes — the parity matrix asserts exact ``==`` there.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax.numpy as jnp
from flax import struct

# pull-wire rungs (exchange.precision) and push-wire rungs (push.precision)
EXCHANGE_PRECISIONS = ("f32", "bf16")
PUSH_PRECISIONS = ("f32", "bf16", "int8_ef")

# plane-token suffix -> (exchange_precision, push_precision)
PLANE_SUFFIXES = {
    "+bf16": ("bf16", "bf16"),
    "+int8": ("bf16", "int8_ef"),
}

# base planes int8_ef may ride: the EF residual lives per (device,
# slice) next to the per-table exchange — the grouped plane's
# concatenated multi-table streams and the cache plane's replica psum
# would each need their own residual story, and psum has no routed wire
INT8_EF_PLANES = ("a2a", "a2a+pipelined")


def parse_plane(plane: str) -> Tuple[str, str, str]:
    """``plane`` token -> (base_plane, exchange_precision, push_precision).

    ``"a2a+bf16"`` -> ``("a2a", "bf16", "bf16")``; tokens without a
    precision suffix come back at the f32 rung.
    """
    for suffix, (ep, pp) in PLANE_SUFFIXES.items():
        if plane.endswith(suffix):
            return plane[: -len(suffix)], ep, pp
    return plane, "f32", "f32"


def plane_label(base_plane: str, exchange_precision: str,
                push_precision: str) -> str:
    """Canonical observable label of a (plane, precision) combination.

    The label keys the contract registry, the graftscope byte ledger
    AND the plane_timed span histograms — pull and push of one spec
    share it, so the ledger join lines up. Mixed non-canonical combos
    (e.g. bf16 pull + f32 push) label ``+bf16``; anything int8 labels
    ``+int8``.
    """
    if push_precision == "int8_ef":
        return base_plane + "+int8"
    if "bf16" in (exchange_precision, push_precision):
        return base_plane + "+bf16"
    return base_plane


def wire_dtype(precision: str):
    """jnp dtype rows take on the wire for one rung (None = no cast)."""
    return jnp.bfloat16 if precision == "bf16" else None


def wire_itemsize(precision: str, *, f32_itemsize: int = 4) -> int:
    """Per-element bytes of gradient/row payload on the wire."""
    if precision == "bf16":
        return 2
    if precision == "int8_ef":
        return 1
    return f32_itemsize


def check_spec_precision(base_plane: str, exchange_precision: str,
                         push_precision: str, *, name: str = "") -> None:
    """Validate one spec's precision rungs against its base plane."""
    where = f"embedding {name!r}: " if name else ""
    if exchange_precision not in EXCHANGE_PRECISIONS:
        raise ValueError(
            f"{where}unknown exchange_precision {exchange_precision!r}; "
            f"known: {EXCHANGE_PRECISIONS}")
    if push_precision not in PUSH_PRECISIONS:
        raise ValueError(
            f"{where}unknown push_precision {push_precision!r}; "
            f"known: {PUSH_PRECISIONS}")
    compressed = (exchange_precision, push_precision) != ("f32", "f32")
    if base_plane == "psum" and compressed:
        raise ValueError(
            f"{where}the psum ablation plane has no routed wire to "
            "compress; keep precision='f32' or use an a2a plane")
    if push_precision == "int8_ef" and base_plane not in INT8_EF_PLANES:
        raise ValueError(
            f"{where}push_precision='int8_ef' rides only the per-table "
            f"owner exchange (base planes {INT8_EF_PLANES}); "
            f"{base_plane!r} needs its own residual story — use 'bf16'")


# --- error-feedback residual state -------------------------------------------

@struct.dataclass
class EFState:
    """Authoritative table + the int8_ef push residual, one pytree.

    ``keys``/``resid`` are the previous step's per-sender unique keys
    and quantization errors, positionally sharded over the exchange
    grid (dim 0 = ``num_devices * slice_uniq_capacity``; each device
    owns its own block inside the push's shard_map). Threaded through
    ``TrainState.emb`` like the hot-row replica's ``CachedState`` —
    derived state: checkpoints dump only ``table`` and a restore
    re-attaches an empty residual (one step of feedback forfeited,
    never correctness).
    """

    table: Any                    # TableState | HashTableState
    keys: jnp.ndarray             # [P*m] or [P*m, kw] int32, sentinel-padded
    resid: jnp.ndarray            # [P*m, dim] float32


def unwrap(state: Any) -> Any:
    """The authoritative table of a possibly-EF-wrapped state."""
    return state.table if isinstance(state, EFState) else state


def empty_ef(table_state: Any, *, dim: int, wide: bool,
             sentinel: int, key_dtype=jnp.int32) -> EFState:
    """Fresh zero-length residual (attached at init/restore; the first
    push sizes it for its batch shape and every later step reuses it)."""
    kshape = (0, 2) if wide else (0,)
    return EFState(
        table=table_state,
        keys=jnp.full(kshape, sentinel, key_dtype),
        resid=jnp.zeros((0, dim), jnp.float32))


def ef_global_len(n_flat_global: int, data: int, model: int,
                  batch_sharded: bool) -> int:
    """dim-0 length of the global EF arrays for one push batch shape.

    Mirrors the exchange's slice math: each of the ``data`` batch
    slices is divided among its ``model`` peers (or the whole grid when
    the batch is replicated), and every device carries one
    ``m``-entry residual block.
    """
    if batch_sharded:
        n_local = -(-n_flat_global // data)
        m = -(-n_local // model)
    else:
        m = -(-n_flat_global // (data * model))
    return data * model * m


def ef_key_space(*, use_hash: bool, wide: bool = False, key_dtype=None
                 ) -> Tuple[int, Any]:
    """(sentinel, key_dtype) of one table's EF key buffer.

    THE single derivation shared by spec-level wrapping
    (``EmbeddingCollection.wrap_hot_cache``) and push-dispatch sizing
    (the array/hash ``ensure_ef`` call sites) — if these ever
    disagreed, ``sized_ef`` would silently reset the residual every
    step (pure lossy int8, feedback forfeited). Array streams and wide
    pairs carry int32 words; narrow hash tables keep their own key
    dtype. Both sentinel families (``dedup.FILL``,
    ``hash_table.empty_key``) are the dtype's minimum, so one rule
    covers all tables.
    """
    kd = jnp.int32 if (not use_hash or wide) else jnp.dtype(key_dtype)
    return int(jnp.iinfo(kd).min), kd


def ensure_ef(state: Any, *, dim: int, wide: bool, sentinel: int,
              n_flat: int, data: int, model: int, batch_sharded: bool,
              key_dtype=jnp.int32
              ) -> Tuple[Any, jnp.ndarray, jnp.ndarray]:
    """(table, ef_keys, ef_resid) for one int8_ef push dispatch.

    The shared prologue of the array and hash apply paths: unwrap a
    possibly-EF-wrapped state (serving restores may hand a bare
    table), fall back to an empty residual, and size the buffers for
    this push's batch shape (``ef_global_len``/``sized_ef`` — a fresh
    or wrong-shape buffer forfeits one step of feedback, never
    correctness).
    """
    table = unwrap(state)
    ef = state if isinstance(state, EFState) \
        else empty_ef(table, dim=dim, wide=wide, sentinel=sentinel,
                      key_dtype=key_dtype)
    glen = ef_global_len(n_flat, data, model, batch_sharded)
    keys, resid = sized_ef(ef, glen, dim=dim, wide=wide,
                           sentinel=sentinel, key_dtype=key_dtype)
    return table, keys, resid


def sized_ef(ef: EFState, glen: int, *, dim: int, wide: bool,
             sentinel: int, key_dtype=jnp.int32
             ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(keys, resid) buffers of exactly ``glen`` rows for this push.

    A buffer from a different batch shape (or the fresh empty one) is
    replaced by sentinel-keys/zero-residual — one step of feedback
    forfeited; steady-state training reuses one shape and keeps all of
    it.
    """
    if ef.keys.shape[0] == glen and ef.resid.shape[0] == glen \
            and (ef.keys.ndim == 2) == wide \
            and ef.keys.dtype == jnp.dtype(key_dtype):
        return ef.keys, ef.resid
    kshape = (glen, 2) if wide else (glen,)
    return (jnp.full(kshape, sentinel, key_dtype),
            jnp.zeros((glen, dim), jnp.float32))
