"""Row-sparse embedding optimizers.

Capability parity with the reference's server-side sparse optimizers
(/root/reference/openembedding/variable/EmbeddingOptimizer.h:49-390): nine
optimizers — ``default`` (stateless), ``adadelta``, ``adagrad``, ``adam``
(with per-row beta-power state), ``adamax``, ``ftrl``, ``rmsprop``, ``sgd``
(momentum + nesterov) and the deterministic ``test`` optimizer used by the
concurrency tests.

Semantics replicated exactly:

* State lives **per row**, contiguous with the weights conceptually; here each
  slot is a separate array co-sharded with the table (row i of every slot
  belongs to table row i).
* Updates touch **only the rows referenced by the batch** — momentum/accums of
  untouched rows do not decay. This intentionally diverges from dense TF
  optimizers exactly like the reference does (reference README.md:240).
* Duplicate keys inside a batch are pre-summed; ``update`` receives the summed
  gradient plus the duplicate count (only ``test`` divides by count, matching
  EmbeddingOptimizer.h:366-390).
* Adam keeps **per-row** beta_1^t / beta_2^t power accumulators
  (EmbeddingOptimizer.h:152-199), so a row first touched at step 1000 sees the
  step-1 bias correction — replicated via 2 extra scalar slots per row.

The TPU-native design difference: instead of a virtual per-row ``update()``
called under a shard lock, each optimizer exposes a **vectorized**
``update_rows`` over a [U, D] block of gathered rows; the caller
gathers touched rows, applies, and scatters back inside one XLA program.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Tuple

import jax.numpy as jnp

from ..utils.config import coerce_fields

Slots = Dict[str, jnp.ndarray]


class SparseOptimizer:
    """Base class for row-sparse optimizers (static config, not a pytree)."""

    category: str = ""

    def slot_shapes(self, dim: int) -> Dict[str, Tuple[int, ...]]:
        """Per-row trailing shapes of each state slot."""
        return {}

    def slot_init(self, name: str) -> float:
        return 0.0

    def slot_dtype(self, name: str, table_dtype):
        """Storage dtype for a slot: at least float32, regardless of the
        table dtype. bf16 tables + f32 slots is the at-rest rung of the
        compressed-exchange precision ladder (``parallel/precision.py``):
        the weights (the HBM-dominant array at dim >= slots-per-row)
        halve while the optimizer statistics keep full precision —
        accumulator drift in bf16 (8-bit mantissa) would compound every
        step, unlike the weights' one rounding per update."""
        return jnp.promote_types(table_dtype, jnp.float32)

    def init_slots(self, num_rows: int, dim: int, dtype) -> Slots:
        return {
            name: jnp.full((num_rows,) + shape, self.slot_init(name),
                           dtype=self.slot_dtype(name, dtype))
            for name, shape in self.slot_shapes(dim).items()
        }

    def update_rows(self, weights: jnp.ndarray, slots: Slots,
                    grads: jnp.ndarray, counts: jnp.ndarray
                    ) -> Tuple[jnp.ndarray, Slots]:
        """Apply one step to a [U, D] block of rows. Returns new (weights, slots)."""
        raise NotImplementedError

    # --- state packing for checkpoints (reference stores states as a flat
    # per-row line of state_dim(dim) scalars; we keep named slots but expose
    # the same flat layout for dump/load parity) ---
    def state_dim(self, dim: int) -> int:
        return sum(math.prod(s) if s else 1
                   for s in self.slot_shapes(dim).values())

    def to_config(self) -> dict:
        out = {"category": self.category}
        out.update(dataclasses.asdict(self))
        return out


@dataclasses.dataclass(frozen=True)
class Default(SparseOptimizer):
    """Stateless; lr=0 (serving / frozen) or plain SGD when lr != 0."""

    learning_rate: float = 0.0
    category = "default"

    def update_rows(self, weights, slots, grads, counts):
        if self.learning_rate != 0:
            weights = weights - self.learning_rate * grads
        return weights, slots


@dataclasses.dataclass(frozen=True)
class Adadelta(SparseOptimizer):
    learning_rate: float = 0.001
    rho: float = 0.95
    epsilon: float = 1e-7
    category = "adadelta"

    def slot_shapes(self, dim):
        return {"accum": (dim,), "accum_update": (dim,)}

    def update_rows(self, weights, slots, grads, counts):
        accum = slots["accum"] * self.rho + grads * grads * (1 - self.rho)
        update = grads * jnp.sqrt(slots["accum_update"] + self.epsilon) \
            / jnp.sqrt(accum + self.epsilon)
        accum_update = slots["accum_update"] * self.rho + update * update * (1 - self.rho)
        weights = weights - self.learning_rate * update
        return weights, {"accum": accum, "accum_update": accum_update}


@dataclasses.dataclass(frozen=True)
class Adagrad(SparseOptimizer):
    learning_rate: float = 0.001
    initial_accumulator_value: float = 0.1
    epsilon: float = 1e-7
    category = "adagrad"

    def slot_shapes(self, dim):
        return {"accum": (dim,)}

    def slot_init(self, name):
        return self.initial_accumulator_value

    def update_rows(self, weights, slots, grads, counts):
        accum = slots["accum"] + grads * grads
        # reference: w -= lr * g / (sqrt(accum) + eps)  (EmbeddingOptimizer.h:138-141)
        weights = weights - self.learning_rate * grads / (jnp.sqrt(accum) + self.epsilon)
        return weights, {"accum": accum}


@dataclasses.dataclass(frozen=True)
class Adam(SparseOptimizer):
    learning_rate: float = 0.001
    beta_1: float = 0.9
    beta_2: float = 0.999
    epsilon: float = 1e-7
    category = "adam"

    def slot_shapes(self, dim):
        # beta powers are PER ROW scalars (EmbeddingOptimizer.h:152-163)
        return {"m": (dim,), "v": (dim,), "beta_1_t": (1,), "beta_2_t": (1,)}

    def slot_init(self, name):
        return 1.0 if name in ("beta_1_t", "beta_2_t") else 0.0

    def update_rows(self, weights, slots, grads, counts):
        beta_1_t = slots["beta_1_t"] * self.beta_1
        beta_2_t = slots["beta_2_t"] * self.beta_2
        lr_t = self.learning_rate * jnp.sqrt(1 - beta_2_t) / (1 - beta_1_t)
        m = slots["m"] * self.beta_1 + grads * (1 - self.beta_1)
        v = slots["v"] * self.beta_2 + grads * grads * (1 - self.beta_2)
        weights = weights - lr_t * m / (jnp.sqrt(v) + self.epsilon)
        return weights, {"m": m, "v": v, "beta_1_t": beta_1_t, "beta_2_t": beta_2_t}


@dataclasses.dataclass(frozen=True)
class Adamax(SparseOptimizer):
    learning_rate: float = 0.001
    beta_1: float = 0.9
    beta_2: float = 0.999
    epsilon: float = 1e-7
    category = "adamax"

    def slot_shapes(self, dim):
        return {"m": (dim,), "v": (dim,), "beta_1_t": (1,)}

    def slot_init(self, name):
        return 1.0 if name == "beta_1_t" else 0.0

    def update_rows(self, weights, slots, grads, counts):
        beta_1_t = slots["beta_1_t"] * self.beta_1
        lr_t = self.learning_rate / (1 - beta_1_t)
        m = slots["m"] * self.beta_1 + grads * (1 - self.beta_1)
        v = jnp.maximum(jnp.abs(grads), slots["v"] * self.beta_2)
        weights = weights - lr_t * m / (v + self.epsilon)
        return weights, {"m": m, "v": v, "beta_1_t": beta_1_t}


@dataclasses.dataclass(frozen=True)
class Ftrl(SparseOptimizer):
    learning_rate: float = 0.001
    initial_accumulator_value: float = 0.1
    l1_regularization_strength: float = 0.0
    l2_regularization_strength: float = 0.0
    l2_shrinkage_regularization_strength: float = 0.0
    learning_rate_power: float = -0.5
    beta: float = 0.0
    category = "ftrl"

    def slot_shapes(self, dim):
        return {"accum": (dim,), "linear": (dim,)}

    def slot_init(self, name):
        return self.initial_accumulator_value if name == "accum" else 0.0

    def update_rows(self, weights, slots, grads, counts):
        # Mirrors EmbeddingOptimizer.h:246-283 (TF-compatible FTRL with
        # l2_shrinkage and generic learning_rate_power).
        lr = self.learning_rate
        adjusted_l2 = self.l2_regularization_strength + self.beta / lr / 2
        g = grads + 2 * self.l2_shrinkage_regularization_strength * weights
        accum_new = slots["accum"] + grads * grads
        p = -self.learning_rate_power
        if self.learning_rate_power == -0.5:
            pow_new, pow_old = jnp.sqrt(accum_new), jnp.sqrt(slots["accum"])
        else:
            pow_new, pow_old = accum_new ** p, slots["accum"] ** p
        sigma = (pow_new - pow_old) / lr
        linear = slots["linear"] + g - sigma * weights
        quadratic = pow_new / lr + 2 * adjusted_l2
        l1 = self.l1_regularization_strength
        l1_reg_adjust = jnp.clip(linear, -l1, l1)
        weights = (l1_reg_adjust - linear) / quadratic
        return weights, {"accum": accum_new, "linear": linear}


@dataclasses.dataclass(frozen=True)
class RMSprop(SparseOptimizer):
    learning_rate: float = 0.001
    rho: float = 0.9
    momentum: float = 0.0
    epsilon: float = 1e-7
    category = "rmsprop"

    def slot_shapes(self, dim):
        return {"accum": (dim,), "moment": (dim,)}

    def update_rows(self, weights, slots, grads, counts):
        accum = slots["accum"] * self.rho + grads * grads * (1 - self.rho)
        moment = slots["moment"] * self.momentum \
            + self.learning_rate * grads / jnp.sqrt(accum + self.epsilon)
        weights = weights - moment
        return weights, {"accum": accum, "moment": moment}


@dataclasses.dataclass(frozen=True)
class SGD(SparseOptimizer):
    learning_rate: float = 0.01
    momentum: float = 0.0
    nesterov: bool = False
    category = "sgd"

    def slot_shapes(self, dim):
        return {"moment": (dim,)}

    def update_rows(self, weights, slots, grads, counts):
        moment = slots["moment"] * self.momentum + self.learning_rate * grads
        if self.nesterov:
            weights = weights - (moment * self.momentum + self.learning_rate * grads)
        else:
            weights = weights - moment
        return weights, {"moment": moment}


@dataclasses.dataclass(frozen=True)
class Test(SparseOptimizer):
    """Deterministic flip-state optimizer for unit tests.

    Same contract as the reference's ``test`` optimizer
    (EmbeddingOptimizer.h:366-390): state flips between ``init`` and
    ``flip - state`` each update; weights += lr * grad / count + new_state.
    Because the expected value is computable client-side it lets tests verify
    exact server-side application under concurrency/dedup.
    """

    learning_rate: float = 0.1
    flip: float = 10000.0
    init: float = 0.0
    category = "test"

    def slot_shapes(self, dim):
        return {"flip_state": (1,)}

    def slot_init(self, name):
        return self.init

    def update_rows(self, weights, slots, grads, counts):
        state = self.flip - slots["flip_state"]
        counts = jnp.maximum(counts, 1).astype(weights.dtype)[:, None]
        weights = weights + self.learning_rate * grads / counts + state
        return weights, {"flip_state": state}


_REGISTRY = {
    cls.category: cls
    for cls in (Default, Adadelta, Adagrad, Adam, Adamax, Ftrl, RMSprop, SGD, Test)
}


def make_optimizer(config: Any) -> SparseOptimizer:
    """Build an optimizer from a SparseOptimizer, config dict, or name.

    Dict configs follow the reference's string-dict convention
    (exb.py:56-86): ``{"category": "adam", "learning_rate": 0.001, ...}``.
    """
    if isinstance(config, SparseOptimizer):
        return config
    if isinstance(config, str):
        config = {"category": config}
    config = dict(config)
    category = config.pop("category")
    if category not in _REGISTRY:
        raise ValueError(f"unknown optimizer category {category!r}; "
                         f"known: {sorted(_REGISTRY)}")
    cls = _REGISTRY[category]
    return cls(**coerce_fields(cls, config))
