"""Row initializers for embedding tables.

Capability parity with the reference's ``variable/EmbeddingInitializer.h``
(/root/reference/openembedding/variable/EmbeddingInitializer.h:1-97):
``constant``, ``uniform`` (minval/maxval) and ``normal`` (mean/stddev, with a
truncated variant). The reference initializes rows lazily on first pull using
``std::random_device`` (seeds unsupported); the TPU-native design initializes
eagerly at table creation with a JAX PRNG key — statistically equivalent,
deterministic under a seed, and XLA-friendly (one fused kernel instead of
per-row host work).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from ..utils.config import coerce_fields


class Initializer:
    """Base class. ``init(key, shape, dtype)`` materializes rows."""

    category: str = ""

    def init(self, key: jax.Array, shape, dtype) -> jax.Array:
        raise NotImplementedError

    def to_config(self) -> dict:
        out = {"category": self.category}
        out.update(dataclasses.asdict(self))
        return out


@dataclasses.dataclass(frozen=True)
class Constant(Initializer):
    value: float = 0.0
    category = "constant"

    def init(self, key, shape, dtype):
        del key
        return jnp.full(shape, self.value, dtype=dtype)


@dataclasses.dataclass(frozen=True)
class Uniform(Initializer):
    minval: float = -1.0
    maxval: float = 1.0
    category = "uniform"

    def init(self, key, shape, dtype):
        return jax.random.uniform(key, shape, dtype=jnp.float32,
                                  minval=self.minval,
                                  maxval=self.maxval).astype(dtype)


@dataclasses.dataclass(frozen=True)
class Normal(Initializer):
    mean: float = 0.0
    stddev: float = 1.0
    truncated: bool = False
    category = "normal"

    def init(self, key, shape, dtype):
        if self.truncated:
            # match the reference's rejection sampling to +/-2 stddev
            # (EmbeddingInitializer.h truncated path) via truncated_normal.
            x = jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype=jnp.float32)
        else:
            x = jax.random.normal(key, shape, dtype=jnp.float32)
        return (x * self.stddev + self.mean).astype(dtype)


_REGISTRY = {
    "constant": Constant,
    "uniform": Uniform,
    "normal": Normal,
}


def make_initializer(config: Any) -> Initializer:
    """Build an initializer from an Initializer, config dict, or name.

    Config dicts use the reference's string-dict convention
    (exb.py:25-53 style): ``{"category": "uniform", "minval": ..., ...}``.
    """
    if isinstance(config, Initializer):
        return config
    if isinstance(config, str):
        config = {"category": config}
    config = dict(config)
    category = config.pop("category")
    if category not in _REGISTRY:
        raise ValueError(f"unknown initializer category {category!r}; "
                         f"known: {sorted(_REGISTRY)}")
    cls = _REGISTRY[category]
    return cls(**coerce_fields(cls, config))
