"""High-level embedding API: specs + a collection of sharded variables.

This is the TPU-native counterpart of the reference's Python surface
(/root/reference/openembedding/tensorflow/exb.py):

* ``EmbeddingSpec`` ≈ ``embed.Embedding(...)`` constructor arguments
  (exb.py:388-443): ``input_dim=-1`` selects the unbounded hash-key space
  (exb.py:231-233 maps it to vocab 2^63), per-variable optimizer/initializer
  configs use the same string-dict convention (exb.py:25-86).
* ``EmbeddingCollection`` ≈ the Context + per-layer ``Variable`` machinery
  (exb.py:222-360): it assigns variable ids by registration order
  (WorkerContext.cpp:95-113), owns each variable's sharding layout over the
  mesh, and exposes the three data-plane verbs —

  - ``init(rng)``            ≈ create_storage + create_variable + initializer
  - ``pull(states, inputs)``  ≈ ``sparse_read`` → PullWeights for every layer
  - ``apply_gradients(states, inputs, row_grads)`` ≈ PushGradients +
    UpdateWeights for the whole model in one fused program. The reference's
    fake-gradient allreduce barrier (exb_ops.cpp:434-437) has no equivalent
    because the SPMD step is already synchronous.

The dense half of a model (MLPs, small `sparse_as_dense` embeddings —
exb.py:100-104) lives in ordinary flax params, replicated and data-parallel,
exactly like the reference keeps small embeddings as plain tf.Variables under
Horovod allreduce.

Everything is functional: states are pytrees, the collection itself is static
configuration (hashable, safe to close over in jit).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from .meta import (EmbeddingVariableMeta, ModelMeta, ModelVariableMeta,
                   UNBOUNDED_VOCAB)
from .optim.initializers import make_initializer
from .optim.optimizers import make_optimizer
from . import table as table_lib
from .parallel import sharded_table as st
from .parallel import sharded_hash as sh
from .parallel.mesh import MODEL_AXIS
from . import ragged


@dataclasses.dataclass(frozen=True)
class EmbeddingSpec:
    """Static description of one embedding variable (one reference Embedding
    layer, exb.py:388-420)."""

    name: str
    input_dim: int                   # -1 => unbounded hash-key space
    output_dim: int
    dtype: str = "float32"
    optimizer: Any = None            # None => collection default
    initializer: Any = None          # None => collection default
    num_shards: int = -1             # -1 => one shard per device (a2a plane)
    hash_capacity: int = 2**20       # reserve_items for hash variables
    layout: str = "mod"              # array-table row layout
    key_dtype: Optional[str] = None  # hash key storage; None resolves to
                                     # "wide" for hash variables — [.., 2]
                                     # int32 (lo, hi) pairs = the full
                                     # 64-bit space with x64 OFF (the
                                     # reference's default 2^63 key space,
                                     # Meta.h:44-46; pair queries via
                                     # hash_table.split64, plain int32/
                                     # int64 id columns widened on device).
                                     # "int32" is the explicit optimization
                                     # for small key spaces; "int64" needs
                                     # the global x64 flag
    plane: str = "a2a"               # "a2a" owner-routed | "psum" baseline
                                     # | "a2a+cache" (a2a + hot-row replica,
                                     # parallel/hot_cache.py)
                                     # | "a2a+grouped" (collection batches
                                     # same-shape tables into ONE exchange
                                     # per group per step,
                                     # parallel/grouped.py)
                                     # | "a2a+pipelined" (Trainer double-
                                     # buffers the exchange: batch N+1's
                                     # pull rides step N's program,
                                     # parallel/pipelined.py)
                                     # | "a2a+grouped+pipelined" (both)
    a2a_capacity: int = 0            # per-destination bucket rows; 0 = auto
    a2a_slack: float = 2.0           # auto bucket = slack * mean
    cache_k: int = 0                 # hot-row replica slots; 0 = default
    exchange_precision: str = "f32"  # pulled rows on the wire: f32 | bf16
                                     # (parallel/precision.py; a "+bf16"/
                                     # "+int8" plane suffix is shorthand)
    push_precision: str = "f32"      # pre-reduced grads on the wire:
                                     # f32 | bf16 | int8_ef (per-row-scale
                                     # int8 with an error-feedback
                                     # residual in the state pytree)
    cache_refresh_every: int = 64    # admission refresh period (steps)
    cache_decay: float = 0.8         # frequency-sketch decay per refresh
    pooling: Optional[str] = None    # sequence combiner: sum | mean | sqrtn;
                                     # inputs become [B, L] padded id matrices
                                     # (ragged.py; reference RaggedTensor
                                     # lookups, exb.py:315-321)

    def __post_init__(self):
        if self.key_dtype is None:
            # out-of-box hash variables hold the reference's full hashed
            # key space (2^62 ids) — int32 (2^31 ids) is opt-in
            object.__setattr__(self, "key_dtype",
                               "wide" if self.input_dim == -1 else "int32")
        # a "+bf16"/"+int8" plane suffix is shorthand for the
        # compressed-exchange rungs: normalize it into the precision
        # fields so spec.plane always names the BASE data plane
        # (parallel/precision.py; conflicts and illegal combinations
        # raise in st._resolve_precision)
        base, ep, pp = st._resolve_precision(
            self.plane, self.exchange_precision, self.push_precision)
        object.__setattr__(self, "plane", base)
        object.__setattr__(self, "exchange_precision", ep)
        object.__setattr__(self, "push_precision", pp)

    @property
    def use_hash(self) -> bool:
        return self.input_dim == -1

    def meta(self) -> EmbeddingVariableMeta:
        vocab = UNBOUNDED_VOCAB if self.use_hash else self.input_dim
        return EmbeddingVariableMeta(datatype=self.dtype,
                                     embedding_dim=self.output_dim,
                                     vocabulary_size=vocab)


class EmbeddingCollection:
    """All sparse variables of one model, sharded over one mesh.

    ``states`` (returned by :meth:`init`, threaded through ``pull`` /
    ``apply_gradients``) is a plain dict ``name -> TableState|HashTableState``
    — a pytree suitable for jit donation and checkpointing.
    """

    def __init__(self, specs, mesh: Mesh,
                 default_optimizer: Any = None,
                 default_initializer: Any = None):
        if default_optimizer is None:
            default_optimizer = {"category": "sgd", "learning_rate": 0.01}
        if default_initializer is None:
            default_initializer = dict(table_lib.DEFAULT_INITIALIZER)
        self.mesh = mesh
        self.specs: Dict[str, EmbeddingSpec] = {}
        # chunk-level dirty bitmaps for delta checkpoints (dirty.py);
        # empty until enable_dirty_tracking() — marking is then fed by
        # the Trainer's host loop and by eager apply_gradients calls
        self._dirty_trackers: Dict[str, Any] = {}
        self._variable_ids: Dict[str, int] = {}
        self._optimizers = {}
        self._initializers = {}
        self._shardings = {}
        for i, spec in enumerate(specs):
            if spec.name in self.specs:
                raise ValueError(f"duplicate embedding name {spec.name!r}")
            if spec.pooling is not None and spec.pooling not in ragged.POOLINGS:
                raise ValueError(
                    f"embedding {spec.name!r}: unknown pooling "
                    f"{spec.pooling!r}; known: {ragged.POOLINGS}")
            self.specs[spec.name] = spec
            self._variable_ids[spec.name] = i
            self._optimizers[spec.name] = make_optimizer(
                spec.optimizer if spec.optimizer is not None else default_optimizer)
            self._initializers[spec.name] = make_initializer(
                spec.initializer if spec.initializer is not None else default_initializer)
            if spec.use_hash:
                self._shardings[spec.name] = sh.make_hash_sharding_spec(
                    mesh, total_capacity=spec.hash_capacity,
                    num_shards=spec.num_shards, plane=spec.plane,
                    a2a_capacity=spec.a2a_capacity, a2a_slack=spec.a2a_slack,
                    key_width=64 if spec.key_dtype == "wide" else 32,
                    cache_k=spec.cache_k,
                    exchange_precision=spec.exchange_precision,
                    push_precision=spec.push_precision)
            else:
                self._shardings[spec.name] = st.make_sharding_spec(
                    spec.meta(), mesh, num_shards=spec.num_shards,
                    layout=spec.layout, plane=spec.plane,
                    a2a_capacity=spec.a2a_capacity, a2a_slack=spec.a2a_slack,
                    cache_k=spec.cache_k,
                    exchange_precision=spec.exchange_precision,
                    push_precision=spec.push_precision)

    # --- dirty tracking (delta checkpoints, checkpoint.py mode="delta") ----
    def enable_dirty_tracking(self, *, target_chunks: int = 1024,
                              names=None) -> None:
        """Arm chunk-level dirty bitmaps for every variable (idempotent).

        ``names``: restrict tracking to a subset of variables. ONLY for
        variables whose rows persist through their own path — the
        offload tier's ``ShardedOffloadedTable.persist`` is the case
        this exists for (its TrainState entry is a transient HBM cache;
        delta-chaining the cache would checkpoint residency noise, not
        the model). A delta save writes chunks for TRACKED variables
        only: an untracked variable that trains between the base and a
        restore silently reverts to its base rows — never exclude a
        variable something else doesn't durably own.

        Required before ``checkpoint.save_checkpoint(mode="delta")``:
        pushes mark chunks (the Trainer feeds every stepped batch's ids
        via :meth:`mark_dirty`; eager ``apply_gradients`` calls mark
        directly), and a delta save writes only the marked chunks —
        the reference's ICDE'23 incremental checkpoints from dirty
        tracking, generalized out of the offload tier (``dirty.py``).

        CUSTOM JITTED LOOPS: inside a jit the indices are tracers and
        cannot mark (the skip is deliberate and silent — marking at
        trace time would record once per COMPILE). A loop that jits its
        own step around ``apply_gradients`` must call
        ``collection.mark_dirty(batch["sparse"])`` host-side once per
        step, exactly as ``Trainer.train_step`` does — otherwise delta
        saves see nothing dirty and a chain restore silently reverts
        to the base.
        """
        from .dirty import make_array_tracker, make_hash_tracker
        if names is not None:
            unknown = set(names) - set(self.specs)
            if unknown:
                # a typo here would silently leave a variable untracked
                # and its trained rows reverting to base on a delta
                # restore — exactly the corruption mode above
                raise ValueError(
                    f"enable_dirty_tracking: unknown variable(s) "
                    f"{sorted(unknown)}; known: {sorted(self.specs)}")
        for name, spec in self.specs.items():
            if name in self._dirty_trackers:
                continue
            if names is not None and name not in names:
                continue
            if spec.use_hash:
                self._dirty_trackers[name] = make_hash_tracker(
                    name, spec.hash_capacity, target_chunks)
            else:
                self._dirty_trackers[name] = make_array_tracker(
                    name, spec.input_dim, target_chunks)

    @property
    def dirty_trackers(self) -> Dict[str, Any]:
        """``name -> DirtyTracker`` (empty unless tracking is enabled)."""
        return self._dirty_trackers

    def mark_dirty(self, sparse_inputs: Dict[str, Any]) -> None:
        """Mark the chunks a batch's pushes touched (host-side; a no-op
        unless tracking is enabled). Safe to over-mark — ids whose
        gradient was zero just cost delta bytes. Tracer inputs (an
        outer jit trace) are skipped: the Trainer marks from the HOST
        batch once per step instead, so marks count per step, not per
        compile."""
        if not self._dirty_trackers:
            return
        from . import hash_table as hash_lib
        for name, idx in sparse_inputs.items():
            tracker = self._dirty_trackers.get(name)
            if tracker is None or idx is None:
                continue
            if isinstance(idx, jax.core.Tracer):
                continue
            arr = np.asarray(jax.device_get(idx)) \
                if isinstance(idx, jax.Array) else np.asarray(idx)
            spec = self.specs[name]
            if spec.use_hash:
                if spec.key_dtype == "wide" and arr.ndim >= 2 \
                        and arr.shape[-1] == 2:
                    keys = hash_lib.join64(arr.reshape(-1, 2))
                else:
                    keys = arr.astype(np.int64).ravel()
                tracker.mark_keys(keys)
            else:
                ids = arr.astype(np.int64).ravel()
                tracker.mark_rows(ids[(ids >= 0) & (ids < spec.input_dim)])

    # --- introspection -----------------------------------------------------
    def variable_id(self, name: str) -> int:
        return self._variable_ids[name]

    def optimizer(self, name: str):
        return self._optimizers[name]

    def initializer(self, name: str):
        return self._initializers[name]

    def sharding_spec(self, name: str):
        return self._shardings[name]

    def cached_names(self) -> tuple:
        """Variables on the ``"a2a+cache"`` plane (hot-row replica)."""
        return tuple(name for name, s in self._shardings.items()
                     if s.is_cached)

    def grouped_names(self) -> tuple:
        """Variables on a grouped plane (collection-batched exchange,
        ``parallel/grouped.py``)."""
        return tuple(name for name, s in self._shardings.items()
                     if s.is_grouped)

    def pipelined_names(self) -> tuple:
        """Variables on a pipelined plane (Trainer-level double-buffered
        exchange schedule, ``parallel/pipelined.py``)."""
        return tuple(name for name, s in self._shardings.items()
                     if s.is_pipelined)

    def make_hot_cache_manager(self, name: str):
        """Admission/refresh driver for one cached variable (the Trainer
        builds one per ``plane="a2a+cache"`` spec automatically)."""
        from .parallel import hot_cache
        spec = self.specs[name]
        sspec = self._shardings[name]
        if not sspec.is_cached:
            raise ValueError(f"{name!r} is not on the a2a+cache plane")
        return hot_cache.HotCacheManager(
            mesh=self.mesh, spec=sspec, k=sspec.cache_k,
            refresh_every=spec.cache_refresh_every,
            decay=spec.cache_decay, name=name)

    def model_meta(self, model_sign: str = "", model_uri: str = "") -> ModelMeta:
        variables = [
            ModelVariableMeta(meta=self.specs[name].meta(),
                              variable_id=self._variable_ids[name],
                              name=name)
            for name in self.specs
        ]
        variables.sort(key=lambda v: v.variable_id)
        # top-level num_shards is the max over variables (informational);
        # the exact per-variable counts ride in extra for mixed-plane models
        num_shards = max((s.num_shards for s in self._shardings.values()),
                         default=1)
        meta = ModelMeta(model_sign=model_sign, model_uri=model_uri,
                         variables=variables, num_shards=num_shards)
        meta.extra["variable_num_shards"] = {
            name: s.num_shards for name, s in self._shardings.items()}
        poolings = {name: s.pooling for name, s in self.specs.items()
                    if s.pooling}
        if poolings:
            # serving rebuilds specs from the meta alone; pooled lookups
            # must keep their combiner (registry._specs_from_meta)
            meta.extra["variable_pooling"] = poolings
        return meta

    # --- state lifecycle ---------------------------------------------------
    def init(self, rng: Optional[jax.Array] = None,
             only: Optional[Any] = None) -> Dict[str, Any]:
        """Materialize variables (each sharded over the mesh model axis).

        ``only`` restricts to a subset of names (the checkpoint loader skips
        device init for variables it overwrites host-side).
        """
        if rng is None:
            rng = jax.random.PRNGKey(0)

        # one jitted program for ALL variables: per-variable table creation
        # would compile (and on a remote-compile TPU link, round-trip) one
        # program per variable — 2F programs for an F-feature model
        def _create_all(key):
            states = {}
            for name, spec in self.specs.items():
                if only is not None and name not in only:
                    continue
                sub = jax.random.fold_in(key, self._variable_ids[name])
                if spec.use_hash:
                    states[name] = sh.create_sharded_hash_table(
                        spec.meta(), self._optimizers[name],
                        mesh=self.mesh,
                        spec=self._shardings[name], rng=sub,
                        key_dtype=jnp.int32 if spec.key_dtype == "wide"
                        else jnp.dtype(spec.key_dtype),
                        wrap_cache=False)
                else:
                    states[name] = st.create_sharded_table(
                        spec.meta(), self._optimizers[name],
                        self._initializers[name], mesh=self.mesh,
                        spec=self._shardings[name], rng=sub,
                        wrap_cache=False)
            return states

        states = jax.jit(_create_all)(rng)
        # hot-row replicas attach eagerly (all-pad: zero hits until the
        # first HotCacheManager refresh admits keys)
        for name in states:
            states[name] = self.wrap_hot_cache(name, states[name])
        return states

    def wrap_hot_cache(self, name: str, table_state):
        """Attach derived per-plane state to a bare table state:
        an empty (all-pad) hot-row replica on the ``"a2a+cache"`` plane,
        an empty int8_ef push residual (``precision.EFState``) for
        ``push_precision="int8_ef"`` variables; pass-through otherwise.
        The checkpoint loader and serving restore use this too — both
        wrappers are derived state, never checkpointed (a restore
        forfeits at most one step of error feedback)."""
        from .parallel import hot_cache, precision
        sspec = self._shardings[name]
        # single-shard meshes have no wire: the push runs the exact
        # masked-local program and returns a bare table, so attaching a
        # wrapper here would flip the state pytree STRUCTURE after the
        # first push (a forced retrace under the donated step jit)
        if getattr(sspec, "is_int8_ef", False) and sspec.num_shards > 1 \
                and not isinstance(table_state, precision.EFState):
            spec = self.specs[name]
            wide = spec.use_hash and spec.key_dtype == "wide"
            sentinel, key_dtype = precision.ef_key_space(
                use_hash=spec.use_hash, wide=wide,
                key_dtype=None if wide or not spec.use_hash
                else spec.key_dtype)
            return precision.empty_ef(table_state, dim=spec.output_dim,
                                      wide=wide, sentinel=sentinel,
                                      key_dtype=key_dtype)
        return hot_cache.attach_empty(table_state, sspec, self.mesh)

    def state_shardings(self) -> Dict[str, Any]:
        """NamedShardings for every state leaf (for jit in/out_shardings)."""
        out = {}
        for name, spec in self.specs.items():
            sspec = self._shardings[name]
            mod = sh if spec.use_hash else st
            specs = mod.state_specs(self._optimizers[name],
                                    spec.output_dim, sspec)
            out[name] = st.state_shardings(specs, self.mesh)
        return out

    # --- data plane --------------------------------------------------------
    def pull(self, states: Dict[str, Any], inputs: Dict[str, jnp.ndarray],
             *, batch_sharded: bool = True,
             read_only: bool = False,
             serving_rows: bool = False) -> Dict[str, jnp.ndarray]:
        """Lookup rows for every (present) input column.

        ``inputs``: name -> integer indices of any shape; returns name ->
        rows shaped ``indices.shape + (dim,)``. Differentiation happens with
        respect to the *returned rows* (pass their grads to
        :meth:`apply_gradients`), not the tables — mirroring the reference's
        custom PullWeights gradient (exb.py:89-97). ``read_only`` selects the
        serving contract: unknown hash keys return zeros instead of init rows
        (reference EmbeddingPullOperator read_only get_weights path).
        ``serving_rows`` selects the ROW contract of the serving data plane:
        one row per index (pair), no pooling, and any trailing dim of 2 on a
        wide spec IS a pair axis — the shape a routing client fans out is
        always a flat pair list, never a ``[B, L=2]`` sequence (a pooled
        spec's training-side heuristic would misread it).
        """
        widened = {
            name: self._widen(self.specs[name], idx,
                              pair_ndim=2 if serving_rows else None)
            for name, idx in inputs.items()}
        # grouped-plane columns batch into ONE exchange per group
        # (parallel/grouped.py) instead of one pipeline per table; the
        # raw rows come back per name and pool below like any other
        grouped_idx = {name: idx for name, idx in widened.items()
                       if self._shardings[name].is_grouped}
        raw = {}
        if grouped_idx:
            from .parallel import grouped
            raw = grouped.pull_grouped(self, states, grouped_idx,
                                       read_only=read_only,
                                       batch_sharded=batch_sharded)
        rows = {}
        for name, idx in widened.items():
            spec = self.specs[name]
            if name in raw:
                r = raw[name]
            elif spec.use_hash:
                r = sh.pull_sharded(
                    states[name], idx,
                    None if read_only else self._initializers[name],
                    mesh=self.mesh, spec=self._shardings[name],
                    batch_sharded=batch_sharded)
            else:
                r = st.pull_sharded(
                    states[name], idx, mesh=self.mesh,
                    spec=self._shardings[name], batch_sharded=batch_sharded)
            if spec.pooling and not serving_rows:
                # wide sequence features carry [B, L, 2] pair ids; the
                # combiner counts validity on the hi word (ragged.py)
                r = ragged.pool_rows(r, idx, spec.pooling,
                                     ragged.pad_id_for(spec),
                                     self._pool_vocab(spec),
                                     wide=spec.key_dtype == "wide")
            rows[name] = r
        return rows

    def _pool_vocab(self, spec: EmbeddingSpec) -> Optional[int]:
        return None if spec.use_hash else spec.input_dim

    def _widen(self, spec: EmbeddingSpec, idx,
               pair_ndim: Optional[int] = None) -> jnp.ndarray:
        """Bridge plain id columns onto wide (pair-keyed) tables.

        Wide tables take ``[..., 2]`` pairs; a NARROW integer input
        (flat ``[B]`` ids, or a ``[B, L]`` padded matrix for pooled
        features) is widened so int32/int64 pipelines run unchanged
        against the default wide key space. HOST int64 columns are split
        on host (``hash_table.split64``) BEFORE any jnp conversion — with
        x64 off ``jnp.asarray`` would silently truncate them to int32 and
        address the wrong rows; device arrays widen on device
        (``hash_table.widen_ids``). Inputs already shaped as pairs pass
        through. Ambiguity rule: a trailing dim of 2 IS a pair axis (for
        pooled specs only at ndim >= 3, since their ``[B, L=2]`` matrices
        are sequences) — feed genuinely 2-wide narrow shapes through
        ``split64`` instead. Callers with an unambiguous wire contract
        (the serving row plane, whose queries are always flat pair lists)
        pass ``pair_ndim=2`` to override the pooled-spec heuristic.
        """
        if not spec.use_hash or spec.key_dtype != "wide":
            return idx
        from . import hash_table as hash_lib
        if pair_ndim is None:
            pair_ndim = 3 if spec.pooling else 2
        if not isinstance(idx, jax.Array):
            arr = np.asarray(idx)
            is_pairs = arr.ndim >= pair_ndim and arr.shape[-1] == 2
            if arr.dtype.kind in "iu" and arr.dtype.itemsize == 8:
                if is_pairs:
                    # 64-bit-typed pair WORDS: values must fit int32 (a
                    # raw 64-bit id belongs in split64, not a pair word)
                    if arr.size and (arr.max() > np.iinfo(np.int32).max
                                     or arr.min() < np.iinfo(np.int32).min):
                        raise ValueError(
                            f"embedding {spec.name!r}: pair words exceed "
                            "int32 — pass hash_table.split64(ids), not "
                            "raw 64-bit ids shaped as pairs")
                    return jnp.asarray(arr.astype(np.int32))
                # host split keeps full 64-bit width with x64 OFF; the
                # int64 sentinel (INT64_MIN) splits into the EMPTY band,
                # staying invalid by the hi-word rule
                return jnp.asarray(hash_lib.split64(arr))
            idx = jnp.asarray(arr)
        if idx.ndim >= pair_ndim and idx.shape[-1] == 2:
            return idx
        return hash_lib.widen_ids(idx)

    def apply_gradients(self, states: Dict[str, Any],
                        inputs: Dict[str, jnp.ndarray],
                        row_grads: Dict[str, jnp.ndarray],
                        *, batch_sharded: bool = True) -> Dict[str, Any]:
        """Push+update for every column present in ``row_grads``.

        ``row_grads[name]`` has the shape of the pulled rows. Untouched
        variables keep their state object unchanged.
        """
        # delta-checkpoint dirty marks for EAGER pushes (tracer inputs —
        # the jitted Trainer step — skip; the Trainer marks host-side)
        self.mark_dirty({n: inputs.get(n) for n in row_grads})
        new_states = dict(states)
        grouped_idx: Dict[str, jnp.ndarray] = {}
        grouped_grads: Dict[str, jnp.ndarray] = {}
        for name, g in row_grads.items():
            spec = self.specs[name]
            idx_in = self._widen(spec, inputs[name])
            if spec.pooling:
                # pooled features carry [B, dim] grads; expand with the
                # pooling VJP so each valid slot updates like a raw lookup
                g = ragged.expand_pooled_grads(
                    g, idx_in, spec.pooling, ragged.pad_id_for(spec),
                    self._pool_vocab(spec),
                    wide=spec.key_dtype == "wide")
            if self._shardings[name].is_grouped:
                # collection-batched push: one pre-reduced exchange per
                # GROUP (parallel/grouped.py), per-table optimizers
                # applied server-side
                grouped_idx[name] = idx_in
                grouped_grads[name] = g
                continue
            if spec.use_hash:
                new_states[name] = sh.apply_gradients_sharded(
                    states[name], self._optimizers[name],
                    self._initializers[name], idx_in, g,
                    mesh=self.mesh, spec=self._shardings[name],
                    batch_sharded=batch_sharded)
            else:
                new_states[name] = st.apply_gradients_sharded(
                    states[name], self._optimizers[name], idx_in, g,
                    mesh=self.mesh, spec=self._shardings[name],
                    batch_sharded=batch_sharded)
        if grouped_idx:
            from .parallel import grouped
            new_states.update(grouped.apply_gradients_grouped(
                self, states, grouped_idx, grouped_grads,
                batch_sharded=batch_sharded))
        return new_states
