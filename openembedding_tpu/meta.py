"""Model / variable metadata.

TPU-native re-design of the reference's ``variable/Meta.h`` (see
/root/reference/openembedding/variable/Meta.h:1-222): the same logical metadata
(datatype, embedding_dim, vocabulary_size, model signature, per-variable list,
format version) round-tripped through JSON so checkpoints are self-describing,
but without the master-tree plumbing — metadata travels inside checkpoint
directories and in-process registries instead of a TCP master.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any

import numpy as np

# Checkpoint/meta format version. The reference uses "0.2"
# (/root/reference/openembedding/variable/Meta.h:109-111); we start our own
# lineage at "tpu-1" to make cross-loading errors explicit.
# "tpu-2": per-variable storage dtypes recorded in extra["storage_dtypes"]
# so at-rest bf16 dumps (numpy-serialized as opaque '<V2' descrs, incl.
# through the compress.py-framed .npyz streams) decode under their TRUE
# dtype on load — and upcast transparently into f32 targets. Readers
# accept every version in META_COMPAT_VERSIONS: an old f32 "tpu-1"
# checkpoint loads unchanged.
META_FORMAT_VERSION = "tpu-2"
META_COMPAT_VERSIONS = ("tpu-1", "tpu-2")

# The reference treats vocabulary_size >= 2**63 as "unbounded key space ->
# use a hash table" (Meta.h:44-46). We keep the same convention.
UNBOUNDED_VOCAB = 2**63

_DTYPE_NAMES = {
    "float32": np.float32,
    "float64": np.float64,
    "bfloat16": None,  # resolved lazily to jnp.bfloat16 to avoid importing jax here
}


def normalize_dtype_name(dtype: Any) -> str:
    """Canonical string name for a supported embedding dtype."""
    name = np.dtype(dtype).name if not isinstance(dtype, str) else dtype
    if name not in _DTYPE_NAMES:
        raise ValueError(f"unsupported embedding dtype {name!r}; "
                         f"supported: {sorted(_DTYPE_NAMES)}")
    return name


@dataclasses.dataclass(frozen=True)
class EmbeddingVariableMeta:
    """Mirror of the reference's EmbeddingVariableMeta (Meta.h:20-60)."""

    datatype: str = "float32"
    embedding_dim: int = 0
    vocabulary_size: int = 0  # UNBOUNDED_VOCAB => hash table

    def __post_init__(self):
        object.__setattr__(self, "datatype", normalize_dtype_name(self.datatype))

    @property
    def use_hash_table(self) -> bool:
        return self.vocabulary_size >= UNBOUNDED_VOCAB

    def to_json(self) -> dict:
        return {
            "datatype": self.datatype,
            "embedding_dim": int(self.embedding_dim),
            "vocabulary_size": int(self.vocabulary_size),
        }

    @classmethod
    def from_json(cls, obj: dict) -> "EmbeddingVariableMeta":
        return cls(datatype=obj["datatype"],
                   embedding_dim=int(obj["embedding_dim"]),
                   vocabulary_size=int(obj["vocabulary_size"]))


@dataclasses.dataclass(frozen=True)
class ModelVariableMeta:
    """Per-variable entry in a model meta (reference Meta.h:62-88)."""

    meta: EmbeddingVariableMeta
    variable_id: int
    name: str = ""

    def to_json(self) -> dict:
        out = self.meta.to_json()
        out["variable_id"] = int(self.variable_id)
        out["name"] = self.name
        return out

    @classmethod
    def from_json(cls, obj: dict) -> "ModelVariableMeta":
        return cls(meta=EmbeddingVariableMeta.from_json(obj),
                   variable_id=int(obj["variable_id"]),
                   name=obj.get("name", ""))


class ModelStatus:
    """Serving model lifecycle states (reference Meta.h / ModelController)."""

    CREATING = "CREATING"
    NORMAL = "NORMAL"
    DELETING = "DELETING"
    ERROR = "ERROR"


@dataclasses.dataclass
class ModelMeta:
    """Model-level metadata: signature, variables, status.

    Mirrors the reference's ModelOfflineMeta/ModelMeta JSON (Meta.h:90-180):
    ``model_sign`` is the serving signature ("<uuid>-<version>"), the variable
    list is ordered by variable_id, and ``version`` guards format drift.
    """

    model_sign: str = ""
    model_uri: str = ""
    model_status: str = ModelStatus.NORMAL
    model_error: str = ""
    variables: list = dataclasses.field(default_factory=list)
    num_shards: int = 1
    version: str = META_FORMAT_VERSION
    extra: dict = dataclasses.field(default_factory=dict)

    def to_json(self) -> dict:
        return {
            "version": self.version,
            "model_sign": self.model_sign,
            "model_uri": self.model_uri,
            "model_status": self.model_status,
            "model_error": self.model_error,
            "num_shards": int(self.num_shards),
            "variables": [v.to_json() for v in self.variables],
            "extra": self.extra,
        }

    @classmethod
    def from_json(cls, obj: dict) -> "ModelMeta":
        version = obj.get("version", "")
        if version not in META_COMPAT_VERSIONS:
            raise ValueError(
                f"checkpoint meta version {version!r} is not one of "
                f"{META_COMPAT_VERSIONS} (writer newer than this reader?)")
        return cls(
            model_sign=obj.get("model_sign", ""),
            model_uri=obj.get("model_uri", ""),
            model_status=obj.get("model_status", ModelStatus.NORMAL),
            model_error=obj.get("model_error", ""),
            num_shards=int(obj.get("num_shards", 1)),
            variables=[ModelVariableMeta.from_json(v) for v in obj.get("variables", [])],
            version=version,
            extra=obj.get("extra", {}),
        )

    def dumps(self) -> str:
        # ensure_ascii=False: variable names go into file paths verbatim, so
        # the meta must carry the same UTF-8 bytes (the native loader reads
        # them raw, it does not decode \\u escapes)
        return json.dumps(self.to_json(), indent=2, sort_keys=True,
                          ensure_ascii=False)

    @classmethod
    def loads(cls, text: str) -> "ModelMeta":
        return cls.from_json(json.loads(text))
