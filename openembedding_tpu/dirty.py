"""Chunk-level dirty tracking for incremental (delta) checkpoints.

Generalization of the offload tier's ``_book``/``_dirty`` machinery
(``offload.ShardedOffloadedTable``): one reusable bitmap that ARRAY
tables, HASH tables, and their co-indexed optimizer slots all feed, so
``checkpoint.save_checkpoint(mode="delta")`` can write only the chunks
that changed since the last save — the reference's ICDE'23 incremental
checkpoints from dirty tracking (PmemEmbeddingTable.h:285-328), lifted
out of the PMem tier into the whole-model checkpoint plane.

Granularity is a CHUNK of rows, not a row: at north-star vocab a per-row
bitmap is GBs and a per-row delta file is an id-per-row index; chunks
keep the bitmap O(vocab / rows_per_chunk) and make every delta file a
run of contiguous row ranges (sequential IO on both ends). The offload
tier uses ``rows_per_chunk=1`` (its writeback scatter is already
row-exact and its bitmap already row-sized).

Mapping:

* array tables: logical row id -> chunk ``id // rows_per_chunk``
  (:meth:`DirtyTracker.mark_rows`); a delta chunk is the contiguous
  logical range ``[c * R, min((c+1) * R, vocab))``.
* hash tables: 64-bit key -> chunk ``key % num_chunks``
  (:meth:`DirtyTracker.mark_keys`); a delta chunk is the set of live
  keys whose joined 64-bit value falls in it. Stable across key-width
  migrations (the owner rule uses the same joined value).
* optimizer slots are co-indexed with their weights — the same chunk
  marks cover them; a delta writes weights AND slots for dirty chunks.

Thread discipline (graftrace): marks land from the Trainer's step loop
while a delta save's snapshot/clear runs on the caller (or a writer
joins/restores on failure) — every bitmap access goes through one lock.
``lock=`` lets an owner with an existing book (the offload ``_book``
RLock) share it so its dirty marks stay atomic with its residency
bookkeeping, exactly as before the refactor.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .analysis.concurrency import make_lock, sync_point


class DirtyTracker:
    """Chunk-granular dirty bitmap with an exact dirty count.

    All methods are thread-safe under the tracker's lock (or the shared
    lock passed at construction). Over-marking is always safe — a chunk
    marked dirty that did not change costs delta bytes, never
    correctness — so producers may mark conservatively (e.g. every batch
    id, including ids whose gradient was zero).
    """

    def __init__(self, num_chunks: int, *, rows_per_chunk: int = 1,
                 name: str = "", lock=None):
        if num_chunks <= 0:
            raise ValueError(f"num_chunks must be positive, got {num_chunks}")
        if rows_per_chunk <= 0:
            raise ValueError(
                f"rows_per_chunk must be positive, got {rows_per_chunk}")
        self.num_chunks = int(num_chunks)
        self.rows_per_chunk = int(rows_per_chunk)
        self.name = name
        self._bits = np.zeros(self.num_chunks, bool)
        self._count = 0
        # make_lock: plain Lock unless OE_REPORT_TRACE_LOCKS arms the
        # graftrace runtime detector (analysis/concurrency.py). A shared
        # lock may be an RLock (offload passes its _book) — only ``with``
        # acquire/release is used, so either kind works.
        self._lock = lock if lock is not None \
            else make_lock(f"dirty.{name or 'tracker'}")

    # --- mapping -----------------------------------------------------------
    def chunks_of_rows(self, ids) -> np.ndarray:
        """Chunk index for each logical row id (out-of-range ids are the
        caller's concern; :meth:`mark_chunks` drops them)."""
        ids = np.asarray(ids, np.int64).ravel()
        if self.rows_per_chunk == 1:
            return ids
        return ids // self.rows_per_chunk

    def chunks_of_keys(self, keys64) -> np.ndarray:
        """Chunk index for 64-bit hash keys: nonnegative ``key % n``
        (numpy's mod of a negative int by a positive is nonnegative, so
        negative keys land in a valid chunk)."""
        keys = np.asarray(keys64, np.int64).ravel()
        return keys % np.int64(self.num_chunks)

    def chunk_row_range(self, chunk: int, vocab: int):
        """Logical row range ``[lo, hi)`` of one array-table chunk."""
        lo = int(chunk) * self.rows_per_chunk
        return lo, min(lo + self.rows_per_chunk, int(vocab))

    # --- marking -----------------------------------------------------------
    def mark_rows(self, ids) -> None:
        self.mark_chunks(self.chunks_of_rows(ids))

    def mark_keys(self, keys64) -> None:
        self.mark_chunks(self.chunks_of_keys(keys64))

    def mark_chunks(self, chunks) -> None:
        chunks = np.asarray(chunks, np.int64).ravel()
        chunks = chunks[(chunks >= 0) & (chunks < self.num_chunks)]
        if not chunks.size:
            return
        # interleaving marker OUTSIDE the lock: a gated test parks the
        # marking thread here without wedging the bitmap for others
        # (graftproto dirty_tracker model action `mark`)
        sync_point("dirty.mark")
        with self._lock:
            fresh = chunks[~self._bits[chunks]]
            if fresh.size:
                fresh = np.unique(fresh)
                self._bits[fresh] = True
                self._count += int(fresh.size)

    def mark_all(self) -> None:
        with self._lock:
            self._bits[:] = True
            self._count = self.num_chunks

    # --- clearing / snapshots ----------------------------------------------
    def clear_chunks(self, chunks) -> None:
        chunks = np.asarray(chunks, np.int64).ravel()
        chunks = chunks[(chunks >= 0) & (chunks < self.num_chunks)]
        if not chunks.size:
            return
        with self._lock:
            set_ = chunks[self._bits[chunks]]
            if set_.size:
                set_ = np.unique(set_)
                self._bits[set_] = False
                self._count -= int(set_.size)

    def clear_all(self) -> None:
        with self._lock:
            self._bits[:] = False
            self._count = 0

    def dirty_chunks(self) -> np.ndarray:
        """Sorted dirty chunk ids (a snapshot; bits stay set)."""
        with self._lock:
            return np.nonzero(self._bits)[0]

    def snapshot_clear(self) -> np.ndarray:
        """Atomically take the dirty set and clear it — the delta writer's
        claim. On a FAILED write the caller must :meth:`restore` the
        snapshot so the next save re-covers those chunks (marks landing
        during the failed write are preserved either way: clearing is
        exact-set, not wholesale)."""
        with self._lock:
            chunks = np.nonzero(self._bits)[0]
            self._bits[:] = False
            self._count = 0
        sync_point("dirty.snapshot")
        return chunks

    def restore(self, chunks) -> None:
        """Re-mark a failed writer's snapshot (over-marking chunks that
        were re-dirtied meanwhile is harmless)."""
        sync_point("dirty.restore")
        self.mark_chunks(chunks)

    def mask_chunks(self, chunks) -> np.ndarray:
        """Dirty bit for each chunk index (out-of-range reads as clean)."""
        chunks = np.asarray(chunks, np.int64).ravel()
        ok = (chunks >= 0) & (chunks < self.num_chunks)
        out = np.zeros(chunks.shape, bool)
        with self._lock:
            out[ok] = self._bits[chunks[ok]]
        return out

    def mask_rows(self, ids) -> np.ndarray:
        return self.mask_chunks(self.chunks_of_rows(ids))

    def __getitem__(self, ids):
        """Row-indexed dirty read — the pre-refactor ``_dirty[ids]``
        bitmap syntax the offload tier (and its tests) used."""
        out = self.mask_rows(ids)
        if isinstance(ids, (int, np.integer)):
            return bool(out[0])
        return out

    # --- introspection -----------------------------------------------------
    @property
    def dirty_count(self) -> int:
        with self._lock:
            return self._count

    @property
    def nbytes(self) -> int:
        """Bitmap bytes (graftwatch host-memory ledger)."""
        return int(self._bits.nbytes)

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return (f"DirtyTracker({self.name!r}, chunks={self.num_chunks}, "
                f"rows_per_chunk={self.rows_per_chunk}, "
                f"dirty={self.dirty_count})")


def make_array_tracker(name: str, vocab: int,
                       target_chunks: int = 1024,
                       lock=None) -> DirtyTracker:
    """Tracker for a bounded (array) variable: ~``target_chunks`` chunks
    of contiguous logical rows (at least one row per chunk)."""
    vocab = max(1, int(vocab))
    rows = max(1, -(-vocab // max(1, int(target_chunks))))
    return DirtyTracker(-(-vocab // rows), rows_per_chunk=rows,
                        name=name, lock=lock)


def make_hash_tracker(name: str, capacity: int,
                      target_chunks: int = 1024,
                      lock=None) -> DirtyTracker:
    """Tracker for a hash variable: key-space partitioned into
    ``min(target_chunks, capacity)`` chunks by ``key % n``."""
    n = max(1, min(int(target_chunks), max(1, int(capacity))))
    return DirtyTracker(n, name=name, lock=lock)
