"""Pallas TPU kernel: sparse row gather (embedding pull) with manual DMA.

The hot op of this framework is "fetch B*F scattered rows from a [V, D]
table in HBM" — the job the reference hand-writes in its C++ pull pipeline
(server row copies + response scatter, EmbeddingPullOperator.cpp:149-252).
XLA's native gather is strong on TPU (and remains the default pull path);
this kernel is the native-kernel form of the same op and the scaffold for
fusions XLA cannot express (gather + probe, gather + on-the-fly dedup):

* the index vector rides **scalar prefetch** (PrefetchScalarGridSpec) so
  row addresses are known before the body runs;
* the table stays in **HBM** (``pltpu.ANY``); each grid step issues R
  parallel row DMAs HBM->VMEM scratch (R in flight hides latency), waits,
  masks invalid ids to zero rows, and writes the output block;
* invalid ids (< 0 or >= V) are clamped for the DMA and zeroed in the
  body — the framework-wide invalid-index contract.

``interpret=True`` runs on CPU (tests); on TPU it compiles to a Mosaic
pipeline. The table's row dimension must be lane-aligned (a multiple of
128): padding inside the call would materialize a full padded table copy
per gather. Use :func:`pad_table` ONCE at table-creation time if the model
dim is ragged.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

ROWS_PER_STEP = 8  # DMAs in flight per grid step (one output sublane tile)


def _gather_kernel(idx_ref, table_ref, out_ref, scratch, sems):
    i = pl.program_id(0)
    vocab = idx_ref[-1]
    for r in range(ROWS_PER_STEP):
        row = idx_ref[i * ROWS_PER_STEP + r]
        safe = jnp.clip(row, 0, vocab - 1)
        pltpu.make_async_copy(
            table_ref.at[pl.dslice(safe, 1), :],
            scratch.at[pl.dslice(r, 1), :],
            sems.at[r],
        ).start()
    for r in range(ROWS_PER_STEP):
        row = idx_ref[i * ROWS_PER_STEP + r]
        safe = jnp.clip(row, 0, vocab - 1)
        pltpu.make_async_copy(
            table_ref.at[pl.dslice(safe, 1), :],
            scratch.at[pl.dslice(r, 1), :],
            sems.at[r],
        ).wait()
        valid = (row >= 0) & (row < vocab)
        out_ref[pl.dslice(r, 1), :] = jnp.where(
            valid, scratch[pl.dslice(r, 1), :], 0.0).astype(out_ref.dtype)


def pad_table(table: jnp.ndarray) -> jnp.ndarray:
    """Pad the row dim to the 128-lane boundary (do this ONCE at table
    creation, not per lookup — the copy is table-sized)."""
    dim = table.shape[1]
    dpad = -(-dim // 128) * 128
    if dpad == dim:
        return table
    return jnp.pad(table, ((0, 0), (0, dpad - dim)))


@functools.partial(jax.jit, static_argnames=("interpret",))
def gather_rows(table: jnp.ndarray, indices: jnp.ndarray,
                *, interpret: bool = False) -> jnp.ndarray:
    """rows[i] = table[indices[i]] with zero rows for invalid ids.

    Drop-in for the gather inside ``table.pull`` — same contract, Pallas
    manual-DMA pipeline instead of XLA gather. ``indices`` is [n] int;
    returns [n, dim] in the table dtype. The table's row dim must be a
    multiple of 128 (see :func:`pad_table`).
    """
    n = indices.shape[0]
    vocab, dim = table.shape
    if dim % 128:
        raise ValueError(
            f"table row dim {dim} is not lane-aligned; pad the TABLE once "
            "with pallas_gather.pad_table (padding per lookup would copy "
            "the whole table every call)")
    dpad = dim
    npad = -(-n // ROWS_PER_STEP) * ROWS_PER_STEP
    # bounds-check in the ORIGINAL dtype: an int64 id >= 2^32 must become an
    # invalid (-1) row, not wrap onto a real one through the int32 cast
    valid = (indices >= 0) & (indices < vocab)
    idx = jnp.where(valid, indices, -1).astype(jnp.int32)
    if npad != n:
        idx = jnp.pad(idx, (0, npad - n), constant_values=-1)
    # the kernel needs the vocab bound; smuggle it as the last prefetch slot
    idx_plus = jnp.concatenate([idx, jnp.asarray([vocab], jnp.int32)])

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(npad // ROWS_PER_STEP,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],  # table in HBM
        out_specs=pl.BlockSpec((ROWS_PER_STEP, dpad),
                               lambda i, idx_ref: (i, 0)),
        scratch_shapes=[
            pltpu.VMEM((ROWS_PER_STEP, dpad), table.dtype),
            pltpu.SemaphoreType.DMA((ROWS_PER_STEP,)),
        ],
    )
    out = pl.pallas_call(
        _gather_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((npad, dpad), table.dtype),
        interpret=interpret,
    )(idx_plus, table)
    return out[:n, :dim]
