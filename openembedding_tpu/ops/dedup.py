"""Static-shape index dedup + gradient combine.

The reference dedups indices client-side before every pull
(/root/reference/openembedding/server/EmbeddingPullOperator.cpp:60-84 via
EasyHashMap) and pre-sums duplicate-key gradients with counts before every
push (EmbeddingPushOperator.cpp:29-62, then MpscGradientReducer on the
server). Under XLA everything must be static-shape, so the TPU-native
equivalent is capacity-padded: ``jnp.unique(..., size=capacity)`` plus
scatter-add segment combines. Worst case capacity == batch size, so the
default is exact; callers may pass a smaller capacity based on measured batch
uniqueness (the reference measures this too: laboratory/benchmark/analyze.py).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

# Padding sentinel for empty unique slots. Indices/keys are remapped away from
# this value by callers when the key space could include it.
FILL = jnp.iinfo(jnp.int32).min


def unique_indices(indices: jnp.ndarray, capacity: int | None = None,
                   fill_value: int = FILL
                   ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Deduplicate a flat index vector into a fixed-capacity buffer.

    Returns ``(uniq [capacity], inverse [n], valid [capacity])`` where
    ``uniq[inverse[i]] == indices[i]`` and padding slots hold ``fill_value``.
    Equivalent of the reference's ``exb_unique_indices`` C-ABI helper
    (c_api.cc:220-231), reshaped for XLA: sorted, padded, mask instead of a
    dynamic length.

    CAUTION: if the batch holds more than ``capacity`` distinct indices, the
    overflow entries get ``inverse`` values >= capacity and their gradients
    are DROPPED by ``combine_gradients`` (scatter mode="drop"). The default
    capacity (== batch size) is always exact; only pass a smaller capacity if
    measured batch uniqueness guarantees it, and monitor with
    ``overflow_count``.
    """
    indices = indices.ravel()
    if capacity is None:
        capacity = indices.shape[0]
    fill = jnp.asarray(fill_value, dtype=indices.dtype)
    uniq, inverse = jnp.unique(indices, size=capacity, fill_value=fill,
                               return_inverse=True)
    return uniq, inverse.ravel(), uniq != fill


def combine_gradients(grads: jnp.ndarray, inverse: jnp.ndarray, capacity: int,
                      in_counts: jnp.ndarray | None = None
                      ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Sum duplicate-key gradients into the unique buffer with counts.

    ``grads`` is [n, dim]; returns ``(summed [capacity, dim], counts
    [capacity])``. Matches the reference's client-side pre-reduce semantics:
    the optimizer sees the SUM over duplicates plus the duplicate count
    (EmbeddingPushOperator.cpp:29-62, MpscGradientReducer.h:27-54).

    ``in_counts`` carries per-entry multiplicities when the incoming grads are
    *already pre-reduced* (the owner side of the all-to-all exchange receives
    (sum, count) pairs from every peer and must SUM the counts) — the
    reference's server-side MpscGradientReducer merging client pre-reduces.
    """
    n, dim = grads.shape
    summed = jnp.zeros((capacity, dim), dtype=grads.dtype).at[inverse].add(
        grads, mode="drop")
    add = jnp.int32(1) if in_counts is None else in_counts.astype(jnp.int32)
    counts = jnp.zeros((capacity,), dtype=jnp.int32).at[inverse].add(
        add, mode="drop")
    return summed, counts


def overflow_count(inverse: jnp.ndarray, capacity: int) -> jnp.ndarray:
    """Number of batch entries whose unique slot overflowed ``capacity``."""
    return jnp.sum(inverse >= capacity)


def unique_rows(rows: jnp.ndarray, capacity: int | None = None,
                fill_value: int = FILL
                ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Deduplicate composite keys: [n, K] integer rows, K >= 2.

    Generalizes :func:`unique_pairs` to any column count — wide (lo, hi)
    keys are K=2, the grouped exchange plane's table-tagged streams carry
    (key..., table_id) rows at K=2 or 3 (``parallel/grouped.py``). Rows
    are ranked lexicographically by K stable argsorts (minor column
    first, major column last — a stable sort by the major key preserves
    the minor order within equal majors), duplicates detected by
    adjacent-row equality, and compacted into a fixed-capacity buffer.
    Returns ``(uniq [capacity, K], inverse [n], valid [capacity])`` with
    padding rows equal to ``fill_value`` in every column. Matching
    :func:`unique_indices`'s contract, the sentinel group (padding rows,
    LAST column == fill) is NOT a valid unique.
    """
    n, k = rows.shape
    if capacity is None:
        capacity = n
    order = jnp.arange(n, dtype=jnp.int32)
    for c in range(k):
        order = order[jnp.argsort(rows[order, c], stable=True)]
    srt = rows[order]
    new_group = jnp.concatenate([
        jnp.ones((1,), bool),
        jnp.any(srt[1:] != srt[:-1], axis=1)])
    # group ordinal per sorted row -> unique slot; first of group writes it
    slot_sorted = jnp.cumsum(new_group.astype(jnp.int32)) - 1
    inverse = jnp.zeros((n,), jnp.int32).at[order].set(slot_sorted)
    fill = jnp.asarray(fill_value, rows.dtype)
    uniq = jnp.full((capacity, k), fill, dtype=rows.dtype)
    dst = jnp.where(new_group, slot_sorted, capacity)
    uniq = uniq.at[dst].set(srt, mode="drop")
    valid = (jnp.arange(capacity) <= (slot_sorted[-1] if n else -1)) \
        & (uniq[:, -1] != fill)
    return uniq, inverse, valid


def unique_pairs(pairs: jnp.ndarray, capacity: int | None = None,
                 fill_value: int = FILL
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Deduplicate WIDE keys: [n, 2] int32 (lo, hi) rows, x64-off.

    The 64-bit twin of :func:`unique_indices` for processes without
    ``jax_enable_x64`` (a jnp int64 pack is unavailable there); the
    K-column generalization lives in :func:`unique_rows`. Returns
    ``(uniq [capacity, 2], inverse [n], valid [capacity])`` with padding
    rows equal to ``(fill_value, fill_value)``.
    """
    return unique_rows(pairs, capacity, fill_value)
