"""Pallas TPU kernel: fused hash-probe + row gather (hash-table pull).

The reference's server-side hash pull is a single C++ loop: probe the
EasyHashMap, copy the matched row into the response
(/root/reference/openembedding/server/EmbeddingPullOperator.cpp:149-252).
The XLA composition splits it into two HBM passes — gather the [n, W]
probe-chain keys, argmax the match, then gather the [n, dim] rows. This
kernel is the reference's loop as one Mosaic pipeline:

* probe starts ride **scalar prefetch** so chain addresses are known before
  the body runs. ``hash_table`` lays the slot space out in 128-slot buckets
  and bounds every chain to consecutive buckets, so a query's candidate
  keys are ONE aligned ``[chain, 128]`` DMA from the ``[num_buckets, 128]``
  key array — no wraparound, no unaligned 1D slices (Mosaic tiles 1D HBM
  refs in 1024-element units and refuses unaligned windows);
* each grid step keeps R queries in flight: key-chain DMAs HBM->VMEM,
  vectorized compare + sum-reduction to the match offset, then the matched
  row's DMA — the probe result never round-trips through HBM;
* misses and EMPTY-sentinel queries yield zero rows and ``hit=0`` — the
  caller overlays deterministic init rows for training pulls (serving
  pulls use zeros directly, the read-only contract).

``interpret=True`` runs the same kernel on CPU (tests); on TPU it compiles
to a Mosaic pipeline. int64-key tables fall back to the XLA path (scalar
prefetch is int32; wide keys route through the hi/lo pair plane).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

ROWS_PER_STEP = 16  # queries in flight per grid step


def _probe_gather_kernel(bkt_ref, qkeys_ref, tkeys_ref, weights_ref,
                         rows_ref, hit_ref, kscratch, rscratch, ksem, rsem,
                         *, chain: int, bucket: int, empty: int,
                         nsteps: int):
    """Double-buffered probe: key-chain DMAs for grid step i+1 are issued
    while step i computes, so the per-query DMA latency (the measured
    bottleneck of the single-buffered version: 2 serial DMAs per query
    issued from the scalar core) hides behind the compare/row phase.
    Buffer parity is resolved with static indices under even/odd
    ``pl.when`` branches (dynamic scratch/semaphore indices don't lower).
    """
    i = pl.program_id(0)
    R = ROWS_PER_STEP

    def key_copy(step, r, buf):
        b = bkt_ref[step * R + r]
        return pltpu.make_async_copy(
            tkeys_ref.at[pl.dslice(b, chain), :],
            kscratch.at[pl.dslice((buf * R + r) * chain, chain), :],
            ksem.at[buf * R + r])

    parity = jax.lax.rem(i, 2)

    @pl.when(i == 0)
    def _():  # prime the pipeline: this step's own chains
        for r in range(R):
            key_copy(i, r, 0).start()

    @pl.when(i + 1 < nsteps)
    def _():  # prefetch the NEXT step's chains into the other buffer
        for buf in (0, 1):  # static-index twin branches
            @pl.when(parity == buf)
            def _(buf=buf):
                for r in range(R):
                    key_copy(i + 1, r, 1 - buf).start()

    def body(buf):
        hits = []
        for r in range(R):
            key_copy(i, r, buf).wait()
            q = qkeys_ref[i * R + r]
            window = kscratch[
                pl.dslice((buf * R + r) * chain, chain), :]
            match = window == q
            # unique keys: at most one slot matches -> sum IS the offset
            iota = jax.lax.broadcasted_iota(
                jnp.int32, (chain, bucket), 1) + \
                jax.lax.broadcasted_iota(
                    jnp.int32, (chain, bucket), 0) * bucket
            off = jnp.sum(jnp.where(match, iota, 0))
            nhit = jnp.sum(match.astype(jnp.int32))
            hit = (nhit > 0) & (q != empty)
            hits.append(hit)
            b = bkt_ref[i * R + r]
            row = jnp.where(hit, b * bucket + off, 0)
            pltpu.make_async_copy(
                weights_ref.at[pl.dslice(row, 1), :],
                rscratch.at[pl.dslice(r, 1), :], rsem.at[r]).start()

        for r in range(R):
            # wait on the row DMA (same byte count; only the sem matters)
            pltpu.make_async_copy(
                weights_ref.at[pl.dslice(0, 1), :],
                rscratch.at[pl.dslice(r, 1), :], rsem.at[r]).wait()
            rows_ref[pl.dslice(r, 1), :] = jnp.where(
                hits[r], rscratch[pl.dslice(r, 1), :],
                jnp.zeros_like(rscratch[pl.dslice(r, 1), :]))

        # scalar stores to VMEM are disallowed: write hits vectorized
        hit_ref[:, :] = jnp.stack(
            [h.astype(jnp.int32) for h in hits]).reshape(R, 1)

    for buf in (0, 1):
        @pl.when(parity == buf)
        def _(buf=buf):
            body(buf)


@functools.partial(jax.jit,
                   static_argnames=("chain", "bucket", "empty", "interpret"))
def probe_gather(table_keys: jnp.ndarray, weights: jnp.ndarray,
                 starts: jnp.ndarray, query: jnp.ndarray,
                 *, chain: int, bucket: int, empty: int,
                 interpret: bool = False):
    """Fused lookup: ``rows[i] = weights[slot(query[i])]``, zeros on miss.

    ``starts`` are the per-query aligned probe starts
    (``hash_table.probe_starts``); the ``chain * bucket`` slots from each
    start are compared against the query key and the matched row is DMA'd
    directly. Returns ``(rows [n, dim], hit [n] bool)``. The weights' row
    dim must be lane-aligned (pad the TABLE once at creation if needed,
    cf. ``pallas_gather.pad_table``).
    """
    n = query.shape[0]
    capacity = table_keys.shape[0]
    dim = weights.shape[1]
    if query.dtype.itemsize > 4 or table_keys.dtype.itemsize > 4:
        # int64 keys would alias mod 2^32 through the int32 scalar-prefetch
        # cast — wide keys must use the XLA path (module contract)
        raise ValueError(
            f"probe_gather requires <=32-bit keys (got query "
            f"{query.dtype}, table {table_keys.dtype}); int64-key tables "
            "use the XLA probe path")
    if dim % 128:
        raise ValueError(
            f"weights row dim {dim} is not lane-aligned; pad the table once "
            "at creation (pallas_gather.pad_table)")
    if capacity % bucket:
        raise ValueError(f"capacity {capacity} not a multiple of {bucket}")
    npad = -(-n // ROWS_PER_STEP) * ROWS_PER_STEP
    bkt = (starts // bucket).astype(jnp.int32)
    qk = query.astype(jnp.int32)
    if npad != n:
        bkt = jnp.pad(bkt, (0, npad - n))
        qk = jnp.pad(qk, (0, npad - n), constant_values=empty)
    keys2d = table_keys.reshape(capacity // bucket, bucket)

    nsteps = npad // ROWS_PER_STEP
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(nsteps,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY),   # keys in HBM
                  pl.BlockSpec(memory_space=pl.ANY)],  # weights in HBM
        out_specs=[pl.BlockSpec((ROWS_PER_STEP, dim),
                                lambda i, s, q: (i, 0)),
                   pl.BlockSpec((ROWS_PER_STEP, 1),
                                lambda i, s, q: (i, 0))],
        scratch_shapes=[
            # x2: double-buffered key staging (this step + the prefetched
            # next step); scratch persists across sequential grid steps
            pltpu.VMEM((2 * ROWS_PER_STEP * chain, bucket),
                       table_keys.dtype),
            pltpu.VMEM((ROWS_PER_STEP, dim), weights.dtype),
            pltpu.SemaphoreType.DMA((2 * ROWS_PER_STEP,)),
            pltpu.SemaphoreType.DMA((ROWS_PER_STEP,)),
        ],
    )
    rows, hit = pl.pallas_call(
        functools.partial(_probe_gather_kernel, chain=chain, bucket=bucket,
                          empty=empty, nsteps=nsteps),
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((npad, dim), weights.dtype),
                   jax.ShapeDtypeStruct((npad, 1), jnp.int32)],
        interpret=interpret,
    )(bkt, qk, keys2d, weights)
    return rows[:n], hit[:n, 0] > 0
