"""Delta checkpoint plane: base snapshot + compacted dirty-chunk chain.

The reference's ICDE 2023 PMem work makes checkpoints cheap with
lightweight INCREMENTAL saves from dirty tracking
(PmemEmbeddingTable.h:285-328); the offload tier already reproduces that
protocol for its own host store (``offload._persist_store``). This
module generalizes it to the WHOLE-MODEL checkpoint
(``checkpoint.save_checkpoint(mode="delta")``):

* a FULL save (``checkpoint._save_checkpoint_impl``, parallel shard
  writers) is the BASE; it arms the chain by writing a fresh manifest
  (:func:`init_manifest`) when the collection's dirty tracking is on;
* a DELTA save writes, per variable, only the chunks whose
  ``DirtyTracker`` bit is set (``dirty.py``; pushes mark chunks) — one
  ``delta_<seq>_<vid>.npz`` per variable, written by the same parallel
  writer pool, checksummed per chunk;
* the MANIFEST (``delta_manifest``, atomic rename) is the single commit
  point: a kill at ANY instant leaves either the previous chain or the
  new chain — never a manifest referencing a torn file. Torn/corrupt
  FINAL entries (crc mismatch after a partial rename on a dying disk)
  are discarded whole at load; a torn MIDDLE entry fails the load (the
  chain is replayed in order — skipping the middle would corrupt);
* a background COMPACTOR folds long chains back into a new base ON DISK
  (no device involvement — folding is the same newest-wins assignment
  the replay performs, so a crash mid-compaction leaves a directory
  that still loads to the identical state) under a chain-length /
  chain-bytes budget;
* the SAME delta stream feeds serving hot-swap: :class:`Delta` payloads
  (``read_delta`` / ``encode_delta``) are applied in place by
  ``ModelRegistry.apply_delta`` — the train->serve loop the reference
  closes with TF-Serving + the HA PS, without a full-model reload.

Delta mode is LOCAL + single-process + uncompressed-base (the delta
files themselves may be compressed): remote/multi-host dumps keep the
full-save part format. A dump written with dirty tracking DISABLED
never has a manifest and loads exactly as before.
"""

from __future__ import annotations

import dataclasses
import io
import json
import os
import threading
import time
import uuid
import warnings
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .analysis.concurrency import make_lock, sync_point
from .embedding import EmbeddingCollection
from .parallel import hot_cache
from .parallel import sharded_hash as sh
from .parallel import sharded_table as st
from .utils import fs
from . import hash_table as hash_lib
from . import table as table_lib

DELTA_MANIFEST_FILE = "delta_manifest"
DELTA_FORMAT = 1
# compaction budget: fold the chain into a new base past either bound
COMPACT_CHAIN_LEN = 8
COMPACT_BYTES_RATIO = 0.5
_APPLY_CHUNK = 1 << 16


def _delta_fname(seq: int, vid: int) -> str:
    return f"delta_{seq:06d}_{vid}.npz"


def _seq_ok(seq: Any) -> bool:
    """True when ``seq`` is an integral number the NATIVE reader's
    json_i64 would also accept (int64 range, no bools, no NaN/inf) —
    both readers must refuse the same manifests or they recover to
    different versions (the graftfuzz divergence oracle)."""
    if isinstance(seq, bool) or not isinstance(seq, (int, float)):
        return False
    try:
        return (seq == int(seq)
                and -(2 ** 63) < int(seq) < 2 ** 63)
    except (OverflowError, ValueError):       # inf / nan
        return False


class DeltaDecodeError(ValueError):
    """Typed refusal for corrupt/garbage delta BYTES (wire frames,
    manifest records, crc-valid-but-unparseable payloads), with offset/
    field context in the message.

    One type for the whole untrusted-bytes delta surface so damage is
    distinguishable from reader bugs: the REST ``POST /models/<sign>/
    delta`` handler maps ``ValueError`` to 400 (client sent garbage —
    this subclasses it on purpose), and the graftfuzz trichotomy oracle
    counts it as a clean typed refusal, where a raw ``struct.error`` /
    ``zlib.error`` / ``KeyError`` escaping a byte parser is scored as a
    crash. Semantic refusals keep their existing types (category swap
    ``ValueError``, checksum ``RuntimeError``, torn mid-chain
    ``RuntimeError``) — this class is specifically for bytes that could
    not be decoded at all."""


# --- manifest ----------------------------------------------------------------

def read_manifest(path: str) -> Optional[Dict[str, Any]]:
    """The committed manifest, or None (a plain full checkpoint)."""
    mpath = fs.join(path, DELTA_MANIFEST_FILE)
    if not fs.exists(mpath):
        return None
    manifest = fs.read_json(mpath)
    if not isinstance(manifest, dict):
        raise DeltaDecodeError(
            f"delta manifest at {path!r} is JSON "
            f"{type(manifest).__name__}, not an object")
    if manifest.get("format") != DELTA_FORMAT:
        raise ValueError(
            f"unknown delta manifest format {manifest.get('format')!r} "
            f"at {path!r} (this build reads format {DELTA_FORMAT})")
    return manifest


def _write_manifest(path: str, manifest: Dict[str, Any]) -> None:
    fs.write_json_atomic(fs.join(path, DELTA_MANIFEST_FILE), manifest)


def init_manifest(path: str, *, step: int, include_optimizer: bool,
                  last_seq: int = 0,
                  content_seq: Optional[int] = None,
                  extra: Optional[Dict[str, Any]] = None
                  ) -> Dict[str, Any]:
    """Arm a fresh chain over a just-written full base. ``last_seq``
    carries the version counter across a compaction AND across a full
    save over an armed dir (seqs are burned, never reused — the serving
    hot-swap version protocol needs monotonicity; a re-arm at 0 would
    make replicas ack the next real delta as stale and silently stop
    updating — graftproto ``full_save_resets_seq``).

    ``content_seq`` records the chain seq the BASE BYTES already
    reflect, so ``applied_seq`` of a chainless manifest reports the true
    version instead of 0 (a full save dumps the live state = everything
    through ``last_seq``, hence the default).

    ``extra``: caller bookkeeping recorded WITH the commit — the elastic
    resume channel (``Trainer.fit(autosave_every=)`` records its step/
    epoch/ingest cursor here; ``resume_from`` restores from whatever
    entry the load verifies). JSON-serializable dict."""
    manifest = {"format": DELTA_FORMAT,
                "base_id": uuid.uuid4().hex,
                "base_step": int(step),
                "include_optimizer": bool(include_optimizer),
                "last_seq": int(last_seq),
                "content_seq": int(last_seq if content_seq is None
                                   else content_seq),
                "extra": dict(extra) if extra else {},
                "chain": []}
    _write_manifest(path, manifest)
    return manifest


def reset_chain(path: str) -> None:
    """Remove the manifest (FIRST — the atomic commit point) and GC every
    delta file. Called by a full save before it touches base files, so a
    crash mid-save can never leave a stale chain to be replayed over a
    half-new base."""
    mpath = fs.join(path, DELTA_MANIFEST_FILE)
    if fs.exists(mpath):
        fs.remove(mpath)
    _gc_orphans(path, chain=())


def chain_state(path: str) -> Dict[str, Any]:
    """Chain summary for version bookkeeping (the serving registry sets
    a loaded model's hot-swap version from ``last_seq``)."""
    manifest = read_manifest(path)
    if manifest is None:
        return {"base_id": "", "base_step": 0, "last_seq": 0,
                "content_seq": 0, "chain_len": 0, "chain_bytes": 0}
    return {"base_id": manifest["base_id"],
            "base_step": manifest["base_step"],
            "last_seq": manifest["last_seq"],
            "content_seq": int(manifest.get("content_seq", 0)),
            "chain_len": len(manifest["chain"]),
            "chain_bytes": sum(int(e.get("bytes", 0))
                               for e in manifest["chain"])}


def _gc_orphans(path: str, chain) -> int:
    """Remove delta files the committed manifest does not reference, plus
    leftover atomic-write tmps and compaction tmps — the debris of a kill
    between a delta-file rename and the manifest commit. Runs on the
    WRITE path only (the saving process owns the directory)."""
    live = set()
    for entry in chain:
        for info in entry.get("vars", {}).values():
            live.add(info["file"])
    n = 0
    try:
        names = fs.listdir(path)
    except OSError:  # pragma: no cover — listing is best-effort
        return 0
    for fname in names:
        orphan = (fname.startswith("delta_") and fname.endswith(".npz")
                  and fname not in live)
        if orphan or fs.is_tmp_orphan(fname):
            try:
                fs.remove(fs.join(path, fname))
                n += 1
            except OSError:  # pragma: no cover
                pass
        elif fname.startswith("var_") and fname.endswith(".d"):
            # a killed compaction leaves <field>.npy.compact.tmp inside
            # var dirs (each commits via atomic rename; debris is inert)
            vdir = fs.join(path, fname)
            try:
                subnames = fs.listdir(vdir)
            except OSError:  # pragma: no cover
                continue
            for sub in subnames:
                if sub.endswith(".compact.tmp") or fs.is_tmp_orphan(sub):
                    try:
                        fs.remove(fs.join(vdir, sub))
                        n += 1
                    except OSError:  # pragma: no cover
                        pass
    return n


# --- delta payloads ----------------------------------------------------------

def _field_order(payload: Dict[str, np.ndarray]) -> List[str]:
    """Deterministic field order for checksums/wire framing: id column
    first, then weights, then slots sorted by name."""
    fields = []
    for f in ("keys", "weights"):
        if f in payload:
            fields.append(f)
    fields += sorted(k for k in payload if k.startswith("slot_"))
    return fields


def _array_delta_payload(state, sspec, vocab: int, rows_per_chunk: int,
                         chunks: np.ndarray, include_optimizer: bool
                         ) -> Tuple[Dict[str, np.ndarray], List[int]]:
    """Gather one bounded variable's dirty chunks into a payload dict +
    per-chunk crc32 list (crc over the chunk's weights+slots bytes, in
    field order). Contiguous chunk runs gather as one logical window —
    the same bulk device->host streams as the full save."""
    from . import checkpoint as ckpt
    fields: Dict[str, Any] = {"weights": state.weights}
    if include_optimizer:
        for sname, sval in state.slots.items():
            fields[f"slot_{sname}"] = sval
    shards = {f: ckpt._sorted_shards(a) for f, a in fields.items()}
    chunks = np.asarray(chunks, np.int64)
    parts: Dict[str, list] = {f: [] for f in fields}
    chunk_crcs: List[int] = []
    R = int(rows_per_chunk)
    # group consecutive chunk ids into runs
    runs: List[Tuple[int, int]] = []
    for c in chunks:
        c = int(c)
        if runs and runs[-1][1] == c:
            runs[-1] = (runs[-1][0], c + 1)
        else:
            runs.append((c, c + 1))
    order = _field_order({f: None for f in fields})
    for c0, c1 in runs:
        l0 = c0 * R
        l1 = min(c1 * R, vocab)
        if l1 <= l0:
            continue
        bufs = {}
        for f, arr in fields.items():
            bufs[f] = ckpt.gather_logical_window(
                shards[f], sspec, l0, l1, arr.shape[1:],
                np.dtype(arr.dtype))
            parts[f].append(bufs[f])
        for c in range(c0, c1):
            a = c * R - l0
            b = min((c + 1) * R, vocab) - l0
            if b <= a:
                continue
            crc = 0
            for f in order:
                crc = zlib.crc32(bufs[f][a:b].tobytes(), crc)
            chunk_crcs.append(crc)
    payload = {}
    for f, arr in fields.items():
        if parts[f]:
            payload[f] = np.concatenate(parts[f])
        else:
            payload[f] = np.zeros((0,) + arr.shape[1:],
                                  np.dtype(arr.dtype))
    payload["chunks"] = chunks
    payload["rows_per_chunk"] = np.int64(R)
    payload["vocab"] = np.int64(vocab)
    return payload, chunk_crcs


def _hash_delta_payload(state, tracker, chunks: np.ndarray,
                        include_optimizer: bool
                        ) -> Dict[str, np.ndarray]:
    """Gather one hash variable's live rows whose key chunk is dirty.
    Newest-wins replay makes over-collection safe: every live row of a
    dirty chunk ships, whether or not that specific key changed."""
    from . import checkpoint as ckpt
    targets = {"keys": state.keys, "weights": state.weights}
    if include_optimizer:
        for sname, sval in state.slots.items():
            targets[f"slot_{sname}"] = sval
    dirty = np.zeros(tracker.num_chunks, bool)
    dirty[np.asarray(chunks, np.int64)] = True
    empty = hash_lib.empty_key(np.dtype(state.keys.dtype))
    wide = hash_lib.is_wide(state.keys)
    parts: Dict[str, list] = {f: [] for f in targets}
    for blocks in ckpt._aligned_shard_blocks(targets):
        bk = blocks["keys"]
        live = (bk[:, 1] != empty) if wide else (bk != empty)
        if not live.any():
            continue
        k64 = hash_lib.join64(bk[live]) if wide \
            else bk[live].astype(np.int64)
        sel = dirty[k64 % np.int64(tracker.num_chunks)]
        if not sel.any():
            continue
        for f, block in blocks.items():
            parts[f].append(block[live][sel])
    payload = {}
    for f, arr in targets.items():
        if parts[f]:
            payload[f] = np.concatenate(parts[f])
        else:
            payload[f] = np.zeros((0,) + arr.shape[1:],
                                  np.dtype(arr.dtype))
    payload["chunks"] = np.asarray(chunks, np.int64)
    payload["num_chunks"] = np.int64(tracker.num_chunks)
    return payload


def _serialize_payload(payload: Dict[str, np.ndarray],
                       compress: str) -> Tuple[bytes, int]:
    """npz bytes + file crc32 (the whole-file checksum the manifest
    records; verified before any byte of the delta is applied)."""
    from .utils import compress as compress_lib
    savez = np.savez_compressed \
        if compress_lib.check_persist_codec(compress) else np.savez
    bio = io.BytesIO()
    savez(bio, **payload)
    raw = bio.getvalue()
    return raw, zlib.crc32(raw)


def _parse_payload(raw: bytes) -> Dict[str, np.ndarray]:
    # every caller checked the whole-file crc first, so a parse failure
    # here means crc-preserving corruption (or an unsupported npz
    # feature) — surface it typed, not as whatever np.load's zip/format
    # internals happen to raise (BadZipFile, struct.error, OSError...)
    try:
        data = np.load(io.BytesIO(raw))
        return {k: data[k] for k in data.files}
    except DeltaDecodeError:
        raise
    except Exception as e:  # noqa: BLE001 — parser surface, see above
        raise DeltaDecodeError(
            f"delta payload npz is unparseable ({len(raw)} bytes, "
            f"crc-verified): {type(e).__name__}: {e}") from e


def _verify_array_chunks(payload: Dict[str, np.ndarray],
                         chunk_crc: List[int]) -> bool:
    """Recompute per-chunk crcs of a parsed array payload.

    Never raises: ill-formed geometry (missing members, out-of-range
    chunk ids, non-list crcs — the manifest and the member bytes
    disagreeing) reports False, which the caller treats exactly like a
    chunk crc mismatch. Mirrored by the native reader's
    ``verify_chunk_crcs`` (oe_serving.cc) so both loaders classify the
    same manifests as damaged."""
    try:
        chunks = np.asarray(payload["chunks"], np.int64)
        R = int(payload["rows_per_chunk"])
        vocab = int(payload["vocab"])
        order = _field_order(payload)
        if R <= 0 or vocab < 0 or chunks.ndim != 1:
            return False
        nchunks = -(-vocab // R)
        if len(chunk_crc) != chunks.size:
            return False
        off = 0
        for i, c in enumerate(chunks):
            c = int(c)
            if c < 0 or c >= nchunks:
                return False
            n = min((c + 1) * R, vocab) - c * R
            crc = 0
            for f in order:
                crc = zlib.crc32(payload[f][off:off + n].tobytes(), crc)
            if crc != int(chunk_crc[i]):
                return False
            off += n
        return all(payload[f].shape[0] == off for f in order)
    except (KeyError, TypeError, ValueError, OverflowError):
        return False


# --- delta save --------------------------------------------------------------

def save_delta(path: str, collection: EmbeddingCollection,
               states: Dict[str, Any], *, step: int,
               dense_state: Any = None,
               include_optimizer: bool = True,
               compress: str = "",
               model_sign: str = "",
               max_workers: Optional[int] = None,
               compact_chain_len: int = COMPACT_CHAIN_LEN,
               compact_bytes_ratio: float = COMPACT_BYTES_RATIO,
               background_compact: bool = True,
               return_payload: bool = False,
               extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """One incremental save: dirty chunks since the last save -> one new
    chain entry. Forces a FULL save when no armed base exists (first
    save into a directory, or the previous dump predates dirty
    tracking). See ``checkpoint.save_checkpoint`` for the public entry.

    ``return_payload=True`` attaches the committed :class:`Delta` to the
    info dict (``info["delta"]``) straight from memory — the PUBLISH
    path for serving hot-swap. Prefer it over a post-save
    :func:`read_delta`: the background compactor may fold the chain
    (deleting the file) before a disk read lands.

    ``extra``: JSON-serializable caller bookkeeping committed WITH this
    entry (and carried into the manifest base when the save is forced
    full) — the elastic-resume channel: ``fit(autosave_every=)`` records
    ``{"fit": {step, epoch, cursor}}`` here and ``fit(resume_from=)``
    restores from the entry the load actually verifies, so a torn tail
    resumes one autosave earlier, never from a half-applied state.
    """
    from . import checkpoint as ckpt
    from .utils import compress as compress_lib
    from .utils import observability
    compress = compress_lib.check_persist_codec(compress)
    if fs.is_remote(path):
        raise ValueError(
            "mode='delta' needs a local path (the compactor folds chain "
            "files into the base in place); dump remote checkpoints full")
    if jax.process_count() > 1:
        raise ValueError("mode='delta' is single-process; multi-host "
                         "dumps use the full part format")
    trackers = collection.dirty_trackers
    if not trackers:
        raise ValueError(
            "mode='delta' needs dirty tracking: call "
            "collection.enable_dirty_tracking() before training")
    # a running background compaction owns the directory — join it (and
    # surface its error) before writing anything
    join_compactor(path)
    manifest = read_manifest(path)
    t0 = time.perf_counter()
    if manifest is None:
        # no armed base: the full save writes one and arms the chain
        nbytes = ckpt._save_checkpoint_impl(
            path, collection, states, dense_state=dense_state,
            include_optimizer=include_optimizer, model_sign=model_sign,
            compress="", step=step, max_workers=max_workers,
            extra=extra)
        dt = time.perf_counter() - t0
        observability.record_ckpt_save("full", nbytes, dt, chain_len=0)
        return {"mode": "full", "forced_full": True, "bytes": int(nbytes),
                "seconds": dt, "seq": 0}
    if bool(manifest.get("include_optimizer", True)) \
            != bool(include_optimizer):
        raise ValueError(
            "delta save include_optimizer does not match the base "
            f"(base={manifest.get('include_optimizer')}); re-save full")
    _gc_orphans(path, manifest["chain"])

    # DENSE params ride OUTSIDE the chain protocol: small, replicated,
    # rewritten whole (atomically) on every save — including a SKIPPED
    # one, so a dense-only training window still persists its params.
    # Last-writer-wins; a torn-tail recovery keeps the newest dense
    # file next to the recovered sparse state (document'd divergence —
    # chain guarantees cover the sparse tables).
    if dense_state is not None:
        from flax import serialization
        with fs.open_atomic(fs.join(path, ckpt.DENSE_FILE)) as f:
            f.write(serialization.to_bytes(jax.device_get(dense_state)))

    snaps = {name: trackers[name].snapshot_clear() for name in trackers}
    total_dirty = sum(s.size for s in snaps.values())
    if total_dirty == 0:
        return {"mode": "delta", "seq": int(manifest["last_seq"]),
                "skipped": True, "bytes": 0, "rows": 0,
                "chain_len": len(manifest["chain"])}
    seq = int(manifest["last_seq"]) + 1
    results: Dict[str, Dict[str, Any]] = {}
    kept_payloads: Dict[str, Dict[str, np.ndarray]] = {}
    tasks = []

    def _write_var(name: str) -> None:
        sync_point("ckpt.delta.write")
        spec = collection.specs[name]
        tracker = trackers[name]
        state = hot_cache.unwrap(states[name])
        chunks = snaps[name]
        if spec.use_hash:
            payload = _hash_delta_payload(state, tracker, chunks,
                                          include_optimizer)
            chunk_crc = None
        else:
            payload, chunk_crc = _array_delta_payload(
                state, collection.sharding_spec(name), spec.input_dim,
                tracker.rows_per_chunk, chunks, include_optimizer)
        rows = int(payload["weights"].shape[0])
        raw, crc = _serialize_payload(payload, compress)
        fname = _delta_fname(seq, collection.variable_id(name))
        with fs.open_atomic(fs.join(path, fname)) as f:
            f.write(raw)
        info = {"file": fname, "bytes": len(raw), "crc32": int(crc),
                "kind": "hash" if spec.use_hash else "array",
                "rows": rows, "dirty_chunks": int(chunks.size)}
        if chunk_crc is not None:
            info["chunk_crc"] = [int(c) for c in chunk_crc]
        results[name] = info
        if return_payload:
            kept_payloads[name] = payload

    for name in trackers:
        if snaps[name].size:
            tasks.append(lambda n=name: _write_var(n))
    try:
        ckpt._run_writers(tasks, max_workers=max_workers)

        entry = {"seq": seq, "step": int(step),
                 "bytes": sum(i["bytes"] for i in results.values()),
                 "rows": sum(i["rows"] for i in results.values()),
                 "vars": results}
        if extra:
            entry["extra"] = dict(extra)
        manifest["chain"].append(entry)
        manifest["last_seq"] = seq
        # the commit point: before this rename readers replay the old
        # chain
        sync_point("ckpt.delta.commit")
        _write_manifest(path, manifest)
    except BaseException:
        # failed write OR failed commit: restore every claim so the next
        # save re-covers it (completed-but-uncommitted files are
        # orphans, GC'd next save); marks that landed during the attempt
        # are preserved either way
        for name, chunks in snaps.items():
            trackers[name].restore(chunks)
        raise
    dt = time.perf_counter() - t0
    observability.record_ckpt_save("delta", entry["bytes"], dt,
                                   chain_len=len(manifest["chain"]))
    info = {"mode": "delta", "seq": seq, "step": int(step),
            "bytes": int(entry["bytes"]), "rows": int(entry["rows"]),
            "seconds": dt, "chain_len": len(manifest["chain"]),
            "skipped": False}
    if return_payload:
        info["delta"] = Delta(seq=seq, step=int(step), vars=kept_payloads)
    if compact_due(manifest, _base_bytes(path),
                   chain_len=compact_chain_len,
                   bytes_ratio=compact_bytes_ratio):
        compact(path, background=background_compact,
                max_workers=max_workers)
        info["compaction"] = "background" if background_compact \
            else "done"
    return info


def _base_bytes(path: str) -> int:
    total = 0
    for d in os.listdir(path):
        if d.startswith("var_") and d.endswith(".d"):
            vd = os.path.join(path, d)
            for f in os.listdir(vd):
                if f.endswith(".npy"):
                    total += os.path.getsize(os.path.join(vd, f))
    return total


def compact_due(manifest: Dict[str, Any], base_bytes: int, *,
                chain_len: int = COMPACT_CHAIN_LEN,
                bytes_ratio: float = COMPACT_BYTES_RATIO) -> bool:
    """Chain budget: past ``chain_len`` entries, or chain bytes past
    ``bytes_ratio`` of the base — both bound replay time and file count
    over arbitrarily long runs (the reference's periodic rebase)."""
    chain = manifest.get("chain", [])
    if len(chain) >= chain_len:
        return True
    cb = sum(int(e.get("bytes", 0)) for e in chain)
    return base_bytes > 0 and cb >= bytes_ratio * base_bytes


# --- chain verification + replay ---------------------------------------------

def verify_chain(path: str, manifest: Dict[str, Any],
                 keep_payloads: bool = True
                 ) -> Tuple[List[Tuple[Dict[str, Any],
                                       Dict[str, Dict[str, np.ndarray]]]],
                            bool]:
    """Read + checksum every committed entry; returns ``(list of
    (entry, {var: payload}), dropped_last)``.

    A torn/corrupt/missing FINAL entry is DISCARDED whole (the state as
    of the previous entry is complete and consistent — a partial last
    delta must never be half-applied); the same damage mid-chain raises
    (later entries were built on top of it). ``keep_payloads=False``
    verifies without holding the parsed arrays (the compactor's
    bounded-memory pass; payloads are re-read one at a time during the
    fold — the chain-bytes budget can be a large fraction of the base,
    which must never be required to fit in RAM at once)."""
    entries = manifest.get("chain", [])
    if not isinstance(entries, list):
        raise DeltaDecodeError(
            f"delta chain at {path!r} is not a list (manifest corrupt)")
    out = []
    for i, entry in enumerate(entries):
        if (not isinstance(entry, dict) or "seq" not in entry
                or not isinstance(entry.get("vars"), dict)
                or not _seq_ok(entry.get("seq"))):
            # native parity (replay_delta_chain "corrupt delta chain
            # entry"): structural manifest corruption refuses the load
            # outright — tear semantics are reserved for FILE damage.
            # The seq bound matches the native json_i64 int64 range: a
            # 1e300 seq that Python's bignums would happily carry must
            # not load here while the native reader refuses it
            raise DeltaDecodeError(
                f"corrupt delta chain entry #{i} at {path!r}")
        payloads: Dict[str, Dict[str, np.ndarray]] = {}
        bad = None
        for name, info in entry["vars"].items():
            try:
                fname = info["file"]
                want_crc = int(info["crc32"])
                if not isinstance(fname, str):
                    raise TypeError(
                        f"file field is {type(fname).__name__}")
            except (TypeError, KeyError, ValueError) as e:
                bad = f"var {name!r}: malformed manifest record ({e})"
                break
            fpath = fs.join(path, fname)
            try:
                with fs.open_file(fpath, "rb") as f:
                    raw = f.read()
            except (OSError, FileNotFoundError):
                bad = f"{fname}: missing/unreadable"
                break
            if zlib.crc32(raw) != want_crc:
                bad = f"{fname}: crc mismatch"
                break
            payload = _parse_payload(raw)
            if info.get("chunk_crc") is not None \
                    and not _verify_array_chunks(payload,
                                                 info["chunk_crc"]):
                bad = f"{info['file']}: chunk checksum mismatch"
                break
            if keep_payloads:
                payloads[name] = payload
            del payload
        if bad is None:
            out.append((entry, payloads))
            continue
        if i == len(entries) - 1:
            warnings.warn(
                f"delta chain at {path!r}: final entry seq="
                f"{entry['seq']} is torn ({bad}); discarded — "
                "recovering to the last complete delta", RuntimeWarning)
            return out, True
        raise RuntimeError(
            f"delta chain at {path!r} is torn mid-chain at seq="
            f"{entry['seq']} ({bad}); later deltas build on it — "
            "restore the file or fall back to an older full checkpoint")
    return out, False


def _entry_payload(path: str, entry: Dict[str, Any],
                   name: str) -> Optional[Dict[str, np.ndarray]]:
    """Re-read one verified entry's payload for one variable (the
    compactor's one-at-a-time loader; crc already checked)."""
    info = entry["vars"].get(name)
    if info is None:
        return None
    with fs.open_file(fs.join(path, info["file"]), "rb") as f:
        return _parse_payload(f.read())


def replay_chain(path: str, collection: EmbeddingCollection,
                 states: Dict[str, Any], *, manifest: Dict[str, Any],
                 with_opt: bool, shard_slice: Optional[tuple],
                 dump_meta: Optional[Dict[str, Any]] = None,
                 info: Optional[Dict[str, Any]] = None
                 ) -> Dict[str, Any]:
    """Apply the committed chain over freshly-loaded base states, in
    order (newest wins by construction). Called by ``load_checkpoint``;
    states are UNWRAPPED table states (hot-cache wrap happens after).
    Payloads stream one ENTRY at a time (host memory bounded by one
    delta, never the whole chain — which the compaction budget allows
    to reach a large fraction of the base). ``info`` (when given) gets
    ``applied_seq`` AND ``resume_extra`` from the SAME verify pass the
    replay uses — the version (and the caller bookkeeping) the loaded
    states actually reflect: a dropped torn tail's extra is never
    surfaced."""
    verified, _dropped = verify_chain(path, manifest, keep_payloads=False)
    if info is not None:
        info["applied_seq"] = verified_seq(manifest, verified)
        info["resume_extra"] = resume_extra(manifest, verified)
    for entry, _ in verified:
        payloads = {name: _entry_payload(path, entry, name)
                    for name in entry["vars"]}
        states = apply_delta_to_states(
            collection, states, payloads, shard_slice=shard_slice,
            with_opt=with_opt, donate=True)
        del payloads
    return states


def verified_seq(manifest: Optional[Dict[str, Any]],
                 verified) -> int:
    """Version of an ALREADY-verified chain view: the last verified
    entry's seq, else the manifest's ``content_seq`` (what the base
    bytes reflect — after a compaction the chain is empty but the base
    carries every folded delta; pre-``content_seq`` manifests read 0,
    their pre-fix behavior). The loaders use THIS over the same verify
    pass their replay performs, so the version a model starts serving at
    can never race ahead of the rows it actually holds."""
    if manifest is None:
        return 0
    if verified:
        return int(verified[-1][0]["seq"])
    return int(manifest.get("content_seq", 0))


def resume_extra(manifest: Optional[Dict[str, Any]],
                 verified) -> Dict[str, Any]:
    """The ``extra`` bookkeeping of an ALREADY-verified chain view: the
    last verified entry's (the newest commit a load applies), else the
    manifest base's (what the base bytes were saved with). Same
    resolution discipline as :func:`verified_seq` — the extra a resume
    restores must describe exactly the rows the load delivered, so a
    dropped torn tail's extra (newer than the loaded content) is never
    returned, and an OLDER entry's is never substituted (its cursor
    would re-apply rows the newer content already holds)."""
    if manifest is None:
        return {}
    if verified:
        return dict(verified[-1][0].get("extra") or {})
    return dict(manifest.get("extra") or {})


def applied_seq(path: str) -> int:
    """Chain seq a load of ``path`` replays up to (torn tail excluded) —
    the hot-swap version a freshly loaded serving model starts at.

    Deliberately re-verifies the chain (one extra checksum pass per
    MODEL LOAD — rare and bounded): the version must reflect exactly
    what a load applies, including a dropped torn tail, and the
    manifest's ``last_seq`` alone cannot say that. NOTE: against a
    directory a trainer is actively saving into, prefer the version the
    load itself reports (``load_checkpoint(..., info=...)``) — this
    standalone read can see a NEWER chain than a just-finished load
    replayed, and a model versioned ahead of its rows acks the next
    delta as stale and loses it (graftproto found this divergence in
    the serving registry; fixed there)."""
    manifest = read_manifest(path)
    if manifest is None:
        return 0
    verified, _ = verify_chain(path, manifest, keep_payloads=False)
    return verified_seq(manifest, verified)


def apply_delta_to_states(collection: EmbeddingCollection,
                          states: Dict[str, Any],
                          payloads: Dict[str, Dict[str, np.ndarray]],
                          *, shard_slice: Optional[tuple] = None,
                          with_opt: bool = True,
                          donate: bool = True) -> Dict[str, Any]:
    """Patch variable states with delta payloads (functional: returns a
    NEW states dict; inputs stay valid unless ``donate``). Shared by the
    load-path replay (donate, with optimizer slots) and the serving
    hot-swap (no donation — in-flight readers keep the pre-swap state;
    serving's stateless optimizer carries no slots)."""
    out = dict(states)
    for name, payload in payloads.items():
        if name not in collection.specs:
            continue
        spec = collection.specs[name]
        state = hot_cache.unwrap(out[name])
        if "keys" in payload:
            if not spec.use_hash:
                raise ValueError(
                    f"delta for {name!r} is a hash payload but the "
                    "variable is bounded — delta chains cannot "
                    "category-swap; load the base full or re-save")
            state = _apply_hash_payload(collection, name, state, payload,
                                        shard_slice=shard_slice,
                                        with_opt=with_opt)
        else:
            if spec.use_hash:
                raise ValueError(
                    f"delta for {name!r} is an array payload but the "
                    "variable is hash — delta chains cannot "
                    "category-swap; load the base full or re-save")
            state = _apply_array_payload(collection, name, state, payload,
                                        shard_slice=shard_slice,
                                        with_opt=with_opt, donate=donate)
        out[name] = collection.wrap_hot_cache(name, state)
    return out


def _payload_ids(payload: Dict[str, np.ndarray]) -> np.ndarray:
    """Global logical row ids of an ARRAY payload's rows (chunk ranges
    expanded in order). Refuses ill-formed headers typed: a hostile
    chunk id or rows_per_chunk would otherwise expand to an unbounded
    ``arange`` (an allocation-of-death, not a parse error) — the native
    reader refuses the same ranges ("array delta chunk id out of
    range")."""
    try:
        chunks = np.asarray(payload["chunks"], np.int64)
        R = int(payload["rows_per_chunk"])
        vocab = int(payload["vocab"])
    except (KeyError, TypeError, ValueError, OverflowError) as e:
        raise DeltaDecodeError(
            f"corrupt array delta header: {type(e).__name__}: {e}"
        ) from e
    if R <= 0 or vocab < 0:
        raise DeltaDecodeError(
            f"corrupt array delta header (rows_per_chunk={R}, "
            f"vocab={vocab})")
    if not chunks.size:
        return np.zeros(0, np.int64)
    nchunks = -(-vocab // R)
    lo, hi = int(chunks.min()), int(chunks.max())
    if lo < 0 or hi >= nchunks:
        raise DeltaDecodeError(
            f"array delta chunk id out of range: [{lo}, {hi}] outside "
            f"[0, {nchunks})")
    return np.concatenate([
        np.arange(int(c) * R, min((int(c) + 1) * R, vocab),
                  dtype=np.int64) for c in chunks])


def _apply_array_payload(collection, name, state, payload, *,
                         shard_slice, with_opt, donate):
    spec = collection.specs[name]
    sspec = collection.sharding_spec(name)
    dtype = np.dtype(table_lib.resolve_dtype(spec.meta()))
    ids = _payload_ids(payload)
    fields = [("weights", dtype)]
    if with_opt:
        for sname, sval in state.slots.items():
            if f"slot_{sname}" in payload:
                fields.append((f"slot_{sname}",
                               np.dtype(sval.dtype)))
    weights = state.weights
    slots = dict(state.slots)
    size = min(_APPLY_CHUNK, max(int(ids.size), 1))
    for lo in range(0, ids.size, size):
        sub = ids[lo:lo + size]
        if shard_slice is not None:
            # serving shard group: keep owned global ids, map to the
            # local row space (local l holds id l*G + k)
            k, G = shard_slice
            sel = (sub % G) == k
            local_ids = sub[sel] // G
        else:
            sel = None
            local_ids = sub
        shard, local = sspec.shard_and_local(local_ids)
        phys = shard * sspec.rows_per_shard + local
        n = phys.shape[0]
        phys_p = np.full((size,), -1, np.int64)
        phys_p[:n] = phys
        jphys = jnp.asarray(phys_p)
        for fname, fdtype in fields:
            rows = payload[fname][lo:lo + size]
            if sel is not None:
                rows = rows[sel]
            buf = np.zeros((size,) + rows.shape[1:], fdtype)
            buf[:n] = fs.view_as(np.asarray(rows), fdtype)
            target = weights if fname == "weights" \
                else slots[fname[len("slot_"):]]
            patched = st.deliver_rows_sharded(
                target, jphys, jnp.asarray(buf), mesh=collection.mesh,
                spec=sspec, donate=donate)
            if fname == "weights":
                weights = patched
            else:
                slots[fname[len("slot_"):]] = patched
    return table_lib.TableState(weights=weights, slots=slots)


def _apply_hash_payload(collection, name, state, payload, *,
                        shard_slice, with_opt):
    sspec = collection.sharding_spec(name)
    keys = np.asarray(payload["keys"])
    key_dtype = np.dtype(state.keys.dtype)
    empty = hash_lib.empty_key(key_dtype)
    table_wide = hash_lib.is_wide(state.keys)
    payload_wide = keys.ndim == 2
    if table_wide != payload_wide:
        raise ValueError(
            f"delta for {name!r}: key width mismatch (payload "
            f"{'wide' if payload_wide else 'narrow'}, table "
            f"{'wide' if table_wide else 'narrow'}) — delta chains "
            "cannot key-migrate; load the base full instead")
    slot_names = [s for s in state.slots
                  if with_opt and f"slot_{s}" in payload] if with_opt \
        else []
    wdtype = np.dtype(state.weights.dtype)
    before = state.insert_failures
    n = keys.shape[0]
    size = min(_APPLY_CHUNK, max(n, 1))
    for lo in range(0, n, size):
        sub = keys[lo:lo + size]
        got = sub.shape[0]
        ck = np.full((size,) + sub.shape[1:], empty, dtype=key_dtype)
        ck[:got] = sub.astype(key_dtype)
        if shard_slice is not None:
            k, G = shard_slice
            ids64 = hash_lib.join64(sub) if payload_wide \
                else sub.astype(np.int64)
            ck[:got][(ids64 % G) != k] = empty
        cw = np.zeros((size,) + payload["weights"].shape[1:], wdtype)
        cw[:got] = fs.view_as(
            np.asarray(payload["weights"][lo:lo + size]), wdtype)
        srows = {}
        for sname in slot_names:
            sdtype = np.dtype(state.slots[sname].dtype)
            block = payload[f"slot_{sname}"][lo:lo + size]
            cs = np.zeros((size,) + block.shape[1:], sdtype)
            cs[:got] = fs.view_as(np.asarray(block), sdtype)
            srows[sname] = jnp.asarray(cs)
        state = sh.insert_rows_sharded(
            state, jnp.asarray(ck), jnp.asarray(cw), srows,
            mesh=collection.mesh, spec=sspec)
    grew = int(jax.device_get(state.insert_failures - before))
    if grew > 0:
        raise RuntimeError(
            f"hash variable {name!r}: {grew} delta rows did not fit "
            "(hash_capacity too small); a delta apply must deliver "
            "every row or fail")
    return state


# --- hot-swap payloads (the train->serve stream) -----------------------------

@dataclasses.dataclass
class Delta:
    """One committed delta as an in-memory payload — the unit the
    trainer publishes and ``ModelRegistry.apply_delta`` patches in.
    ``vars`` holds the same per-variable dicts the chain files store."""

    seq: int
    step: int
    vars: Dict[str, Dict[str, np.ndarray]]

    @property
    def rows(self) -> int:
        return sum(int(p["weights"].shape[0]) for p in self.vars.values())


def read_delta(path: str, seq: Optional[int] = None) -> Delta:
    """Load one committed delta (default: the newest) for publishing."""
    manifest = read_manifest(path)
    if manifest is None or not manifest.get("chain"):
        raise ValueError(f"no committed deltas at {path!r}")
    entries = manifest["chain"]
    if not isinstance(entries, list):
        raise DeltaDecodeError(
            f"delta chain at {path!r} is not a list (manifest corrupt)")
    if seq is None:
        entry = entries[-1]
    else:
        match = [e for e in entries
                 if isinstance(e, dict) and e.get("seq") == seq]
        if not match:
            raise KeyError(
                f"no delta seq={seq} at {path!r} (chain has "
                f"{[e.get('seq') for e in entries if isinstance(e, dict)]})")
        entry = match[0]
    try:
        eseq = int(entry["seq"])
        estep = int(entry["step"])
        var_items = list(entry["vars"].items())
    except (TypeError, ValueError, KeyError, AttributeError) as e:
        raise DeltaDecodeError(
            f"corrupt delta chain entry at {path!r}: "
            f"{type(e).__name__}: {e}") from e
    payloads = {}
    for name, info in var_items:
        try:
            fname = info["file"]
            want_crc = int(info["crc32"])
            if not isinstance(fname, str):
                raise TypeError(f"file field is {type(fname).__name__}")
        except (TypeError, KeyError, ValueError) as e:
            raise DeltaDecodeError(
                f"corrupt delta manifest record for {name!r} at "
                f"{path!r}: {type(e).__name__}: {e}") from e
        with fs.open_file(fs.join(path, fname), "rb") as f:
            raw = f.read()
        if zlib.crc32(raw) != want_crc:
            raise RuntimeError(
                f"delta seq={eseq} file {fname} fails "
                "its checksum; refusing to publish a corrupt delta")
        payloads[name] = _parse_payload(raw)
    return Delta(seq=eseq, step=estep, vars=payloads)


def read_deltas_since(path: str, after_seq: int) -> List[Delta]:
    """Committed deltas with ``seq > after_seq``, in order — the catch-up
    stream for a replica that fell behind."""
    manifest = read_manifest(path)
    if manifest is None:
        return []
    chain = manifest.get("chain") or []
    try:
        seqs = [int(e["seq"]) for e in chain]
        if not all(_seq_ok(s) for s in seqs):
            raise ValueError("seq outside the int64 range")
    except (TypeError, ValueError, KeyError) as e:
        raise DeltaDecodeError(
            f"corrupt delta chain at {path!r}: "
            f"{type(e).__name__}: {e}") from e
    return [read_delta(path, s) for s in seqs if s > int(after_seq)]


def encode_delta(delta: Delta, compress: str = "") -> bytes:
    """Wire-frame a delta: one JSON header line (seq/step/field specs)
    + concatenated raw array bytes, optionally compressed — the same
    header-line + packed-body shape as the serving ``lookup_bin`` and
    peer-restore row pages."""
    from .utils import compress as compress_lib
    compress = compress_lib.check(compress)
    head: Dict[str, Any] = {"seq": delta.seq, "step": delta.step,
                            "vars": {}}
    body = bytearray()
    for name in sorted(delta.vars):
        payload = delta.vars[name]
        specs = []
        for f in sorted(payload):
            arr = np.ascontiguousarray(np.asarray(payload[f]))
            specs.append([f, np.lib.format.dtype_to_descr(arr.dtype),
                          list(arr.shape)])
            body += arr.tobytes()
        head["vars"][name] = specs
    raw = bytes(body)
    if compress:
        head["compress"] = compress
        raw = compress_lib.compress(compress, raw)
    return json.dumps(head).encode() + b"\n" + raw


def decode_delta(data: bytes) -> Delta:
    """Decode one :func:`encode_delta` wire frame.

    The frame is UNTRUSTED bytes (the REST ``POST /models/<sign>/delta``
    body): every malformed shape — missing header line, garbage JSON,
    bad codec, corrupt field specs, a body too short for its specs —
    refuses with :class:`DeltaDecodeError` carrying offset context, so
    the REST handler answers 400 and the graftfuzz oracle sees a typed
    refusal instead of a raw ``struct.error``/``zlib.error``/
    ``KeyError`` escaping the parser."""
    from .utils import compress as compress_lib
    data = bytes(data)
    nl = data.find(b"\n")
    if nl < 0:
        raise DeltaDecodeError(
            f"delta wire frame has no header line ({len(data)} bytes, "
            "no newline)")
    try:
        head = json.loads(data[:nl])
    except ValueError as e:
        raise DeltaDecodeError(
            f"delta wire header (bytes 0..{nl}) is not valid JSON: {e}"
        ) from e
    if not isinstance(head, dict):
        raise DeltaDecodeError(
            f"delta wire header is JSON {type(head).__name__}, "
            "not an object")
    raw = data[nl + 1:]
    codec = head.get("compress", "")
    if codec:
        try:
            raw = compress_lib.decompress(codec, raw)
        except DeltaDecodeError:
            raise
        except Exception as e:  # noqa: BLE001 — zlib.error/bad codec
            raise DeltaDecodeError(
                f"delta wire body (offset {nl + 1}) fails {codec!r} "
                f"decompression: {type(e).__name__}: {e}") from e
    try:
        seq = int(head["seq"])
        step = int(head["step"])
        var_specs = head["vars"]
    except (KeyError, TypeError, ValueError) as e:
        raise DeltaDecodeError(
            f"delta wire header missing/corrupt field: "
            f"{type(e).__name__}: {e}") from e
    if not isinstance(var_specs, dict):
        raise DeltaDecodeError(
            f"delta wire header 'vars' is JSON "
            f"{type(var_specs).__name__}, not an object")
    off = 0
    out: Dict[str, Dict[str, np.ndarray]] = {}
    for name, specs in var_specs.items():
        if not isinstance(specs, list):
            raise DeltaDecodeError(
                f"delta wire specs for {name!r} are not a list")
        payload = {}
        for spec in specs:
            try:
                f, descr, shape = spec
                dtype = np.dtype(np.lib.format.descr_to_dtype(descr))
                dims = [int(d) for d in shape]
            except (TypeError, ValueError, KeyError) as e:
                raise DeltaDecodeError(
                    f"corrupt field spec {spec!r} for {name!r}: "
                    f"{type(e).__name__}: {e}") from e
            if any(d < 0 for d in dims):
                raise DeltaDecodeError(
                    f"negative dim in field spec {spec!r} for {name!r}")
            count = 1
            for d in dims:
                count *= d
            nb = (count if dims else 1) * dtype.itemsize
            if off + nb > len(raw):
                raise DeltaDecodeError(
                    f"delta wire body truncated: field {f!r} of "
                    f"{name!r} needs body bytes [{off}, {off + nb}) "
                    f"but the body holds {len(raw)}")
            try:
                arr = np.frombuffer(raw[off:off + nb], dtype=dtype)
                payload[f] = arr.reshape(dims) if dims else arr[0]
            except (ValueError, IndexError) as e:
                raise DeltaDecodeError(
                    f"field {f!r} of {name!r} does not decode as "
                    f"{descr!r} x {dims}: {type(e).__name__}: {e}"
                ) from e
            off += nb
        out[name] = payload
    return Delta(seq=seq, step=step, vars=out)


# --- the compactor -----------------------------------------------------------

class _Compactor:
    def __init__(self, thread: threading.Thread):
        self.thread = thread
        self.err: Optional[BaseException] = None


_COMPACT_LOCK = make_lock("ckpt.compactors")
_COMPACTORS: Dict[str, _Compactor] = {}


def join_compactor(path: str) -> None:
    """Join (and surface the error of) any background compaction of
    ``path``. Every delta save calls this first — the compactor and the
    saver are the directory's only writers and never run concurrently."""
    key = os.path.realpath(path)
    with _COMPACT_LOCK:
        holder = _COMPACTORS.pop(key, None)
    if holder is None:
        return
    holder.thread.join()
    if holder.err is not None:
        raise RuntimeError("background chain compaction failed") \
            from holder.err


def compact(path: str, *, background: bool = False,
            max_workers: Optional[int] = None) -> Optional[Dict[str, Any]]:
    """Fold the committed chain into a new base ON DISK.

    Pure file work (base memmaps + chain payloads; no device, no live
    states), so it runs on a background thread while training continues.
    CRASH-SAFE by idempotence: folding performs exactly the newest-wins
    assignments the load-time replay would, and each base file commits
    via tmp + atomic rename — a kill mid-compaction leaves the OLD
    manifest (still referencing the chain) over partially-folded base
    files, and replaying the chain over a partially-folded base yields
    the identical state. The new manifest (empty chain, new base_id,
    ``last_seq`` preserved — seqs are burned, never reused) is the
    single commit point; superseded delta files are GC'd after it.
    """
    if background:
        key = os.path.realpath(path)
        join_compactor(path)
        holder_ref: List[_Compactor] = []

        def _run():
            sync_point("ckpt.compact.run")
            try:
                _compact_impl(path, max_workers=max_workers)
            except BaseException as e:  # noqa: BLE001 — re-raised at join
                holder_ref[0].err = e

        t = threading.Thread(target=_run, daemon=False,
                             name="oe-ckpt-compact")
        holder = _Compactor(t)
        holder_ref.append(holder)
        with _COMPACT_LOCK:
            _COMPACTORS[key] = holder
        t.start()
        return None
    return _compact_impl(path, max_workers=max_workers)


def _compact_impl(path: str, *,
                  max_workers: Optional[int] = None) -> Dict[str, Any]:
    from . import checkpoint as ckpt
    from .meta import ModelMeta, UNBOUNDED_VOCAB
    manifest = read_manifest(path)
    if manifest is None or not manifest["chain"]:
        return {"compacted": False}
    # bounded-memory verification: payloads re-read one at a time below.
    # A MID-chain tear raises out of verify_chain: refuse to compact
    # (graceful — compaction is an optimization; the damage keeps
    # surfacing loudly at every load until a full save), never fail the
    # delta save that happened to trigger the fold
    try:
        verified, dropped = verify_chain(path, manifest,
                                         keep_payloads=False)
    except RuntimeError as e:
        warnings.warn(
            f"delta chain at {path!r}: refusing to compact a chain that "
            f"does not verify ({e}); re-save full to restore durability",
            RuntimeWarning)
        return {"compacted": False, "error": str(e)}
    entries = [e for e, _p in verified]
    if dropped:
        # graftproto true positive: a torn COMMITTED entry must not be
        # compacted away. Folding the verified prefix and GC'ing the
        # torn file would let later deltas commit over the hole with
        # the torn delta's chunks permanently lost (they were claim-
        # cleared at its save; nothing re-covers them) — and loads
        # would "succeed" on the folded base instead of hitting the
        # documented loud mid-chain refusal. Abort untouched: loads
        # keep their drop-the-tail recovery, and once a later delta
        # lands the tear is mid-chain and every load fails loudly until
        # a full save rebuilds the base from the live state.
        torn = manifest["chain"][len(entries)]["seq"]
        warnings.warn(
            f"delta chain at {path!r}: refusing to compact across torn "
            f"entry seq={torn}; re-save full to restore durability",
            RuntimeWarning)
        return {"compacted": False, "torn_seq": int(torn)}
    with fs.open_file(fs.join(path, ckpt.MODEL_META_FILE), "rb") as f:
        meta = ModelMeta.loads(f.read().decode("utf-8"))
    by_name = {v.name: v for v in meta.variables}
    # fold per variable: every chain payload for it, in order
    folded_steps = [e["step"] for e in entries]
    for name, v in by_name.items():
        has = [e for e in entries if name in e["vars"]]
        if not has:
            continue
        vdir = os.path.join(path, ckpt._var_dir(v.variable_id, name))
        if v.meta.vocabulary_size >= UNBOUNDED_VOCAB:
            # hash folds need every payload's keys up front for the
            # newest-wins merge + sizing; hash deltas carry live rows
            # only, so this is the dirty working set, not the table
            _fold_hash_var(vdir, [_entry_payload(path, e, name)
                                  for e in has])
        else:
            _fold_array_var(vdir, path, has, name,
                            max_workers=max_workers)
    new_manifest = {"format": DELTA_FORMAT,
                    "base_id": uuid.uuid4().hex,
                    "base_step": int(folded_steps[-1]) if folded_steps
                    else manifest["base_step"],
                    "include_optimizer":
                        bool(manifest.get("include_optimizer", True)),
                    "last_seq": int(manifest["last_seq"]),
                    # the folded base now REFLECTS the whole verified
                    # chain: record it so applied_seq of the chainless
                    # manifest reports the true version, not 0 (which
                    # wedged hot-swap behind gap refusals after every
                    # compaction — graftproto compact_zero_version)
                    "content_seq": int(entries[-1]["seq"]) if entries
                    else int(manifest.get("content_seq", 0)),
                    # the folded base absorbs the NEWEST folded entry's
                    # resume extra (the model's comp_commit carrying
                    # base_cursor forward) — dropping it would silently
                    # rewind every elastic resume to cursor 0 after the
                    # first compaction. Newest entry ONLY: an older
                    # entry's cursor under newer content re-applies rows
                    "extra": dict(entries[-1].get("extra") or {}),
                    "chain": []}
    sync_point("ckpt.compact.commit")
    _write_manifest(path, new_manifest)
    _gc_orphans(path, chain=())
    return {"compacted": True, "folded": len(verified),
            "last_seq": new_manifest["last_seq"]}


def _commit_file(tmp: str, final: str) -> None:
    os.replace(tmp, final)


def _fold_array_var(vdir: str, path: str, entries: List[Dict[str, Any]],
                    name: str,
                    max_workers: Optional[int] = None) -> None:
    """New base field files = old base with every payload's chunk rows
    overwritten (in chain order; later payloads win by overwrite).
    Payloads are loaded ONE AT A TIME (memory stays bounded by one
    delta, not the chain)."""
    from . import checkpoint as ckpt
    fields = sorted(f[:-4] for f in os.listdir(vdir)
                    if f.endswith(".npy"))
    srcs, dsts = {}, {}
    tasks = []
    for field in fields:
        src_path = os.path.join(vdir, field + ".npy")
        src = np.load(src_path, mmap_mode="r")
        dst = np.lib.format.open_memmap(
            src_path + ".compact.tmp", mode="w+",
            dtype=src.dtype, shape=src.shape)
        srcs[field], dsts[field] = src, dst
        row_bytes = max(1, src.nbytes // max(1, src.shape[0]))
        win = max(1, ckpt._PAR_WINDOW_BYTES // row_bytes)
        for lo in range(0, src.shape[0], win):
            hi = min(src.shape[0], lo + win)
            tasks.append(lambda lo=lo, hi=hi, src=src, dst=dst:
                         dst.__setitem__(slice(lo, hi), src[lo:hi]))
    ckpt._run_writers(tasks, max_workers=max_workers)
    for entry in entries:
        payload = _entry_payload(path, entry, name)
        if payload is None:
            continue
        ids = _payload_ids(payload)
        for field in fields:
            if field not in payload:
                continue
            # delta-sized scatter (random IO bounded by the delta, not
            # the base)
            dsts[field][ids] = fs.view_as(np.asarray(payload[field]),
                                          srcs[field].dtype)
        del payload
    for field in fields:
        dsts[field].flush()
        del dsts[field], srcs[field]
        _commit_file(os.path.join(vdir, field + ".npy.compact.tmp"),
                     os.path.join(vdir, field + ".npy"))


def _fold_hash_var(vdir: str, payloads: List[Dict[str, np.ndarray]]
                   ) -> None:
    """New base = old live rows with payload rows merged newest-wins by
    64-bit key; keys absent from the base append at the end."""
    key_path = os.path.join(vdir, "keys.npy")
    base_keys = np.load(key_path, mmap_mode="r")
    wide = base_keys.ndim == 2
    k64_base = hash_lib.join64(np.asarray(base_keys)) if wide \
        else np.asarray(base_keys).astype(np.int64)
    order = np.argsort(k64_base, kind="stable")
    sorted_base = k64_base[order]
    # newest-wins merge across payloads: last occurrence of each key
    all_k, all_src = [], []
    for pi, payload in enumerate(payloads):
        pk = np.asarray(payload["keys"])
        k64 = hash_lib.join64(pk) if pk.ndim == 2 \
            else pk.astype(np.int64)
        all_k.append(k64)
        all_src.append(np.stack(
            [np.full(k64.shape, pi, np.int64),
             np.arange(k64.shape[0], dtype=np.int64)], axis=1))
    cat_k = np.concatenate(all_k) if all_k else np.zeros(0, np.int64)
    cat_src = np.concatenate(all_src) if all_src \
        else np.zeros((0, 2), np.int64)
    rev_k = cat_k[::-1]
    uniq, ridx = np.unique(rev_k, return_index=True)
    take = cat_k.shape[0] - 1 - ridx          # last occurrence, keys sorted
    src = cat_src[take]
    pos = np.searchsorted(sorted_base, uniq)
    pos_c = np.minimum(pos, max(0, sorted_base.shape[0] - 1))
    hit = (pos < sorted_base.shape[0]) & (sorted_base[pos_c] == uniq) \
        if sorted_base.size else np.zeros(uniq.shape, bool)
    exist_rows = order[pos_c[hit]] if sorted_base.size \
        else np.zeros(0, np.int64)
    new_src = src[~hit]
    n_base = int(base_keys.shape[0])
    total = n_base + int(new_src.shape[0])
    fields = sorted(f[:-4] for f in os.listdir(vdir)
                    if f.endswith(".npy"))
    del base_keys
    for field in fields:
        src_path = os.path.join(vdir, field + ".npy")
        base = np.load(src_path, mmap_mode="r")
        tmp_path = src_path + ".compact.tmp"
        dst = np.lib.format.open_memmap(
            tmp_path, mode="w+", dtype=base.dtype,
            shape=(total,) + base.shape[1:])
        chunk = max(1, (32 << 20) // max(1, base.nbytes
                                         // max(1, n_base or 1)))
        for lo in range(0, n_base, chunk):
            hi = min(n_base, lo + chunk)
            dst[lo:hi] = base[lo:hi]

        def rows_for(sel_src):
            parts = []
            for pi, payload in enumerate(payloads):
                mask = sel_src[:, 0] == pi
                if mask.any():
                    parts.append((mask, payload[field][sel_src[mask, 1]]))
            out = None
            for mask, rows in parts:
                if out is None:
                    out = np.zeros((sel_src.shape[0],) + rows.shape[1:],
                                   base.dtype)
                out[mask] = fs.view_as(np.asarray(rows), base.dtype)
            return out

        if exist_rows.size:
            upd = rows_for(src[hit])
            if upd is not None:
                dst[exist_rows] = upd
        if new_src.size:
            app = rows_for(new_src)
            if app is not None:
                dst[n_base:] = app
        dst.flush()
        del dst, base
        _commit_file(tmp_path, src_path)
